"""Federated round bodies: one client's uplink, and a cohort's worth of them.

This module is the round *body* shared by the two federated drivers:

- `fedavg.FedAvg.run_round` — the paper-faithful scalar harness (ONE
  `lax.scan` over the sampled cohort, `impl="scan"`), kept as the proven
  reference semantics.
- `fedsim.sim.FedSim` — the population-scale driver (`impl="vmap"`,
  optionally chunked), which runs thousands of simulated clients per device
  step and shards cohorts across a mesh axis.

Both execute the *same* `client_step` closure per client: local training,
update compression through the real `TensorCodec` stack with per-client
error feedback, and (when engaged) the resilience uplink stage — payload
pack → chaos perturbation → checksum verify — with graceful
zero-contribution degradation. Equivalence between the two `impl`s is
pinned by tests/test_fedsim.py.

Degradation semantics (mirrors train.py's worker-dropout story):

- a *non-participating* client (churn: `FaultPlan` / drop_rate) never
  trained: its update, wire bits, and residual write are all suppressed —
  its pending EF mass waits for the next time it is sampled.
- a client whose payload *fails the checksum* did train and transmit: its
  wire bits count, its residual advances (client-side EF already ran — the
  lost mass is genuinely lost, which is the graceful-degradation price),
  but its decoded update is excluded from the server mean via a
  `jnp.where` SELECT (never a multiply: corrupt payloads can decode to
  Inf/NaN, and `NaN * 0 == NaN`).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu.fedsim.codec_tree import TreeCodec

# wire scalars threaded through scan/vmap as a plain tuple: WireStats'
# host-numpy ici_bits default must not be vmapped/scanned (see metrics.py)
WIRE_FIELDS = ("index_bits", "value_bits", "dense_bits", "saturated")


@dataclasses.dataclass(frozen=True)
class FedConfig:
    """Round geometry (paper §6.2: 56 clients sampled from 57 VMs;
    Table 5: 10 clients, 800 rounds)."""

    num_clients: int
    clients_per_round: int
    local_steps: int = 1
    server_lr: float = 1.0

    def __post_init__(self):
        if self.num_clients <= 0:
            raise ValueError(f"num_clients must be positive, got {self.num_clients}")
        if self.clients_per_round <= 0:
            raise ValueError(
                f"clients_per_round must be positive, got {self.clients_per_round}"
            )
        if self.clients_per_round > self.num_clients:
            raise ValueError(
                f"clients_per_round={self.clients_per_round} exceeds the "
                f"population num_clients={self.num_clients} — sampling is "
                "without replacement (Algorithm 2), so a round cannot draw "
                "more clients than exist"
            )
        if self.local_steps <= 0:
            raise ValueError(f"local_steps must be positive, got {self.local_steps}")
        if self.server_lr <= 0:
            raise ValueError(f"server_lr must be positive, got {self.server_lr}")


def tree_sub(a: Any, b: Any) -> Any:
    return jax.tree_util.tree_map(lambda x, y: x - y, a, b)


def tree_add(a: Any, b: Any) -> Any:
    return jax.tree_util.tree_map(jnp.add, a, b)


def make_client_step(
    tree_codec: TreeCodec,
    local_train: Callable[[Any, Any, jax.Array], Any],
    w_ref: Any,
    step: jax.Array,
    key_c2s: jax.Array,
    *,
    layout=None,
    chaos=None,
) -> Callable:
    """Build the per-client body. `pos` is the client's *cohort position*
    (uint32 scalar): PRNG keys fold `2*pos` (local train) and `2*pos + 1`
    (compression), exactly the pre-refactor `FedAvg` derivation, so the
    scalar path's numerics are unchanged.

    `layout` (a `comm.PayloadLayout` over this model's payload pytree)
    engages the wire-image stage: payloads are packed to a flat byte
    buffer, optionally chaos-perturbed, checksum-verified, and decoded
    from the buffer — the same pack/verify/unpack path the data-parallel
    exchange uses. Without it, the sender-side reconstruction doubles as
    the receiver's (pack/unpack is a bitcast round-trip, so this is exact,
    not an approximation).

    Returns `(dec_update_tree, new_residual_tree_or_None, wire4, ok)` where
    `wire4` is `(index, value, dense, saturated)` bits as f32 scalars and
    `ok` is the f32 checksum gate (1.0 when no layout)."""

    def client_step(batch_c: Any, res_c: Optional[Any], pos: jax.Array):
        p_end = local_train(w_ref, batch_c, jax.random.fold_in(key_c2s, 2 * pos))
        update = tree_sub(p_end, w_ref)
        payloads, comps, spec = tree_codec.encode_tree(
            update, res_c, step, jax.random.fold_in(key_c2s, 2 * pos + 1)
        )
        dec_leaves = [
            tree_codec.codec(path, shape).decode(p, step=step).reshape(shape)
            for path, shape, p in zip(spec.paths, spec.shapes, payloads)
        ]
        if layout is not None:
            buf = layout.pack(payloads)
            if chaos is not None:
                buf = chaos.perturb(buf, step=step, worker=pos)
                # the wire image changed: the receiver decodes what arrived
                recv = layout.unpack(buf)
                dec_recv = tree_codec.decode_tree(recv, spec, step)
            else:
                dec_recv = spec.unflatten(dec_leaves)
            ok = layout.verify(buf)
        else:
            dec_recv = spec.unflatten(dec_leaves)
            ok = jnp.ones((), jnp.float32)
        # sender-side EF: the client's residual is against what IT encoded
        # (it cannot observe wire corruption), i.e. the clean decode
        new_res = (
            spec.unflatten([c - d for c, d in zip(comps, dec_leaves)])
            if res_c is not None
            else None
        )
        wire = tree_codec.wire_tree(payloads, spec)
        wire4 = tuple(
            jnp.asarray(getattr(wire, f), jnp.float32).reshape(()) for f in WIRE_FIELDS
        )
        return dec_recv, new_res, wire4, ok

    return client_step


def _mask_tree(tree: Any, gate: jax.Array) -> Any:
    """Zero a client's contribution via SELECT (gate is a f32 scalar or a
    [C] vector broadcast against [C, ...] leaves)."""

    def _one(u):
        g = gate.reshape(gate.shape + (1,) * (u.ndim - gate.ndim))
        return jnp.where(g > 0, u, jnp.zeros_like(u))

    return jax.tree_util.tree_map(_one, tree)


def cohort_updates(
    client_step: Callable,
    client_batches: Any,
    res_stack: Optional[Any],
    positions: jax.Array,
    *,
    update_template: Any,
    participation: Optional[jax.Array] = None,
    checksum: bool = False,
    impl: str = "scan",
    chunk: int = 0,
) -> Tuple[Any, Optional[Any], Tuple[jax.Array, ...], jax.Array]:
    """Run `client_step` over a cohort and aggregate. `update_template` is
    any tree with the model's structure/shapes/dtypes (e.g. `w_ref`) — it
    seeds the scan accumulators.

    `client_batches` leaves are [C, local_steps, ...]; `res_stack` (or None)
    leaves are [C, ...]; `positions` is uint32[C] cohort positions (global
    across shards in the fedsim case). `participation` is an optional
    f32/bool[C] churn mask. `checksum` declares (statically) that
    `client_step`'s `ok` output is a real gate — when False and no
    participation mask is given, no masking is staged at all, which keeps
    the plain round's jaxpr identical to the pre-resilience program.

    impl="scan" — ONE `lax.scan` over the cohort (compiled size independent
    of C; the `FedAvg` reference path). impl="vmap" — all clients batched
    in one vmapped block; `chunk` > 0 additionally scans over blocks of
    `chunk` vmapped clients to bound peak memory ("vmapped client
    batches"), requiring chunk | C.

    Returns (upd_sum_tree, new_res_stack_or_None, wire4_sums, live_f32[C])
    where `live[c] = participation[c] * ok[c]` is the effective
    contribution gate (all-ones when nothing is engaged)."""
    (C,) = positions.shape
    use_res = res_stack is not None
    has_part = participation is not None
    has_live = has_part or checksum
    part = jnp.asarray(participation, jnp.float32) if has_part else None

    def one_client(batch_c, res_c, pos, m):
        dec_upd, new_res_c, wire4, ok = client_step(batch_c, res_c, pos)
        live_c = ok * m if has_part else ok
        if has_live:
            dec_upd = _mask_tree(dec_upd, live_c)
        if has_part:
            if use_res:
                # churned client never compressed: keep its old residual
                new_res_c = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(m > 0, new, old), new_res_c, res_c
                )
            # churned client transmitted nothing; a checksum-failed client
            # DID transmit, so `ok` does not gate the wire accounting
            wire4 = tuple(w * m for w in wire4)
        return dec_upd, new_res_c, wire4, live_c

    upd_sum0 = jax.tree_util.tree_map(jnp.zeros_like, update_template)
    wire0 = tuple(jnp.zeros((), jnp.float32) for _ in WIRE_FIELDS)

    if impl == "scan":

        def body(carry, xs):
            upd_sum, wire_acc = carry
            pos, batch_c = xs[0], xs[1]
            rest = xs[2:]
            res_c = rest[0] if use_res else None
            m = rest[-1] if has_part else None
            dec_upd, new_res_c, wire4, live_c = one_client(batch_c, res_c, pos, m)
            upd_sum = tree_add(upd_sum, dec_upd)
            wire_acc = tuple(a + w for a, w in zip(wire_acc, wire4))
            return (upd_sum, wire_acc), (new_res_c if use_res else 0, live_c)

        xs = (positions, client_batches)
        if use_res:
            xs = xs + (res_stack,)
        if has_part:
            xs = xs + (part,)
        (upd_sum, wire_acc), (new_res_stack, live) = jax.lax.scan(
            body, (upd_sum0, wire0), xs
        )
        return upd_sum, (new_res_stack if use_res else None), wire_acc, live

    if impl != "vmap":
        raise ValueError(f"impl must be 'scan' or 'vmap', got {impl!r}")

    def block(batches_b, res_b, pos_b, part_b):
        """One vmapped block of clients -> (upd_sum, new_res, wire4, live)."""
        if use_res:
            dec, nres, wire4, ok = jax.vmap(
                lambda b, r, p: client_step(b, r, p)
            )(batches_b, res_b, pos_b)
        else:
            dec, nres, wire4, ok = jax.vmap(
                lambda b, p: client_step(b, None, p)
            )(batches_b, pos_b)
        live_b = ok * part_b if has_part else ok
        if has_live:
            dec = _mask_tree(dec, live_b)
        if has_part:
            if use_res:
                nres = jax.tree_util.tree_map(
                    lambda new, old: _mask_where(part_b, new, old), nres, res_b
                )
            wire4 = tuple(w * part_b for w in wire4)
        upd_b = jax.tree_util.tree_map(lambda u: jnp.sum(u, axis=0), dec)
        wire_b = tuple(jnp.sum(w) for w in wire4)
        return upd_b, nres, wire_b, live_b

    if chunk and 0 < chunk < C:
        if C % chunk:
            raise ValueError(f"chunk={chunk} must divide the cohort size {C}")
        n_blocks = C // chunk

        def reshape_blocks(tree):
            return jax.tree_util.tree_map(
                lambda x: x.reshape((n_blocks, chunk) + x.shape[1:]), tree
            )

        xs = (
            reshape_blocks(positions),
            reshape_blocks(client_batches),
            reshape_blocks(res_stack) if use_res else None,
            reshape_blocks(part) if has_part else None,
        )

        def body(carry, xs_b):
            upd_sum, wire_acc = carry
            pos_b, batches_b, res_b, part_b = xs_b
            upd_b, nres_b, wire_b, live_b = block(batches_b, res_b, pos_b, part_b)
            upd_sum = tree_add(upd_sum, upd_b)
            wire_acc = tuple(a + w for a, w in zip(wire_acc, wire_b))
            return (upd_sum, wire_acc), (nres_b if use_res else 0, live_b)

        (upd_sum, wire_acc), (nres_blocks, live_blocks) = jax.lax.scan(
            body, (upd_sum0, wire0), xs
        )
        new_res_stack = (
            jax.tree_util.tree_map(
                lambda x: x.reshape((C,) + x.shape[2:]), nres_blocks
            )
            if use_res
            else None
        )
        live = live_blocks.reshape((C,))
        return upd_sum, new_res_stack, wire_acc, live

    upd_sum, new_res_stack, wire_acc, live = block(
        client_batches, res_stack, positions, part
    )
    return upd_sum, new_res_stack, wire_acc, live


def _mask_where(gate_vec: jax.Array, new: jax.Array, old: jax.Array) -> jax.Array:
    g = gate_vec.reshape(gate_vec.shape + (1,) * (new.ndim - gate_vec.ndim))
    return jnp.where(g > 0, new, old)


# --------------------------------------------------------------------------- #
# Asynchronous buffered mode (FedBuff-style): stragglers as a deterministic
# per-client latency distribution instead of binary churn. A client drawn
# with latency tau trained from the model as of tau server versions ago;
# the server down-weights its delta by 1/(1+tau)^alpha and buffers it until
# K contributions have arrived.
# --------------------------------------------------------------------------- #

# fold_in domain-separation tag for the latency draw (mirrors faults.py's
# _DROPOUT_TAG): the async tick derives its latency key from the round key
# BEFORE the 5-way split, so the five synchronous subkeys stay bit-identical
_LATENCY_TAG = 0x57A1E


def parse_latency(spec: str, name: str = "fed_async_latency") -> Tuple[float, ...]:
    """Parse a latency distribution spec: comma-separated non-negative
    weights over staleness tau = 0, 1, 2, ..., normalized to probabilities.
    "" (the default) is zero latency: (1.0,). The tuple length is the
    overlap depth D — the number of past model versions kept in the w_hist
    ring, so tau is bounded by D-1 *by construction* (no runtime clamp).

    This is THE latency row parser: the global `fed_async_latency` knob,
    the per-tenant rows (`parse_tenant_latency`), and the per-class rows
    (`parse_class_latency`) all route through it — `name` labels the
    failing knob in the message."""
    if not spec:
        return (1.0,)
    try:
        weights = [float(tok) for tok in spec.split(",")]
    except ValueError as e:
        raise ValueError(
            f"{name}={spec!r}: every comma-separated token must "
            f"be a float weight ({e})"
        ) from None
    if any(not math.isfinite(w) for w in weights):
        raise ValueError(
            f"{name}={spec!r}: weights must be finite — nan/inf cannot "
            "normalize to a probability row"
        )
    if any(w < 0 for w in weights):
        raise ValueError(
            f"{name}={spec!r}: weights are unnormalized "
            "probabilities and must be >= 0"
        )
    total = sum(weights)
    if total <= 0:
        raise ValueError(
            f"{name}={spec!r}: weights must not all be zero"
        )
    if len(weights) > 64:
        raise ValueError(
            f"{name}={spec!r}: {len(weights)} staleness levels — "
            "the w_hist ring keeps one full model copy per level; cap is 64"
        )
    return tuple(w / total for w in weights)


def draw_latency(key: jax.Array, probs, C: int) -> jax.Array:
    """Draw int32[C] per-client staleness over GLOBAL cohort positions from
    the shared round key (every worker computes the identical replicated
    vector — no collective, the same trick as FaultPlan churn). Zero-latency
    (D == 1) stages no sampling ops at all, keeping the degenerate program
    minimal.

    `probs` is either the concrete tuple `parse_latency` returns (the
    single-tenant path — it becomes an XLA constant) or a TRACED f32[D] row
    (the multi-tenant path: per-tenant distributions ride as traced
    operands so a heterogeneous fleet shares one compiled program). Both
    stage the identical choice(cumsum/searchsorted) ops, so a traced row
    that equals the concrete tuple draws bitwise the same staleness — and a
    row zero-PADDED to a deeper fleet D keeps its cumsum (and therefore its
    draws) unchanged too."""
    D = len(probs) if isinstance(probs, tuple) else int(probs.shape[0])
    if D == 1:
        return jnp.zeros((C,), jnp.int32)
    lat_key = jax.random.fold_in(key, _LATENCY_TAG)
    return jax.random.choice(
        lat_key, D, (C,), p=jnp.asarray(probs, jnp.float32)
    ).astype(jnp.int32)


def _alpha_is_static_zero(alpha) -> bool:
    """True iff alpha is a compile-time 0.0 (the static identity-weighting
    fast path). Traced alphas are never static zero — their multiply is
    staged and exact at runtime-0.0 (multiply by 1.0)."""
    return isinstance(alpha, (int, float)) and float(alpha) == 0.0


def staleness_weights(taus_f: jax.Array, alpha) -> jax.Array:
    """`1/(1+tau)^alpha` down-weighting. A static (Python float) alpha of
    0.0 (identity) returns exact ones without staging a power — the
    bitwise-identity contract the degenerate-equivalence test pins. A
    TRACED alpha (the multi-tenant per-tenant knob) always stages the
    power; at alpha == 0.0 that is `pow(1+tau, -0.0) == 1.0` exactly
    (IEEE-754), so the multi-tenant T=1 degeneracy stays bitwise."""
    if _alpha_is_static_zero(alpha):
        return jnp.ones_like(taus_f)
    return jnp.power(1.0 + taus_f, -alpha)


# --------------------------------------------------------------------------- #
# Multi-tenant knob parsing: per-tenant K / alpha / latency / cohort specs.
# Shared by config validation (syntax at construction) and the FedSim
# driver (concrete stacked arrays at build).
# --------------------------------------------------------------------------- #


def parse_tenant_floats(
    spec: str, tenants: int, name: str, default: float
) -> Tuple[float, ...]:
    """Parse a comma-separated per-tenant float list. '' broadcasts
    `default` to every tenant; a single value broadcasts to the fleet;
    otherwise the list length must equal `tenants`."""
    if not spec:
        return (float(default),) * tenants
    try:
        vals = [float(tok) for tok in spec.split(",")]
    except ValueError as e:
        raise ValueError(
            f"{name}={spec!r}: every comma-separated token must be a "
            f"float ({e})"
        ) from None
    if len(vals) == 1:
        vals = vals * tenants
    if len(vals) != tenants:
        raise ValueError(
            f"{name}={spec!r}: got {len(vals)} per-tenant values for a "
            f"{tenants}-tenant fleet — give 1 (broadcast) or exactly "
            f"{tenants}"
        )
    return tuple(vals)


def _pad_latency_rows(
    rows: Sequence[Tuple[float, ...]]
) -> Tuple[Tuple[float, ...], ...]:
    """Zero-pad parsed latency rows to their common overlap depth D = max
    over rows. Padding is draw-preserving: the padded tail adds no
    probability mass, so a row's staleness draws match the ones its
    unpadded spec would produce. Shared by the per-tenant and per-class
    row parsers (one padding rule, two row families)."""
    depth = max(len(r) for r in rows)
    return tuple(r + (0.0,) * (depth - len(r)) for r in rows)


def parse_tenant_latency(
    spec: str, tenants: int, default: str
) -> Tuple[Tuple[float, ...], ...]:
    """Parse a semicolon-separated list of per-tenant latency specs (each
    one a `parse_latency` comma list), zero-padded to the fleet's common
    overlap depth D = max over tenants. '' broadcasts `default`; a single
    spec broadcasts. An EMPTY row inside a multi-row spec is rejected —
    it would silently read as zero latency for that tenant."""
    src = spec if spec else (default or "")
    if src:
        toks = src.split(";")
        if len(toks) > 1 and any(not t for t in toks):
            raise ValueError(
                f"fed_mt_latency={spec!r}: empty per-tenant row — every "
                "semicolon-separated row needs at least one weight (an "
                "empty row would silently mean zero latency)"
            )
        rows = [parse_latency(tok, name="fed_mt_latency") for tok in toks]
    else:
        rows = [(1.0,)]
    if len(rows) == 1:
        rows = rows * tenants
    if len(rows) != tenants:
        raise ValueError(
            f"fed_mt_latency={spec!r}: got {len(rows)} per-tenant latency "
            f"specs for a {tenants}-tenant fleet — give 1 (broadcast) or "
            f"exactly {tenants}"
        )
    return _pad_latency_rows(rows)


def parse_class_latency(
    class_specs: Sequence[str], default: str = ""
) -> Tuple[Tuple[float, ...], ...]:
    """Parse per-CLASS latency rows for the heterogeneous population
    plane: one `parse_latency` comma list per class, '' inheriting the
    global `default` (the fed_async_latency knob), all zero-padded to the
    population's common overlap depth D = max over classes. The returned
    f32-ready rows ride as the per-class CDF table the async tick draws
    each client's staleness from (by the client's class)."""
    base = parse_latency(default or "")
    rows = [
        parse_latency(s, name=f"population class[{i}] latency") if s else base
        for i, s in enumerate(class_specs)
    ]
    return _pad_latency_rows(rows)


def make_async_client_step(
    tree_codec: TreeCodec,
    local_train: Callable[[Any, Any, jax.Array], Any],
    w_ref: Any,
    w_hist: Optional[Any],
    version: jax.Array,
    taus: jax.Array,
    alpha: float,
    step: jax.Array,
    key_c2s: jax.Array,
    *,
    layout=None,
    chaos=None,
) -> Callable:
    """The async variant of `make_client_step`: a client at cohort position
    `pos` (global) with drawn staleness `taus[pos]` trains from the model as
    of `version - tau` — read from the replicated `w_hist` ring ([D, ...]
    leaves; None when D == 1, in which case every client reads `w_ref`
    directly and the staged program matches the synchronous client step) —
    and its decoded update is pre-scaled by `1/(1+tau)^alpha` so the
    cohort sum aggregated by `cohort_updates` is already staleness-weighted.

    `taus` is the GLOBAL int32[C] staleness vector (replicated) — cohort
    positions are global, so `taus[pos]` is the direct lookup. Same PRNG
    derivation as the sync step (fold 2*pos / 2*pos + 1): with a
    zero-latency draw the trained updates are bit-identical to sync's.

    Returns the same `(dec_update_tree, new_res, wire4, ok)` contract, so
    `cohort_updates` runs it unchanged. The weight multiply happens BEFORE
    the live-gate SELECT in cohort_updates, and weights are always finite
    and positive — a corrupt payload's Inf/NaN decode times a finite weight
    stays Inf/NaN and is then zeroed by SELECT, never by multiply."""
    use_hist = w_hist is not None

    def client_step(batch_c: Any, res_c: Optional[Any], pos: jax.Array):
        tau = taus[pos]
        if use_hist:
            depth = jax.tree_util.tree_leaves(w_hist)[0].shape[0]
            slot = jnp.mod(version - tau, depth)
            ref_c = jax.tree_util.tree_map(lambda h: h[slot], w_hist)
        else:
            ref_c = w_ref
        p_end = local_train(ref_c, batch_c, jax.random.fold_in(key_c2s, 2 * pos))
        update = tree_sub(p_end, ref_c)
        payloads, comps, spec = tree_codec.encode_tree(
            update, res_c, step, jax.random.fold_in(key_c2s, 2 * pos + 1)
        )
        dec_leaves = [
            tree_codec.codec(path, shape).decode(p, step=step).reshape(shape)
            for path, shape, p in zip(spec.paths, spec.shapes, payloads)
        ]
        if layout is not None:
            buf = layout.pack(payloads)
            if chaos is not None:
                buf = chaos.perturb(buf, step=step, worker=pos)
                recv = layout.unpack(buf)
                dec_recv = tree_codec.decode_tree(recv, spec, step)
            else:
                dec_recv = spec.unflatten(dec_leaves)
            ok = layout.verify(buf)
        else:
            dec_recv = spec.unflatten(dec_leaves)
            ok = jnp.ones((), jnp.float32)
        w_c = staleness_weights(jnp.asarray(tau, jnp.float32), alpha)
        if not _alpha_is_static_zero(alpha):
            dec_recv = jax.tree_util.tree_map(lambda u: u * w_c, dec_recv)
        new_res = (
            spec.unflatten([c - d for c, d in zip(comps, dec_leaves)])
            if res_c is not None
            else None
        )
        wire = tree_codec.wire_tree(payloads, spec)
        wire4 = tuple(
            jnp.asarray(getattr(wire, f), jnp.float32).reshape(()) for f in WIRE_FIELDS
        )
        return dec_recv, new_res, wire4, ok

    return client_step
