"""Tensor parallelism as GSPMD sharding rules.

Megatron-style column/row parallel splits, expressed the TPU-native way:
regex rules mapping flax param paths to `PartitionSpec`s. Parameters get
placed with `NamedSharding`s and XLA's GSPMD partitioner inserts the
all-reduces — no hand-written collectives, and the model code is untouched
(contrast with CUDA frameworks that fork the layer implementations).

Pattern per transformer block: QKV projections split the heads axis
(column parallel — no communication), the attention output projection and
the second MLP matmul split their *input* axis (row parallel — one
all-reduce each), biases follow their kernel's output axis.
"""

from __future__ import annotations

import re
from typing import Any, List, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Sequence[Tuple[str, P]]


def bert_tp_rules(axis: str = "model") -> List[Tuple[str, P]]:
    """Sharding rules for `deepreduce_tpu.models.BertEncoder` params
    (flax `nn.MultiHeadDotProductAttention` + Dense MLP layout)."""
    return [
        # fused-head attention projections: [hidden, heads, head_dim] — shard heads
        (r".*/(query|key|value)/kernel$", P(None, axis, None)),
        (r".*/(query|key|value)/bias$", P(axis, None)),
        # output projection: [heads, head_dim, hidden] — row parallel
        (r".*/out/kernel$", P(axis, None, None)),
        (r".*/out/bias$", P()),
        # MLP: column then row parallel
        (r".*TransformerLayer_\d+/Dense_0/kernel$", P(None, axis)),
        (r".*TransformerLayer_\d+/Dense_0/bias$", P(axis)),
        (r".*TransformerLayer_\d+/Dense_1/kernel$", P(axis, None)),
        (r".*TransformerLayer_\d+/Dense_1/bias$", P()),
        # embeddings / MLM head: shard the vocab axis
        (r".*/tok/embedding$", P(axis, None)),
        (r".*/mlm/kernel$", P(None, axis)),
        (r".*/mlm/bias$", P(axis)),
    ]


def tp_shardings(params: Any, mesh: Mesh, rules: Rules) -> Any:
    """Pytree of `NamedSharding`s for `params`: first rule whose regex
    matches the '/'-joined path wins; unmatched params replicate."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def assign(path, leaf):
        name = "/".join(str(getattr(p, "key", getattr(p, "name", p))) for p in path)
        for pat, spec in compiled:
            if pat.search(name):
                if len(spec) > getattr(leaf, "ndim", 0):
                    break  # malformed match (e.g. scalar) — replicate
                return NamedSharding(mesh, spec)
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(assign, params)


def shard_params(params: Any, mesh: Mesh, rules: Rules) -> Any:
    """Place `params` onto the mesh per the rules (device_put)."""
    return jax.device_put(params, tp_shardings(params, mesh, rules))
