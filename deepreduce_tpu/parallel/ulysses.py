"""Ulysses-style all-to-all sequence parallelism.

The dual of ring attention: instead of rotating K/V around the sequence
axis, one `jax.lax.all_to_all` re-shards the activations from
sequence-sharded ``[b, s/n, h, d]`` to head-sharded ``[b, s, h/n, d]``,
dense attention runs locally over the *full* sequence for the local head
group (big MXU-friendly matmuls, exact softmax, no ring bookkeeping), and a
second all-to-all inverts the layout. Two collectives per attention call
vs ring's n ppermutes; requires heads % axis_size == 0.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from deepreduce_tpu.parallel.ring import ring_self_attention_reference


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: Optional[str],
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Attention over a sequence sharded on ``axis_name`` (inside
    shard_map). Per-device chunks ``[batch, chunk, heads, head_dim]``;
    heads must divide evenly by the axis size. ``axis_name=None`` = local
    dense attention."""
    if axis_name is None:
        return ring_self_attention_reference(q, k, v, causal=causal, scale=scale)

    a2a = lambda x, split, concat: jax.lax.all_to_all(
        x, axis_name, split_axis=split, concat_axis=concat, tiled=True
    )
    # seq-sharded -> head-sharded: split heads(2), gather seq(1)
    qh, kh, vh = (a2a(t, 2, 1) for t in (q, k, v))
    out = ring_self_attention_reference(qh, kh, vh, causal=causal, scale=scale)
    # head-sharded -> seq-sharded: split seq(1), gather heads(2)
    return a2a(out, 1, 2)
