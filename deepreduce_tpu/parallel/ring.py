"""Ring attention — context parallelism for long sequences.

Q/K/V live sequence-sharded over a mesh axis: each device holds one
contiguous chunk ``[batch, seq/n, heads, head_dim]``. Attention runs in
``n`` rounds: every round each device computes blockwise attention of its
resident Q chunk against the K/V block currently in hand (flash-style
streaming softmax so nothing seq×seq ever materializes), then rotates the
K/V block one hop around the ring with `jax.lax.ppermute` — compute
overlaps the ICI transfer and no device ever holds more than one remote
block. This is the TPU-native long-context answer to a capability the
CUDA/NCCL reference lacks entirely (SURVEY.md §5: "long-context: absent").

Numerics: scores and the softmax accumulator run in float32 regardless of
input dtype (bf16 Q/K/V stays bf16 on the MXU matmuls).

Causal mode uses *global* positions (device index × chunk) so the mask is
exact across the ring. Fully-masked (future) blocks still run — one wasted
matmul per skippable block; the streaming max starts at a finite floor so
their contribution is exactly zeroed once any unmasked block lands, and the
diagonal block lands first (round 0), so every row is anchored from the
start.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from deepreduce_tpu.utils.compat import pcast

_NEG_INF = -1e30  # finite floor: keeps exp() well-defined for masked rows


def _block_attend(q, k, v, o, m, l, *, scale, causal, q_off, k_off):
    """One flash-attention block update.

    q [b,cq,h,d], k/v [b,ck,h,d]; accumulators o [b,cq,h,d] f32,
    m,l [b,h,cq] f32. Returns updated (o, m, l).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if causal:
        cq, ck = q.shape[1], k.shape[1]
        qpos = q_off + jnp.arange(cq)
        kpos = k_off + jnp.arange(ck)
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m - m_new)
    l_new = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = o * jnp.moveaxis(alpha, 1, 2)[..., None] + pv
    return o_new, m_new, l_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: Optional[str],
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Multi-head attention over a sequence sharded on ``axis_name``.

    Call inside ``shard_map``; q/k/v are the per-device chunks
    ``[batch, chunk, heads, head_dim]``. With ``axis_name=None`` it
    degrades to plain (local, unsharded) flash attention — the oracle the
    tests compare against.
    """
    b, cq, h, d = q.shape
    scale = (1.0 / d**0.5) if scale is None else scale
    o = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full((b, h, cq), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, cq), jnp.float32)

    if axis_name is None:
        o, m, l = _block_attend(
            q, k, v, o, m, l, scale=scale, causal=causal, q_off=0, k_off=0
        )
        return (o / l[..., None].swapaxes(1, 2)).astype(q.dtype)

    n = jax.lax.psum(1, axis_name)
    me = jax.lax.axis_index(axis_name)
    ck = k.shape[1]
    perm = [(j, (j + 1) % n) for j in range(n)]
    # fresh accumulators are device-invariant; mark them varying over the
    # ring axis so the fori_loop carry type is stable round-to-round
    o, m, l = pcast((o, m, l), (axis_name,), to="varying")

    def body(i, carry):
        o, m, l, k_blk, v_blk = carry
        src = (me - i) % n  # which global block is resident this round
        o, m, l = _block_attend(
            q, k_blk, v_blk, o, m, l,
            scale=scale, causal=causal, q_off=me * cq, k_off=src * ck,
        )
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return o, m, l, k_blk, v_blk

    o, m, l, _, _ = jax.lax.fori_loop(0, n, body, (o, m, l, k, v))
    return (o / l[..., None].swapaxes(1, 2)).astype(q.dtype)


def ring_self_attention_reference(q, k, v, *, causal=False, scale=None):
    """Unsharded O(s²) oracle for tests: plain softmax attention."""
    d = q.shape[-1]
    scale = (1.0 / d**0.5) if scale is None else scale
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)
