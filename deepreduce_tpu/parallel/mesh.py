"""Mesh construction helpers.

The reference pins its world layout in a shell script (`mpirun -np 8 -H
host:1,...`, run_deepreduce.sh:4-9); here the layout is a
`jax.sharding.Mesh` with named axes, and every collective in the framework
names the axis it rides on.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def factor_devices(n: int, axes: Sequence[str]) -> Dict[str, int]:
    """Factor a device count into mesh axis sizes, greedily giving the
    earlier axes the larger factors (data first, then seq/model).

    8, ('data','seq') -> {'data': 4, 'seq': 2};  7 -> {'data': 7, 'seq': 1}.
    """
    sizes = {a: 1 for a in axes}
    remaining = n
    names = list(axes)
    for i, name in enumerate(names[:-1]):
        # largest factor <= sqrt-balanced split that divides `remaining`,
        # biased so the leading axis keeps the bulk
        target = max(1, round(remaining ** (1.0 - 1.0 / (len(names) - i))))
        best = 1
        for f in range(1, remaining + 1):
            if remaining % f == 0 and f <= max(target, 1):
                best = f
        # leading axis gets the co-factor (the big one)
        sizes[name] = remaining // best if i == 0 else best
        remaining = remaining // sizes[name]
    sizes[names[-1]] = remaining
    return sizes


def make_mesh(
    axes: Dict[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    dcn_axis: Optional[str] = None,
) -> Mesh:
    """Mesh from {axis_name: size}. Sizes must multiply to the device count
    used. `make_mesh({'data': 4, 'seq': 2})` on 8 devices.

    `dcn_axis` names the axis that crosses the inter-slice DCN link (it
    must be the LEADING axis, so the remaining axes stay inside a slice).
    When set, the device layout comes from
    `mesh_utils.create_hybrid_device_mesh` — on real multi-slice hardware
    that places each mesh row within one slice, which is the entire
    bandwidth premise of the hierarchical exchange. On a single slice or a
    virtual CPU mesh, where hybrid construction cannot apply, a plain
    reshape is the right layout; but if the device set spans real slices
    and DCN-aware construction fails, this raises instead of silently
    handing back a slice-oblivious layout (a wrong layout would route the
    dense psum over DCN — inverting the premise, not degrading it)."""
    shape: Tuple[int, ...] = tuple(axes.values())
    n = int(np.prod(shape))
    devs = list(devices) if devices is not None else jax.devices()[:n]
    if len(devs) != n:
        raise ValueError(f"need {n} devices for mesh {axes}, have {len(devs)}")
    names = tuple(axes.keys())
    if dcn_axis is None:
        return Mesh(np.asarray(devs).reshape(shape), names)
    if names[0] != dcn_axis:
        raise ValueError(
            f"dcn_axis={dcn_axis!r} must be the leading mesh axis, got "
            f"axis order {names}"
        )
    n_slices = axes[dcn_axis]
    per_slice = n // max(1, n_slices)
    try:  # DCN-aware layout when more than one real slice exists
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(
            (per_slice,), (n_slices,), devices=devs
        ).reshape(shape)
    except Exception as e:
        if any(getattr(dev, "slice_index", 0) for dev in devs):
            raise RuntimeError(
                "multi-slice device set but DCN-aware mesh construction "
                f"failed ({e!r}); refusing a slice-oblivious layout"
            ) from e
        arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, names)
