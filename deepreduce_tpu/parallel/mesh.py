"""Mesh construction helpers.

The reference pins its world layout in a shell script (`mpirun -np 8 -H
host:1,...`, run_deepreduce.sh:4-9); here the layout is a
`jax.sharding.Mesh` with named axes, and every collective in the framework
names the axis it rides on.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def factor_devices(n: int, axes: Sequence[str]) -> Dict[str, int]:
    """Factor a device count into mesh axis sizes, greedily giving the
    earlier axes the larger factors (data first, then seq/model).

    8, ('data','seq') -> {'data': 4, 'seq': 2};  7 -> {'data': 7, 'seq': 1}.
    """
    sizes = {a: 1 for a in axes}
    remaining = n
    names = list(axes)
    for i, name in enumerate(names[:-1]):
        # largest factor <= sqrt-balanced split that divides `remaining`,
        # biased so the leading axis keeps the bulk
        target = max(1, round(remaining ** (1.0 - 1.0 / (len(names) - i))))
        best = 1
        for f in range(1, remaining + 1):
            if remaining % f == 0 and f <= max(target, 1):
                best = f
        # leading axis gets the co-factor (the big one)
        sizes[name] = remaining // best if i == 0 else best
        remaining = remaining // sizes[name]
    sizes[names[-1]] = remaining
    return sizes


def make_mesh(
    axes: Dict[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Mesh from {axis_name: size}. Sizes must multiply to the device count
    used. `make_mesh({'data': 4, 'seq': 2})` on 8 devices."""
    shape: Tuple[int, ...] = tuple(axes.values())
    n = int(np.prod(shape))
    devs = list(devices) if devices is not None else jax.devices()[:n]
    if len(devs) != n:
        raise ValueError(f"need {n} devices for mesh {axes}, have {len(devs)}")
    return Mesh(np.asarray(devs).reshape(shape), tuple(axes.keys()))
