"""Two-level ICI x DCN gradient exchange — the multi-slice deployment shape.

The reference's world is flat: 8 MPI ranks on one 100 Gbps network, one
allgather over all of them (run_deepreduce.sh:4-9). A TPU fleet is not
flat: devices within a slice are joined by ICI (fast, wide), slices are
joined by DCN (the scarce link — the role the reference's 100 Mbps
simulated-FL link plays in paper Table 4). Compression belongs on the
scarce link only:

    1. dense `psum` of gradients over the `ici` axis — full-precision
       slice mean, rides ICI where bandwidth is nearly free;
    2. compressed exchange (any DeepReduce codec config) over the `dcn`
       axis — the usual sparsify/encode/all_gather/decode/aggregate, with
       wire accounting now measuring exactly the bytes that cross DCN.

Every device in a slice enters step 2 with the identical slice-mean
gradient and the same PRNG key, so all ICI replicas of a DCN group run the
same deterministic exchange and agree bit-for-bit — no second broadcast is
needed (the decode-side determinism contract that the bloom policies
already guarantee, bloom_filter_compression.cc:217-218).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepreduce_tpu.comm import GradientExchanger
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.metrics import WireStats


def make_hybrid_mesh(n_slices: int, per_slice: int,
                     dcn_axis: str = "dcn", ici_axis: str = "ici"):
    """(dcn, ici) mesh. On real multi-slice hardware prefer
    `mesh_utils.create_hybrid_device_mesh` (DCN-aware device order); on a
    single slice / virtual CPU mesh a plain reshape is the right layout."""
    from jax.sharding import Mesh

    devices = jax.devices()
    need = n_slices * per_slice
    if len(devices) < need:
        raise ValueError(f"need {need} devices, have {len(devices)}")
    try:  # DCN-aware layout when more than one real slice exists
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_hybrid_device_mesh(
            (per_slice,), (n_slices,), devices=devices[:need]
        ).reshape(n_slices, per_slice)
    except Exception as e:
        # On real multi-slice hardware a wrong layout inverts the bandwidth
        # premise (dense psum would cross DCN) — never fall back silently.
        if any(getattr(dev, "slice_index", 0) for dev in devices[:need]):
            raise RuntimeError(
                "multi-slice device set but DCN-aware mesh construction "
                f"failed ({e!r}); refusing a slice-oblivious layout"
            ) from e
        arr = np.array(devices[:need]).reshape(n_slices, per_slice)
    return Mesh(arr, (dcn_axis, ici_axis))


class HierarchicalExchanger:
    """ICI-dense + DCN-compressed exchange. Same call contract as
    `GradientExchanger.exchange`, for use inside shard_map over BOTH axes.

    Correctness contract: every ICI replica within a slice must run the
    *identical* stochastic encode, otherwise model replicas silently
    desynchronize under stochastic codecs. This class enforces the
    contract by construction — `exchange` replaces each replica's key
    with ICI-replica 0's key (one tiny all_gather over the ici axis), so
    a caller that accidentally folds the ici position into the key still
    gets bit-identical encodes across the slice."""

    def __init__(self, grads_like: Any, cfg: DeepReduceConfig, *,
                 dcn_axis: str = "dcn", ici_axis: str = "ici",
                 num_slices: Optional[int] = None):
        self.ici_axis = ici_axis
        self.dcn_axis = dcn_axis
        self.exchanger = GradientExchanger(
            grads_like, cfg, axis_name=dcn_axis, num_workers=num_slices
        )

    def init_state(self, grads_like: Any) -> Any:
        return self.exchanger.init_state(grads_like)

    def exchange(
        self,
        grads: Any,
        state: Any,
        *,
        step: jax.Array = 0,
        key: Optional[jax.Array] = None,
    ) -> Tuple[Any, Any, WireStats]:
        n_ici = jax.lax.psum(1, self.ici_axis)
        slice_mean = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, self.ici_axis) / n_ici, grads
        )
        # enforce the class contract: every ICI replica of a DCN group runs
        # the identical stochastic encode. Broadcast replica 0's key over
        # the ici axis (identity when the caller already passed a shared
        # key; repairs an accidentally position-folded key).
        if key is not None:
            if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):  # typed key
                kdata = jax.lax.all_gather(jax.random.key_data(key), self.ici_axis)[0]
                key = jax.random.wrap_key_data(kdata, impl=jax.random.key_impl(key))
            else:  # raw uint32 PRNGKey array
                key = jax.lax.all_gather(key, self.ici_axis)[0]
        return self.exchanger.exchange(slice_mean, state, step=step, key=key)

    def payload_bytes(self, grads_like: Any) -> int:
        """Bytes crossing DCN per device per step (ICI psum not counted —
        it is the cheap link by construction)."""
        return self.exchanger.payload_bytes(grads_like)
