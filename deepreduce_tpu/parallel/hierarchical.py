"""Two-level ICI x DCN gradient exchange — the multi-slice deployment shape.

The reference's world is flat: 8 MPI ranks on one 100 Gbps network, one
allgather over all of them (run_deepreduce.sh:4-9). A TPU fleet is not
flat: devices within a slice are joined by ICI (fast, wide), slices are
joined by DCN (the scarce link — the role the reference's 100 Mbps
simulated-FL link plays in paper Table 4). Compression belongs on the
scarce link only:

    1. slice reduction over the `ici` axis — either a dense full-precision
       `psum` or the int8 two-phase quantized allreduce (qar.py), selected
       by ``cfg.hier_ici``; rides ICI where bandwidth is nearly free;
    2. compressed exchange over the `dcn` axis — any of the framework's
       cross-worker routes: the fused allgather stack (per-tensor or
       `BucketedExchanger` when ``bucket_bytes`` is set), the sparse_rs
       in-collective routes (including `quantized`/`adaptive`), dense
       allreduce, or qar. ``cfg.hier_dcn='auto'`` lets
       `costmodel.select_hier_plan` rewrite the route at construction.

Every device in a slice enters step 2 with the identical slice-mean
gradient and the same PRNG key, so all ICI replicas of a DCN group run the
same deterministic exchange and agree bit-for-bit — no second broadcast is
needed (the decode-side determinism contract that the bloom policies
already guarantee, bloom_filter_compression.cc:217-218).

Wire accounting is split by fabric: `payload_bytes()` / WireStats
index+value bits stay DCN-only (the scarce-link numbers every committed
bench compares), while the ICI leg (slice psum or qar phases, plus the
key-repair all_gather) is reported under the separate `WireStats.ici_bits`
counter and the `exchange/ici` span.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepreduce_tpu import costmodel, qar
from deepreduce_tpu.comm import GradientExchanger
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.metrics import WireStats
from deepreduce_tpu.parallel.mesh import make_mesh
from deepreduce_tpu.telemetry import spans


def make_hybrid_mesh(n_slices: int, per_slice: int,
                     dcn_axis: str = "dcn", ici_axis: str = "ici"):
    """(dcn, ici) mesh — thin alias over the one mesh factory.

    `make_mesh(..., dcn_axis=...)` owns the DCN-aware device layout
    (`mesh_utils.create_hybrid_device_mesh`) and the refuse-silent-fallback
    guard for real multi-slice device sets."""
    return make_mesh(
        {dcn_axis: n_slices, ici_axis: per_slice}, dcn_axis=dcn_axis
    )


def _total_elems(grads_like: Any) -> int:
    return sum(
        int(np.prod(leaf.shape)) if leaf.shape else 1
        for leaf in jax.tree_util.tree_leaves(grads_like)
    )


def _cfg_dcn_leg(
    cfg: DeepReduceConfig, d: int, n_slices: Optional[int],
    profile: Optional[costmodel.MachineProfile] = None,
) -> Optional[str]:
    """The cost-model leg name of the DCN route this config describes, or
    None when the route has no model row (allreduce / qar across DCN)."""
    if cfg.communicator == "sparse_rs":
        if cfg.rs_mode != "auto":
            return cfg.rs_mode
        if n_slices is None:
            return None
        return costmodel.select_rs_mode(
            d, n_slices, cfg.compress_ratio,
            headroom=cfg.rs_headroom, out_headroom=cfg.rs_out_headroom,
            block=cfg.rs_block_size, rows=cfg.rs_sketch_rows,
            cols=cfg.rs_sketch_cols, profile=profile,
        )
    if cfg.communicator == "allgather":
        return "bucketed" if cfg.bucket_bytes else "fused"
    return None


class HierarchicalExchanger:
    """ICI-reduce + DCN-compressed exchange. Same call contract as
    `GradientExchanger.exchange`, for use inside shard_map over BOTH axes.

    Correctness contract: every ICI replica within a slice must run the
    *identical* stochastic encode, otherwise model replicas silently
    desynchronize under stochastic codecs. This class enforces the
    contract by construction — `exchange` replaces each replica's key
    with ICI-replica 0's key (one tiny all_gather over the ici axis), so
    a caller that accidentally folds the ici position into the key still
    gets bit-identical encodes across the slice.

    With ``cfg.hier_ici='auto'`` or ``cfg.hier_dcn='auto'`` the
    construction-time planner (`costmodel.select_hier_plan`) argmins the
    two legs jointly; a chosen DCN route rewrites the inner exchanger's
    config (e.g. to ``communicator='sparse_rs', rs_mode='quantized'``),
    and the winning plan is exposed as ``self.plan`` so drivers/bench can
    report it."""

    def __init__(self, grads_like: Any, cfg: DeepReduceConfig, *,
                 dcn_axis: str = "dcn", ici_axis: str = "ici",
                 num_slices: Optional[int] = None,
                 per_slice: Optional[int] = None,
                 profile: Optional[costmodel.MachineProfile] = None):
        self.cfg = cfg
        self.ici_axis = ici_axis
        self.dcn_axis = dcn_axis
        self.num_slices = num_slices
        self.per_slice = per_slice
        if profile is None and cfg.profile is not None:
            profile = costmodel.load_profile(cfg.profile)
        self.profile = profile
        d = _total_elems(grads_like)
        self.ici_leg = cfg.hier_ici
        self.plan: Optional[Dict] = None
        inner_cfg = cfg
        if "auto" in (cfg.hier_ici, cfg.hier_dcn):
            if num_slices is None or per_slice is None:
                raise ValueError(
                    "hier auto-planning needs the static mesh split: "
                    "construct HierarchicalExchanger(..., num_slices="
                    "mesh.shape['dcn'], per_slice=mesh.shape['ici'])"
                )
            if cfg.hier_dcn == "auto":
                # candidate cross-slice routes the planner may rewrite to.
                # bucketed and fused share the allgather wire model; offer
                # whichever the config can express (bucket_bytes set or not)
                # so the rewrite never invents a bucket partition.
                dcn_legs = (("bucketed",) if cfg.bucket_bytes else ("fused",)) + (
                    "sparse", "adaptive", "quantized", "sketch",
                )
            else:
                leg = _cfg_dcn_leg(cfg, d, num_slices, profile)
                if leg is None:
                    raise ValueError(
                        "hier_ici='auto' needs a cost-modelable DCN leg to "
                        "argmin against, but "
                        f"communicator={cfg.communicator!r} has no "
                        "cross-slice model row — pick hier_ici explicitly"
                    )
                dcn_legs = (leg,)
            self.plan = costmodel.select_hier_plan(
                d, num_slices, per_slice, cfg.compress_ratio,
                ici_block=cfg.bucket_size,
                ici_legs=None if cfg.hier_ici == "auto" else (cfg.hier_ici,),
                dcn_legs=dcn_legs,
                headroom=cfg.rs_headroom, out_headroom=cfg.rs_out_headroom,
                block=cfg.rs_block_size, rows=cfg.rs_sketch_rows,
                cols=cfg.rs_sketch_cols, profile=profile,
            )
            if cfg.hier_ici == "auto":
                self.ici_leg = self.plan["ici"]
            if cfg.hier_dcn == "auto":
                leg = self.plan["dcn"]
                if leg in ("fused", "bucketed"):
                    inner_cfg = dataclasses.replace(
                        cfg, communicator="allgather", rs_mode="sparse"
                    )
                else:
                    inner_cfg = dataclasses.replace(
                        cfg, communicator="sparse_rs", rs_mode=leg,
                        bucket_bytes=None,
                    )
        self.inner_cfg = inner_cfg
        self.exchanger = GradientExchanger(
            grads_like, inner_cfg, axis_name=dcn_axis, num_workers=num_slices,
            profile=profile,
        )

    # --- surface the GradientExchanger attributes drivers consume -------- #

    @property
    def axis_name(self):
        """Both mesh axes — the loss/metric pmean in make_worker_step must
        average over every device, not just the dcn groups."""
        return (self.dcn_axis, self.ici_axis)

    @property
    def num_workers(self) -> Optional[int]:
        if self.num_slices is None or self.per_slice is None:
            return None
        return self.num_slices * self.per_slice

    @property
    def num_buckets(self) -> int:
        return self.exchanger.num_buckets

    def init_state(self, grads_like: Any) -> Any:
        return self.exchanger.init_state(grads_like)

    # --- the exchange ----------------------------------------------------- #

    def exchange(
        self,
        grads: Any,
        state: Any,
        *,
        step: jax.Array = 0,
        key: Optional[jax.Array] = None,
        collect: Optional[dict] = None,
        mask: Optional[jax.Array] = None,
    ) -> Tuple[Any, Any, WireStats]:
        if mask is not None:
            raise ValueError(
                "hierarchical exchange takes no participation mask (the ici "
                "slice mean is an unmasked psum; config rejects "
                "resilience=True with hier=True)"
            )
        n_ici = jax.lax.psum(1, self.ici_axis)  # static mesh-axis size
        ici_bits = 0.0
        with spans.span("exchange/ici"):
            if self.ici_leg == "qar":
                from jax.flatten_util import ravel_pytree

                # encode/decode sub-spans inside the ici leg: calibrate()
                # charges them to t_enc/t_dec (self-time keeps the wire
                # share in exchange/ici itself). Route "qar" keeps this
                # codec's row distinct from the DCN leg's — ici-leg encode
                # must not pollute a DCN route's fitted seconds.
                with spans.span("exchange/encode", route="qar"):
                    flat, unravel = ravel_pytree(grads)
                    d = flat.shape[0]
                    n = qar.pad_len(d, n_ici, self.cfg.bucket_size)
                    padded = flat.astype(jnp.float32)
                    if n > d:
                        padded = jnp.zeros((n,), jnp.float32).at[:d].set(padded)
                kq = key if key is not None else jax.random.PRNGKey(step)
                mean = qar.quantized_allreduce(
                    padded, self.ici_axis, n_ici,
                    key=kq,
                    quantum_num=self.cfg.quantum_num,
                    bucket_size=self.cfg.bucket_size,
                    use_pallas=self.cfg.use_pallas,
                )
                with spans.span("exchange/decode", route="qar"):
                    slice_mean = unravel(mean[:d].astype(flat.dtype))
                ici_bits += qar.wire_bits_per_worker(d, n_ici, self.cfg.bucket_size)
            else:
                slice_mean = jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, self.ici_axis) / n_ici, grads
                )
                if n_ici > 1:
                    d = _total_elems(grads)
                    ici_bits += 2.0 * (n_ici - 1) / n_ici * 32.0 * d
            # enforce the class contract: every ICI replica of a DCN group
            # runs the identical stochastic encode. Broadcast replica 0's
            # key over the ici axis (identity when the caller already passed
            # a shared key; repairs an accidentally position-folded key).
            if key is not None:
                if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):  # typed key
                    kdata = jax.random.key_data(key)
                    ici_bits += kdata.size * 32.0 * (n_ici - 1)
                    kdata = jax.lax.all_gather(kdata, self.ici_axis)[0]
                    key = jax.random.wrap_key_data(
                        kdata, impl=jax.random.key_impl(key)
                    )
                else:  # raw uint32 PRNGKey array
                    ici_bits += key.size * 32.0 * (n_ici - 1)
                    key = jax.lax.all_gather(key, self.ici_axis)[0]
        with spans.span("exchange/dcn"):
            agg, new_state, wire = self.exchanger.exchange(
                slice_mean, state, step=step, key=key, collect=collect
            )
        return agg, new_state, dataclasses.replace(
            wire,
            ici_bits=wire.ici_bits + jnp.asarray(ici_bits, jnp.float32),
        )

    # --- accounting -------------------------------------------------------- #

    def payload_bytes(self, grads_like: Any) -> int:
        """Bytes crossing DCN per device per step — DCN-only BY CONTRACT.

        The ICI leg (slice-mean psum or qar phases) and the key-repair
        all_gather never touch the scarce link and are deliberately
        excluded here so this number stays comparable with every flat
        exchange's `payload_bytes()`. ICI traffic is accounted separately:
        statically via `ici_payload_bytes()`, and per step under the
        `WireStats.ici_bits` counter the exchange returns."""
        return self.exchanger.payload_bytes(grads_like)

    def ici_payload_bytes(self, grads_like: Any,
                          per_slice: Optional[int] = None) -> float:
        """Ring-adjusted bytes one device moves on the ICI fabric per step
        for the slice-reduction leg (excludes the ~8-byte key-repair
        gather, which only exists when the caller passes a key)."""
        p = per_slice if per_slice is not None else self.per_slice
        if p is None:
            raise ValueError(
                "ici_payload_bytes needs the static slice size: pass "
                "per_slice= here or at construction"
            )
        d = _total_elems(grads_like)
        if self.ici_leg == "qar":
            return qar.wire_bits_per_worker(d, p, self.cfg.bucket_size) / 8.0
        if p <= 1:
            return 0.0
        return 2.0 * (p - 1) / p * 4.0 * d
