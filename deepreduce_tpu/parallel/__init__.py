"""Parallelism strategies over the device mesh.

The reference is data-parallel only (SURVEY.md §2.5: Horovod DP over
MPI+NCCL, run_deepreduce.sh:4-9); this package carries the framework past
it: the DP gradient-exchange communicator lives in `deepreduce_tpu.comm`,
and long-context sequence/context parallelism + tensor parallelism live
here, all expressed as XLA collectives (`ppermute`, `all_to_all`, GSPMD
sharding) over a `jax.sharding.Mesh` — ICI-native, no NCCL/MPI.

- `mesh`      — mesh construction helpers (factor a device count into
                named axes: data / seq / model).
- `ring`      — ring attention: blockwise flash-style attention with K/V
                blocks rotating around the sequence axis via `ppermute`.
- `ulysses`   — all-to-all sequence parallelism (DeepSpeed-Ulysses style):
                scatter heads / gather sequence, dense attention, invert.
- `tp`        — tensor-parallel GSPMD sharding rules (Megatron-style
                column/row splits expressed as PartitionSpecs; XLA inserts
                the collectives).
- `hierarchical` — two-level ICI x DCN exchange: dense psum within a
                slice, DeepReduce-compressed allgather across slices (the
                multi-slice deployment of the communicator).
"""

from deepreduce_tpu.parallel.mesh import factor_devices, make_mesh
from deepreduce_tpu.parallel.ring import ring_attention
from deepreduce_tpu.parallel.ulysses import ulysses_attention
from deepreduce_tpu.parallel.tp import bert_tp_rules, tp_shardings
from deepreduce_tpu.parallel.hierarchical import (
    HierarchicalExchanger,
    make_hybrid_mesh,
)

__all__ = [
    "factor_devices",
    "make_mesh",
    "ring_attention",
    "ulysses_attention",
    "bert_tp_rules",
    "tp_shardings",
    "HierarchicalExchanger",
    "make_hybrid_mesh",
]
