"""Residual error-feedback memory, as a functional optimizer-state pytree.

Reference parity: GRACE's ``'memory': 'residual'`` on the PyTorch path
(run_deepreduce.sh:35,107) and the TF ``Compressor.memory_compensate`` /
``memory_update`` pair (/root/reference/tensorflow/deepreduce.py:31-52):

    compensated = beta * residual + gamma * grad
    residual'   = compensated - decompressed

(The TF reference re-creates a zero residual variable at graph build —
tensorflow/deepreduce.py:39-40 — making its residual a no-op; we implement
the *spec*, the accumulating residual, per SURVEY.md §2.7.)
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp


def init(params_or_grads: Any) -> Any:
    """Zero residual with the same pytree structure as the gradients."""
    return jax.tree_util.tree_map(jnp.zeros_like, params_or_grads)


def compensate(grads: Any, residuals: Any, *, beta: float = 1.0, gamma: float = 1.0) -> Any:
    """compensated = beta * residual + gamma * grad (tensorflow/deepreduce.py:41)."""
    return jax.tree_util.tree_map(lambda r, g: beta * r + gamma * g, residuals, grads)


def update(compensated: Any, decompressed: Any) -> Any:
    """residual' = compensated - decompressed (tensorflow/deepreduce.py:43-52).

    `decompressed` is *this worker's own* decompressed contribution, so the
    residual holds exactly the gradient mass the codec dropped this step.
    """
    return jax.tree_util.tree_map(lambda c, d: c - d, compensated, decompressed)
