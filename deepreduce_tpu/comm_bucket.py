"""Bucketed tensor-fusion exchange: one codec + one collective per BUCKET.

The per-tensor fused path (comm.py) builds one TensorCodec, one top-k, and
one payload per gradient leaf, then ships everything in a single bulk
all_gather. On many-leaf models (LSTM gate stacks, MobileNet's dozens of
tiny BN/bias tensors) the encode side pays O(leaves) fixed codec cost and
the one bulk collective serializes the whole transfer ahead of the decode
tail.

This module trades both costs down:

* `partition_buckets` splits the pytree into size-balanced buckets of at
  most ``cfg.bucket_bytes`` dense f32 bytes. Leaves too big for a bucket
  stay SOLO (and keep their leaf name, so their codec/PRNG contract is
  bit-identical to the per-tensor path); the small leaves are packed
  first-fit-decreasing into fused buckets and concatenated into one
  contiguous f32 super-tensor each.
* `BucketedExchanger` runs ONE TensorCodec per bucket — sparsifier +
  index/value codec cost drops from O(leaves) to O(buckets) — with the
  bucket's slot budget set to the SUM of its member leaves' per-tensor
  budgets (`sparse.bucket_num_slots`), so bucketing never changes the
  total wire budget.
* One `all_gather` per bucket, software-pipelined in trace order: the
  collective for bucket b+1 is dispatched BEFORE the decode of bucket b
  (the SparCML streaming shape), so XLA can overlap the next transfer
  with the current decode.
* A third schedule streams each bucket out of the BACKWARD pass itself
  (`run_streaming_bucket`, driven by comm_stream.py's custom_vjp hooks
  when ``cfg.stream_exchange`` is on): the encode + all_gather dispatch
  the moment backprop produces the bucket's last member gradient, pinned
  in dispatch order by an `optimization_barrier` token chain. Pair it
  with ``partition_buckets(order="reverse")`` so buckets fill in
  backward-completion order.

Slicing a bucket's aggregate back into leaf shapes is static offsets
(`split_bucket`), so residual error-feedback, WireStats accounting, and
the deterministic policy contract carry over unchanged. The per-bucket
wire format reuses `PayloadLayout`, and the per-bucket decode reuses the
shared `decode_gathered_loop` / `decode_gathered_vmap` machinery.
"""
from __future__ import annotations

import contextlib
import dataclasses
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu.comm import (
    PayloadLayout,
    decode_gathered_loop,
    decode_gathered_vmap,
)
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.metrics import WireStats
from deepreduce_tpu.resilience.chaos import ChaosInjector
from deepreduce_tpu.sparse import bucket_num_slots, per_tensor_key
from deepreduce_tpu.telemetry import spans
from deepreduce_tpu.wrappers import TensorCodec


@dataclasses.dataclass(frozen=True)
class BucketSpec:
    """One bucket of the partition: which leaves it fuses (in concat
    order), their flat element counts, and each leaf's static offset into
    the bucket's f32 super-tensor. ``solo`` buckets hold exactly one leaf
    and are labelled by that leaf's name, so their codec name — and hence
    their deterministic per-tensor PRNG key — matches the unbucketed
    path exactly."""

    label: str
    names: Tuple[str, ...]
    sizes: Tuple[int, ...]
    offsets: Tuple[int, ...]
    total: int
    solo: bool


def partition_buckets(
    names: Sequence[str],
    sizes: Sequence[int],
    bucket_bytes: int,
    *,
    order: str = "trace",
) -> List[BucketSpec]:
    """Deterministic size-balanced partition computed from (name, size)
    pairs alone — every worker derives the identical bucket list from the
    gradient shapes with no coordination.

    Leaves whose dense f32 payload exceeds ``bucket_bytes`` become solo
    buckets. With ``order="trace"`` (the default, byte-identical to the
    r09 behavior) the remaining small leaves are packed
    first-fit-decreasing (ties broken by original leaf order) into fused
    buckets of at most ``bucket_bytes``, and the bucket list is ordered by
    each bucket's earliest member leaf.

    ``order="reverse"`` is the backward-completion policy the streaming
    schedule wants: small leaves are packed next-fit walking the leaf
    indices in DESCENDING order, so each fused bucket holds a contiguous
    reverse-trace run — backprop, which produces gradients in reverse
    forward order, finishes an entire bucket before touching the next.
    The bucket list is sorted by descending earliest member (= ascending
    backward completion time), so bucket 0 is the first one backprop can
    close. Within a fused bucket the leaves are still concatenated in
    original pytree order, keeping `split_bucket` offsets and the codec
    slot budget independent of the policy.
    """
    if order not in ("trace", "reverse"):
        raise ValueError(f"order must be 'trace' or 'reverse', got {order!r}")
    if len(names) != len(sizes):
        raise ValueError("names and sizes must align")
    if len(set(names)) != len(names):
        raise ValueError("duplicate leaf names")
    cap = max(1, int(bucket_bytes) // 4)  # f32 elements per fused bucket
    index = {n: i for i, n in enumerate(names)}

    def _solo(i: int) -> BucketSpec:
        return BucketSpec(
            label=names[i],
            names=(names[i],),
            sizes=(int(sizes[i]),),
            offsets=(0,),
            total=int(sizes[i]),
            solo=True,
        )

    small: List[int] = []
    specs: List[BucketSpec] = []
    for i, size in enumerate(sizes):
        if int(size) <= 0:
            raise ValueError(f"leaf {names[i]!r} has non-positive size {size}")
        (specs if int(size) > cap else small).append(
            _solo(i) if int(size) > cap else i
        )

    bins: List[List[int]] = []
    loads: List[int] = []
    if order == "reverse":
        # Next-fit over DESCENDING leaf index: the current bin takes
        # consecutive reverse-trace leaves until one no longer fits, then a
        # fresh bin opens. Strict contiguity costs some packing density vs
        # FFD, but it is exactly what makes a streaming bucket close as a
        # single uninterrupted stretch of the backward pass.
        for i in sorted(small, reverse=True):
            size = int(sizes[i])
            if bins and loads[-1] + size <= cap:
                bins[-1].append(i)
                loads[-1] += size
            else:
                bins.append([i])
                loads.append(size)
    else:
        # First-fit-decreasing over the small leaves: visit by descending
        # size (original order breaks ties), drop each into the first bin
        # with room. Deterministic, and within ~22% of the optimal bin
        # count.
        for i in sorted(small, key=lambda i: (-int(sizes[i]), i)):
            size = int(sizes[i])
            for b, load in enumerate(loads):
                if load + size <= cap:
                    bins[b].append(i)
                    loads[b] += size
                    break
            else:
                bins.append([i])
                loads.append(size)

    fused_count = 0
    for members in bins:
        if len(members) == 1:
            specs.append(_solo(members[0]))
            continue
        members = sorted(members)  # concat in original pytree order
        label = f"bucket{fused_count}"
        fused_count += 1
        while label in index:  # collision with a literal leaf name
            label += "_"
        offsets, off = [], 0
        for i in members:
            offsets.append(off)
            off += int(sizes[i])
        specs.append(
            BucketSpec(
                label=label,
                names=tuple(names[i] for i in members),
                sizes=tuple(int(sizes[i]) for i in members),
                offsets=tuple(offsets),
                total=off,
                solo=False,
            )
        )

    if order == "reverse":
        # Backward-completion order: backprop emits gradients from the last
        # leaf down to the first, so a bucket is complete once its
        # EARLIEST-forward member arrives — the bucket with the largest
        # min-index closes first.
        specs.sort(key=lambda s: -min(index[n] for n in s.names))
    else:
        specs.sort(key=lambda s: min(index[n] for n in s.names))
    return specs


class BucketedExchanger:
    """Per-bucket encode → all_gather → decode, built by GradientExchanger
    when ``cfg.bucket_bytes`` is set. Holds one TensorCodec and one
    PayloadLayout per bucket; `run` performs the whole exchange on the
    compensated flat-gradient dict and hands back f32 leaf dicts plus
    per-bucket WireStats and payloads (for fp_stats / telemetry)."""

    def __init__(
        self,
        names: Sequence[str],
        shapes: Sequence[Tuple[int, ...]],
        cfg: DeepReduceConfig,
        *,
        axis_name: str,
        points=None,
    ):
        self.cfg = cfg
        self.axis_name = axis_name
        self.leaf_shapes: Dict[str, Tuple[int, ...]] = {
            n: tuple(int(x) for x in s) for n, s in zip(names, shapes)
        }
        sizes = [_numel(self.leaf_shapes[n]) for n in names]
        self.specs: Tuple[BucketSpec, ...] = tuple(
            partition_buckets(
                list(names), sizes, cfg.bucket_bytes, order=cfg.bucket_order
            )
        )
        # per-bucket operating points from the adaptive controller's ladder:
        # a (ratio, fpr-or-None) pair per bucket, in spec order, overriding
        # the config's global ratio/fpr for that bucket's codec and slot
        # budget. The partition above never depends on the points (it is a
        # pure function of (name, size, bucket_bytes)), so the bucket count
        # and spec order are identical across every ladder rung — which is
        # what lets residuals and accumulators carry across rung switches.
        if points is not None and len(points) != len(self.specs):
            raise ValueError(
                f"points must carry one (ratio, fpr) per bucket: got "
                f"{len(points)} for {len(self.specs)} buckets"
            )
        self.points = None if points is None else tuple(
            (float(r), None if f is None else float(f)) for r, f in points
        )
        self.codecs: Dict[str, TensorCodec] = {}
        self.layouts: Dict[str, PayloadLayout] = {}
        self.payload_nbytes = 0
        for b, spec in enumerate(self.specs):
            ratio, fpr = (
                (cfg.compress_ratio, cfg.fpr)
                if self.points is None
                else self.points[b]
            )
            cfg_b = cfg if self.points is None else dataclasses.replace(
                cfg,
                compress_ratio=ratio,
                **({} if fpr is None else {"fpr": fpr}),
            )
            # The bucket's slot budget is the SUM of its member leaves'
            # per-tensor budgets, so fusing never changes the total wire
            # budget (per-leaf rounding and the max(1, .) floor included).
            codec = TensorCodec(
                (spec.total,),
                cfg_b,
                name=spec.label,
                slots=bucket_num_slots(spec.sizes, ratio),
            )
            payload_sds = jax.eval_shape(
                lambda g, c=codec: c.encode(g, step=0, key=jax.random.PRNGKey(0)),
                jax.ShapeDtypeStruct((spec.total,), jnp.float32),
            )
            self.codecs[spec.label] = codec
            self.layouts[spec.label] = PayloadLayout(
                payload_sds, checksum=bool(cfg.payload_checksum)
            )
            # the layout's exact wire size — includes the optional trailing
            # checksum word, which the all_gather operand carries too
            self.payload_nbytes += self.layouts[spec.label].nbytes
        self._chaos = ChaosInjector.from_config(cfg)
        self._checksum = bool(cfg.payload_checksum)

    @property
    def num_buckets(self) -> int:
        return len(self.specs)

    def concat_bucket(self, flat_grads: Dict[str, jax.Array], spec: BucketSpec):
        """Flatten + concatenate the bucket's member leaves (in spec.names
        order) into its contiguous f32 super-tensor."""
        parts = [
            flat_grads[n].reshape(-1).astype(jnp.float32) for n in spec.names
        ]
        return parts[0] if len(parts) == 1 else jnp.concatenate(parts)

    def split_bucket(self, spec: BucketSpec, dense: jax.Array):
        """Static-offset slices of the bucket's dense f32 aggregate back to
        member leaf shapes (the inverse of `concat_bucket`)."""
        return {
            n: jax.lax.slice_in_dim(dense, off, off + size).reshape(
                self.leaf_shapes[n]
            )
            for n, size, off in zip(spec.names, spec.sizes, spec.offsets)
        }

    def _decode_bucket(
        self, spec, gathered, num_workers, step, *, need_own, row_weights=None
    ):
        """Returns (total, own, fails): the over-workers decode sum, this
        worker's own decode (None unless need_own), and the bucket's
        checksum-failure count over gathered rows (None unless checksums
        are on). Failed-checksum rows decode to an exact zero vector."""
        codec = self.codecs[spec.label]
        layout = self.layouts[spec.label]

        if self._checksum:

            def decode_row(row):
                ok = layout.verify(row)
                dec = codec.decode(layout.unpack(row), step=step).astype(jnp.float32)
                # where-select, not `dec * ok`: corrupt bytes can decode to
                # Inf/NaN and Inf * 0 is NaN — the select stays exact zero
                return (jnp.where(ok > 0.5, dec, jnp.zeros_like(dec)), 1.0 - ok)

            out_shapes = ((spec.total,), ())
        else:

            def decode_row(row):
                return (
                    codec.decode(layout.unpack(row), step=step).astype(jnp.float32),
                )

            out_shapes = ((spec.total,),)

        if self.cfg.decode_strategy == "vmap":
            total, own = decode_gathered_vmap(
                gathered,
                num_workers,
                decode_row,
                out_shapes,
                axis_name=self.axis_name,
                need_own=need_own,
                decode_batch=self.cfg.decode_batch,
                row_weights=row_weights,
            )
        else:
            total, own = decode_gathered_loop(
                gathered,
                num_workers,
                decode_row,
                out_shapes,
                axis_name=self.axis_name,
                need_own=need_own,
                row_weights=row_weights,
            )
        fails = total[1] if self._checksum else None
        return total[0], (own[0] if need_own else None), fails

    def run(
        self,
        flat_grads,
        num_workers,
        step,
        worker_key,
        *,
        need_own: bool,
        row_weights=None,
        denom=None,
        collect=None,
    ):
        """Full bucketed exchange over the compensated flat-gradient dict.

        Returns ``(agg_leaves, own_leaves, stats_per, payloads)`` where the
        leaf dicts are keyed like ``flat_grads`` (f32, mean over workers /
        this worker's decode) and stats/payloads are keyed by bucket label.
        ``row_weights``/``denom`` carry the participation mask (see
        GradientExchanger.exchange); checksum failures summed over buckets
        land in ``collect["checksum_failures"]``.
        """
        payloads: Dict[str, object] = {}
        stats_per: Dict[str, WireStats] = {}
        with spans.span("exchange/encode", route="bucketed"):
            for spec in self.specs:
                codec = self.codecs[spec.label]
                key = per_tensor_key(worker_key, spec.label, step)
                payload = codec.encode(
                    self.concat_bucket(flat_grads, spec), step=step, key=key
                )
                payloads[spec.label] = payload
                stats_per[spec.label] = codec.wire_stats(payload)
        with spans.span("exchange/pack", route="bucketed"):
            bufs = [self.layouts[s.label].pack(payloads[s.label]) for s in self.specs]

        if self._chaos is not None:
            # per-bucket salt: each bucket draws its own fault events, so a
            # chaotic step doesn't corrupt every bucket in lockstep
            widx = jax.lax.axis_index(self.axis_name)
            with spans.span("resilience/chaos"):
                bufs = [
                    self._chaos.perturb(buf, step=step, worker=widx, salt=b)
                    for b, buf in enumerate(bufs)
                ]

        C = len(self.specs)
        totals: List = [None] * C
        owns: List = [None] * C
        fails_per: List = [None] * C

        def decode_into(b, gathered):
            with spans.span(f"exchange/bucket/{self.specs[b].label}"):
                with spans.span("exchange/decode", route="bucketed"):
                    totals[b], owns[b], fails_per[b] = self._decode_bucket(
                        self.specs[b],
                        gathered,
                        num_workers,
                        step,
                        need_own=need_own,
                        row_weights=row_weights,
                    )

        if self.cfg.bucket_pipeline and C > 0:
            # Software pipeline in trace order (the comm_ring idiom): the
            # all_gather for bucket b+1 is dispatched BEFORE bucket b's
            # decode, so the next transfer overlaps the current decode.
            with spans.span("exchange/allgather", route="bucketed"):
                nxt = jax.lax.all_gather(bufs[0], self.axis_name)
            for b in range(C):
                cur = nxt
                if b + 1 < C:
                    with spans.span("exchange/allgather", route="bucketed"):
                        nxt = jax.lax.all_gather(bufs[b + 1], self.axis_name)
                decode_into(b, cur)
        else:
            with spans.span("exchange/allgather", route="bucketed"):
                gathered = [jax.lax.all_gather(buf, self.axis_name) for buf in bufs]
            for b in range(C):
                decode_into(b, gathered[b])

        if self._checksum and collect is not None:
            fails = jnp.zeros((), jnp.float32)
            for f in fails_per:
                fails = fails + f
            collect["checksum_failures"] = fails

        den = denom if denom is not None else num_workers
        agg_leaves: Dict[str, jax.Array] = {}
        own_leaves: Dict[str, jax.Array] = {}
        for b, spec in enumerate(self.specs):
            agg_leaves.update(self.split_bucket(spec, totals[b] / den))
            if need_own:
                own_leaves.update(self.split_bucket(spec, owns[b]))
        return agg_leaves, own_leaves, stats_per, payloads

    def run_streaming_bucket(
        self,
        b: int,
        flat_grads,
        num_workers,
        step,
        worker_key,
        *,
        need_own: bool,
        token,
        pre_encode=None,
    ):
        """One bucket of the STREAMING schedule (comm_stream.py): the same
        encode → pack → all_gather → decode a barrier/pipeline bucket runs,
        but dispatched from inside a custom_vjp backward rule the moment the
        bucket's last member gradient exists. `token` is the f32 scalar
        dispatch token threaded bucket-to-bucket: the incoming token pins
        this bucket's encode AFTER the previous bucket's gather dispatch,
        and the returned token is pinned to this bucket's gathered buffer —
        `lax.optimization_barrier` is value-identity, so the pinning moves
        only the schedule, never the numbers.

        ``pre_encode`` is the composable upstream leg slot (comm_stream's
        hierarchical composition): a callable applied to the concatenated
        bucket AFTER the entry barrier and BEFORE encode, so a leg it
        dispatches (the ICI slice-mean psum) is ordered on the same token
        chain as this bucket's gather — per-axis collective order stays
        pinned, still exactly two barriers per bucket. When set, the
        bucket's remaining DCN half is wrapped in the ``exchange/dcn``
        span (the composed runs' overlap-attribution hook); the flat
        schedule's span structure is untouched.

        Returns ``(total, own, stats, payload, token, dense)`` — the
        pre-division decode sum over workers, this worker's own decode
        (None unless ``need_own``), the bucket's WireStats, its payload
        (for fp_stats), the chained token, and the encoded (post-
        ``pre_encode``) dense bucket the residual update needs.
        """
        if self._chaos is not None or self._checksum:
            raise ValueError(
                "streaming schedule does not thread chaos/checksum state "
                "(config validation rejects stream_exchange with resilience)"
            )
        spec = self.specs[b]
        codec = self.codecs[spec.label]
        with spans.span(f"exchange/bucket/{spec.label}"):
            dense = self.concat_bucket(flat_grads, spec)
            dense, token = jax.lax.optimization_barrier((dense, token))
            if pre_encode is not None:
                dense = pre_encode(dense)
            dcn_span = (
                spans.span("exchange/dcn")
                if pre_encode is not None
                else contextlib.nullcontext()
            )
            with dcn_span:
                with spans.span("exchange/encode", route="bucketed"):
                    key = per_tensor_key(worker_key, spec.label, step)
                    payload = codec.encode(dense, step=step, key=key)
                    stats = codec.wire_stats(payload)
                with spans.span("exchange/pack", route="bucketed"):
                    buf = self.layouts[spec.label].pack(payload)
                with spans.span("exchange/allgather", route="bucketed"):
                    gathered = jax.lax.all_gather(buf, self.axis_name)
                gathered, token = jax.lax.optimization_barrier((gathered, token))
                with spans.span("exchange/decode", route="bucketed"):
                    total, own, _fails = self._decode_bucket(
                        spec, gathered, num_workers, step, need_own=need_own
                    )
        return total, own, stats, payload, token, dense

    def saturation_vector(self, stats_per: Dict[str, WireStats]) -> jax.Array:
        """f32[C] per-bucket saturation flags in spec order — the telemetry
        counter that keeps one overfull bucket visible next to the summed
        WireStats total."""
        if not self.specs:
            return jnp.zeros((0,), jnp.float32)
        return jnp.stack(
            [
                jnp.asarray(stats_per[s.label].saturated, jnp.float32).reshape(())
                for s in self.specs
            ]
        )


def _numel(shape: Tuple[int, ...]) -> int:
    n = 1
    for x in shape:
        n *= int(x)
    return n
