"""Deterministic population sampling: everything derives from the spec.

Three PRNG domains, all rooted at ``PRNGKey(spec.seed)`` (a literal trace
constant — never the round key), with fold_in tags for domain separation
exactly like round.py's ``_LATENCY_TAG`` discipline:

- ``_POP_ASSIGN_TAG``: the class-assignment permutation. Class COUNTS are
  exact largest-remainder quotas of the normalized weights (no sampling
  noise in the population composition); the permutation only shuffles
  which client id gets which class, so every worker's stratum holds a
  representative mix.
- ``_POP_MIX_TAG``: per-client persistent label mixtures
  ``pi_g ~ Dirichlet(c_class(g))`` via ``fold_in(fold_in(root, tag), g)``
  — round-independent, so a client keeps its mixture for life.
- ``_POP_LABEL_TAG``: per-round per-sample labels, folded from the
  client's ROUND data key (the one the base generator already consumes),
  so label draws advance with the round schedule without touching the
  base generator's stream.

The skew transform is a per-sample mean shift ``mu[label]`` (centered
over the label universe, scaled by ``label_shift``) applied by an exact
``jnp.where`` SELECT per class gate — an alpha=0 class's batch is the
base generator's output BITWISE, and a spec with no skewed class at all
returns the base ``data_fn`` untouched (zero staged ops).
"""

from __future__ import annotations

import math
from typing import Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepreduce_tpu.population.spec import PopulationSpec

# fold_in domain-separation tags (see module docstring)
_POP_ASSIGN_TAG = 0xA551
_POP_MIX_TAG = 0x314D
_POP_LABEL_TAG = 0x1ABE1


def class_counts(spec: PopulationSpec, num_clients: int) -> Tuple[int, ...]:
    """Exact largest-remainder quotas of the normalized class weights —
    deterministic, sums to num_clients, ties broken by class order."""
    if num_clients < 1:
        raise ValueError(f"num_clients must be >= 1, got {num_clients}")
    raw = [w * num_clients for w in spec.weights]
    counts = [int(math.floor(r)) for r in raw]
    rem = num_clients - sum(counts)
    order = sorted(
        range(spec.num_classes),
        key=lambda k: (-(raw[k] - counts[k]), k),
    )
    for k in order[:rem]:
        counts[k] += 1
    return tuple(counts)


def class_assignments(spec: PopulationSpec, num_clients: int) -> jax.Array:
    """The i32[num_clients] class-id vector: quota-exact composition,
    spec-seeded permutation. Bitwise reproducible from (spec, N) alone."""
    counts = class_counts(spec, num_clients)
    base = np.repeat(
        np.arange(spec.num_classes, dtype=np.int32), np.asarray(counts)
    )
    key = jax.random.fold_in(
        jax.random.PRNGKey(spec.seed), _POP_ASSIGN_TAG
    )
    perm = jax.random.permutation(key, num_clients)
    return jnp.asarray(base)[perm].astype(jnp.int32)


def concentration_table(spec: PopulationSpec) -> np.ndarray:
    """f32[K, L] Dirichlet concentration rows: ``c[k, l] = data_alpha_k +
    data_bias_k·[l == k % L]``. An alpha=0 (IID-sentinel) class's row is
    all zeros — callers must gate it out, or use the mixture helpers
    below which substitute the uniform mixture for those rows."""
    K, L = spec.num_classes, spec.num_labels
    c = np.zeros((K, L), dtype=np.float32)
    for k, cls in enumerate(spec.classes):
        c[k, :] = cls.data_alpha
        if cls.data_bias > 0.0:
            c[k, k % L] += cls.data_bias
    return c


def expected_marginals(spec: PopulationSpec) -> np.ndarray:
    """f32[K, L] analytic per-class label marginals ``E[pi | class k] =
    c_k / sum(c_k)`` (uniform for alpha=0 rows) — what the planted-skew
    test pins the empirical mixtures against."""
    c = concentration_table(spec)
    out = np.full_like(c, 1.0 / spec.num_labels)
    for k in range(spec.num_classes):
        s = c[k].sum()
        if s > 0:
            out[k] = c[k] / s
    return out


def label_means(spec: PopulationSpec) -> np.ndarray:
    """f32[L] centered per-label mean shifts spanning
    [-label_shift, +label_shift] with exact zero mean over the universe."""
    L = spec.num_labels
    levels = 2.0 * np.arange(L, dtype=np.float32) - (L - 1)
    return (spec.label_shift * levels / (L - 1)).astype(np.float32)


def label_mixtures(
    spec: PopulationSpec,
    client_ids: Sequence[int],
    classes: Sequence[int],
) -> jax.Array:
    """f32[n, L] persistent per-client label mixtures for the given
    (global client id, class id) pairs — the same fold_in chain the
    in-trace generator uses, so host-side inspection matches the traced
    draws bitwise. Alpha=0 classes get the uniform mixture."""
    conc = jnp.asarray(concentration_table(spec))
    safe = jnp.where(conc > 0, conc, 1.0)
    on = jnp.asarray(
        [1.0 if c.data_alpha > 0.0 else 0.0 for c in spec.classes],
        jnp.float32,
    )
    L = spec.num_labels
    mix_base = jax.random.fold_in(
        jax.random.PRNGKey(spec.seed), _POP_MIX_TAG
    )

    def one(g, k):
        pi = jax.random.dirichlet(jax.random.fold_in(mix_base, g), safe[k])
        return jnp.where(on[k] > 0, pi, jnp.full((L,), 1.0 / L))

    return jax.vmap(one)(
        jnp.asarray(client_ids, jnp.int32), jnp.asarray(classes, jnp.int32)
    )


def make_population_data_fn(
    spec: PopulationSpec, data_fn: Callable
) -> Callable:
    """Wrap a ``data_fn(client_id, rnd, key) -> batch`` into
    ``pop_data_fn(client_id, class_id, rnd, key) -> batch`` applying the
    class-conditioned non-IID transform. With no skewed class the base
    generator is returned untouched (modulo the extra ignored class
    argument) — zero staged ops, the bitwise-degeneracy anchor.

    The transform assumes the batch's leaves lead with the
    ``[local_steps, batch]`` sample dims (the `synthetic_linear_problem`
    shape); leaves with other leading dims pass through unshifted."""
    if not spec.skew_on:
        def iid_data_fn(client_id, class_id, rnd, key):
            return data_fn(client_id, rnd, key)

        return iid_data_fn

    conc = jnp.asarray(concentration_table(spec))
    safe = jnp.where(conc > 0, conc, 1.0)
    gates = jnp.asarray(
        [1.0 if c.data_alpha > 0.0 else 0.0 for c in spec.classes],
        jnp.float32,
    )
    mu = jnp.asarray(label_means(spec))
    mix_base = jax.random.fold_in(
        jax.random.PRNGKey(spec.seed), _POP_MIX_TAG
    )

    def pop_data_fn(client_id, class_id, rnd, key):
        batch = data_fn(client_id, rnd, key)
        leaves = jax.tree_util.tree_leaves(batch)
        lead = leaves[0].shape[: min(2, leaves[0].ndim)]
        # persistent mixture (client-for-life), per-round labels (from
        # the round data key's domain-separated sibling)
        pi = jax.random.dirichlet(
            jax.random.fold_in(mix_base, client_id), safe[class_id]
        )
        labels = jax.random.categorical(
            jax.random.fold_in(key, _POP_LABEL_TAG), jnp.log(pi),
            shape=lead,
        )
        shift = mu[labels]
        on = gates[class_id] > 0

        def shifted(v):
            if v.ndim >= len(lead) and v.shape[: len(lead)] == lead:
                s = shift.reshape(lead + (1,) * (v.ndim - len(lead)))
                return jnp.where(on, v + s, v)
            return v

        return jax.tree_util.tree_map(shifted, batch)

    return pop_data_fn
