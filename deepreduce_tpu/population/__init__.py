"""Heterogeneous non-IID client populations for the federated serving path.

`PopulationSpec` (spec.py) is the schema-validated, version-tagged class
table — every client in the `[num_clients, ...]` residual bank belongs to
one class carrying three heterogeneity axes: data skew (a Dirichlet
label-concentration per class driving the in-trace non-IID synthetic-data
generator), a latency class (per-class staleness distribution for the
async tick), and a compute class (a local-step multiplier priced by
`costmodel`). sampler.py derives everything at trace/device level from
the spec's seed alone — class assignments, per-client label mixtures,
and the batch transform — so no host data ever materializes and the same
(spec, key) reproduces bitwise anywhere.
"""

from deepreduce_tpu.population.spec import ClassSpec, PopulationSpec
from deepreduce_tpu.population.sampler import (
    class_assignments,
    concentration_table,
    label_means,
    label_mixtures,
    make_population_data_fn,
)

__all__ = [
    "ClassSpec",
    "PopulationSpec",
    "class_assignments",
    "concentration_table",
    "label_means",
    "label_mixtures",
    "make_population_data_fn",
]
