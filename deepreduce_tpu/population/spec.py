"""Schema-validated heterogeneous-population specification.

A `PopulationSpec` is parsed from a JSON file or an inline JSON object
(the `pop_spec` config knob accepts either) and rejected loudly — unknown
keys, out-of-range values, and malformed per-class latency rows all raise
`ConfigError` with a registered reason code, mirroring `SLOSpec` /
`MachineProfile`: a typo'd spec must never silently serve an IID
population.

Each `ClassSpec` carries the three heterogeneity axes:

- **data skew**: `data_alpha` is the symmetric Dirichlet label
  concentration (0.0 is the IID sentinel — the class stages NO skew ops
  and its clients see the base generator bitwise); `data_bias` adds
  extra concentration on the class's home label ``class_index %
  num_labels``, so the expected per-class label marginal is analytically
  ``c / sum(c)`` with ``c[l] = data_alpha + data_bias·[l == home]`` —
  the planted-skew contract the sampler tests pin.
- **latency class**: a `parse_latency` comma list replacing the single
  global `fed_async_latency` for this class's clients ("" inherits the
  global row). Rows are zero-padded to the population's common overlap
  depth D exactly like r21's per-tenant rows (padding is
  draw-preserving).
- **compute class**: `local_steps_mult` >= 1, a relative compute cost
  priced by `costmodel.pop_compute_factor` (the trace itself runs the
  shared `fed_local_steps` program — pricing, not per-class retracing).

The degenerate spec — one class, alpha 0, no latency row, mult 1 —
is `is_uniform`, and the driver proves it bitwise identical to the
population-free program (params AND residual bank, sync and async).
"""

from __future__ import annotations

import dataclasses
import json
import math
import pathlib
from typing import Any, Dict, Tuple

from deepreduce_tpu.config import ConfigError

# hard cap on the class count: the per-class participation histogram
# rides the one fused psum (f32[K] operand), and the reason-coded cap
# keeps a typo'd spec from silently inflating every round's wire term
MAX_CLASSES = 64

_CLASS_KEYS = frozenset({
    "name", "weight", "data_alpha", "data_bias", "latency",
    "local_steps_mult",
})
_SPEC_KEYS = frozenset({
    "version", "classes", "num_labels", "label_shift", "seed",
})


def _num(where: str, key: str, raw: Any) -> float:
    if isinstance(raw, bool) or not isinstance(raw, (int, float)):
        raise ConfigError(
            "pop-spec-syntax",
            f"{where}[{key!r}] must be a number, got {raw!r}"
        )
    return float(raw)


@dataclasses.dataclass(frozen=True)
class ClassSpec:
    """One client class: a population share plus the three axes."""

    name: str
    weight: float = 1.0
    data_alpha: float = 0.0
    data_bias: float = 0.0
    latency: str = ""
    local_steps_mult: float = 1.0

    def __post_init__(self):
        if not isinstance(self.name, str) or not self.name:
            raise ConfigError(
                "pop-spec-syntax",
                f"class name must be a non-empty string, got {self.name!r}"
            )
        for field in ("weight", "data_alpha", "data_bias",
                      "local_steps_mult"):
            v = getattr(self, field)
            if isinstance(v, bool) or not isinstance(v, (int, float)) \
                    or not math.isfinite(v):
                raise ConfigError(
                    "pop-spec-range",
                    f"class {self.name!r}: {field} must be a finite "
                    f"number, got {v!r}"
                )
        if self.weight <= 0.0:
            raise ConfigError(
                "pop-spec-range",
                f"class {self.name!r}: weight is a population share and "
                f"must be > 0, got {self.weight}"
            )
        if self.data_alpha < 0.0:
            raise ConfigError(
                "pop-spec-range",
                f"class {self.name!r}: data_alpha is a Dirichlet "
                "concentration and must be >= 0 (0 = IID sentinel), got "
                f"{self.data_alpha}"
            )
        if self.data_bias < 0.0:
            raise ConfigError(
                "pop-spec-range",
                f"class {self.name!r}: data_bias must be >= 0, got "
                f"{self.data_bias}"
            )
        if self.data_bias > 0.0 and self.data_alpha == 0.0:
            raise ConfigError(
                "pop-spec-range",
                f"class {self.name!r}: data_bias={self.data_bias} with "
                "data_alpha=0 — the IID sentinel has no Dirichlet to "
                "bias; set data_alpha > 0"
            )
        if self.local_steps_mult < 1.0:
            raise ConfigError(
                "pop-spec-range",
                f"class {self.name!r}: local_steps_mult is a relative "
                f"compute cost and must be >= 1, got {self.local_steps_mult}"
            )
        if not isinstance(self.latency, str):
            raise ConfigError(
                "pop-spec-syntax",
                f"class {self.name!r}: latency must be a parse_latency "
                f"string ('' inherits fed_async_latency), got "
                f"{self.latency!r}"
            )
        if self.latency:
            # syntax check at construction (deferred import: round.py's
            # parser is config-free at parse time — mirrors the
            # fed_async_latency check in config.__post_init__)
            from deepreduce_tpu.fedsim.round import parse_latency

            try:
                parse_latency(self.latency, name=f"class {self.name!r} latency")
            except ConfigError:
                raise
            except ValueError as e:
                raise ConfigError("pop-latency-syntax", str(e)) from e

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "weight": self.weight,
            "data_alpha": self.data_alpha,
            "data_bias": self.data_bias,
            "latency": self.latency,
            "local_steps_mult": self.local_steps_mult,
        }


@dataclasses.dataclass(frozen=True)
class PopulationSpec:
    """The version-tagged class table plus the skew-generator knobs."""

    classes: Tuple[ClassSpec, ...] = ()
    # label universe of the synthetic non-IID generator; pop_labels
    # config knob overrides (0 keeps the spec value)
    num_labels: int = 8
    # magnitude of the centered per-label mean shift the skew transform
    # applies; 0.0 makes the skew branch value-free even when staged
    label_shift: float = 1.0
    # the spec's own PRNG seed: class assignments and per-client label
    # mixtures derive from fold_in chains rooted at PRNGKey(seed), so the
    # same spec reproduces bitwise on any process
    seed: int = 0

    def __post_init__(self):
        if not isinstance(self.classes, tuple) or not all(
            isinstance(c, ClassSpec) for c in self.classes
        ):
            raise ConfigError(
                "pop-spec-syntax",
                "classes must be a tuple of ClassSpec"
            )
        if not self.classes:
            raise ConfigError(
                "pop-spec-range",
                "a population needs at least one class"
            )
        if len(self.classes) > MAX_CLASSES:
            raise ConfigError(
                "pop-spec-range",
                f"{len(self.classes)} classes exceeds the cap of "
                f"{MAX_CLASSES} — the per-class histogram rides the one "
                "fused psum"
            )
        names = [c.name for c in self.classes]
        if len(set(names)) != len(names):
            raise ConfigError(
                "pop-spec-syntax",
                f"duplicate class name(s) in {names}"
            )
        if not isinstance(self.num_labels, int) \
                or isinstance(self.num_labels, bool) or self.num_labels < 2:
            raise ConfigError(
                "pop-labels-range",
                f"num_labels must be an int >= 2, got {self.num_labels!r}"
            )
        if isinstance(self.label_shift, bool) \
                or not isinstance(self.label_shift, (int, float)) \
                or not math.isfinite(self.label_shift) \
                or self.label_shift < 0.0:
            raise ConfigError(
                "pop-spec-range",
                f"label_shift must be a finite number >= 0, got "
                f"{self.label_shift!r}"
            )
        if not isinstance(self.seed, int) or isinstance(self.seed, bool) \
                or self.seed < 0:
            raise ConfigError(
                "pop-spec-range",
                f"seed must be an int >= 0, got {self.seed!r}"
            )

    # -- construction --------------------------------------------------

    @classmethod
    def from_dict(cls, d: Any) -> "PopulationSpec":
        if not isinstance(d, dict):
            raise ConfigError(
                "pop-spec-syntax",
                f"population spec must be a JSON object, got "
                f"{type(d).__name__}"
            )
        unknown = sorted(set(d) - _SPEC_KEYS)
        if unknown:
            raise ConfigError(
                "pop-spec-syntax",
                f"population spec has unknown key(s) {unknown}; valid "
                f"keys: {sorted(_SPEC_KEYS)}"
            )
        version = d.get("version", 1)
        if version != 1:
            raise ConfigError(
                "pop-spec-syntax",
                f"population spec version must be 1, got {version!r}"
            )
        raw_classes = d.get("classes", [])
        if not isinstance(raw_classes, list):
            raise ConfigError(
                "pop-spec-syntax",
                f"classes must be an array of class objects, got "
                f"{type(raw_classes).__name__}"
            )
        classes = []
        for i, raw in enumerate(raw_classes):
            if not isinstance(raw, dict):
                raise ConfigError(
                    "pop-spec-syntax",
                    f"classes[{i}] must be an object, got "
                    f"{type(raw).__name__}"
                )
            unknown = sorted(set(raw) - _CLASS_KEYS)
            if unknown:
                raise ConfigError(
                    "pop-spec-syntax",
                    f"classes[{i}] has unknown key(s) {unknown}; valid "
                    f"keys: {sorted(_CLASS_KEYS)}"
                )
            if "name" not in raw:
                raise ConfigError(
                    "pop-spec-syntax", f"classes[{i}] is missing 'name'"
                )
            kwargs: Dict[str, Any] = {"name": raw["name"]}
            for key in ("weight", "data_alpha", "data_bias",
                        "local_steps_mult"):
                if key in raw:
                    kwargs[key] = _num(f"classes[{i}]", key, raw[key])
            if "latency" in raw:
                kwargs["latency"] = raw["latency"]
            classes.append(ClassSpec(**kwargs))
        kwargs = {"classes": tuple(classes)}
        if "num_labels" in d:
            v = d["num_labels"]
            if not isinstance(v, int) or isinstance(v, bool):
                raise ConfigError(
                    "pop-labels-range",
                    f"num_labels must be an int, got {v!r}"
                )
            kwargs["num_labels"] = v
        if "label_shift" in d:
            kwargs["label_shift"] = _num("spec", "label_shift",
                                         d["label_shift"])
        if "seed" in d:
            v = d["seed"]
            if not isinstance(v, int) or isinstance(v, bool):
                raise ConfigError(
                    "pop-spec-range", f"seed must be an int, got {v!r}"
                )
            kwargs["seed"] = v
        return cls(**kwargs)

    @classmethod
    def load(cls, path) -> "PopulationSpec":
        path = pathlib.Path(path)
        try:
            raw = json.loads(path.read_text())
        except FileNotFoundError:
            raise ConfigError(
                "pop-spec-syntax",
                f"population spec file not found: {path}"
            ) from None
        except json.JSONDecodeError as e:
            raise ConfigError(
                "pop-spec-syntax",
                f"population spec {path} is not valid JSON: {e}"
            ) from e
        return cls.from_dict(raw)

    @classmethod
    def load_any(cls, spec: str) -> "PopulationSpec":
        """A path OR an inline JSON object (leading '{') — the `pop_spec`
        config knob accepts both, so the lattice and bench drivers never
        need a spec file on disk."""
        if not isinstance(spec, str) or not spec.strip():
            raise ConfigError(
                "pop-spec-syntax",
                f"pop_spec must be a file path or an inline JSON object, "
                f"got {spec!r}"
            )
        if spec.lstrip().startswith("{"):
            try:
                raw = json.loads(spec)
            except json.JSONDecodeError as e:
                raise ConfigError(
                    "pop-spec-syntax",
                    f"inline population spec is not valid JSON: {e}"
                ) from e
            return cls.from_dict(raw)
        return cls.load(spec)

    @classmethod
    def uniform(cls, **overrides) -> "PopulationSpec":
        """The degenerate single-class IID spec — the bitwise-degeneracy
        anchor the driver tests pin against the population-free program."""
        return cls(classes=(ClassSpec(name="uniform"),), **overrides)

    # -- views ----------------------------------------------------------

    @property
    def num_classes(self) -> int:
        return len(self.classes)

    @property
    def weights(self) -> Tuple[float, ...]:
        """Normalized population shares, in class order."""
        total = sum(c.weight for c in self.classes)
        return tuple(c.weight / total for c in self.classes)

    @property
    def local_steps_mults(self) -> Tuple[float, ...]:
        return tuple(c.local_steps_mult for c in self.classes)

    @property
    def skew_on(self) -> bool:
        """True when any class stages the non-IID data transform."""
        return any(c.data_alpha > 0.0 for c in self.classes)

    @property
    def latency_on(self) -> bool:
        """True when any class overrides the global latency row."""
        return any(c.latency for c in self.classes)

    @property
    def is_uniform(self) -> bool:
        """True for the degenerate spec the bitwise contract covers: one
        class, no skew, no latency override, unit compute."""
        return (
            len(self.classes) == 1
            and not self.skew_on
            and not self.latency_on
            and self.classes[0].local_steps_mult == 1.0
        )

    def with_overrides(self, num_labels: int = 0) -> "PopulationSpec":
        """Apply the config-knob overrides (0 keeps the spec value)."""
        if num_labels:
            return dataclasses.replace(self, num_labels=num_labels)
        return self

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": 1,
            "classes": [c.to_dict() for c in self.classes],
            "num_labels": self.num_labels,
            "label_shift": self.label_shift,
            "seed": self.seed,
        }
