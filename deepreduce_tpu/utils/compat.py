"""JAX version compatibility shims.

The codebase targets the jax>=0.8 public surface (`jax.shard_map` with
`check_vma`, `jax.lax.pcast`), but must also run on the 0.4.x series where
`shard_map` still lives in `jax.experimental.shard_map` (with the older
`check_rep` keyword) and `pcast` does not exist. Every entry point and test
imports `shard_map` / `pcast` from here instead of from jax directly, so a
jax upgrade or downgrade breaks exactly one module.
"""

from __future__ import annotations

import inspect

import jax

try:  # jax >= 0.8: top-level export, `check_vma` keyword
    from jax import shard_map as _shard_map_impl
except ImportError:  # jax 0.4.x: experimental module, `check_rep` keyword
    from jax.experimental.shard_map import shard_map as _shard_map_impl

_HAS_CHECK_VMA = "check_vma" in inspect.signature(_shard_map_impl).parameters


def shard_map(f, *args, **kwargs):
    """`jax.shard_map` with the replication-check keyword translated to
    whatever this jax version spells it (`check_vma` >= 0.8, `check_rep`
    before)."""
    if _HAS_CHECK_VMA and "check_rep" in kwargs:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    elif not _HAS_CHECK_VMA and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map_impl(f, *args, **kwargs)


if hasattr(jax.lax, "pcast"):
    pcast = jax.lax.pcast
else:

    def pcast(x, axis_names, *, to):  # noqa: ARG001 — signature parity
        """No-op fallback: pre-0.8 jax has no varying/manual type system, so
        there is nothing to cast (we run shard_map with the replication
        check disabled anyway)."""
        return x


__all__ = ["shard_map", "pcast"]
