"""Cross-cutting utilities: bit-packing, wire accounting, debug dumps.

Stable aliases for the subsystems that the reference keeps in
compression_utils.hpp / logger.cc / GRACE's `tensor_bits` (SURVEY.md §5):

- `packing`       — jit-compatible bit-packing (codecs/packing.py; the
                    reference's CuPy packbits + 3x21-bit int64 packers,
                    pytorch/deepreduce.py:165-248)
- `metrics`       — `WireStats` bits-on-wire accounting (`tensor_bits` role)
- `logging_utils` — fpr/policy-error/stats/values file dumps
                    (compression_utils.hpp:96-176 + Logger op roles)
"""

from deepreduce_tpu import logging_utils, metrics
from deepreduce_tpu.codecs import packing
from deepreduce_tpu.logging_utils import DumpLogger, policy_errors
from deepreduce_tpu.metrics import WireStats, combine, payload_device_bytes


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Persistent XLA compilation cache (default: <repo>/.jax_cache,
    gitignored). Repeat runs of the driver entry points and benchmarks skip
    the cold compile of the big spmd programs. Safe no-op on jax versions
    without the knobs."""
    import os

    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            ".jax_cache",
        )
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass


def host_device_count_flags(flags: str, device_count: int) -> str:
    """XLA_FLAGS string with --xla_force_host_platform_device_count set to
    `device_count`, replacing any existing setting (one shared helper — the
    flag is consulted once, at CPU-client init)."""
    kept = [
        f
        for f in flags.split()
        if "xla_force_host_platform_device_count" not in f
    ]
    kept.append(f"--xla_force_host_platform_device_count={device_count}")
    return " ".join(kept)


def backends_initialized() -> bool:
    """True once any JAX backend client exists (after which the platform can
    no longer be switched in-process). Wraps the private xla_bridge probe in
    one place so a jax upgrade breaks one helper, not every entry point."""
    from jax._src import xla_bridge

    try:
        return bool(xla_bridge.backends_are_initialized())
    except Exception:  # noqa: BLE001 — private API; fall back to the dict
        return bool(getattr(xla_bridge, "_backends", {}))


def device_responsive(
    timeout_s: float = 120.0, attempts: int = 1, sleep_s: float = 60.0
) -> bool:
    """True if a trivial device round-trip completes within `timeout_s`,
    probed in a SUBPROCESS: a wedged axon tunnel hangs inside the first
    device_put with no way to recover in-process, so the probe must be
    expendable. `attempts` > 1 retries with `sleep_s` pauses — the tunnel
    wedges transiently and often recovers within minutes."""
    import subprocess
    import sys
    import time

    code = (
        "import jax, jax.numpy as jnp, numpy as np;"
        "v = jax.jit(lambda t: t * 2.0)(jnp.zeros((8,), jnp.float32));"
        "np.asarray(v[:1])"
    )
    for attempt in range(max(1, attempts)):
        try:
            if (
                subprocess.run(
                    [sys.executable, "-c", code],
                    timeout=timeout_s,
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                ).returncode
                == 0
            ):
                return True
        except Exception:  # noqa: BLE001 — includes TimeoutExpired
            pass
        if attempt + 1 < max(1, attempts):
            time.sleep(sleep_s)
    return False


def force_platform(platform: str, device_count: int = 8) -> None:
    """Pin the JAX platform in-process. Env vars alone don't stick under the
    axon TPU tunnel, so anything that needs the virtual CPU mesh (tests,
    dry runs, CPU benchmarks) must set both the env *and* jax.config before
    a backend initializes. `device_count` only applies to 'cpu'."""
    import os

    os.environ["JAX_PLATFORMS"] = platform
    flags = os.environ.get("XLA_FLAGS", "")
    if platform == "cpu" and "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = host_device_count_flags(flags, device_count)
    import jax

    jax.config.update("jax_platforms", platform)


__all__ = [
    "packing",
    "metrics",
    "logging_utils",
    "DumpLogger",
    "policy_errors",
    "WireStats",
    "combine",
    "payload_device_bytes",
    "enable_compile_cache",
    "force_platform",
]
