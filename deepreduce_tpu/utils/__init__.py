"""Cross-cutting utilities: bit-packing, wire accounting, debug dumps.

Stable aliases for the subsystems that the reference keeps in
compression_utils.hpp / logger.cc / GRACE's `tensor_bits` (SURVEY.md §5):

- `packing`       — jit-compatible bit-packing (codecs/packing.py; the
                    reference's CuPy packbits + 3x21-bit int64 packers,
                    pytorch/deepreduce.py:165-248)
- `metrics`       — `WireStats` bits-on-wire accounting (`tensor_bits` role)
- `logging_utils` — fpr/policy-error/stats/values file dumps
                    (compression_utils.hpp:96-176 + Logger op roles)
"""

from deepreduce_tpu import logging_utils, metrics
from deepreduce_tpu.codecs import packing
from deepreduce_tpu.logging_utils import DumpLogger, policy_errors
from deepreduce_tpu.metrics import WireStats, combine, payload_device_bytes

__all__ = [
    "packing",
    "metrics",
    "logging_utils",
    "DumpLogger",
    "policy_errors",
    "WireStats",
    "combine",
    "payload_device_bytes",
]
