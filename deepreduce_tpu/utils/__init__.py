"""Cross-cutting utilities: bit-packing, wire accounting, debug dumps.

Stable aliases for the subsystems that the reference keeps in
compression_utils.hpp / logger.cc / GRACE's `tensor_bits` (SURVEY.md §5):

- `packing`       — jit-compatible bit-packing (codecs/packing.py; the
                    reference's CuPy packbits + 3x21-bit int64 packers,
                    pytorch/deepreduce.py:165-248)
- `metrics`       — `WireStats` bits-on-wire accounting (`tensor_bits` role)
- `logging_utils` — fpr/policy-error/stats/values file dumps
                    (compression_utils.hpp:96-176 + Logger op roles)
"""

from deepreduce_tpu import logging_utils, metrics
from deepreduce_tpu.codecs import packing
from deepreduce_tpu.logging_utils import DumpLogger, policy_errors
from deepreduce_tpu.metrics import WireStats, combine, payload_device_bytes


def enable_compile_cache(cache_dir: str | None = None) -> None:
    """Persistent XLA compilation cache (default: <repo>/.jax_cache,
    gitignored). Repeat runs of the driver entry points and benchmarks skip
    the cold compile of the big spmd programs. Safe no-op on jax versions
    without the knobs."""
    import os

    if cache_dir is None:
        cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            ".jax_cache",
        )
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass


def host_device_count_flags(flags: str, device_count: int) -> str:
    """XLA_FLAGS string with --xla_force_host_platform_device_count set to
    `device_count`, replacing any existing setting (one shared helper — the
    flag is consulted once, at CPU-client init)."""
    kept = [
        f
        for f in flags.split()
        if "xla_force_host_platform_device_count" not in f
    ]
    kept.append(f"--xla_force_host_platform_device_count={device_count}")
    return " ".join(kept)


def force_platform(platform: str, device_count: int = 8) -> None:
    """Pin the JAX platform in-process. Env vars alone don't stick under the
    axon TPU tunnel, so anything that needs the virtual CPU mesh (tests,
    dry runs, CPU benchmarks) must set both the env *and* jax.config before
    a backend initializes. `device_count` only applies to 'cpu'."""
    import os

    os.environ["JAX_PLATFORMS"] = platform
    flags = os.environ.get("XLA_FLAGS", "")
    if platform == "cpu" and "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = host_device_count_flags(flags, device_count)
    import jax

    jax.config.update("jax_platforms", platform)


__all__ = [
    "packing",
    "metrics",
    "logging_utils",
    "DumpLogger",
    "policy_errors",
    "WireStats",
    "combine",
    "payload_device_bytes",
    "enable_compile_cache",
    "force_platform",
]
