"""Jaxpr invariant auditor: trace every registered codec and communicator
config to a ClosedJaxpr on an abstract 8-way mesh and run the rule set.

No devices, no compiles: everything here is `jax.make_jaxpr` over
`ShapeDtypeStruct`s, with `shard_map` traced over an `AbstractMesh` (a real
8-CPU-device mesh is the fallback for jax builds without one). That makes
the audit runnable in CI on any host in seconds — the structural half of
the tier-1 contract, next to the numeric half the tests pin.

What gets audited per config:

- codec encode AND decode programs (`TensorCodec`), with the sorted-gather
  rule armed at the codec's budget on the mod-blocked hot-path configs;
- the mod-blocked bloom universe query in isolation (`query:bloom-mod`),
  contracted gather-free;
- the full `GradientExchanger.exchange` program inside shard_map for each
  communicator/decode-strategy, with the collective inventory pinned
  (fused = exactly one all_gather; ring = ppermute only; dense = one psum)
  and collective operand bytes cross-checked against `payload_bytes()`;
- a retrace guard: each program is traced twice and the jaxpr hashes must
  agree (nondeterministic tracing means silent per-step recompiles).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu import memory
from deepreduce_tpu.analysis import liveness
from deepreduce_tpu.analysis.rules import (
    AuditContext,
    R_CALIB_RESELECT,
    R_CTRL_LADDER,
    R_PEAK_BYTES,
    R_RESILIENCE_OFF,
    R_RETRACE,
    Violation,
    collective_counts,
    collective_counts_by_axis,
    jaxpr_hash,
    run_rules,
)
from deepreduce_tpu.codecs import bloom
from deepreduce_tpu.comm import GradientExchanger
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.wrappers import TensorCodec

AXIS = "data"
NUM_WORKERS = 8  # the audit mesh width (tests and CI both use 8)

# host codecs whose pure_callback is the design, not a leak
CALLBACK_WHITELIST = ("bloom_native", "integer_native", "polyfit_host", "huffman", "gzip")


@dataclasses.dataclass
class TraceRecord:
    """One audited program: its violations plus the reportable facts."""

    label: str
    violations: List[Violation]
    collectives: Dict[str, int]
    jaxpr_hash: str
    payload_bytes: Optional[int] = None
    skipped: Optional[str] = None
    # {mesh axis: {prim: count}} — the fabric-split view of `collectives`
    collectives_by_axis: Optional[Dict[str, Dict[str, int]]] = None
    # the liveness interpreter's priced memory envelope (analysis/liveness):
    # modeled peak live bytes under the trace's topological schedule, the
    # top contributing buffers at the peak, and the live-byte residency at
    # each collective. peak_bytes doubles as the committed per-trace byte
    # budget jx-peak-bytes gates against.
    peak_bytes: Optional[int] = None
    peak_top: Optional[List[Dict[str, Any]]] = None
    collective_residency: Optional[Dict[str, int]] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "label": self.label,
            "violations": [v.to_dict() for v in self.violations],
            "collectives": self.collectives,
            "jaxpr_hash": self.jaxpr_hash,
        }
        if self.payload_bytes is not None:
            out["payload_bytes"] = self.payload_bytes
        if self.skipped is not None:
            out["skipped"] = self.skipped
        if self.collectives_by_axis:
            out["collectives_by_axis"] = self.collectives_by_axis
        if self.peak_bytes is not None:
            out["peak_bytes"] = self.peak_bytes
        if self.peak_top is not None:
            out["peak_top"] = self.peak_top
        if self.collective_residency is not None:
            out["collective_residency"] = self.collective_residency
        return out


# ---------------------------------------------------------------------- #
# mesh + tracing plumbing
# ---------------------------------------------------------------------- #


def audit_mesh(num_workers: int = NUM_WORKERS):
    """An abstract mesh when this jax has one (trace-only, no devices);
    otherwise a real mesh over host devices (requires
    --xla_force_host_platform_device_count)."""
    try:
        from jax.sharding import AbstractMesh

        try:
            return AbstractMesh(((AXIS, num_workers),))
        except TypeError:  # newer signature: (axis_sizes, axis_names)
            return AbstractMesh((num_workers,), (AXIS,))
    except ImportError:
        import numpy as np
        from jax.sharding import Mesh

        devs = jax.devices()
        if len(devs) < num_workers:
            raise RuntimeError(
                f"audit needs {num_workers} devices (have {len(devs)}): set "
                "XLA_FLAGS=--xla_force_host_platform_device_count="
                f"{num_workers} before jax initializes"
            )
        return Mesh(np.array(devs[:num_workers]), (AXIS,))


def trace_and_check(
    label: str,
    fn: Callable,
    args: Tuple[Any, ...],
    ctx: AuditContext,
    *,
    payload_bytes: Optional[int] = None,
) -> TraceRecord:
    """make_jaxpr twice (retrace guard), run the rule set once, price the
    memory envelope once (the liveness interpreter)."""
    closed = jax.make_jaxpr(fn)(*args)
    h1 = jaxpr_hash(closed)
    h2 = jaxpr_hash(jax.make_jaxpr(fn)(*args))
    violations = run_rules(closed, ctx)
    if h1 != h2:
        violations.append(
            Violation(
                R_RETRACE,
                label,
                f"two traces of the same program hash differently "
                f"({h1} vs {h2}) — tracing is nondeterministic, every step "
                "would recompile",
            )
        )
    mem = liveness.analyze(closed)
    for fail in mem.residency_failures:
        violations.append(
            Violation(
                R_PEAK_BYTES,
                label,
                f"collective operand-residency failure: {fail}",
            )
        )
    return TraceRecord(
        label=label,
        violations=violations,
        collectives=collective_counts(closed),
        jaxpr_hash=h1,
        payload_bytes=payload_bytes,
        collectives_by_axis=collective_counts_by_axis(closed) or None,
        peak_bytes=mem.peak_bytes,
        peak_top=mem.peak_top,
        collective_residency=mem.collective_residency or None,
    )


def check_off_identical(
    label: str,
    make_fn: Callable[[], Callable],
    args: Tuple[Any, ...],
    patches: List[Tuple[Any, str, Any]],
) -> TraceRecord:
    """The zero-cost-off contract, checked on the trace: `make_fn` builds a
    step program whose config has resilience DISABLED. Trace it as shipped,
    then again with every resilience seam monkeypatched away entirely
    (chaos perturb -> identity, participation_mask -> None, checksum verify
    -> constant 1.0), and require byte-identical jaxpr hashes. If disabling
    the knobs left ANY residue in the traced program — an extra select, a
    checksum word, a mask broadcast — the two traces differ and this emits
    jx-resilience-off-identical.

    `patches` is a list of (object, attr, replacement) seams, setattr'd for
    the second trace and restored in a finally block.

    `make_fn` is a BUILDER, invoked once per trace: jax caches traces by
    function identity, so re-tracing one shared callable after patching
    would return the cached (unpatched) jaxpr and make the check vacuous —
    every trace must go through freshly-built function objects."""
    closed = jax.make_jaxpr(make_fn())(*args)
    h_off = jaxpr_hash(closed)
    saved = [(obj, attr, getattr(obj, attr)) for obj, attr, _ in patches]
    try:
        for obj, attr, repl in patches:
            setattr(obj, attr, repl)
        h_absent = jaxpr_hash(jax.make_jaxpr(make_fn())(*args))
    finally:
        for obj, attr, orig in saved:
            setattr(obj, attr, orig)
    violations: List[Violation] = []
    if h_off != h_absent:
        violations.append(
            Violation(
                R_RESILIENCE_OFF,
                label,
                f"resilience-off trace ({h_off}) differs from the "
                f"resilience-absent trace ({h_absent}) — disabling the "
                "knobs must leave a byte-identical program (zero-cost-off)",
            )
        )
    return TraceRecord(
        label=label,
        violations=violations,
        collectives=collective_counts(closed),
        jaxpr_hash=h_off,
    )


def _sds(shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


_STEP = _sds((), jnp.int32)


# ---------------------------------------------------------------------- #
# codec-level audits
# ---------------------------------------------------------------------- #


def audit_codec(
    label: str,
    cfg: DeepReduceConfig,
    *,
    d: int = 8192,
    name: str = "g",
    enforce_sorted: bool = False,
) -> List[TraceRecord]:
    """Trace one TensorCodec's encode and decode programs and audit both."""
    codec = TensorCodec((d,), cfg, name=name)
    key = jax.random.PRNGKey(cfg.seed)
    allow_cb = cfg.index in CALLBACK_WHITELIST and cfg.deepreduce in ("index", "both")
    allow_cb = allow_cb or (
        cfg.value in CALLBACK_WHITELIST and cfg.deepreduce in ("value", "both")
    )
    budget = None
    if enforce_sorted:
        meta = getattr(codec.idx_codec, "meta", None)
        budget = getattr(meta, "budget", codec.k)

    def enc(t, s):
        return codec.encode(t, step=s, key=key)

    def dec(p, s):
        return codec.decode(p, step=s)

    t_sds = _sds((d,))
    payload_sds = jax.eval_shape(enc, t_sds, _STEP)
    ctx_e = AuditContext(
        label=f"{label}/encode", allow_callbacks=allow_cb, budget_scale=budget
    )
    ctx_d = AuditContext(
        label=f"{label}/decode", allow_callbacks=allow_cb, budget_scale=budget
    )
    return [
        trace_and_check(ctx_e.label, enc, (t_sds, _STEP), ctx_e),
        trace_and_check(ctx_d.label, dec, (payload_sds, _STEP), ctx_d),
    ]


def audit_mod_query(*, d: int = 8192, k: int = 163) -> List[TraceRecord]:
    """The flagship claim, checked literally: the mod-blocked universe query
    contains ZERO gather eqns (it is a broadcast membership test)."""
    meta = bloom.BloomMeta.create(k, d, policy="leftmost", blocked="mod")
    words_sds = _sds((meta.m_bits // 32,), jnp.uint32)
    ctx = AuditContext(label="query:bloom-mod", forbid_gather=True)
    return [
        trace_and_check(
            ctx.label, lambda w: bloom.query_universe(w, meta), (words_sds,), ctx
        )
    ]


# ---------------------------------------------------------------------- #
# exchange-level audits
# ---------------------------------------------------------------------- #


def _shard_map(fn, mesh, in_specs, out_specs):
    from jax.sharding import PartitionSpec as P  # noqa: F401

    from deepreduce_tpu.utils.compat import shard_map

    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
    )


def audit_exchange(
    label: str,
    cfg: DeepReduceConfig,
    *,
    d: int = 4096,
    leaves: Optional[Dict[str, int]] = None,
    expect: Optional[Dict[str, int]] = None,
    wire_mode: Optional[str] = None,
    enforce_sorted: bool = False,
    expect_codec: Optional[int] = None,
    with_mask: bool = False,
    mesh=None,
    profile=None,
) -> List[TraceRecord]:
    """Trace one full `exchange` step inside shard_map on the 8-way mesh.

    `leaves` (name -> flat size) swaps the default single-(d,) gradient for
    a multi-leaf dict pytree — the shape the bucketed-exchange audits need.
    `expect_codec` arms jx-codec-count: the exact static count of
    sparsifier-selection eqns (O(leaves) per-tensor, O(buckets) bucketed).
    `with_mask` threads a replicated bool[W] participation mask into the
    exchange — the resilient-path audit shape (requires memory='residual').
    `profile` hands the exchanger a costmodel.MachineProfile for its
    construction-time 'auto' selection (the calib-reselect audit shape).
    """
    from jax.sharding import PartitionSpec as P

    tmap = jax.tree_util.tree_map
    mesh = audit_mesh() if mesh is None else mesh
    if leaves is None:
        grads_like: Any = _sds((d,))
    else:
        grads_like = {n: _sds((int(sz),)) for n, sz in leaves.items()}
    ex = GradientExchanger(
        grads_like, cfg, axis_name=AXIS, num_workers=NUM_WORKERS,
        profile=profile,
    )
    with_state = cfg.memory == "residual"
    pb = ex.payload_bytes(grads_like) if wire_mode is not None else None
    g_w = tmap(lambda s: _sds((NUM_WORKERS,) + s.shape), grads_like)

    if with_mask and with_state:

        def spmd(g, res, step, m):
            g0 = tmap(lambda x: x[0], g)
            res0 = tmap(lambda r: r[0], res)
            agg, new_res, _ = ex.exchange(g0, res0, step=step, mask=m)
            new_res = tmap(lambda r: r[None], new_res)
            return tmap(lambda x: x[None], agg), new_res

        fn = _shard_map(
            spmd, mesh, (P(AXIS), P(AXIS), P(), P()), (P(AXIS), P(AXIS))
        )
        args = (g_w, g_w, _STEP, _sds((NUM_WORKERS,), jnp.bool_))
    elif with_mask:
        # the stateless masked shape: the resilient sparse_rs routes run
        # memory='none' (their EF residual lives inside the route itself)
        # but still thread the replicated live mask

        def spmd(g, step, m):
            agg, _, _ = ex.exchange(
                tmap(lambda x: x[0], g), None, step=step, mask=m
            )
            return tmap(lambda x: x[None], agg)

        fn = _shard_map(spmd, mesh, (P(AXIS), P(), P()), P(AXIS))
        args = (g_w, _STEP, _sds((NUM_WORKERS,), jnp.bool_))
    elif with_state:

        def spmd(g, res, step):
            g0 = tmap(lambda x: x[0], g)
            res0 = tmap(lambda r: r[0], res)
            agg, new_res, _ = ex.exchange(g0, res0, step=step)
            new_res = tmap(lambda r: r[None], new_res)
            return tmap(lambda x: x[None], agg), new_res

        fn = _shard_map(
            spmd, mesh, (P(AXIS), P(AXIS), P()), (P(AXIS), P(AXIS))
        )
        args = (g_w, g_w, _STEP)
    else:

        def spmd(g, step):
            agg, _, _ = ex.exchange(tmap(lambda x: x[0], g), None, step=step)
            return tmap(lambda x: x[None], agg)

        fn = _shard_map(spmd, mesh, (P(AXIS), P()), P(AXIS))
        args = (g_w, _STEP)

    budget = None
    if enforce_sorted:
        codecs = ex.codecs or (
            ex._bucketed.codecs if ex._bucketed is not None else {}
        )
        codec = next(iter(codecs.values()))
        meta = getattr(codec.idx_codec, "meta", None)
        budget = getattr(meta, "budget", codec.k)
    ctx = AuditContext(
        label=label,
        allow_callbacks=False,
        budget_scale=budget,
        expect_collectives=expect,
        wire_mode=wire_mode,
        expected_wire_bytes=pb,
        num_workers=NUM_WORKERS,
        expect_codec_invocations=expect_codec,
        # exchange-level traces contract the per-worker/tensor/step fold
        # discipline (codec unit audits legitimately pass raw keys)
        require_key_lineage=True,
    )
    return [trace_and_check(label, fn, args, ctx, payload_bytes=pb)]


def audit_hier_mesh(n_slices: int = 2, per_slice: int = 4):
    """Two-axis (dcn, ici) abstract mesh for the hierarchical audits —
    same fallback ladder as `audit_mesh`."""
    try:
        from jax.sharding import AbstractMesh

        try:
            return AbstractMesh((("dcn", n_slices), ("ici", per_slice)))
        except TypeError:  # newer signature: (axis_sizes, axis_names)
            return AbstractMesh((n_slices, per_slice), ("dcn", "ici"))
    except ImportError:
        import numpy as np
        from jax.sharding import Mesh

        n = n_slices * per_slice
        devs = jax.devices()
        if len(devs) < n:
            raise RuntimeError(
                f"hier audit needs {n} devices (have {len(devs)}): set "
                f"XLA_FLAGS=--xla_force_host_platform_device_count={n}"
            )
        return Mesh(
            np.array(devs[:n]).reshape(n_slices, per_slice), ("dcn", "ici")
        )


def audit_hier_exchange(
    label: str,
    cfg: DeepReduceConfig,
    *,
    d: int = 4096,
    leaves: Optional[Dict[str, int]] = None,
    expect_by_axis: Optional[Dict[str, Dict[str, int]]] = None,
    wire_mode: Optional[str] = None,
    expect_codec: Optional[int] = None,
    with_key: bool = False,
    n_slices: int = 2,
    per_slice: int = 4,
) -> List[TraceRecord]:
    """Trace one `HierarchicalExchanger.exchange` step inside shard_map over
    the two-axis (dcn, ici) mesh and pin the PER-AXIS collective inventory:
    the slice-reduction leg (and the key-repair gather, when `with_key`)
    must ride ici only, the compressed leg dcn only, and nothing may touch
    an axis the contract does not name. Wire accounting runs with
    `wire_axis='dcn'` — `payload_bytes()` is DCN-only by contract, so only
    the dcn-leg collective operands may sum to it."""
    from jax.sharding import PartitionSpec as P

    from deepreduce_tpu.parallel.hierarchical import HierarchicalExchanger

    tmap = jax.tree_util.tree_map
    mesh = audit_hier_mesh(n_slices, per_slice)
    axes = ("dcn", "ici")
    w = n_slices * per_slice
    if leaves is None:
        grads_like: Any = _sds((d,))
    else:
        grads_like = {n: _sds((int(sz),)) for n, sz in leaves.items()}
    ex = HierarchicalExchanger(
        grads_like, cfg, num_slices=n_slices, per_slice=per_slice
    )
    pb = ex.payload_bytes(grads_like) if wire_mode is not None else None
    g_w = tmap(lambda s: _sds((w,) + s.shape), grads_like)
    with_state = cfg.memory == "residual"

    if with_state:

        def spmd(g, res, step, *key):
            g0 = tmap(lambda x: x[0], g)
            res0 = tmap(lambda r: r[0], res)
            agg, new_res, _ = ex.exchange(
                g0, res0, step=step, key=key[0] if key else None
            )
            new_res = tmap(lambda r: r[None], new_res)
            return tmap(lambda x: x[None], agg), new_res

        in_specs = (P(axes), P(axes), P()) + ((P(),) if with_key else ())
        fn = _shard_map(spmd, mesh, in_specs, (P(axes), P(axes)))
        args = (g_w, g_w, _STEP) + (
            (_sds((2,), jnp.uint32),) if with_key else ()
        )
    else:

        def spmd(g, step, *key):
            agg, _, _ = ex.exchange(
                tmap(lambda x: x[0], g), None, step=step,
                key=key[0] if key else None,
            )
            return tmap(lambda x: x[None], agg)

        in_specs = (P(axes), P()) + ((P(),) if with_key else ())
        fn = _shard_map(spmd, mesh, in_specs, P(axes))
        args = (g_w, _STEP) + ((_sds((2,), jnp.uint32),) if with_key else ())

    ctx = AuditContext(
        label=label,
        allow_callbacks=False,
        expect_collectives_by_axis=expect_by_axis,
        wire_mode=wire_mode,
        expected_wire_bytes=pb,
        wire_axis="dcn",
        num_workers=n_slices,
        expect_codec_invocations=expect_codec,
        require_key_lineage=True,
    )
    return [trace_and_check(label, fn, args, ctx, payload_bytes=pb)]


def audit_resilience_off(*, d: int = 4096) -> List[TraceRecord]:
    """Zero-cost-off audit: the flagship fused exchange with every
    resilience knob at its default must trace to a byte-identical jaxpr
    when the resilience seams are monkeypatched out of existence — any
    unconditional mask/chaos/checksum residue in the disabled program
    trips jx-resilience-off-identical."""
    from jax.sharding import PartitionSpec as P

    import deepreduce_tpu.comm as comm_mod
    from deepreduce_tpu.resilience import chaos as chaos_mod
    from deepreduce_tpu.resilience import faults as faults_mod

    cfg = DeepReduceConfig(memory="residual", decode_strategy="loop", **_FLAGSHIP)
    mesh = audit_mesh()
    g_w = _sds((NUM_WORKERS, d))

    def make_fn():
        # everything rebuilt per trace (exchanger included) so no stale
        # trace cache can mask residue — see check_off_identical
        ex = GradientExchanger(
            _sds((d,)), cfg, axis_name=AXIS, num_workers=NUM_WORKERS
        )

        def spmd(g, res, step):
            agg, new_res, _ = ex.exchange(g[0], res[0], step=step)
            return agg[None], new_res[None]

        return _shard_map(spmd, mesh, (P(AXIS), P(AXIS), P()), (P(AXIS), P(AXIS)))

    args = (g_w, g_w, _STEP)
    patches = [
        (chaos_mod.ChaosInjector, "perturb", lambda self, buf, **kw: buf),
        (faults_mod, "participation_mask", lambda *a, **kw: None),
        (
            comm_mod.PayloadLayout,
            "verify",
            lambda self, buf: jnp.ones((), jnp.float32),
        ),
    ]
    return [check_off_identical("resilience:off-identical", make_fn, args, patches)]


def audit_fedsim_round(
    *,
    d: int = 512,
    num_clients: int = 64,
    clients_per_round: int = 16,
    label: str = "fedsim:round",
) -> List[TraceRecord]:
    """The federated round's cross-worker traffic, pinned: the whole round
    (S2C broadcast compression, in-step stratified cohort sampling, vmapped
    client local-train + uplink compression, bank scatter, server update)
    contracts to exactly ONE psum — the tuple (update sums, wire bits, live
    count, checksum failures) — and the operand bytes of that psum are
    exactly 4*(param_elements + 6) B/worker. Codec count pins TWO top-k
    selections: one S2C delta encode + one vmapped C2S client encode (the
    cohort shares a single traced selection, however many clients run).

    num_clients/clients_per_round are parametrized so the liveness tests can
    show the residual-bank peak scales with the population N, not the cohort.
    """
    import optax

    from deepreduce_tpu.fedsim.sim import FedSim, synthetic_linear_problem

    tmap = jax.tree_util.tree_map
    cfg = DeepReduceConfig(
        memory="residual",
        fed=True,
        fed_num_clients=num_clients,
        fed_clients_per_round=clients_per_round,
        fed_local_steps=2,
        **_FLAGSHIP,
    )
    fed = cfg.fed_config()
    params0, data_fn, loss_fn = synthetic_linear_problem(d, 4, fed.local_steps)
    fs = FedSim(
        loss_fn, cfg, fed, optax.sgd(0.1), data_fn, mesh=audit_mesh(), axis=AXIS
    )
    fn = fs.sharded_round_fn()
    params_sds = tmap(lambda p: _sds(p.shape, p.dtype), params0)
    bank_sds = tmap(
        lambda p: _sds((fed.num_clients,) + p.shape, p.dtype), params_sds
    )
    n_elems = sum(
        int(jnp.prod(jnp.array(p.shape))) if p.shape else 1
        for p in jax.tree_util.tree_leaves(params_sds)
    )
    # psum tuple = param-leaf update sums + wire4 (4 scalars) + nlive + nfail
    pb = 4 * (n_elems + 6)
    args = (
        params_sds,  # params (replicated)
        params_sds,  # w_ref (replicated)
        bank_sds,  # residual bank, P(axis) on dim 0
        None,  # telemetry accumulators (off)
        _STEP,  # round counter
        _sds((2,), jnp.uint32),  # round key
    )
    ctx = AuditContext(
        label=label,
        allow_callbacks=False,
        expect_collectives={"psum": 1},
        wire_mode="collective",
        expected_wire_bytes=pb,
        num_workers=NUM_WORKERS,
        expect_codec_invocations=2,
        require_key_lineage=True,
    )
    return [trace_and_check(label, fn, args, ctx, payload_bytes=pb)]


def audit_fedsim_async_round(*, d: int = 512) -> List[TraceRecord]:
    """The asynchronous buffered tick keeps the round's collective contract:
    however deep the overlap ring, however the K-threshold buffered apply
    gates the server update, the whole ingest tick is still exactly ONE
    fused psum — the sync tuple plus the staleness-weight mass plus the
    D-level staleness histogram (r23 health plane: accepted contributions
    counted per staleness level, on device), so the operand bytes are
    exactly 4*(param_elements + 7 + D) B/worker. This is a DELIBERATE
    re-pin from the r20 law 4*(n+7): the histogram members ride the same
    fused psum — the collective count stays ONE — and only its operand
    bytes grow, by the 4*D B/worker the D counters cost. Codec count
    stays at TWO (pending-gated S2C delta encode is staged exactly once;
    the vmapped C2S client encode is shared by the cohort); the latency
    draw and buffered apply add no collectives because staleness is drawn
    replicated over global cohort positions from the shared tick key."""
    import optax

    from deepreduce_tpu.fedsim.sim import (
        AsyncBuffer,
        FedSim,
        synthetic_linear_problem,
    )

    tmap = jax.tree_util.tree_map
    cfg = DeepReduceConfig(
        memory="residual",
        fed=True,
        fed_num_clients=64,
        fed_clients_per_round=16,
        fed_local_steps=2,
        fed_async=True,
        fed_async_k=40,
        fed_async_alpha=0.5,
        fed_async_latency="0.5,0.3,0.2",
        **_FLAGSHIP,
    )
    fed = cfg.fed_config()
    params0, data_fn, loss_fn = synthetic_linear_problem(d, 4, fed.local_steps)
    fs = FedSim(
        loss_fn, cfg, fed, optax.sgd(0.1), data_fn, mesh=audit_mesh(), axis=AXIS
    )
    fn = fs.sharded_round_fn()
    params_sds = tmap(lambda p: _sds(p.shape, p.dtype), params0)
    bank_sds = tmap(
        lambda p: _sds((fed.num_clients,) + p.shape, p.dtype), params_sds
    )
    D = len(fs.latency_probs)
    buf_sds = AsyncBuffer(
        delta_sum=params_sds,
        weight=_sds((), jnp.float32),
        count=_sds((), jnp.float32),
        k=_sds((), jnp.float32),
        version=_sds((), jnp.int32),
        hist=tmap(lambda p: _sds((D,) + p.shape, p.dtype), params_sds),
        stale_sum=_sds((), jnp.float32),
        stale_max=_sds((), jnp.float32),
        pending=_sds((), jnp.float32),
    )
    n_elems = sum(
        int(jnp.prod(jnp.array(p.shape))) if p.shape else 1
        for p in jax.tree_util.tree_leaves(params_sds)
    )
    # psum tuple = param-leaf update sums + wire4 + nlive + nfail + wsum
    # + D staleness-histogram counters (r23 re-pin: +4*D B/worker)
    pb = 4 * (n_elems + 7 + D)
    args = (
        params_sds,  # params (replicated)
        params_sds,  # w_ref (replicated)
        bank_sds,  # residual bank, P(axis) on dim 0
        None,  # telemetry accumulators (off)
        _STEP,  # tick counter
        _sds((2,), jnp.uint32),  # tick key
        buf_sds,  # aggregation buffer + w_hist ring (replicated)
    )
    ctx = AuditContext(
        label="fedsim:async-round",
        allow_callbacks=False,
        expect_collectives={"psum": 1},
        wire_mode="collective",
        expected_wire_bytes=pb,
        num_workers=NUM_WORKERS,
        expect_codec_invocations=2,
        require_key_lineage=True,
    )
    return [trace_and_check("fedsim:async-round", fn, args, ctx, payload_bytes=pb)]


def audit_fedsim_population(*, d: int = 512) -> List[TraceRecord]:
    """The r25 heterogeneous-population plane keeps the one-psum contract
    and re-pins its operand bytes by exactly the members the plane adds:

    - sync round + population: the exact per-class participation
      histogram (f32[K]) rides the fused tuple, so the operand bytes are
      4*(n + 6 + K) B/worker — the r20 law 4*(n+6) plus 4*K. The class-id
      vector enters as one extra i32[num_clients] operand sharded with
      the residual bank; it adds NO collective (each worker reads only
      its own slice).
    - async tick + population: 4*(n + 7 + D + K) — the r23 staleness
      histogram law plus the same 4*K.
    - async tick + per-class latency rows: the transmit-level histogram
      (f32[D], the exact per-level transmission counts the staleness
      stats derive from once levels differ per class) also rides the
      tuple: 4*(n + 7 + 2*D + K).

    Codec count stays at TWO everywhere: the non-IID skew transform is a
    per-client mean shift staged inside the vmapped generator — no extra
    selection, no collective."""
    import json as _json

    import optax

    from deepreduce_tpu.fedsim.sim import (
        AsyncBuffer,
        FedSim,
        synthetic_linear_problem,
    )

    tmap = jax.tree_util.tree_map
    K = 2
    spec_of = lambda cls: _json.dumps(  # noqa: E731
        {"version": 1, "num_labels": 8, "classes": cls}
    )
    pop_plain = spec_of([
        {"name": "bulk", "weight": 3.0, "data_alpha": 0.5},
        {"name": "skewed", "weight": 1.0, "data_alpha": 0.1,
         "data_bias": 4.0, "local_steps_mult": 2.0},
    ])
    pop_latency = spec_of([
        {"name": "bulk", "weight": 3.0, "data_alpha": 0.5,
         "latency": "0.6,0.3,0.1"},
        {"name": "skewed", "weight": 1.0, "data_alpha": 0.1,
         "data_bias": 4.0, "latency": "0.2,0.5,0.3"},
    ])

    def build(pop_spec, fed_async):
        kw = dict(
            memory="residual",
            fed=True,
            fed_num_clients=64,
            fed_clients_per_round=16,
            fed_local_steps=2,
            pop_spec=pop_spec,
            **_FLAGSHIP,
        )
        if fed_async:
            kw.update(
                fed_async=True,
                fed_async_k=40,
                fed_async_alpha=0.5,
                fed_async_latency="0.5,0.3,0.2",
            )
        cfg = DeepReduceConfig(**kw)
        fed = cfg.fed_config()
        params0, data_fn, loss_fn = synthetic_linear_problem(
            d, 4, fed.local_steps
        )
        fs = FedSim(
            loss_fn, cfg, fed, optax.sgd(0.1), data_fn,
            mesh=audit_mesh(), axis=AXIS,
        )
        params_sds = tmap(lambda p: _sds(p.shape, p.dtype), params0)
        bank_sds = tmap(
            lambda p: _sds((fed.num_clients,) + p.shape, p.dtype),
            params_sds,
        )
        n_elems = sum(
            int(jnp.prod(jnp.array(p.shape))) if p.shape else 1
            for p in jax.tree_util.tree_leaves(params_sds)
        )
        classes_sds = _sds((fed.num_clients,), jnp.int32)
        return fs, params_sds, bank_sds, classes_sds, n_elems

    records: List[TraceRecord] = []

    def check(label, fs, args, pb):
        ctx = AuditContext(
            label=label,
            allow_callbacks=False,
            expect_collectives={"psum": 1},
            wire_mode="collective",
            expected_wire_bytes=pb,
            num_workers=NUM_WORKERS,
            expect_codec_invocations=2,
            require_key_lineage=True,
        )
        records.append(
            trace_and_check(label, fs.sharded_round_fn(), args, ctx,
                            payload_bytes=pb)
        )

    # sync round: 4*(n + 6 + K)
    fs, params_sds, bank_sds, classes_sds, n = build(pop_plain, False)
    check(
        "fedsim:population",
        fs,
        (params_sds, params_sds, bank_sds, None, _STEP,
         _sds((2,), jnp.uint32), classes_sds),
        4 * (n + 6 + K),
    )

    def buf_sds_of(fs, params_sds):
        D = len(fs.latency_probs)
        return AsyncBuffer(
            delta_sum=params_sds,
            weight=_sds((), jnp.float32),
            count=_sds((), jnp.float32),
            k=_sds((), jnp.float32),
            version=_sds((), jnp.int32),
            hist=tmap(lambda p: _sds((D,) + p.shape, p.dtype), params_sds),
            stale_sum=_sds((), jnp.float32),
            stale_max=_sds((), jnp.float32),
            pending=_sds((), jnp.float32),
        )

    # async tick, global latency row shared by both classes:
    # 4*(n + 7 + D + K)
    fs, params_sds, bank_sds, classes_sds, n = build(pop_plain, True)
    D = len(fs.latency_probs)
    check(
        "fedsim:population-async",
        fs,
        (params_sds, params_sds, bank_sds, None, _STEP,
         _sds((2,), jnp.uint32), buf_sds_of(fs, params_sds), classes_sds),
        4 * (n + 7 + D + K),
    )

    # async tick, per-class latency rows: the tx-level histogram rides
    # too — 4*(n + 7 + 2*D + K)
    fs, params_sds, bank_sds, classes_sds, n = build(pop_latency, True)
    D = len(fs.latency_probs)
    check(
        "fedsim:population-latency",
        fs,
        (params_sds, params_sds, bank_sds, None, _STEP,
         _sds((2,), jnp.uint32), buf_sds_of(fs, params_sds), classes_sds),
        4 * (n + 7 + 2 * D + K),
    )
    return records


def audit_fedsim_multitenant(
    *, d: int = 512, tenants: Tuple[int, ...] = (2, 4)
) -> List[TraceRecord]:
    """The multi-tenant tick's amortization contract, pinned at two fleet
    sizes: stacking T async populations through the one vmapped tick keeps
    EXACTLY ONE psum — the collective count is independent of T — while
    the psum tuple's operand bytes grow exactly linearly in T,
    4*(T*(n_elems+3+D) + 4) B/worker: the param-leaf update sums, the
    nlive/nfail/wsum scalars, AND the D-level staleness histogram (r23
    health plane — per-tenant tail percentiles, so its counters batch
    like the other data-dependent members) gain a leading tenant dim,
    while the four wire-accounting scalars are shape-static and
    tenant-invariant, so vmap leaves them unbatched. This is a DELIBERATE
    re-pin from the r21 law 4*(T*(n+3)+4): the histogram adds 4*T*D
    B/worker and nothing else moves. Codec count stays at TWO: the vmap
    over tenants batches the S2C delta encode and the shared C2S client
    encode instead of re-staging them per tenant — the whole point of
    serving T populations from one compiled program."""
    import optax

    from deepreduce_tpu.fedsim.sim import (
        AsyncBuffer,
        FedSim,
        synthetic_linear_problem,
    )

    tmap = jax.tree_util.tree_map
    records: List[TraceRecord] = []
    for T in tenants:
        cfg = DeepReduceConfig(
            memory="residual",
            fed=True,
            fed_num_clients=64,
            fed_clients_per_round=16,
            fed_local_steps=2,
            fed_async=True,
            fed_async_k=40,
            fed_async_alpha=0.5,
            fed_async_latency="0.5,0.3,0.2",
            fed_tenants=T,
            **_FLAGSHIP,
        )
        fed = cfg.fed_config()
        params0, data_fn, loss_fn = synthetic_linear_problem(
            d, 4, fed.local_steps
        )
        fs = FedSim(
            loss_fn, cfg, fed, optax.sgd(0.1), data_fn, mesh=audit_mesh(),
            axis=AXIS,
        )
        fn = fs.sharded_round_fn()
        params_sds = tmap(lambda p: _sds(p.shape, p.dtype), params0)
        stacked_sds = tmap(lambda p: _sds((T,) + p.shape, p.dtype), params_sds)
        bank_sds = tmap(
            lambda p: _sds((T, fed.num_clients) + p.shape, p.dtype),
            params_sds,
        )
        D = len(fs.mt_latency[0])
        buf_sds = AsyncBuffer(
            delta_sum=stacked_sds,
            weight=_sds((T,), jnp.float32),
            count=_sds((T,), jnp.float32),
            k=_sds((T,), jnp.float32),
            version=_sds((T,), jnp.int32),
            hist=tmap(lambda p: _sds((T, D) + p.shape, p.dtype), params_sds),
            stale_sum=_sds((T,), jnp.float32),
            stale_max=_sds((T,), jnp.float32),
            pending=_sds((T,), jnp.float32),
        )
        n_elems = sum(
            int(jnp.prod(jnp.array(p.shape))) if p.shape else 1
            for p in jax.tree_util.tree_leaves(params_sds)
        )
        # batched members (leading tenant dim): param-leaf update sums +
        # nlive + nfail + wsum + D staleness-histogram counters (r23
        # re-pin: +4*T*D B/worker); unbatched: the 4 tenant-invariant
        # wire scalars. Linear in T, one psum regardless of T.
        pb = 4 * (T * (n_elems + 3 + D) + 4)
        args = (
            stacked_sds,  # params [T, ...] (replicated)
            stacked_sds,  # w_ref [T, ...] (replicated)
            bank_sds,  # residual bank [T, N, ...], P(None, axis)
            None,  # telemetry accumulators (off)
            _sds((T,), jnp.int32),  # per-tenant round counters
            _sds((2,), jnp.uint32),  # tick key
            buf_sds,  # stacked aggregation buffers + w_hist rings
            _sds((T,), jnp.bool_),  # active tenant-slot mask
            _sds((T,), jnp.float32),  # per-tenant alpha
            _sds((T, D), jnp.float32),  # per-tenant latency rows
            None,  # cohort override (off: default trace)
            _sds((), jnp.int32),  # global tick counter
        )
        label = f"fedsim:multi-tenant-T{T}"
        ctx = AuditContext(
            label=label,
            allow_callbacks=False,
            expect_collectives={"psum": 1},
            wire_mode="collective",
            expected_wire_bytes=pb,
            num_workers=NUM_WORKERS,
            expect_codec_invocations=2,
            require_key_lineage=True,
        )
        records.append(trace_and_check(label, fn, args, ctx, payload_bytes=pb))
    return records


def _per_tensor_expected_gathers(cfg: DeepReduceConfig, d: int) -> int:
    """fused=False issues one all_gather per payload *leaf* (all_gather maps
    over the pytree) — the static count is the leaf count."""
    codec = TensorCodec((d,), cfg, name="g")
    key = jax.random.PRNGKey(cfg.seed)
    payload_sds = jax.eval_shape(lambda t: codec.encode(t, step=0, key=key), _sds((d,)))
    return len(jax.tree_util.tree_leaves(payload_sds))


def audit_ctrl_ladder(*, d: int = 4096) -> List[TraceRecord]:
    """The adaptive controller's bounded-re-jit contract, on the trace.

    The controller only ever moves along a pre-declared discrete ladder of
    operating points, and each rung builds ONE exchanger — so the whole
    adaptive run compiles at most len(ladder) step executables. This audit
    traces the flagship fused exchange at every rung (each trace runs the
    full rule set, including jx-callback: the controller must add no host
    callbacks to the step program) and pins two hash facts with
    jx-ctrl-ladder:

    - cardinality: the rungs trace to exactly len(ladder) DISTINCT jaxpr
      hashes — no accidental collisions (a rung that silently compiles to
      another rung's program would mean the ladder is lying about its
      resolution) and trivially no more than len(ladder) variants;
    - off-identity: a ctrl=True config at a rung traces byte-identical to
      a plain fixed config at the same operating point — the controller is
      host-side Python only and leaves zero residue in the traced program.
    """
    import hashlib

    from deepreduce_tpu.controller.ladder import Ladder

    base = dict(memory="residual", decode_strategy="loop", **_FLAGSHIP)
    cfg = DeepReduceConfig(
        telemetry=True, ctrl=True, ctrl_ladder=_CTRL_LADDER, **base
    )
    ladder = Ladder.parse(cfg.ctrl_ladder)
    records: List[TraceRecord] = []
    hashes: List[str] = []
    for i in range(len(ladder)):
        (rec,) = audit_exchange(
            f"ctrl:ladder[{i}]",
            ladder.apply(cfg, i),
            d=d,
            expect={"all_gather": 1},
            wire_mode="allgather",
        )
        hashes.append(rec.jaxpr_hash)
        records.append(rec)

    violations: List[Violation] = []
    if len(set(hashes)) != len(ladder):
        violations.append(
            Violation(
                R_CTRL_LADDER,
                "ctrl:ladder-cardinality",
                f"{len(ladder)} ladder rungs traced to "
                f"{len(set(hashes))} distinct jaxpr hashes ({hashes}) — "
                "bounded re-jit requires exactly one executable per rung",
            )
        )
    # off-identity at rung 0: same operating point, no ctrl knobs at all
    pt = ladder[0]
    off = DeepReduceConfig(
        telemetry=True,
        **{**base, "compress_ratio": pt.ratio,
           **({} if pt.fpr is None else {"fpr": pt.fpr})},
    )
    (rec_off,) = audit_exchange(
        "ctrl:off-identical", off, d=d,
        expect={"all_gather": 1}, wire_mode="allgather",
    )
    if rec_off.jaxpr_hash != hashes[0]:
        violations.append(
            Violation(
                R_CTRL_LADDER,
                "ctrl:off-identical",
                f"ctrl=True trace at rung 0 ({hashes[0]}) differs from the "
                f"fixed-config trace at the same operating point "
                f"({rec_off.jaxpr_hash}) — the controller must be host-side "
                "only",
            )
        )
    records.append(rec_off)
    records.append(
        TraceRecord(
            label="ctrl:ladder-cardinality",
            violations=violations,
            collectives={},
            # a stable digest over the per-rung hashes: re-baselining
            # catches any rung's program changing even via this record
            jaxpr_hash=hashlib.sha256(
                "".join(hashes).encode()
            ).hexdigest()[:16],
        )
    )
    return records


def audit_streaming_exchange() -> List[TraceRecord]:
    """The backprop-overlapped streaming schedule (cfg.stream_exchange):
    trace one streamed grad+exchange step — comm_stream's custom_vjp
    hooks dispatch each bucket's encode + all_gather from inside the
    backward pass — over the bucketed census on the 8-way mesh.

    The invariants are the BARRIER schedule's, unchanged: exactly
    _BUCKET_COUNT all_gather eqns whose operand bytes sum to
    payload_bytes() (the wire-accounting rule), _BUCKET_COUNT sparsifier
    selections for 6 leaves, no callbacks, retrace-stable. Streaming
    moves dispatch order only; if it ever grew an extra collective, a
    re-encode, or changed a payload byte, this audit flags it."""
    from jax.sharding import PartitionSpec as P

    from deepreduce_tpu.comm_stream import StreamingExchange

    label = "exchange:streaming"
    tmap = jax.tree_util.tree_map
    mesh = audit_mesh()
    cfg = DeepReduceConfig(
        memory="residual", decode_strategy="loop",
        bucket_bytes=_BUCKET_BYTES, stream_exchange=True, **_FLAGSHIP
    )
    grads_like = {n: _sds((int(sz),)) for n, sz in _BUCKET_LEAVES.items()}
    ex = GradientExchanger(
        grads_like, cfg, axis_name=AXIS, num_workers=NUM_WORKERS
    )
    stream = StreamingExchange(ex)
    pb = ex.payload_bytes(grads_like)
    g_w = tmap(lambda s: _sds((NUM_WORKERS,) + s.shape), grads_like)

    def loss_fn(params, batch_stats, batch):
        # linear-in-params probe: the cotangent of each leaf is its batch
        # row, so the streamed hooks see ordinary per-worker gradients
        loss = sum(
            jnp.sum(p * batch[n]) for n, p in params.items()
        )
        return loss, batch_stats

    def spmd(p, b_w, res, step):
        b0 = tmap(lambda x: x[0], b_w)
        res0 = tmap(lambda r: r[0], res)
        _, _, agg, new_res, _ = stream.value_and_grad_exchange(
            loss_fn, p, {}, b0, res0, step=step
        )
        new_res = tmap(lambda r: r[None], new_res)
        return tmap(lambda x: x[None], agg), new_res

    fn = _shard_map(
        spmd, mesh, (P(), P(AXIS), P(AXIS), P()), (P(AXIS), P(AXIS))
    )
    args = (grads_like, g_w, g_w, _STEP)
    ctx = AuditContext(
        label=label,
        allow_callbacks=False,
        expect_collectives={"all_gather": _BUCKET_COUNT},
        wire_mode="allgather",
        expected_wire_bytes=pb,
        num_workers=NUM_WORKERS,
        expect_codec_invocations=_BUCKET_COUNT,
        expect_stream_buckets=_BUCKET_COUNT,
        require_key_lineage=True,
    )
    return [trace_and_check(label, fn, args, ctx, payload_bytes=pb)]


def audit_streaming_hier_exchange() -> List[TraceRecord]:
    """The composed stream-over-hier schedule (cfg.stream_exchange AND
    cfg.hier): trace one streamed grad+exchange step where the
    StreamingExchange wraps a HierarchicalExchanger on the (2, 4)
    two-axis mesh.

    The per-axis inventory pins the composition: each bucket's dense
    slice-mean psum rides ici and its compressed gather rides dcn —
    exactly _BUCKET_COUNT of each, nothing else anywhere.  Wire
    accounting runs dcn-filtered against the DCN-only payload_bytes()
    (the ici leg is accounted separately via WireStats.ici_bits), and
    token dominance still contracts exactly two optimization barriers
    per bucket: the ici psum runs INSIDE each bucket's barrier bracket
    via the pre_encode hook, so the barrier count is the barrier
    schedule's, unchanged."""
    from jax.sharding import PartitionSpec as P

    from deepreduce_tpu.comm_stream import StreamingExchange
    from deepreduce_tpu.parallel.hierarchical import HierarchicalExchanger

    label = "exchange:stream-hier"
    tmap = jax.tree_util.tree_map
    n_slices, per_slice = 2, 4
    mesh = audit_hier_mesh(n_slices, per_slice)
    cfg = DeepReduceConfig(
        memory="residual", decode_strategy="loop",
        bucket_bytes=_BUCKET_BYTES, stream_exchange=True, hier=True,
        **_FLAGSHIP
    )
    grads_like = {n: _sds((int(sz),)) for n, sz in _BUCKET_LEAVES.items()}
    ex = HierarchicalExchanger(
        grads_like, cfg, num_slices=n_slices, per_slice=per_slice
    )
    stream = StreamingExchange(ex)
    n_buckets = len(ex.exchanger._bucketed.codecs)
    pb = ex.payload_bytes(grads_like)
    w = n_slices * per_slice
    g_w = tmap(lambda s: _sds((w,) + s.shape), grads_like)

    def loss_fn(params, batch_stats, batch):
        loss = sum(jnp.sum(p * batch[n]) for n, p in params.items())
        return loss, batch_stats

    def spmd(p, b_w, res, step):
        b0 = tmap(lambda x: x[0], b_w)
        res0 = tmap(lambda r: r[0], res)
        _, _, agg, new_res, _ = stream.value_and_grad_exchange(
            loss_fn, p, {}, b0, res0, step=step
        )
        new_res = tmap(lambda r: r[None], new_res)
        return tmap(lambda x: x[None], agg), new_res

    spec_p = P(("dcn", "ici"))
    fn = _shard_map(
        spmd, mesh, (P(), spec_p, spec_p, P()), (spec_p, spec_p)
    )
    args = (grads_like, g_w, g_w, _STEP)
    ctx = AuditContext(
        label=label,
        allow_callbacks=False,
        expect_collectives_by_axis={
            "ici": {"psum": n_buckets},
            "dcn": {"all_gather": n_buckets},
        },
        wire_mode="allgather",
        wire_axis="dcn",
        expected_wire_bytes=pb,
        num_workers=n_slices,
        expect_codec_invocations=_BUCKET_COUNT,
        expect_stream_buckets=n_buckets,
        require_key_lineage=True,
    )
    return [trace_and_check(label, fn, args, ctx, payload_bytes=pb)]


def audit_calib_reselect() -> List[TraceRecord]:
    """The calibration no-op contract (jx-calib-reselect), in two halves.

    Selector identity: `costmodel.static_profile()` encodes exactly the
    static constants, so threading it through `select_rs_mode` and
    `select_hier_plan` must change NOTHING — same pick, and the same
    candidate table to the last float — across a shape sweep that
    includes the flip-prone small-slice-count hierarchy (2x16, the shape
    a genuinely *fitted* profile does flip in BENCH_CALIB_r16). If this
    half ever fires, the profile plumbing is biased: it would re-price
    candidates even when telemetry taught us nothing.

    Program identity: an rs_mode='auto' exchange traced with
    profile=static_profile() must be byte-identical (same jaxpr hash) to
    the same config traced with no profile at all. Profiles act at
    construction-time selection only — they must leave zero residue in
    the traced program, so `Trainer.apply_profile`'s bounded-retrace
    accounting (one executable per visited plan key) stays honest.

    The final digest record folds every hash and every pick into one
    sha256, so re-baselining ANALYSIS.json catches a selector pick
    drifting even while both arms keep agreeing with each other.
    """
    import hashlib

    from deepreduce_tpu import costmodel

    prof = costmodel.static_profile()
    violations: List[Violation] = []
    picks: List[str] = []

    # --- selector identity sweep -------------------------------------- #
    for d in (4096, 4_053_428):
        for ratio in (0.001, 0.01, 0.1):
            for W in (8, 32):
                base = costmodel.select_rs_mode(d, W, ratio)
                with_p = costmodel.select_rs_mode(d, W, ratio, profile=prof)
                picks.append(f"rs:{d}:{W}:{ratio}:{base}")
                if base != with_p:
                    violations.append(
                        Violation(
                            R_CALIB_RESELECT,
                            "calib:selector-identity",
                            f"select_rs_mode(d={d}, W={W}, ratio={ratio}) "
                            f"flipped {base!r} -> {with_p!r} under "
                            "static_profile() — the constants-equivalent "
                            "profile must be a no-op",
                        )
                    )
            for n_slices, per_slice in ((8, 4), (2, 16)):
                base = costmodel.select_hier_plan(d, n_slices, per_slice, ratio)
                with_p = costmodel.select_hier_plan(
                    d, n_slices, per_slice, ratio, profile=prof
                )
                picks.append(
                    f"hier:{d}:{n_slices}x{per_slice}:{ratio}:"
                    f"{base['ici']}+{base['dcn']}"
                )
                if (base["ici"], base["dcn"]) != (with_p["ici"], with_p["dcn"]):
                    violations.append(
                        Violation(
                            R_CALIB_RESELECT,
                            "calib:selector-identity",
                            f"select_hier_plan(d={d}, {n_slices}x{per_slice}, "
                            f"ratio={ratio}) flipped "
                            f"{base['ici']}+{base['dcn']} -> "
                            f"{with_p['ici']}+{with_p['dcn']} under "
                            "static_profile()",
                        )
                    )
                elif base["table"] != with_p["table"]:
                    violations.append(
                        Violation(
                            R_CALIB_RESELECT,
                            "calib:selector-identity",
                            f"select_hier_plan(d={d}, {n_slices}x{per_slice}, "
                            f"ratio={ratio}) kept its pick but re-priced the "
                            "candidate table under static_profile() — the "
                            "constants-equivalent profile must not move a "
                            "single float",
                        )
                    )

    # --- traced-program identity --------------------------------------- #
    cfg = DeepReduceConfig(
        communicator="sparse_rs", compressor="topk", memory="none",
        deepreduce=None, compress_ratio=0.01, rs_mode="auto",
    )
    (rec_off,) = audit_exchange("calib:auto-no-profile", cfg, d=4096)
    (rec_on,) = audit_exchange(
        "calib:auto-static-profile", cfg, d=4096, profile=prof
    )
    if rec_off.jaxpr_hash != rec_on.jaxpr_hash:
        violations.append(
            Violation(
                R_CALIB_RESELECT,
                "calib:program-identity",
                f"rs_mode='auto' exchange traced with static_profile() "
                f"({rec_on.jaxpr_hash}) differs from the profile-free trace "
                f"({rec_off.jaxpr_hash}) — profiles must act at "
                "construction-time selection only and leave no residue in "
                "the step program",
            )
        )
    return [
        rec_off,
        rec_on,
        TraceRecord(
            label="calib:reselect-identity",
            violations=violations,
            collectives={},
            # digest over both traced hashes and every static selector
            # pick: re-baselining pins the picks themselves, not just the
            # agreement between the two arms
            jaxpr_hash=hashlib.sha256(
                "".join([rec_off.jaxpr_hash, rec_on.jaxpr_hash] + picks).encode()
            ).hexdigest()[:16],
        ),
    ]


# ---------------------------------------------------------------------- #
# the audited configuration inventory
# ---------------------------------------------------------------------- #

# the ladder the ctrl audits and tier-1 adaptive tests pin (matches the
# controller check CLI)
_CTRL_LADDER = "0.01,0.02,0.05"

_FLAGSHIP = dict(
    deepreduce="index",
    index="bloom",
    bloom_blocked="mod",
    compress_ratio=0.02,
    fpr=0.01,
    min_compress_size=100,
)

# the many-leaf census the bucketed audits trace: one big embedding-style
# leaf (stays solo) plus five small gate/bias-style leaves. At
# _BUCKET_BYTES = 4800 B (1200 f32 elements) the deterministic FFD
# partition is exactly THREE buckets — emb solo, {w1,b1}, {w2,b2,b3} —
# so the collective inventory pins all_gather == 3 and jx-codec-count
# pins 3 sparsifier selections for 6 leaves (the O(buckets) claim).
_BUCKET_LEAVES = {"emb": 3000, "w1": 900, "w2": 700, "b1": 300, "b2": 150, "b3": 50}
_BUCKET_BYTES = 4800
_BUCKET_COUNT = 3


def audit_specs(quick: bool = False) -> List[Tuple[str, Callable[[], List[TraceRecord]]]]:
    """(label, thunk) per audited config. `quick` keeps the tier-1 subset:
    the flagship codec + query + the three fused decode strategies."""
    C = DeepReduceConfig
    specs: List[Tuple[str, Callable[[], List[TraceRecord]]]] = []

    def add(label, thunk):
        specs.append((label, thunk))

    # --- the flagship mod-blocked hot path (always audited) ---
    add("query:bloom-mod", lambda: audit_mod_query())
    add(
        "codec:bloom-mod",
        lambda: audit_codec(
            "codec:bloom-mod", C(**_FLAGSHIP), enforce_sorted=True
        ),
    )
    add(
        "exchange:fused-loop",
        lambda: audit_exchange(
            "exchange:fused-loop",
            C(memory="residual", decode_strategy="loop", **_FLAGSHIP),
            expect={"all_gather": 1},
            wire_mode="allgather",
            enforce_sorted=True,
            # single leaf -> exactly one sparsifier selection (the
            # O(leaves) baseline jx-codec-count pins)
            expect_codec=1,
        ),
    )
    add(
        "exchange:bucketed-loop",
        lambda: audit_exchange(
            "exchange:bucketed-loop",
            C(memory="residual", decode_strategy="loop",
              bucket_bytes=_BUCKET_BYTES, **_FLAGSHIP),
            leaves=_BUCKET_LEAVES,
            # exactly C all_gather eqns whose operand bytes sum to
            # payload_bytes(), and C codec invocations for 6 leaves
            expect={"all_gather": _BUCKET_COUNT},
            wire_mode="allgather",
            enforce_sorted=True,
            expect_codec=_BUCKET_COUNT,
        ),
    )
    add(
        "exchange:fused-vmap",
        lambda: audit_exchange(
            "exchange:fused-vmap",
            C(memory="residual", decode_strategy="vmap", decode_batch=4, **_FLAGSHIP),
            expect={"all_gather": 1},
            wire_mode="allgather",
        ),
    )
    add(
        "exchange:fused-ring",
        lambda: audit_exchange(
            "exchange:fused-ring",
            C(memory="residual", decode_strategy="ring", **_FLAGSHIP),
            expect={"ppermute": 2},  # prologue hop + the loop-body hop
            wire_mode="ring",
        ),
    )
    # --- resilience: the masked/checksummed fused path still shows exactly
    # one all_gather whose operand bytes match payload_bytes() (the psum(1)
    # live-count in train.py constant-folds; the mask denominator is a local
    # reduction over the replicated mask, not a collective) ---
    add(
        "exchange:fused-loop-resilient",
        lambda: audit_exchange(
            "exchange:fused-loop-resilient",
            C(memory="residual", decode_strategy="loop", resilience=True,
              payload_checksum=True, chaos_corrupt_rate=0.2, **_FLAGSHIP),
            expect={"all_gather": 1},
            wire_mode="allgather",
            with_mask=True,
        ),
    )
    # --- resilience off must be zero-cost (byte-identical trace) ---
    add("resilience:off-identical", lambda: audit_resilience_off())
    # --- hierarchical flagship: dense ici psum + fused dcn allgather on the
    # (2, 4) two-axis mesh. The per-axis inventory pins the fabric split —
    # exactly one psum on ici, exactly one all_gather on dcn, nothing else
    # anywhere — and the dcn-filtered wire accounting pins payload_bytes()
    # (DCN-only by contract) against the dcn leg alone ---
    add(
        "hier:fused-loop",
        lambda: audit_hier_exchange(
            "hier:fused-loop",
            C(memory="residual", decode_strategy="loop", hier=True, **_FLAGSHIP),
            expect_by_axis={"ici": {"psum": 1}, "dcn": {"all_gather": 1}},
            wire_mode="allgather",
            expect_codec=1,
        ),
    )
    if quick:
        return specs

    # --- every registered index codec ---
    add(
        "codec:bloom",
        lambda: audit_codec(
            "codec:bloom",
            C(deepreduce="index", index="bloom", compress_ratio=0.02, fpr=0.01,
              min_compress_size=100),
        ),
    )
    add(
        "codec:bloom-hash",
        lambda: audit_codec(
            "codec:bloom-hash",
            C(deepreduce="index", index="bloom", bloom_blocked="hash",
              compress_ratio=0.02, fpr=0.01, min_compress_size=100),
        ),
    )
    add(
        "codec:bloom-mod-p0",
        lambda: audit_codec(
            "codec:bloom-mod-p0",
            C(policy="p0", **_FLAGSHIP),
            enforce_sorted=True,
        ),
    )
    add(
        "codec:bloom-direct",
        lambda: audit_codec(
            "codec:bloom-direct",
            C(compressor="topk_sampled", bloom_threshold_insert=True, **_FLAGSHIP),
            enforce_sorted=True,
        ),
    )
    for idx in ("rle", "integer", "huffman"):
        add(
            f"codec:{idx}",
            lambda idx=idx: audit_codec(
                f"codec:{idx}",
                C(deepreduce="index", index=idx, compress_ratio=0.02,
                  min_compress_size=100),
            ),
        )
    for idx in ("bloom_native", "integer_native"):
        add(
            f"codec:{idx}",
            lambda idx=idx: audit_codec(
                f"codec:{idx}",
                C(deepreduce="index", index=idx, compress_ratio=0.02, fpr=0.01,
                  min_compress_size=100),
            ),
        )

    # --- every registered value codec ---
    for val in ("polyfit", "doubleexp", "qsgd", "gzip", "polyfit_host"):
        add(
            f"codec:{val}",
            lambda val=val: audit_codec(
                f"codec:{val}",
                C(deepreduce="value", value=val, compress_ratio=0.02,
                  min_compress_size=100),
            ),
        )
    add(
        "codec:polyseg",
        lambda: audit_codec(
            "codec:polyseg",
            C(deepreduce="value", value="polyseg", compress_ratio=0.02,
              min_compress_size=100),
            name="conv_kernel",
        ),
    )
    add(
        "codec:both-modbloom-qsgd",
        lambda: audit_codec(
            "codec:both-modbloom-qsgd",
            C(**{**_FLAGSHIP, "deepreduce": "both", "value": "qsgd", "policy": "p0"}),
        ),
    )

    # --- remaining communicator shapes ---
    add(
        "exchange:bucketed-vmap",
        lambda: audit_exchange(
            "exchange:bucketed-vmap",
            C(memory="residual", decode_strategy="vmap", decode_batch=4,
              bucket_bytes=_BUCKET_BYTES, **_FLAGSHIP),
            leaves=_BUCKET_LEAVES,
            expect={"all_gather": _BUCKET_COUNT},
            wire_mode="allgather",
            expect_codec=_BUCKET_COUNT,
        ),
    )
    add(
        "exchange:per-tensor",
        lambda: audit_exchange(
            "exchange:per-tensor",
            C(fused=False, memory="none", **_FLAGSHIP),
            expect={"all_gather": _per_tensor_expected_gathers(C(**_FLAGSHIP), 4096)},
            wire_mode="allgather",
        ),
    )
    add(
        "exchange:dense-allreduce",
        lambda: audit_exchange(
            "exchange:dense-allreduce",
            C(communicator="allreduce", compressor="none", memory="none",
              deepreduce=None),
            expect={"psum": 1},
        ),
    )
    add(
        "exchange:qar",
        lambda: audit_exchange(
            "exchange:qar",
            C(communicator="qar", compressor="none", memory="none", deepreduce=None),
            # 2 all_to_all (quantized levels + bucket norms to shard owners)
            # + 2 all_gather (reduced levels + norms back) — qar.py:124-135
            expect={"all_to_all": 2, "all_gather": 2},
        ),
    )
    add(
        "exchange:sparse_rs",
        lambda: audit_exchange(
            "exchange:sparse_rs",
            C(communicator="sparse_rs", compressor="topk", memory="none",
              deepreduce=None, compress_ratio=0.02),
            # 1 all_to_all (routed (val,idx) pairs) + 1 all_gather (reduced
            # shards back) — sparse_rs.py:123,143
            expect={"all_to_all": 1, "all_gather": 1},
            # wire accounting over ALL collectives: the all_to_all rows plus
            # the phase-2 gather must sum exactly to payload_bytes()
            wire_mode="collective",
        ),
    )
    # --- the r11 in-collective routes: same communicator, three new
    # rs_mode arms. Each pins its full collective inventory AND exact
    # per-collective operand bytes against costmodel.rs_payload_bytes ---
    add(
        "exchange:sparse_rs-adaptive",
        lambda: audit_exchange(
            "exchange:sparse_rs-adaptive",
            C(communicator="sparse_rs", compressor="topk", memory="none",
              deepreduce=None, compress_ratio=0.02, rs_mode="adaptive"),
            # same skeleton as sparse (phase-1 all_to_all + phase-2
            # all_gather); the density switch widens the gathered row to
            # the fixed dual-interpretation lane budget, never adds a
            # collective
            expect={"all_to_all": 1, "all_gather": 1},
            wire_mode="collective",
        ),
    )
    add(
        "exchange:sparse_rs-quantized",
        lambda: audit_exchange(
            "exchange:sparse_rs-quantized",
            C(communicator="sparse_rs", compressor="topk", memory="none",
              deepreduce=None, compress_ratio=0.02, rs_mode="quantized"),
            # pmax (shared bucket norms) + the int8 psum_scatter — which
            # lowers to one reduce_scatter eqn — + phase-2 all_gather of
            # the re-selected top-K2
            expect={"pmax": 1, "reduce_scatter": 1, "all_gather": 1},
            wire_mode="collective",
        ),
    )
    add(
        "exchange:sparse_rs-sketch",
        lambda: audit_exchange(
            "exchange:sparse_rs-sketch",
            C(communicator="sparse_rs", compressor="topk", memory="none",
              deepreduce=None, compress_ratio=0.02, rs_mode="sketch"),
            # ONE psum of the [rows, cols] count-sketch (linear, summable)
            # + phase-2 all_gather of the unsketched shard's top-K2
            expect={"psum": 1, "all_gather": 1},
            wire_mode="collective",
        ),
    )
    # --- remaining hierarchical shapes: every leg combination the planner
    # can pick, each with its full per-axis inventory ---
    add(
        "hier:fused-loop-keyed",
        lambda: audit_hier_exchange(
            "hier:fused-loop-keyed",
            C(memory="residual", decode_strategy="loop", hier=True, **_FLAGSHIP),
            # the key-repair broadcast is ONE extra tiny all_gather on ici
            # (replica 0's PRNGKey), never on dcn
            expect_by_axis={
                "ici": {"psum": 1, "all_gather": 1},
                "dcn": {"all_gather": 1},
            },
            wire_mode="allgather",
            with_key=True,
        ),
    )
    add(
        "hier:qar-ici",
        lambda: audit_hier_exchange(
            "hier:qar-ici",
            C(memory="residual", decode_strategy="loop", hier=True,
              hier_ici="qar", **_FLAGSHIP),
            # the int8 quantized allreduce rides ici with its flat inventory
            # (2 all_to_all + 2 all_gather, exchange:qar above); the dcn leg
            # is untouched by the ici choice
            expect_by_axis={
                "ici": {"all_to_all": 2, "all_gather": 2},
                "dcn": {"all_gather": 1},
            },
            wire_mode="allgather",
        ),
    )
    add(
        "hier:bucketed-dcn",
        lambda: audit_hier_exchange(
            "hier:bucketed-dcn",
            C(memory="residual", decode_strategy="loop", hier=True,
              bucket_bytes=_BUCKET_BYTES, **_FLAGSHIP),
            leaves=_BUCKET_LEAVES,
            # dense ici reduction is one psum PER LEAF (6); the bucketed dcn
            # leg keeps its O(buckets) shape: C all_gathers, C codec runs
            expect_by_axis={
                "ici": {"psum": len(_BUCKET_LEAVES)},
                "dcn": {"all_gather": _BUCKET_COUNT},
            },
            wire_mode="allgather",
            expect_codec=_BUCKET_COUNT,
        ),
    )
    add(
        "hier:quantized-dcn",
        lambda: audit_hier_exchange(
            "hier:quantized-dcn",
            C(communicator="sparse_rs", compressor="topk", memory="none",
              deepreduce=None, compress_ratio=0.02, rs_mode="quantized",
              hier=True),
            # the in-collective quantized route keeps its flat inventory on
            # dcn (pmax + reduce_scatter + all_gather, exchange:sparse_rs-
            # quantized above) with the dense slice psum on ici
            expect_by_axis={
                "ici": {"psum": 1},
                "dcn": {"pmax": 1, "reduce_scatter": 1, "all_gather": 1},
            },
            wire_mode="collective",
        ),
    )
    # --- the federated round: one psum, exact wire accounting, two codec
    # invocations (S2C delta + the shared vmapped C2S client encode) ---
    add("fedsim:round", lambda: audit_fedsim_round())
    add(
        "codec:countsketch",
        lambda: audit_codec(
            "codec:countsketch",
            C(deepreduce="value", value="countsketch", compress_ratio=0.02,
              min_compress_size=100),
        ),
    )
    # --- the adaptive controller's ladder: one executable per rung,
    # distinct hashes, zero traced residue (registered last so the
    # pre-existing record order — and ANALYSIS.json hashes — are stable) ---
    add("ctrl:ladder", lambda: audit_ctrl_ladder())
    # --- the streaming schedule: bucketed invariants unchanged with every
    # dispatch moved into the backward pass (registered last so the
    # pre-existing record order — and ANALYSIS.json hashes — are stable) ---
    add("exchange:streaming", lambda: audit_streaming_exchange())
    add("calib:reselect", lambda: audit_calib_reselect())
    # --- the r18 oktopk balanced route (registered last so the pre-existing
    # record order — and ANALYSIS.json hashes — are stable) ---
    add(
        "exchange:sparse_rs-oktopk",
        lambda: audit_exchange(
            "exchange:sparse_rs-oktopk",
            C(communicator="sparse_rs", compressor="topk", memory="none",
              deepreduce=None, compress_ratio=0.02, rs_mode="oktopk"),
            # ONE psum of the f32[bins] magnitude histogram (the global
            # threshold pass) + the balanced all_to_all of surviving
            # (val, idx) pairs + phase-2 all_gather of the re-selected
            # top-K2 — and exact per-collective byte agreement with
            # costmodel.rs_wire_bytes('oktopk', ...)
            expect={"psum": 1, "all_to_all": 1, "all_gather": 1},
            wire_mode="collective",
        ),
    )
    # --- the r20 asynchronous buffered tick: same one-psum contract with
    # the staleness-weight mass riding the fused tuple (registered last so
    # the pre-existing record order — and ANALYSIS.json hashes — are
    # stable) ---
    add("fedsim:async-round", lambda: audit_fedsim_async_round())
    # --- the r21 multi-tenant tick: one psum independent of T, operand
    # bytes linear in T (registered last so the pre-existing record order —
    # and ANALYSIS.json hashes — are stable) ---
    add("fedsim:multi-tenant", lambda: audit_fedsim_multitenant())
    # --- the r24 composed legs (registered last so the pre-existing record
    # order — and ANALYSIS.json hashes — are stable) ---
    # stream-over-hier: each bucket's ici psum + dcn gather dispatched from
    # inside the bucket's backward hook, two barriers per bucket unchanged
    add("exchange:stream-hier", lambda: audit_streaming_hier_exchange())
    # the re-owned resilient sparse_rs routes: the live mask threads through
    # the exchange without changing the collective skeleton (sparse) or
    # adding more than the one int8 shard re-broadcast (quantized, whose
    # masked wire grows by exactly n/W bytes — pinned by the byte audit
    # against rs_payload_bytes(..., masked=True))
    add(
        "exchange:sparse_rs-sparse-masked",
        lambda: audit_exchange(
            "exchange:sparse_rs-sparse-masked",
            C(communicator="sparse_rs", compressor="topk", memory="none",
              deepreduce=None, compress_ratio=0.02, rs_mode="sparse",
              resilience=True),
            expect={"all_to_all": 1, "all_gather": 1},
            wire_mode="collective",
            with_mask=True,
        ),
    )
    add(
        "exchange:sparse_rs-quantized-masked",
        lambda: audit_exchange(
            "exchange:sparse_rs-quantized-masked",
            C(communicator="sparse_rs", compressor="topk", memory="none",
              deepreduce=None, compress_ratio=0.02, rs_mode="quantized",
              resilience=True),
            # the flat quantized inventory plus ONE extra int8 all_gather:
            # every worker re-broadcasts its summed shard so deputies can
            # dequantize and re-own a dropped worker's slice
            expect={"pmax": 1, "reduce_scatter": 1, "all_gather": 2},
            wire_mode="collective",
            with_mask=True,
        ),
    )
    add(
        "exchange:sparse_rs-oktopk-masked",
        lambda: audit_exchange(
            "exchange:sparse_rs-oktopk-masked",
            C(communicator="sparse_rs", compressor="topk", memory="none",
              deepreduce=None, compress_ratio=0.02, rs_mode="oktopk",
              resilience=True),
            # masked oktopk zeroes dropped histogram weights before the
            # psum and re-owns on the route — wire layout unchanged
            expect={"psum": 1, "all_to_all": 1, "all_gather": 1},
            wire_mode="collective",
            with_mask=True,
        ),
    )
    # --- the r25 heterogeneous-population plane: one psum with the exact
    # per-class participation histogram riding the fused tuple — operand
    # bytes re-pinned to 4*(n+6+K) sync / 4*(n+7+D+K) async / +D more
    # with per-class latency rows (registered last so the pre-existing
    # record order — and ANALYSIS.json hashes — are stable) ---
    add("fedsim:population", lambda: audit_fedsim_population())
    return specs


def audit_all(quick: bool = False) -> Tuple[List[TraceRecord], List[Violation]]:
    """Run every audit spec; native-backed codecs degrade to a 'skipped'
    record when the host library cannot build in this environment."""
    records: List[TraceRecord] = []
    for label, thunk in audit_specs(quick=quick):
        try:
            records.extend(thunk())
        except (ImportError, OSError, RuntimeError) as e:
            # host-library-dependent configs (bloom_native/integer_native)
            # may be unbuildable here; that is an environment limitation,
            # not an invariant violation — record it visibly
            records.append(
                TraceRecord(
                    label=label,
                    violations=[],
                    collectives={},
                    jaxpr_hash="",
                    skipped=f"{type(e).__name__}: {e}",
                )
            )
    violations = [v for r in records for v in r.violations]
    return records, violations


def peak_budget_violations(
    records: List[TraceRecord], budgets: Dict[str, int]
) -> List[Violation]:
    """jx-peak-bytes budget gate: each fresh trace's modeled peak must equal
    the committed per-trace byte budget. Labels absent from the baseline
    (new traces) and records without a peak (skipped / digest-only) bootstrap
    silently — the write that follows commits them."""
    out: List[Violation] = []
    for rec in records:
        if rec.peak_bytes is None or rec.label not in budgets:
            continue
        want = budgets[rec.label]
        if rec.peak_bytes != want:
            out.append(
                Violation(
                    R_PEAK_BYTES,
                    rec.label,
                    f"peak live bytes drifted from the committed budget: "
                    f"modeled {rec.peak_bytes} B vs committed {want} B "
                    f"(re-baseline deliberately with --update)",
                )
            )
    return out
