"""CLI gate: `python -m deepreduce_tpu.analysis [COMMAND] [flags]`.

Commands:

- ``audit`` (default): AST lint over the repo + the jaxpr audit over every
  registered codec/communicator config (or the tier-1 ``--quick`` subset).
  Writes a deterministic ANALYSIS.json; exits 1 on any violation.
- ``matrix``: probe the full composition lattice (analysis/lattice.py),
  rebuild MATRIX.json, and diff it against the committed baseline. Exits 1
  on any rule violation, any codeless rejection, or any legality /
  reason-code / trace-hash / peak-byte drift vs the baseline; ``--update``
  rewrites the baseline instead of failing on drift. Prints memoization +
  wall-time stats (cells probed, fingerprint cache hits, seconds) so
  lattice-widening PRs can see their audit-cost budget.
- ``mem``: the liveness interpreter over the flagship fused / bucketed /
  streaming / fedsim traces — a human-readable peak + top-3 buffer table
  with provenance. Exits 1 on any violation in those traces.
- ``list``: print every rule id with its one-line contract and exit.

``audit`` additionally gates jx-peak-bytes: each trace's modeled peak live
bytes must equal the budget committed in ANALYSIS.json. Drift exits 1
without touching the baseline; ``--update`` re-baselines deliberately.

``--only RULE[,RULE]`` restricts the failure gate (and the printed
violations) to the named rules — the full audit still runs and the report
still records everything, so a focused run can never silently shrink the
committed artifact.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def _parse_only(spec, parser):
    from deepreduce_tpu.analysis.rules import ALL_RULE_IDS

    if spec is None:
        return None
    rules = [r.strip() for r in spec.split(",") if r.strip()]
    unknown = [r for r in rules if r not in ALL_RULE_IDS]
    if unknown:
        parser.error(
            f"unknown rule(s) {', '.join(unknown)}; "
            f"run `list` for the rule table"
        )
    return set(rules)


def _gate(violations, only):
    """The subset of violations that fail the run under --only."""
    if only is None:
        return list(violations)
    return [v for v in violations if v.get("rule") in only]


def _cmd_list() -> int:
    from deepreduce_tpu.analysis.rules import ALL_RULE_IDS, RULE_DESCRIPTIONS

    width = max(len(r) for r in ALL_RULE_IDS)
    for rule in ALL_RULE_IDS:
        print(f"{rule:<{width}}  {RULE_DESCRIPTIONS[rule]}")
    return 0


def _load_budgets(path):
    """Committed per-trace peak budgets from an existing ANALYSIS.json;
    {} when there is no baseline (or it predates peak accounting)."""
    try:
        baseline = json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return {}
    budgets = {}
    for t in baseline.get("jaxpr_audit", {}).get("traces", []):
        if t.get("peak_bytes") is not None:
            budgets[t["label"]] = t["peak_bytes"]
    return budgets


def _cmd_audit(args, only) -> int:
    from deepreduce_tpu.analysis.ast_lint import lint_repo
    from deepreduce_tpu.analysis.jaxpr_audit import (
        audit_all,
        peak_budget_violations,
    )
    from deepreduce_tpu.analysis.lattice import SCHEMA

    root = Path(__file__).resolve().parents[2]
    ast_violations = lint_repo(root)
    records, jaxpr_violations = audit_all(quick=args.quick)

    # jx-peak-bytes budget gate: compare fresh peaks against the committed
    # baseline at the output path BEFORE overwriting it. --quick audits a
    # subset, so only the labels it produced are compared. --update skips
    # the comparison and re-baselines deliberately.
    out_path = args.out if args.out is not None else root / "ANALYSIS.json"
    budget_drift = []
    if not args.update and str(out_path) != "-":
        budget_drift = peak_budget_violations(records, _load_budgets(out_path))
        jaxpr_violations = jaxpr_violations + budget_drift

    violations = [v.to_dict() for v in ast_violations + jaxpr_violations]
    skipped = [r.label for r in records if r.skipped is not None]
    report = {
        "schema": SCHEMA,
        "quick": args.quick,
        "ast_lint": {
            "violations": [v.to_dict() for v in ast_violations],
        },
        "jaxpr_audit": {
            "traces": [r.to_dict() for r in records],
            "violations": [v.to_dict() for v in jaxpr_violations],
        },
        "summary": {
            "traces": len(records),
            "skipped": skipped,
            "violations": len(violations),
        },
    }

    if str(out_path) != "-":
        if budget_drift:
            # leave the committed baseline alone on drift — re-baselining
            # a busted budget must be a deliberate --update
            print(f"NOT writing {out_path} (peak budget drift)")
        else:
            out_path.write_text(
                json.dumps(report, indent=2, sort_keys=True) + "\n"
            )
            print(f"wrote {out_path}")

    gate = _gate(violations, only)
    print(
        f"analysis: {len(records)} traces audited"
        + (f" ({len(skipped)} skipped: {', '.join(skipped)})" if skipped else "")
        + f", {len(ast_violations)} lint + {len(jaxpr_violations)} jaxpr violations"
        + (f" ({len(gate)} gated by --only)" if only is not None else "")
    )
    for v in gate:
        print(f"  [{v['rule']}] {v['where']}: {v['detail']}", file=sys.stderr)
    return 1 if gate else 0


# the memcheck flagships: the fused + bucketed exchange loops, the
# backprop-overlapped streaming step, and the federated round
MEM_LABELS = (
    "exchange:fused-loop",
    "exchange:bucketed-loop",
    "exchange:streaming",
    "fedsim:round",
)


def _human_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024
    return f"{n} B"


def _cmd_mem(args, only) -> int:
    from deepreduce_tpu.analysis.jaxpr_audit import audit_specs

    records = []
    for label, thunk in audit_specs():
        if label in MEM_LABELS:
            records.extend(thunk())

    for rec in records:
        print(f"{rec.label}: peak {rec.peak_bytes} B "
              f"({_human_bytes(rec.peak_bytes or 0)}) live")
        for buf in rec.peak_top or []:
            print(
                f"    {_human_bytes(buf['bytes']):>10}  "
                f"{buf['dtype']}{buf['shape']}  "
                f"<- {buf['prim']} @ {buf['site']}"
            )
        for prim, live in sorted((rec.collective_residency or {}).items()):
            print(f"    at {prim}: {_human_bytes(live)} live")

    gate = _gate([v.to_dict() for r in records for v in r.violations], only)
    for v in gate:
        print(f"  [{v['rule']}] {v['where']}: {v['detail']}", file=sys.stderr)
    print(f"memcheck: {len(records)} flagship traces, {len(gate)} violations")
    return 1 if gate else 0


def _cmd_matrix(args, only) -> int:
    import time

    from deepreduce_tpu.analysis import lattice

    root = Path(__file__).resolve().parents[2]
    baseline_path = args.out if args.out is not None else root / "MATRIX.json"

    stats = {}
    t0 = time.monotonic()
    report = lattice.build_matrix(
        progress=lambda m: print(f"  {m}", flush=True), stats=stats
    )
    wall = time.monotonic() - t0
    s = report["summary"]
    print(
        f"matrix: {s['cells']} cells -> {s['legal']} legal / "
        f"{s['rejected']} rejected ({len(s['reason_codes'])} reason codes, "
        f"{s['distinct_traces']} distinct traces)"
    )
    # audit-cost budget line (printed only — never written to the baseline)
    print(
        f"matrix cost: {stats.get('cells_probed', 0)} cells probed, "
        f"{stats.get('cache_hits', 0)} fingerprint cache hits, "
        f"{wall:.1f}s wall"
    )

    gate = _gate(report["violations"], only)
    for v in gate:
        print(f"  [{v['rule']}] {v['where']}: {v['detail']}", file=sys.stderr)

    drift = []
    if str(baseline_path) != "-":
        if not baseline_path.exists():
            lattice.write_matrix(report, baseline_path)
            print(f"wrote {baseline_path} (no baseline existed)")
        elif args.update:
            lattice.write_matrix(report, baseline_path)
            print(f"wrote {baseline_path} (--update)")
        else:
            baseline = lattice.load_report(baseline_path)
            drift = lattice.compare_matrix(baseline, report)
            for d in drift:
                print(f"  [matrix-drift] {d}", file=sys.stderr)
            if not drift:
                print(f"baseline {baseline_path}: no drift")

    return 1 if (gate or drift) else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m deepreduce_tpu.analysis",
        description="jaxpr invariant audit + repo AST lint + legality matrix",
    )
    parser.add_argument(
        "command",
        nargs="?",
        default="audit",
        choices=("audit", "matrix", "mem", "list"),
        help="audit (default): fixed trace list -> ANALYSIS.json; "
        "matrix: full composition lattice -> MATRIX.json; "
        "mem: liveness peak + top-3 buffer table for the flagship traces; "
        "list: print the rule table",
    )
    parser.add_argument(
        "--quick",
        action="store_true",
        help="audit only the tier-1 subset (flagship codec/query + the "
        "three fused decode strategies)",
    )
    parser.add_argument(
        "--only",
        metavar="RULE[,RULE]",
        default=None,
        help="gate the exit code on these rule ids only (audit still runs "
        "and records everything)",
    )
    parser.add_argument(
        "--list",
        action="store_true",
        dest="list_rules",
        help="alias for the `list` command",
    )
    parser.add_argument(
        "--update",
        action="store_true",
        help="rewrite the committed baseline instead of failing on drift "
        "(matrix: legality/hash/peak; audit: jx-peak-bytes budgets)",
    )
    parser.add_argument(
        "--out",
        type=Path,
        default=None,
        help="report path (default: ANALYSIS.json / MATRIX.json at the "
        "repo root; '-' to skip writing)",
    )
    args = parser.parse_args(argv)

    if args.list_rules or args.command == "list":
        return _cmd_list()
    only = _parse_only(args.only, parser)
    if args.command == "matrix":
        return _cmd_matrix(args, only)
    if args.command == "mem":
        return _cmd_mem(args, only)
    return _cmd_audit(args, only)


if __name__ == "__main__":
    sys.exit(main())
