"""CLI gate: `python -m deepreduce_tpu.analysis [--quick] [--out PATH]`.

Runs the AST lint over the repo and the jaxpr audit over every registered
codec/communicator config (or the tier-1 quick subset), writes a
deterministic ANALYSIS.json report, and exits 1 if anything violated.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m deepreduce_tpu.analysis",
        description="jaxpr invariant audit + repo AST lint",
    )
    parser.add_argument(
        "--quick",
        action="store_true",
        help="audit only the tier-1 subset (flagship codec/query + the "
        "three fused decode strategies)",
    )
    parser.add_argument(
        "--out",
        type=Path,
        default=None,
        help="report path (default: ANALYSIS.json at the repo root; '-' "
        "to skip writing)",
    )
    args = parser.parse_args(argv)

    from deepreduce_tpu.analysis.ast_lint import lint_repo
    from deepreduce_tpu.analysis.jaxpr_audit import audit_all

    root = Path(__file__).resolve().parents[2]
    ast_violations = lint_repo(root)
    records, jaxpr_violations = audit_all(quick=args.quick)

    violations = ast_violations + jaxpr_violations
    skipped = [r.label for r in records if r.skipped is not None]
    report = {
        "quick": args.quick,
        "ast_lint": {
            "violations": [v.to_dict() for v in ast_violations],
        },
        "jaxpr_audit": {
            "traces": [r.to_dict() for r in records],
            "violations": [v.to_dict() for v in jaxpr_violations],
        },
        "summary": {
            "traces": len(records),
            "skipped": skipped,
            "violations": len(violations),
        },
    }

    out_path = args.out if args.out is not None else root / "ANALYSIS.json"
    if str(out_path) != "-":
        out_path.write_text(json.dumps(report, indent=2, sort_keys=True) + "\n")
        print(f"wrote {out_path}")

    print(
        f"analysis: {len(records)} traces audited"
        + (f" ({len(skipped)} skipped: {', '.join(skipped)})" if skipped else "")
        + f", {len(ast_violations)} lint + {len(jaxpr_violations)} jaxpr violations"
    )
    for v in violations:
        print(f"  [{v.rule}] {v.where}: {v.detail}", file=sys.stderr)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
