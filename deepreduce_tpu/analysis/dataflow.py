"""SPMD dataflow rules over a flattened jaxpr graph.

The linear-walk rules in rules.py check *presence* properties (which eqns
exist, what they move). The composition lattice also needs *ordering and
lineage* properties — does the streaming token chain actually dominate each
bucket's collective, does anything read a donated buffer after its alias is
live, does every stochastic draw fold its key — and those are questions
about the dataflow DAG, not the eqn list.

``build_graph`` flattens a ClosedJaxpr into one linear node list: any call
eqn whose params carry exactly one sub-jaxpr with matching invar/outvar
arity (pjit / shard_map / remat / custom_* call bodies) is inlined so data
dependencies thread straight through it; ``cond`` / ``while`` / ``scan``
and anything else stay opaque single nodes whose outputs depend on all
inputs. Node emission order is topological (jaxprs are), so ancestor
reachability is a single forward pass over Python-int bitsets — cheap even
for the multi-thousand-eqn fedsim round.

SparCML (arXiv:1802.08021) is the motivation for jx-collective-schedule:
composed sparse-collective legs are only sound when every worker provably
enters the same collective sequence, which a collective under data-dependent
control flow breaks (divergence = deadlock on a real mesh).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from deepreduce_tpu.analysis.rules import (
    COLLECTIVE_PRIMS,
    R_COLLECTIVE_SCHEDULE,
    R_DONATION,
    R_KEY_LINEAGE,
    R_TOKEN_DOMINANCE,
    AuditContext,
    Violation,
    _subjaxprs,
)

# eqn params control flow recursion must treat as opaque: their sub-jaxprs
# run data-dependently (branch select / trip count), so inlining them into
# a straight-line dataflow would fabricate orderings that never execute
_OPAQUE_PRIMS = ("cond", "while", "scan")

# a ref is a producer handle for one value: ("lit", <repr>) for literals,
# (node_idx, out_pos) for everything else
Ref = Tuple[Any, Any]


@dataclasses.dataclass
class FlatEqn:
    """One node of the flattened graph: a primitive eqn, an opaque call, or
    a pseudo-source for a top-level invar/constvar."""

    idx: int
    prim: str
    eqn: Any  # None for sources
    in_refs: Tuple[Ref, ...]
    # aval per produced value (position-aligned with the node's out refs);
    # a source node has exactly one. The liveness interpreter prices
    # buffers off these.
    out_avals: Tuple[Any, ...] = ()


@dataclasses.dataclass
class Donation:
    """One inlined pjit call that donated buffers: which input refs were
    donated (with their avals) and the resolved refs/avals of the call's
    outputs, for first-fit alias matching."""

    donated: List[Tuple[int, Ref, Any]]  # (invar pos, ref, aval)
    out_refs: List[Tuple[Ref, Any]]  # (ref, aval) per call outvar


@dataclasses.dataclass
class DataflowGraph:
    nodes: List[FlatEqn]
    donations: List[Donation]
    # per-node ancestor bitset over node idxs (sources included)
    anc: List[int]
    # resolved refs of the top-level jaxpr outvars — the values that stay
    # live through the end of the program (liveness roots)
    out_refs: Tuple[Ref, ...] = ()

    def by_prim(self, name: str) -> List[FlatEqn]:
        return [fe for fe in self.nodes if fe.prim == name]

    def is_ancestor(self, a: int, b: int) -> bool:
        return bool((self.anc[b] >> a) & 1)


def _is_literal(v: Any) -> bool:
    return hasattr(v, "val")


def _lit_ref(v: Any) -> Ref:
    try:
        return ("lit", repr(v.val))
    except Exception:
        return ("lit", "?")


def _inline_target(eqn: Any) -> Optional[Any]:
    """The single sub-jaxpr an eqn can be inlined through, or None. Opaque
    control flow never inlines; neither does anything carrying several
    jaxprs (cond branches) or a jaxpr whose arity disagrees with the eqn
    (scan's carry/xs split)."""
    if eqn.primitive.name in _OPAQUE_PRIMS:
        return None
    subs = [s for v in eqn.params.values() for s in _subjaxprs(v)]
    if len(subs) != 1:
        return None
    sub = subs[0]
    inner = getattr(sub, "jaxpr", None)  # ClosedJaxpr exposes .eqns too
    if inner is not None and hasattr(inner, "eqns"):
        sub = inner
    if len(sub.invars) != len(eqn.invars) or len(sub.outvars) != len(eqn.outvars):
        return None
    return sub


def build_graph(closed_jaxpr: Any) -> DataflowGraph:
    """Flatten a (Closed)Jaxpr into a DataflowGraph. Eager, order-preserving
    ref resolution makes it safe to inline the SAME sub-jaxpr object at two
    call sites (jit caches share jaxprs): each inline re-binds the sub's
    vars and emits its own node copies before any later binding clobbers
    the env."""
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)
    nodes: List[FlatEqn] = []
    donations: List[Donation] = []
    env: Dict[Any, Ref] = {}

    def new_node(
        prim: str, eqn: Any, in_refs: Tuple[Ref, ...],
        out_avals: Tuple[Any, ...] = (),
    ) -> FlatEqn:
        fe = FlatEqn(len(nodes), prim, eqn, in_refs, out_avals)
        nodes.append(fe)
        return fe

    def source(var: Any, kind: str) -> None:
        aval = getattr(var, "aval", None)
        env[var] = (
            new_node(f"source:{kind}", None, (), (aval,) if aval is not None else ()).idx,
            0,
        )

    for v in jaxpr.constvars:
        source(v, "const")
    for v in jaxpr.invars:
        source(v, "invar")

    def ref_of(v: Any) -> Ref:
        if _is_literal(v):
            return _lit_ref(v)
        r = env.get(v)
        if r is None:  # defensively bind stray free vars as sources
            source(v, "free")
            r = env[v]
        return r

    def emit(j: Any) -> None:
        for eqn in j.eqns:
            in_refs = tuple(ref_of(v) for v in eqn.invars)
            sub = _inline_target(eqn)
            if sub is None:
                fe = new_node(
                    eqn.primitive.name, eqn, in_refs,
                    tuple(getattr(ov, "aval", None) for ov in eqn.outvars),
                )
                for pos, ov in enumerate(eqn.outvars):
                    env[ov] = (fe.idx, pos)
                continue
            for sv, r in zip(sub.invars, in_refs):
                env[sv] = r
            for cv in sub.constvars:
                source(cv, "const")
            emit(sub)
            out_refs = [ref_of(ov) for ov in sub.outvars]
            for ov, r in zip(eqn.outvars, out_refs):
                env[ov] = r
            don = eqn.params.get("donated_invars")
            if don is not None and any(don):
                donations.append(
                    Donation(
                        donated=[
                            (i, in_refs[i], eqn.invars[i].aval)
                            for i, d in enumerate(don)
                            if d and not _is_literal(eqn.invars[i])
                        ],
                        out_refs=[
                            (r, ov.aval) for r, ov in zip(out_refs, eqn.outvars)
                        ],
                    )
                )

    emit(jaxpr)
    out_refs = tuple(ref_of(ov) for ov in jaxpr.outvars)

    anc = [0] * len(nodes)
    for fe in nodes:
        a = 0
        for r in fe.in_refs:
            if r[0] != "lit":
                i = r[0]
                a |= anc[i] | (1 << i)
        anc[fe.idx] = a
    return DataflowGraph(
        nodes=nodes, donations=donations, anc=anc, out_refs=out_refs
    )


# ---------------------------------------------------------------------- #
# jx-collective-schedule
# ---------------------------------------------------------------------- #


def rule_collective_schedule(jaxpr: Any, ctx: AuditContext) -> List[Violation]:
    """No collective may sit inside a ``cond``/``while`` sub-jaxpr: under
    SPMD, a data-dependent predicate can diverge across workers, leaving
    some waiting in a collective the rest never enter — deadlock. ``scan``
    bodies are fine (static trip count, every worker runs every iteration;
    the ring decode's ppermute-in-fori_loop lowers there). Always armed."""
    bad: List[str] = []

    def walk(j: Any, under: Optional[str]) -> None:
        j = getattr(j, "jaxpr", j)
        for eqn in j.eqns:
            name = eqn.primitive.name
            if under is not None and name in COLLECTIVE_PRIMS:
                bad.append(f"{name} under {under}")
            nested = under if under is not None else (
                name if name in ("cond", "while") else None
            )
            for v in eqn.params.values():
                for sub in _subjaxprs(v):
                    walk(sub, nested)

    walk(jaxpr, None)
    if not bad:
        return []
    return [
        Violation(
            R_COLLECTIVE_SCHEDULE,
            ctx.label,
            f"{len(bad)} collective(s) nested under data-dependent control "
            f"flow (first: {bad[0]}) — SPMD workers could diverge on whether "
            "they enter the collective, deadlocking the mesh",
        )
    ]


# ---------------------------------------------------------------------- #
# jx-token-dominance
# ---------------------------------------------------------------------- #


def rule_token_dominance(jaxpr: Any, ctx: AuditContext) -> List[Violation]:
    """The streaming exchange brackets every bucket's dispatch between an
    entry and an exit ``optimization_barrier`` threaded on one token chain
    (comm_stream.py). On the trace that means: exactly 2*B barriers, the
    barriers form a dependency chain in emission order, and every
    all_gather both descends from a barrier and feeds one — the structural
    form of 'encode -> all_gather -> decode is ordered per bucket and
    buckets are ordered against each other'."""
    if ctx.expect_stream_buckets is None:
        return []
    g = build_graph(jaxpr)
    barriers = g.by_prim("optimization_barrier")
    gathers = g.by_prim("all_gather")
    probs: List[str] = []
    want = 2 * ctx.expect_stream_buckets
    if len(barriers) != want:
        probs.append(
            f"{len(barriers)} optimization_barrier eqn(s); the token chain "
            f"contracts {want} (2 per bucket x {ctx.expect_stream_buckets})"
        )
    barrier_mask = 0
    for b in barriers:
        barrier_mask |= 1 << b.idx
    for fe in gathers:
        if not (g.anc[fe.idx] & barrier_mask):
            probs.append(f"all_gather@{fe.idx} has no barrier ancestor")
        if not any(g.is_ancestor(fe.idx, b.idx) for b in barriers):
            probs.append(f"all_gather@{fe.idx} feeds no barrier")
    for a, b in zip(barriers, barriers[1:]):
        if not g.is_ancestor(a.idx, b.idx):
            probs.append(
                f"barrier@{a.idx} is not an ancestor of barrier@{b.idx} — "
                "token chain broken"
            )
    if not probs:
        return []
    return [
        Violation(
            R_TOKEN_DOMINANCE,
            ctx.label,
            f"{len(probs)} token-chain defect(s) (first: {probs[0]})",
        )
    ]


# ---------------------------------------------------------------------- #
# jx-donation-soundness
# ---------------------------------------------------------------------- #


def _aval_eq(a: Any, b: Any) -> bool:
    return (
        tuple(getattr(a, "shape", ())) == tuple(getattr(b, "shape", ()))
        and str(getattr(a, "dtype", "?")) == str(getattr(b, "dtype", "?"))
    )


def rule_donation_soundness(jaxpr: Any, ctx: AuditContext) -> List[Violation]:
    """XLA reuses a donated input's buffer for an output of the same
    shape/dtype; any eqn still reading the donated value after that output
    is defined reads freed (rewritten) memory. The jaxpr does not record
    which output aliases which donated input, so mirror XLA's assignment
    greedily: each donated invar claims the first same-aval output not yet
    claimed, and every direct read of the donated ref at a node later than
    the alias's defining node is flagged. Armed automatically whenever the
    trace carries a donating call."""
    g = build_graph(jaxpr)
    if not g.donations:
        return []
    probs: List[str] = []
    for don in g.donations:
        claimed: set = set()
        for _pos, ref, aval in don.donated:
            if ref[0] == "lit":
                continue
            alias: Optional[Ref] = None
            for j, (oref, oaval) in enumerate(don.out_refs):
                if j not in claimed and _aval_eq(aval, oaval):
                    claimed.add(j)
                    alias = oref
                    break
            if alias is None or alias[0] == "lit":
                continue  # nothing aliased this buffer — no constraint
            def_idx = alias[0]
            for fe in g.nodes:
                if fe.idx > def_idx and ref in fe.in_refs:
                    probs.append(
                        f"node {fe.idx} ({fe.prim}) reads the donated buffer "
                        f"(source node {ref[0]}) after its alias is defined "
                        f"at node {def_idx} ({g.nodes[def_idx].prim})"
                    )
                    break
    if not probs:
        return []
    return [
        Violation(
            R_DONATION,
            ctx.label,
            f"{len(probs)} read(s) of a donated buffer after its aliased "
            f"output is live (first: {probs[0]})",
        )
    ]


# ---------------------------------------------------------------------- #
# jx-key-lineage
# ---------------------------------------------------------------------- #

# ops that forward a key value unchanged — the signature rides through
_KEY_PASS_THROUGH = (
    "random_wrap",
    "random_unwrap",
    "convert_element_type",
    "copy",
    "device_put",
    "squeeze",
    "reshape",
)

# ops that pick an element out of a batch of keys (jax.random.split lowers
# to split -> [unwrap ->] slice -> squeeze [-> wrap]): the signature rides
# through but is extended with a pick descriptor, so distinct slices of one
# split stay distinct draws while two identical slices still count as reuse
_KEY_PICK = ("slice", "dynamic_slice", "gather")


def _is_key_aval(aval: Any) -> bool:
    return str(getattr(aval, "dtype", "")).startswith("key<")


def rule_key_lineage(jaxpr: Any, ctx: AuditContext) -> List[Violation]:
    """Every stochastic draw (``random_bits``) must consume a key whose
    lineage passes through at least one ``fold_in``, and no two draws may
    share the same fold signature — the per-worker/per-tensor/per-step key
    discipline (sparse.per_tensor_key, comm's worker fold) checked on the
    trace. A signature is the chain of fold descriptors (literal value, or
    the producing node of a traced operand like axis_index) accumulated
    from the key's origin; it deliberately ignores intermediate
    wrap/unwrap hops, which every jax.random call inserts. Armed per-trace
    (ctx.require_key_lineage): codec unit audits legitimately pass raw
    unfolded keys."""
    if not ctx.require_key_lineage:
        return []
    g = build_graph(jaxpr)
    sigs: Dict[Ref, Tuple[tuple, bool]] = {}
    draws: Dict[tuple, int] = {}
    unfolded: List[int] = []
    reused: List[str] = []

    def sig_of(ref: Ref) -> Tuple[tuple, bool]:
        got = sigs.get(ref)
        if got is not None:
            return got
        return ((("src", ref),), False)

    for fe in g.nodes:
        name = fe.prim
        if name == "random_seed":
            if fe.in_refs and fe.in_refs[0][0] == "lit":
                # a literal-seeded key is a trace-constant stream — equal on
                # every worker and every step, so fold discipline is moot;
                # keyed by the literal so two PRNGKey(42) streams collide in
                # reuse detection
                sigs[(fe.idx, 0)] = ((("seed-const", fe.in_refs[0][1]),), True)
            else:
                sigs[(fe.idx, 0)] = ((("seed", fe.idx),), False)
        elif name == "random_fold_in":
            parent, _folded = sig_of(fe.in_refs[0])
            sigs[(fe.idx, 0)] = (parent + (("fold", fe.in_refs[1]),), True)
        elif name == "random_bits":
            sig, folded = sig_of(fe.in_refs[0])
            if not folded:
                unfolded.append(fe.idx)
            prev = draws.get(sig)
            if prev is None:
                draws[sig] = fe.idx
            else:
                reused.append(f"draws @{prev} and @{fe.idx}")
        elif name in _KEY_PASS_THROUGH:
            if fe.in_refs:
                s = sigs.get(fe.in_refs[0])
                if s is not None and fe.eqn is not None:
                    for pos in range(len(fe.eqn.outvars)):
                        sigs[(fe.idx, pos)] = s
        elif name in _KEY_PICK:
            if fe.in_refs:
                s = sigs.get(fe.in_refs[0])
                if s is not None and fe.eqn is not None:
                    parent, folded = s
                    try:
                        static = repr(sorted(fe.eqn.params.items()))
                    except Exception:
                        static = name
                    pick = ("pick", name, static, tuple(fe.in_refs[1:]))
                    for pos in range(len(fe.eqn.outvars)):
                        sigs[(fe.idx, pos)] = (parent + (pick,), folded)
        elif fe.eqn is not None:
            # any other producer of a key-typed value (split, ...) derives
            # fresh distinct keys: give each output a unique signature that
            # inherits the folded flag
            folded_in = any(
                sigs.get(r, ((), False))[1] for r in fe.in_refs if r[0] != "lit"
            )
            for pos, ov in enumerate(fe.eqn.outvars):
                if _is_key_aval(getattr(ov, "aval", None)):
                    sigs[(fe.idx, pos)] = (
                        (("op", name, fe.idx, pos),),
                        folded_in,
                    )
    probs: List[str] = []
    if unfolded:
        probs.append(
            f"{len(unfolded)} draw(s) from a key that never passed through "
            f"fold_in (first random_bits @{unfolded[0]})"
        )
    if reused:
        probs.append(
            f"{len(reused)} pair(s) of draws share one fold signature "
            f"(first: {reused[0]})"
        )
    if not probs:
        return []
    return [Violation(R_KEY_LINEAGE, ctx.label, "; ".join(probs))]


DATAFLOW_RULES = (
    rule_collective_schedule,
    rule_token_dominance,
    rule_donation_soundness,
    rule_key_lineage,
)
