"""Repo-specific AST lint — the source-level half of the analysis gate.

Five rules, each pinned to the scope where the hazard is real:

- ``ast-compat-route`` (repo-wide): `shard_map` / `pcast` must be imported
  from `deepreduce_tpu.utils.compat`, never from `jax.experimental.*`
  directly. The shim is what keeps the tree collecting across the jax
  versions we straddle; one direct import reintroduces the 0.4.37
  collection failure the shim exists to absorb.
- ``ast-host-entropy`` (traced modules): no `np.random.*`, no global
  `random.*` seeding, no `time.time()` in code that runs under trace.
  Host entropy inside a traced function is baked in at trace time — the
  program silently stops being a function of its inputs.
- ``ast-traced-branch`` (codec modules): no Python `if`/`while` whose test
  is a `jnp.*`/`jax.lax.*`/`jax.numpy.*` call. Under trace that raises a
  TracerBoolConversionError at best; at worst (concrete sub-values) it
  bakes a data-dependent branch into what must be a static program.
- ``ast-span-outside-host`` (codecs/): no `telemetry.span`/`spans.span`
  and no `DumpLogger` construction inside codec modules. Spans are
  host-side context managers (wall clock + profiler annotation); a codec
  body is traced once and replayed, so a span there measures trace time
  and then silently never fires again — instrument the communicator and
  driver layers instead (comm.py, train.py, bench drivers).
- ``ast-mask-host-branch`` (traced modules + train/fedavg): no Python
  `if`/`while` whose test reads a participation-mask value (`mask`,
  `row_weights`, ...). A host branch on a mask would bake one trace's
  liveness pattern into the compiled step — the mask is per-step traced
  data and must flow through `jnp.where`/arithmetic. The one allowed host
  branch is the `is (not) None` presence gate, which is exactly the
  Python-level zero-cost-off switch.

Pure stdlib `ast`; no jax import, so this pass runs anywhere in
milliseconds.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional

from deepreduce_tpu.analysis.rules import Violation

R_AST_COMPAT = "ast-compat-route"
R_AST_ENTROPY = "ast-host-entropy"
R_AST_BRANCH = "ast-traced-branch"
R_AST_SPAN = "ast-span-outside-host"
R_AST_MASK = "ast-mask-host-branch"

# the one module allowed to touch jax.experimental.shard_map directly
COMPAT_MODULE = "deepreduce_tpu/utils/compat.py"

# modules whose function bodies execute under jax trace (host-side tooling
# like tracking.py / bench drivers is deliberately NOT here)
TRACED_MODULES = (
    "deepreduce_tpu/codecs/",
    "deepreduce_tpu/sparse.py",
    "deepreduce_tpu/comm.py",
    "deepreduce_tpu/comm_bucket.py",
    "deepreduce_tpu/comm_ring.py",
    "deepreduce_tpu/memory.py",
    "deepreduce_tpu/qar.py",
    "deepreduce_tpu/sparse_rs.py",
    "deepreduce_tpu/wrappers.py",
    "deepreduce_tpu/resilience/chaos.py",
    "deepreduce_tpu/resilience/faults.py",
    "deepreduce_tpu/parallel/",
    "deepreduce_tpu/fedsim/",
)

# scope of the mask-host-branch rule: every traced module plus the two
# drivers that thread the participation mask through their jitted steps
MASK_SCOPED_MODULES = TRACED_MODULES + (
    "deepreduce_tpu/train.py",
    "deepreduce_tpu/fedavg.py",
)

# identifiers the mask-host-branch rule treats as participation-mask values
_MASK_NAMES = ("mask", "masks", "participation", "row_weights")

# modules where a Python branch on an array value is always a bug
CODEC_MODULES = (
    "deepreduce_tpu/codecs/",
    "deepreduce_tpu/sparse.py",
    "deepreduce_tpu/wrappers.py",
)

# modules where host-side telemetry (spans, dump loggers) is banned: codec
# bodies are traced once and replayed — a span there is a silent lie
SPAN_BANNED_MODULES = ("deepreduce_tpu/codecs/",)

_SPAN_HEADS = ("telemetry", "spans")

_ENTROPY_CHAINS = (
    ("time", "time"),
    ("np", "random"),
    ("numpy", "random"),
    ("random", "seed"),
    ("random", "random"),
    ("random", "randint"),
    ("random", "uniform"),
    ("random", "choice"),
    ("random", "shuffle"),
)

_TRACED_CALL_HEADS = ("jnp", "lax")


def _attr_chain(node: ast.AST) -> List[str]:
    """`np.random.seed` -> ["np", "random", "seed"]; [] if not a pure chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return []


def _in_scope(relpath: str, scopes) -> bool:
    return any(relpath == s or relpath.startswith(s) for s in scopes)


def _shard_map_import_violations(tree: ast.AST, relpath: str) -> List[Violation]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            names = [a.name for a in node.names]
            bad = mod.startswith("jax.experimental") and (
                "shard_map" in mod or "shard_map" in names or "pcast" in names
            )
            if bad:
                out.append(
                    Violation(
                        R_AST_COMPAT,
                        f"{relpath}:{node.lineno}",
                        f"direct `from {mod} import {', '.join(names)}` — route "
                        "shard_map/pcast through deepreduce_tpu.utils.compat",
                    )
                )
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith("jax.experimental.shard_map"):
                    out.append(
                        Violation(
                            R_AST_COMPAT,
                            f"{relpath}:{node.lineno}",
                            f"direct `import {a.name}` — route shard_map through "
                            "deepreduce_tpu.utils.compat",
                        )
                    )
    return out


def _entropy_violations(tree: ast.AST, relpath: str) -> List[Violation]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if len(chain) < 2:
            continue
        for head, second in _ENTROPY_CHAINS:
            if chain[0] == head and chain[1] == second:
                out.append(
                    Violation(
                        R_AST_ENTROPY,
                        f"{relpath}:{node.lineno}",
                        f"host entropy `{'.'.join(chain)}(...)` in traced module — "
                        "thread a jax PRNG key (or hoist to untraced setup)",
                    )
                )
                break
    return out


def _has_traced_call(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func)
            if chain and chain[0] in _TRACED_CALL_HEADS:
                return True
            if len(chain) >= 2 and chain[0] == "jax" and chain[1] in ("numpy", "lax"):
                return True
    return False


def _traced_branch_violations(tree: ast.AST, relpath: str) -> List[Violation]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.If, ast.While)) and _has_traced_call(node.test):
            kind = "if" if isinstance(node, ast.If) else "while"
            out.append(
                Violation(
                    R_AST_BRANCH,
                    f"{relpath}:{node.lineno}",
                    f"Python `{kind}` on a traced-array expression in a codec "
                    "module — use jnp.where / lax.cond / lax.select",
                )
            )
    return out


def _span_violations(tree: ast.AST, relpath: str) -> List[Violation]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _attr_chain(node.func)
        if not chain:
            continue
        span_call = chain[-1] == "span" and (
            len(chain) == 1 or chain[0] in _SPAN_HEADS
        )
        if span_call or "DumpLogger" in chain:
            out.append(
                Violation(
                    R_AST_SPAN,
                    f"{relpath}:{node.lineno}",
                    f"host-side telemetry `{'.'.join(chain)}(...)` inside a "
                    "codec module — codec bodies are traced (a span here "
                    "fires once at trace time, then never again); "
                    "instrument the communicator/driver layer instead",
                )
            )
    return out


def _mentions_mask(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in _MASK_NAMES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _MASK_NAMES:
            return True
    return False


def _branches_on_mask_value(expr: ast.AST) -> bool:
    """True when the test reads a mask VALUE (not just its presence).
    and/or/not decompose into their operands; an identity comparison
    (`is` / `is not` — the `mask is not None` presence gate) never reads
    the value; any other subexpression mentioning a mask name does."""
    if isinstance(expr, ast.BoolOp):
        return any(_branches_on_mask_value(v) for v in expr.values)
    if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Not):
        return _branches_on_mask_value(expr.operand)
    if isinstance(expr, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops
    ):
        return False
    return _mentions_mask(expr)


def _mask_branch_violations(tree: ast.AST, relpath: str) -> List[Violation]:
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.If, ast.While)):
            continue
        if _branches_on_mask_value(node.test):
            kind = "if" if isinstance(node, ast.If) else "while"
            out.append(
                Violation(
                    R_AST_MASK,
                    f"{relpath}:{node.lineno}",
                    f"Python `{kind}` on a participation-mask value — the "
                    "mask is per-step traced data; branch with jnp.where / "
                    "arithmetic (only `is None` presence gates may branch)",
                )
            )
    return out


def lint_source(src: str, relpath: str) -> List[Violation]:
    """Lint one module's source. `relpath` is repo-relative with forward
    slashes; it selects which rule scopes apply."""
    tree = ast.parse(src, filename=relpath)
    out: List[Violation] = []
    if relpath != COMPAT_MODULE:
        out.extend(_shard_map_import_violations(tree, relpath))
    if _in_scope(relpath, TRACED_MODULES):
        out.extend(_entropy_violations(tree, relpath))
    if _in_scope(relpath, CODEC_MODULES):
        out.extend(_traced_branch_violations(tree, relpath))
    if _in_scope(relpath, SPAN_BANNED_MODULES):
        out.extend(_span_violations(tree, relpath))
    if _in_scope(relpath, MASK_SCOPED_MODULES):
        out.extend(_mask_branch_violations(tree, relpath))
    return out


def lint_file(path: Path, root: Path) -> List[Violation]:
    relpath = path.relative_to(root).as_posix()
    return lint_source(path.read_text(), relpath)


def lint_repo(root: Optional[Path] = None) -> List[Violation]:
    """Lint every python module under deepreduce_tpu/, tests/, and
    benchmarks/ (compat-route is repo-wide; the other rules scope
    themselves)."""
    if root is None:
        root = Path(__file__).resolve().parents[2]
    out: List[Violation] = []
    for sub in ("deepreduce_tpu", "tests", "benchmarks"):
        base = root / sub
        if not base.is_dir():
            continue
        for path in sorted(base.rglob("*.py")):
            out.extend(lint_file(path, root))
    return out
