"""Composition-lattice auditor: machine-checked legality over every
flagship feature combination.

The fixed audit list (jaxpr_audit.audit_specs) pins ~66 hand-chosen
programs. This module probes the FULL cross-product of the flagship
feature axes — communicator x decode_strategy x bucket_bytes x
stream_exchange x rs_mode x hier(+ici/dcn legs) x resilience x ctrl x
fed — and partitions it into LEGAL and REJECTED:

- a REJECTED cell records WHERE it was refused (config `__post_init__`
  vs exchanger construction) and the machine-readable `reason_code` the
  raising `ConfigError` carries, so the exclusion matrix is data, not
  prose scattered across error messages;
- a LEGAL cell's step function is traced to jaxpr on the appropriate
  AbstractMesh (flat 8-way, hierarchical 2x4, the streaming grad hook,
  or the federated round) and run through the FULL rule set — the
  linear-walk rules plus the dataflow rules (collective schedule, token
  dominance, donation soundness, key lineage) — with the per-axis
  collective inventory and wire bytes recorded per cell.

The result is a deterministic MATRIX.json. `python -m
deepreduce_tpu.analysis matrix` regenerates it and exits 1 on any rule
violation OR any legality/trace drift from the committed baseline: the
exclusion matrix can only shrink deliberately, and the planned
composability refactor (ROADMAP) gets a cell-by-cell equivalence oracle.

Cells sharing one effective traced program (the ctrl axis is host-side
by the audited jx-ctrl-ladder off-identity contract, so ctrl knobs are
stripped from the trace fingerprint) share one memoized trace — the
lattice has 15k cells but only tens of distinct programs.
"""

from __future__ import annotations

import hashlib
import itertools
import json
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepreduce_tpu.config import ConfigError, DeepReduceConfig, reason_code_of

SCHEMA = "deepreduce_tpu/analysis-report/v1"

# ---------------------------------------------------------------------- #
# the lattice axes
# ---------------------------------------------------------------------- #

# (axis name, value labels) in lexicographic cell order. Every label maps
# to concrete config kwargs in `cell_kwargs`; the cross-product is the
# probed lattice (4*3*2*2*6*4*2*2*2*2*2*2 = 73728 cells). New axes are
# appended LAST: product order then expands every pre-existing cell into
# an adjacent (off, on) pair with the off plane first, so the old lattice
# survives as the population=off plane and re-baselining can be diffed
# cell-by-cell.
AXES: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("communicator", ("allgather", "allreduce", "qar", "sparse_rs")),
    ("decode", ("loop", "vmap", "ring")),
    ("buckets", ("off", "on")),
    ("stream", ("off", "on")),
    ("rs_mode", ("sparse", "adaptive", "quantized", "sketch", "oktopk", "auto")),
    ("hier", ("off", "dense", "qar_ici", "auto_dcn")),
    ("resilience", ("off", "on")),
    ("ctrl", ("off", "on")),
    ("fed", ("off", "on")),
    ("fed_async", ("off", "on")),
    ("fed_mt", ("off", "on")),
    ("population", ("off", "on")),
)

# ctrl + telemetry are host-side only (the audited jx-ctrl-ladder
# off-identity contract; re-verified empirically — identical jaxpr hash
# with them on/off): these kwargs never reach the traced program, so they
# are stripped from the trace fingerprint and memoized cells share a trace
_CTRL_KWARGS = ("ctrl", "ctrl_ladder", "telemetry")


def iter_cells():
    """Yield every cell as {axis: label}, in lexicographic product order —
    the order `cells` is serialized in."""
    names = [n for n, _ in AXES]
    for combo in itertools.product(*(vals for _, vals in AXES)):
        yield dict(zip(names, combo))


def n_cells() -> int:
    out = 1
    for _, vals in AXES:
        out *= len(vals)
    return out


def cell_kwargs(cell: Dict[str, str]) -> Dict[str, Any]:
    """Concrete DeepReduceConfig kwargs for one cell. Pure and total: every
    cell maps to kwargs; whether they survive `__post_init__` is exactly
    what the probe measures."""
    from deepreduce_tpu.analysis.jaxpr_audit import (
        _BUCKET_BYTES,
        _CTRL_LADDER,
        _FLAGSHIP,
    )

    comm = cell["communicator"]
    if comm == "allgather":
        kw: Dict[str, Any] = dict(memory="residual", **_FLAGSHIP)
    elif comm == "allreduce":
        kw = dict(
            communicator="allreduce", compressor="none", memory="none",
            deepreduce=None,
        )
    elif comm == "qar":
        kw = dict(
            communicator="qar", compressor="none", memory="none",
            deepreduce=None,
        )
    else:
        kw = dict(
            communicator="sparse_rs", compressor="topk", memory="none",
            deepreduce=None, compress_ratio=0.02,
        )
    kw["decode_strategy"] = cell["decode"]
    if cell["decode"] == "vmap":
        kw["decode_batch"] = 4
    if cell["buckets"] == "on":
        kw["bucket_bytes"] = _BUCKET_BYTES
    if cell["stream"] == "on":
        kw["stream_exchange"] = True
    if cell["rs_mode"] != "sparse":
        kw["rs_mode"] = cell["rs_mode"]
    if cell["hier"] != "off":
        kw["hier"] = True
        if cell["hier"] == "qar_ici":
            kw["hier_ici"] = "qar"
        elif cell["hier"] == "auto_dcn":
            kw["hier_dcn"] = "auto"
    if cell["resilience"] == "on":
        if comm == "sparse_rs":
            # the reduce-scatter routes thread the live mask through shard
            # re-ownership but have no fused PayloadLayout — checksum/chaos
            # are allgather-wire knobs and would (correctly) be refused by
            # checksum-needs-fused-allgather, which is not this axis's fact
            kw.update(resilience=True)
        else:
            kw.update(
                resilience=True, payload_checksum=True, chaos_corrupt_rate=0.2
            )
    if cell["ctrl"] == "on":
        kw.update(ctrl=True, telemetry=True, ctrl_ladder=_CTRL_LADDER)
    if cell["fed"] == "on":
        kw.update(
            fed=True, fed_num_clients=64, fed_clients_per_round=16,
            fed_local_steps=2,
        )
    if cell["fed_async"] == "on":
        # without fed=on this cell is ILLEGAL by construction
        # (fed-async-needs-fed) — the probe measures exactly that
        kw.update(
            fed_async=True, fed_async_k=8, fed_async_alpha=0.5,
            fed_async_latency="0.6,0.3,0.1",
        )
    if cell["fed_mt"] == "on":
        # without fed=on this cell is ILLEGAL by construction
        # (fed-mt-needs-fed) — the probe measures exactly that. With
        # fed=on the T=2 fleet rides the same jitted tick (sync AND
        # async planes), still exactly one psum.
        kw.update(fed_tenants=2)
    if cell["population"] == "on":
        # without fed=on this cell is ILLEGAL by construction
        # (pop-needs-fed); with fed_mt=on it is ILLEGAL too (pop-vs-mt) —
        # the probe measures exactly that. Two classes with non-IID skew
        # staged and NO per-class latency rows, so the async plane keeps
        # the 4*(n+7+D+K) law with no transmit-histogram term.
        kw.update(
            pop_spec='{"version": 1, "num_labels": 8, "classes": ['
            '{"name": "bulk", "weight": 3.0, "data_alpha": 0.5}, '
            '{"name": "skewed", "weight": 1.0, "data_alpha": 0.1, '
            '"data_bias": 4.0}]}'
        )
    return kw


def trace_fingerprint(kw: Dict[str, Any], harness: str) -> str:
    """Stable fingerprint of the traced program a cell resolves to: the
    harness name plus every config kwarg that can reach the trace (ctrl
    knobs stripped — host-side by contract)."""
    eff = {k: v for k, v in sorted(kw.items()) if k not in _CTRL_KWARGS}
    blob = json.dumps({"harness": harness, "kw": eff}, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()


# ---------------------------------------------------------------------- #
# per-cell probing
# ---------------------------------------------------------------------- #


def _harness_name(cell: Dict[str, str]) -> str:
    if cell["fed"] == "on":
        return "fed"
    if cell["stream"] == "on":
        return "stream"
    if cell["hier"] != "off":
        return "hier"
    return "flat"


def _wire_mode(cfg: DeepReduceConfig) -> Optional[str]:
    """Which wire-accounting contract a config's trace can be pinned to —
    mirrors the fixed audits' arming."""
    if cfg.communicator == "sparse_rs":
        return "collective"
    if cfg.communicator == "allgather" and cfg.fused:
        return "ring" if cfg.decode_strategy == "ring" else "allgather"
    return None


def _trace_flat(label: str, cfg: DeepReduceConfig, cell: Dict[str, str]):
    from deepreduce_tpu.analysis import jaxpr_audit as ja

    leaves = ja._BUCKET_LEAVES if cfg.bucket_bytes is not None else None
    (rec,) = ja.audit_exchange(
        label, cfg, leaves=leaves, wire_mode=_wire_mode(cfg),
        with_mask=cell["resilience"] == "on",
    )
    return rec


def _trace_hier(label: str, cfg: DeepReduceConfig, cell: Dict[str, str]):
    from deepreduce_tpu.analysis import jaxpr_audit as ja

    leaves = ja._BUCKET_LEAVES if cfg.bucket_bytes is not None else None
    (rec,) = ja.audit_hier_exchange(
        label, cfg, leaves=leaves, wire_mode=_wire_mode(cfg),
    )
    return rec


def _trace_stream(label: str, cfg: DeepReduceConfig, cell: Dict[str, str]):
    """The streaming grad+exchange harness, parametrized over cfg (the
    fixed audit hardcodes the flagship config): trace
    StreamingExchange.value_and_grad_exchange over the bucketed census
    with the token-dominance rule armed at the actual bucket count.

    On the hier != off plane the StreamingExchange wraps a
    HierarchicalExchanger on the (dcn, ici) mesh: the per-axis inventory
    pins each bucket's ICI slice-mean psum to ici and its compressed
    gather to dcn, wire accounting runs dcn-filtered against the DCN-only
    payload_bytes(), and token dominance still contracts exactly two
    barriers per bucket — the ici psum rides inside the bracket."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from deepreduce_tpu.analysis import jaxpr_audit as ja
    from deepreduce_tpu.analysis.rules import AuditContext
    from deepreduce_tpu.comm import GradientExchanger
    from deepreduce_tpu.comm_stream import StreamingExchange

    tmap = jax.tree_util.tree_map
    hier = cell["hier"] != "off"
    grads_like = {
        n: ja._sds((int(sz),)) for n, sz in ja._BUCKET_LEAVES.items()
    }
    if hier:
        from deepreduce_tpu.parallel.hierarchical import HierarchicalExchanger

        n_slices, per_slice = 2, 4
        mesh = ja.audit_hier_mesh(n_slices, per_slice)
        axes = ("dcn", "ici")
        w = n_slices * per_slice
        ex = HierarchicalExchanger(
            grads_like, cfg, num_slices=n_slices, per_slice=per_slice
        )
        stream = StreamingExchange(ex)
        n_buckets = len(ex.exchanger._bucketed.codecs)
        num_workers = n_slices
        spec_p, wire_axis = P(axes), "dcn"
        expect_by_axis = {
            "ici": {"psum": n_buckets},
            "dcn": {"all_gather": n_buckets},
        }
    else:
        mesh = ja.audit_mesh()
        w = ja.NUM_WORKERS
        ex = GradientExchanger(
            grads_like, cfg, axis_name=ja.AXIS, num_workers=ja.NUM_WORKERS
        )
        stream = StreamingExchange(ex)
        n_buckets = len(ex._bucketed.codecs)
        num_workers = ja.NUM_WORKERS
        spec_p, wire_axis = P(ja.AXIS), None
        expect_by_axis = None
    pb = ex.payload_bytes(grads_like)
    g_w = tmap(lambda s: ja._sds((w,) + s.shape), grads_like)

    def loss_fn(params, batch_stats, batch):
        loss = sum(jnp.sum(p * batch[n]) for n, p in params.items())
        return loss, batch_stats

    def spmd(p, b_w, res, step):
        b0 = tmap(lambda x: x[0], b_w)
        res0 = tmap(lambda r: r[0], res)
        _, _, agg, new_res, _ = stream.value_and_grad_exchange(
            loss_fn, p, {}, b0, res0, step=step
        )
        new_res = tmap(lambda r: r[None], new_res)
        return tmap(lambda x: x[None], agg), new_res

    fn = ja._shard_map(
        spmd, mesh, (P(), spec_p, spec_p, P()), (spec_p, spec_p)
    )
    args = (grads_like, g_w, g_w, ja._STEP)
    ctx = AuditContext(
        label=label,
        wire_mode="allgather",
        expected_wire_bytes=pb,
        wire_axis=wire_axis,
        expect_collectives_by_axis=expect_by_axis,
        num_workers=num_workers,
        expect_stream_buckets=n_buckets,
        require_key_lineage=True,
    )
    return ja.trace_and_check(label, fn, args, ctx, payload_bytes=pb)


def _trace_fed(label: str, cfg: DeepReduceConfig, cell: Dict[str, str]):
    """The federated round harness, parametrized over cfg (the fixed audit
    hardcodes the flagship config): one jitted shard_map round over the
    client-sharded residual bank, wire accounting pinned to the single
    fused psum's 4*(param_elements + 6) B/worker — or, on the fed_async=on
    plane, the buffered ingest tick's 4*(param_elements + 7 + D) (the
    staleness-weight mass AND the D-level staleness histogram — the r23
    health plane's on-device tail counters — ride the same fused tuple;
    a deliberate re-pin from the r20 law 4*(n+7)).

    On the fed_mt=on plane the T=2 fleet runs through the one vmapped
    tick: still exactly one psum, operand bytes linear in T. vmap
    batches the param-leaf sums plus the tenant-varying tuple scalars
    (nlive/nfail, +wsum and the D histogram counters when async, +2 wire
    scalars when the checksum makes wire accounting data-dependent) and
    leaves the shape-static wire scalars unbatched.

    On the population=on plane (fed=on, fed_mt=off — pop-vs-mt fences the
    rest) the class-id vector rides as one extra i32[num_clients] operand
    sharded with the bank, and the exact per-class participation
    histogram adds K members to the fused tuple: 4*(n+6+K) sync,
    4*(n+7+D+K) async (the lattice spec stages no per-class latency rows,
    so the transmit-histogram term stays off — the fixed
    fedsim:population-latency audit pins that +D separately)."""
    import jax
    import jax.numpy as jnp
    import optax

    from deepreduce_tpu.analysis import jaxpr_audit as ja
    from deepreduce_tpu.analysis.rules import AuditContext
    from deepreduce_tpu.fedsim.sim import (
        AsyncBuffer,
        FedSim,
        synthetic_linear_problem,
    )

    tmap = jax.tree_util.tree_map
    fed = cfg.fed_config()
    params0, data_fn, loss_fn = synthetic_linear_problem(512, 4, fed.local_steps)
    fs = FedSim(
        loss_fn, cfg, fed, optax.sgd(0.1), data_fn, mesh=ja.audit_mesh(),
        axis=ja.AXIS,
    )
    params_sds = tmap(lambda p: ja._sds(p.shape, p.dtype), params0)
    if cfg.payload_checksum or cfg.chaos_corrupt_rate:
        fs.build_layout(params_sds)
    fn = fs.sharded_round_fn()
    bank_sds = tmap(
        lambda p: ja._sds((fed.num_clients,) + p.shape, p.dtype), params_sds
    )
    n_elems = sum(
        int(jnp.prod(jnp.array(p.shape))) if p.shape else 1
        for p in jax.tree_util.tree_leaves(params_sds)
    )
    T = int(getattr(cfg, "fed_tenants", 0) or 0)
    if T >= 1:
        data_dep_wire = bool(cfg.payload_checksum or cfg.chaos_corrupt_rate)
        # async batched members gain wsum + the D staleness-histogram
        # counters (r23 re-pin: +4*T*D B/worker)
        D_mt = len(fs.mt_latency[0]) if cfg.fed_async else 0
        s_batched = (
            (3 + D_mt if cfg.fed_async else 2) + (2 if data_dep_wire else 0)
        )
        s_static = 2 if data_dep_wire else 4
        pb = 4 * (T * (n_elems + s_batched) + s_static)
        stacked_sds = tmap(lambda p: ja._sds((T,) + p.shape, p.dtype), params_sds)
        buf_sds = alpha_sds = lat_sds = None
        if cfg.fed_async:
            D = len(fs.mt_latency[0])
            vec = lambda dt=jnp.float32: ja._sds((T,), dt)
            buf_sds = AsyncBuffer(
                delta_sum=stacked_sds,
                weight=vec(),
                count=vec(),
                k=vec(),
                version=vec(jnp.int32),
                hist=(
                    tmap(
                        lambda p: ja._sds((T, D) + p.shape, p.dtype),
                        params_sds,
                    )
                    if D > 1
                    else None
                ),
                stale_sum=vec(),
                stale_max=vec(),
                pending=vec(),
            )
            alpha_sds = vec()
            lat_sds = ja._sds((T, D), jnp.float32)
        args = (
            stacked_sds,
            stacked_sds,
            tmap(
                lambda p: ja._sds((T, fed.num_clients) + p.shape, p.dtype),
                params_sds,
            ),
            None,
            ja._sds((T,), jnp.int32),
            ja._sds((2,), jnp.uint32),
            buf_sds,
            ja._sds((T,), jnp.bool_),
            alpha_sds,
            lat_sds,
            None,
            ja._sds((), jnp.int32),
        )
        ctx = AuditContext(
            label=label,
            wire_mode="collective",
            expected_wire_bytes=pb,
            num_workers=ja.NUM_WORKERS,
            require_key_lineage=True,
        )
        return ja.trace_and_check(label, fn, args, ctx, payload_bytes=pb)
    # async adds wsum + the D staleness-histogram counters to the fused
    # tuple (r23 re-pin: the old law was n_elems + 7 when async); the
    # population plane adds its exact K-class participation histogram
    # (r25 re-pin: +4*K B/worker)
    K = fs.pop.num_classes if fs.pop is not None else 0
    pb = 4 * (
        n_elems + 6 + K
        + ((1 + len(fs.latency_probs)) if cfg.fed_async else 0)
    )
    args = (
        params_sds,
        params_sds,
        bank_sds,
        None,
        ja._STEP,
        ja._sds((2,), jnp.uint32),
    )
    if cfg.fed_async:
        D = len(fs.latency_probs)
        sc = lambda dt=jnp.float32: ja._sds((), dt)
        args = args + (
            AsyncBuffer(
                delta_sum=params_sds,
                weight=sc(),
                count=sc(),
                k=sc(),
                version=sc(jnp.int32),
                hist=(
                    tmap(lambda p: ja._sds((D,) + p.shape, p.dtype), params_sds)
                    if D > 1
                    else None
                ),
                stale_sum=sc(),
                stale_max=sc(),
                pending=sc(),
            ),
        )
    if fs.pop is not None:
        # class-id vector, i32[num_clients] sharded with the bank — one
        # extra operand, no extra collective
        args = args + (ja._sds((fed.num_clients,), jnp.int32),)
    ctx = AuditContext(
        label=label,
        wire_mode="collective",
        expected_wire_bytes=pb,
        num_workers=ja.NUM_WORKERS,
        require_key_lineage=True,
    )
    return ja.trace_and_check(label, fn, args, ctx, payload_bytes=pb)


_HARNESSES: Dict[str, Callable] = {
    "flat": _trace_flat,
    "hier": _trace_hier,
    "stream": _trace_stream,
    "fed": _trace_fed,
}


def probe_partition(cell: Dict[str, str]):
    """Config-stage probe only (no tracing): returns ("legal", cfg, kw) or
    ("rejected", stage, exc_name, reason_code). Cheap enough to run over
    the whole lattice in tests."""
    kw = cell_kwargs(cell)
    try:
        cfg = DeepReduceConfig(**kw)
    except ValueError as e:
        return ("rejected", "config", type(e).__name__, reason_code_of(e))
    return ("legal", cfg, kw)


def probe_cell(
    cell: Dict[str, str],
    memo: Dict[str, Tuple[str, Any]],
    stats: Optional[Dict[str, int]] = None,
):
    """Full probe of one cell: partition, then (for legal cells) build and
    trace through the cell's harness, memoized on the trace fingerprint.
    When `stats` is given, `cache_hits` counts legal cells answered from
    the fingerprint memo without tracing.

    Returns a cell entry dict plus (for legal cells) the (label, record)
    pair. Construction-stage ConfigError/ValueError becomes a 'build'
    rejection; anything raised during tracing propagates — a trace crash
    is a harness bug, not a legality fact."""
    part = probe_partition(cell)
    if part[0] == "rejected":
        _, stage, exc, code = part
        return (
            {"status": "rejected", "stage": stage, "exception": exc,
             "reason_code": code},
            None,
        )
    _, cfg, kw = part
    harness = _harness_name(cell)
    fp = trace_fingerprint(kw, harness)
    if fp in memo:
        if stats is not None:
            stats["cache_hits"] = stats.get("cache_hits", 0) + 1
        label, rec = memo[fp]
        return ({"status": "legal", "trace": label}, (label, rec))
    label = f"lat:{fp[:12]}"
    try:
        rec = _HARNESSES[harness](label, cfg, cell)
    except ConfigError as e:
        return (
            {"status": "rejected", "stage": "build",
             "exception": type(e).__name__, "reason_code": e.reason_code},
            None,
        )
    except ValueError as e:
        # a build-time refusal that never got a reason code: recorded as a
        # codeless rejection, which build_matrix turns into a violation —
        # the acceptance bar is that every rejection is machine-readable
        return (
            {"status": "rejected", "stage": "build",
             "exception": type(e).__name__, "reason_code": None},
            None,
        )
    memo[fp] = (label, rec)
    return ({"status": "legal", "trace": label}, (label, rec))


# ---------------------------------------------------------------------- #
# matrix build / serialize / compare
# ---------------------------------------------------------------------- #


def build_matrix(
    progress: Optional[Callable[[str], None]] = None,
    stats: Optional[Dict[str, int]] = None,
) -> Dict[str, Any]:
    """Probe every cell and assemble the MATRIX report: `entries` is the
    deduplicated outcome table (first-encounter order), `cells` maps each
    lattice cell (lexicographic order) to an entry index, `traces` holds
    one record per distinct traced program.

    `stats` (optional, caller-owned) is filled with the audit-cost view —
    cells probed and fingerprint-memo cache hits. It stays OUT of the
    report so MATRIX.json never carries run-cost noise."""
    memo: Dict[str, Tuple[str, Any]] = {}
    entries: List[Dict[str, Any]] = []
    entry_index: Dict[str, int] = {}
    cells: List[int] = []
    trace_meta: Dict[str, Dict[str, Any]] = {}
    violations: List[Dict[str, str]] = []
    codeless: List[str] = []
    done = 0
    for cell in iter_cells():
        entry, traced = probe_cell(cell, memo, stats)
        key = json.dumps(entry, sort_keys=True)
        if key not in entry_index:
            entry_index[key] = len(entries)
            entries.append(entry)
        cells.append(entry_index[key])
        if entry["status"] == "rejected" and entry["reason_code"] is None:
            codeless.append(_cell_slug(cell))
        if traced is not None:
            label, rec = traced
            if label not in trace_meta:
                meta = rec.to_dict()
                meta["config"] = {
                    k: v for k, v in sorted(cell_kwargs(cell).items())
                    if k not in _CTRL_KWARGS
                }
                meta["harness"] = _harness_name(cell)
                meta.pop("label", None)
                meta.pop("violations", None)
                trace_meta[label] = meta
                violations.extend(v.to_dict() for v in rec.violations)
        done += 1
        if progress is not None and done % 2048 == 0:
            progress(f"{done}/{n_cells()} cells probed, "
                     f"{len(trace_meta)} distinct traces")
    if stats is not None:
        stats["cells_probed"] = done
        stats["distinct_traces"] = len(trace_meta)
    for slug in codeless[:20]:
        violations.append(
            {
                "rule": "matrix-codeless-rejection",
                "where": slug,
                "detail": "REJECTED without a machine-readable reason_code — "
                "convert the raising ValueError to config.ConfigError",
            }
        )
    n_legal = sum(1 for i in cells if entries[i]["status"] == "legal")
    report = {
        "schema": SCHEMA,
        "axes": [[name, list(vals)] for name, vals in AXES],
        "entries": entries,
        "cells": cells,
        "traces": trace_meta,
        "violations": violations,
        "summary": {
            "cells": len(cells),
            "legal": n_legal,
            "rejected": len(cells) - n_legal,
            "distinct_traces": len(trace_meta),
            "reason_codes": sorted(
                {
                    e["reason_code"]
                    for e in entries
                    if e["status"] == "rejected" and e["reason_code"]
                }
            ),
            "violations": len(violations),
        },
    }
    return report


def _cell_slug(cell: Dict[str, str]) -> str:
    return "/".join(f"{n}={cell[n]}" for n, _ in AXES)


def write_matrix(report: Dict[str, Any], path: Path) -> None:
    """Deterministic writer: standard indented JSON with the (15k-int)
    `cells` list packed 64 per line so the committed file stays diffable
    and an order of magnitude smaller than naive indent."""
    obj = dict(report)
    cells = obj["cells"]
    obj["cells"] = "@CELLS@"
    txt = json.dumps(obj, indent=2, sort_keys=True)
    lines = []
    for i in range(0, len(cells), 64):
        chunk = ",".join(str(c) for c in cells[i : i + 64])
        lines.append("    " + chunk)
    cells_txt = "[\n" + ",\n".join(lines) + "\n  ]"
    path.write_text(txt.replace('"@CELLS@"', cells_txt) + "\n")


def load_report(path: Path, *, expect_schema: str = SCHEMA) -> Dict[str, Any]:
    """Load + schema-validate a committed report (ANALYSIS.json or
    MATRIX.json). A missing/mismatched schema tag fails loudly — never
    diff against a stale or foreign baseline."""
    try:
        report = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise ValueError(f"cannot load report {path}: {e}") from e
    got = report.get("schema")
    if got != expect_schema:
        raise ValueError(
            f"{path} carries schema {got!r}, expected {expect_schema!r} — "
            "stale or malformed baseline; regenerate it (matrix --update / "
            "make analyze)"
        )
    return report


def compare_matrix(
    baseline: Dict[str, Any], fresh: Dict[str, Any], *, limit: int = 25
) -> List[str]:
    """Cell-by-cell legality + trace-hash + peak-byte drift between a
    committed baseline and a fresh build. Any returned diff means the
    legality surface, a traced program, or a cell's memory envelope
    changed without a deliberate re-baseline."""
    diffs: List[str] = []
    if baseline.get("axes") != fresh.get("axes"):
        return ["axes changed — the lattice itself moved; re-baseline deliberately"]

    def resolved(report):
        entries = report["entries"]
        traces = report["traces"]
        for idx in report["cells"]:
            e = entries[idx]
            if e["status"] == "legal":
                t = traces[e["trace"]]
                yield ("legal", None, t["jaxpr_hash"], t.get("peak_bytes"))
            else:
                yield ("rejected", e.get("reason_code"), None, None)

    if len(baseline["cells"]) != len(fresh["cells"]):
        return [
            f"cell count changed: {len(baseline['cells'])} -> "
            f"{len(fresh['cells'])}"
        ]
    for cell, old, new in zip(iter_cells(), resolved(baseline), resolved(fresh)):
        if old == new:
            continue
        if len(diffs) >= limit:
            diffs.append("... (more diffs suppressed)")
            break
        if old[0] != new[0]:
            diffs.append(
                f"{_cell_slug(cell)}: legality changed {old[0]} -> {new[0]}"
            )
        elif old[0] == "rejected":
            diffs.append(
                f"{_cell_slug(cell)}: reason_code changed "
                f"{old[1]} -> {new[1]}"
            )
        elif old[2] != new[2]:
            diffs.append(
                f"{_cell_slug(cell)}: trace hash changed {old[2]} -> {new[2]}"
            )
        elif old[3] is not None:
            # same program hash, different priced peak: the liveness model
            # itself moved — a collective-budget drift on this legal cell
            diffs.append(
                f"{_cell_slug(cell)}: peak bytes changed {old[3]} -> {new[3]}"
            )
    return diffs


def matrix_reason_codes(report: Dict[str, Any]) -> set:
    """Every reason_code appearing in a matrix report."""
    return {
        e["reason_code"]
        for e in report["entries"]
        if e["status"] == "rejected" and e.get("reason_code")
    }
