"""Pluggable jaxpr invariant rules — the checkable form of ARCHITECTURE.md.

Every performance claim in this repro is a *structural* property of the
traced program: static payload shapes, a gather-free mod-blocked bloom
query, sorted/unique budget-scale gathers and scatters, one collective per
step on the fused path, no f64 anywhere near the hot loop, host callbacks
only in the explicitly-host codecs. Ok-Topk (arXiv:2201.07598) and SparCML
(arXiv:1802.08021) locate the whole win in the operator/collective
structure of the exchange — so these rules pin that structure down where
end-to-end timings cannot: at trace time, on any host, with no compile.

Each rule is a function ``rule(closed_jaxpr, ctx) -> list[Violation]`` that
emits AT MOST ONE aggregated violation per trace (counts ride in the
detail), so a negative fixture maps to exactly one finding with a distinct
rule id. `walk_eqns` recurses through every sub-jaxpr a primitive carries
(shard_map / pjit / scan / while / cond / custom_* / ...), so nothing hides
inside a loop body.
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
import re
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

# ---------------------------------------------------------------------- #
# rule ids — one per distinct invariant; tests assert on these exact ids
# ---------------------------------------------------------------------- #

R_F64 = "jx-f64"
R_DYNAMIC_SHAPE = "jx-dynamic-shape"
R_UNSORTED_BUDGET_GATHER = "jx-unsorted-budget-gather"
R_GATHER_IN_MOD_QUERY = "jx-gather-in-mod-query"
R_COLLECTIVE_COUNT = "jx-collective-count"
R_WIRE_ACCOUNTING = "jx-wire-accounting"
R_CALLBACK = "jx-callback"
R_CODEC_COUNT = "jx-codec-count"
R_RETRACE = "jx-retrace"  # emitted by the audit harness (two-trace hash)
# emitted by the audit harness (check_off_identical): the resilience-off
# step program must trace to a byte-identical jaxpr with every resilience
# seam (mask / chaos / checksum) stubbed out — the zero-cost-off contract
R_RESILIENCE_OFF = "jx-resilience-off-identical"
# emitted by the audit harness (audit_ctrl_ladder): the adaptive
# controller's bounded-re-jit contract — the ladder's rungs must trace to
# exactly len(ladder) distinct jaxpr hashes (each rung one executable, no
# accidental collisions and no hidden extra variants), and a ctrl=True
# config at a rung must trace byte-identical to a plain fixed config at
# the same operating point (the controller is host-side only)
R_CTRL_LADDER = "jx-ctrl-ladder"
# emitted by the audit harness (audit_calib_reselect): the fitted-profile
# re-selection contract — a MachineProfile that restates the static
# constants (costmodel.static_profile) must change NO selector's pick
# across the shape sweep, and an 'auto' exchange built with that profile
# must trace byte-identical to one built with no profile at all
# (re-selection swaps which cached program runs; it never edits a program)
R_CALIB_RESELECT = "jx-calib-reselect"
# dataflow rules (analysis/dataflow.py) — they run on a flattened dataflow
# graph of the jaxpr rather than a linear eqn walk
R_COLLECTIVE_SCHEDULE = "jx-collective-schedule"
R_TOKEN_DOMINANCE = "jx-token-dominance"
R_DONATION = "jx-donation-soundness"
R_KEY_LINEAGE = "jx-key-lineage"
# memory rules (analysis/liveness.py): the peak-liveness interpreter's
# committed byte budget (drift gated against ANALYSIS.json, harness- and
# CLI-emitted like jx-retrace) and the forward dtype-propagation rule
R_PEAK_BYTES = "jx-peak-bytes"
R_DTYPE_FLOW = "jx-dtype-flow"

ALL_RULE_IDS = (
    R_F64,
    R_DYNAMIC_SHAPE,
    R_UNSORTED_BUDGET_GATHER,
    R_GATHER_IN_MOD_QUERY,
    R_COLLECTIVE_COUNT,
    R_WIRE_ACCOUNTING,
    R_CALLBACK,
    R_CODEC_COUNT,
    R_RETRACE,
    R_RESILIENCE_OFF,
    R_CTRL_LADDER,
    R_CALIB_RESELECT,
    R_COLLECTIVE_SCHEDULE,
    R_TOKEN_DOMINANCE,
    R_DONATION,
    R_KEY_LINEAGE,
    R_PEAK_BYTES,
    R_DTYPE_FLOW,
)

# one-line summaries for ``python -m deepreduce_tpu.analysis --list``; tests
# assert this dict covers ALL_RULE_IDS exactly
RULE_DESCRIPTIONS = {
    R_F64: "no float64/complex128 aval anywhere in the traced hot path",
    R_DYNAMIC_SHAPE: "every aval dim is a concrete int (no per-step recompiles)",
    R_UNSORTED_BUDGET_GATHER: "budget-scale gathers/scatters declare sorted/unique indices",
    R_GATHER_IN_MOD_QUERY: "the mod-blocked bloom universe query is gather-free",
    R_COLLECTIVE_COUNT: "exact static collective inventory (flat and per mesh axis)",
    R_WIRE_ACCOUNTING: "collective operand bytes equal payload_bytes() exactly",
    R_CALLBACK: "host callbacks only inside the whitelisted host codecs",
    R_CODEC_COUNT: "exact sparsifier-selection count: O(leaves) vs O(buckets) encode",
    R_RETRACE: "two traces of the same step hash identically (no retrace drift)",
    R_RESILIENCE_OFF: "resilience=off traces byte-identical to the no-seam program",
    R_CTRL_LADDER: "controller ladder: one distinct executable per rung, host-side only",
    R_CALIB_RESELECT: "a constants-restating profile changes no selector pick or trace",
    R_COLLECTIVE_SCHEDULE: "no collective nested under data-dependent cond/while",
    R_TOKEN_DOMINANCE: "streaming barrier token chain orders encode -> all_gather -> decode",
    R_DONATION: "no equation reads a donated input after its aliased output is live",
    R_KEY_LINEAGE: "every PRNG draw's key folds from the step key; no key reuse",
    R_PEAK_BYTES: "per-trace peak live bytes match the committed budget; collective operands resident",
    R_DTYPE_FLOW: "no f64 promotion, no out-of-site payload widening, f32 output round-trip",
}

# sparsifier-selection primitives: every TensorCodec encode lowers its
# top-k selection to exactly one of these, so their static eqn count is the
# codec-invocation count of the traced exchange (the O(leaves) vs
# O(buckets) claim, checked structurally)
_SELECT_PRIMS = ("top_k", "approx_top_k")

# collectives the inventory tracks (jax primitive names as they appear in
# jaxprs); anything else moving data across the mesh axis would be a new
# primitive and should be added here deliberately
COLLECTIVE_PRIMS = (
    "all_gather",
    "all_to_all",
    "ppermute",
    "pshuffle",
    "psum",
    "psum_scatter",
    "reduce_scatter",
    "pmax",
    "pmin",
    "pbroadcast",
)

CALLBACK_PRIMS = ("pure_callback", "io_callback", "callback")

_GATHER_PRIMS = ("gather",)
_SCATTER_PRIMS = ("scatter", "scatter-add", "scatter-mul", "scatter-min", "scatter-max")


@dataclasses.dataclass(frozen=True)
class Violation:
    """One broken invariant: the rule id, which trace it broke in, and an
    aggregated human-readable detail (counts, first offending eqn)."""

    rule: str
    where: str
    detail: str

    def to_dict(self) -> Dict[str, str]:
        return {"rule": self.rule, "where": self.where, "detail": self.detail}


@dataclasses.dataclass
class AuditContext:
    """Per-trace knobs for the rule set.

    `budget_scale` arms the sorted-gather rule: any gather/scatter moving at
    least that many indices is "budget-scale" and must be annotated
    (`indices_are_sorted` for gathers; sorted OR `unique_indices` for
    scatters — every shipped budget-scale scatter is a unique-index
    scatter). `forbid_gather` is the mod-blocked query trace's zero-gather
    contract. `expect_collectives` maps primitive name -> exact static eqn
    count; listed-or-tracked primitives not in the dict must not appear.
    `wire_mode`/`expected_wire_bytes` cross-check collective operand sizes
    against `GradientExchanger.payload_bytes`."""

    label: str
    allow_callbacks: bool = False
    budget_scale: Optional[int] = None
    forbid_gather: bool = False
    expect_collectives: Optional[Dict[str, int]] = None
    # per-axis form of the same contract, for multi-axis (hierarchical)
    # traces: {axis_name: {prim: exact count}}. Every axis named in the
    # dict is inventoried exhaustively (unlisted prims must not ride it),
    # and a collective touching an axis NOT named in the dict is itself a
    # violation — nothing crosses a fabric the contract doesn't mention.
    # Independent of `expect_collectives` (flat traces keep the flat form).
    expect_collectives_by_axis: Optional[Dict[str, Dict[str, int]]] = None
    wire_mode: Optional[str] = None  # 'allgather' | 'ring' | 'collective'
    expected_wire_bytes: Optional[int] = None
    # restrict wire accounting to collectives riding this mesh axis — the
    # hierarchical audits pin payload_bytes() (DCN-only by contract)
    # against the dcn-leg collectives while the ici leg is accounted
    # separately via WireStats.ici_bits
    wire_axis: Optional[str] = None
    num_workers: Optional[int] = None
    # exact static count of sparsifier-selection eqns (top_k/approx_top_k):
    # O(leaves) on the per-tensor path, O(buckets) on the bucketed path
    expect_codec_invocations: Optional[int] = None
    # arms jx-token-dominance: the streaming exchange dispatches each bucket
    # between an entry and an exit optimization_barrier, so the trace must
    # carry exactly 2*B barriers forming a dependency chain that brackets
    # every all_gather
    expect_stream_buckets: Optional[int] = None
    # arms jx-key-lineage: in this trace every random_bits draw must consume
    # a key that passed through fold_in (and no two draws share a fold
    # signature). Only set on traces whose base key is contracted to be
    # folded per worker/tensor/step — codec-unit audits pass raw keys
    require_key_lineage: bool = False


# ---------------------------------------------------------------------- #
# jaxpr traversal
# ---------------------------------------------------------------------- #


def _subjaxprs(value: Any) -> Iterator[Any]:
    """Yield every (open) Jaxpr reachable from one eqn param value."""
    items = value if isinstance(value, (list, tuple)) else (value,)
    for item in items:
        if hasattr(item, "eqns"):  # open Jaxpr
            yield item
        else:
            inner = getattr(item, "jaxpr", None)  # ClosedJaxpr
            if inner is not None and hasattr(inner, "eqns"):
                yield inner


def walk_eqns(jaxpr: Any) -> Iterator[Any]:
    """Depth-first over every eqn, including all nested sub-jaxprs
    (shard_map/pjit/scan/while/cond bodies). Accepts a Jaxpr or
    ClosedJaxpr."""
    inner = getattr(jaxpr, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        jaxpr = inner
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _subjaxprs(v):
                yield from walk_eqns(sub)


def _avals(eqn: Any) -> Iterator[Any]:
    for v in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(v, "aval", None)
        if aval is not None:
            yield aval


def _aval_bytes(aval: Any) -> int:
    n = int(math.prod(int(s) for s in aval.shape)) if aval.shape else 1
    return n * np.dtype(aval.dtype).itemsize


def _index_count(eqn: Any) -> int:
    """Number of indexed positions a gather/scatter touches: the index
    operand's shape with the trailing index-vector dim dropped."""
    aval = getattr(eqn.invars[1], "aval", None)
    if aval is None or not getattr(aval, "shape", None):
        return 1
    shape = aval.shape
    lead = shape[:-1] if len(shape) > 1 else shape
    return int(math.prod(int(s) for s in lead)) if lead else 1


def _canon_mask(s: str) -> str:
    return re.sub(r"0x[0-9a-fA-F]+", "0x", s)


def _canon_aval(aval: Any) -> str:
    try:
        return f"{aval.dtype}{tuple(aval.shape)}"
    except Exception:
        return _canon_mask(str(aval))


def _canon_const(c: Any) -> str:
    """Closed-over constants hash by shape/dtype only — their values are
    trace-time data (hash seeds, offset tables) already pinned by the
    numeric tests, and repr'ing megabyte arrays into the hash text would
    be both slow and numpy-print-options-dependent."""
    try:
        a = np.asarray(c)
        return f"const[{a.dtype}{a.shape}]"
    except Exception:
        return _canon_mask(repr(type(c)))


def _canon_param(v: Any, memo: Dict[int, str]) -> str:
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_canon_param(x, memo) for x in v) + "]"
    if isinstance(v, dict):
        items = sorted(v.items(), key=lambda kv: str(kv[0]))
        return "{" + ",".join(
            f"{k}:{_canon_param(val, memo)}" for k, val in items
        ) + "}"
    if isinstance(v, (set, frozenset)):
        # set reprs follow per-process string hashing — render sorted
        return "{" + ",".join(sorted(_canon_mask(repr(x)) for x in v)) + "}"
    inner = getattr(v, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):  # ClosedJaxpr
        consts = ",".join(_canon_const(c) for c in getattr(v, "consts", ()))
        return "{" + _canon_jaxpr(inner, memo) + ";consts=" + consts + "}"
    if hasattr(v, "eqns"):  # open sub-jaxpr: canonicalize inline
        return "{" + _canon_jaxpr(v, memo) + "}"
    return _canon_mask(repr(v))


def _canon_jaxpr(jaxpr: Any, memo: Dict[int, str]) -> str:
    """Deterministic canonical rendering of one (open) jaxpr: vars renamed
    in first-appearance order, params sorted by key, and every sub-jaxpr
    rendered INLINE at its point of use with a fresh name scope. Memoized
    by object identity — the canonical text is context-free, so the same
    (jit-cache-shared) sub-jaxpr object renders once however many call
    sites inline it, and two structurally equal jaxprs always render to
    the same text regardless of which traced first."""
    got = memo.get(id(jaxpr))
    if got is not None:
        return got
    names: Dict[Any, str] = {}

    def bind(v: Any) -> str:
        nm = f"v{len(names)}"
        names[v] = nm
        return nm

    def rd(v: Any) -> str:
        if hasattr(v, "val"):  # Literal
            return _canon_mask(repr(v.val)) + ":" + _canon_aval(v.aval)
        nm = names.get(v)
        if nm is not None:
            return nm
        return "free:" + _canon_aval(getattr(v, "aval", None))

    lines = [
        "in=" + ",".join(
            bind(v) + ":" + _canon_aval(v.aval) for v in jaxpr.invars
        ),
        "const=" + ",".join(
            bind(v) + ":" + _canon_aval(v.aval) for v in jaxpr.constvars
        ),
    ]
    for eqn in jaxpr.eqns:
        ins = ",".join(rd(v) for v in eqn.invars)
        params = ",".join(
            f"{k}={_canon_param(val, memo)}"
            for k, val in sorted(eqn.params.items(), key=lambda kv: str(kv[0]))
        )
        outs = ",".join(
            bind(ov) + ":" + _canon_aval(ov.aval) for ov in eqn.outvars
        )
        lines.append(f"{outs}={eqn.primitive.name}[{params}]({ins})")
    lines.append("out=" + ",".join(rd(v) for v in jaxpr.outvars))
    text = "\n".join(lines)
    memo[id(jaxpr)] = text
    return text


def jaxpr_hash(jaxpr: Any) -> str:
    """Stable content hash of a traced program — two traces of the same
    step must agree (the retrace/recompile guard), in the same process or
    across processes, whatever traced before them. Hashing the
    pretty-printer output proved trace-history-sensitive (its shared-
    sub-jaxpr hoisting order follows the jit cache), so the hash is taken
    over a custom canonical rendering instead: first-appearance var
    renaming, key-sorted params, sub-jaxprs inlined at their use sites,
    object addresses masked, set-valued params sorted."""
    consts = getattr(jaxpr, "consts", None)
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    memo: Dict[int, str] = {}
    text = _canon_jaxpr(inner, memo)
    if consts:
        text += "\nconsts=" + ",".join(_canon_const(c) for c in consts)
    return hashlib.sha256(text.encode()).hexdigest()[:16]


def collective_counts(jaxpr: Any) -> Dict[str, int]:
    """Static eqn count per collective primitive (loop bodies count once —
    the *program* has one collective op there, however many trips run)."""
    counts: Dict[str, int] = {}
    for eqn in walk_eqns(jaxpr):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            counts[name] = counts.get(name, 0) + 1
    return counts


def eqn_axes(eqn: Any) -> tuple:
    """Mesh axes a collective eqn rides, as a tuple of axis names. JAX
    spells the param `axis_name` on the data movers (all_gather / ppermute
    / reduce_scatter / all_to_all) and `axes` on the reducers (psum / pmax
    / pmin); both may be a single name or a tuple."""
    axes = eqn.params.get("axis_name", eqn.params.get("axes", ()))
    if isinstance(axes, (tuple, list)):
        return tuple(str(a) for a in axes)
    return (str(axes),)


def collective_counts_by_axis(jaxpr: Any) -> Dict[str, Dict[str, int]]:
    """`collective_counts` split by mesh axis: {axis: {prim: count}}. A
    collective naming several axes at once (e.g. pmax over ('dcn','ici'))
    counts once under EACH — it moves data on every fabric it names."""
    counts: Dict[str, Dict[str, int]] = {}
    for eqn in walk_eqns(jaxpr):
        name = eqn.primitive.name
        if name not in COLLECTIVE_PRIMS:
            continue
        for ax in eqn_axes(eqn):
            per = counts.setdefault(ax, {})
            per[name] = per.get(name, 0) + 1
    return counts


# ---------------------------------------------------------------------- #
# rules
# ---------------------------------------------------------------------- #


def rule_no_f64(jaxpr: Any, ctx: AuditContext) -> List[Violation]:
    """TPUs have no fast f64; ARCHITECTURE.md pins every fit/codec to f32.
    Any float64/complex128 aval in the traced program is a violation."""
    bad: List[str] = []
    for eqn in walk_eqns(jaxpr):
        for aval in _avals(eqn):
            dt = getattr(aval, "dtype", None)
            if dt is None:
                continue
            try:
                npdt = np.dtype(dt)
            except TypeError:
                continue  # extended dtypes (PRNG key<fry>) — not numeric
            if npdt in (np.dtype(np.float64), np.dtype(np.complex128)):
                bad.append(eqn.primitive.name)
                break
    if not bad:
        return []
    return [
        Violation(
            R_F64,
            ctx.label,
            f"{len(bad)} eqn(s) carry float64/complex128 values "
            f"(first: {bad[0]}); the TPU hot path is f32-only",
        )
    ]


def rule_static_shapes(jaxpr: Any, ctx: AuditContext) -> List[Violation]:
    """Every aval dim must be a concrete int — dynamic/polymorphic shapes
    under jit would mean per-step recompiles (the reference's
    tensors_size_are_same=False world the whole design exists to avoid)."""
    bad: List[str] = []
    for eqn in walk_eqns(jaxpr):
        for aval in _avals(eqn):
            dims = getattr(aval, "shape", ())
            if any(not isinstance(d, (int, np.integer)) for d in dims):
                bad.append(f"{eqn.primitive.name}:{dims}")
                break
    if not bad:
        return []
    return [
        Violation(
            R_DYNAMIC_SHAPE,
            ctx.label,
            f"{len(bad)} eqn(s) have non-static dims (first: {bad[0]})",
        )
    ]


def rule_sorted_budget_ops(jaxpr: Any, ctx: AuditContext) -> List[Violation]:
    """Budget-scale gathers must declare indices_are_sorted=True (XLA skips
    the bounds-sort); budget-scale scatters must be sorted or unique
    (unsorted+non-unique serializes on collision handling). Armed only when
    ctx.budget_scale is set — the hot-path configs where the annotations
    are load-bearing."""
    if ctx.budget_scale is None:
        return []
    bad: List[str] = []
    for eqn in walk_eqns(jaxpr):
        name = eqn.primitive.name
        if name in _GATHER_PRIMS:
            if _index_count(eqn) >= ctx.budget_scale and not eqn.params.get(
                "indices_are_sorted", False
            ):
                bad.append(f"{name}[n={_index_count(eqn)}]")
        elif name in _SCATTER_PRIMS:
            if (
                _index_count(eqn) >= ctx.budget_scale
                and not eqn.params.get("indices_are_sorted", False)
                and not eqn.params.get("unique_indices", False)
            ):
                bad.append(f"{name}[n={_index_count(eqn)}]")
    if not bad:
        return []
    return [
        Violation(
            R_UNSORTED_BUDGET_GATHER,
            ctx.label,
            f"{len(bad)} budget-scale gather/scatter eqn(s) lack "
            f"indices_are_sorted/unique_indices (first: {bad[0]}; "
            f"threshold n>={ctx.budget_scale})",
        )
    ]


def rule_gather_free(jaxpr: Any, ctx: AuditContext) -> List[Violation]:
    """The bloom_blocked='mod' universe query is a pure broadcast —
    ARCHITECTURE.md's 'zero gathers' claim, checked literally."""
    if not ctx.forbid_gather:
        return []
    n = sum(1 for eqn in walk_eqns(jaxpr) if eqn.primitive.name in _GATHER_PRIMS)
    if n == 0:
        return []
    return [
        Violation(
            R_GATHER_IN_MOD_QUERY,
            ctx.label,
            f"{n} gather eqn(s) in a trace contracted to be gather-free "
            "(mod-blocked bloom query is a broadcast membership test)",
        )
    ]


def rule_collective_inventory(jaxpr: Any, ctx: AuditContext) -> List[Violation]:
    """The fused path is exactly ONE all_gather per step; the ring path is
    ppermute-only; the dense baseline is one psum. Any extra collective is
    a silent regression of the latency story."""
    diffs = []
    if ctx.expect_collectives is not None:
        got = collective_counts(jaxpr)
        for prim in sorted(set(COLLECTIVE_PRIMS) | set(ctx.expect_collectives)):
            want = ctx.expect_collectives.get(prim, 0)
            have = got.get(prim, 0)
            if want != have:
                diffs.append(f"{prim}: want {want}, got {have}")
    if ctx.expect_collectives_by_axis is not None:
        by_axis = collective_counts_by_axis(jaxpr)
        spec = ctx.expect_collectives_by_axis
        for ax in sorted(set(by_axis) - set(spec)):
            diffs.append(
                f"axis {ax!r}: {sum(by_axis[ax].values())} collective(s) on "
                "an axis the contract does not mention"
            )
        for ax in sorted(spec):
            have_ax = by_axis.get(ax, {})
            for prim in sorted(set(COLLECTIVE_PRIMS) | set(spec[ax])):
                want = spec[ax].get(prim, 0)
                have = have_ax.get(prim, 0)
                if want != have:
                    diffs.append(f"{ax}/{prim}: want {want}, got {have}")
    if not diffs:
        return []
    return [
        Violation(
            R_COLLECTIVE_COUNT,
            ctx.label,
            "collective inventory mismatch — " + "; ".join(diffs),
        )
    ]


def rule_wire_accounting(jaxpr: Any, ctx: AuditContext) -> List[Violation]:
    """Cross-check what the collectives actually move against
    `GradientExchanger.payload_bytes()`: allgather mode sums all_gather
    operand bytes; ring mode requires every ppermute hop to forward the
    B-byte fused buffer with (W-1)*B == payload_bytes."""
    if ctx.wire_mode is None or ctx.expected_wire_bytes is None:
        return []

    def on_axis(eqn: Any) -> bool:
        # wire_axis narrows the accounting to one fabric (the hierarchical
        # audits pin the DCN leg); unset means every collective counts
        return ctx.wire_axis is None or ctx.wire_axis in eqn_axes(eqn)

    if ctx.wire_mode == "allgather":
        moved = sum(
            _aval_bytes(eqn.invars[0].aval)
            for eqn in walk_eqns(jaxpr)
            if eqn.primitive.name == "all_gather" and on_axis(eqn)
        )
        if moved == ctx.expected_wire_bytes:
            return []
        return [
            Violation(
                R_WIRE_ACCOUNTING,
                ctx.label,
                f"all_gather operands move {moved} B/worker but "
                f"payload_bytes() reports {ctx.expected_wire_bytes} B",
            )
        ]
    if ctx.wire_mode == "collective":
        # in-collective routes (sparse_rs): the wire story spans multiple
        # collective shapes (all_to_all / psum_scatter / pmax / psum /
        # all_gather), so sum the operand bytes of EVERY collective eqn and
        # require exact agreement with payload_bytes() — which routes
        # through costmodel.rs_payload_bytes, the same per-collective
        # accounting the bench sweep prices
        moved = sum(
            _aval_bytes(v.aval)
            for eqn in walk_eqns(jaxpr)
            if eqn.primitive.name in COLLECTIVE_PRIMS and on_axis(eqn)
            for v in eqn.invars
            if getattr(v, "aval", None) is not None
        )
        if moved == ctx.expected_wire_bytes:
            return []
        return [
            Violation(
                R_WIRE_ACCOUNTING,
                ctx.label,
                f"collective operands move {moved} B/worker but "
                f"payload_bytes() reports {ctx.expected_wire_bytes} B",
            )
        ]
    if ctx.wire_mode == "ring":
        w = ctx.num_workers
        hop_sizes = {
            _aval_bytes(eqn.invars[0].aval)
            for eqn in walk_eqns(jaxpr)
            if eqn.primitive.name == "ppermute" and on_axis(eqn)
        }
        if not hop_sizes:
            return [
                Violation(
                    R_WIRE_ACCOUNTING, ctx.label, "ring trace contains no ppermute hops"
                )
            ]
        if len(hop_sizes) > 1:
            return [
                Violation(
                    R_WIRE_ACCOUNTING,
                    ctx.label,
                    f"ring hops forward different buffer sizes: {sorted(hop_sizes)}",
                )
            ]
        b = hop_sizes.pop()
        want = ctx.expected_wire_bytes
        if w is not None and b * (w - 1) == want:
            return []
        return [
            Violation(
                R_WIRE_ACCOUNTING,
                ctx.label,
                f"ring hop buffer is {b} B; (W-1)*B = {b * ((w or 1) - 1)} B "
                f"!= payload_bytes() {want} B",
            )
        ]
    raise ValueError(f"unknown wire_mode {ctx.wire_mode!r}")


def rule_callback_whitelist(jaxpr: Any, ctx: AuditContext) -> List[Violation]:
    """Host callbacks stall the device; they are allowed only in the
    explicitly-host codecs (bloom_native / integer_native / polyfit_host /
    huffman / gzip). Anywhere else, a pure_callback sneaking into the hot
    path is a violation."""
    if ctx.allow_callbacks:
        return []
    n = sum(1 for eqn in walk_eqns(jaxpr) if eqn.primitive.name in CALLBACK_PRIMS)
    if n == 0:
        return []
    return [
        Violation(
            R_CALLBACK,
            ctx.label,
            f"{n} host-callback eqn(s) outside the whitelisted host codecs",
        )
    ]


def rule_codec_invocations(jaxpr: Any, ctx: AuditContext) -> List[Violation]:
    """Pin the codec-invocation count of the exchange: each TensorCodec
    encode runs exactly one top-k selection (sparse.topk lowers to one
    top_k eqn; approx mode to one approx_top_k), so the per-tensor fused
    path must show exactly L selections and the bucketed path exactly C —
    the O(leaves) → O(buckets) encode claim, checked on the trace."""
    if ctx.expect_codec_invocations is None:
        return []
    got = sum(
        1 for eqn in walk_eqns(jaxpr) if eqn.primitive.name in _SELECT_PRIMS
    )
    if got == ctx.expect_codec_invocations:
        return []
    return [
        Violation(
            R_CODEC_COUNT,
            ctx.label,
            f"{got} sparsifier-selection eqn(s) (top_k/approx_top_k) but the "
            f"trace contracts exactly {ctx.expect_codec_invocations} codec "
            "invocation(s)",
        )
    ]


JAXPR_RULES = (
    rule_no_f64,
    rule_static_shapes,
    rule_sorted_budget_ops,
    rule_gather_free,
    rule_collective_inventory,
    rule_wire_accounting,
    rule_callback_whitelist,
    rule_codec_invocations,
)


def run_rules(jaxpr: Any, ctx: AuditContext) -> List[Violation]:
    """Run every jaxpr rule over one traced program — the linear-walk rules
    above plus the dataflow-graph and dtype-flow rules (imported late:
    dataflow.py/liveness.py import this module's plumbing)."""
    from deepreduce_tpu.analysis import dataflow, liveness

    out: List[Violation] = []
    for rule in JAXPR_RULES + dataflow.DATAFLOW_RULES + liveness.DTYPE_RULES:
        out.extend(rule(jaxpr, ctx))
    return out
