"""Static-analysis gate: jaxpr invariant auditor + repo AST lint.

`python -m deepreduce_tpu.analysis` runs both passes, writes ANALYSIS.json,
and exits nonzero on any violation. tests/test_analysis.py wraps the fast
subset into tier-1.
"""

from deepreduce_tpu.analysis.rules import AuditContext, Violation, run_rules

__all__ = ["AuditContext", "Violation", "run_rules"]
