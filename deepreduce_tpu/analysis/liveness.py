"""Donation-aware liveness / peak-HBM interpreter + the dtype-flow rule.

Every headline plan in this repro is about wire, but a route that OOMs HBM
— or silently double-buffers the [num_clients, ...] residual bank — dies
before the wire story matters. EQuARX (PAPERS.md) is the precedent:
compression only wins on TPU when it lives inside XLA's memory envelope.
This module prices that envelope statically, on the same flattened
dataflow graph the SPMD rules already walk (dataflow.build_graph), with
no devices and no compiles:

- ``analyze`` runs an abstract interpretation of buffer lifetimes under
  the jaxpr's own topological schedule: a value's buffer is born at its
  defining node, inputs and outputs of a node are simultaneously resident
  (XLA op semantics), and a buffer dies after its last read — except a
  DONATED input, which dies exactly at the birth of the output XLA
  aliases it to (first-fit same-aval matching, mirroring
  rule_donation_soundness), the in-place-reuse semantics of
  `donate_argnums`. The report carries the peak live bytes, the top
  contributing buffers at the peak with provenance (producer primitive +
  source site), and the live-byte residency at every collective.

- ``rule_dtype_flow`` is a forward dtype-propagation rule over the same
  trace: no f64/complex128 promotion anywhere, no silent widening of a
  quantized narrow payload (int8/uint8/int16/uint16/f16/bf16) into f32
  outside the registered dequant sites (matched on the eqn's user source
  frame), and every floating top-level output — aggregated gradients,
  residual/EF leaves — round-trips at the declared f32.

Model limits, stated: opaque control flow (cond/while/scan) is a single
node — its body's internal scratch is not priced (the decode fori_loop's
per-trip temporaries are a few leaf-sized buffers, dwarfed by the gathered
payload and the residual state this auditor exists to pin). XLA's fusion
can shave transients the model counts; the committed budgets are
therefore a *model* peak, compared against itself across PRs — exactly
like the modeled wire in costmodel — not a silicon measurement.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deepreduce_tpu.analysis import dataflow
from deepreduce_tpu.analysis.rules import (
    COLLECTIVE_PRIMS,
    R_DTYPE_FLOW,
    AuditContext,
    Violation,
    walk_eqns,
)

try:  # private but stable since 0.3; fail-open (no provenance) without it
    from jax._src import source_info_util as _siu
except ImportError:  # pragma: no cover
    _siu = None


def _aval_nbytes(aval: Any) -> int:
    """Buffer bytes an aval occupies; 0 for unpriceable extended dtypes
    (PRNG keys, tokens) — they are word-sized bookkeeping, not payload."""
    if aval is None:
        return 0
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        itemsize = np.dtype(dtype).itemsize
    except TypeError:
        return 0
    try:
        n = int(math.prod(int(s) for s in shape)) if shape else 1
    except (TypeError, ValueError):
        return 0
    return n * itemsize


def _site_of(eqn: Any) -> str:
    """`file.py:function` of the innermost user frame that emitted an eqn
    (line numbers deliberately dropped — committed provenance must not
    churn on unrelated edits). Sources and info-less eqns get '-'."""
    if eqn is None or _siu is None:
        return "-"
    try:
        fr = _siu.user_frame(eqn.source_info)
    except Exception:
        return "-"
    if fr is None:
        return "-"
    fname = fr.file_name.rsplit("/", 1)[-1]
    return f"{fname}:{fr.function_name}"


@dataclasses.dataclass
class LivenessReport:
    """The priced memory envelope of one traced program."""

    peak_bytes: int
    # top contributing buffers at the peak, largest first:
    # {bytes, prim, shape, dtype, site}
    peak_top: List[Dict[str, Any]]
    # max live bytes observed at any collective eqn, per primitive —
    # the operand-residency envelope each collective must fit inside
    collective_residency: Dict[str, int]
    # operand refs of a collective that were NOT live when the collective
    # fired (a freed/donated buffer fed a collective — in-place reuse gone
    # wrong); human-readable, empty on sound traces
    residency_failures: List[str]
    nodes: int

    def to_dict(self) -> Dict[str, Any]:
        return {
            "peak_bytes": self.peak_bytes,
            "peak_top": self.peak_top,
            "collective_residency": self.collective_residency,
        }


def analyze(closed_jaxpr: Any) -> LivenessReport:
    """Peak-liveness abstract interpretation over the flattened graph."""
    g = dataflow.build_graph(closed_jaxpr)
    n = len(g.nodes)

    # last read per ref; traced outputs stay live through the end
    last_use: Dict[dataflow.Ref, int] = {}
    for fe in g.nodes:
        for r in fe.in_refs:
            if r[0] != "lit":
                last_use[r] = fe.idx  # emission order: the max wins
    for r in g.out_refs:
        if r[0] != "lit":
            last_use[r] = n

    # donation: mirror rule_donation_soundness's first-fit same-aval
    # matching, then free the donated buffer at its alias's birth instead
    # of at its last read — XLA writes the aliased output into it
    donated: set = set()
    free_at_birth: Dict[int, List[dataflow.Ref]] = {}
    for don in g.donations:
        claimed: set = set()
        for _pos, ref, aval in don.donated:
            if ref[0] == "lit" or ref in donated:
                continue
            for j, (oref, oaval) in enumerate(don.out_refs):
                if j not in claimed and dataflow._aval_eq(aval, oaval):
                    claimed.add(j)
                    if oref[0] != "lit" and oref != ref:
                        free_at_birth.setdefault(oref[0], []).append(ref)
                        donated.add(ref)
                    break

    # non-donated refs die after their last read; dead values die at birth
    free_after: Dict[int, List[dataflow.Ref]] = {}
    for fe in g.nodes:
        for pos in range(len(fe.out_avals)):
            r = (fe.idx, pos)
            if r in donated:
                continue
            when = last_use.get(r, fe.idx)
            if when < n:
                free_after.setdefault(when, []).append(r)

    live: Dict[dataflow.Ref, int] = {}
    cur = peak = 0
    peak_live: Dict[dataflow.Ref, int] = {}
    residency: Dict[str, int] = {}
    failures: List[str] = []

    def free(r: dataflow.Ref) -> None:
        nonlocal cur
        b = live.pop(r, None)
        if b is not None:
            cur -= b

    for fe in g.nodes:
        for r in free_at_birth.get(fe.idx, ()):
            free(r)  # in-place reuse: donated buffer dies as its alias is born
        for pos, aval in enumerate(fe.out_avals):
            b = _aval_nbytes(aval)
            if b:
                live[(fe.idx, pos)] = b
                cur += b
        if cur > peak:
            peak = cur
            peak_live = dict(live)
        if fe.prim in COLLECTIVE_PRIMS:
            residency[fe.prim] = max(residency.get(fe.prim, 0), cur)
            for r in fe.in_refs:
                if r[0] != "lit" and r not in live and _aval_nbytes(
                    g.nodes[r[0]].out_avals[r[1]]
                    if r[1] < len(g.nodes[r[0]].out_avals) else None
                ):
                    failures.append(
                        f"{fe.prim}@{fe.idx} reads ref {r} that is no longer "
                        "resident (freed or donated away before the "
                        "collective fired)"
                    )
        for r in free_after.get(fe.idx, ()):
            free(r)

    top = sorted(peak_live.items(), key=lambda kv: (-kv[1], kv[0]))[:3]
    peak_top = []
    for (idx, pos), b in top:
        fe = g.nodes[idx]
        aval = fe.out_avals[pos] if pos < len(fe.out_avals) else None
        peak_top.append(
            {
                "bytes": b,
                "prim": fe.prim,
                "shape": list(getattr(aval, "shape", ())),
                "dtype": str(getattr(aval, "dtype", "?")),
                "site": _site_of(fe.eqn),
            }
        )
    return LivenessReport(
        peak_bytes=peak,
        peak_top=peak_top,
        collective_residency=residency,
        residency_failures=failures,
        nodes=n,
    )


# ---------------------------------------------------------------------- #
# jx-dtype-flow
# ---------------------------------------------------------------------- #

# quantized-payload dtypes: widening one of these to f32/f64 re-inflates a
# compressed representation and must only happen at a registered dequant
# site. bool is deliberately excluded — mask/flag -> f32 counters are
# arithmetic, not payload decompression.
_NARROW = frozenset(
    {"int8", "uint8", "int16", "uint16", "float16", "bfloat16"}
)
_WIDE = frozenset({"float32", "float64"})

# the registered dequant/decode sites, as (file basename, function name)
# of the innermost user frame that emits the widening convert. Everything
# that legitimately turns a narrow wire payload back into f32 lives here;
# a widening convert anywhere else is a silent re-inflation.
DEQUANT_SITES = frozenset(
    {
        ("qar.py", "bucket_dequantize"),  # int8 levels -> f32 (qar + rs quantized)
        ("qsgd.py", "decode"),  # QSGD codec: int8 levels -> f32
        ("qsgd.py", "bucket_scale"),  # norm path shared by encode/decode
        ("sparse_rs.py", "_exchange_adaptive"),  # dense-lane int8 dequant
        ("sparse_rs.py", "_exchange_quantized"),  # summed int8 levels -> f32
        ("integer.py", "decode"),  # packed index deltas -> values
        ("doubleexp.py", "decode"),  # sign/exponent payload -> f32
        ("packing.py", "unpack_bits"),  # bit-packed wire words -> values
    }
)


def _is_f64(dt: Any) -> bool:
    return str(dt) in ("float64", "complex128")


def rule_dtype_flow(jaxpr: Any, ctx: AuditContext) -> List[Violation]:
    """Forward dtype discipline over one traced program (always armed):

    - no promotion to float64/complex128 anywhere (the f64 *presence* rule
      jx-f64 catches the values; this catches the conversion that minted
      them, so a planted promotion trips both with distinct stories);
    - every ``convert_element_type`` widening a quantized narrow dtype
      (int8/uint8/int16/uint16/f16/bf16) to f32/f64 must be emitted from a
      registered dequant site (DEQUANT_SITES, matched on the innermost
      user source frame) — anywhere else it silently re-inflates a
      compressed payload to dense f32;
    - every floating top-level output (aggregated gradients, residual/EF
      leaves) must be exactly f32 — the declared round-trip dtype.

    Source-info matching fails open: an eqn with no user frame (or a jax
    build without source_info_util) is not flagged, so the rule can never
    false-positive on synthetic traces."""
    promotions: List[str] = []
    rogue: List[str] = []
    for eqn in walk_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        new_dt = str(eqn.params.get("new_dtype", ""))
        if _is_f64(new_dt):
            promotions.append(f"-> {new_dt} at {_site_of(eqn)}")
            continue
        src = getattr(eqn.invars[0], "aval", None)
        old_dt = str(getattr(src, "dtype", ""))
        if old_dt in _NARROW and new_dt in _WIDE:
            site = _site_of(eqn)
            if site == "-":
                continue  # no provenance — fail open
            key = tuple(site.split(":", 1))
            if key not in DEQUANT_SITES:
                rogue.append(f"{old_dt} -> {new_dt} at {site}")
    bad_out: List[str] = []
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for pos, ov in enumerate(getattr(inner, "outvars", ())):
        dt = getattr(getattr(ov, "aval", None), "dtype", None)
        if dt is None:
            continue
        try:
            npdt = np.dtype(dt)
        except TypeError:
            continue  # extended dtypes (PRNG keys) are not wire payloads
        if npdt.kind in ("f", "c") and npdt != np.dtype(np.float32):
            bad_out.append(f"output[{pos}] is {npdt}")
    probs: List[str] = []
    if promotions:
        probs.append(
            f"{len(promotions)} promotion(s) to f64/c128 "
            f"(first: {promotions[0]})"
        )
    if rogue:
        probs.append(
            f"{len(rogue)} widening(s) of a quantized payload outside the "
            f"registered dequant sites (first: {rogue[0]})"
        )
    if bad_out:
        probs.append(
            f"{len(bad_out)} floating output(s) not f32 "
            f"(first: {bad_out[0]}) — residual/EF state must round-trip at "
            "its declared dtype"
        )
    if not probs:
        return []
    return [Violation(R_DTYPE_FLOW, ctx.label, "; ".join(probs))]


DTYPE_RULES = (rule_dtype_flow,)
