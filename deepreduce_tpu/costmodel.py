"""Shared wire/compute cost model for exchange-mode selection and bench.

One implementation of the step-time model that bench.py previously
duplicated inline (`T = payload_bytes/BW + t_enc + t_dec` at a 100 Mbps
default link), plus the W-aware extensions the in-collective reduction
sweep and the ``rs_mode="auto"`` selector need.

Two families of estimators live here:

- **Flat (W-independent)** — `exchange_time(m, bw)`: the historical bench
  model. Payload bytes are the per-worker *injection* (what one worker
  puts on the wire), encode/decode measured once. This is what every
  committed BENCH_*.json before r11 reports; it stays byte-for-byte the
  same function so those numbers remain reproducible.
- **W-aware (ring)** — per-collective wire times from standard ring
  algorithm costs, and `fused_step_time` / `rs_step_time` which model what
  actually scales with W: the fused allgather path *receives* W-1 remote
  payloads and runs W decodes per step, while an in-collective route pays
  ~1x decode and ring-bounded wire. This is the model under which the
  ROADMAP target "beat drqsgd_bloom_* at W>=8" is meaningful at all — in
  the flat model W never appears.

Everything here is host-side pure python/float math: it runs at
construction time (mode selection) or in bench drivers, never under
trace.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import pathlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

# 100 Mbps in bytes/s — the paper's federated uplink assumption, and the
# default link every committed bench record uses.
BW_100MBPS = 12.5e6

RS_MODES = ("sparse", "adaptive", "quantized", "sketch", "oktopk", "auto")


# ---------------------------------------------------------------------------
# Machine profiles — the calibrated counterpart of the static constants.
#
# Every estimator below accepts an optional ``profile=`` (a MachineProfile):
# wherever a bandwidth/compute default would have come from a hardcoded
# constant, the profile's fitted value is used instead. An explicitly passed
# bw always wins over the profile, and profile=None reproduces the historical
# constants byte-for-byte — so every committed BENCH_*.json stays replayable.
# Profiles are fitted from a tracking run dir by `calibrate()` and serialized
# as a schema-tagged JSON record with NO wall-clock fields, so a profile is
# bitwise-replayable from a committed run dir.
# ---------------------------------------------------------------------------

PROFILE_SCHEMA_V1 = "deepreduce_tpu/machine-profile/v1"
PROFILE_SCHEMA = "deepreduce_tpu/machine-profile/v2"

# the model parameters a profile carries; each is either "fitted" (recovered
# from telemetry) or "fixed" (unidentifiable in that run — held at the
# static constant and recorded as such)
PROFILE_PARAMS = ("bw_dcn", "bw_ici", "t_enc", "t_dec", "compute_time")

# keys every per-route row carries (v2 `routes` table): one encode / one
# decode in seconds plus the number of labeled span events the fit saw
ROUTE_ROW_KEYS = ("t_enc_s", "t_dec_s", "samples")

# routes whose decode runs once per received payload — the fused
# gather-then-decode family pays W decodes per step, so their fitted rows
# divide the per-step decode seconds by W (matching the t_decode_s
# convention measurement rows use). Every other route (the in-collective
# rs family, qar) decodes once per step.
GATHER_DECODE_ROUTES = frozenset({"fused", "bucketed"})


def _route_decodes_per_step(label: str, W: int) -> int:
    return W if label in GATHER_DECODE_ROUTES else 1


@dataclasses.dataclass(frozen=True)
class MachineProfile:
    """Fitted (or static-default) machine parameters for the cost model.

    bw_* are link bandwidths in bytes/s; t_enc_s / t_dec_s are one encode /
    one decode in seconds (the same units the measurement rows use);
    compute_time_s is the per-step backward compute available to hide wire
    behind. `fitted` / `fixed` partition PROFILE_PARAMS by whether the run's
    telemetry identified the parameter; `source` documents the fit inputs
    (run name, measured step time, apportioned component seconds — and
    deliberately no wall-clock timestamps, so the record is deterministic)."""

    bw_dcn: float = BW_100MBPS
    bw_ici: float = 1.25e9  # == BW_ICI_10GBPS (defined below)
    t_enc_s: float = 0.0
    t_dec_s: float = 0.0
    compute_time_s: float = 0.0
    fitted: Tuple[str, ...] = ()
    fixed: Tuple[str, ...] = PROFILE_PARAMS
    routes: Dict[str, Dict[str, float]] = dataclasses.field(default_factory=dict)
    source: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_record(self) -> Dict[str, Any]:
        return {
            "schema": PROFILE_SCHEMA,
            "bw_dcn_bytes_per_s": float(self.bw_dcn),
            "bw_ici_bytes_per_s": float(self.bw_ici),
            "t_enc_s": float(self.t_enc_s),
            "t_dec_s": float(self.t_dec_s),
            "compute_time_s": float(self.compute_time_s),
            "fitted": list(self.fitted),
            "fixed": list(self.fixed),
            "routes": {
                label: {
                    "t_enc_s": float(row["t_enc_s"]),
                    "t_dec_s": float(row["t_dec_s"]),
                    "samples": int(row["samples"]),
                }
                for label, row in sorted(self.routes.items())
            },
            "source": dict(self.source),
        }

    @classmethod
    def from_record(cls, rec: Dict[str, Any]) -> "MachineProfile":
        validate_profile(rec)
        # v1 records carry no route table: they load with routes == {} and
        # every estimator/selector output stays byte-identical to r16.
        routes = {
            label: {
                "t_enc_s": float(row["t_enc_s"]),
                "t_dec_s": float(row["t_dec_s"]),
                "samples": int(row["samples"]),
            }
            for label, row in (rec.get("routes") or {}).items()
        }
        return cls(
            bw_dcn=float(rec["bw_dcn_bytes_per_s"]),
            bw_ici=float(rec["bw_ici_bytes_per_s"]),
            t_enc_s=float(rec["t_enc_s"]),
            t_dec_s=float(rec["t_dec_s"]),
            compute_time_s=float(rec["compute_time_s"]),
            fitted=tuple(rec["fitted"]),
            fixed=tuple(rec["fixed"]),
            routes=routes,
            source=dict(rec.get("source", {})),
        )

    def content_hash(self) -> str:
        """Deterministic digest of the full record — the provenance stamp
        bench.py attaches to every record priced under this profile."""
        blob = json.dumps(self.to_record(), sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def save(self, path) -> None:
        with open(path, "w") as f:
            json.dump(self.to_record(), f, indent=2, sort_keys=True)
            f.write("\n")


def static_profile() -> MachineProfile:
    """The profile that encodes exactly the static constants — by contract
    it changes NO selector's pick (pinned by the jx-calib-reselect audit)."""
    return MachineProfile()


def validate_profile(rec: Any) -> None:
    """Raise ValueError unless `rec` is a well-formed machine-profile record
    (the schema the `telemetry calibrate` CLI emits and `load_profile`
    accepts)."""
    if not isinstance(rec, dict):
        raise ValueError(f"profile record must be a dict, got {type(rec).__name__}")
    schema = rec.get("schema")
    if schema not in (PROFILE_SCHEMA, PROFILE_SCHEMA_V1):
        raise ValueError(
            f"profile schema must be {PROFILE_SCHEMA!r} (or legacy "
            f"{PROFILE_SCHEMA_V1!r}), got {schema!r}"
        )
    if schema == PROFILE_SCHEMA_V1 and "routes" in rec:
        raise ValueError("v1 profile records carry no 'routes' table")
    for key, positive in (
        ("bw_dcn_bytes_per_s", True),
        ("bw_ici_bytes_per_s", True),
        ("t_enc_s", False),
        ("t_dec_s", False),
        ("compute_time_s", False),
    ):
        v = rec.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            raise ValueError(f"profile field {key!r} must be a number, got {v!r}")
        if not math.isfinite(float(v)):
            raise ValueError(f"profile field {key!r} must be finite, got {v!r}")
        if positive and float(v) <= 0:
            raise ValueError(f"profile field {key!r} must be > 0, got {v!r}")
        if not positive and float(v) < 0:
            raise ValueError(f"profile field {key!r} must be >= 0, got {v!r}")
    fitted = rec.get("fitted")
    fixed = rec.get("fixed")
    for name, val in (("fitted", fitted), ("fixed", fixed)):
        if not isinstance(val, (list, tuple)) or not all(
            isinstance(p, str) for p in val
        ):
            raise ValueError(f"profile field {name!r} must be a list of strings")
    both = list(fitted) + list(fixed)
    if sorted(both) != sorted(PROFILE_PARAMS):
        raise ValueError(
            "profile fitted+fixed must partition "
            f"{sorted(PROFILE_PARAMS)}, got fitted={list(fitted)} "
            f"fixed={list(fixed)}"
        )
    if "source" in rec and not isinstance(rec["source"], dict):
        raise ValueError("profile field 'source' must be a dict")
    routes = rec.get("routes")
    if schema == PROFILE_SCHEMA and routes is not None:
        if not isinstance(routes, dict):
            raise ValueError(
                f"profile field 'routes' must be a dict, got {type(routes).__name__}"
            )
        for label, row in routes.items():
            if not isinstance(label, str) or not label:
                raise ValueError(f"route label must be a non-empty string, got {label!r}")
            if not isinstance(row, dict):
                raise ValueError(
                    f"route row {label!r} must be a dict, got {type(row).__name__}"
                )
            extra = set(row) - set(ROUTE_ROW_KEYS)
            if extra:
                raise ValueError(
                    f"route row {label!r} has unknown keys {sorted(extra)} "
                    f"(expected exactly {list(ROUTE_ROW_KEYS)})"
                )
            for key in ("t_enc_s", "t_dec_s"):
                v = row.get(key)
                if not isinstance(v, (int, float)) or isinstance(v, bool):
                    raise ValueError(
                        f"route row {label!r} field {key!r} must be a number, got {v!r}"
                    )
                if not math.isfinite(float(v)) or float(v) < 0:
                    raise ValueError(
                        f"route row {label!r} field {key!r} must be finite and "
                        f">= 0, got {v!r}"
                    )
            n = row.get("samples")
            if not isinstance(n, int) or isinstance(n, bool) or n < 1:
                raise ValueError(
                    f"route row {label!r} field 'samples' must be a positive "
                    f"int, got {n!r}"
                )


def load_profile(path) -> MachineProfile:
    """Read + schema-validate a profile.json written by `MachineProfile.save`
    (or the `telemetry calibrate --out` CLI). Raises ValueError on schema
    violations, OSError on unreadable paths."""
    with open(path) as f:
        rec = json.load(f)
    return MachineProfile.from_record(rec)


def _bw_dcn(bw: Optional[float], profile: Optional[MachineProfile]) -> float:
    """Resolve a DCN bandwidth: explicit bw > profile > static constant."""
    if bw is not None:
        return bw
    if profile is not None:
        return profile.bw_dcn
    return BW_100MBPS


def _bw_ici(bw: Optional[float], profile: Optional[MachineProfile]) -> float:
    """Resolve an ICI bandwidth: explicit bw > profile > static constant."""
    if bw is not None:
        return bw
    if profile is not None:
        return profile.bw_ici
    return BW_ICI_10GBPS


def route_measurement(
    profile: Optional[MachineProfile], label: str
) -> Optional[Dict[str, float]]:
    """The profile's fitted per-route row as a flat measurement fragment
    (``{"t_encode_s", "t_decode_s"}`` — the spelling the measurement-row
    plumbing uses), or None when the profile carries no row for `label`.
    This is the join point between calibrate()'s v2 `routes` table and the
    selectors' existing `measurements` parameter."""
    if profile is None:
        return None
    row = profile.routes.get(label)
    if row is None:
        return None
    return {
        "t_encode_s": float(row["t_enc_s"]),
        "t_decode_s": float(row["t_dec_s"]),
    }


def dense_measurement(d: int) -> Dict[str, float]:
    """The uncompressed f32 baseline row (zero codec compute)."""
    return {
        "payload_bytes": 4.0 * d,
        "rel_volume": 1.0,
        "t_encode_s": 0.0,
        "t_decode_s": 0.0,
    }


def exchange_time(
    m: Dict[str, float],
    bw: Optional[float] = None,
    *,
    profile: Optional[MachineProfile] = None,
) -> float:
    """Flat per-worker step-time model: injection bytes over the link plus
    one encode and one decode. Unchanged from the pre-r11 bench.py inline
    form; every historical BENCH_*.json speedup is computed with this.
    ``profile`` substitutes a calibrated link bandwidth for the 100 Mbps
    constant (an explicit bw still wins); None keeps the historical model."""
    return m["payload_bytes"] / _bw_dcn(bw, profile) + m["t_encode_s"] + m["t_decode_s"]


# ---------------------------------------------------------------------------
# W-aware ring collective wire times (per-worker seconds on one link).
#
# Standard ring costs for message of `size` bytes per worker:
#   all_gather      — each worker receives (W-1) remote payloads
#   all_to_all      — each worker sends/receives (W-1)/W of its buffer
#   psum/allreduce  — reduce-scatter + allgather: 2*(W-1)/W of the buffer
#   psum_scatter    — reduce-scatter half alone: (W-1)/W of the buffer
# ---------------------------------------------------------------------------


def allgather_time(payload_bytes: float, W: int, bw: float = BW_100MBPS) -> float:
    return (W - 1) * payload_bytes / bw


def all_to_all_time(buffer_bytes: float, W: int, bw: float = BW_100MBPS) -> float:
    return (W - 1) / W * buffer_bytes / bw


def allreduce_time(buffer_bytes: float, W: int, bw: float = BW_100MBPS) -> float:
    return 2.0 * (W - 1) / W * buffer_bytes / bw


def reduce_scatter_time(buffer_bytes: float, W: int, bw: float = BW_100MBPS) -> float:
    return (W - 1) / W * buffer_bytes / bw


def fused_step_time(
    m: Dict[str, float],
    W: int,
    bw: Optional[float] = None,
    *,
    profile: Optional[MachineProfile] = None,
) -> float:
    """W-aware model of the fused gather-then-decode exchange: one encode,
    an allgather of the per-worker payload, then W payload decodes (own +
    W-1 remote). `m` is a flat measurement row (t_decode_s = one decode)."""
    return (
        m["t_encode_s"]
        + allgather_time(m["payload_bytes"], W, _bw_dcn(bw, profile))
        + W * m["t_decode_s"]
    )


def overlapped_step_time(
    m: Dict[str, float],
    W: int,
    bw: Optional[float] = None,
    compute_time: Optional[float] = None,
    *,
    profile: Optional[MachineProfile] = None,
) -> float:
    """Step-time model of the backprop-overlapped streaming schedule
    (``cfg.stream_exchange``): each bucket's allgather dispatches while
    backward compute for earlier layers is still running, so up to
    ``compute_time`` seconds of wire hide behind it and only the residual
    exposed tail ``max(0, wire - compute_time)`` is charged serially —
    encode and the W decodes still pay their serial cost. With
    ``compute_time=0`` this is exactly `fused_step_time` (nothing to hide
    behind), so the streamed model can never exceed the r09 pipelined
    schedule's. ``compute_time=None`` takes the profile's fitted per-step
    compute when one is given (else 0.0 — the historical model)."""
    if compute_time is None:
        compute_time = profile.compute_time_s if profile is not None else 0.0
    wire = allgather_time(m["payload_bytes"], W, _bw_dcn(bw, profile))
    exposed = max(0.0, wire - max(0.0, compute_time))
    return m["t_encode_s"] + exposed + W * m["t_decode_s"]


def overlap_fraction(
    m: Dict[str, float],
    W: int,
    bw: Optional[float] = None,
    compute_time: Optional[float] = None,
    *,
    profile: Optional[MachineProfile] = None,
) -> float:
    """Fraction of the allgather wire time hidden behind backward compute
    under the streaming schedule — the modeled counterpart of the measured
    `trace --overlap` report. 1.0 when there is no wire to expose."""
    if compute_time is None:
        compute_time = profile.compute_time_s if profile is not None else 0.0
    wire = allgather_time(m["payload_bytes"], W, _bw_dcn(bw, profile))
    if wire <= 0.0:
        return 1.0
    return min(wire, max(0.0, compute_time)) / wire


# ---------------------------------------------------------------------------
# Federated round-time model (fedsim): the paper's deployment setting is C
# client uplinks per round into one parameter server behind a shared ingest
# link. Client local compute runs in parallel across clients (it bounds the
# first arrival, not the total), so the round wall time is ingest-serialized:
# every live uplink's compressed payload crosses the server's link(s), plus
# the one S2C broadcast going out. This is what makes DeepReduce's uplink
# compression a *clients/sec* multiplier — the serving capacity of one
# server link scales inversely with per-client payload bytes.
# ---------------------------------------------------------------------------


def fed_round_time(
    uplink_bytes_per_client: float,
    clients: int,
    bw: float = BW_100MBPS,
    *,
    t_client_s: float = 0.0,
    downlink_bytes: float = 0.0,
    server_links: int = 1,
) -> float:
    """Wall seconds of one federated round at `clients` live uplinks.
    `t_client_s` is one client's local-train latency (paid once — clients
    compute concurrently); `server_links` models ingest parallelism."""
    wire = clients * uplink_bytes_per_client + downlink_bytes
    return t_client_s + wire / (bw * max(server_links, 1))


def fed_clients_per_sec(
    uplink_bytes_per_client: float,
    clients: int,
    bw: float = BW_100MBPS,
    *,
    t_client_s: float = 0.0,
    downlink_bytes: float = 0.0,
    server_links: int = 1,
) -> float:
    """Served clients per second at the modeled round time — the serving
    throughput the ROADMAP's million-user scenario is priced in."""
    t = fed_round_time(
        uplink_bytes_per_client,
        clients,
        bw,
        t_client_s=t_client_s,
        downlink_bytes=downlink_bytes,
        server_links=server_links,
    )
    return clients / max(t, 1e-12)


def expected_staleness(latency_probs=(1.0,)) -> float:
    """Mean staleness E[tau] of a latency distribution (unnormalized
    weights over tau = 0, 1, 2, ... — same spec `parse_latency` accepts)."""
    total = sum(latency_probs)
    if total <= 0:
        return 0.0
    return sum(i * p for i, p in enumerate(latency_probs)) / total


def fed_async_apply_time(
    uplink_bytes_per_client: float,
    k: int,
    bw: float = BW_100MBPS,
    *,
    t_client_s: float = 0.0,
    downlink_bytes: float = 0.0,
    server_links: int = 1,
    overlap_depth: int = 1,
    latency_probs=(1.0,),
) -> float:
    """Wall seconds between buffered server applies in the asynchronous
    (FedBuff-style) mode. Two pipelined limits, the slower of which gates
    the apply cadence:

    - *ingest*: K live uplinks (plus the one S2C broadcast of the apply)
      must cross the server link(s) — identical shape to the synchronous
      wire term, but sized by the buffer threshold K instead of the cohort.
    - *compute*: clients of up to `overlap_depth` in-flight cohorts train
      concurrently against ring versions of the model, so the K-th delta
      arrives after one client latency *stretched by the mean staleness*
      (a tau-stale cohort started tau applies ago) and *divided by the
      overlap depth* (deeper overlap keeps more deltas perpetually in
      flight — the whole point of leaving rounds for a stream).

    Unlike `fed_round_time`, the client latency is NOT additive with the
    wire: overlapped cohorts hide compute behind ingest, which is exactly
    why the async apply time can beat the synchronous round at equal K."""
    wire = (k * uplink_bytes_per_client + downlink_bytes) / (
        bw * max(server_links, 1)
    )
    depth = max(int(overlap_depth), 1)
    compute = t_client_s * (1.0 + expected_staleness(latency_probs)) / depth
    return max(wire, compute)


def fed_async_clients_per_sec(
    uplink_bytes_per_client: float,
    k: int,
    bw: float = BW_100MBPS,
    *,
    t_client_s: float = 0.0,
    downlink_bytes: float = 0.0,
    server_links: int = 1,
    overlap_depth: int = 1,
    latency_probs=(1.0,),
) -> float:
    """Served clients per second of the buffered async stream: K
    contributions are absorbed per apply period. With zero client latency
    this upper-bounds `fed_clients_per_sec` (the sync round pays the same
    wire per client, serialized behind the cohort barrier); with a real
    latency distribution the gap is the hidden `t_client_s` term."""
    t = fed_async_apply_time(
        uplink_bytes_per_client,
        k,
        bw,
        t_client_s=t_client_s,
        downlink_bytes=downlink_bytes,
        server_links=server_links,
        overlap_depth=overlap_depth,
        latency_probs=latency_probs,
    )
    return k / max(t, 1e-12)


def _per_tenant(val, tenants: int, name: str) -> list:
    """Broadcast a scalar (or length-1 sequence) to `tenants` entries, or
    validate a per-tenant sequence's length — the costmodel mirror of
    `parse_tenant_floats` for already-typed values."""
    if isinstance(val, (list, tuple)):
        vals = list(val)
        if len(vals) == 1:
            vals = vals * tenants
        if len(vals) != tenants:
            raise ValueError(
                f"{name}: got {len(vals)} per-tenant values for a "
                f"{tenants}-tenant fleet — give 1 (broadcast) or exactly "
                f"{tenants}"
            )
        return vals
    return [val] * tenants


def fed_mt_clients_per_sec(
    tenants: int,
    uplink_bytes_per_client,
    cohort_or_k,
    bw: float = BW_100MBPS,
    *,
    asynchronous: bool = False,
    t_client_s=0.0,
    downlink_bytes=0.0,
    server_links: int = 1,
    overlap_depth=1,
    latency_probs=(1.0,),
) -> float:
    """Aggregate served clients per second of a T-tenant fleet multiplexed
    through ONE server (the multi-tenant tick): every tenant's wire crosses
    the same shared ingest link(s) — wire terms SUM across tenants — while
    client compute runs concurrently across populations — compute terms
    take the fleet MAX. Per-tenant heterogeneity rides as sequences (scalar
    broadcasts), mirroring the fed_mt_* config knobs.

    T=1 collapses EXACTLY (same float expressions, bitwise) to
    `fed_clients_per_sec` (synchronous) / `fed_async_clients_per_sec`
    (asynchronous) — the costmodel half of the T=1 degeneracy contract —
    and the aggregate rate is nondecreasing in T for identical tenants
    (amortizing the fixed compute term is the whole point; once the shared
    link saturates the rate plateaus at link capacity, never drops)."""
    T = int(tenants)
    if T < 1:
        raise ValueError(f"tenants must be >= 1, got {tenants}")
    up = _per_tenant(uplink_bytes_per_client, T, "uplink_bytes_per_client")
    n = _per_tenant(cohort_or_k, T, "cohort_or_k")
    dl = _per_tenant(downlink_bytes, T, "downlink_bytes")
    tc = _per_tenant(t_client_s, T, "t_client_s")
    links = bw * max(server_links, 1)
    if not asynchronous:
        # synchronous rounds: one shared link serializes every tenant's
        # cohort ingest + broadcast; cohorts train concurrently so the
        # fleet pays the slowest tenant's client latency once
        wire = sum(c * u + d for c, u, d in zip(n, up, dl))
        t = max(tc) + wire / links
        return sum(n) / max(t, 1e-12)
    depth = _per_tenant(overlap_depth, T, "overlap_depth")
    probs = (
        list(latency_probs)
        if latency_probs and isinstance(latency_probs[0], (list, tuple))
        else [latency_probs] * T
    )
    if len(probs) == 1:
        probs = probs * T
    if len(probs) != T:
        raise ValueError(
            f"latency_probs: got {len(probs)} per-tenant rows for a "
            f"{T}-tenant fleet — give 1 (broadcast) or exactly {T}"
        )
    # buffered async: the fleet's apply cadence is gated by total ingest
    # across tenants vs. the slowest tenant's overlapped compute
    wire = sum(k * u + d for k, u, d in zip(n, up, dl)) / links
    compute = max(
        t * (1.0 + expected_staleness(p)) / max(int(dp), 1)
        for t, p, dp in zip(tc, probs, depth)
    )
    period = max(wire, compute)
    return sum(n) / max(period, 1e-12)


# ---------------------------------------------------------------------------
# Heterogeneous population pricing (the compute-class axis of a
# PopulationSpec). Classes differ in local-step multipliers and latency
# rows; the serving model prices both as population-weighted expectations.
# A uniform population (every multiplier 1.0, no per-class rows) collapses
# EXACTLY — same float expressions — to the population-free estimators,
# the costmodel half of the bitwise IID-degeneracy contract.
# ---------------------------------------------------------------------------


def pop_compute_factor(weights, local_steps_mults) -> float:
    """Population-weighted local-compute stretch: ``Σ w̄_k · mult_k`` over
    the classes, the factor one client's expected local-train latency
    grows by when compute classes are heterogeneous. Returns the EXACT
    literal 1.0 when every multiplier is 1.0 (no ``Σ w̄_k`` rounding), so
    a uniform population prices bitwise like no population at all."""
    if len(weights) != len(local_steps_mults):
        raise ValueError(
            f"pop_compute_factor: {len(weights)} class weights vs "
            f"{len(local_steps_mults)} local-step multipliers"
        )
    if not weights:
        raise ValueError("pop_compute_factor: need at least one class")
    if all(float(m) == 1.0 for m in local_steps_mults):
        return 1.0
    total = float(sum(weights))
    if total <= 0.0:
        raise ValueError(f"pop_compute_factor: weights sum to {total}")
    return sum(
        float(w) / total * float(m)
        for w, m in zip(weights, local_steps_mults)
    )


def pop_expected_staleness(weights, class_latency_rows) -> float:
    """Mixture mean staleness of a heterogeneous population: the
    class-weighted expectation of each class's `expected_staleness` —
    what E[tau] becomes when the latency distribution is per-class."""
    if len(weights) != len(class_latency_rows):
        raise ValueError(
            f"pop_expected_staleness: {len(weights)} class weights vs "
            f"{len(class_latency_rows)} latency rows"
        )
    if not weights:
        raise ValueError("pop_expected_staleness: need at least one class")
    total = float(sum(weights))
    if total <= 0.0:
        raise ValueError(f"pop_expected_staleness: weights sum to {total}")
    return sum(
        float(w) / total * expected_staleness(row)
        for w, row in zip(weights, class_latency_rows)
    )


def fed_pop_clients_per_sec(
    uplink_bytes_per_client: float,
    clients: int,
    bw: float = BW_100MBPS,
    *,
    weights=(1.0,),
    local_steps_mults=(1.0,),
    t_client_s: float = 0.0,
    downlink_bytes: float = 0.0,
    server_links: int = 1,
) -> float:
    """Population-aware synchronous serving throughput: the cohort barrier
    waits for the SLOWEST compute class's clients, priced as the weighted
    compute stretch on `t_client_s`. Delegates to `fed_clients_per_sec`,
    so a uniform population collapses exactly."""
    factor = pop_compute_factor(weights, local_steps_mults)
    t = t_client_s if factor == 1.0 else t_client_s * factor
    return fed_clients_per_sec(
        uplink_bytes_per_client,
        clients,
        bw,
        t_client_s=t,
        downlink_bytes=downlink_bytes,
        server_links=server_links,
    )


def fed_pop_async_clients_per_sec(
    uplink_bytes_per_client: float,
    k: int,
    bw: float = BW_100MBPS,
    *,
    weights=(1.0,),
    local_steps_mults=(1.0,),
    class_latency_rows=None,
    t_client_s: float = 0.0,
    downlink_bytes: float = 0.0,
    server_links: int = 1,
    overlap_depth: int = 1,
    latency_probs=(1.0,),
) -> float:
    """Population-aware buffered-async serving throughput: compute classes
    stretch the client latency by the weighted factor, and per-class
    latency rows (when given) replace E[tau] with the mixture expectation.
    With `class_latency_rows=None` and unit multipliers this IS
    `fed_async_clients_per_sec` (exact delegation — the collapse half of
    the degeneracy contract)."""
    factor = pop_compute_factor(weights, local_steps_mults)
    t = t_client_s if factor == 1.0 else t_client_s * factor
    if class_latency_rows is None:
        return fed_async_clients_per_sec(
            uplink_bytes_per_client,
            k,
            bw,
            t_client_s=t,
            downlink_bytes=downlink_bytes,
            server_links=server_links,
            overlap_depth=overlap_depth,
            latency_probs=latency_probs,
        )
    wire = (k * uplink_bytes_per_client + downlink_bytes) / (
        bw * max(server_links, 1)
    )
    depth = max(int(overlap_depth), 1)
    compute = (
        t * (1.0 + pop_expected_staleness(weights, class_latency_rows))
        / depth
    )
    return k / max(max(wire, compute), 1e-12)


# ---------------------------------------------------------------------------
# Per-rs_mode static wire accounting. These return the per-worker
# *injection* bytes of every collective the route issues — the same
# numbers GradientExchanger.payload_bytes() reports and the
# jx-wire-accounting "collective" rule pins against the traced jaxpr.
# ---------------------------------------------------------------------------


def _shard_size(d: int, W: int) -> int:
    return (d + W - 1) // W


def _send_budget(d: int, ratio: float, W: int, headroom: float) -> int:
    k = max(1, int(d * ratio))
    return max(1, int(math.ceil(k / W * headroom)))


def _out_budget(d: int, ratio: float, W: int, out_headroom: float) -> int:
    k = max(1, int(d * ratio))
    return min(max(1, int(math.ceil(k / W * out_headroom))), _shard_size(d, W))


def _oktopk_budget(d: int, ratio: float, W: int, cap_headroom: float) -> int:
    """Host-side mirror of sparse_rs.oktopk_send_budget: the global
    threshold targets ~k survivors TOTAL, so expected per-(worker, shard)
    occupancy is k/W² — W× below the sparse route's k/W."""
    k = max(1, int(d * ratio))
    return max(1, int(math.ceil(k / (W * W) * cap_headroom)))


def sketch_cols(d: int, ratio: float, rows: int, cols: int = 0) -> int:
    """Resolved sketch width: explicit `cols` wins; 0 auto-sizes to ~2k/rows
    buckets (constant expected load factor ~1/2 per row) with a floor that
    keeps tiny problems from degenerating."""
    if cols > 0:
        return int(cols)
    k = max(1, int(d * ratio))
    return max(256, int(math.ceil(2.0 * k / max(1, rows))))


def quantized_padded_len(d: int, W: int, block: int) -> int:
    """Length after padding d up to a multiple of W*block so every worker's
    shard is whole blocks."""
    chunk = W * block
    return ((d + chunk - 1) // chunk) * chunk


def adaptive_lane_count(d: int, ratio: float, W: int, out_headroom: float, block: int) -> int:
    """f32 lanes in the adaptive phase-2 row (excluding the +1 flag lane):
    max of the sparse encoding (2 lanes/entry) and the int8-block dense
    shard encoding (levels bitcast into f32 lanes + one f32 norm/block)."""
    S = _shard_size(d, W)
    Sp = ((S + block - 1) // block) * block
    dense_lanes = Sp // 4 + Sp // block
    sparse_lanes = 2 * _out_budget(d, ratio, W, out_headroom)
    return max(sparse_lanes, dense_lanes)


def rs_wire_bytes(
    mode: str,
    d: int,
    W: int,
    ratio: float,
    *,
    headroom: float = 2.0,
    out_headroom: float = 1.0,
    block: int = 256,
    rows: int = 5,
    cols: int = 0,
    bins: int = 4096,
    cap_headroom: float = 2.0,
    masked: bool = False,
) -> Dict[str, float]:
    """Per-collective injection bytes for one sparse_rs route. Keys are the
    collective primitive names the route traces; values are the operand
    bytes one worker contributes to that collective.

    `masked` prices the live-mask-aware re-ownership variants
    (sparse_rs.owner_permutation): the sparse/oktopk wire layout is
    unchanged (global indices ride the same lanes local ones did), but the
    quantized route adds one int8 all_gather of the summed [Ssh] shard so
    deputies can dequantize the shards they serve."""
    B = _send_budget(d, ratio, W, headroom)
    K2 = _out_budget(d, ratio, W, out_headroom)
    if mode == "sparse":
        return {"all_to_all": W * B * 8.0, "all_gather": K2 * 8.0}
    if mode == "adaptive":
        L = adaptive_lane_count(d, ratio, W, out_headroom, block)
        return {"all_to_all": W * B * 8.0, "all_gather": (L + 1) * 4.0}
    if mode == "quantized":
        n = quantized_padded_len(d, W, block)
        extra = (n // W) * 1.0 if masked else 0.0
        return {
            "pmax": (n // block) * 4.0,
            "psum_scatter": n * 1.0,
            "all_gather": K2 * 8.0 + extra,
        }
    if mode == "sketch":
        C = sketch_cols(d, ratio, rows, cols)
        return {"psum": rows * C * 4.0, "all_gather": K2 * 8.0}
    if mode == "oktopk":
        Bo = _oktopk_budget(d, ratio, W, cap_headroom)
        return {
            "psum": bins * 4.0,
            "all_to_all": W * Bo * 8.0,
            "all_gather": K2 * 8.0,
        }
    raise ValueError(f"unknown rs_mode {mode!r}")


def rs_payload_bytes(mode: str, d: int, W: int, ratio: float, **kw) -> float:
    """Total per-worker injection bytes for one route (sum over its
    collectives) — the number payload_bytes()/jx-wire-accounting pin."""
    return float(sum(rs_wire_bytes(mode, d, W, ratio, **kw).values()))


def peak_hbm_bytes(
    route: str,
    d: int,
    W: int,
    *,
    residual: bool = True,
    dtype_bytes: int = 4,
) -> int:
    """Modeled peak live bytes of one audited exchange trace — the number
    the liveness interpreter (analysis/liveness.py) computes and
    jx-peak-bytes commits as the trace's byte budget.

    The audit harness stacks every per-worker operand to ``[W, d]``, so the
    peak is dominated by the stacked gradient (and, with error-feedback
    residuals, the stacked residual bank). What rides on top at the peak
    differs per route:

    - ``fused``: the dense per-worker view sliced out of the stack is still
      live when the peak lands, plus the i32 step counter;
    - ``oktopk``: same dense view (no residual bank — the in-collective
      sparse_rs routes are memory='none');
    - ``bucketed``: per-bucket views die bucket-by-bucket before the peak,
      leaving only encode scratch that is O(payload), not O(d) — modeled
      as zero here, so the estimate is a tight floor.

    tests/test_liveness.py cross-checks these predictions against the
    static analyzer on the committed fused/bucketed/oktopk traces: model,
    trace, and budget cannot drift apart (the jx-wire-accounting contract,
    applied to HBM).
    """
    if route not in ("fused", "bucketed", "oktopk"):
        raise ValueError(f"unknown peak route {route!r}")
    banks = 2 if residual else 1
    stacked = banks * dtype_bytes * W * d
    if route == "fused":
        return stacked + dtype_bytes * d + dtype_bytes
    if route == "oktopk":
        return stacked + dtype_bytes * d
    return stacked


_RING_TIME = {
    "all_gather": allgather_time,
    "all_to_all": all_to_all_time,
    "psum": allreduce_time,
    "pmax": allreduce_time,
    "psum_scatter": reduce_scatter_time,
}


def rs_step_time(
    mode: str,
    d: int,
    W: int,
    ratio: float,
    *,
    t_compute_s: float = 0.0,
    bw: Optional[float] = None,
    compute_time: float = 0.0,
    profile: Optional[MachineProfile] = None,
    **kw,
) -> float:
    """W-aware modeled step time of one in-collective route: ring wire time
    of each collective it issues plus its (once-per-worker) compute.
    ``compute_time`` is backward-pass compute available to hide wire behind
    (the streaming-overlap discipline); 0 keeps the historical serialized
    model byte-for-byte. ``profile`` supplies a calibrated link bandwidth
    when no explicit bw is given."""
    bw = _bw_dcn(bw, profile)
    wire = 0.0
    for prim, size in rs_wire_bytes(mode, d, W, ratio, **kw).items():
        wire += _RING_TIME[prim](size, W, bw)
    wire = max(0.0, wire - max(0.0, compute_time))
    return wire + t_compute_s


def _rs_kw(kw: Dict) -> Dict:
    """Filter **kw down to the keys rs_wire_bytes understands."""
    keep = ("headroom", "out_headroom", "block", "rows", "cols",
            "bins", "cap_headroom", "masked")
    return {k: kw[k] for k in keep if k in kw}


def select_rs_mode(
    d: int,
    W: int,
    ratio: float,
    *,
    headroom: float = 2.0,
    out_headroom: float = 1.0,
    block: int = 256,
    rows: int = 5,
    cols: int = 0,
    bins: int = 4096,
    cap_headroom: float = 2.0,
    bw: Optional[float] = None,
    modes: Optional[tuple] = None,
    measurements: Optional[Dict[str, Dict[str, float]]] = None,
    compute_time: float = 0.0,
    profile: Optional[MachineProfile] = None,
) -> str:
    """Resolve ``rs_mode="auto"`` at construction time: argmin of the
    W-aware model over the concrete routes. At the 100 Mbps default link
    the step is wire-dominated and, with no measured rows, compute terms
    are excluded — the selector is deterministic from (d, W, ratio) and
    static config alone. ``measurements[mode]`` rows (t_encode_s/t_decode_s
    per route, the bench measurement convention) charge each candidate its
    measured codec compute; when absent, a ``profile`` with fitted
    per-route `routes` rows fills them in, so a calibrated profile
    re-ranks the routes on measured encode/decode, not just bandwidth (a
    bandwidth-only v1 profile still can never flip this argmin — every rs
    route's wire scales as 1/bw). ``compute_time`` (hideable backward
    compute, see `overlapped_step_time`) threads through to each
    candidate's `rs_step_time`; the default 0 keeps the historical
    selection."""
    candidates = modes or ("sparse", "adaptive", "quantized", "sketch", "oktopk")
    best, best_t = None, float("inf")
    for mode in candidates:
        m = (measurements or {}).get(mode) or route_measurement(profile, mode)
        tc = (m["t_encode_s"] + m["t_decode_s"]) if m else 0.0
        t = rs_step_time(
            mode, d, W, ratio,
            t_compute_s=tc,
            headroom=headroom, out_headroom=out_headroom,
            block=block, rows=rows, cols=cols,
            bins=bins, cap_headroom=cap_headroom, bw=bw,
            compute_time=compute_time, profile=profile,
        )
        if t < best_t:
            best, best_t = mode, t
    return best


# ---------------------------------------------------------------------------
# Two-tier (ICI x DCN) hierarchical model.
#
# A multi-slice mesh has two links with a ~100x bandwidth gap: the
# intra-slice ICI fabric and the cross-slice DCN. The hierarchical
# exchange reduces densely (or int8-quantized) over the fast axis first,
# then runs one of the flat compressed exchanges across slices only.
# Modeled step time is the SUM of the two legs — the slice mean must
# complete before the DCN leg can start, so the legs serialize.
# ---------------------------------------------------------------------------

# 10 Gbps in bytes/s — a deliberately conservative stand-in for the
# intra-slice fabric (real ICI is far faster; the planner only needs the
# order-of-magnitude gap against the 100 Mbps DCN default).
BW_ICI_10GBPS = 1.25e9

HIER_ICI_LEGS = ("dense", "qar")
HIER_DCN_LEGS = ("fused", "bucketed", "sparse", "adaptive", "quantized", "sketch")


def qar_wire_bytes_per_worker(d: int, W: int, block: int = 512) -> float:
    """Per-worker wire bytes of the int8 two-phase quantized allreduce.

    Mirrors ``qar.wire_bits_per_worker`` (kept numerically identical by
    tests/test_hierarchical.py) without importing jax: two tiled
    all_to_all phases of int8 levels plus two all_gathers of f32 bucket
    norms, each moving the (W-1)/W ring fraction."""
    n = quantized_padded_len(d, W, block)
    bits = 2.0 * (n * 8 + (n // block) * 32) * (W - 1) / W
    return bits / 8.0


def hier_ici_time(
    leg: str, d: int, per_slice: int, bw_ici: Optional[float] = None,
    *, block: int = 512, profile: Optional[MachineProfile] = None,
) -> float:
    """Modeled ICI-leg time: dense f32 psum or int8 quantized allreduce
    over the `per_slice` devices of one slice."""
    bw_ici = _bw_ici(bw_ici, profile)
    if per_slice <= 1:
        return 0.0
    if leg == "dense":
        return allreduce_time(4.0 * d, per_slice, bw_ici)
    if leg == "qar":
        return qar_wire_bytes_per_worker(d, per_slice, block) / bw_ici
    raise ValueError(f"unknown ici leg {leg!r} (expected one of {HIER_ICI_LEGS})")


def hier_dcn_time(
    leg: str,
    d: int,
    n_slices: int,
    ratio: float,
    bw_dcn: Optional[float] = None,
    *,
    measurement: Optional[Dict[str, float]] = None,
    t_compute_s: float = 0.0,
    compute_time: float = 0.0,
    profile: Optional[MachineProfile] = None,
    **kw,
) -> float:
    """Modeled DCN-leg time with `n_slices` workers on the scarce link.

    "fused"/"bucketed" use the allgather model; without a measured codec
    row the payload defaults to 8 bytes/entry at k = d*ratio (the same
    value+index convention rs_wire_bytes uses). "bucketed" overlaps
    decode under the next bucket's gather, so it pays max(wire, decode)
    instead of their sum; with no measured compute the two tie and the
    planner's candidate order prefers plain "fused". ``compute_time`` is
    hideable backward compute (the streaming overlap, `overlapped_step_
    time`): it shaves every leg's wire before the formulas above, so the
    planner can price what streaming buys on the scarce link; 0 keeps the
    historical model. A ``profile`` supplies its calibrated bandwidth AND
    fills the measurement gaps with its fitted encode/decode seconds: a
    per-route `routes` row for the leg wins over the global t_enc/t_dec
    fallback on the fused/bucketed legs, and charges the rs legs one
    encode + one decode of codec compute — so a v2 profile can flip plans
    on ANY leg, not just the gather-then-decode family (explicit
    `measurement`/`t_compute_s` still win)."""
    bw_dcn = _bw_dcn(bw_dcn, profile)
    rm = route_measurement(profile, leg)
    if leg in ("fused", "bucketed"):
        if measurement is not None:
            m = measurement
        elif rm is not None:
            m = {"payload_bytes": 8.0 * max(1, int(d * ratio)), **rm}
        else:
            m = {
                "payload_bytes": 8.0 * max(1, int(d * ratio)),
                "t_encode_s": profile.t_enc_s if profile is not None else 0.0,
                "t_decode_s": profile.t_dec_s if profile is not None else 0.0,
            }
        wire = allgather_time(m["payload_bytes"], n_slices, bw_dcn)
        wire = max(0.0, wire - max(0.0, compute_time))
        if leg == "bucketed":
            return m["t_encode_s"] + max(wire, n_slices * m["t_decode_s"])
        return m["t_encode_s"] + wire + n_slices * m["t_decode_s"]
    if t_compute_s == 0.0 and rm is not None:
        t_compute_s = rm["t_encode_s"] + rm["t_decode_s"]
    return rs_step_time(
        leg, d, n_slices, ratio, t_compute_s=t_compute_s, bw=bw_dcn,
        compute_time=compute_time, **_rs_kw(kw)
    )


def hier_step_time(
    ici: str,
    dcn: str,
    d: int,
    n_slices: int,
    per_slice: int,
    ratio: float,
    *,
    bw_ici: Optional[float] = None,
    bw_dcn: Optional[float] = None,
    ici_block: int = 512,
    measurement: Optional[Dict[str, float]] = None,
    t_compute_s: float = 0.0,
    compute_time: float = 0.0,
    profile: Optional[MachineProfile] = None,
    **kw,
) -> float:
    """Modeled step time of one (ici, dcn) plan: serialized two-leg sum.
    ``compute_time`` (hideable backward compute) applies to the DCN leg
    only — the ICI leg runs after the slice mean and cannot stream."""
    return hier_ici_time(
        ici, d, per_slice, bw_ici, block=ici_block, profile=profile
    ) + hier_dcn_time(
        dcn, d, n_slices, ratio, bw_dcn,
        measurement=measurement, t_compute_s=t_compute_s,
        compute_time=compute_time, profile=profile, **kw,
    )


def stream_hier_step_time(
    dcn: str,
    d: int,
    n_slices: int,
    per_slice: int,
    ratio: float,
    *,
    bw_ici: Optional[float] = None,
    bw_dcn: Optional[float] = None,
    ici_block: int = 512,
    measurement: Optional[Dict[str, float]] = None,
    compute_time: float = 0.0,
    profile: Optional[MachineProfile] = None,
) -> float:
    """`overlapped_step_time` composed with the hierarchical two-leg model:
    the stream-over-hier schedule dispatches each bucket's dense ICI psum
    AND its compressed DCN gather from inside the bucket's backward hook,
    so hideable backward compute shaves the COMBINED wire of both legs —
    the barrier-scheduled `hier_step_time` can only hide the DCN leg.

    Defined for the composable stack only: dense ICI + the allgather
    family on DCN (`dcn` in {"fused", "bucketed"} — the config fences
    every other shape out of streaming). With ``compute_time=0`` the
    "fused" form is exactly ``hier_step_time("dense", "fused", ...)``
    (nothing to hide behind), and "bucketed" pays
    ``max(ici + dcn_wire, decode)`` ≤ the barrier schedule's
    ``ici + max(dcn_wire, decode)`` — so the composed model can never
    exceed the barrier-hier parent, and with compute it can never exceed
    what the same compute buys the flat streaming parent on a scarcer
    gather."""
    if dcn not in ("fused", "bucketed"):
        raise ValueError(
            f"stream_hier_step_time composes the allgather family only "
            f"(fused/bucketed), got dcn={dcn!r}"
        )
    rm = route_measurement(profile, dcn)
    if measurement is not None:
        m = measurement
    elif rm is not None:
        m = {"payload_bytes": 8.0 * max(1, int(d * ratio)), **rm}
    else:
        m = {
            "payload_bytes": 8.0 * max(1, int(d * ratio)),
            "t_encode_s": profile.t_enc_s if profile is not None else 0.0,
            "t_decode_s": profile.t_dec_s if profile is not None else 0.0,
        }
    ici_wire = hier_ici_time(
        "dense", d, per_slice, bw_ici, block=ici_block, profile=profile
    )
    dcn_wire = allgather_time(
        m["payload_bytes"], n_slices, _bw_dcn(bw_dcn, profile)
    )
    exposed = max(0.0, ici_wire + dcn_wire - max(0.0, compute_time))
    if dcn == "bucketed":
        return m["t_encode_s"] + max(exposed, n_slices * m["t_decode_s"])
    return m["t_encode_s"] + exposed + n_slices * m["t_decode_s"]


def select_hier_plan(
    d: int,
    n_slices: int,
    per_slice: int,
    ratio: float,
    bw_ici: Optional[float] = None,
    bw_dcn: Optional[float] = None,
    *,
    ici_block: int = 512,
    ici_legs: Optional[tuple] = None,
    dcn_legs: Optional[tuple] = None,
    measurements: Optional[Dict[str, Dict[str, float]]] = None,
    compute: Optional[Dict[str, float]] = None,
    compute_time: float = 0.0,
    stream: bool = False,
    profile: Optional[MachineProfile] = None,
    **kw,
) -> Dict:
    """Construction-time auto-planner: argmin of `hier_step_time` over
    {ici: dense|qar} x {dcn: fused|bucketed|rs modes}.

    Deterministic from static shapes and config alone, like
    `select_rs_mode`; bench.py --hier-sweep optionally supplies measured
    codec rows (`measurements[dcn_leg]` -> flat measurement dict) and
    per-route compute (`compute[dcn_leg]` seconds) so its report and the
    planner argmin over exactly the same numbers. A ``profile`` re-prices
    every candidate under the calibrated bandwidths and charges the fitted
    encode/decode seconds on the fused/bucketed legs (explicit bw_* and
    `measurements` rows still win) — this is the selector a fitted profile
    can actually flip.

    ``stream=True`` makes the planner overlap-aware: the composable
    candidates (dense ICI x fused/bucketed DCN — the only stack the
    config lets streaming wrap) are priced with `stream_hier_step_time`,
    where ``compute_time`` hides the combined ici+dcn wire instead of the
    dcn leg alone; every other candidate keeps the barrier model so the
    argmin compares what streaming actually buys. The default False keeps
    the historical table to the last float (the calib-reselect audit pins
    it).

    Returns {"ici", "dcn", "modeled_step_s", "table"} where table maps
    "ici+dcn" -> modeled seconds for every candidate pair."""
    ici_cands = ici_legs or HIER_ICI_LEGS
    dcn_cands = dcn_legs or HIER_DCN_LEGS
    table: Dict[str, float] = {}
    best = None
    for dcn in dcn_cands:
        m = (measurements or {}).get(dcn)
        tc = (compute or {}).get(dcn, 0.0)
        for ici in ici_cands:
            if stream and ici == "dense" and dcn in ("fused", "bucketed"):
                t = stream_hier_step_time(
                    dcn, d, n_slices, per_slice, ratio,
                    bw_ici=bw_ici, bw_dcn=bw_dcn, ici_block=ici_block,
                    measurement=m, compute_time=compute_time,
                    profile=profile,
                )
            else:
                t = hier_step_time(
                    ici, dcn, d, n_slices, per_slice, ratio,
                    bw_ici=bw_ici, bw_dcn=bw_dcn, ici_block=ici_block,
                    measurement=m, t_compute_s=tc, compute_time=compute_time,
                    profile=profile, **kw,
                )
            table[f"{ici}+{dcn}"] = t
            if best is None or t < table[f"{best[0]}+{best[1]}"]:
                best = (ici, dcn)
    return {
        "ici": best[0],
        "dcn": best[1],
        "modeled_step_s": table[f"{best[0]}+{best[1]}"],
        "table": table,
    }


# ---------------------------------------------------------------------------
# Calibration: fit a MachineProfile from a tracking run directory.
#
# The fit joins three telemetry artifacts every `--telemetry` run writes:
#   trace.json    — host-side span X-events. Spans wrapping traced code fire
#                   ONCE PER TRACE (their durations are trace-time, inflated
#                   ~10x over a compiled step), while the driver's
#                   `train/step` span fires every step with real wall time.
#   summary.json  — the on-device accumulators' derived rows, including the
#                   per-axis wire counters `dcn_bytes_per_step` /
#                   `ici_bytes_per_step`.
#   metrics.jsonl — per-step records; consecutive `ts` deltas are the step-
#                   time fallback when the run has no train/step spans.
#
# The decomposition is share-based: per-span-name SELF time (container time
# minus children — streaming runs nest the exchange spans inside
# train/forward_backward) is bucketed into encode / decode / DCN-wire /
# ICI-wire / compute / other categories, and each category's share of the
# trace-time pool is apportioned against the measured (warmup-dropped) mean
# step time. The identifiability assumption this rests on: RELATIVE span
# durations at trace time track relative durations at run time — trace-time
# inflation cancels in the shares. Predicted step time is the sum of the
# apportioned components, so the fit reproduces the measured step time
# exactly by construction; the CLI tolerance gate checks the round trip
# through the model formulas (bw inverted, then wire recomputed).
#
# Parameters a run cannot identify (no decode spans, zero ICI bytes, ...)
# are held at the static constants and listed under `fixed` — a profile is
# honest about what it measured.
# ---------------------------------------------------------------------------

# leaf spans charged to each model parameter. Everything not listed (and
# not excluded) lands in the residual "other" component, which calibrate()
# carries through so the decomposition stays exact.
CAL_ENCODE_SPANS = frozenset({
    "exchange/encode", "exchange/pack",
    "sparse_rs/select", "sparse_rs/quantize", "sparse_rs/adaptive-quantize",
    "sparse_rs/sketch",
})
CAL_DECODE_SPANS = frozenset({
    "exchange/decode", "sparse_rs/unsketch", "sparse_rs/reduce",
})
CAL_WIRE_DCN_SPANS = frozenset({
    "exchange/allgather", "exchange/ring", "exchange/qar",
    "sparse_rs/route", "sparse_rs/allgather", "sparse_rs/psum",
    "sparse_rs/reduce-scatter", "sparse_rs/norm-pmax",
})
CAL_WIRE_ICI_SPANS = frozenset({"exchange/ici"})
CAL_COMPUTE_SPANS = frozenset({"train/forward_backward"})
# spans that are not per-step work at all: the driver's step timer (it is
# the measurement target, not a component) and the one-time program build
CAL_EXCLUDED_SPANS = frozenset({"train/step", "train/build"})


def drop_warmup(xs: Sequence[float], k: float = 4.0) -> List[float]:
    """Strip the leading run of compile-skewed samples: with the median of
    the trailing half as the steady-state scale, drop leading samples more
    than `k` times it. Robust to MULTIPLE warmup steps (a telemetry run
    compiles once per distinct program — streaming runs show two) where a
    drop-first-only policy is not. Always keeps at least one sample."""
    xs = list(xs)
    if len(xs) <= 1:
        return xs
    tail = sorted(xs[len(xs) // 2:])
    ref = tail[len(tail) // 2]
    i = 0
    while i < len(xs) - 1 and xs[i] > k * ref:
        i += 1
    return xs[i:]


def _span_route(e: Dict[str, Any]) -> str:
    """The event's route attribution ("" when unlabeled)."""
    args = e.get("args")
    if isinstance(args, dict) and isinstance(args.get("route"), str):
        return args["route"]
    return ""


def span_self_times_by_route(events) -> Dict[Tuple[str, str], float]:
    """Per-(span-name, route) SELF time in seconds from Chrome-trace "X"
    events: each span's duration minus its direct children's, computed with
    a per-(pid, tid) interval stack — so a container like
    train/forward_backward is not double-charged for the exchange spans a
    streaming run nests inside it, and a wire span nested inside a labeled
    encode span keeps its time out of that route's encode row. Unlabeled
    spans key under route ""."""
    by_tid: Dict[Any, List[Tuple[float, float, str, str]]] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        ts, dur = e.get("ts"), e.get("dur")
        if not isinstance(ts, (int, float)) or not isinstance(dur, (int, float)):
            continue
        key = (e.get("pid"), e.get("tid"))
        by_tid.setdefault(key, []).append(
            (float(ts), float(dur), str(e.get("name", "")), _span_route(e))
        )
    self_us: Dict[Tuple[str, str], float] = {}
    for evs in by_tid.values():
        # parents sort before children: earlier start first, longer first on
        # ties (a child can share its parent's start timestamp)
        evs.sort(key=lambda t: (t[0], -t[1]))
        stack: List[Tuple[float, Tuple[str, str]]] = []  # (end_ts, key)
        for ts, dur, name, route in evs:
            while stack and ts >= stack[-1][0]:
                stack.pop()
            k = (name, route)
            self_us[k] = self_us.get(k, 0.0) + dur
            if stack:
                parent = stack[-1][1]
                self_us[parent] = self_us.get(parent, 0.0) - dur
            stack.append((ts + dur, k))
    return {k: us * 1e-6 for k, us in self_us.items()}


def span_self_times(events) -> Dict[str, float]:
    """Per-span-name SELF time in seconds — `span_self_times_by_route`
    aggregated over the route attribution (the pre-v2 view; adding route
    labels to spans cannot change these totals)."""
    out: Dict[str, float] = {}
    for (name, _route), s in span_self_times_by_route(events).items():
        out[name] = out.get(name, 0.0) + s
    return out


def _read_json(path: pathlib.Path) -> Dict[str, Any]:
    if not path.exists():
        return {}
    with open(path) as f:
        return json.load(f)


def calibrate(
    run_dir, *, include_warmup: bool = False, warmup_k: float = 4.0
) -> MachineProfile:
    """Fit a MachineProfile from one tracking run dir (see the section
    comment above for the decomposition). Deterministic: the profile is a
    pure function of the run dir's committed files — no wall clock enters
    the record, so re-running on a committed run dir is bitwise stable.
    Raises ValueError when the run lacks the telemetry the fit needs."""
    run = pathlib.Path(run_dir)
    cfg_rec = _read_json(run / "config.json")
    if not cfg_rec:
        raise ValueError(f"{run}: no config.json — not a tracking run dir")
    config = cfg_rec.get("config", {}) or {}
    W = int(config.get("workers", cfg_rec.get("workers", 1)) or 1)
    trace = _read_json(run / "trace.json")
    events = [e for e in trace.get("traceEvents", []) if e.get("ph") == "X"]
    if not events:
        raise ValueError(
            f"{run}: no span trace (trace.json) — re-run with --telemetry "
            "to record the spans the fit decomposes"
        )
    routed_s = span_self_times_by_route(events)
    self_s: Dict[str, float] = {}
    for (name, _route), s in routed_s.items():
        self_s[name] = self_s.get(name, 0.0) + s

    # --- measured step time: train/step spans, else metrics.jsonl ts ---- #
    step_durs = sorted(
        (float(e["ts"]), float(e["dur"]) * 1e-6)
        for e in events
        if e.get("name") == "train/step"
        and isinstance(e.get("ts"), (int, float))
        and isinstance(e.get("dur"), (int, float))
    )
    samples = [dur for _, dur in step_durs]
    step_source = "train/step spans"
    if not samples:
        ts: List[float] = []
        mpath = run / "metrics.jsonl"
        if mpath.exists():
            with open(mpath) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rec = json.loads(line)
                        if isinstance(rec.get("ts"), (int, float)):
                            ts.append(float(rec["ts"]))
        samples = [b - a for a, b in zip(ts, ts[1:]) if b >= a]
        step_source = "metrics.jsonl ts deltas"
    if not samples:
        raise ValueError(
            f"{run}: no step-time samples (no train/step spans and no "
            "metrics.jsonl timestamps)"
        )
    n_total = len(samples)
    kept = samples if include_warmup else drop_warmup(samples, k=warmup_k)
    if len(kept) < 4:
        raise ValueError(
            f"{run}: too few step-time samples to fit from — the run has "
            f"{n_total} sample(s), {len(kept)} left after the warmup drop; "
            "the share-based fit needs >= 4 post-warmup samples "
            "(re-run with more steps)"
        )
    T = sum(kept) / len(kept)
    if T <= 0.0:
        raise ValueError(f"{run}: measured mean step time is not positive")

    # --- trace-time shares -> apportioned per-step component seconds ---- #
    pool = {
        name: s
        for name, s in self_s.items()
        if name not in CAL_EXCLUDED_SPANS and s > 0.0
    }
    total_tr = sum(pool.values())

    def _cat(names) -> float:
        return sum(s for n, s in pool.items() if n in names)

    enc_tr = _cat(CAL_ENCODE_SPANS)
    dec_tr = _cat(CAL_DECODE_SPANS)
    wdcn_tr = _cat(CAL_WIRE_DCN_SPANS)
    wici_tr = _cat(CAL_WIRE_ICI_SPANS)
    comp_tr = _cat(CAL_COMPUTE_SPANS)
    other_tr = total_tr - (enc_tr + dec_tr + wdcn_tr + wici_tr + comp_tr)
    scale = T / total_tr if total_tr > 0.0 else 0.0
    enc_s, dec_s = enc_tr * scale, dec_tr * scale
    wdcn_s, wici_s = wdcn_tr * scale, wici_tr * scale
    comp_s, other_s = comp_tr * scale, other_tr * scale

    # --- per-route encode/decode rows (the v2 `routes` table) ----------- #
    # labeled encode/decode self-time buckets per route BEFORE the share
    # fit; the same trace-time -> step-time scale apportions each bucket,
    # so the route rows sum (up to the decode-multiplicity convention) to
    # the global enc_s/dec_s they were split out of.
    enc_tr_route: Dict[str, float] = {}
    dec_tr_route: Dict[str, float] = {}
    for (name, route), s in routed_s.items():
        if not route or s <= 0.0:
            continue
        if name in CAL_ENCODE_SPANS:
            enc_tr_route[route] = enc_tr_route.get(route, 0.0) + s
        elif name in CAL_DECODE_SPANS:
            dec_tr_route[route] = dec_tr_route.get(route, 0.0) + s
    route_samples: Dict[str, int] = {}
    for e in events:
        nm, route = str(e.get("name", "")), _span_route(e)
        if route and (nm in CAL_ENCODE_SPANS or nm in CAL_DECODE_SPANS):
            route_samples[route] = route_samples.get(route, 0) + 1
    routes: Dict[str, Dict[str, float]] = {}
    for label in sorted(set(enc_tr_route) | set(dec_tr_route)):
        routes[label] = {
            "t_enc_s": enc_tr_route.get(label, 0.0) * scale,
            "t_dec_s": dec_tr_route.get(label, 0.0)
            * scale
            / _route_decodes_per_step(label, W),
            "samples": route_samples.get(label, 1),
        }

    # --- wire counters (per-worker injection bytes per step) ------------ #
    telem = _read_json(run / "summary.json").get("telemetry") or {}
    dcn_bytes = float(telem.get("dcn_bytes_per_step", 0.0) or 0.0)
    ici_bytes = float(telem.get("ici_bytes_per_step", 0.0) or 0.0)

    # --- invert the model where identifiable, hold constants where not -- #
    fitted: List[str] = []
    fixed: List[str] = []
    t_enc = 0.0
    if enc_tr > 0.0:
        t_enc = enc_s
        fitted.append("t_enc")
    else:
        fixed.append("t_enc")
    t_dec = 0.0
    if dec_tr > 0.0:
        # the model charges W decodes per step (own + W-1 remote rows)
        t_dec = dec_s / W
        fitted.append("t_dec")
    else:
        fixed.append("t_dec")
    bw_dcn = BW_100MBPS
    if wdcn_tr > 0.0 and dcn_bytes > 0.0 and W > 1:
        # allgather ring: wire_s = (W-1) * injection_bytes / bw
        bw_dcn = (W - 1) * dcn_bytes / wdcn_s
        fitted.append("bw_dcn")
    else:
        fixed.append("bw_dcn")
    bw_ici = BW_ICI_10GBPS
    if wici_tr > 0.0 and ici_bytes > 0.0:
        bw_ici = ici_bytes / wici_s
        fitted.append("bw_ici")
    else:
        fixed.append("bw_ici")
    compute_time = 0.0
    if comp_tr > 0.0:
        compute_time = comp_s
        fitted.append("compute_time")
    else:
        fixed.append("compute_time")

    # round trip through the model formulas: fitted bandwidths re-price the
    # observed bytes, fixed components keep their apportioned seconds
    wire_dcn_pred = (
        allgather_time(dcn_bytes, W, bw_dcn) if "bw_dcn" in fitted else wdcn_s
    )
    wire_ici_pred = ici_bytes / bw_ici if "bw_ici" in fitted else wici_s
    predicted = (
        t_enc + wire_dcn_pred + wire_ici_pred + W * t_dec + compute_time + other_s
    )
    cfg_digest = hashlib.sha256(
        json.dumps(config, sort_keys=True).encode()
    ).hexdigest()[:16]
    source = {
        "run": run.name,
        "config_digest": cfg_digest,
        "workers": W,
        "step_time_source": step_source,
        "include_warmup": bool(include_warmup),
        "steps_total": n_total,
        "steps_measured": len(kept),
        "warmup_dropped": n_total - len(kept),
        "measured_step_s": T,
        "predicted_step_s": predicted,
        "encode_s": enc_s,
        "decode_s": dec_s,
        "wire_dcn_s": wdcn_s,
        "wire_ici_s": wici_s,
        "compute_s": comp_s,
        "other_s": other_s,
        "dcn_bytes_per_step": dcn_bytes,
        "ici_bytes_per_step": ici_bytes,
    }
    return MachineProfile(
        bw_dcn=bw_dcn,
        bw_ici=bw_ici,
        t_enc_s=t_enc,
        t_dec_s=t_dec,
        compute_time_s=compute_time,
        fitted=tuple(fitted),
        fixed=tuple(fixed),
        routes=routes,
        source=source,
    )
