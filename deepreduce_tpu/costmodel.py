"""Shared wire/compute cost model for exchange-mode selection and bench.

One implementation of the step-time model that bench.py previously
duplicated inline (`T = payload_bytes/BW + t_enc + t_dec` at a 100 Mbps
default link), plus the W-aware extensions the in-collective reduction
sweep and the ``rs_mode="auto"`` selector need.

Two families of estimators live here:

- **Flat (W-independent)** — `exchange_time(m, bw)`: the historical bench
  model. Payload bytes are the per-worker *injection* (what one worker
  puts on the wire), encode/decode measured once. This is what every
  committed BENCH_*.json before r11 reports; it stays byte-for-byte the
  same function so those numbers remain reproducible.
- **W-aware (ring)** — per-collective wire times from standard ring
  algorithm costs, and `fused_step_time` / `rs_step_time` which model what
  actually scales with W: the fused allgather path *receives* W-1 remote
  payloads and runs W decodes per step, while an in-collective route pays
  ~1x decode and ring-bounded wire. This is the model under which the
  ROADMAP target "beat drqsgd_bloom_* at W>=8" is meaningful at all — in
  the flat model W never appears.

Everything here is host-side pure python/float math: it runs at
construction time (mode selection) or in bench drivers, never under
trace.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

# 100 Mbps in bytes/s — the paper's federated uplink assumption, and the
# default link every committed bench record uses.
BW_100MBPS = 12.5e6

RS_MODES = ("sparse", "adaptive", "quantized", "sketch", "auto")


def dense_measurement(d: int) -> Dict[str, float]:
    """The uncompressed f32 baseline row (zero codec compute)."""
    return {
        "payload_bytes": 4.0 * d,
        "rel_volume": 1.0,
        "t_encode_s": 0.0,
        "t_decode_s": 0.0,
    }


def exchange_time(m: Dict[str, float], bw: float = BW_100MBPS) -> float:
    """Flat per-worker step-time model: injection bytes over the link plus
    one encode and one decode. Unchanged from the pre-r11 bench.py inline
    form; every historical BENCH_*.json speedup is computed with this."""
    return m["payload_bytes"] / bw + m["t_encode_s"] + m["t_decode_s"]


# ---------------------------------------------------------------------------
# W-aware ring collective wire times (per-worker seconds on one link).
#
# Standard ring costs for message of `size` bytes per worker:
#   all_gather      — each worker receives (W-1) remote payloads
#   all_to_all      — each worker sends/receives (W-1)/W of its buffer
#   psum/allreduce  — reduce-scatter + allgather: 2*(W-1)/W of the buffer
#   psum_scatter    — reduce-scatter half alone: (W-1)/W of the buffer
# ---------------------------------------------------------------------------


def allgather_time(payload_bytes: float, W: int, bw: float = BW_100MBPS) -> float:
    return (W - 1) * payload_bytes / bw


def all_to_all_time(buffer_bytes: float, W: int, bw: float = BW_100MBPS) -> float:
    return (W - 1) / W * buffer_bytes / bw


def allreduce_time(buffer_bytes: float, W: int, bw: float = BW_100MBPS) -> float:
    return 2.0 * (W - 1) / W * buffer_bytes / bw


def reduce_scatter_time(buffer_bytes: float, W: int, bw: float = BW_100MBPS) -> float:
    return (W - 1) / W * buffer_bytes / bw


def fused_step_time(
    m: Dict[str, float], W: int, bw: float = BW_100MBPS
) -> float:
    """W-aware model of the fused gather-then-decode exchange: one encode,
    an allgather of the per-worker payload, then W payload decodes (own +
    W-1 remote). `m` is a flat measurement row (t_decode_s = one decode)."""
    return (
        m["t_encode_s"]
        + allgather_time(m["payload_bytes"], W, bw)
        + W * m["t_decode_s"]
    )


def overlapped_step_time(
    m: Dict[str, float],
    W: int,
    bw: float = BW_100MBPS,
    compute_time: float = 0.0,
) -> float:
    """Step-time model of the backprop-overlapped streaming schedule
    (``cfg.stream_exchange``): each bucket's allgather dispatches while
    backward compute for earlier layers is still running, so up to
    ``compute_time`` seconds of wire hide behind it and only the residual
    exposed tail ``max(0, wire - compute_time)`` is charged serially —
    encode and the W decodes still pay their serial cost. With
    ``compute_time=0`` this is exactly `fused_step_time` (nothing to hide
    behind), so the streamed model can never exceed the r09 pipelined
    schedule's."""
    wire = allgather_time(m["payload_bytes"], W, bw)
    exposed = max(0.0, wire - max(0.0, compute_time))
    return m["t_encode_s"] + exposed + W * m["t_decode_s"]


def overlap_fraction(
    m: Dict[str, float],
    W: int,
    bw: float = BW_100MBPS,
    compute_time: float = 0.0,
) -> float:
    """Fraction of the allgather wire time hidden behind backward compute
    under the streaming schedule — the modeled counterpart of the measured
    `trace --overlap` report. 1.0 when there is no wire to expose."""
    wire = allgather_time(m["payload_bytes"], W, bw)
    if wire <= 0.0:
        return 1.0
    return min(wire, max(0.0, compute_time)) / wire


# ---------------------------------------------------------------------------
# Federated round-time model (fedsim): the paper's deployment setting is C
# client uplinks per round into one parameter server behind a shared ingest
# link. Client local compute runs in parallel across clients (it bounds the
# first arrival, not the total), so the round wall time is ingest-serialized:
# every live uplink's compressed payload crosses the server's link(s), plus
# the one S2C broadcast going out. This is what makes DeepReduce's uplink
# compression a *clients/sec* multiplier — the serving capacity of one
# server link scales inversely with per-client payload bytes.
# ---------------------------------------------------------------------------


def fed_round_time(
    uplink_bytes_per_client: float,
    clients: int,
    bw: float = BW_100MBPS,
    *,
    t_client_s: float = 0.0,
    downlink_bytes: float = 0.0,
    server_links: int = 1,
) -> float:
    """Wall seconds of one federated round at `clients` live uplinks.
    `t_client_s` is one client's local-train latency (paid once — clients
    compute concurrently); `server_links` models ingest parallelism."""
    wire = clients * uplink_bytes_per_client + downlink_bytes
    return t_client_s + wire / (bw * max(server_links, 1))


def fed_clients_per_sec(
    uplink_bytes_per_client: float,
    clients: int,
    bw: float = BW_100MBPS,
    *,
    t_client_s: float = 0.0,
    downlink_bytes: float = 0.0,
    server_links: int = 1,
) -> float:
    """Served clients per second at the modeled round time — the serving
    throughput the ROADMAP's million-user scenario is priced in."""
    t = fed_round_time(
        uplink_bytes_per_client,
        clients,
        bw,
        t_client_s=t_client_s,
        downlink_bytes=downlink_bytes,
        server_links=server_links,
    )
    return clients / max(t, 1e-12)


# ---------------------------------------------------------------------------
# Per-rs_mode static wire accounting. These return the per-worker
# *injection* bytes of every collective the route issues — the same
# numbers GradientExchanger.payload_bytes() reports and the
# jx-wire-accounting "collective" rule pins against the traced jaxpr.
# ---------------------------------------------------------------------------


def _shard_size(d: int, W: int) -> int:
    return (d + W - 1) // W


def _send_budget(d: int, ratio: float, W: int, headroom: float) -> int:
    k = max(1, int(d * ratio))
    return max(1, int(math.ceil(k / W * headroom)))


def _out_budget(d: int, ratio: float, W: int, out_headroom: float) -> int:
    k = max(1, int(d * ratio))
    return min(max(1, int(math.ceil(k / W * out_headroom))), _shard_size(d, W))


def sketch_cols(d: int, ratio: float, rows: int, cols: int = 0) -> int:
    """Resolved sketch width: explicit `cols` wins; 0 auto-sizes to ~2k/rows
    buckets (constant expected load factor ~1/2 per row) with a floor that
    keeps tiny problems from degenerating."""
    if cols > 0:
        return int(cols)
    k = max(1, int(d * ratio))
    return max(256, int(math.ceil(2.0 * k / max(1, rows))))


def quantized_padded_len(d: int, W: int, block: int) -> int:
    """Length after padding d up to a multiple of W*block so every worker's
    shard is whole blocks."""
    chunk = W * block
    return ((d + chunk - 1) // chunk) * chunk


def adaptive_lane_count(d: int, ratio: float, W: int, out_headroom: float, block: int) -> int:
    """f32 lanes in the adaptive phase-2 row (excluding the +1 flag lane):
    max of the sparse encoding (2 lanes/entry) and the int8-block dense
    shard encoding (levels bitcast into f32 lanes + one f32 norm/block)."""
    S = _shard_size(d, W)
    Sp = ((S + block - 1) // block) * block
    dense_lanes = Sp // 4 + Sp // block
    sparse_lanes = 2 * _out_budget(d, ratio, W, out_headroom)
    return max(sparse_lanes, dense_lanes)


def rs_wire_bytes(
    mode: str,
    d: int,
    W: int,
    ratio: float,
    *,
    headroom: float = 2.0,
    out_headroom: float = 1.0,
    block: int = 256,
    rows: int = 5,
    cols: int = 0,
) -> Dict[str, float]:
    """Per-collective injection bytes for one sparse_rs route. Keys are the
    collective primitive names the route traces; values are the operand
    bytes one worker contributes to that collective."""
    B = _send_budget(d, ratio, W, headroom)
    K2 = _out_budget(d, ratio, W, out_headroom)
    if mode == "sparse":
        return {"all_to_all": W * B * 8.0, "all_gather": K2 * 8.0}
    if mode == "adaptive":
        L = adaptive_lane_count(d, ratio, W, out_headroom, block)
        return {"all_to_all": W * B * 8.0, "all_gather": (L + 1) * 4.0}
    if mode == "quantized":
        n = quantized_padded_len(d, W, block)
        return {
            "pmax": (n // block) * 4.0,
            "psum_scatter": n * 1.0,
            "all_gather": K2 * 8.0,
        }
    if mode == "sketch":
        C = sketch_cols(d, ratio, rows, cols)
        return {"psum": rows * C * 4.0, "all_gather": K2 * 8.0}
    raise ValueError(f"unknown rs_mode {mode!r}")


def rs_payload_bytes(mode: str, d: int, W: int, ratio: float, **kw) -> float:
    """Total per-worker injection bytes for one route (sum over its
    collectives) — the number payload_bytes()/jx-wire-accounting pin."""
    return float(sum(rs_wire_bytes(mode, d, W, ratio, **kw).values()))


_RING_TIME = {
    "all_gather": allgather_time,
    "all_to_all": all_to_all_time,
    "psum": allreduce_time,
    "pmax": allreduce_time,
    "psum_scatter": reduce_scatter_time,
}


def rs_step_time(
    mode: str,
    d: int,
    W: int,
    ratio: float,
    *,
    t_compute_s: float = 0.0,
    bw: float = BW_100MBPS,
    compute_time: float = 0.0,
    **kw,
) -> float:
    """W-aware modeled step time of one in-collective route: ring wire time
    of each collective it issues plus its (once-per-worker) compute.
    ``compute_time`` is backward-pass compute available to hide wire behind
    (the streaming-overlap discipline); 0 keeps the historical serialized
    model byte-for-byte."""
    wire = 0.0
    for prim, size in rs_wire_bytes(mode, d, W, ratio, **kw).items():
        wire += _RING_TIME[prim](size, W, bw)
    wire = max(0.0, wire - max(0.0, compute_time))
    return wire + t_compute_s


def _rs_kw(kw: Dict) -> Dict:
    """Filter **kw down to the keys rs_wire_bytes understands."""
    keep = ("headroom", "out_headroom", "block", "rows", "cols")
    return {k: kw[k] for k in keep if k in kw}


def select_rs_mode(
    d: int,
    W: int,
    ratio: float,
    *,
    headroom: float = 2.0,
    out_headroom: float = 1.0,
    block: int = 256,
    rows: int = 5,
    cols: int = 0,
    bw: float = BW_100MBPS,
    modes: Optional[tuple] = None,
    compute_time: float = 0.0,
) -> str:
    """Resolve ``rs_mode="auto"`` at construction time: argmin of the
    wire-only W-aware model over the concrete routes. At the 100 Mbps
    default link the step is wire-dominated, so compute terms (which need
    per-platform measurement) are deliberately excluded — the selector is
    deterministic from (d, W, ratio) and static config alone.
    ``compute_time`` (hideable backward compute, see `overlapped_step_time`)
    threads through to each candidate's `rs_step_time`; the default 0
    keeps the historical selection."""
    candidates = modes or ("sparse", "adaptive", "quantized", "sketch")
    best, best_t = None, float("inf")
    for mode in candidates:
        t = rs_step_time(
            mode, d, W, ratio,
            headroom=headroom, out_headroom=out_headroom,
            block=block, rows=rows, cols=cols, bw=bw,
            compute_time=compute_time,
        )
        if t < best_t:
            best, best_t = mode, t
    return best


# ---------------------------------------------------------------------------
# Two-tier (ICI x DCN) hierarchical model.
#
# A multi-slice mesh has two links with a ~100x bandwidth gap: the
# intra-slice ICI fabric and the cross-slice DCN. The hierarchical
# exchange reduces densely (or int8-quantized) over the fast axis first,
# then runs one of the flat compressed exchanges across slices only.
# Modeled step time is the SUM of the two legs — the slice mean must
# complete before the DCN leg can start, so the legs serialize.
# ---------------------------------------------------------------------------

# 10 Gbps in bytes/s — a deliberately conservative stand-in for the
# intra-slice fabric (real ICI is far faster; the planner only needs the
# order-of-magnitude gap against the 100 Mbps DCN default).
BW_ICI_10GBPS = 1.25e9

HIER_ICI_LEGS = ("dense", "qar")
HIER_DCN_LEGS = ("fused", "bucketed", "sparse", "adaptive", "quantized", "sketch")


def qar_wire_bytes_per_worker(d: int, W: int, block: int = 512) -> float:
    """Per-worker wire bytes of the int8 two-phase quantized allreduce.

    Mirrors ``qar.wire_bits_per_worker`` (kept numerically identical by
    tests/test_hierarchical.py) without importing jax: two tiled
    all_to_all phases of int8 levels plus two all_gathers of f32 bucket
    norms, each moving the (W-1)/W ring fraction."""
    n = quantized_padded_len(d, W, block)
    bits = 2.0 * (n * 8 + (n // block) * 32) * (W - 1) / W
    return bits / 8.0


def hier_ici_time(
    leg: str, d: int, per_slice: int, bw_ici: float = BW_ICI_10GBPS,
    *, block: int = 512,
) -> float:
    """Modeled ICI-leg time: dense f32 psum or int8 quantized allreduce
    over the `per_slice` devices of one slice."""
    if per_slice <= 1:
        return 0.0
    if leg == "dense":
        return allreduce_time(4.0 * d, per_slice, bw_ici)
    if leg == "qar":
        return qar_wire_bytes_per_worker(d, per_slice, block) / bw_ici
    raise ValueError(f"unknown ici leg {leg!r} (expected one of {HIER_ICI_LEGS})")


def hier_dcn_time(
    leg: str,
    d: int,
    n_slices: int,
    ratio: float,
    bw_dcn: float = BW_100MBPS,
    *,
    measurement: Optional[Dict[str, float]] = None,
    t_compute_s: float = 0.0,
    compute_time: float = 0.0,
    **kw,
) -> float:
    """Modeled DCN-leg time with `n_slices` workers on the scarce link.

    "fused"/"bucketed" use the allgather model; without a measured codec
    row the payload defaults to 8 bytes/entry at k = d*ratio (the same
    value+index convention rs_wire_bytes uses). "bucketed" overlaps
    decode under the next bucket's gather, so it pays max(wire, decode)
    instead of their sum; with no measured compute the two tie and the
    planner's candidate order prefers plain "fused". ``compute_time`` is
    hideable backward compute (the streaming overlap, `overlapped_step_
    time`): it shaves every leg's wire before the formulas above, so the
    planner can price what streaming buys on the scarce link; 0 keeps the
    historical model."""
    if leg in ("fused", "bucketed"):
        m = measurement or {
            "payload_bytes": 8.0 * max(1, int(d * ratio)),
            "t_encode_s": 0.0,
            "t_decode_s": 0.0,
        }
        wire = allgather_time(m["payload_bytes"], n_slices, bw_dcn)
        wire = max(0.0, wire - max(0.0, compute_time))
        if leg == "bucketed":
            return m["t_encode_s"] + max(wire, n_slices * m["t_decode_s"])
        return m["t_encode_s"] + wire + n_slices * m["t_decode_s"]
    return rs_step_time(
        leg, d, n_slices, ratio, t_compute_s=t_compute_s, bw=bw_dcn,
        compute_time=compute_time, **_rs_kw(kw)
    )


def hier_step_time(
    ici: str,
    dcn: str,
    d: int,
    n_slices: int,
    per_slice: int,
    ratio: float,
    *,
    bw_ici: float = BW_ICI_10GBPS,
    bw_dcn: float = BW_100MBPS,
    ici_block: int = 512,
    measurement: Optional[Dict[str, float]] = None,
    t_compute_s: float = 0.0,
    compute_time: float = 0.0,
    **kw,
) -> float:
    """Modeled step time of one (ici, dcn) plan: serialized two-leg sum.
    ``compute_time`` (hideable backward compute) applies to the DCN leg
    only — the ICI leg runs after the slice mean and cannot stream."""
    return hier_ici_time(ici, d, per_slice, bw_ici, block=ici_block) + hier_dcn_time(
        dcn, d, n_slices, ratio, bw_dcn,
        measurement=measurement, t_compute_s=t_compute_s,
        compute_time=compute_time, **kw,
    )


def select_hier_plan(
    d: int,
    n_slices: int,
    per_slice: int,
    ratio: float,
    bw_ici: float = BW_ICI_10GBPS,
    bw_dcn: float = BW_100MBPS,
    *,
    ici_block: int = 512,
    ici_legs: Optional[tuple] = None,
    dcn_legs: Optional[tuple] = None,
    measurements: Optional[Dict[str, Dict[str, float]]] = None,
    compute: Optional[Dict[str, float]] = None,
    compute_time: float = 0.0,
    **kw,
) -> Dict:
    """Construction-time auto-planner: argmin of `hier_step_time` over
    {ici: dense|qar} x {dcn: fused|bucketed|rs modes}.

    Deterministic from static shapes and config alone, like
    `select_rs_mode`; bench.py --hier-sweep optionally supplies measured
    codec rows (`measurements[dcn_leg]` -> flat measurement dict) and
    per-route compute (`compute[dcn_leg]` seconds) so its report and the
    planner argmin over exactly the same numbers.

    Returns {"ici", "dcn", "modeled_step_s", "table"} where table maps
    "ici+dcn" -> modeled seconds for every candidate pair."""
    ici_cands = ici_legs or HIER_ICI_LEGS
    dcn_cands = dcn_legs or HIER_DCN_LEGS
    table: Dict[str, float] = {}
    best = None
    for dcn in dcn_cands:
        m = (measurements or {}).get(dcn)
        tc = (compute or {}).get(dcn, 0.0)
        for ici in ici_cands:
            t = hier_step_time(
                ici, dcn, d, n_slices, per_slice, ratio,
                bw_ici=bw_ici, bw_dcn=bw_dcn, ici_block=ici_block,
                measurement=m, t_compute_s=tc, compute_time=compute_time,
                **kw,
            )
            table[f"{ici}+{dcn}"] = t
            if best is None or t < table[f"{best[0]}+{best[1]}"]:
                best = (ici, dcn)
    return {
        "ici": best[0],
        "dcn": best[1],
        "modeled_step_s": table[f"{best[0]}+{best[1]}"],
        "table": table,
    }
