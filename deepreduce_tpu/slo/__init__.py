"""SLO health plane for the federated serving path.

`spec.py` defines the schema-validated `SLOSpec` (targets + windows +
burn-rate thresholds, with per-tenant overrides); `monitor.py` runs a
`HealthMonitor` over the telemetry report stream — a host-side pure
function of the recorded metrics, so its ``health.jsonl`` event trail
replays bitwise across checkpoint kill/resume (`fedsim check --slo`
enforces this). The on-device side is the exact staleness histogram
that rides the ONE fused psum of the async tick (fedsim/sim.py); the
monitor only ever consumes what telemetry already logged.
"""

from deepreduce_tpu.slo.spec import SLOSpec, TARGET_KEYS
from deepreduce_tpu.slo.monitor import (
    HEALTH_SCHEMA,
    HEALTH_STATES,
    HealthLog,
    HealthMonitor,
    validate_health,
    validate_health_stream,
)

__all__ = [
    "SLOSpec",
    "TARGET_KEYS",
    "HEALTH_SCHEMA",
    "HEALTH_STATES",
    "HealthLog",
    "HealthMonitor",
    "validate_health",
    "validate_health_stream",
]
