"""Schema-validated SLO specification for the federated serving path.

An `SLOSpec` is parsed from a JSON file (``telemetry slo RUN --spec
slo.json``, or the `slo_spec` config knob for the in-driver monitor) and
rejected loudly — unknown keys, out-of-range targets, and malformed
tenant overrides all raise `ConfigError` with a registered reason code,
mirroring the config legality matrix: a typo'd spec must never silently
monitor nothing.

Targets are all optional; a spec with no targets anywhere is the
*degenerate* spec — `SLOSpec.is_noop` is True and the `HealthMonitor`
provably does nothing (no state, no events). Per-tenant overrides
(``"tenants": {"1": {...}}``) replace the global value key-by-key.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
from typing import Any, Dict, Mapping, Optional

from deepreduce_tpu.config import ConfigError

# Target key -> what it bounds. Floors (min_*) trip when the windowed
# value falls BELOW the threshold; ceilings trip when it rises ABOVE.
TARGET_KEYS: Dict[str, str] = {
    "min_clients_per_round":
        "floor on window-mean accepted clients per tick",
    "min_clients_per_sec":
        "floor on window-mean admission rate (rows must carry a measured "
        "clients_per_sec; absent rows do not count)",
    "staleness_p95_max":
        "ceiling on window p95 staleness from the on-device histogram",
    "buffer_fill_max":
        "ceiling on window-max buffer fill fraction",
    "checksum_failure_budget":
        "error budget: allowed failed fraction of transmissions "
        "(evaluated as fast/slow burn rates, not a point threshold)",
    "convergence_band":
        "w_rel_err ceiling defining the convergence band",
    "convergence_residency_min":
        "floor on the fraction of window ticks inside the band "
        "(requires convergence_band; defaults to 1.0 when band is set)",
    "pop_residency_min":
        "floor on the smallest per-class participation share over the "
        "window, from the exact on-device population histogram (rows "
        "must carry a pop_hist; absent rows do not count)",
}

_SPEC_KEYS = frozenset({
    "version", "window_ticks", "fast_window_ticks", "slow_window_ticks",
    "hysteresis_ticks", "burn_fast", "burn_slow", "targets", "tenants",
})


def _check_targets(targets: Any, where: str) -> Dict[str, float]:
    if not isinstance(targets, dict):
        raise ConfigError(
            "slo-spec-syntax",
            f"{where} must be an object of target -> number, got "
            f"{type(targets).__name__}"
        )
    unknown = sorted(set(targets) - set(TARGET_KEYS))
    if unknown:
        raise ConfigError(
            "slo-spec-unknown-target",
            f"{where} has unknown target(s) {unknown}; valid targets: "
            f"{sorted(TARGET_KEYS)}"
        )
    out: Dict[str, float] = {}
    for key, raw in targets.items():
        if isinstance(raw, bool) or not isinstance(raw, (int, float)):
            raise ConfigError(
                "slo-spec-target-range",
                f"{where}[{key!r}] must be a number, got {raw!r}"
            )
        val = float(raw)
        if key == "checksum_failure_budget":
            ok = 0.0 < val <= 1.0
        elif key in ("convergence_residency_min", "pop_residency_min"):
            ok = 0.0 <= val <= 1.0
        elif key == "convergence_band":
            ok = val > 0.0
        else:
            ok = val >= 0.0
        if not ok:
            raise ConfigError(
                "slo-spec-target-range",
                f"{where}[{key!r}]={val} is outside the target's legal range"
            )
        out[key] = val
    if "convergence_residency_min" in out and "convergence_band" not in out:
        raise ConfigError(
            "slo-spec-target-range",
            f"{where} sets convergence_residency_min without "
            "convergence_band — there is no band to reside in"
        )
    return out


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """Windows, burn thresholds, and targets for the health monitor."""

    # rolling evaluation window (ticks) for the plain windowed targets
    window_ticks: int = 8
    # burn-rate windows: the error-budget target must be burning fast
    # (short window) AND still burning over the long window to reach
    # BREACH grade — the classic multi-window page/ticket split
    fast_window_ticks: int = 2
    slow_window_ticks: int = 8
    # consecutive same-direction evaluations required before the state
    # ladder moves one rung (anti-flap, mirrors ctrl_hysteresis)
    hysteresis_ticks: int = 2
    # burn-rate thresholds: burn = (observed failure fraction) / budget
    burn_fast: float = 2.0
    burn_slow: float = 1.0
    # global targets and per-tenant overrides (tenant -> partial targets)
    targets: Mapping[str, float] = dataclasses.field(default_factory=dict)
    tenant_targets: Mapping[int, Mapping[str, float]] = dataclasses.field(
        default_factory=dict
    )

    def __post_init__(self):
        for name in ("window_ticks", "fast_window_ticks",
                     "slow_window_ticks", "hysteresis_ticks"):
            v = getattr(self, name)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                raise ConfigError(
                    "slo-spec-window-range",
                    f"{name} must be an int >= 1, got {v!r}"
                )
        if self.slow_window_ticks < self.fast_window_ticks:
            raise ConfigError(
                "slo-spec-window-range",
                f"slow_window_ticks={self.slow_window_ticks} < "
                f"fast_window_ticks={self.fast_window_ticks}: the slow "
                "burn window must contain the fast one"
            )
        if not (self.burn_fast > 0.0 and self.burn_slow > 0.0):
            raise ConfigError(
                "slo-spec-target-range",
                "burn_fast and burn_slow must both be > 0, got "
                f"{self.burn_fast}/{self.burn_slow}"
            )

    # -- construction --------------------------------------------------

    @classmethod
    def from_dict(cls, d: Any) -> "SLOSpec":
        if not isinstance(d, dict):
            raise ConfigError(
                "slo-spec-syntax",
                f"SLO spec must be a JSON object, got {type(d).__name__}"
            )
        unknown = sorted(set(d) - _SPEC_KEYS)
        if unknown:
            raise ConfigError(
                "slo-spec-syntax",
                f"SLO spec has unknown key(s) {unknown}; valid keys: "
                f"{sorted(_SPEC_KEYS)}"
            )
        version = d.get("version", 1)
        if version != 1:
            raise ConfigError(
                "slo-spec-syntax",
                f"SLO spec version must be 1, got {version!r}"
            )
        kwargs: Dict[str, Any] = {}
        for name in ("window_ticks", "fast_window_ticks",
                     "slow_window_ticks", "hysteresis_ticks"):
            if name in d:
                v = d[name]
                if not isinstance(v, int) or isinstance(v, bool):
                    raise ConfigError(
                        "slo-spec-window-range",
                        f"{name} must be an int, got {v!r}"
                    )
                kwargs[name] = v
        for name in ("burn_fast", "burn_slow"):
            if name in d:
                v = d[name]
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    raise ConfigError(
                        "slo-spec-target-range",
                        f"{name} must be a number, got {v!r}"
                    )
                kwargs[name] = float(v)
        kwargs["targets"] = _check_targets(d.get("targets", {}), "targets")
        tenants: Dict[int, Dict[str, float]] = {}
        raw_tenants = d.get("tenants", {})
        if not isinstance(raw_tenants, dict):
            raise ConfigError(
                "slo-spec-tenant-override",
                "tenants must be an object of tenant-index -> targets, got "
                f"{type(raw_tenants).__name__}"
            )
        for key, sub in raw_tenants.items():
            try:
                t = int(key)
            except (TypeError, ValueError):
                raise ConfigError(
                    "slo-spec-tenant-override",
                    f"tenant override key {key!r} is not an integer index"
                ) from None
            if t < 0:
                raise ConfigError(
                    "slo-spec-tenant-override",
                    f"tenant override index {t} must be >= 0"
                )
            tenants[t] = _check_targets(sub, f"tenants[{key!r}]")
        kwargs["tenant_targets"] = tenants
        return cls(**kwargs)

    @classmethod
    def load(cls, path) -> "SLOSpec":
        path = pathlib.Path(path)
        try:
            raw = json.loads(path.read_text())
        except FileNotFoundError:
            raise ConfigError(
                "slo-spec-syntax", f"SLO spec file not found: {path}"
            ) from None
        except json.JSONDecodeError as e:
            raise ConfigError(
                "slo-spec-syntax", f"SLO spec {path} is not valid JSON: {e}"
            ) from e
        return cls.from_dict(raw)

    # -- views ----------------------------------------------------------

    @property
    def is_noop(self) -> bool:
        """True when no target is set anywhere: the monitor must do
        nothing (no windows, no state, no events)."""
        return not self.targets and not any(
            t for t in self.tenant_targets.values()
        )

    def effective_targets(self, tenant: int) -> Dict[str, float]:
        """Global targets with the tenant's overrides applied on top."""
        out = dict(self.targets)
        out.update(self.tenant_targets.get(tenant, {}))
        return out

    def with_overrides(
        self,
        window_ticks: int = 0,
        hysteresis_ticks: int = 0,
    ) -> "SLOSpec":
        """Apply the config-knob overrides (0 keeps the spec value)."""
        changes: Dict[str, Any] = {}
        if window_ticks:
            changes["window_ticks"] = window_ticks
        if hysteresis_ticks:
            changes["hysteresis_ticks"] = hysteresis_ticks
        return dataclasses.replace(self, **changes) if changes else self

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": 1,
            "window_ticks": self.window_ticks,
            "fast_window_ticks": self.fast_window_ticks,
            "slow_window_ticks": self.slow_window_ticks,
            "hysteresis_ticks": self.hysteresis_ticks,
            "burn_fast": self.burn_fast,
            "burn_slow": self.burn_slow,
            "targets": dict(self.targets),
            "tenants": {
                str(t): dict(sub) for t, sub in self.tenant_targets.items()
            },
        }
