"""Rolling-window health monitor with an auditable ``health.jsonl`` trail.

The `HealthMonitor` is the serving-path sibling of the r14 compression
controller: a host-side, deterministic pure function of the telemetry
report stream. Each tick it evaluates the `SLOSpec` targets over rolling
windows of recorded reports and walks a hysteretic three-rung ladder

    OK -> DEGRADED -> BREACH   (and back down, one rung at a time)

Transitions — and only transitions — are emitted as schema-validated
records to ``health.jsonl``; a flapping metric that crosses its ceiling
every other window never builds the `hysteresis_ticks` streak and so
emits nothing (no transition storms). Records carry no wall-clock
timestamp: the trail is a pure function of the report stream, which is
what lets `fedsim check --slo` replay it bitwise across kill/resume.

Severity grading is multi-window. A plain target (clients floor,
staleness-p95 ceiling, buffer-fill bound, convergence residency) grades
DEGRADED when violated over the evaluation window and BREACH-grade only
when the violation also holds over the full slow window. The checksum
error budget grades on classic fast/slow burn rates: burn = observed
failure fraction / budget; BREACH-grade requires the fast window to burn
at `burn_fast` x budget WHILE the slow window still burns at `burn_slow`
x budget, so a transient spike pages nobody but a sustained burn cannot
hide behind a long quiet history.

Everything in the monitor state is plain JSON-serializable Python
(ints, floats, lists, dicts), so `state_dict()` round-trips bitwise
through a JSON sidecar next to the checkpoint.
"""

from __future__ import annotations

import json
import pathlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

from deepreduce_tpu.slo.spec import SLOSpec, TARGET_KEYS
from deepreduce_tpu.telemetry.device_metrics import hist_quantile

HEALTH_STATES = ("OK", "DEGRADED", "BREACH")
_LEVEL = {s: i for i, s in enumerate(HEALTH_STATES)}

# the downward transition's trigger code; upward transitions carry the
# violated target key
TRIG_RECOVERED = "recovered"
TRIGGER_CODES = tuple(TARGET_KEYS) + (TRIG_RECOVERED,)

# health.jsonl schema: field name -> accepted types. Every record must
# carry exactly these keys (documented in ARCHITECTURE.md).
HEALTH_SCHEMA: Dict[str, Tuple[type, ...]] = {
    "tick": (int,),
    "tenant": (int,),
    "window_ticks": (int,),
    "from_state": (str,),
    "to_state": (str,),
    "trigger": (str,),
    "value": (float, type(None)),
    "threshold": (float, type(None)),
    "burn_fast": (float, type(None)),
    "burn_slow": (float, type(None)),
}


def validate_health(rec: Dict[str, Any]) -> None:
    """Raise ValueError unless `rec` matches HEALTH_SCHEMA exactly."""
    if not isinstance(rec, dict):
        raise ValueError(f"health record must be a dict, got {type(rec)}")
    missing = sorted(set(HEALTH_SCHEMA) - set(rec))
    extra = sorted(set(rec) - set(HEALTH_SCHEMA))
    if missing or extra:
        raise ValueError(
            f"health record keys mismatch: missing={missing} extra={extra}"
        )
    for key, types in HEALTH_SCHEMA.items():
        # bool is an int subclass; keep tick/tenant/window strictly int.
        if isinstance(rec[key], bool) and bool not in types:
            raise ValueError(f"health field {key}={rec[key]!r} is bool, want {types}")
        if not isinstance(rec[key], types):
            raise ValueError(
                f"health field {key}={rec[key]!r} has type "
                f"{type(rec[key]).__name__}, want {types}"
            )
    for key in ("from_state", "to_state"):
        if rec[key] not in HEALTH_STATES:
            raise ValueError(f"unknown health state {rec[key]!r} in {key}")
    if rec["trigger"] not in TRIGGER_CODES:
        raise ValueError(f"unknown trigger code {rec['trigger']!r}")
    if rec["tick"] < 0 or rec["tenant"] < 0 or rec["window_ticks"] < 1:
        raise ValueError(
            f"health record out of range: tick={rec['tick']} "
            f"tenant={rec['tenant']} window_ticks={rec['window_ticks']}"
        )
    delta = _LEVEL[rec["to_state"]] - _LEVEL[rec["from_state"]]
    if abs(delta) != 1:
        raise ValueError(
            f"health transition {rec['from_state']} -> {rec['to_state']} "
            "must move exactly one rung"
        )
    if (delta < 0) != (rec["trigger"] == TRIG_RECOVERED):
        raise ValueError(
            "downward transitions carry trigger='recovered' and upward "
            f"ones a target key; got {rec['trigger']!r} for "
            f"{rec['from_state']} -> {rec['to_state']}"
        )


def validate_health_stream(records: Sequence[Dict[str, Any]]) -> None:
    """Validate every record plus the cross-record contracts: per-tenant
    ticks strictly increase and consecutive transitions chain (a
    tenant's from_state equals its previous to_state)."""
    last: Dict[int, Dict[str, Any]] = {}
    for i, rec in enumerate(records):
        try:
            validate_health(rec)
        except ValueError as e:
            raise ValueError(f"health.jsonl record {i}: {e}") from e
        prev = last.get(rec["tenant"])
        if prev is not None:
            if rec["tick"] <= prev["tick"]:
                raise ValueError(
                    f"health.jsonl record {i}: non-monotonic tick "
                    f"{rec['tick']} <= {prev['tick']} for tenant "
                    f"{rec['tenant']}"
                )
            if rec["from_state"] != prev["to_state"]:
                raise ValueError(
                    f"health.jsonl record {i}: broken transition chain "
                    f"for tenant {rec['tenant']}: from_state="
                    f"{rec['from_state']!r} after to_state="
                    f"{prev['to_state']!r}"
                )
        last[rec["tenant"]] = rec


class HealthLog:
    """Append-only, schema-validated ``health.jsonl`` writer. Rejects
    per-tenant tick regressions at append time, so a buggy driver can
    never write a trail the stream validator would refuse to read."""

    def __init__(self, path) -> None:
        self.path = pathlib.Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._last_tick: Dict[int, int] = {}

    def append(self, rec: Dict[str, Any]) -> None:
        validate_health(rec)
        last = self._last_tick.get(rec["tenant"])
        if last is not None and rec["tick"] <= last:
            raise ValueError(
                f"non-monotonic health tick {rec['tick']} <= {last} for "
                f"tenant {rec['tenant']}"
            )
        self._last_tick[rec["tenant"]] = rec["tick"]
        with self.path.open("a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")

    @staticmethod
    def read(path) -> List[Dict[str, Any]]:
        path = pathlib.Path(path)
        if not path.exists():
            return []
        records = []
        with path.open() as f:
            for line in f:
                line = line.strip()
                if line:
                    records.append(json.loads(line))
        return records


# report keys the monitor consumes; everything else in a row is ignored.
# staleness_hist is a list, the rest are scalars.
_REPORT_SCALARS = (
    "clients", "clients_per_sec", "buffer_fill", "checksum_failures",
    "w_rel_err",
)


def _normalize_report(report: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key in _REPORT_SCALARS:
        if report.get(key) is not None:
            out[key] = float(report[key])
    hist = report.get("staleness_hist")
    if hist is not None and len(hist):
        out["staleness_hist"] = [float(h) for h in hist]
    pop = report.get("pop_hist")
    if pop is not None and len(pop):
        out["pop_hist"] = [float(h) for h in pop]
    return out


def _mean_of(rows: Sequence[Dict[str, Any]], key: str) -> Optional[float]:
    vals = [r[key] for r in rows if key in r]
    return sum(vals) / len(vals) if vals else None


def _max_of(rows: Sequence[Dict[str, Any]], key: str) -> Optional[float]:
    vals = [r[key] for r in rows if key in r]
    return max(vals) if vals else None


def _hist_p95(rows: Sequence[Dict[str, Any]]) -> Optional[float]:
    hists = [r["staleness_hist"] for r in rows if "staleness_hist" in r]
    if not hists:
        return None
    depth = max(len(h) for h in hists)
    total = [0.0] * depth
    for h in hists:
        for d, v in enumerate(h):
            total[d] += v
    return hist_quantile(total, 0.95)


def _residency(
    rows: Sequence[Dict[str, Any]], band: float
) -> Optional[float]:
    vals = [r["w_rel_err"] for r in rows if "w_rel_err" in r]
    if not vals:
        return None
    return sum(1.0 for v in vals if v <= band) / len(vals)


def _pop_min_share(rows: Sequence[Dict[str, Any]]) -> Optional[float]:
    hists = [r["pop_hist"] for r in rows if "pop_hist" in r]
    if not hists:
        return None
    depth = max(len(h) for h in hists)
    total = [0.0] * depth
    for h in hists:
        for k, v in enumerate(h):
            total[k] += v
    grand = sum(total)
    if grand <= 0.0:
        return None
    return min(total) / grand


def _burn(rows: Sequence[Dict[str, Any]], budget: float) -> float:
    fails = sum(r.get("checksum_failures", 0.0) for r in rows)
    total = sum(
        r.get("clients", 0.0) + r.get("checksum_failures", 0.0)
        for r in rows
    )
    frac = fails / total if total > 0.0 else 0.0
    return frac / budget


class _Eval:
    """One target's evaluation this tick (value=None: no data, level 0)."""

    __slots__ = ("key", "level", "value", "threshold", "burn_fast",
                 "burn_slow")

    def __init__(self, key, level, value, threshold,
                 burn_fast=None, burn_slow=None):
        self.key = key
        self.level = level
        self.value = value
        self.threshold = threshold
        self.burn_fast = burn_fast
        self.burn_slow = burn_slow


class HealthMonitor:
    """Walks the OK/DEGRADED/BREACH ladder from the report stream.

    Host-side only, like the compression controller: feed it one report
    dict per (tick, tenant) via `observe` and it returns the transition
    records it emitted (at most one per call — the ladder moves one rung
    per tick). State round-trips through `state_dict`/`load_state_dict`
    as plain JSON types, so a resumed run continues the trail bitwise.
    """

    def __init__(
        self,
        spec: SLOSpec,
        *,
        log: Optional[HealthLog] = None,
    ) -> None:
        self.spec = spec
        self.log = log
        self.events: List[Dict[str, Any]] = []
        # tenant -> {"level", "up", "down", "last_tick", "history"}
        self._tenants: Dict[int, Dict[str, Any]] = {}

    # -- evaluation -----------------------------------------------------

    @property
    def is_noop(self) -> bool:
        return self.spec.is_noop

    def _history_cap(self) -> int:
        return max(self.spec.window_ticks, self.spec.slow_window_ticks)

    def _tenant(self, tenant: int) -> Dict[str, Any]:
        st = self._tenants.get(tenant)
        if st is None:
            st = {"level": 0, "up": 0, "down": 0, "last_tick": -1,
                  "history": []}
            self._tenants[tenant] = st
        return st

    def _evaluate(
        self, targets: Dict[str, float], history: List[Dict[str, Any]]
    ) -> List[_Eval]:
        spec = self.spec
        win = history[-spec.window_ticks:]
        slow = history[-spec.slow_window_ticks:]
        fast = history[-spec.fast_window_ticks:]
        # BREACH grade needs the violation sustained over a FULL slow
        # window; with a shorter history the grade caps at DEGRADED.
        slow_full = len(history) >= spec.slow_window_ticks

        def graded(key, thr, now, sustained, violated):
            level = 0
            if now is not None and violated(now):
                level = 1
                if (slow_full and sustained is not None
                        and violated(sustained)):
                    level = 2
            return _Eval(key, level, now, thr)

        evals: List[_Eval] = []
        for key in TARGET_KEYS:
            if key not in targets:
                continue
            thr = targets[key]
            if key == "min_clients_per_round":
                evals.append(graded(
                    key, thr, _mean_of(win, "clients"),
                    _mean_of(slow, "clients"), lambda v: v < thr))
            elif key == "min_clients_per_sec":
                evals.append(graded(
                    key, thr, _mean_of(win, "clients_per_sec"),
                    _mean_of(slow, "clients_per_sec"), lambda v: v < thr))
            elif key == "staleness_p95_max":
                evals.append(graded(
                    key, thr, _hist_p95(win), _hist_p95(slow),
                    lambda v: v > thr))
            elif key == "buffer_fill_max":
                evals.append(graded(
                    key, thr, _max_of(win, "buffer_fill"),
                    _max_of(slow, "buffer_fill"), lambda v: v > thr))
            elif key == "checksum_failure_budget":
                bf = _burn(fast, thr)
                bs = _burn(slow, thr)
                level = 0
                if bs >= self.spec.burn_slow:
                    level = 2 if (
                        slow_full and bf >= self.spec.burn_fast
                    ) else 1
                ev = _Eval(key, level, bs * thr, thr, bf, bs)
                evals.append(ev)
            elif key == "convergence_band":
                resid_min = targets.get("convergence_residency_min", 1.0)
                evals.append(graded(
                    key, resid_min, _residency(win, thr),
                    _residency(slow, thr), lambda v: v < resid_min))
            elif key == "pop_residency_min":
                evals.append(graded(
                    key, thr, _pop_min_share(win), _pop_min_share(slow),
                    lambda v: v < thr))
            # convergence_residency_min is folded into convergence_band
        return evals

    def observe(
        self, tick: int, report: Dict[str, Any], tenant: int = 0
    ) -> List[Dict[str, Any]]:
        """Feed one (tick, tenant) report; returns the emitted records."""
        if self.is_noop:
            return []
        targets = self.spec.effective_targets(tenant)
        if not targets:
            return []
        st = self._tenant(tenant)
        if tick <= st["last_tick"]:
            raise ValueError(
                f"non-monotonic observe tick {tick} <= {st['last_tick']} "
                f"for tenant {tenant}"
            )
        st["last_tick"] = tick
        st["history"].append(_normalize_report(report))
        del st["history"][:-self._history_cap()]

        evals = self._evaluate(targets, st["history"])
        desired = max((e.level for e in evals), default=0)
        cur = st["level"]
        if desired > cur:
            st["up"] += 1
            st["down"] = 0
        elif desired < cur:
            st["down"] += 1
            st["up"] = 0
        else:
            st["up"] = 0
            st["down"] = 0

        emitted: List[Dict[str, Any]] = []
        hyst = self.spec.hysteresis_ticks
        if st["up"] >= hyst and cur < len(HEALTH_STATES) - 1:
            worst = max(
                (e for e in evals if e.level > 0),
                key=lambda e: (e.level, -list(TARGET_KEYS).index(e.key)),
            )
            rec = {
                "tick": tick,
                "tenant": tenant,
                "window_ticks": self.spec.window_ticks,
                "from_state": HEALTH_STATES[cur],
                "to_state": HEALTH_STATES[cur + 1],
                "trigger": worst.key,
                "value": worst.value,
                "threshold": worst.threshold,
                "burn_fast": worst.burn_fast,
                "burn_slow": worst.burn_slow,
            }
            st["level"] = cur + 1
            st["up"] = 0
            emitted.append(rec)
        elif st["down"] >= hyst and cur > 0:
            rec = {
                "tick": tick,
                "tenant": tenant,
                "window_ticks": self.spec.window_ticks,
                "from_state": HEALTH_STATES[cur],
                "to_state": HEALTH_STATES[cur - 1],
                "trigger": TRIG_RECOVERED,
                "value": None,
                "threshold": None,
                "burn_fast": None,
                "burn_slow": None,
            }
            st["level"] = cur - 1
            st["down"] = 0
            emitted.append(rec)
        for rec in emitted:
            self.events.append(rec)
            if self.log is not None:
                self.log.append(rec)
        return emitted

    # -- verdicts -------------------------------------------------------

    def state_of(self, tenant: int = 0) -> str:
        st = self._tenants.get(tenant)
        return HEALTH_STATES[st["level"]] if st is not None else "OK"

    def final_states(self) -> Dict[int, str]:
        return {
            t: HEALTH_STATES[st["level"]]
            for t, st in sorted(self._tenants.items())
        }

    def healthy(self) -> bool:
        """True iff every observed tenant sits at OK right now."""
        return all(st["level"] == 0 for st in self._tenants.values())

    def verdict(self, tenant: int = 0) -> Dict[str, Any]:
        """Current state + per-target windowed value/threshold/ok for the
        `telemetry slo` table. value None = no data in the window."""
        targets = self.spec.effective_targets(tenant)
        st = self._tenants.get(tenant)
        history = st["history"] if st is not None else []
        rows = {}
        for ev in self._evaluate(targets, list(history)):
            rows[ev.key] = {
                "value": ev.value,
                "threshold": ev.threshold,
                "ok": ev.level == 0,
                "burn_fast": ev.burn_fast,
                "burn_slow": ev.burn_slow,
            }
        return {
            "tenant": tenant,
            "state": self.state_of(tenant),
            "targets": rows,
        }

    # -- checkpoint plumbing -------------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        """Plain-JSON state: round-trips bitwise through json.dumps."""
        return {
            "tenants": {
                str(t): {
                    "level": st["level"],
                    "up": st["up"],
                    "down": st["down"],
                    "last_tick": st["last_tick"],
                    "history": [dict(r) for r in st["history"]],
                }
                for t, st in self._tenants.items()
            },
            "events": [dict(r) for r in self.events],
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self._tenants = {
            int(t): {
                "level": int(st["level"]),
                "up": int(st["up"]),
                "down": int(st["down"]),
                "last_tick": int(st["last_tick"]),
                "history": [dict(r) for r in st["history"]],
            }
            for t, st in state["tenants"].items()
        }
        self.events = [dict(r) for r in state["events"]]
