"""Gradient exchange over the device mesh — the communicator layer.

Reference stack being replaced: GRACE `DistributedOptimizer` hook →
compress → Horovod allgather (OpenMPI + NCCL) → per-worker decompress →
``add_n / size`` aggregate (/root/reference/tensorflow/deepreduce.py:54-61;
run_deepreduce.sh:4-9). Allgather is used *because* compressed payloads
differ per worker (`tensors_size_are_same=False`,
pytorch/deepreduce.py:54-59); the dense baseline uses allreduce.

TPU-native equivalents:

- allgather  -> `jax.lax.all_gather` of the static-budget payload pytree
  over a mesh axis inside `shard_map`; XLA routes it over ICI.
- allreduce  -> `jax.lax.psum` (dense baseline path).
- aggregate  -> a `fori_loop` over the gathered leading axis, decoding each
  worker's payload and accumulating into ONE dense buffer (the reference
  materializes n dense tensors then `add_n`s them; the scatter-add
  accumulator avoids the n-way peak memory).
- residual error-feedback state rides along functionally
  (`deepreduce_tpu.memory`).

`GradientExchanger` is built once from the gradient pytree's shapes (codec
geometry is static); its `exchange` method is called inside the
shard_map'ped train step.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepreduce_tpu import comm_ring, memory
from deepreduce_tpu.config import ConfigError, DeepReduceConfig
from deepreduce_tpu.resilience.chaos import ChaosInjector
from deepreduce_tpu.metrics import (
    WireStats,
    combine,
    payload_device_bytes,
    ring_wire_bytes,
)
from deepreduce_tpu.sparse import per_tensor_key
from deepreduce_tpu.telemetry import spans
from deepreduce_tpu.wrappers import TensorCodec


def _leaf_name(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)


class PayloadLayout:
    """Static byte layout of one tensor's payload inside the fused buffer.

    Payload pytrees have static structure and leaf shapes (that is the
    whole point of the static-budget codec design), so the flattening is
    computed once from abstract shapes and the packing is pure slicing —
    no per-step host work, no dynamic shapes for XLA."""

    def __init__(self, payload_sds: Any, *, checksum: bool = False):
        leaves, self.treedef = jax.tree_util.tree_flatten(payload_sds)
        self.specs: List[Tuple[Tuple[int, ...], Any]] = [
            (tuple(int(s) for s in l.shape), jnp.dtype(l.dtype)) for l in leaves
        ]
        self.leaf_bytes = [
            int(np.prod(s, dtype=np.int64)) * dt.itemsize for s, dt in self.specs
        ]
        self.checksum = bool(checksum)
        self.payload_nbytes = int(sum(self.leaf_bytes))
        # wire footprint: payload bytes plus the optional trailing uint32
        # checksum word (resilience). `unpack` only walks the payload
        # offsets, so the tail is invisible to it either way.
        self.nbytes = self.payload_nbytes + (4 if self.checksum else 0)

    @staticmethod
    def _checksum_word(body: jax.Array) -> jax.Array:
        """Position-weighted uint32 checksum of the payload bytes. The
        per-position weights make byte order matter (a plain byte sum
        would miss transpositions); the XOR salt makes an all-zero buffer
        FAIL against its own zeroed word, so a chaos 'drop' (fully zeroed
        payload) is always detected."""
        n = body.shape[0]
        w = jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(2654435761) + jnp.uint32(1)
        s = jnp.sum(body.astype(jnp.uint32) * w, dtype=jnp.uint32)
        return s ^ jnp.uint32(0xA5A5A5A5)

    def pack(self, payload: Any) -> jax.Array:
        """payload pytree -> uint8[nbytes] (bitcast, zero-copy in XLA)."""
        leaves = jax.tree_util.tree_leaves(payload)
        segs = []
        for leaf, (shape, dt) in zip(leaves, self.specs):
            x = leaf.reshape(-1)
            if dt == jnp.bool_:
                x = x.astype(jnp.uint8)
            elif dt.itemsize > 1:
                x = jax.lax.bitcast_convert_type(x, jnp.uint8).reshape(-1)
            else:
                x = jax.lax.bitcast_convert_type(x, jnp.uint8)
            segs.append(x)
        body = jnp.concatenate(segs) if segs else jnp.zeros((0,), jnp.uint8)
        if not self.checksum:
            return body
        word = self._checksum_word(body)
        tail = jax.lax.bitcast_convert_type(word[None], jnp.uint8).reshape(-1)
        return jnp.concatenate([body, tail])

    def verify(self, buf: jax.Array) -> jax.Array:
        """f32 validity gate over one packed buffer: 1.0 when the stored
        checksum word matches the payload bytes (or checksum is off), else
        0.0. Callers gate the decoded leaf with a `where` select on this
        value rather than a host branch, so decode stays branch-free under
        tracing — a failed payload degrades to an exact-zero contribution
        instead of NaN (corrupt bytes can decode to Inf/NaN, so the select,
        not a multiply, does the zeroing)."""
        if not self.checksum:
            return jnp.ones((), jnp.float32)
        body = buf[: self.payload_nbytes]
        tail = buf[self.payload_nbytes : self.nbytes]
        stored = jax.lax.bitcast_convert_type(tail.reshape(1, 4), jnp.uint32)[0]
        return (stored == self._checksum_word(body)).astype(jnp.float32)

    def unpack(self, buf: jax.Array) -> Any:
        """uint8[nbytes] -> payload pytree (inverse of pack)."""
        leaves = []
        off = 0
        for (shape, dt), nb in zip(self.specs, self.leaf_bytes):
            seg = buf[off : off + nb]  # static offsets: pure XLA slices
            n = int(np.prod(shape, dtype=np.int64))
            if dt == jnp.bool_:
                leaf = seg.astype(jnp.bool_)
            elif dt.itemsize > 1:
                leaf = jax.lax.bitcast_convert_type(seg.reshape(n, dt.itemsize), dt)
            else:
                leaf = jax.lax.bitcast_convert_type(seg, dt)
            leaves.append(leaf.reshape(shape))
            off += nb
        return jax.tree_util.tree_unflatten(self.treedef, leaves)


def decode_gathered_loop(
    gathered,
    num_workers,
    decode_row,
    out_shapes,
    *,
    axis_name: str,
    need_own: bool,
    row_weights=None,
):
    """Sequential fori_loop over gathered workers (the original shape):
    O(W·d) serial decode on the critical path, but only ONE dense
    accumulator lives at a time. `decode_row` maps one worker's uint8 row
    to a tuple of f32 arrays shaped like `out_shapes`; the own-row decode
    (residual error-feedback) is folded into the same loop with a select
    at w == my_index, so the decode program is traced once. Shared by the
    whole-pytree fused path and the per-bucket decodes (comm_bucket.py).

    `row_weights` (f32[W] or None) scales each worker's decode BEFORE both
    the accumulator and the own-row select: a masked-out worker (weight 0)
    contributes nothing to the aggregate AND its own decode is zero, so
    `memory.update` keeps its whole compensated gradient in the residual —
    elastic-participation re-delivery rides the existing EF machinery."""
    widx = jax.lax.axis_index(axis_name)
    acc0 = tuple(jnp.zeros(s, jnp.float32) for s in out_shapes)
    own0 = acc0 if need_own else ()

    def body(w, carry):
        acc, own = carry
        row = jax.lax.dynamic_index_in_dim(gathered, w, keepdims=False)
        decs = decode_row(row)
        if row_weights is not None:
            wgt = jax.lax.dynamic_index_in_dim(row_weights, w, keepdims=False)
            decs = tuple(d * wgt for d in decs)
        new_acc = tuple(a + dec for a, dec in zip(acc, decs))
        new_own = (
            tuple(jnp.where(w == widx, dec, o) for dec, o in zip(decs, own))
            if need_own
            else ()
        )
        return new_acc, new_own

    return jax.lax.fori_loop(0, num_workers, body, (acc0, own0))


def decode_gathered_vmap(
    gathered,
    num_workers,
    decode_row,
    out_shapes,
    *,
    axis_name: str,
    need_own: bool,
    decode_batch: int,
    row_weights=None,
):
    """Batched decode: the [W, B] gathered buffer is decoded in static
    groups of `decode_batch` rows under jax.vmap — one wide kernel per
    group (W/decode_batch launches instead of W sequential programs), with
    peak memory bounded at decode_batch dense tensors per output. The
    own-row decode is recovered by a masked sum over each group's rows
    (adding exact zeros), so the decode program is still traced once
    (vmapped), never a second unbatched time. `row_weights` scales each
    worker's decode before both sums (see decode_gathered_loop)."""
    W = int(num_workers)
    G = max(1, min(int(decode_batch), W))
    widx = jax.lax.axis_index(axis_name)
    vdec = jax.vmap(decode_row)
    acc = tuple(jnp.zeros(s, jnp.float32) for s in out_shapes)
    own = acc if need_own else ()
    for g0 in range(0, W, G):
        g1 = min(g0 + G, W)
        decs = vdec(jax.lax.slice_in_dim(gathered, g0, g1))  # [g, ...] each
        if row_weights is not None:
            wseg = jax.lax.slice_in_dim(row_weights, g0, g1)  # [g]
            decs = tuple(
                d * wseg.reshape((-1,) + (1,) * (d.ndim - 1)) for d in decs
            )
        acc = tuple(a + d.sum(axis=0) for a, d in zip(acc, decs))
        if need_own:
            mine = jnp.arange(g0, g1) == widx  # [g] one-hot or all-false
            own = tuple(
                o + (d * mine.reshape((-1,) + (1,) * (d.ndim - 1))).sum(axis=0)
                for o, d in zip(own, decs)
            )
    return acc, own


class GradientExchanger:
    """Compress -> all_gather -> decompress -> aggregate, per gradient tensor.

    The role of the whole GRACE instance the reference builds in
    `deepreduce_from_params` (pytorch/deepreduce.py:28-48)."""

    def __init__(
        self,
        grads_like: Any,
        cfg: DeepReduceConfig,
        *,
        axis_name: str = "data",
        num_workers: Optional[int] = None,
        bucket_points: Optional[Any] = None,
        profile: Optional[Any] = None,
    ):
        self.cfg = cfg
        self.axis_name = axis_name
        # static mesh-axis size; required by the communicators built on
        # all_to_all ('qar', 'sparse_rs') whose reshapes need it
        self.num_workers = num_workers
        if cfg.communicator == "qar" and (
            cfg.deepreduce is not None
            or cfg.compressor not in ("none",)
            or cfg.memory == "residual"
        ):
            raise ConfigError(
                "build-qar-codec-stack",
                "communicator='qar' quantizes the DENSE gradient inside the "
                "collective and never runs the sparsifier, codecs, or "
                "error-feedback (its quantization is unbiased); "
                f"compressor={cfg.compressor!r} / deepreduce={cfg.deepreduce!r} "
                f"/ memory={cfg.memory!r} would be silently ignored — use "
                "compressor='none', deepreduce=None, memory='none' (or a "
                "different communicator)"
            )
        if cfg.communicator == "sparse_rs" and (
            cfg.deepreduce is not None or cfg.compressor != "topk"
        ):
            raise ConfigError(
                "build-sparse-rs-codec-stack",
                "communicator='sparse_rs' top-k-sparsifies and routes "
                "entries itself (sparse_rs.py); a deepreduce codec stack or "
                "a different sparsifier would be silently ignored — got "
                f"deepreduce={cfg.deepreduce!r}, compressor={cfg.compressor!r}. "
                "Use compressor='topk', deepreduce=None (compression comes "
                "from the top-k + sharded re-selection), or the allgather "
                "communicator for codec-compressed payloads."
            )
        # resolve the sparse_rs route once, at construction: 'auto' asks the
        # shared W-aware cost model (costmodel.select_rs_mode) to argmin the
        # ring wire time of the concrete routes from (d, W, ratio) — the
        # traced exchange only ever sees a concrete mode. An explicit
        # `profile=` (or cfg.profile path) prices the argmin with fitted
        # machine constants instead of the static ones.
        from deepreduce_tpu import costmodel

        if profile is None and cfg.profile is not None:
            profile = costmodel.load_profile(cfg.profile)
        self.profile = profile
        self._rs_mode = cfg.rs_mode
        if cfg.communicator == "sparse_rs" and cfg.rs_mode == "auto":
            if num_workers is None:
                raise ConfigError(
                    "build-rs-auto-needs-workers",
                    "rs_mode='auto' resolves against the W-aware cost model "
                    "at construction and needs the static mesh size: "
                    "construct GradientExchanger(..., num_workers=...)"
                )
            d = sum(
                int(math.prod(l.shape)) if l.shape else 1
                for l in jax.tree_util.tree_leaves(grads_like)
            )
            # under resilience only the re-ownable routes are candidates —
            # adaptive lane switches and sketch rows are per-worker wire
            # state no deputy can serve (config fences them explicitly)
            rs_candidates = (
                ("sparse", "quantized", "oktopk") if cfg.resilience else None
            )
            self._rs_mode = costmodel.select_rs_mode(
                d,
                num_workers,
                cfg.compress_ratio,
                headroom=cfg.rs_headroom,
                out_headroom=cfg.rs_out_headroom,
                block=cfg.rs_block_size,
                rows=cfg.rs_sketch_rows,
                cols=cfg.rs_sketch_cols,
                bins=cfg.rs_oktopk_bins,
                cap_headroom=cfg.rs_oktopk_cap_headroom,
                profile=profile,
                modes=rs_candidates,
            )
        leaves, self.treedef = jax.tree_util.tree_flatten_with_path(grads_like)
        self.names = [_leaf_name(path) for path, _ in leaves]
        self._grad_dtypes = {
            name: jnp.dtype(leaf.dtype) for name, (path, leaf) in zip(self.names, leaves)
        }
        self.codecs: Dict[str, TensorCodec] = {}
        self._bucketed = None
        self._layouts: Optional[Dict[str, PayloadLayout]] = None
        self._offsets: Dict[str, int] = {}
        self._fused_nbytes = 0
        # resilience seams: both None/False unless configured, so the
        # default program contains no chaos or checksum ops at all
        self._chaos = ChaosInjector.from_config(cfg)
        self._checksum = bool(cfg.payload_checksum)
        if cfg.bucket_bytes is not None:
            if not (cfg.fused and cfg.communicator == "allgather"):
                raise ConfigError(
                    "build-buckets-need-fused-allgather",
                    "bucket_bytes partitions the FUSED allgather exchange and "
                    "would be silently ignored here "
                    f"(communicator={cfg.communicator!r}, fused={cfg.fused}) — "
                    "use fused=True with communicator='allgather', or "
                    "bucket_bytes=None"
                )
            if cfg.decode_strategy == "ring":
                raise ConfigError(
                    "build-buckets-vs-ring",
                    "decode_strategy='ring' already pipelines transfer against "
                    "decode over ppermute hops; combining it with bucket_bytes "
                    "would nest two pipelines and the bucketing would be "
                    "silently ignored — use decode_strategy='loop' or 'vmap' "
                    "with bucket_bytes, or ring without it"
                )
            if cfg.deepreduce is None and cfg.compressor == "none":
                raise ConfigError(
                    "build-buckets-need-compression",
                    "bucket_bytes only affects the compressed allgather path; "
                    "the dense baseline (deepreduce=None, compressor='none') "
                    "is a psum and would silently ignore it — set "
                    "bucket_bytes=None for dense runs"
                )
            if cfg.layer_pattern is not None:
                raise ConfigError(
                    "build-buckets-vs-layer-pattern",
                    "layer_pattern excludes leaves BY NAME from compression, "
                    "but fused buckets dissolve leaf identity (one codec spans "
                    "many leaves) so the pattern would be silently ignored — "
                    "use layer_pattern=None with bucket_bytes, or per-tensor "
                    "codecs with layer_pattern"
                )
            # deferred import: comm_bucket imports PayloadLayout and the
            # decode helpers from this module (same idiom as qar/sparse_rs)
            from deepreduce_tpu.comm_bucket import BucketedExchanger

            self._bucketed = BucketedExchanger(
                self.names,
                [leaf.shape for _, leaf in leaves],
                cfg,
                axis_name=axis_name,
                points=bucket_points,
            )
        else:
            if bucket_points is not None:
                raise ConfigError(
                    "build-bucket-points-need-buckets",
                    "bucket_points is the adaptive controller's per-bucket "
                    "(ratio, fpr) vector for the BUCKETED exchange and would "
                    "be silently ignored without bucket_bytes — set "
                    "bucket_bytes, or bucket_points=None"
                )
            self.codecs = {
                name: TensorCodec(leaf.shape, cfg, name=name)
                for name, (path, leaf) in zip(self.names, leaves)
            }
            if cfg.fused and cfg.communicator == "allgather":
                self._layouts = {}
                for name in self.names:
                    codec = self.codecs[name]
                    g_sds = jax.ShapeDtypeStruct(codec.shape, self._grad_dtypes[name])
                    payload_sds = jax.eval_shape(
                        lambda g, c=codec: c.encode(g, step=0, key=jax.random.PRNGKey(0)),
                        g_sds,
                    )
                    self._layouts[name] = PayloadLayout(
                        payload_sds, checksum=self._checksum
                    )
                    self._offsets[name] = self._fused_nbytes
                    self._fused_nbytes += self._layouts[name].nbytes
        if (
            cfg.decode_strategy != "loop"
            and self._layouts is None
            and self._bucketed is None
        ):
            raise ConfigError(
                "build-decode-strategy-needs-fused-allgather",
                f"decode_strategy={cfg.decode_strategy!r} restructures the "
                "FUSED allgather decode and would be silently ignored here "
                f"(communicator={cfg.communicator!r}, fused={cfg.fused}) — "
                "use fused=True with communicator='allgather', or "
                "decode_strategy='loop'"
            )

    @property
    def num_buckets(self) -> int:
        """Bucket count C of the bucketed exchange; 0 when unbucketed."""
        return self._bucketed.num_buckets if self._bucketed is not None else 0

    @property
    def bucket_specs(self):
        """The static BucketSpec partition (empty tuple when unbucketed)."""
        return self._bucketed.specs if self._bucketed is not None else ()

    # ------------------------------------------------------------------ #

    def init_state(self, grads_like: Any) -> Any:
        # qar + residual is rejected at construction, so no guard needed here
        if self.cfg.memory == "residual":
            return memory.init(grads_like)
        return None

    def _keys(self, key: Optional[jax.Array], step: jax.Array) -> Dict[str, jax.Array]:
        if key is None:
            key = jax.random.PRNGKey(self.cfg.seed)
        return {name: per_tensor_key(key, name, step) for name in self.names}

    def exchange(
        self,
        grads: Any,
        state: Any,
        *,
        step: jax.Array = 0,
        key: Optional[jax.Array] = None,
        collect: Optional[Dict[str, jax.Array]] = None,
        mask: Optional[jax.Array] = None,
    ) -> Tuple[Any, Any, WireStats]:
        """Inside shard_map over `axis_name`: returns (aggregated dense
        grads, new residual state, combined wire stats).

        `collect`, when given a dict, receives worker-local traced
        telemetry scalars the caller psums: ``fp_count`` (index-filter
        positives beyond the payload's in-band selected count — bloom
        false positives, measured by the codec's own `fp_stats` query) and
        ``fp_universe`` (the not-selected universe, the FPR denominator).
        Adds a d-scale filter query per bloom tensor, so only pass it when
        `cfg.telemetry` is enabled.

        `mask` (bool[W], replicated across workers, or None) is the
        elastic-participation vector: a False worker's payload is scaled
        to zero on the decode side and the mean renormalizes by the live
        count (traced `jnp.sum` — never host control flow). Its own-row
        decode is zeroed too, so its residual EF accumulator retains the
        un-sent gradient mass for re-delivery on rejoin."""
        cfg = self.cfg
        if mask is not None and cfg.communicator == "qar":
            raise ValueError(
                "participation masks renormalize the decode-side mean of the "
                "allgather/allreduce paths and re-own reduce-scatter shards "
                "on the sparse_rs routes; communicator='qar' folds the mean "
                "into one int8 psum_scatter with no per-worker decode row to "
                "zero — a masked-out worker's levels are already summed "
                "(see DeepReduceConfig.__post_init__) — use "
                "communicator='allgather', 'allreduce', or 'sparse_rs'"
            )
        num_workers = jax.lax.psum(1, self.axis_name)
        if collect is not None:
            zero = jnp.zeros((), jnp.float32)
            collect.setdefault("fp_count", zero)
            collect.setdefault("fp_universe", zero)
            if self._checksum:
                collect.setdefault("checksum_failures", zero)
        # masked aggregation: weight each worker's decode by its mask entry
        # and divide by the live count instead of W. Both stay None on the
        # mask-free path so the traced program is byte-identical to pre-
        # resilience builds (jx-resilience-off-identical pins this).
        row_weights = None
        denom = None
        if mask is not None:
            row_weights = mask.astype(jnp.float32)
            denom = jnp.maximum(jnp.sum(row_weights), 1.0)

        if cfg.communicator == "qar":
            return self._exchange_qar(grads, state, step=step, key=key)
        if cfg.communicator == "sparse_rs":
            return self._exchange_sparse_rs(
                grads, state, step=step, key=key, collect=collect, mask=mask
            )

        if cfg.communicator == "allreduce" or cfg.deepreduce is None and cfg.compressor == "none":
            # dense baseline: NCCL allreduce -> psum (run_deepreduce.sh:51)
            if mask is not None:
                me = row_weights[jax.lax.axis_index(self.axis_name)]
                agg = jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g * me, self.axis_name) / denom, grads
                )
            else:
                agg = jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, self.axis_name) / num_workers, grads
                )
            dense_bits = sum(
                jnp.asarray(c.d * 32, jnp.float32) for c in self.codecs.values()
            )
            stats = WireStats(
                index_bits=jnp.asarray(0.0, jnp.float32),
                value_bits=dense_bits,
                dense_bits=dense_bits,
            )
            return agg, state, stats

        # worker-distinct randomness for stochastic codecs, shared `step` for
        # the deterministic policy contract
        widx = jax.lax.axis_index(self.axis_name)
        if key is None:
            key = jax.random.PRNGKey(cfg.seed)
        worker_key = jax.random.fold_in(key, widx)

        compensated = grads
        if state is not None:
            compensated = memory.compensate(grads, state, beta=cfg.beta, gamma=cfg.gamma)

        flat_grads = dict(zip(self.names, jax.tree_util.tree_leaves(compensated)))
        need_own = state is not None

        if self._bucketed is not None:
            agg_leaves, own_leaves, stats_per, payloads = self._bucketed.run(
                flat_grads,
                num_workers,
                step,
                worker_key,
                need_own=need_own,
                row_weights=row_weights,
                denom=denom,
                collect=collect,
            )
            codecs = self._bucketed.codecs
            if collect is not None:
                collect["bucket_saturated"] = self._bucketed.saturation_vector(
                    stats_per
                )
        else:
            keys = self._keys(worker_key, step)
            codecs = self.codecs
            payloads = {}
            stats_per = {}
            with spans.span("exchange/encode", route="fused"):
                for name in self.names:
                    payloads[name] = self.codecs[name].encode(
                        flat_grads[name], step=step, key=keys[name]
                    )
                    stats_per[name] = self.codecs[name].wire_stats(payloads[name])

            if self._layouts is not None:
                agg_leaves, own_leaves = self._exchange_fused(
                    payloads,
                    num_workers,
                    step,
                    need_own=need_own,
                    row_weights=row_weights,
                    denom=denom,
                    collect=collect,
                )
            else:
                agg_leaves, own_leaves = self._exchange_per_tensor(
                    payloads,
                    num_workers,
                    step,
                    need_own=need_own,
                    row_weights=row_weights,
                    denom=denom,
                )

        if collect is not None:
            # measured bloom FPR inputs: the codec queries its own payload's
            # filter (codecs expose fp_stats; exact index codecs return
            # None). NOT derivable from the decoded tensor — the decoder
            # places at most nsel values, so its nonzero count never
            # exceeds nsel regardless of how many false positives fired
            fp_c = jnp.zeros((), jnp.float32)
            fp_u = jnp.zeros((), jnp.float32)
            for name, codec in codecs.items():
                stats = codec.fp_stats(payloads[name])
                if stats is None:
                    continue
                fp_c = fp_c + stats[0]
                fp_u = fp_u + stats[1]
            collect["fp_count"] = fp_c
            collect["fp_universe"] = fp_u

        # both paths aggregate/decode in f32; hand leaves back in the runtime
        # gradient dtype so residual state and optimizer updates keep their
        # dtype across steps (bf16 grads stay bf16)
        agg = jax.tree_util.tree_unflatten(
            self.treedef,
            [agg_leaves[n].astype(flat_grads[n].dtype) for n in self.names],
        )
        new_state = state
        if state is not None:
            own = jax.tree_util.tree_unflatten(
                self.treedef,
                [own_leaves[n].astype(flat_grads[n].dtype) for n in self.names],
            )
            new_state = memory.update(compensated, own)
        return agg, new_state, combine(stats_per)

    def _exchange_per_tensor(
        self, payloads, num_workers, step, *, need_own: bool, row_weights=None, denom=None
    ) -> Tuple[Dict[str, jax.Array], Dict[str, jax.Array]]:
        """The reference's shape: one all_gather per gradient tensor
        (pytorch/deepreduce.py:54-61), sequential worker decode. Returns
        f32 leaves; `exchange` casts back to the runtime gradient dtype.
        `row_weights`/`denom` implement masked participation exactly as in
        decode_gathered_loop (weight before both sums, live-count mean)."""
        den = denom if denom is not None else num_workers
        agg_leaves, own_leaves = {}, {}
        for name in self.names:
            codec = self.codecs[name]
            payload = payloads[name]
            if need_own:
                own = codec.decode(payload, step=step).astype(jnp.float32)
                if row_weights is not None:
                    own = own * row_weights[jax.lax.axis_index(self.axis_name)]
                own_leaves[name] = own
            gathered = jax.lax.all_gather(payload, self.axis_name)  # leading axis W

            def body(w, acc, _gathered=gathered, _codec=codec):
                p_w = jax.tree_util.tree_map(lambda x: x[w], _gathered)
                dec = _codec.decode(p_w, step=step)
                if row_weights is not None:
                    dec = dec * jax.lax.dynamic_index_in_dim(
                        row_weights, w, keepdims=False
                    )
                return acc + dec

            acc0 = jnp.zeros(codec.shape, jnp.float32)
            total = jax.lax.fori_loop(0, num_workers, body, acc0)
            agg_leaves[name] = total / den
        return agg_leaves, own_leaves

    def _pack_fused(self, payloads) -> jax.Array:
        """Every tensor's payload bitcast into ONE uint8[B] buffer at the
        static offsets computed in __init__."""
        return jnp.concatenate(
            [self._layouts[n].pack(payloads[n]) for n in self.names]
        )

    def _decode_fused_row(self, row: jax.Array, step) -> Tuple[jax.Array, ...]:
        """One worker's uint8[B] fused buffer -> tuple of dense f32 leaves
        (ordered like self.names). The shared decode program of all three
        decode strategies — bit-compatibility across strategies is this
        function being the single source of truth.

        With payload checksums on, each tensor's decode is gated by its
        layout's `verify` word (failed checksum -> exact zero leaf) and a
        trailing scalar counts the failures in this row; the decode
        helpers treat it as just another f32 output of shape ()."""
        out = []
        fails = jnp.zeros((), jnp.float32)
        for name in self.names:
            layout = self._layouts[name]
            lo = self._offsets[name]
            seg = row[lo : lo + layout.nbytes]
            dec = self.codecs[name].decode(layout.unpack(seg), step=step).astype(
                jnp.float32
            )
            if self._checksum:
                ok = layout.verify(seg)
                # where-select, not `dec * ok`: corrupted bytes can decode
                # to Inf/NaN, and Inf * 0 is NaN — the select yields an
                # exact zero regardless of the decoded garbage
                dec = jnp.where(ok > 0.5, dec, jnp.zeros_like(dec))
                fails = fails + (1.0 - ok)
            out.append(dec)
        if self._checksum:
            out.append(fails)
        return tuple(out)

    def _exchange_fused(
        self,
        payloads,
        num_workers,
        step,
        *,
        need_own: bool,
        row_weights=None,
        denom=None,
        collect=None,
    ) -> Tuple[Dict[str, jax.Array], Dict[str, jax.Array]]:
        """TPU-native shape: every tensor's payload bitcast into ONE uint8
        buffer, then one of three decode strategies (cfg.decode_strategy):

        - 'loop': ONE all_gather for the whole step (ICI sees a single large
          transfer instead of ~T latency-bound small ones), then a single
          fori_loop over workers whose body decodes all tensors. The own-
          payload decode (for residual error-feedback) is folded into the
          same loop with a select at w == my_index, so the decode program is
          traced once, not twice.
        - 'vmap': same all_gather, but the [W, B] buffer is decoded in
          groups of cfg.decode_batch workers under jax.vmap — one big
          batched kernel per group instead of W tiny sequential ones, with
          grouping bounding the W-way peak-memory blowup the loop avoids.
        - 'ring': no all_gather; W-1 double-buffered lax.ppermute hops
          overlap each chunk's transfer with the previous chunk's decode
          (comm_ring.ring_decode_exchange).
        """
        strategy = self.cfg.decode_strategy
        with spans.span("exchange/pack", route="fused"):
            buf = self._pack_fused(payloads)

        if self._chaos is not None:
            # the wire boundary: perturb AFTER pack (checksum included), so
            # the decode side sees corrupt bytes exactly as a lossy
            # transport would deliver them — the own-row decode included
            with spans.span("resilience/chaos"):
                buf = self._chaos.perturb(
                    buf, step=step, worker=jax.lax.axis_index(self.axis_name)
                )

        if strategy == "ring":
            total, own_fin = comm_ring.ring_decode_exchange(
                buf,
                lambda row: self._decode_fused_row(row, step),
                axis_name=self.axis_name,
                num_workers=num_workers,
                need_own=need_own,
                row_weights=row_weights,
            )
        else:
            with spans.span("exchange/allgather", route="fused"):
                gathered = jax.lax.all_gather(buf, self.axis_name)  # [W, B]
            decoder = (
                self._decode_gathered_vmap
                if strategy == "vmap"
                else self._decode_gathered_loop
            )
            with spans.span("exchange/decode", route="fused"):
                total, own_fin = decoder(
                    gathered,
                    num_workers,
                    step,
                    need_own=need_own,
                    row_weights=row_weights,
                )

        if self._checksum:
            # the trailing scalar is the replicated failure count over all
            # gathered rows — every worker decodes the same [W, B] buffer,
            # so no psum is needed. (Masked-out rows are weighted to zero
            # before the sum, so their failures don't count — their
            # contribution was discarded anyway.)
            if collect is not None:
                collect["checksum_failures"] = total[-1]
            total = total[:-1]
            if need_own:
                own_fin = own_fin[:-1]

        den = denom if denom is not None else num_workers
        agg_leaves = {name: t / den for name, t in zip(self.names, total)}
        own_leaves = dict(zip(self.names, own_fin)) if need_own else {}
        return agg_leaves, own_leaves

    def _fused_out_shapes(self) -> Tuple[Tuple[int, ...], ...]:
        shapes = tuple(self.codecs[n].shape for n in self.names)
        if self._checksum:
            shapes = shapes + ((),)  # the per-row checksum-failure count
        return shapes

    def _decode_gathered_loop(
        self, gathered, num_workers, step, *, need_own: bool, row_weights=None
    ) -> Tuple[Tuple[jax.Array, ...], Tuple[jax.Array, ...]]:
        return decode_gathered_loop(
            gathered,
            num_workers,
            lambda row: self._decode_fused_row(row, step),
            self._fused_out_shapes(),
            axis_name=self.axis_name,
            need_own=need_own,
            row_weights=row_weights,
        )

    def _decode_gathered_vmap(
        self, gathered, num_workers, step, *, need_own: bool, row_weights=None
    ) -> Tuple[Tuple[jax.Array, ...], Tuple[jax.Array, ...]]:
        return decode_gathered_vmap(
            gathered,
            num_workers,
            lambda row: self._decode_fused_row(row, step),
            self._fused_out_shapes(),
            axis_name=self.axis_name,
            need_own=need_own,
            decode_batch=self.cfg.decode_batch,
            row_weights=row_weights,
        )

    def _exchange_sparse_rs(
        self,
        grads: Any,
        state: Any,
        *,
        step: jax.Array,
        key: Optional[jax.Array],
        collect: Optional[dict] = None,
        mask: Optional[jax.Array] = None,
    ) -> Tuple[Any, Any, WireStats]:
        """Compressed in-collective allreduce (sparse_rs.py — the Ok-Topk /
        SparCML collective shape, with the adaptive/quantized/sketch routes
        of r11 and the balanced oktopk route of r18 behind `rs_mode`):
        entries routed/reduced inside the collective, re-selected per
        shard, allgathered. Per-worker decode is O(k) (or O(d·rows/W) for
        the sketch route) instead of the allgather path's O(W·k). Residual
        error feedback covers send-side truncation (and quantization/
        sketch noise in those routes; sub-threshold and capacity-spilled
        mass in the oktopk route). `mask` (replicated bool[W]) selects the
        live-mask-aware variants: shard ownership re-assigned over the
        live set by a traced permutation, mean renormalized by the live
        count (sparse_rs.owner_permutation)."""
        from deepreduce_tpu import sparse_rs
        from jax.flatten_util import ravel_pytree

        cfg = self.cfg
        if self.num_workers is None:
            raise ValueError(
                "communicator='sparse_rs' needs the static mesh size: "
                "construct GradientExchanger(..., num_workers=mesh.shape[axis])"
            )
        rs_mode = self._rs_mode
        if rs_mode in ("adaptive", "quantized"):
            # stochastic-rounding routes need per-step randomness; the
            # sparse/sketch routes never touch the key (the default-mode
            # trace stays byte-identical to the pre-r11 program)
            if key is None:
                key = jax.random.PRNGKey(cfg.seed)
            key = jax.random.fold_in(key, jnp.asarray(step, jnp.uint32))
        else:
            key = None
        # encode/decode sub-spans make t_enc/t_dec separately identifiable
        # to costmodel.calibrate; the wire work stays under exchange/sparse_rs.
        # The resolved route name attributes them to the active rs codec so
        # the fit can emit a per-route row.
        with spans.span("exchange/encode", route=rs_mode):
            compensated = grads
            if state is not None:
                compensated = memory.compensate(
                    grads, state, beta=cfg.beta, gamma=cfg.gamma
                )
            flat, unravel = ravel_pytree(compensated)
        with spans.span("exchange/sparse_rs"):
            mean, own_flat, stats = sparse_rs.exchange(
                flat.astype(jnp.float32),
                self.axis_name,
                self.num_workers,
                ratio=cfg.compress_ratio,
                approx_topk=cfg.approx_topk,
                headroom=cfg.rs_headroom,
                out_headroom=cfg.rs_out_headroom,
                rs_mode=rs_mode,
                block_size=cfg.rs_block_size,
                density_threshold=cfg.rs_density_threshold,
                sketch_rows=cfg.rs_sketch_rows,
                sketch_cols=cfg.rs_sketch_cols,
                sketch_seed=cfg.seed,
                oktopk_bins=cfg.rs_oktopk_bins,
                oktopk_cap_headroom=cfg.rs_oktopk_cap_headroom,
                key=key,
                collect=collect,
                mask=mask,
            )
        with spans.span("exchange/decode", route=rs_mode):
            agg = unravel(mean.astype(flat.dtype))
            new_state = state
            if state is not None:
                own = unravel(own_flat.astype(flat.dtype))
                new_state = memory.update(compensated, own)
        return agg, new_state, stats

    def _exchange_qar(
        self, grads: Any, state: Any, *, step: jax.Array, key: Optional[jax.Array]
    ) -> Tuple[Any, Any, WireStats]:
        """Quantized allreduce (qar.py): the whole pytree flattened into ONE
        int8 two-phase exchange — no sparsifier, no residual (unbiased)."""
        from deepreduce_tpu import qar

        cfg = self.cfg
        if self.num_workers is None:
            raise ValueError(
                "communicator='qar' needs the static mesh size: construct "
                "GradientExchanger(..., num_workers=mesh.shape[axis])"
            )
        from jax.flatten_util import ravel_pytree

        with spans.span("exchange/encode", route="qar"):
            flat, unravel = ravel_pytree(grads)
            d = flat.shape[0]
            n = qar.pad_len(d, self.num_workers, cfg.bucket_size)
            # quantization scales and dequantized sums are f32; cast up front
            # so bf16 inputs get f32 bucket norms, and hand leaves back in
            # their own dtype like the psum branch does
            padded = (
                jnp.zeros((n,), jnp.float32).at[:d].set(flat.astype(jnp.float32))
            )
        if key is None:
            key = jax.random.PRNGKey(cfg.seed)
        key = jax.random.fold_in(key, jnp.asarray(step, jnp.uint32))
        with spans.span("exchange/qar"):
            mean = qar.quantized_allreduce(
                padded,
                self.axis_name,
                self.num_workers,
                key=key,
                quantum_num=cfg.quantum_num,
                bucket_size=cfg.bucket_size,
                use_pallas=cfg.use_pallas,
            )[:d]
        with spans.span("exchange/decode", route="qar"):
            agg = unravel(mean.astype(flat.dtype))
        # one payload (int8 levels + f32 norms) per phase-equivalent dense
        # transmission: rel_volume = payload_bits / dense_bits, the same
        # convention the allreduce branch uses (the ring's (W-1)/W factor is
        # identical for both sides of the ratio and cancels).
        # NOTE: this is a *ratio vs the dense-allreduce baseline*, which is
        # the comparable quantity across communicators; `payload_bytes()`
        # reports *absolute per-worker wire bytes* and therefore keeps the
        # explicit 2*(W-1)/W two-phase factor. Do not compare the qar
        # rel_volume against allgather-path payload_bytes directly — use
        # rel_volume for cross-config comparisons (both normalize against
        # their own dense baseline) and payload_bytes for wire sizing.
        payload_bits = n * 8 + (n // cfg.bucket_size) * 32
        stats = WireStats(
            index_bits=jnp.zeros(()),
            value_bits=jnp.asarray(payload_bits, jnp.float32),
            dense_bits=jnp.asarray(d * 32, jnp.float32),
        )
        return agg, state, stats

    # ------------------------------------------------------------------ #

    def payload_bytes(self, grads_like: Any) -> int:
        """Static per-worker wire bytes — what actually crosses ICI each
        step for the configured communicator."""
        if self.cfg.communicator == "qar":
            from deepreduce_tpu import qar

            d = sum(
                int(math.prod(l.shape)) if l.shape else 1
                for l in jax.tree_util.tree_leaves(grads_like)
            )
            if self.num_workers is None:
                raise ValueError("qar payload accounting needs num_workers")
            return int(
                qar.wire_bits_per_worker(d, self.num_workers, self.cfg.bucket_size) // 8
            )
        if self.cfg.communicator == "sparse_rs":
            from deepreduce_tpu import costmodel

            d = sum(
                int(math.prod(l.shape)) if l.shape else 1
                for l in jax.tree_util.tree_leaves(grads_like)
            )
            if self.num_workers is None:
                raise ValueError("sparse_rs payload accounting needs num_workers")
            # per-route injection bytes (sum over the route's collectives);
            # the jx-wire-accounting 'collective' rule pins this against the
            # traced collective operands, route by route
            return int(
                costmodel.rs_payload_bytes(
                    self._rs_mode,
                    d,
                    self.num_workers,
                    self.cfg.compress_ratio,
                    headroom=self.cfg.rs_headroom,
                    out_headroom=self.cfg.rs_out_headroom,
                    block=self.cfg.rs_block_size,
                    rows=self.cfg.rs_sketch_rows,
                    cols=self.cfg.rs_sketch_cols,
                    bins=self.cfg.rs_oktopk_bins,
                    cap_headroom=self.cfg.rs_oktopk_cap_headroom,
                    masked=self.cfg.resilience,
                )
            )
        if self._bucketed is not None:
            # sum of the per-bucket PayloadLayout sizes — exactly what the C
            # bucketed all_gather operands carry (jx-wire-accounting checks
            # this equality against the traced jaxpr)
            return self._bucketed.payload_nbytes
        if self._layouts is not None:
            # the fused buffer's exact byte count — includes the optional
            # per-tensor checksum words, which DO cross the wire (the
            # jx-wire-accounting rule compares this against the traced
            # all_gather operand)
            total = self._fused_nbytes
        else:
            total = 0
            flat = dict(zip(self.names, jax.tree_util.tree_leaves(grads_like)))
            for name, codec in self.codecs.items():
                payload_shape = jax.eval_shape(
                    lambda g, c=codec: c.encode(g, step=0, key=jax.random.PRNGKey(0)),
                    flat[name],
                )
                total += payload_device_bytes(payload_shape)
        if self.cfg.decode_strategy == "ring":
            # explicit W-1 ppermute hops: each forwards the whole fused
            # buffer, so per-worker wire is (W-1)·B, not the allgather
            # path's logical injection B
            if self.num_workers is None:
                raise ValueError(
                    "ring payload accounting needs the static mesh size: "
                    "construct GradientExchanger(..., num_workers=mesh.shape[axis])"
                )
            return ring_wire_bytes(total, self.num_workers)
        return total
