"""Compressed in-collective allreduce — the Ok-Topk / SparCML exchange shape.

The allgather communicator (the reference's only compressed collective,
README.md:37) makes every worker decode every peer's payload: O(W·k) decode
work and W·k wire entries per worker. The sparse-allreduce literature
(PAPERS.md: "Near-Optimal Sparse Allreduce" (Ok-Topk), SparCML, S2 Reducer)
splits the universe into W contiguous shards instead, and this module now
carries five routes over that skeleton, selected by ``rs_mode``:

- ``sparse`` (default; byte-identical trace to the pre-r11 exchange):
    phase 1 (sparse reduce-scatter): each worker routes its top-k entries
        to the shard-owner via `all_to_all` (static per-shard budget,
        largest-|v| kept on overflow — the dropped mass stays in the
        sender's residual by construction); the owner scatter-adds the W
        received slices into a dense shard buffer.
    phase 2 (sparse allgather): the owner re-selects the top k/W of its
        *reduced* shard and `all_gather`s (values, global indices); every
        worker scatters W small payloads into the dense result.
- ``adaptive`` (SparCML's stream-aware switch): same phase 1; after the
    reduce, a traced live-entry count decides per worker whether its
    phase-2 row travels as (values, indices) pairs or as an int8
    block-quantized dense shard (per-block f32 scales, EQuARX style, via
    qar.bucket_quantize). Both encodings ride one static
    ``[max(sparse, dense) + 1]``-lane buffer whose last lane is the flag;
    receivers compute both interpretations and `jnp.where`-select on the
    flag (selection, not masking-by-multiply: the unused interpretation
    bitcasts garbage lanes that may be NaN).
- ``quantized`` (EQuARX reduce-scatter arm): no phase-1 sparsification —
    the whole compensated gradient is int8 block-quantized against
    `pmax`-shared per-block norms (shared scales + level budget
    ``127 // W`` make the int8 `psum_scatter` an exact integer sum), each
    worker dequantizes its summed shard and re-enters the sparse phase 2.
- ``sketch`` (S2 Reducer): the top-k selection (sortless
    `sparse.topk_sampled`) is count-sketched (codecs.countsketch); one
    `psum` sums the linear sketches in-collective; each worker unsketches
    only *its shard* (O(d·rows/W) — the decode itself is sharded) and
    re-enters the sparse phase 2. Error feedback uses the unsketch
    estimate of the worker's own sketch at the globally selected indices.
- ``oktopk`` (the Ok-Topk balanced exchange proper): a psum'd magnitude
    histogram over each worker's local top-k candidates picks ONE global
    threshold targeting ~k total survivors (bit-pattern bucketing — for
    positive f32 the int32 interpretation is monotonic in value, so
    ``bitcast(|v|) >> shift`` is a shared magnitude quantizer needing no
    scale agreement); only coordinates at-or-above the threshold are
    routed to their shard-owners through the same stable-sort all_to_all,
    but with a W×-smaller per-(worker, shard) capacity ``~k/W²·headroom``
    since the *global* survivor count is ~k, not k per worker. Sub-
    threshold mass AND capacity-spilled mass both stay in the sender's
    residual (own-transmitted EF counts kept entries only). Owner-local
    reduction and the sparse phase 2 are unchanged. Per-worker wire is
    O(k/W) + the fixed histogram — O(k) total across the mesh, the
    Ok-Topk headline.

Per-worker wire ~ k·headroom + k entries vs the allgather path's W·k, and
decode is O(k) instead of O(W·k) — the gap grows with the mesh. The phase-2
re-selection is lossy (Ok-Topk §4 accepts the same truncation; its mass is
bounded by the per-shard budget) while phase-1 truncation is error-fed back
like any sparsifier.

All static-shape: budgets derive from (d, ratio, W) at trace time; live
counts ride in-band; the adaptive switch is data on the wire, not a trace
decision. Runs inside shard_map over the data axis. Mode selection
(including ``auto`` via deepreduce_tpu.costmodel.select_rs_mode) happens at
GradientExchanger construction — `exchange` receives a concrete mode.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu import qar, sparse
from deepreduce_tpu.codecs import countsketch
from deepreduce_tpu.metrics import WireStats
from deepreduce_tpu.telemetry import spans

RS_EXCHANGE_MODES = ("sparse", "adaptive", "quantized", "sketch", "oktopk")


def shard_size(d: int, num_workers: int) -> int:
    return (d + num_workers - 1) // num_workers


def send_budget(d: int, ratio: float, num_workers: int, headroom: float) -> int:
    """Per-shard slots in the phase-1 all_to_all: expected k/W occupancy
    times headroom (top-k positions are ~uniform over shards; Poisson
    fluctuation at k/W ~ thousands is a few percent, so a modest headroom
    captures nearly all mass — what overflows stays in the residual)."""
    k = sparse.num_slots(d, ratio)
    return max(1, int(math.ceil(k / num_workers * headroom)))


def out_budget(
    d: int, ratio: float, num_workers: int, headroom: float = 1.0
) -> int:
    """Phase-2 slots per shard: k/W (total across shards == k — the
    Ok-Topk output-volume convention) times an optional headroom, capped
    at the shard size."""
    k = sparse.num_slots(d, ratio)
    b = max(1, int(math.ceil(k / num_workers * headroom)))
    return min(b, shard_size(d, num_workers))


def padded_shard(d: int, num_workers: int, block: int) -> int:
    """Shard length rounded up to whole quantization blocks (adaptive
    phase-2 dense rows and the quantized arm both need block-aligned
    shards)."""
    s = shard_size(d, num_workers)
    return ((s + block - 1) // block) * block


def adaptive_lanes(
    d: int, ratio: float, num_workers: int, out_headroom: float, block: int
) -> int:
    """f32 lanes in the adaptive phase-2 row, excluding the +1 flag lane:
    max of the sparse encoding (2 lanes per phase-2 slot) and the dense
    encoding (int8 levels bitcast 4-per-lane + one f32 norm per block)."""
    sp = padded_shard(d, num_workers, block)
    dense_lanes = sp // 4 + sp // block
    sparse_lanes = 2 * out_budget(d, ratio, num_workers, out_headroom)
    return max(sparse_lanes, dense_lanes)


def quantized_levels_budget(num_workers: int) -> int:
    """Max |level| each worker may emit so the W-worker int8 sum cannot
    exceed 127: with pmax-shared norms every worker's stochastic level is
    bounded by this, and W * (127 // W) <= 127."""
    return max(1, 127 // num_workers)


def oktopk_send_budget(
    d: int, ratio: float, num_workers: int, cap_headroom: float = 2.0
) -> int:
    """Per-(worker, shard) slots in the oktopk all_to_all: the global
    threshold targets ~k survivors TOTAL, so one worker holds ~k/W of them
    and spreads those over W shards — expected occupancy k/W² per pair,
    times headroom. Overflow (and the degenerate all-equal-magnitude case
    where every candidate ties at the threshold bucket) spills into the
    sender's residual."""
    k = sparse.num_slots(d, ratio)
    return max(1, int(math.ceil(k / (num_workers * num_workers) * cap_headroom)))


def oktopk_shift(bins: int) -> int:
    """Right-shift turning a positive-f32 bit pattern into a histogram
    bucket in [0, bins): finite positive f32 patterns live in [0, 2^31),
    so `31 - log2(bins)` maps them onto exactly `bins` buckets while
    preserving magnitude order (bit-pattern order == value order for
    non-negative floats). With the 4096-bin default each exponent octave
    gets 16 sub-bins — ~4% relative threshold granularity."""
    return 31 - int(round(math.log2(bins)))


def exchange(
    flat: jax.Array,
    axis_name: str,
    num_workers: int,
    *,
    ratio: float,
    approx_topk: bool = False,
    headroom: float = 2.0,
    out_headroom: float = 1.0,
    rs_mode: str = "sparse",
    block_size: int = 256,
    density_threshold: float = 1.0,
    sketch_rows: int = 5,
    sketch_cols: int = 0,
    sketch_seed: int = 0,
    oktopk_bins: int = 4096,
    oktopk_cap_headroom: float = 2.0,
    key: Optional[jax.Array] = None,
    collect: Optional[dict] = None,
    mask: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, WireStats]:
    """-> (mean gradient f32[d], own-transmitted dense f32[d] for error
    feedback, wire stats). Call inside shard_map over `axis_name`.

    `rs_mode` must be one of RS_EXCHANGE_MODES (``auto`` is resolved by the
    caller). `key` is required by the stochastic-rounding routes (adaptive,
    quantized). `collect`, when a dict, receives the adaptive route's
    density/switch observables and the oktopk route's survivor/threshold/
    spill observables.

    `mask` (replicated bool/f32[W], the resilience participation mask)
    selects the live-mask-aware variants of the sparse / quantized /
    oktopk routes: shard ownership is re-assigned over the live set by a
    traced permutation (`owner_permutation`), dropped contributions are
    zeroed on both the send and receive side, and the mean renormalizes
    by the live count — one static trace, mask as traced data, with the
    all-ones mask bitwise-equal to the mask-free route on the exchanged
    outputs. The adaptive and sketch routes bake per-worker lane/sketch
    state into the wire layout that no deputy can reproduce; they refuse
    the mask (config fences them as resilience-vs-owner-communicator)."""
    if mask is not None:
        if rs_mode == "sparse":
            return _exchange_sparse_masked(
                flat, axis_name, num_workers, ratio=ratio,
                approx_topk=approx_topk, headroom=headroom,
                out_headroom=out_headroom, mask=mask,
            )
        if rs_mode == "quantized":
            return _exchange_quantized_masked(
                flat, axis_name, num_workers, ratio=ratio,
                out_headroom=out_headroom, block=block_size, key=key,
                mask=mask,
            )
        if rs_mode == "oktopk":
            return _exchange_oktopk_masked(
                flat, axis_name, num_workers, ratio=ratio,
                out_headroom=out_headroom, bins=oktopk_bins,
                cap_headroom=oktopk_cap_headroom, collect=collect, mask=mask,
            )
        raise ValueError(
            f"rs_mode={rs_mode!r} has no live-mask-aware variant (adaptive "
            "lane switches and sketch rows are per-worker wire state no "
            "deputy can re-own) — config fences this as "
            "resilience-vs-owner-communicator"
        )
    if rs_mode == "sparse":
        return _exchange_sparse(
            flat, axis_name, num_workers, ratio=ratio, approx_topk=approx_topk,
            headroom=headroom, out_headroom=out_headroom,
        )
    if rs_mode == "adaptive":
        return _exchange_adaptive(
            flat, axis_name, num_workers, ratio=ratio, approx_topk=approx_topk,
            headroom=headroom, out_headroom=out_headroom, block=block_size,
            density_threshold=density_threshold, key=key, collect=collect,
        )
    if rs_mode == "quantized":
        return _exchange_quantized(
            flat, axis_name, num_workers, ratio=ratio,
            out_headroom=out_headroom, block=block_size, key=key,
        )
    if rs_mode == "sketch":
        return _exchange_sketch(
            flat, axis_name, num_workers, ratio=ratio,
            out_headroom=out_headroom, rows=sketch_rows, cols=sketch_cols,
            seed=sketch_seed,
        )
    if rs_mode == "oktopk":
        if approx_topk:
            # the threshold-count containment argument needs the local
            # candidate set to be the EXACT top-k: an approximate selection
            # can miss above-threshold entries, biasing the psum'd survivor
            # count the threshold is solved against (config fences this as
            # 'rs-oktopk-vs-approx-topk'; this is the traced-path backstop)
            raise ValueError(
                "rs_mode='oktopk' requires exact local top-k candidates "
                "(approx_topk=False)"
            )
        return _exchange_oktopk(
            flat, axis_name, num_workers, ratio=ratio,
            out_headroom=out_headroom, bins=oktopk_bins,
            cap_headroom=oktopk_cap_headroom, collect=collect,
        )
    raise ValueError(
        f"rs_mode={rs_mode!r} is not a concrete sparse_rs route "
        f"(expected one of {RS_EXCHANGE_MODES}; 'auto' must be resolved by "
        "the caller via costmodel.select_rs_mode)"
    )


def _phase1_route(flat, axis_name, W, S, B, *, ratio, approx_topk, route=None):
    """Shared phase 1: top-k select, route entries to their shard-owners
    through one all_to_all, scatter-add into the owner's dense shard.
    Returns (shard_buf f32[S], keep mask, routed idxs/vals, pos) — the
    latter three feed the own-transmitted EF scatter."""
    # sort_indices=False keeps lax.top_k's descending-|v| order — the
    # overflow-drop-smallest property below depends on it
    with spans.span("sparse_rs/select", route=route):
        sp = sparse.topk(flat, ratio, sort_indices=False, approx=approx_topk)
    k = sp.k

    live = jnp.arange(k, dtype=jnp.int32) < sp.nnz
    shard_of = jnp.where(live, sp.indices // S, W)  # dead -> parked shard W
    # stable sort by shard keeps lax.top_k's descending-|v| order within
    # each shard, so budget overflow drops the smallest magnitudes
    order = jnp.argsort(shard_of, stable=True)
    sh = shard_of[order]
    vals = sp.values[order]
    idxs = sp.indices[order]
    # per-shard rank = position within my shard's run
    pos = jnp.arange(k, dtype=jnp.int32)
    first_of_run = jnp.where(
        jnp.concatenate([jnp.ones((1,), bool), sh[1:] != sh[:-1]]), pos, -1
    )
    run_start = jax.lax.cummax(first_of_run)
    rank = pos - run_start
    keep = jnp.logical_and(sh < W, rank < B)
    # scatter into the [W, B] send matrix (unique targets by construction)
    tgt = jnp.where(keep, sh * B + rank, W * B + pos)
    send_v = (
        jnp.zeros((W * B,), flat.dtype)
        .at[tgt].set(vals, mode="drop", unique_indices=True)
        .reshape(W, B)
    )
    # local index within the shard; dead slots point at 0 with value 0
    send_i = (
        jnp.zeros((W * B,), jnp.int32)
        .at[tgt].set(idxs - sh * S, mode="drop", unique_indices=True)
        .reshape(W, B)
    )
    # ONE collective per phase: ride the indices next to the values as
    # bitcast f32 lanes in the same buffer (the fused-allgather pattern)
    send_buf = jnp.concatenate(
        [send_v.astype(jnp.float32),
         jax.lax.bitcast_convert_type(send_i, jnp.float32)], axis=1
    )  # [W, 2B]
    with spans.span("sparse_rs/route", route=route):
        rx = jax.lax.all_to_all(
            send_buf, axis_name, split_axis=0, concat_axis=0, tiled=True
        )
    rx_v = rx[:, :B]
    rx_i = jax.lax.bitcast_convert_type(rx[:, B:], jnp.int32)

    with spans.span("sparse_rs/reduce", route=route):
        shard_buf = jnp.zeros((S,), jnp.float32).at[rx_i.reshape(-1)].add(
            rx_v.reshape(-1).astype(jnp.float32)
        )
    # zero-value dead slots all land on local index 0: adding 0 is exact
    return shard_buf, keep, idxs, vals, pos


def _own_transmitted(flat, keep, idxs, vals, pos, W, S, d):
    """Own-transmitted mass (what actually left this worker, phase-1
    truncation applied) for residual error feedback; dead/overflow slots
    park at unique out-of-range targets."""
    return (
        jnp.zeros((W * S,), flat.dtype)
        .at[jnp.where(keep, idxs, W * S + pos)]
        .set(vals, mode="drop", unique_indices=True)[:d]
    )


def _phase2_pack(shard_est, widx, S, K2):
    """Re-select the reduced/estimated shard: -> [2*K2] f32 buffer with
    bitcast global indices in the upper lanes."""
    mag = jnp.abs(shard_est)
    top_v, top_i = jax.lax.top_k(mag, K2)
    out_vals = shard_est[top_i]
    out_idx = (top_i + widx * S).astype(jnp.int32)
    return jnp.concatenate(
        [out_vals.astype(jnp.float32),
         jax.lax.bitcast_convert_type(out_idx, jnp.float32)]
    )


def _phase2_unpack(gathered, K2, W, S):
    """-> (values f32[W*K2], clipped global indices i32[W*K2], dense mean
    numerator f32[W*S])."""
    gathered_v = gathered[:, :K2]
    gathered_i = jax.lax.bitcast_convert_type(gathered[:, K2:], jnp.int32)
    gi = jnp.clip(gathered_i.reshape(-1), 0, W * S - 1)
    dense = jnp.zeros((W * S,), jnp.float32).at[gi].add(
        gathered_v.reshape(-1)
    )
    return gathered_v.reshape(-1), gi, dense


def _exchange_sparse(
    flat, axis_name, num_workers, *, ratio, approx_topk, headroom, out_headroom
):
    """The pre-r11 route, body unchanged — the all-modes-off trace must stay
    byte-identical to the r10 baseline."""
    d = flat.shape[0]
    W = num_workers
    S = shard_size(d, W)
    B = send_budget(d, ratio, W, headroom)
    K2 = out_budget(d, ratio, W, out_headroom)

    # sort_indices=False keeps lax.top_k's descending-|v| order — the
    # overflow-drop-smallest property below depends on it
    with spans.span("sparse_rs/select", route="sparse"):
        sp = sparse.topk(flat, ratio, sort_indices=False, approx=approx_topk)
    k = sp.k

    # --- phase 1: route entries to their shard-owners ------------------- #
    live = jnp.arange(k, dtype=jnp.int32) < sp.nnz
    shard_of = jnp.where(live, sp.indices // S, W)  # dead -> parked shard W
    # stable sort by shard keeps lax.top_k's descending-|v| order within
    # each shard, so budget overflow drops the smallest magnitudes
    order = jnp.argsort(shard_of, stable=True)
    sh = shard_of[order]
    vals = sp.values[order]
    idxs = sp.indices[order]
    # per-shard rank = position within my shard's run
    pos = jnp.arange(k, dtype=jnp.int32)
    first_of_run = jnp.where(
        jnp.concatenate([jnp.ones((1,), bool), sh[1:] != sh[:-1]]), pos, -1
    )
    run_start = jax.lax.cummax(first_of_run)
    rank = pos - run_start
    keep = jnp.logical_and(sh < W, rank < B)
    # scatter into the [W, B] send matrix (unique targets by construction)
    tgt = jnp.where(keep, sh * B + rank, W * B + pos)
    send_v = (
        jnp.zeros((W * B,), flat.dtype)
        .at[tgt].set(vals, mode="drop", unique_indices=True)
        .reshape(W, B)
    )
    # local index within the shard; dead slots point at 0 with value 0
    send_i = (
        jnp.zeros((W * B,), jnp.int32)
        .at[tgt].set(idxs - sh * S, mode="drop", unique_indices=True)
        .reshape(W, B)
    )
    # ONE collective per phase: ride the indices next to the values as
    # bitcast f32 lanes in the same buffer (the fused-allgather pattern)
    send_buf = jnp.concatenate(
        [send_v.astype(jnp.float32),
         jax.lax.bitcast_convert_type(send_i, jnp.float32)], axis=1
    )  # [W, 2B]
    with spans.span("sparse_rs/route", route="sparse"):
        rx = jax.lax.all_to_all(
            send_buf, axis_name, split_axis=0, concat_axis=0, tiled=True
        )
    rx_v = rx[:, :B]
    rx_i = jax.lax.bitcast_convert_type(rx[:, B:], jnp.int32)

    # --- reduce my shard ------------------------------------------------- #
    with spans.span("sparse_rs/reduce", route="sparse"):
        shard_buf = jnp.zeros((S,), jnp.float32).at[rx_i.reshape(-1)].add(
            rx_v.reshape(-1).astype(jnp.float32)
        )
    # zero-value dead slots all land on local index 0: adding 0 is exact

    # --- phase 2: re-select the reduced shard and allgather -------------- #
    widx = jax.lax.axis_index(axis_name)
    mag = jnp.abs(shard_buf)
    top_v, top_i = jax.lax.top_k(mag, K2)
    out_vals = shard_buf[top_i]
    out_idx = (top_i + widx * S).astype(jnp.int32)
    out_buf = jnp.concatenate(
        [out_vals.astype(jnp.float32),
         jax.lax.bitcast_convert_type(out_idx, jnp.float32)]
    )  # [2*K2]
    with spans.span("sparse_rs/allgather", route="sparse"):
        gathered = jax.lax.all_gather(out_buf, axis_name)  # [W, 2*K2]
    gathered_v = gathered[:, :K2]
    gathered_i = jax.lax.bitcast_convert_type(gathered[:, K2:], jnp.int32)
    dense = (
        jnp.zeros((W * S,), jnp.float32)
        .at[jnp.clip(gathered_i.reshape(-1), 0, W * S - 1)]
        .add(gathered_v.reshape(-1))[:d]
    )
    # indices are globally unique (each worker owns a disjoint shard and
    # top_k returns distinct positions), so add == set; mean over workers
    mean = dense / W

    # own-transmitted mass (what actually left this worker, phase-1
    # truncation applied) for residual error feedback; dead/overflow slots
    # park at unique out-of-range targets
    own_dense = (
        jnp.zeros((W * S,), flat.dtype)
        .at[jnp.where(keep, idxs, W * S + pos)]
        .set(vals, mode="drop", unique_indices=True)[:d]
    )

    # wire accounting: every transmitted entry is an f32 value + i32 index
    # (phase 1: W*B slots out per worker; phase 2: K2 slots gathered out)
    stats = WireStats(
        index_bits=jnp.asarray((W * B + K2) * 32.0, jnp.float32),
        value_bits=jnp.asarray((W * B + K2) * 32.0, jnp.float32),
        dense_bits=jnp.asarray(d * 32.0, jnp.float32),
    )
    return mean.astype(flat.dtype), own_dense, stats


def _exchange_adaptive(
    flat, axis_name, num_workers, *, ratio, approx_topk, headroom,
    out_headroom, block, density_threshold, key, collect,
):
    """Sparse phase 1, density-switched phase 2: each worker's gathered row
    is either (values, bitcast indices) or an int8 block-quantized dense
    shard, flagged in-band. The switch is traced data — one static trace
    covers both branches on both sides of the collective."""
    if key is None:
        raise ValueError("rs_mode='adaptive' needs a PRNG key (stochastic "
                         "rounding of the dense phase-2 rows)")
    d = flat.shape[0]
    W = num_workers
    S = shard_size(d, W)
    Sp = padded_shard(d, W, block)
    B = send_budget(d, ratio, W, headroom)
    K2 = out_budget(d, ratio, W, out_headroom)
    L = adaptive_lanes(d, ratio, W, out_headroom, block)
    q = 127  # per-row dequantize is per-worker — no summation, full int8 range

    shard_buf, keep, idxs, vals, pos = _phase1_route(
        flat, axis_name, W, S, B, ratio=ratio, approx_topk=approx_topk,
        route="adaptive",
    )
    widx = jax.lax.axis_index(axis_name)

    # --- traced density decision ---------------------------------------- #
    live_count = jnp.sum((shard_buf != 0.0).astype(jnp.float32))
    density = live_count / float(S)
    go_dense = (density > density_threshold).astype(jnp.float32)
    if collect is not None:
        collect["rs_density"] = density
        collect["rs_dense_switches"] = go_dense

    # --- both phase-2 encodings over one static buffer ------------------- #
    sparse_row = jnp.zeros((L,), jnp.float32).at[: 2 * K2].set(
        _phase2_pack(shard_buf, widx, S, K2)
    )
    with spans.span("sparse_rs/adaptive-quantize", route="adaptive"):
        levels, norms = qar.bucket_quantize(
            jnp.zeros((Sp,), jnp.float32).at[:S].set(shard_buf),
            q, block, jax.random.fold_in(key, widx),
        )
    lv_lanes = jax.lax.bitcast_convert_type(levels.reshape(Sp // 4, 4), jnp.float32)
    dense_row = jnp.zeros((L,), jnp.float32).at[: Sp // 4 + Sp // block].set(
        jnp.concatenate([lv_lanes, norms])
    )
    row = jnp.concatenate(
        [jnp.where(go_dense > 0.5, dense_row, sparse_row), go_dense[None]]
    )  # [L+1]
    with spans.span("sparse_rs/allgather", route="adaptive"):
        gathered = jax.lax.all_gather(row, axis_name)  # [W, L+1]

    # --- decode both interpretations, select on the flag ----------------- #
    flags = gathered[:, L]  # [W]
    body = gathered[:, :L]
    # sparse interpretation (garbage lanes under a dense flag may be NaN —
    # jnp.where *selects*, so they never reach the accumulator)
    s_vals = body[:, :K2]
    s_idx = jax.lax.bitcast_convert_type(body[:, K2 : 2 * K2], jnp.int32)
    s_contrib = jnp.zeros((W * S,), jnp.float32).at[
        jnp.clip(s_idx.reshape(-1), 0, W * S - 1)
    ].add(
        jnp.where(flags[:, None] < 0.5, s_vals, 0.0).reshape(-1)
    )
    # dense interpretation: per-row int8 dequantize, rows masked by flag
    lv_rx = jax.lax.bitcast_convert_type(
        body[:, : Sp // 4], jnp.int8
    ).reshape(W, Sp)
    nm_rx = body[:, Sp // 4 : Sp // 4 + Sp // block]
    deq = jax.vmap(lambda l, nm: qar.bucket_dequantize(l, nm, q, block))(
        lv_rx, nm_rx
    )  # [W, Sp]
    d_contrib = jnp.where(
        flags[:, None] > 0.5, jnp.nan_to_num(deq[:, :S]), 0.0
    ).reshape(W * S)
    mean = (s_contrib + d_contrib)[:d] / W

    own_dense = _own_transmitted(flat, keep, idxs, vals, pos, W, S, d)
    stats = WireStats(
        index_bits=jnp.asarray(W * B * 32.0, jnp.float32),
        value_bits=jnp.asarray((W * B + L + 1) * 32.0, jnp.float32),
        dense_bits=jnp.asarray(d * 32.0, jnp.float32),
    )
    return mean.astype(flat.dtype), own_dense, stats


def _exchange_quantized(
    flat, axis_name, num_workers, *, ratio, out_headroom, block, key
):
    """EQuARX-style phase 1: int8 block quantization against pmax-shared
    norms, exact integer in-collective sum via psum_scatter, then the
    sparse phase-2 re-select over the dequantized summed shard. No
    phase-1 sparsifier — stochastic rounding is unbiased and its realized
    noise lands in the residual via the own-contribution estimate."""
    if key is None:
        raise ValueError("rs_mode='quantized' needs a PRNG key (stochastic "
                         "rounding of the int8 levels)")
    d = flat.shape[0]
    W = num_workers
    n = padded_shard(d, W, block) * W
    Ssh = n // W
    K2 = out_budget(d, ratio, W, out_headroom)
    q = quantized_levels_budget(W)
    widx = jax.lax.axis_index(axis_name)

    gp = jnp.zeros((n,), jnp.float32).at[:d].set(flat)
    # pmax-shared norms: every worker's per-element magnitude is bounded by
    # its local block L2 norm, hence by the shared max — so each stochastic
    # level is <= q and the W-worker int8 sum cannot exceed W*q <= 127
    norms_local = jnp.linalg.norm(gp.reshape(-1, block), axis=1)
    with spans.span("sparse_rs/norm-pmax", route="quantized"):
        norms_shared = jax.lax.pmax(norms_local, axis_name)
    with spans.span("sparse_rs/quantize", route="quantized"):
        levels, _ = qar.bucket_quantize(
            gp, q, block, jax.random.fold_in(key, widx), norms=norms_shared
        )
    with spans.span("sparse_rs/reduce-scatter", route="quantized"):
        summed = jax.lax.psum_scatter(
            levels, axis_name, scatter_dimension=0, tiled=True
        )  # int8[Ssh] — exact: levels bounded so the sum never wraps
    my_norms = jax.lax.dynamic_slice(
        norms_shared, (widx * (Ssh // block),), (Ssh // block,)
    )
    shard_est = qar.bucket_dequantize(summed, my_norms, q, block)

    # --- phase 2: sparse re-select + allgather --------------------------- #
    out_buf = _phase2_pack(shard_est, widx, Ssh, K2)
    with spans.span("sparse_rs/allgather", route="quantized"):
        gathered = jax.lax.all_gather(out_buf, axis_name)  # [W, 2*K2]
    _, gi, dense = _phase2_unpack(gathered, K2, W, Ssh)
    mean = dense[:d] / W

    # own contribution = my dequantized levels at the globally selected
    # indices (disjoint shards -> unique indices, add == set)
    my_deq = qar.bucket_dequantize(levels, norms_shared, q, block)
    own_dense = jnp.zeros((W * Ssh,), jnp.float32).at[gi].add(my_deq[gi])[:d]

    stats = WireStats(
        index_bits=jnp.asarray(K2 * 32.0, jnp.float32),
        value_bits=jnp.asarray(
            n * 8.0 + (n // block) * 32.0 + K2 * 32.0, jnp.float32
        ),
        dense_bits=jnp.asarray(d * 32.0, jnp.float32),
    )
    return mean.astype(flat.dtype), own_dense.astype(flat.dtype), stats


def _exchange_sketch(
    flat, axis_name, num_workers, *, ratio, out_headroom, rows, cols, seed
):
    """S2-Reducer phase 1: count-sketch the (sortless sampled) top-k
    selection, sum the linear sketches with one psum, unsketch only this
    worker's shard, then the sparse phase-2 re-select + allgather. Decode
    work is O(d·rows/W) per worker — sharded, unlike the fused path's
    O(W·k)."""
    d = flat.shape[0]
    W = num_workers
    S = shard_size(d, W)
    K2 = out_budget(d, ratio, W, out_headroom)
    k = sparse.num_slots(d, ratio)
    C = cols if cols > 0 else max(256, int(math.ceil(2.0 * k / max(1, rows))))
    widx = jax.lax.axis_index(axis_name)

    with spans.span("sparse_rs/select", route="sketch"):
        sp = sparse.topk_sampled(flat, ratio, k=k)
    live = jnp.arange(sp.k, dtype=jnp.int32) < sp.nnz
    sel_vals = jnp.where(live, sp.values, 0.0)
    with spans.span("sparse_rs/sketch", route="sketch"):
        sk = countsketch.sketch_from_sparse(
            sel_vals, sp.indices, rows, C, seed=seed
        )
    with spans.span("sparse_rs/psum", route="sketch"):
        summed = jax.lax.psum(sk, axis_name)  # linear: sketch of the sum

    # --- unsketch my shard only ------------------------------------------ #
    with spans.span("sparse_rs/unsketch", route="sketch"):
        shard_idx = jnp.arange(S, dtype=jnp.int32) + widx * S
        shard_est = countsketch.unsketch_at(summed, shard_idx, seed=seed)

    # --- phase 2: sparse re-select + allgather --------------------------- #
    out_buf = _phase2_pack(shard_est, widx, S, K2)
    with spans.span("sparse_rs/allgather", route="sketch"):
        gathered = jax.lax.all_gather(out_buf, axis_name)  # [W, 2*K2]
    _, gi, dense = _phase2_unpack(gathered, K2, W, S)
    mean = dense[:d] / W

    # error feedback via the unsketch estimate of *my own* sketch at the
    # globally selected coordinates — what this worker effectively
    # contributed to the decoded mean, collision noise included
    own_est = countsketch.unsketch_at(sk, gi, seed=seed)
    own_dense = jnp.zeros((W * S,), jnp.float32).at[gi].add(own_est)[:d]

    stats = WireStats(
        index_bits=jnp.asarray(K2 * 32.0, jnp.float32),
        value_bits=jnp.asarray((rows * C + K2) * 32.0, jnp.float32),
        dense_bits=jnp.asarray(d * 32.0, jnp.float32),
    )
    return mean.astype(flat.dtype), own_dense.astype(flat.dtype), stats


def _exchange_oktopk(
    flat, axis_name, num_workers, *, ratio, out_headroom, bins,
    cap_headroom, collect,
):
    """Ok-Topk phase 1: one psum'd bit-pattern magnitude histogram over the
    local exact top-k candidates picks a single global threshold targeting
    ~k survivors TOTAL; survivors route to their shard-owners through the
    stable-sort all_to_all with a W×-smaller per-pair capacity. Containment
    argument: every global survivor is, at its own worker, one of at most k
    entries at-or-above a threshold that globally admits ~k — so the local
    exact top-k candidate set cannot miss it. Deterministic (no PRNG): the
    only losses are sub-threshold mass and capacity spill, both of which
    stay in the residual via the kept-entries-only own-transmitted EF."""
    d = flat.shape[0]
    W = num_workers
    S = shard_size(d, W)
    Bo = oktopk_send_budget(d, ratio, W, cap_headroom)
    K2 = out_budget(d, ratio, W, out_headroom)
    shift = oktopk_shift(bins)

    # encode phase (histogram + select + routing pack) under an
    # exchange/encode sub-span so calibrate can see this route's codec
    # compute; the nested wire spans (psum) keep their own category — the
    # interval-stack self-time subtraction never double-charges them
    with spans.span("exchange/encode", route="oktopk"):
        # --- candidates: local exact top-k (descending |v| order) ------- #
        with spans.span("sparse_rs/select", route="oktopk"):
            sp = sparse.topk(flat, ratio, sort_indices=False, approx=False)
        k = sp.k
        live = jnp.arange(k, dtype=jnp.int32) < sp.nnz
        mag = jnp.where(live, jnp.abs(sp.values), 0.0).astype(jnp.float32)

        # --- global threshold from one psum'd histogram ----------------- #
        # non-negative f32 bit patterns sort like the values, so the shifted
        # pattern is a shared magnitude bucket — no scale agreement (no pmax)
        bucket = jnp.right_shift(
            jax.lax.bitcast_convert_type(mag, jnp.int32), shift
        )
        weight = jnp.logical_and(live, mag > 0.0).astype(jnp.float32)
        hist = jnp.zeros((bins,), jnp.float32).at[bucket].add(weight)
        # zero-weight dead slots land in bucket 0: adding 0 is exact
        with spans.span("sparse_rs/psum", route="oktopk"):
            g_hist = jax.lax.psum(hist, axis_name)
        # cum[j] = global count of candidates in bucket >= j; the threshold
        # is the HIGHEST bucket still admitting >= k entries. All-false
        # (fewer than k nonzero candidates in the whole mesh) degrades to
        # bucket 0 — every nonzero entry survives, which is correct:
        # total < k.
        cum = jnp.flip(jnp.cumsum(jnp.flip(g_hist)))
        ok = cum >= float(k)
        b_star = jnp.max(
            jnp.where(ok, jnp.arange(bins, dtype=jnp.int32), 0)
        )
        survive = jnp.logical_and(
            jnp.logical_and(live, mag > 0.0), bucket >= b_star
        )

        # --- balanced routing: survivors only, capacity Bo per pair ----- #
        shard_of = jnp.where(survive, sp.indices // S, W)  # dead -> parked W
        # stable sort by shard keeps the descending-|v| candidate order
        # within each shard, so capacity overflow drops the smallest
        # magnitudes
        order = jnp.argsort(shard_of, stable=True)
        sh = shard_of[order]
        vals = sp.values[order]
        idxs = sp.indices[order]
        pos = jnp.arange(k, dtype=jnp.int32)
        first_of_run = jnp.where(
            jnp.concatenate([jnp.ones((1,), bool), sh[1:] != sh[:-1]]), pos, -1
        )
        run_start = jax.lax.cummax(first_of_run)
        rank = pos - run_start
        keep = jnp.logical_and(sh < W, rank < Bo)
        tgt = jnp.where(keep, sh * Bo + rank, W * Bo + pos)
        send_v = (
            jnp.zeros((W * Bo,), flat.dtype)
            .at[tgt].set(vals, mode="drop", unique_indices=True)
            .reshape(W, Bo)
        )
        send_i = (
            jnp.zeros((W * Bo,), jnp.int32)
            .at[tgt].set(idxs - sh * S, mode="drop", unique_indices=True)
            .reshape(W, Bo)
        )
        send_buf = jnp.concatenate(
            [send_v.astype(jnp.float32),
             jax.lax.bitcast_convert_type(send_i, jnp.float32)], axis=1
        )  # [W, 2*Bo]
    with spans.span("sparse_rs/route", route="oktopk"):
        rx = jax.lax.all_to_all(
            send_buf, axis_name, split_axis=0, concat_axis=0, tiled=True
        )
    # decode phase (scatter-reduce + phase-2 re-select + unpack) under an
    # exchange/decode sub-span; the nested allgather stays wire
    with spans.span("exchange/decode", route="oktopk"):
        rx_v = rx[:, :Bo]
        rx_i = jax.lax.bitcast_convert_type(rx[:, Bo:], jnp.int32)
        with spans.span("sparse_rs/reduce", route="oktopk"):
            shard_buf = jnp.zeros((S,), jnp.float32).at[rx_i.reshape(-1)].add(
                rx_v.reshape(-1).astype(jnp.float32)
            )

        # --- phase 2: sparse re-select + allgather ---------------------- #
        widx = jax.lax.axis_index(axis_name)
        out_buf = _phase2_pack(shard_buf, widx, S, K2)
        with spans.span("sparse_rs/allgather", route="oktopk"):
            gathered = jax.lax.all_gather(out_buf, axis_name)  # [W, 2*K2]
        _, _, dense = _phase2_unpack(gathered, K2, W, S)
        mean = dense[:d] / W

        own_dense = _own_transmitted(flat, keep, idxs, vals, pos, W, S, d)

    if collect is not None:
        # survivors: the global count the threshold admitted (identical on
        # every worker — the psum'd cumulative at b_star); spills: entries
        # THIS worker's threshold passed but capacity dropped (per-worker)
        collect["rs_oktopk_survivors"] = jnp.take(cum, b_star)
        collect["rs_oktopk_threshold"] = jax.lax.bitcast_convert_type(
            jnp.left_shift(b_star, shift), jnp.float32
        )
        collect["rs_oktopk_spills"] = jnp.sum(
            survive.astype(jnp.float32)
        ) - jnp.sum(keep.astype(jnp.float32))

    # wire accounting: histogram lanes are value-side; every routed or
    # gathered entry is an f32 value + i32 index
    stats = WireStats(
        index_bits=jnp.asarray((W * Bo + K2) * 32.0, jnp.float32),
        value_bits=jnp.asarray((W * Bo + K2 + bins) * 32.0, jnp.float32),
        dense_bits=jnp.asarray(d * 32.0, jnp.float32),
    )
    return mean.astype(flat.dtype), own_dense, stats


# --------------------------------------------------------------------------- #
# Live-mask-aware routes: shard re-ownership over the live set                #
# --------------------------------------------------------------------------- #
#
# The reduce-scatter routes assign shard s to worker s.  Under the resilience
# participation mask a dropped owner would silently eat its shard: nobody
# reduces it, nobody re-selects it, and the mean loses a 1/W slice of every
# step.  The masked variants below re-own shards by a TRACED permutation of
# the live set (mask is data, the trace is static — same contract as the
# allgather resilience path): senders route each entry to owner_of[shard]
# carrying GLOBAL indices (a deputy owns foreign shards, so shard-local
# offsets are ambiguous), receivers scatter-add into a [W*S] deputy buffer
# with rows zeroed by the mask, phase 2 re-selects only owned coordinates,
# and the mean renormalizes by the live count exactly like the allgather
# row-weights path.  A dropped worker's own-transmitted estimate is zero, so
# its entire update stays in its residual (error feedback conserves the
# mass).  Under the all-ones mask every step is a *1.0 / +0.0 / identity-
# permutation no-op, so the exchanged outputs are bitwise-equal to the
# mask-free route (tie-breaking caveat: with fewer than K2 nonzero owned
# magnitudes the zero-value padding picks park at different — still
# zero-valued — coordinates).


def owner_permutation(mask, num_workers: int) -> jax.Array:
    """Traced shard re-ownership map: owner_of[s] = worker serving shard s
    under the participation mask. Live workers keep their own shards; a
    dropped worker's shard is deputized to the live worker at rank
    (s mod n_live) of the ascending live set. Identity under the all-ones
    mask, deterministic, and replicated — every worker derives the same
    permutation from the same replicated mask."""
    W = num_workers
    mask_f = jnp.asarray(mask, jnp.float32).reshape((W,))
    live = mask_f > 0.0
    n_live = jnp.sum(live.astype(jnp.int32))
    # live worker ids packed to the front, ascending (stable argsort of
    # the not-live flags)
    packed = jnp.argsort(jnp.logical_not(live), stable=True).astype(jnp.int32)
    deputy = packed[
        jnp.mod(jnp.arange(W, dtype=jnp.int32), jnp.maximum(n_live, 1))
    ]
    return jnp.where(live, jnp.arange(W, dtype=jnp.int32), deputy)


def _masked_route(
    values, indices, select, owner_of, live_self, W, S, B, axis_name,
    mask_f, dtype, route,
):
    """Masked phase 1: route candidate entries (select mask applied) to the
    DEPUTY owner of their shard through one all_to_all, carrying global
    indices; scatter-add into the [W*S] deputy buffer with sender rows
    zeroed by the mask. Returns (deputy_buf f32[W*S], keep, idxs, vals,
    pos) — the latter four feed the own-transmitted EF scatter."""
    k = values.shape[0]
    # target worker = deputy owner of the entry's shard; dead -> parked W
    tw = jnp.where(
        select, owner_of[jnp.clip(indices // S, 0, W - 1)], W
    )
    # stable sort by target keeps lax.top_k's descending-|v| order within
    # each target's run, so budget overflow drops the smallest magnitudes
    order = jnp.argsort(tw, stable=True)
    tws = tw[order]
    vals = values[order]
    idxs = indices[order]
    pos = jnp.arange(k, dtype=jnp.int32)
    first_of_run = jnp.where(
        jnp.concatenate([jnp.ones((1,), bool), tws[1:] != tws[:-1]]), pos, -1
    )
    run_start = jax.lax.cummax(first_of_run)
    rank = pos - run_start
    # a dropped sender transmits nothing: its mass stays in the residual
    keep = jnp.logical_and(
        jnp.logical_and(tws < W, rank < B), live_self
    )
    tgt = jnp.where(keep, tws * B + rank, W * B + pos)
    send_v = (
        jnp.zeros((W * B,), dtype)
        .at[tgt].set(vals, mode="drop", unique_indices=True)
        .reshape(W, B)
    )
    # GLOBAL index on the wire — the deputy owns foreign shards, so a
    # shard-local offset would be ambiguous; dead slots point at 0 with
    # value 0
    send_i = (
        jnp.zeros((W * B,), jnp.int32)
        .at[tgt].set(idxs, mode="drop", unique_indices=True)
        .reshape(W, B)
    )
    send_buf = jnp.concatenate(
        [send_v.astype(jnp.float32),
         jax.lax.bitcast_convert_type(send_i, jnp.float32)], axis=1
    )  # [W, 2B]
    with spans.span("sparse_rs/route", route=route):
        rx = jax.lax.all_to_all(
            send_buf, axis_name, split_axis=0, concat_axis=0, tiled=True
        )
    # receiver-side row zeroing mirrors the allgather row-weights path
    # (belt and braces with the sender-side keep gate; *1.0 is exact)
    rx_v = rx[:, :B] * mask_f[:, None]
    rx_i = jax.lax.bitcast_convert_type(rx[:, B:], jnp.int32)
    with spans.span("sparse_rs/reduce", route=route):
        deputy_buf = (
            jnp.zeros((W * S,), jnp.float32)
            .at[jnp.clip(rx_i.reshape(-1), 0, W * S - 1)]
            .add(rx_v.reshape(-1).astype(jnp.float32))
        )
    return deputy_buf, keep, idxs, vals, pos


def _masked_phase2(est, owned, W, S, K2, axis_name, mask_f, route):
    """Masked phase 2: re-select the K2 largest OWNED coordinates of the
    [W*S] deputy estimate (indices are already global), allgather, and
    scatter-add the mean numerator with gathered rows zeroed by the mask.
    Returns (clipped global indices i32[W*K2], dense numerator f32[W*S])."""
    mag = jnp.where(owned, jnp.abs(est), 0.0)
    top_v, top_i = jax.lax.top_k(mag, K2)
    # gate non-owned tie picks to exact zero (deputy_buf is zero outside
    # the owned region by construction, but the gate keeps that invariant
    # explicit)
    out_vals = jnp.where(owned[top_i], est[top_i], 0.0)
    out_idx = top_i.astype(jnp.int32)
    out_buf = jnp.concatenate(
        [out_vals.astype(jnp.float32),
         jax.lax.bitcast_convert_type(out_idx, jnp.float32)]
    )  # [2*K2]
    with spans.span("sparse_rs/allgather", route=route):
        gathered = jax.lax.all_gather(out_buf, axis_name)  # [W, 2*K2]
    gathered_v = gathered[:, :K2] * mask_f[:, None]
    gathered_i = jax.lax.bitcast_convert_type(gathered[:, K2:], jnp.int32)
    gi = jnp.clip(gathered_i.reshape(-1), 0, W * S - 1)
    dense = jnp.zeros((W * S,), jnp.float32).at[gi].add(
        gathered_v.reshape(-1)
    )
    return gi, dense


def _exchange_sparse_masked(
    flat, axis_name, num_workers, *, ratio, approx_topk, headroom,
    out_headroom, mask,
):
    """The sparse route under the live mask: re-owned routing, owned-only
    phase-2 re-select, live-count renormalization. All-ones mask is
    bitwise-equal to `_exchange_sparse` on the exchanged outputs."""
    d = flat.shape[0]
    W = num_workers
    S = shard_size(d, W)
    B = send_budget(d, ratio, W, headroom)
    K2 = out_budget(d, ratio, W, out_headroom)
    mask_f = jnp.asarray(mask, jnp.float32).reshape((W,))
    widx = jax.lax.axis_index(axis_name)
    live_self = mask_f[widx] > 0.0
    owner_of = owner_permutation(mask_f, W)

    with spans.span("sparse_rs/select", route="sparse"):
        sp = sparse.topk(flat, ratio, sort_indices=False, approx=approx_topk)
    k = sp.k
    live = jnp.arange(k, dtype=jnp.int32) < sp.nnz

    deputy_buf, keep, idxs, vals, pos = _masked_route(
        sp.values, sp.indices, live, owner_of, live_self, W, S, B,
        axis_name, mask_f, flat.dtype, "sparse",
    )
    owned = owner_of[jnp.arange(W * S, dtype=jnp.int32) // S] == widx
    gi, dense = _masked_phase2(
        deputy_buf, owned, W, S, K2, axis_name, mask_f, "sparse"
    )
    # renormalize by the live count, exactly like the allgather row-weights
    # path (sum of W ones is exactly W.0 in f32 -> /W bitwise under all-ones)
    denom = jnp.maximum(jnp.sum(mask_f), 1.0)
    mean = dense[:d] / denom

    own_dense = _own_transmitted(flat, keep, idxs, vals, pos, W, S, d)

    stats = WireStats(
        index_bits=jnp.asarray((W * B + K2) * 32.0, jnp.float32),
        value_bits=jnp.asarray((W * B + K2) * 32.0, jnp.float32),
        dense_bits=jnp.asarray(d * 32.0, jnp.float32),
    )
    return mean.astype(flat.dtype), own_dense, stats


def _exchange_quantized_masked(
    flat, axis_name, num_workers, *, ratio, out_headroom, block, key, mask,
):
    """The quantized route under the live mask. The psum_scatter still
    lands shard s on worker s — re-ownership instead adds ONE int8
    all_gather of the summed shard (+Ssh wire bytes, mirrored by
    `costmodel.rs_wire_bytes(masked=True)`) so every deputy can dequantize
    the shards it serves. Dropped workers are excluded on both legs: their
    norms leave the pmax and their levels leave the integer sum, so the
    live-sum bound n_live*q <= W*q <= 127 still holds."""
    if key is None:
        raise ValueError("rs_mode='quantized' needs a PRNG key (stochastic "
                         "rounding of the int8 levels)")
    d = flat.shape[0]
    W = num_workers
    n = padded_shard(d, W, block) * W
    Ssh = n // W
    K2 = out_budget(d, ratio, W, out_headroom)
    q = quantized_levels_budget(W)
    widx = jax.lax.axis_index(axis_name)
    mask_f = jnp.asarray(mask, jnp.float32).reshape((W,))
    live_self = mask_f[widx] > 0.0
    owner_of = owner_permutation(mask_f, W)

    gp = jnp.zeros((n,), jnp.float32).at[:d].set(flat)
    norms_local = jnp.linalg.norm(gp.reshape(-1, block), axis=1)
    # a dropped worker's scale must not inflate the shared max
    norms_eff = jnp.where(live_self, norms_local, 0.0)
    with spans.span("sparse_rs/norm-pmax", route="quantized"):
        norms_shared = jax.lax.pmax(norms_eff, axis_name)
    with spans.span("sparse_rs/quantize", route="quantized"):
        levels, _ = qar.bucket_quantize(
            gp, q, block, jax.random.fold_in(key, widx), norms=norms_shared
        )
    # zero the dropped worker's integer contribution before the exact sum
    levels_eff = jnp.where(live_self, levels, jnp.zeros_like(levels))
    with spans.span("sparse_rs/reduce-scatter", route="quantized"):
        summed = jax.lax.psum_scatter(
            levels_eff, axis_name, scatter_dimension=0, tiled=True
        )  # int8[Ssh]
    # the one extra wire leg: every worker sees every summed shard, so a
    # deputy can serve a dropped owner's shard in phase 2
    with spans.span("sparse_rs/shard-allgather", route="quantized"):
        all_shards = jax.lax.all_gather(summed, axis_name)  # int8[W, Ssh]

    bpw = Ssh // block  # norm blocks per shard
    est = jnp.zeros((n,), jnp.float32)
    for v in range(W):  # static: one dequantize per shard, owner-gated
        norms_v = jax.lax.dynamic_slice(norms_shared, (v * bpw,), (bpw,))
        deq_v = qar.bucket_dequantize(all_shards[v], norms_v, q, block)
        est = jax.lax.dynamic_update_slice(
            est, jnp.where(owner_of[v] == widx, deq_v, 0.0), (v * Ssh,)
        )

    owned = owner_of[jnp.arange(n, dtype=jnp.int32) // Ssh] == widx
    gi, dense = _masked_phase2(
        est, owned, W, Ssh, K2, axis_name, mask_f, "quantized"
    )
    denom = jnp.maximum(jnp.sum(mask_f), 1.0)
    mean = dense[:d] / denom

    # own contribution from the ZEROED levels: a dropped worker contributed
    # nothing, so its full update stays in the residual
    my_deq = qar.bucket_dequantize(levels_eff, norms_shared, q, block)
    own_dense = jnp.zeros((n,), jnp.float32).at[gi].add(my_deq[gi])[:d]

    stats = WireStats(
        index_bits=jnp.asarray(K2 * 32.0, jnp.float32),
        value_bits=jnp.asarray(
            n * 8.0 + (n // block) * 32.0 + K2 * 32.0 + Ssh * 8.0,
            jnp.float32,
        ),
        dense_bits=jnp.asarray(d * 32.0, jnp.float32),
    )
    return mean.astype(flat.dtype), own_dense.astype(flat.dtype), stats


def _exchange_oktopk_masked(
    flat, axis_name, num_workers, *, ratio, out_headroom, bins,
    cap_headroom, collect, mask,
):
    """The Ok-Topk route under the live mask: the dropped worker's
    candidates leave the psum'd histogram (the global threshold is chosen
    over live candidates only), survivors route to deputy owners, phase 2
    re-selects owned coordinates, the mean renormalizes by the live
    count. Wire layout is unchanged from the mask-free route."""
    d = flat.shape[0]
    W = num_workers
    S = shard_size(d, W)
    Bo = oktopk_send_budget(d, ratio, W, cap_headroom)
    K2 = out_budget(d, ratio, W, out_headroom)
    shift = oktopk_shift(bins)
    widx = jax.lax.axis_index(axis_name)
    mask_f = jnp.asarray(mask, jnp.float32).reshape((W,))
    live_self = mask_f[widx] > 0.0
    owner_of = owner_permutation(mask_f, W)

    with spans.span("exchange/encode", route="oktopk"):
        with spans.span("sparse_rs/select", route="oktopk"):
            sp = sparse.topk(flat, ratio, sort_indices=False, approx=False)
        k = sp.k
        live = jnp.arange(k, dtype=jnp.int32) < sp.nnz
        mag = jnp.where(live, jnp.abs(sp.values), 0.0).astype(jnp.float32)

        bucket = jnp.right_shift(
            jax.lax.bitcast_convert_type(mag, jnp.int32), shift
        )
        weight = jnp.logical_and(live, mag > 0.0).astype(jnp.float32)
        # a dropped worker's candidates must not move the global threshold
        weight = jnp.where(live_self, weight, 0.0)
        hist = jnp.zeros((bins,), jnp.float32).at[bucket].add(weight)
        with spans.span("sparse_rs/psum", route="oktopk"):
            g_hist = jax.lax.psum(hist, axis_name)
        cum = jnp.flip(jnp.cumsum(jnp.flip(g_hist)))
        ok = cum >= float(k)
        b_star = jnp.max(
            jnp.where(ok, jnp.arange(bins, dtype=jnp.int32), 0)
        )
        survive = jnp.logical_and(
            jnp.logical_and(live, mag > 0.0), bucket >= b_star
        )

    deputy_buf, keep, idxs, vals, pos = _masked_route(
        sp.values, sp.indices, survive, owner_of, live_self, W, S, Bo,
        axis_name, mask_f, flat.dtype, "oktopk",
    )
    with spans.span("exchange/decode", route="oktopk"):
        owned = owner_of[jnp.arange(W * S, dtype=jnp.int32) // S] == widx
        gi, dense = _masked_phase2(
            deputy_buf, owned, W, S, K2, axis_name, mask_f, "oktopk"
        )
        denom = jnp.maximum(jnp.sum(mask_f), 1.0)
        mean = dense[:d] / denom

        own_dense = _own_transmitted(flat, keep, idxs, vals, pos, W, S, d)

    if collect is not None:
        collect["rs_oktopk_survivors"] = jnp.take(cum, b_star)
        collect["rs_oktopk_threshold"] = jax.lax.bitcast_convert_type(
            jnp.left_shift(b_star, shift), jnp.float32
        )
        collect["rs_oktopk_spills"] = jnp.sum(
            survive.astype(jnp.float32)
        ) - jnp.sum(keep.astype(jnp.float32))

    stats = WireStats(
        index_bits=jnp.asarray((W * Bo + K2) * 32.0, jnp.float32),
        value_bits=jnp.asarray((W * Bo + K2 + bins) * 32.0, jnp.float32),
        dense_bits=jnp.asarray(d * 32.0, jnp.float32),
    )
    return mean.astype(flat.dtype), own_dense, stats
