"""Sparse reduce-scatter + allgather — the Ok-Topk / SparCML exchange shape.

The allgather communicator (the reference's only compressed collective,
README.md:37) makes every worker decode every peer's payload: O(W·k) decode
work and W·k wire entries per worker. The sparse-allreduce literature
(PAPERS.md: "Near-Optimal Sparse Allreduce" (Ok-Topk), SparCML, S2 Reducer)
splits the universe into W contiguous shards instead:

    phase 1 (sparse reduce-scatter): each worker routes its top-k entries
        to the shard-owner via `all_to_all` (static per-shard budget,
        largest-|v| kept on overflow — the dropped mass stays in the
        sender's residual by construction); the owner scatter-adds the W
        received slices into a dense shard buffer.
    phase 2 (sparse allgather): the owner re-selects the top k/W of its
        *reduced* shard and `all_gather`s (values, global indices); every
        worker scatters W small payloads into the dense result.

Per-worker wire ~ k·headroom + k entries vs the allgather path's W·k, and
decode is O(k) instead of O(W·k) — the gap grows with the mesh. The phase-2
re-selection is lossy (Ok-Topk §4 accepts the same truncation; its mass is
bounded by the per-shard budget) while phase-1 truncation is error-fed back
like any sparsifier.

All static-shape: budgets derive from (d, ratio, W) at trace time; live
counts ride in-band. Runs inside shard_map over the data axis.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu import sparse
from deepreduce_tpu.metrics import WireStats
from deepreduce_tpu.telemetry import spans


def shard_size(d: int, num_workers: int) -> int:
    return (d + num_workers - 1) // num_workers


def send_budget(d: int, ratio: float, num_workers: int, headroom: float) -> int:
    """Per-shard slots in the phase-1 all_to_all: expected k/W occupancy
    times headroom (top-k positions are ~uniform over shards; Poisson
    fluctuation at k/W ~ thousands is a few percent, so a modest headroom
    captures nearly all mass — what overflows stays in the residual)."""
    k = sparse.num_slots(d, ratio)
    return max(1, int(math.ceil(k / num_workers * headroom)))


def out_budget(
    d: int, ratio: float, num_workers: int, headroom: float = 1.0
) -> int:
    """Phase-2 slots per shard: k/W (total across shards == k — the
    Ok-Topk output-volume convention) times an optional headroom, capped
    at the shard size."""
    k = sparse.num_slots(d, ratio)
    b = max(1, int(math.ceil(k / num_workers * headroom)))
    return min(b, shard_size(d, num_workers))


def exchange(
    flat: jax.Array,
    axis_name: str,
    num_workers: int,
    *,
    ratio: float,
    approx_topk: bool = False,
    headroom: float = 2.0,
    out_headroom: float = 1.0,
) -> Tuple[jax.Array, jax.Array, WireStats]:
    """-> (mean gradient f32[d], own-transmitted dense f32[d] for error
    feedback, wire stats). Call inside shard_map over `axis_name`."""
    d = flat.shape[0]
    W = num_workers
    S = shard_size(d, W)
    B = send_budget(d, ratio, W, headroom)
    K2 = out_budget(d, ratio, W, out_headroom)

    # sort_indices=False keeps lax.top_k's descending-|v| order — the
    # overflow-drop-smallest property below depends on it
    with spans.span("sparse_rs/select"):
        sp = sparse.topk(flat, ratio, sort_indices=False, approx=approx_topk)
    k = sp.k

    # --- phase 1: route entries to their shard-owners ------------------- #
    live = jnp.arange(k, dtype=jnp.int32) < sp.nnz
    shard_of = jnp.where(live, sp.indices // S, W)  # dead -> parked shard W
    # stable sort by shard keeps lax.top_k's descending-|v| order within
    # each shard, so budget overflow drops the smallest magnitudes
    order = jnp.argsort(shard_of, stable=True)
    sh = shard_of[order]
    vals = sp.values[order]
    idxs = sp.indices[order]
    # per-shard rank = position within my shard's run
    pos = jnp.arange(k, dtype=jnp.int32)
    first_of_run = jnp.where(
        jnp.concatenate([jnp.ones((1,), bool), sh[1:] != sh[:-1]]), pos, -1
    )
    run_start = jax.lax.cummax(first_of_run)
    rank = pos - run_start
    keep = jnp.logical_and(sh < W, rank < B)
    # scatter into the [W, B] send matrix (unique targets by construction)
    tgt = jnp.where(keep, sh * B + rank, W * B + pos)
    send_v = (
        jnp.zeros((W * B,), flat.dtype)
        .at[tgt].set(vals, mode="drop", unique_indices=True)
        .reshape(W, B)
    )
    # local index within the shard; dead slots point at 0 with value 0
    send_i = (
        jnp.zeros((W * B,), jnp.int32)
        .at[tgt].set(idxs - sh * S, mode="drop", unique_indices=True)
        .reshape(W, B)
    )
    # ONE collective per phase: ride the indices next to the values as
    # bitcast f32 lanes in the same buffer (the fused-allgather pattern)
    send_buf = jnp.concatenate(
        [send_v.astype(jnp.float32),
         jax.lax.bitcast_convert_type(send_i, jnp.float32)], axis=1
    )  # [W, 2B]
    with spans.span("sparse_rs/route"):
        rx = jax.lax.all_to_all(
            send_buf, axis_name, split_axis=0, concat_axis=0, tiled=True
        )
    rx_v = rx[:, :B]
    rx_i = jax.lax.bitcast_convert_type(rx[:, B:], jnp.int32)

    # --- reduce my shard ------------------------------------------------- #
    with spans.span("sparse_rs/reduce"):
        shard_buf = jnp.zeros((S,), jnp.float32).at[rx_i.reshape(-1)].add(
            rx_v.reshape(-1).astype(jnp.float32)
        )
    # zero-value dead slots all land on local index 0: adding 0 is exact

    # --- phase 2: re-select the reduced shard and allgather -------------- #
    widx = jax.lax.axis_index(axis_name)
    mag = jnp.abs(shard_buf)
    top_v, top_i = jax.lax.top_k(mag, K2)
    out_vals = shard_buf[top_i]
    out_idx = (top_i + widx * S).astype(jnp.int32)
    out_buf = jnp.concatenate(
        [out_vals.astype(jnp.float32),
         jax.lax.bitcast_convert_type(out_idx, jnp.float32)]
    )  # [2*K2]
    with spans.span("sparse_rs/allgather"):
        gathered = jax.lax.all_gather(out_buf, axis_name)  # [W, 2*K2]
    gathered_v = gathered[:, :K2]
    gathered_i = jax.lax.bitcast_convert_type(gathered[:, K2:], jnp.int32)
    dense = (
        jnp.zeros((W * S,), jnp.float32)
        .at[jnp.clip(gathered_i.reshape(-1), 0, W * S - 1)]
        .add(gathered_v.reshape(-1))[:d]
    )
    # indices are globally unique (each worker owns a disjoint shard and
    # top_k returns distinct positions), so add == set; mean over workers
    mean = dense / W

    # own-transmitted mass (what actually left this worker, phase-1
    # truncation applied) for residual error feedback; dead/overflow slots
    # park at unique out-of-range targets
    own_dense = (
        jnp.zeros((W * S,), flat.dtype)
        .at[jnp.where(keep, idxs, W * S + pos)]
        .set(vals, mode="drop", unique_indices=True)[:d]
    )

    # wire accounting: every transmitted entry is an f32 value + i32 index
    # (phase 1: W*B slots out per worker; phase 2: K2 slots gathered out)
    stats = WireStats(
        index_bits=jnp.asarray((W * B + K2) * 32.0, jnp.float32),
        value_bits=jnp.asarray((W * B + K2) * 32.0, jnp.float32),
        dense_bits=jnp.asarray(d * 32.0, jnp.float32),
    )
    return mean.astype(flat.dtype), own_dense, stats
