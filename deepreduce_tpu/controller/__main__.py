"""Controller CLI: the adaptive-run smoke check behind `make ctrl-check`.

    python -m deepreduce_tpu.controller --platform cpu check

`check` runs a short adaptive train on the 8-worker CPU mesh with a
mid-run checkpoint, then a second trainer that resumes from that
checkpoint, and asserts the observability contract end to end:

* ``decisions.jsonl`` is non-empty and every record validates against
  `DECISION_SCHEMA`;
* the controller actually moved (≥ 1 operating-point switch) and the
  compiled-executable count equals the rungs visited (bounded re-jit);
* the resumed run replays the decision trail BITWISE — its post-resume
  decisions are byte-identical JSON to the same-step records of the
  uninterrupted run, and the final params match bit for bit.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import shutil
import sys
import tempfile


def _build_cfg(**overrides):
    from deepreduce_tpu.config import DeepReduceConfig

    base = dict(
        deepreduce="index",
        index="bloom",
        compress_ratio=0.05,
        fpr=0.01,
        memory="residual",
        min_compress_size=100,
        telemetry=True,
        telemetry_every=5,
        ctrl=True,
        ctrl_ladder="0.01,0.02,0.05",
        ctrl_hysteresis=1,
        # band chosen so the middle rung's measured err_cos (~0.39 on the
        # synthetic task) sits inside [target, target+headroom]: the run
        # starts at the top rung (0.05, err_cos ~0.55), steps down, settles
        ctrl_target_err_cos=0.3,
        ctrl_headroom=0.12,
    )
    base.update(overrides)
    return DeepReduceConfig(**base)


def _run_train(
    cfg,
    *,
    steps: int,
    num_workers: int,
    seed: int = 0,
    lr: float = 0.1,
    log_path=None,
    ckpt_path=None,
    ckpt_at=None,
    resume_from=None,
):
    """Deterministic synthetic-data adaptive train on the CPU mesh.
    Batches are a pure function of (seed, step), so an uninterrupted run
    and a resumed run see identical data. Returns (losses, trainer,
    final state)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    import flax.linen as nn
    from jax.sharding import Mesh

    from deepreduce_tpu.train import Trainer

    class _MLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(64)(x))
            x = nn.relu(nn.Dense(64)(x))
            return nn.Dense(8)(x)

    n_dev = min(num_workers, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("data",))
    trainer = Trainer(_MLP(), cfg, optax.sgd(lr, momentum=0.9), mesh)
    if log_path is not None:
        trainer.attach_decision_log(log_path)

    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(512, 32)), jnp.float32)
    w_true = rng.normal(size=(32, 8))
    y = jnp.asarray(
        np.argmax(rng.normal(size=(512, 8)) * 0.1 + x @ w_true, axis=1), jnp.int32
    )

    batch = 64
    state = trainer.init_state(jax.random.PRNGKey(seed), (x[:batch], y[:batch]))
    start = 0
    if resume_from is not None:
        from deepreduce_tpu import checkpoint
        from deepreduce_tpu.telemetry import MetricAccumulators

        template = {
            "state": state,
            "telemetry": MetricAccumulators.zeros(trainer.exchanger.num_buckets),
            "ctrl": trainer.controller_state(),
        }
        restored = checkpoint.restore(str(resume_from), template, config=cfg)
        state = restored["state"]
        trainer._telemetry_acc = restored["telemetry"]
        trainer.load_controller_state(restored["ctrl"])
        start = int(state.step)

    key = jax.random.PRNGKey(seed + 1)
    losses = []
    for step in range(start, steps):
        lo = (step * batch) % (512 - batch)
        state, loss, _ = trainer.step(
            state, (x[lo : lo + batch], y[lo : lo + batch]),
            jax.random.fold_in(key, step),
        )
        losses.append(float(loss))
        if ckpt_path is not None and ckpt_at == step + 1:
            from deepreduce_tpu import checkpoint

            checkpoint.save(
                str(ckpt_path),
                {
                    "state": state,
                    "telemetry": trainer._telemetry_acc,
                    "ctrl": trainer.controller_state(),
                },
                config=cfg,
            )
    return losses, trainer, state


def cmd_check(args) -> int:
    import jax
    import numpy as np

    from deepreduce_tpu.controller import DecisionLog, validate_decision

    cfg = _build_cfg(ctrl_target_err_cos=args.target_err_cos)
    workdir = pathlib.Path(tempfile.mkdtemp(prefix="drtpu_ctrl_check_"))
    try:
        full_log = workdir / "full" / "decisions.jsonl"
        resume_log = workdir / "resume" / "decisions.jsonl"
        ckpt = workdir / "ckpt" / "last"
        ckpt_at = args.steps // 2

        losses, trainer, state = _run_train(
            cfg,
            steps=args.steps,
            num_workers=args.num_workers,
            log_path=full_log,
            ckpt_path=ckpt,
            ckpt_at=ckpt_at,
        )
        _, trainer2, state2 = _run_train(
            cfg,
            steps=args.steps,
            num_workers=args.num_workers,
            log_path=resume_log,
            resume_from=ckpt,
        )

        full = DecisionLog.read(full_log)
        resumed = DecisionLog.read(resume_log)
        schema_ok = True
        try:
            for rec in full + resumed:
                validate_decision(rec)
        except ValueError as e:
            schema_ok = False
            print(f"schema violation: {e}", file=sys.stderr)

        # bitwise replay: the resumed run's decisions must be byte-identical
        # JSON to the uninterrupted run's records from the checkpoint step
        # on (the boundary AT ckpt_at fires at the start of the next step,
        # i.e. after the checkpoint was taken, so both runs record it)
        tail = [r for r in full if r["step"] >= ckpt_at]
        replay_ok = [
            json.dumps(r, sort_keys=True) for r in tail
        ] == [json.dumps(r, sort_keys=True) for r in resumed]

        leaves1 = jax.tree_util.tree_leaves(state.params)
        leaves2 = jax.tree_util.tree_leaves(state2.params)
        params_ok = len(leaves1) == len(leaves2) and all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(leaves1, leaves2)
        )

        checks = {
            "losses_finite": all(
                l == l and abs(l) != float("inf") for l in losses
            ),
            "decisions_nonempty": len(full) > 0,
            "decisions_schema_valid": schema_ok,
            "controller_switched": trainer.controller.switches >= 1,
            "bounded_rejit": len(trainer.visited_ladder_indices)
            <= len(trainer.controller.ladder),
            "resume_replays_bitwise": replay_ok,
            "resume_params_bitwise": params_ok,
        }
        report = {
            "ok": all(checks.values()),
            "checks": checks,
            "steps": len(losses),
            "decisions": len(full),
            "switches": int(trainer.controller.switches),
            "visited_indices": list(trainer.visited_ladder_indices),
            "effective_ratio": trainer.controller.effective_ratio(),
            "trail": [
                f"{r['step']}: {r['old_index']}->{r['new_index']} ({r['rationale']})"
                for r in full
                if r["switched"]
            ],
            "config": {
                "ctrl_ladder": cfg.ctrl_ladder,
                "ctrl_target_err_cos": cfg.ctrl_target_err_cos,
                "ctrl_hysteresis": cfg.ctrl_hysteresis,
                "telemetry_every": cfg.telemetry_every,
            },
        }
        print(json.dumps(report, indent=2))
        return 0 if report["ok"] else 1
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m deepreduce_tpu.controller")
    ap.add_argument("--platform", type=str, default="",
                    help="pin the JAX platform (e.g. 'cpu' for the virtual "
                         "8-device mesh)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_check = sub.add_parser(
        "check", help="adaptive-run smoke check (make ctrl-check)"
    )
    p_check.add_argument("--steps", type=int, default=40)
    p_check.add_argument("--num_workers", type=int, default=8)
    p_check.add_argument("--target_err_cos", type=float, default=0.3)
    args = ap.parse_args(argv)
    if args.platform:
        from deepreduce_tpu.utils import force_platform

        force_platform(args.platform, device_count=max(2, args.num_workers))
    return cmd_check(args)


if __name__ == "__main__":
    sys.exit(main())
