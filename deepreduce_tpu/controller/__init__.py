"""Adaptive compression controller (see ARCHITECTURE.md "Adaptive controller").

`Ladder` declares the bounded set of operating points; `CompressionController`
moves a single index along it from telemetry window deltas; `DecisionLog`
persists the auditable trail as ``decisions.jsonl``.
"""

from deepreduce_tpu.controller.controller import (
    DECISION_SCHEMA,
    CompressionController,
    DecisionLog,
    RATIONALES,
    TRIGGERS,
    validate_decision,
)
from deepreduce_tpu.controller.ladder import Ladder, OperatingPoint

__all__ = [
    "CompressionController",
    "DecisionLog",
    "DECISION_SCHEMA",
    "Ladder",
    "OperatingPoint",
    "RATIONALES",
    "TRIGGERS",
    "validate_decision",
]
