"""Telemetry-driven compression controller with an auditable decision trail.

The controller closes the r08 telemetry loop: every `telemetry_every`
steps the trainer fetches the on-device `MetricAccumulators` (the fetch
it was already doing — the controller adds zero hot-loop syncs), hands
the cumulative snapshot to `CompressionController.observe`, and the
controller turns the *window delta* (this fetch minus the previous one)
into at most one ±1 move along the pre-declared operating-point ladder.

Policy, in priority order over the window metrics:

1. ``saturated_per_step > ctrl_saturation_ceiling`` → vote UP (payloads
   are overflowing their slot budget; buy more wire).
2. ``compress_err_cos < ctrl_target_err_cos`` → vote UP (the compressed
   gradient has drifted too far from the dense one).
3. ``compress_err_cos > ctrl_target_err_cos + ctrl_headroom`` → vote
   DOWN (fidelity surplus; spend it on wire savings).
4. otherwise → in band, hold, and reset both vote counters.

``ctrl_hysteresis`` consecutive same-direction votes are required before
a move; any hold or opposite vote resets the streak, so a noisy metric
cannot make the controller oscillate every window.

Every evaluation — switch or hold — is a `Decision` appended to the
in-memory trail and, when a `DecisionLog` is attached, to
``decisions.jsonl`` in the run directory. Decisions carry no wall-clock
timestamp on purpose: the trail is a pure function of the metric stream,
which is what lets checkpoint resume replay it bitwise (`make
ctrl-check` enforces this). The telemetry CLI maps decision steps onto
trace timestamps via ``metrics.jsonl`` when rendering Perfetto tracks.
"""

from __future__ import annotations

import json
import pathlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.controller.ladder import Ladder, OperatingPoint
from deepreduce_tpu.telemetry.device_metrics import MetricAccumulators, fetch_delta

# Trigger codes: which window metric drove the vote.
TRIG_SATURATION = "saturation_high"
TRIG_ERR_LOW = "err_cos_low"
TRIG_HEADROOM = "err_cos_headroom"
TRIG_IN_BAND = "in_band"

# Rationale codes: what the controller did with the vote.
RAT_MOVE_UP = "move_up"
RAT_MOVE_DOWN = "move_down"
RAT_HOLD_HYSTERESIS = "hold_hysteresis"
RAT_HOLD_IN_BAND = "hold_in_band"
RAT_HOLD_AT_TOP = "hold_at_top"
RAT_HOLD_AT_BOTTOM = "hold_at_bottom"

TRIGGERS = (TRIG_SATURATION, TRIG_ERR_LOW, TRIG_HEADROOM, TRIG_IN_BAND)
RATIONALES = (
    RAT_MOVE_UP,
    RAT_MOVE_DOWN,
    RAT_HOLD_HYSTERESIS,
    RAT_HOLD_IN_BAND,
    RAT_HOLD_AT_TOP,
    RAT_HOLD_AT_BOTTOM,
)

# decisions.jsonl schema: field name -> accepted types. Every record must
# carry exactly these keys (documented in ARCHITECTURE.md).
DECISION_SCHEMA: Dict[str, Tuple[type, ...]] = {
    "step": (int,),
    "window_steps": (int,),
    "trigger": (str,),
    "rationale": (str,),
    "switched": (bool,),
    "old_index": (int,),
    "new_index": (int,),
    "old_ratio": (float,),
    "new_ratio": (float,),
    "old_fpr": (float, type(None)),
    "new_fpr": (float, type(None)),
    "err_cos": (float,),
    "saturated_per_step": (float,),
    "rel_volume": (float,),
}


def validate_decision(rec: Dict[str, Any]) -> None:
    """Raise ValueError unless `rec` matches DECISION_SCHEMA exactly."""
    if not isinstance(rec, dict):
        raise ValueError(f"decision record must be a dict, got {type(rec)}")
    missing = sorted(set(DECISION_SCHEMA) - set(rec))
    extra = sorted(set(rec) - set(DECISION_SCHEMA))
    if missing or extra:
        raise ValueError(
            f"decision record keys mismatch: missing={missing} extra={extra}"
        )
    for key, types in DECISION_SCHEMA.items():
        # bool is an int subclass; keep step/index fields strictly int.
        if isinstance(rec[key], bool) and bool not in types:
            raise ValueError(f"decision field {key}={rec[key]!r} is bool, want {types}")
        if not isinstance(rec[key], types):
            raise ValueError(
                f"decision field {key}={rec[key]!r} has type "
                f"{type(rec[key]).__name__}, want {types}"
            )
    if rec["trigger"] not in TRIGGERS:
        raise ValueError(f"unknown trigger code {rec['trigger']!r}")
    if rec["rationale"] not in RATIONALES:
        raise ValueError(f"unknown rationale code {rec['rationale']!r}")
    if rec["switched"] != (rec["old_index"] != rec["new_index"]):
        raise ValueError("decision 'switched' inconsistent with index change")


class DecisionLog:
    """Append-only, schema-validated ``decisions.jsonl`` writer."""

    def __init__(self, path) -> None:
        self.path = pathlib.Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)

    def append(self, rec: Dict[str, Any]) -> None:
        validate_decision(rec)
        with self.path.open("a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")

    @staticmethod
    def read(path) -> List[Dict[str, Any]]:
        path = pathlib.Path(path)
        if not path.exists():
            return []
        records = []
        with path.open() as f:
            for line in f:
                line = line.strip()
                if line:
                    records.append(json.loads(line))
        return records


def _zero_fetch(num_buckets: int) -> Dict[str, Any]:
    vals = {name: 0.0 for name in MetricAccumulators.scalar_fields()}
    vals["bucket_saturated"] = [0.0] * int(num_buckets)
    return vals


class CompressionController:
    """Moves the ladder index from fetched telemetry windows.

    Host-side only: the controller never appears in the traced step. Its
    entire state (index, vote streaks, window accounting, previous fetch)
    round-trips through `state_dict`/`load_state_dict` so a checkpoint
    resume continues the decision trail bitwise.
    """

    def __init__(
        self,
        cfg: DeepReduceConfig,
        ladder: Optional[Ladder] = None,
        *,
        log: Optional[DecisionLog] = None,
    ) -> None:
        self.cfg = cfg
        self.ladder = ladder if ladder is not None else Ladder.parse(cfg.ctrl_ladder)
        self.log = log
        self.index = self.ladder.index_near(cfg.compress_ratio)
        self.up_votes = 0
        self.down_votes = 0
        self.windows = 0
        self.switches = 0
        # Σ window_steps · ratio-in-effect, for effective_ratio reporting.
        self.weighted_ratio = 0.0
        self.weight_steps = 0
        self.decisions: List[Dict[str, Any]] = []
        self._prev: Optional[Dict[str, Any]] = None

    # -- operating point plumbing -------------------------------------

    @property
    def point(self) -> OperatingPoint:
        return self.ladder[self.index]

    def bucket_points(self, num_buckets: int) -> Tuple[Tuple[float, Optional[float]], ...]:
        """Per-bucket (ratio, fpr) vector for the current rung. The default
        policy moves all buckets together — a uniform vector — which is what
        keeps the audited retrace cardinality at len(ladder) rather than
        len(ladder)**num_buckets. The mechanism below it (comm_bucket's
        `points=`) accepts non-uniform vectors for future policies."""
        pt = self.point
        return tuple((pt.ratio, pt.fpr) for _ in range(num_buckets))

    def ensure_prev(self, num_buckets: int) -> None:
        """Initialise the previous-fetch baseline to the zero snapshot
        (cumulative-from-zero equals the first window's delta)."""
        if self._prev is None:
            self._prev = _zero_fetch(num_buckets)

    # -- the control law ----------------------------------------------

    def observe(self, step: int, fetch: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Evaluate one telemetry window ending at `step`.

        `fetch` is the cumulative `MetricAccumulators.fetch()` snapshot.
        Returns the decision record (also logged), or None when the
        window is empty (no steps since the previous fetch)."""
        self.ensure_prev(len(fetch.get("bucket_saturated", [])))
        delta = fetch_delta(fetch, self._prev)
        window_steps = int(round(delta["steps"]))
        if window_steps <= 0:
            return None
        window = MetricAccumulators.derive(delta)
        self._prev = fetch

        err_cos = float(window["compress_err_cos"])
        saturated = float(window["saturated_per_step"])
        rel_volume = float(window["rel_volume"])
        cfg = self.cfg

        if saturated > cfg.ctrl_saturation_ceiling:
            vote, trigger = +1, TRIG_SATURATION
        elif err_cos < cfg.ctrl_target_err_cos:
            vote, trigger = +1, TRIG_ERR_LOW
        elif err_cos > cfg.ctrl_target_err_cos + cfg.ctrl_headroom:
            vote, trigger = -1, TRIG_HEADROOM
        else:
            vote, trigger = 0, TRIG_IN_BAND

        if vote > 0:
            self.up_votes += 1
            self.down_votes = 0
        elif vote < 0:
            self.down_votes += 1
            self.up_votes = 0
        else:
            self.up_votes = self.down_votes = 0

        old_index = self.index
        new_index = old_index
        rationale = RAT_HOLD_IN_BAND
        if vote > 0:
            if self.up_votes >= cfg.ctrl_hysteresis:
                self.up_votes = 0
                if old_index + 1 < len(self.ladder):
                    new_index = old_index + 1
                    rationale = RAT_MOVE_UP
                else:
                    rationale = RAT_HOLD_AT_TOP
            else:
                rationale = RAT_HOLD_HYSTERESIS
        elif vote < 0:
            if self.down_votes >= cfg.ctrl_hysteresis:
                self.down_votes = 0
                if old_index > 0:
                    new_index = old_index - 1
                    rationale = RAT_MOVE_DOWN
                else:
                    rationale = RAT_HOLD_AT_BOTTOM
            else:
                rationale = RAT_HOLD_HYSTERESIS

        old_pt = self.ladder[old_index]
        new_pt = self.ladder[new_index]
        switched = new_index != old_index
        if switched:
            self.switches += 1
            self.up_votes = self.down_votes = 0
        self.windows += 1
        # The old rung was in effect for this whole window.
        self.weighted_ratio += window_steps * old_pt.ratio
        self.weight_steps += window_steps
        self.index = new_index

        rec = {
            "step": int(step),
            "window_steps": window_steps,
            "trigger": trigger,
            "rationale": rationale,
            "switched": switched,
            "old_index": int(old_index),
            "new_index": int(new_index),
            "old_ratio": float(old_pt.ratio),
            "new_ratio": float(new_pt.ratio),
            "old_fpr": None if old_pt.fpr is None else float(old_pt.fpr),
            "new_fpr": None if new_pt.fpr is None else float(new_pt.fpr),
            "err_cos": err_cos,
            "saturated_per_step": saturated,
            "rel_volume": rel_volume,
        }
        self.decisions.append(rec)
        if self.log is not None:
            self.log.append(rec)
        return rec

    # -- reporting -----------------------------------------------------

    def effective_ratio(self) -> float:
        """Step-weighted mean compress_ratio actually in effect so far."""
        if self.weight_steps <= 0:
            return float(self.point.ratio)
        return float(self.weighted_ratio / self.weight_steps)

    # -- checkpoint round-trip ----------------------------------------

    def state_dict(self, num_buckets: int = 0) -> Dict[str, Any]:
        """Controller state as a fixed-structure numpy pytree, suitable
        for stamping into an orbax checkpoint next to the train state."""
        self.ensure_prev(num_buckets)
        prev = dict(self._prev)
        # 0-d ndarrays, not numpy scalars — orbax rejects scalar types.
        # f32 is lossless here: every prev value came out of an f32
        # accumulator, so the round trip is bitwise.
        return {
            "index": np.asarray(self.index, np.int32),
            "up_votes": np.asarray(self.up_votes, np.int32),
            "down_votes": np.asarray(self.down_votes, np.int32),
            "windows": np.asarray(self.windows, np.int32),
            "switches": np.asarray(self.switches, np.int32),
            "weighted_ratio": np.asarray(self.weighted_ratio, np.float32),
            "weight_steps": np.asarray(self.weight_steps, np.int32),
            "prev": {
                **{
                    name: np.asarray(prev[name], np.float32)
                    for name in MetricAccumulators.scalar_fields()
                },
                "bucket_saturated": np.asarray(
                    prev["bucket_saturated"], dtype=np.float32
                ),
            },
        }

    def load_state_dict(self, state: Dict[str, Any]) -> None:
        self.index = int(state["index"])
        self.up_votes = int(state["up_votes"])
        self.down_votes = int(state["down_votes"])
        self.windows = int(state["windows"])
        self.switches = int(state["switches"])
        self.weighted_ratio = float(state["weighted_ratio"])
        self.weight_steps = int(state["weight_steps"])
        prev = state["prev"]
        self._prev = {
            **{name: float(prev[name]) for name in MetricAccumulators.scalar_fields()},
            "bucket_saturated": [float(v) for v in np.ravel(prev["bucket_saturated"])],
        }
