"""Discrete operating-point ladder — the bounded-re-jit contract.

The adaptive controller never tunes `compress_ratio`/`fpr` continuously:
payload shapes are a function of the slot budget k, so a continuous knob
would retrace (and recompile) the step on every move. Instead every value
the controller may ever select is pre-declared here as one rung of a
small, strictly-ordered ladder of `OperatingPoint`s (parsed once from
`cfg.ctrl_ladder` at construction). The ladder index is the ONLY thing
the controller moves, and each index maps to one static step program —
so at most ``len(ladder)`` distinct traces can ever exist over a run,
however long it is. The `jx-ctrl-ladder` analysis rule pins exactly that
cardinality on the traced exchange, and tests/test_controller.py pins it
on live compiled executables.

Spec syntax (``cfg.ctrl_ladder``): comma-separated ``ratio`` or
``ratio@fpr`` entries with strictly increasing ratios, e.g.
``"0.005,0.01@0.01,0.02@0.01,0.05"``. An entry without ``@fpr`` keeps the
base config's `fpr` semantics (including the default 0.1*k/d scaling).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from deepreduce_tpu.config import DeepReduceConfig


@dataclasses.dataclass(frozen=True)
class OperatingPoint:
    """One rung: the sparsifier budget ratio and (optionally) the bloom
    FPR pinned for that rung. ``fpr=None`` defers to the base config."""

    ratio: float
    fpr: Optional[float] = None

    def label(self) -> str:
        if self.fpr is None:
            return f"{self.ratio:g}"
        return f"{self.ratio:g}@{self.fpr:g}"


@dataclasses.dataclass(frozen=True)
class Ladder:
    """Ordered tuple of operating points, cheapest (lowest ratio) first."""

    points: Tuple[OperatingPoint, ...]

    @classmethod
    def parse(cls, spec: str) -> "Ladder":
        entries = [e.strip() for e in str(spec).split(",") if e.strip()]
        if len(entries) < 2:
            raise ValueError(
                "ctrl_ladder needs at least two operating points (a "
                f"single-point ladder cannot adapt), got {spec!r}"
            )
        points = []
        for entry in entries:
            ratio_s, _, fpr_s = entry.partition("@")
            try:
                ratio = float(ratio_s)
                fpr = float(fpr_s) if fpr_s else None
            except ValueError:
                raise ValueError(
                    f"ctrl_ladder entry {entry!r} is not 'ratio' or "
                    f"'ratio@fpr' (in {spec!r})"
                ) from None
            if not 0.0 < ratio <= 1.0:
                raise ValueError(
                    f"ctrl_ladder ratio must be in (0, 1], got {ratio} "
                    f"(in {spec!r})"
                )
            if fpr is not None and not 0.0 < fpr < 1.0:
                raise ValueError(
                    f"ctrl_ladder fpr must be in (0, 1), got {fpr} "
                    f"(in {spec!r})"
                )
            points.append(OperatingPoint(ratio=ratio, fpr=fpr))
        ratios = [p.ratio for p in points]
        if sorted(set(ratios)) != ratios:
            raise ValueError(
                "ctrl_ladder ratios must be strictly increasing (the "
                f"controller moves ±1 rung on an ordered ladder), got {spec!r}"
            )
        return cls(points=tuple(points))

    def __len__(self) -> int:
        return len(self.points)

    def __getitem__(self, idx: int) -> OperatingPoint:
        return self.points[idx]

    def index_near(self, ratio: float) -> int:
        """The rung closest to `ratio` (ties toward the cheaper rung) —
        where an adaptive run starts from its base `compress_ratio`."""
        best = min(
            range(len(self.points)),
            key=lambda i: (abs(self.points[i].ratio - ratio), i),
        )
        return best

    def apply(self, cfg: DeepReduceConfig, idx: int) -> DeepReduceConfig:
        """The config for rung `idx`: the base config with the rung's
        ratio (and fpr, when the rung pins one) substituted. Everything
        the step builds from this config — slot budgets, bloom geometry,
        payload layouts — follows statically, so one rung == one trace."""
        if not 0 <= idx < len(self.points):
            raise ValueError(
                f"ladder index {idx} out of range [0, {len(self.points)})"
            )
        pt = self.points[idx]
        kw = {"compress_ratio": pt.ratio}
        if pt.fpr is not None:
            kw["fpr"] = pt.fpr
        return dataclasses.replace(cfg, **kw)

    def labels(self) -> Tuple[str, ...]:
        return tuple(p.label() for p in self.points)
