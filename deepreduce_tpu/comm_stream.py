"""Backprop-overlapped streaming bucket exchange (``cfg.stream_exchange``).

The barrier and pipeline schedules (comm_bucket.run) both wait for the
full ``value_and_grad`` pytree before the first encode. This module moves
each bucket's encode + all_gather INTO the backward pass: the loss is
wrapped so every bucket's member parameters flow through an identity
``jax.custom_vjp`` hook, and the hook's backward rule — which reverse-mode
AD executes at the exact point where the bucket's last member cotangent
exists — runs that bucket's compensate → encode → pack → all_gather →
decode (`BucketedExchanger.run_streaming_bucket`). Wire time hides behind
the backward compute still running for earlier layers, the per-tensor-hook
design DeepReduce inherited from Horovod done natively in XLA.

Mechanics worth knowing before editing:

* **Hook placement.** Reverse-mode AD runs each equation's transpose at
  the mirrored position of its forward occurrence, so the hooks are
  applied to the params in REVERSED bucket order during the forward pass —
  their backward rules then fire in bucket order 0..C-1, which under
  ``bucket_order="reverse"`` is backward-completion order.
* **Dispatch pinning.** A scalar f32 token threads hook-to-hook. Inside
  each backward rule the incoming token is `optimization_barrier`-tied to
  the bucket's dense gradient before encode, and the outgoing token to its
  gathered buffer — so bucket b+1's encode cannot be hoisted above bucket
  b's gather dispatch, while the barrier (a value identity) leaves every
  number untouched.
* **Bitwise contract.** Same partition, same codecs, same
  ``per_tensor_key(worker_key, label, step)`` PRNG keys, same pack/gather/
  decode arithmetic, same ``total / num_workers`` mean and dtype casts as
  `GradientExchanger.exchange` over `BucketedExchanger.run` — the
  streaming step's params/residuals/telemetry are bit-identical to the
  ``bucket_pipeline`` schedules (tests/test_streaming.py pins this); only
  the dispatch order moves.
* **Residual feedback as cotangents.** The hook takes the bucket's
  residual leaves as a differentiated argument; its backward rule returns
  the aggregated mean as the PARAM cotangent and the updated residual
  (compensated − own decode) as the RESIDUAL cotangent, so one
  ``jax.value_and_grad(..., argnums=(0, 1))`` yields both trees with no
  second pass.
* **Trace-time side channel.** Backward rules execute while the grad call
  is being traced, so per-bucket WireStats, payloads (for fp_stats), and
  the raw incoming cotangents (the un-compensated gradients telemetry
  needs) are stashed in host-side dicts and consumed right after the grad
  call returns — same trace, no host sync.
* **`step`/`worker_key` ride as hook arguments** (custom_vjp rejects
  closed-over tracers); being integer-dtype primals their cotangents are
  ``float0`` zeros.

What does NOT compose (rejected loudly in config.__post_init__):
resilience (mask/chaos/checksum state has no per-hook threading), hier
(its two-leg slice schedule owns the whole pytree), fed. A flat streaming
exchange over a multi-axis mesh via a tuple ``axis_name`` works and is
covered by tests.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepreduce_tpu.metrics import combine


def _float0_zeros(x):
    """The cotangent for an integer-dtype primal: float0 zeros of its shape."""
    return np.zeros(np.shape(x), jax.dtypes.float0)


class StreamingExchange:
    """Streams a GradientExchanger's bucketed exchange out of the backward
    pass. Built once per compiled step (Trainer rung) from an exchanger
    that already has a `BucketedExchanger`; `value_and_grad_exchange` is
    the streaming replacement for value_and_grad + `exchanger.exchange`.
    """

    def __init__(self, exchanger):
        if exchanger._bucketed is None:
            raise ValueError(
                "StreamingExchange needs the bucketed exchange — construct "
                "the GradientExchanger with cfg.bucket_bytes set"
            )
        self.exchanger = exchanger
        self.bucketed = exchanger._bucketed
        self.cfg = exchanger.cfg
        self.axis_name = exchanger.axis_name
        self.names = list(exchanger.names)
        self._pos = {n: i for i, n in enumerate(self.names)}

    def value_and_grad_exchange(
        self,
        loss_fn: Callable,
        params: Any,
        batch_stats: Any,
        batch: Any,
        residuals: Any,
        *,
        step,
        key=None,
        collect: Optional[Dict[str, jax.Array]] = None,
    ):
        """One streamed forward+backward+exchange.

        Returns ``((loss, aux), grads, agg, new_residuals, wire)``:
        worker-local loss and aux exactly as ``value_and_grad(loss_fn,
        has_aux=True)`` would, the RAW per-worker gradients (for telemetry
        parity with the unstreamed step), the aggregated mean gradients in
        the runtime grad dtype, the updated residual tree (None when
        ``residuals`` is None), and the combined WireStats. ``collect``
        receives the same fp_count / fp_universe / bucket_saturated
        telemetry scalars `GradientExchanger.exchange` would produce.
        """
        cfg = self.cfg
        bucketed = self.bucketed
        specs = bucketed.specs
        has_res = residuals is not None
        widx = jax.lax.axis_index(self.axis_name)
        if key is None:
            key = jax.random.PRNGKey(cfg.seed)
        worker_key = jax.random.fold_in(key, widx)

        # trace-time side channel: the hooks' backward rules populate these
        # while the grad call below is being traced
        stash: Dict[str, Dict[str, Any]] = {"stats": {}, "payloads": {}, "raw": {}}
        hooks = [
            self._make_hook(b, stash, need_own=has_res) for b in range(len(specs))
        ]
        leaves_like = jax.tree_util.tree_leaves(params)
        if len(leaves_like) != len(self.names):
            raise ValueError(
                f"params tree has {len(leaves_like)} leaves but the "
                f"exchanger was built for {len(self.names)}"
            )

        def hooked_loss(p, r):
            leaves = list(jax.tree_util.tree_leaves(p))
            res_leaves = jax.tree_util.tree_leaves(r) if has_res else None
            tok = jnp.zeros((), jnp.float32)
            # reversed bucket order here → backward rules fire in bucket
            # order 0..C-1 during backprop (see module docstring)
            for b in range(len(specs) - 1, -1, -1):
                idxs = [self._pos[n] for n in specs[b].names]
                sub = tuple(leaves[i] for i in idxs)
                rsub = (
                    tuple(res_leaves[i] for i in idxs) if has_res else ()
                )
                sub, tok = hooks[b](sub, rsub, step, worker_key, tok)
                for j, i in enumerate(idxs):
                    leaves[i] = sub[j]
            p_hooked = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(p), leaves
            )
            return loss_fn(p_hooked, batch_stats, batch)

        if has_res:
            (loss, aux), (agg_tree, new_res) = jax.value_and_grad(
                hooked_loss, argnums=(0, 1), has_aux=True
            )(params, residuals)
        else:
            (loss, aux), agg_tree = jax.value_and_grad(
                hooked_loss, has_aux=True
            )(params, None)
            new_res = None

        # spec-order dicts so combine()'s summation order — and therefore
        # the f32 wire totals — match the barrier/pipeline encode loop
        stats_per = {s.label: stash["stats"][s.label] for s in specs}
        payloads = {s.label: stash["payloads"][s.label] for s in specs}
        raw_leaves = {}
        for spec in specs:
            raw_leaves.update(dict(zip(spec.names, stash["raw"][spec.label])))
        grads = jax.tree_util.tree_unflatten(
            self.exchanger.treedef, [raw_leaves[n] for n in self.names]
        )

        if collect is not None:
            fp_c = jnp.zeros((), jnp.float32)
            fp_u = jnp.zeros((), jnp.float32)
            for label, codec in bucketed.codecs.items():
                stats = codec.fp_stats(payloads[label])
                if stats is None:
                    continue
                fp_c = fp_c + stats[0]
                fp_u = fp_u + stats[1]
            collect["fp_count"] = fp_c
            collect["fp_universe"] = fp_u
            collect["bucket_saturated"] = bucketed.saturation_vector(stats_per)

        return (loss, aux), grads, agg_tree, new_res, combine(stats_per)

    def _make_hook(self, b: int, stash, *, need_own: bool):
        """The identity custom_vjp hook for bucket `b`. Forward passes the
        bucket's param leaves (and the dispatch token) through unchanged;
        backward runs the bucket's whole streamed exchange and returns the
        aggregated mean as the param cotangent, the updated residual as the
        residual cotangent, and the chained token."""
        bucketed = self.bucketed
        spec = bucketed.specs[b]
        cfg = self.cfg
        axis = self.axis_name

        @jax.custom_vjp
        def hook(p_leaves, r_leaves, step, worker_key, token):
            return p_leaves, token

        def fwd(p_leaves, r_leaves, step, worker_key, token):
            return (p_leaves, token), (r_leaves, step, worker_key)

        def bwd(saved, cts):
            r_leaves, step, worker_key = saved
            g_leaves, token = cts
            num_workers = jax.lax.psum(1, axis)
            # per-leaf memory.compensate (identical expression per leaf)
            if need_own:
                comp = tuple(
                    cfg.beta * r + cfg.gamma * g
                    for r, g in zip(r_leaves, g_leaves)
                )
            else:
                comp = tuple(g_leaves)
            flat = dict(zip(spec.names, comp))
            total, own, stats, payload, token = bucketed.run_streaming_bucket(
                b,
                flat,
                num_workers,
                step,
                worker_key,
                need_own=need_own,
                token=token,
            )
            agg_slices = bucketed.split_bucket(spec, total / num_workers)
            agg_ct = tuple(
                agg_slices[n].astype(c.dtype) for n, c in zip(spec.names, comp)
            )
            if need_own:
                own_slices = bucketed.split_bucket(spec, own)
                # per-leaf memory.update: compensated − own decode, with the
                # same dtype cast exchange() applies before the update
                res_ct = tuple(
                    c - own_slices[n].astype(c.dtype)
                    for n, c in zip(spec.names, comp)
                )
            else:
                res_ct = ()
            stash["stats"][spec.label] = stats
            stash["payloads"][spec.label] = payload
            stash["raw"][spec.label] = tuple(g_leaves)
            return (
                agg_ct,
                res_ct,
                _float0_zeros(step),
                _float0_zeros(worker_key),
                token,
            )

        hook.defvjp(fwd, bwd)
        return hook
