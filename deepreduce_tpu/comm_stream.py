"""Backprop-overlapped streaming bucket exchange (``cfg.stream_exchange``).

The barrier and pipeline schedules (comm_bucket.run) both wait for the
full ``value_and_grad`` pytree before the first encode. This module moves
each bucket's encode + all_gather INTO the backward pass: the loss is
wrapped so every bucket's member parameters flow through an identity
``jax.custom_vjp`` hook, and the hook's backward rule — which reverse-mode
AD executes at the exact point where the bucket's last member cotangent
exists — runs that bucket's compensate → encode → pack → all_gather →
decode (`BucketedExchanger.run_streaming_bucket`). Wire time hides behind
the backward compute still running for earlier layers, the per-tensor-hook
design DeepReduce inherited from Horovod done natively in XLA.

Mechanics worth knowing before editing:

* **Hook placement.** Reverse-mode AD runs each equation's transpose at
  the mirrored position of its forward occurrence, so the hooks are
  applied to the params in REVERSED bucket order during the forward pass —
  their backward rules then fire in bucket order 0..C-1, which under
  ``bucket_order="reverse"`` is backward-completion order.
* **Dispatch pinning.** A scalar f32 token threads hook-to-hook. Inside
  each backward rule the incoming token is `optimization_barrier`-tied to
  the bucket's dense gradient before encode, and the outgoing token to its
  gathered buffer — so bucket b+1's encode cannot be hoisted above bucket
  b's gather dispatch, while the barrier (a value identity) leaves every
  number untouched.
* **Bitwise contract.** Same partition, same codecs, same
  ``per_tensor_key(worker_key, label, step)`` PRNG keys, same pack/gather/
  decode arithmetic, same ``total / num_workers`` mean and dtype casts as
  `GradientExchanger.exchange` over `BucketedExchanger.run` — the
  streaming step's params/residuals/telemetry are bit-identical to the
  ``bucket_pipeline`` schedules (tests/test_streaming.py pins this); only
  the dispatch order moves.
* **Residual feedback as cotangents.** The hook takes the bucket's
  residual leaves as a differentiated argument; its backward rule returns
  the aggregated mean as the PARAM cotangent and the updated residual
  (compensated − own decode) as the RESIDUAL cotangent, so one
  ``jax.value_and_grad(..., argnums=(0, 1))`` yields both trees with no
  second pass.
* **Trace-time side channel.** Backward rules execute while the grad call
  is being traced, so per-bucket WireStats, payloads (for fp_stats), and
  the raw incoming cotangents (the un-compensated gradients telemetry
  needs) are stashed in host-side dicts and consumed right after the grad
  call returns — same trace, no host sync.
* **`step`/`worker_key` ride as hook arguments** (custom_vjp rejects
  closed-over tracers); being integer-dtype primals their cotangents are
  ``float0`` zeros.

* **Hierarchical composition.** Constructed over a
  ``HierarchicalExchanger`` (dense ICI leg, config-pinned bucketed DCN
  leg — the shape config's narrowed ``stream-vs-hier`` fence admits),
  each hook's backward rule runs its bucket's ICI slice-mean psum AND its
  compressed DCN gather: the psum rides the bucket's ``pre_encode`` slot
  between the entry barrier and the encode, so the one token chain pins
  per-AXIS collective order (bucket b+1's ici psum cannot be hoisted
  above bucket b's dcn gather) with still exactly two barriers per
  bucket. ``psum(concat(leaves)) == concat(psum(leaves))`` elementwise,
  so the streamed step stays bitwise-equal to the barrier-scheduled
  `HierarchicalExchanger.exchange` (tests/test_streaming.py pins this
  too); `WireStats.ici_bits` and the caller-key ici repair gather follow
  the barrier path's arithmetic exactly.

What does NOT compose (rejected loudly in config.__post_init__):
resilience (mask/chaos/checksum state has no per-hook threading), the
qar ICI leg and auto-rewritten DCN routes (they restructure the legs the
hooks captured), fed. A flat streaming exchange over a multi-axis mesh
via a tuple ``axis_name`` works and is covered by tests.
"""
from __future__ import annotations

import dataclasses

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepreduce_tpu.metrics import combine
from deepreduce_tpu.telemetry import spans


def _float0_zeros(x):
    """The cotangent for an integer-dtype primal: float0 zeros of its shape."""
    return np.zeros(np.shape(x), jax.dtypes.float0)


class StreamingExchange:
    """Streams a GradientExchanger's bucketed exchange out of the backward
    pass. Built once per compiled step (Trainer rung) from an exchanger
    that already has a `BucketedExchanger`; `value_and_grad_exchange` is
    the streaming replacement for value_and_grad + `exchanger.exchange`.
    """

    def __init__(self, exchanger):
        # composable-leg detection: a HierarchicalExchanger wraps the inner
        # DCN-leg GradientExchanger and names the ici axis its hooks must
        # reduce over (duck-typed so this module stays import-cycle-free)
        self.hier = None
        inner = exchanger
        if hasattr(exchanger, "ici_axis") and hasattr(exchanger, "exchanger"):
            self.hier = exchanger
            inner = exchanger.exchanger
            if exchanger.ici_leg != "dense":
                raise ValueError(
                    "StreamingExchange over a HierarchicalExchanger "
                    "requires the dense ICI leg — the qar leg's two-phase "
                    "quantized allreduce cannot split per bucket hook "
                    f"(got hier_ici={exchanger.ici_leg!r})"
                )
        if inner._bucketed is None:
            raise ValueError(
                "StreamingExchange needs the bucketed exchange — construct "
                "the GradientExchanger with cfg.bucket_bytes set"
            )
        self.exchanger = inner
        self.bucketed = inner._bucketed
        self.cfg = inner.cfg
        self.axis_name = inner.axis_name
        self.names = list(inner.names)
        self._pos = {n: i for i, n in enumerate(self.names)}

    def value_and_grad_exchange(
        self,
        loss_fn: Callable,
        params: Any,
        batch_stats: Any,
        batch: Any,
        residuals: Any,
        *,
        step,
        key=None,
        collect: Optional[Dict[str, jax.Array]] = None,
    ):
        """One streamed forward+backward+exchange.

        Returns ``((loss, aux), grads, agg, new_residuals, wire)``:
        worker-local loss and aux exactly as ``value_and_grad(loss_fn,
        has_aux=True)`` would, the RAW per-worker gradients (for telemetry
        parity with the unstreamed step), the aggregated mean gradients in
        the runtime grad dtype, the updated residual tree (None when
        ``residuals`` is None), and the combined WireStats. ``collect``
        receives the same fp_count / fp_universe / bucket_saturated
        telemetry scalars `GradientExchanger.exchange` would produce.
        """
        cfg = self.cfg
        bucketed = self.bucketed
        specs = bucketed.specs
        has_res = residuals is not None
        widx = jax.lax.axis_index(self.axis_name)
        key_repair_bits = 0.0
        if key is None:
            key = jax.random.PRNGKey(cfg.seed)
        elif self.hier is not None:
            # the HierarchicalExchanger contract: every ICI replica of a
            # DCN group runs the identical stochastic encode — broadcast
            # replica 0's key over the ici axis, exactly as the barrier
            # path does (parallel/hierarchical.py)
            n_ici = jax.lax.psum(1, self.hier.ici_axis)
            if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
                kdata = jax.random.key_data(key)
                key_repair_bits += kdata.size * 32.0 * (n_ici - 1)
                kdata = jax.lax.all_gather(kdata, self.hier.ici_axis)[0]
                key = jax.random.wrap_key_data(
                    kdata, impl=jax.random.key_impl(key)
                )
            else:
                key_repair_bits += key.size * 32.0 * (n_ici - 1)
                key = jax.lax.all_gather(key, self.hier.ici_axis)[0]
        worker_key = jax.random.fold_in(key, widx)

        # trace-time side channel: the hooks' backward rules populate these
        # while the grad call below is being traced
        stash: Dict[str, Dict[str, Any]] = {"stats": {}, "payloads": {}, "raw": {}}
        hooks = [
            self._make_hook(b, stash, need_own=has_res) for b in range(len(specs))
        ]
        leaves_like = jax.tree_util.tree_leaves(params)
        if len(leaves_like) != len(self.names):
            raise ValueError(
                f"params tree has {len(leaves_like)} leaves but the "
                f"exchanger was built for {len(self.names)}"
            )

        def hooked_loss(p, r):
            leaves = list(jax.tree_util.tree_leaves(p))
            res_leaves = jax.tree_util.tree_leaves(r) if has_res else None
            tok = jnp.zeros((), jnp.float32)
            # reversed bucket order here → backward rules fire in bucket
            # order 0..C-1 during backprop (see module docstring)
            for b in range(len(specs) - 1, -1, -1):
                idxs = [self._pos[n] for n in specs[b].names]
                sub = tuple(leaves[i] for i in idxs)
                rsub = (
                    tuple(res_leaves[i] for i in idxs) if has_res else ()
                )
                sub, tok = hooks[b](sub, rsub, step, worker_key, tok)
                for j, i in enumerate(idxs):
                    leaves[i] = sub[j]
            p_hooked = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(p), leaves
            )
            return loss_fn(p_hooked, batch_stats, batch)

        if has_res:
            (loss, aux), (agg_tree, new_res) = jax.value_and_grad(
                hooked_loss, argnums=(0, 1), has_aux=True
            )(params, residuals)
        else:
            (loss, aux), agg_tree = jax.value_and_grad(
                hooked_loss, has_aux=True
            )(params, None)
            new_res = None

        # spec-order dicts so combine()'s summation order — and therefore
        # the f32 wire totals — match the barrier/pipeline encode loop
        stats_per = {s.label: stash["stats"][s.label] for s in specs}
        payloads = {s.label: stash["payloads"][s.label] for s in specs}
        raw_leaves = {}
        for spec in specs:
            raw_leaves.update(dict(zip(spec.names, stash["raw"][spec.label])))
        grads = jax.tree_util.tree_unflatten(
            self.exchanger.treedef, [raw_leaves[n] for n in self.names]
        )

        if collect is not None:
            fp_c = jnp.zeros((), jnp.float32)
            fp_u = jnp.zeros((), jnp.float32)
            for label, codec in bucketed.codecs.items():
                stats = codec.fp_stats(payloads[label])
                if stats is None:
                    continue
                fp_c = fp_c + stats[0]
                fp_u = fp_u + stats[1]
            collect["fp_count"] = fp_c
            collect["fp_universe"] = fp_u
            collect["bucket_saturated"] = bucketed.saturation_vector(stats_per)

        wire = combine(stats_per)
        if self.hier is not None:
            # the ICI leg's wire share, split by fabric exactly as the
            # barrier path accounts it: the dense slice-mean psum's
            # ring-adjusted bits (whole tree — bucketing does not change
            # the total) plus the key-repair gather when a key was passed.
            # payload_bytes()/index+value bits stay DCN-only by contract.
            n_ici = jax.lax.psum(1, self.hier.ici_axis)
            ici_bits = key_repair_bits
            if n_ici > 1:
                d = sum(
                    int(np.prod(l.shape)) if l.shape else 1
                    for l in leaves_like
                )
                ici_bits += 2.0 * (n_ici - 1) / n_ici * 32.0 * d
            wire = dataclasses.replace(
                wire,
                ici_bits=wire.ici_bits + jnp.asarray(ici_bits, jnp.float32),
            )
        return (loss, aux), grads, agg_tree, new_res, wire

    def _make_hook(self, b: int, stash, *, need_own: bool):
        """The identity custom_vjp hook for bucket `b`. Forward passes the
        bucket's param leaves (and the dispatch token) through unchanged;
        backward runs the bucket's whole streamed exchange and returns the
        aggregated mean as the param cotangent, the updated residual as the
        residual cotangent, and the chained token."""
        bucketed = self.bucketed
        spec = bucketed.specs[b]
        cfg = self.cfg
        axis = self.axis_name
        ici_axis = self.hier.ici_axis if self.hier is not None else None

        @jax.custom_vjp
        def hook(p_leaves, r_leaves, step, worker_key, token):
            return p_leaves, token

        def fwd(p_leaves, r_leaves, step, worker_key, token):
            return (p_leaves, token), (r_leaves, step, worker_key)

        def bwd(saved, cts):
            r_leaves, step, worker_key = saved
            g_leaves, token = cts
            num_workers = jax.lax.psum(1, axis)
            pre_encode = None
            if ici_axis is not None:
                # hierarchical composition: the bucket's ICI slice-mean
                # psum + per-leaf compensate run in the pre_encode slot —
                # after the entry barrier, so the token chain pins the
                # psum's dispatch order too. psum(concat) == concat(psum)
                # elementwise, and beta*r + gamma*sm commutes with concat,
                # so every number matches the barrier-scheduled
                # HierarchicalExchanger.exchange bit for bit.
                n_ici = jax.lax.psum(1, ici_axis)
                r_dense = (
                    bucketed.concat_bucket(dict(zip(spec.names, r_leaves)), spec)
                    if need_own
                    else None
                )

                def pre_encode(dense):
                    with spans.span("exchange/ici"):
                        sm = jax.lax.psum(dense, ici_axis) / n_ici
                    if need_own:
                        return cfg.beta * r_dense + cfg.gamma * sm
                    return sm

                flat = dict(zip(spec.names, g_leaves))
            else:
                # per-leaf memory.compensate (identical expression per leaf)
                if need_own:
                    comp = tuple(
                        cfg.beta * r + cfg.gamma * g
                        for r, g in zip(r_leaves, g_leaves)
                    )
                else:
                    comp = tuple(g_leaves)
                flat = dict(zip(spec.names, comp))
            total, own, stats, payload, token, dense = (
                bucketed.run_streaming_bucket(
                    b,
                    flat,
                    num_workers,
                    step,
                    worker_key,
                    need_own=need_own,
                    token=token,
                    pre_encode=pre_encode,
                )
            )
            if ici_axis is not None:
                # the hook's comp leaves are slices of the compensated
                # slice-mean run_streaming_bucket encoded
                comp_slices = bucketed.split_bucket(spec, dense)
                comp = tuple(comp_slices[n] for n in spec.names)
            agg_slices = bucketed.split_bucket(spec, total / num_workers)
            agg_ct = tuple(
                agg_slices[n].astype(c.dtype) for n, c in zip(spec.names, comp)
            )
            if need_own:
                own_slices = bucketed.split_bucket(spec, own)
                # per-leaf memory.update: compensated − own decode, with the
                # same dtype cast exchange() applies before the update
                res_ct = tuple(
                    c - own_slices[n].astype(c.dtype)
                    for n, c in zip(spec.names, comp)
                )
            else:
                res_ct = ()
            stash["stats"][spec.label] = stats
            stash["payloads"][spec.label] = payload
            stash["raw"][spec.label] = tuple(g_leaves)
            return (
                agg_ct,
                res_ct,
                _float0_zeros(step),
                _float0_zeros(worker_key),
                token,
            )

        hook.defvjp(fwd, bwd)
        return hook
