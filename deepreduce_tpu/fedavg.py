"""Federated averaging with bidirectionally-compressed exchange.

The reference's second deployment (paper §6.2, Algorithm 2, Tables 2/5/6;
SURVEY.md §2.5 'Parameter-server / FedAvg topology'): a server and N
clients; each round the server samples C clients, broadcasts the model
delta **compressed** (S2C), the sampled clients run E local SGD steps and
return their updates **compressed** (C2S), and the server averages. Both
directions run through the same DeepReduce codec stack the DP path uses
(`wrappers.TensorCodec`). Error feedback: the S2C broadcast compresses
the delta `params - w_ref` against the *receiver's* reconstructed state, a
closed loop that re-sends compression error by construction (an explicit
residual on top would deliver it twice and oscillate); C2S updates are
fresh each round, so they carry a per-client residual accumulator.

Design notes (TPU-native, vs the reference's 57-VM AWS testbed):

- The topology is a *simulation harness* in one program: payloads are
  encoded then decoded in place, and the wire cost is accounted through
  `WireStats` exactly as the paper's Table-2 relative-volume numbers are
  (transmitted bits / dense bits, both directions). On a real multi-host
  deployment the payload pytrees are what crosses DCN.
- Clients share one reference model `w_ref` (what every client can
  reconstruct from the broadcast stream); the server's true model differs
  from it only by not-yet-delivered delta mass. This keeps state O(model), not
  O(clients x model) — except the per-client C2S residuals, which are the
  price of client-side error feedback (paper keeps these on each device).
- The round is ONE `lax.scan` over the stacked client axis (each body
  iteration is itself a `lax.scan` over local steps), so the compiled
  program size is independent of the number of sampled clients — the
  paper's 56-client rounds compile exactly one copy of
  local-train + codec.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.metrics import WireStats, combine
from deepreduce_tpu.telemetry import spans
from deepreduce_tpu.wrappers import TensorCodec


@dataclasses.dataclass(frozen=True)
class FedConfig:
    """Round geometry (paper §6.2: 56 clients sampled from 57 VMs;
    Table 5: 10 clients, 800 rounds)."""

    num_clients: int
    clients_per_round: int
    local_steps: int = 1
    server_lr: float = 1.0


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class FedAvgState:
    params: Any  # server's true model
    w_ref: Any  # the model every client can reconstruct from broadcasts
    c2s_residuals: Optional[Any]  # [num_clients, ...] per-client EF
    round: jax.Array

    def tree_flatten(self):
        return ((self.params, self.w_ref, self.c2s_residuals, self.round), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


class FedAvg:
    """Compressed-FedAvg harness.

    loss_fn(params, batch) -> scalar loss; client_optimizer is applied for
    `local_steps` on each sampled client's batches.
    """

    def __init__(
        self,
        loss_fn: Callable,
        cfg_c2s: DeepReduceConfig,
        fed: FedConfig,
        client_optimizer: optax.GradientTransformation,
        *,
        cfg_s2c: Optional[DeepReduceConfig] = None,
    ):
        self.loss_fn = loss_fn
        self.cfg_c2s = cfg_c2s
        self.cfg_s2c = cfg_s2c if cfg_s2c is not None else cfg_c2s
        self.fed = fed
        self.client_opt = client_optimizer
        self._codecs: Dict[str, Dict[Any, TensorCodec]] = {}

    # ------------------------------------------------------------------ #

    def _codec(self, direction: str, path: str, shape) -> TensorCodec:
        cfg = self.cfg_s2c if direction == "s2c" else self.cfg_c2s
        per_dir = self._codecs.setdefault(direction, {})
        if path not in per_dir:
            per_dir[path] = TensorCodec(tuple(shape), cfg, name=f"{direction}/{path}")
        return per_dir[path]

    def _compress_tree(
        self, direction: str, tree: Any, residual: Optional[Any], step, key
    ) -> Tuple[Any, Optional[Any], WireStats]:
        """Encode+decode each leaf through its codec: returns (what the
        receiver reconstructs, updated residual, wire bits)."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        res_leaves = (
            jax.tree_util.tree_leaves(residual) if residual is not None else [None] * len(leaves)
        )
        out, new_res, stats = [], [], []
        for i, (leaf, r) in enumerate(zip(leaves, res_leaves)):
            codec = self._codec(direction, str(i), leaf.shape)
            flat = leaf.reshape(-1)
            comp = flat + r.reshape(-1) if r is not None else flat
            k = jax.random.fold_in(key, i)
            payload = codec.encode(comp.reshape(leaf.shape), step=step, key=k)
            dec = codec.decode(payload, step=step).reshape(leaf.shape)
            out.append(dec)
            new_res.append((comp.reshape(leaf.shape) - dec) if r is not None else None)
            stats.append(codec.wire_stats(payload))
        wire = combine({str(i): s for i, s in enumerate(stats)})
        new_residual = (
            jax.tree_util.tree_unflatten(treedef, new_res) if residual is not None else None
        )
        return jax.tree_util.tree_unflatten(treedef, out), new_residual, wire

    # ------------------------------------------------------------------ #

    def init(self, params: Any) -> FedAvgState:
        use_res = self.cfg_c2s.memory == "residual"
        c2s = (
            jax.tree_util.tree_map(
                lambda p: jnp.zeros((self.fed.num_clients,) + p.shape, p.dtype), params
            )
            if use_res
            else None
        )
        return FedAvgState(
            params=params,
            w_ref=jax.tree_util.tree_map(jnp.array, params),
            c2s_residuals=c2s,
            round=jnp.zeros((), jnp.int32),
        )

    def sample_clients(self, state: FedAvgState, key: jax.Array) -> jax.Array:
        """C client ids drawn without replacement (Algorithm 2's random
        subset per round)."""
        return jax.random.choice(
            key,
            self.fed.num_clients,
            (self.fed.clients_per_round,),
            replace=False,
        )

    def _local_train(self, params: Any, batches: Any, key: jax.Array) -> Any:
        opt_state = self.client_opt.init(params)

        def one_step(carry, batch):
            p, o = carry
            grads = jax.grad(self.loss_fn)(p, batch)
            updates, o = self.client_opt.update(grads, o, p)
            return (optax.apply_updates(p, updates), o), None

        (p_end, _), _ = jax.lax.scan(one_step, (params, opt_state), batches)
        return p_end

    def run_round(
        self,
        state: FedAvgState,
        ids: jax.Array,
        client_batches: Any,
        key: jax.Array,
        *,
        participation: Optional[jax.Array] = None,
    ) -> Tuple[FedAvgState, Dict[str, Any]]:
        """One round. `ids` from `sample_clients`; `client_batches` leaves
        are [clients_per_round, local_steps, ...] for exactly those ids.

        `participation` (bool[C] over the SAMPLED clients, or None) models
        a sampled client failing to return its C2S update: a False
        client's decoded update and wire bits are scaled to zero, the
        server mean renormalizes by the live count, and the client's C2S
        residual is left untouched (it never compressed, so there is no
        new error to feed back — its pending mass waits for the next time
        it is sampled). The S2C broadcast stays global: `w_ref` models
        what every client *can* reconstruct from the broadcast stream.
        With participation=None the traced round is unchanged."""
        C = self.fed.clients_per_round
        has_part = participation is not None
        part = participation.astype(jnp.float32) if has_part else None
        key_s2c, key_c2s = jax.random.split(key)

        # --- S2C: broadcast the compressed model delta -------------------
        # delta is taken against the receiver-side state w_ref, so the
        # loop is self-correcting: undelivered mass reappears in the next
        # round's delta (no explicit residual — see module docstring)
        delta = jax.tree_util.tree_map(lambda w, r: w - r, state.params, state.w_ref)
        with spans.span("fedavg/s2c"):
            dec_delta, _, wire_s2c = self._compress_tree(
                "s2c", delta, None, state.round, key_s2c
            )
        w_ref = jax.tree_util.tree_map(jnp.add, state.w_ref, dec_delta)

        # --- local training + C2S on each sampled client -----------------
        # ONE lax.scan over the stacked client axis: the compiled program
        # size is independent of C (the paper's 56-client config would
        # otherwise build 56 copies of local-train + codec). Residuals for
        # the sampled ids are gathered up front and scattered back after —
        # ids are drawn without replacement, so the batched scatter is
        # collision-free.
        c2s_res = state.c2s_residuals
        use_res = c2s_res is not None
        res_stack = (
            jax.tree_util.tree_map(lambda r: r[ids], c2s_res) if use_res else None
        )
        upd_sum0 = jax.tree_util.tree_map(jnp.zeros_like, state.params)
        wire0 = WireStats(
            index_bits=jnp.zeros((), jnp.float32),
            value_bits=jnp.zeros((), jnp.float32),
            dense_bits=jnp.zeros((), jnp.float32),
        )

        def client_body(carry, xs):
            upd_sum, wire_acc = carry
            c, batch_c = xs[0], xs[1]
            rest = xs[2:]
            res_c = rest[0] if use_res else None
            m = rest[-1] if has_part else None
            with spans.span("fedavg/local_train"):
                p_end = self._local_train(
                    w_ref, batch_c, jax.random.fold_in(key_c2s, 2 * c)
                )
            update = jax.tree_util.tree_map(lambda a, b: a - b, p_end, w_ref)
            with spans.span("fedavg/c2s"):
                dec_upd, new_res_c, wire_c = self._compress_tree(
                    "c2s", update, res_c, state.round,
                    jax.random.fold_in(key_c2s, 2 * c + 1),
                )
            if has_part:
                # a non-participating client returns nothing: zero its
                # decoded update and wire bits, and keep its residual as it
                # was (no compression happened, no new error to feed back)
                dec_upd = jax.tree_util.tree_map(lambda u: u * m, dec_upd)
                if use_res:
                    new_res_c = jax.tree_util.tree_map(
                        lambda new, old: jnp.where(m > 0, new, old),
                        new_res_c,
                        res_c,
                    )
                wire_c = WireStats(
                    index_bits=wire_c.index_bits * m,
                    value_bits=wire_c.value_bits * m,
                    dense_bits=wire_c.dense_bits * m,
                )
            upd_sum = jax.tree_util.tree_map(jnp.add, upd_sum, dec_upd)
            wire_acc = WireStats(
                index_bits=wire_acc.index_bits + wire_c.index_bits,
                value_bits=wire_acc.value_bits + wire_c.value_bits,
                dense_bits=wire_acc.dense_bits + wire_c.dense_bits,
            )
            return (upd_sum, wire_acc), (new_res_c if use_res else 0)

        cs = jnp.arange(C, dtype=jnp.uint32)
        xs = (cs, client_batches)
        if use_res:
            xs = xs + (res_stack,)
        if has_part:
            xs = xs + (part,)
        with spans.span("fedavg/clients"):
            (upd_sum, wire_c2s), new_res_stack = jax.lax.scan(
                client_body, (upd_sum0, wire0), xs
            )
        if use_res:
            c2s_res = jax.tree_util.tree_map(
                lambda buf, nr: buf.at[ids].set(nr), c2s_res, new_res_stack
            )
        wires = [wire_s2c, wire_c2s]

        if has_part:
            live = jnp.maximum(jnp.sum(part), 1.0)
            mean_upd = jax.tree_util.tree_map(lambda s: s / live, upd_sum)
        else:
            mean_upd = jax.tree_util.tree_map(lambda s: s / C, upd_sum)
        new_params = jax.tree_util.tree_map(
            lambda w, u: w + self.fed.server_lr * u, state.params, mean_upd
        )
        wire = combine({str(i): s for i, s in enumerate(wires)})
        new_state = FedAvgState(
            params=new_params,
            w_ref=w_ref,
            c2s_residuals=c2s_res,
            round=state.round + 1,
        )
        # dense bits counted once per direction-crossing: S2C once (broadcast)
        # + C2S per sampled client — matches the paper's Table-2 accounting
        # (relative data volume over everything transmitted)
        return new_state, {"wire": wire, "rel_volume": wire.rel_volume()}
