"""Federated averaging with bidirectionally-compressed exchange.

The reference's second deployment (paper §6.2, Algorithm 2, Tables 2/5/6;
SURVEY.md §2.5 'Parameter-server / FedAvg topology'): a server and N
clients; each round the server samples C clients, broadcasts the model
delta **compressed** (S2C), the sampled clients run E local SGD steps and
return their updates **compressed** (C2S), and the server averages. Both
directions run through the same DeepReduce codec stack the DP path uses
(`wrappers.TensorCodec`). Error feedback: the S2C broadcast compresses
the delta `params - w_ref` against the *receiver's* reconstructed state, a
closed loop that re-sends compression error by construction (an explicit
residual on top would deliver it twice and oscillate); C2S updates are
fresh each round, so they carry a per-client residual accumulator.

Design notes (TPU-native, vs the reference's 57-VM AWS testbed):

- The topology is a *simulation harness* in one program: payloads are
  encoded then decoded in place, and the wire cost is accounted through
  `WireStats` exactly as the paper's Table-2 relative-volume numbers are
  (transmitted bits / dense bits, both directions). On a real multi-host
  deployment the payload pytrees are what crosses DCN.
- Clients share one reference model `w_ref` (what every client can
  reconstruct from the broadcast stream); the server's true model differs
  from it only by not-yet-delivered delta mass. This keeps state O(model), not
  O(clients x model) — except the per-client C2S residuals, which are the
  price of client-side error feedback (paper keeps these on each device).
- The round *body* — local train, per-client compression with EF, cohort
  aggregation with churn masking — lives in `fedsim.round` and is shared
  with the population-scale `fedsim.FedSim` driver. This harness keeps
  the proven scalar path: ONE `lax.scan` over the stacked client axis
  (`impl="scan"`), so the compiled program size is independent of the
  number of sampled clients. `impl="vmap"` runs the same body batched
  (tests pin the two equivalent).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.fedsim.codec_tree import TreeCodec
from deepreduce_tpu.fedsim.round import (  # noqa: F401  (FedConfig re-export)
    FedConfig,
    cohort_updates,
    make_client_step,
    tree_add,
    tree_sub,
)
from deepreduce_tpu.metrics import WireStats, combine
from deepreduce_tpu.telemetry import spans
from deepreduce_tpu.wrappers import TensorCodec


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class FedAvgState:
    params: Any  # server's true model
    w_ref: Any  # the model every client can reconstruct from broadcasts
    c2s_residuals: Optional[Any]  # [num_clients, ...] per-client EF
    round: jax.Array

    def tree_flatten(self):
        return ((self.params, self.w_ref, self.c2s_residuals, self.round), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


class FedAvg:
    """Compressed-FedAvg harness.

    loss_fn(params, batch) -> scalar loss; client_optimizer is applied for
    `local_steps` on each sampled client's batches.
    """

    def __init__(
        self,
        loss_fn: Callable,
        cfg_c2s: DeepReduceConfig,
        fed: FedConfig,
        client_optimizer: optax.GradientTransformation,
        *,
        cfg_s2c: Optional[DeepReduceConfig] = None,
    ):
        self.loss_fn = loss_fn
        self.cfg_c2s = cfg_c2s
        self.cfg_s2c = cfg_s2c if cfg_s2c is not None else cfg_c2s
        self.fed = fed
        self.client_opt = client_optimizer
        # per-direction path-keyed codec banks (one TensorCodec per leaf
        # PATH, not per flat index — see fedsim.codec_tree)
        self._tree_codecs: Dict[str, TreeCodec] = {
            "s2c": TreeCodec("s2c", self.cfg_s2c),
            "c2s": TreeCodec("c2s", self.cfg_c2s),
        }

    # ------------------------------------------------------------------ #

    def _codec(self, direction: str, path: str, shape) -> TensorCodec:
        """One direction's codec for the leaf at treedef `path` (e.g.
        `"['w']"` from `jax.tree_util.keystr`)."""
        return self._tree_codecs[direction].codec(path, shape)

    def _compress_tree(
        self, direction: str, tree: Any, residual: Optional[Any], step, key
    ) -> Tuple[Any, Optional[Any], WireStats]:
        """Encode+decode each leaf through its codec: returns (what the
        receiver reconstructs, updated residual, wire bits)."""
        return self._tree_codecs[direction].compress_tree(tree, residual, step, key)

    # ------------------------------------------------------------------ #

    def init(self, params: Any) -> FedAvgState:
        use_res = self.cfg_c2s.memory == "residual"
        c2s = (
            jax.tree_util.tree_map(
                lambda p: jnp.zeros((self.fed.num_clients,) + p.shape, p.dtype), params
            )
            if use_res
            else None
        )
        return FedAvgState(
            params=params,
            w_ref=jax.tree_util.tree_map(jnp.array, params),
            c2s_residuals=c2s,
            round=jnp.zeros((), jnp.int32),
        )

    def sample_clients(self, state: FedAvgState, key: jax.Array) -> jax.Array:
        """C client ids drawn without replacement (Algorithm 2's random
        subset per round)."""
        return jax.random.choice(
            key,
            self.fed.num_clients,
            (self.fed.clients_per_round,),
            replace=False,
        )

    def _local_train(self, params: Any, batches: Any, key: jax.Array) -> Any:
        opt_state = self.client_opt.init(params)

        def one_step(carry, batch):
            p, o = carry
            grads = jax.grad(self.loss_fn)(p, batch)
            updates, o = self.client_opt.update(grads, o, p)
            return (optax.apply_updates(p, updates), o), None

        (p_end, _), _ = jax.lax.scan(one_step, (params, opt_state), batches)
        return p_end

    def run_round(
        self,
        state: FedAvgState,
        ids: jax.Array,
        client_batches: Any,
        key: jax.Array,
        *,
        participation: Optional[jax.Array] = None,
        impl: str = "scan",
    ) -> Tuple[FedAvgState, Dict[str, Any]]:
        """One round. `ids` from `sample_clients`; `client_batches` leaves
        are [clients_per_round, local_steps, ...] for exactly those ids.

        `participation` (bool[C] over the SAMPLED clients, or None) models
        a sampled client failing to return its C2S update: a False
        client's decoded update and wire bits are zeroed, the server mean
        renormalizes by the live count, and the client's C2S residual is
        left untouched (it never compressed, so there is no new error to
        feed back — its pending mass waits for the next time it is
        sampled). The S2C broadcast stays global: `w_ref` models what
        every client *can* reconstruct from the broadcast stream. With
        participation=None the traced round is unchanged.

        `impl` selects the cohort execution: "scan" (the reference scalar
        path, compiled size independent of C) or "vmap" (all clients in
        one batched block — what `fedsim.FedSim` scales out)."""
        C = self.fed.clients_per_round
        key_s2c, key_c2s = jax.random.split(key)

        # --- S2C: broadcast the compressed model delta -------------------
        # delta is taken against the receiver-side state w_ref, so the
        # loop is self-correcting: undelivered mass reappears in the next
        # round's delta (no explicit residual — see module docstring)
        delta = tree_sub(state.params, state.w_ref)
        with spans.span("fedavg/s2c"):
            dec_delta, _, wire_s2c = self._compress_tree(
                "s2c", delta, None, state.round, key_s2c
            )
        w_ref = tree_add(state.w_ref, dec_delta)

        # --- local training + C2S on each sampled client -----------------
        # Residuals for the sampled ids are gathered up front and scattered
        # back after — ids are drawn without replacement, so the batched
        # scatter is collision-free.
        c2s_res = state.c2s_residuals
        use_res = c2s_res is not None
        res_stack = (
            jax.tree_util.tree_map(lambda r: r[ids], c2s_res) if use_res else None
        )
        client_step = make_client_step(
            self._tree_codecs["c2s"], self._local_train, w_ref, state.round, key_c2s
        )
        positions = jnp.arange(C, dtype=jnp.uint32)
        with spans.span("fedavg/clients"):
            upd_sum, new_res_stack, wire4, live = cohort_updates(
                client_step,
                client_batches,
                res_stack,
                positions,
                update_template=state.params,
                participation=participation,
                impl=impl,
            )
        if use_res:
            c2s_res = jax.tree_util.tree_map(
                lambda buf, nr: buf.at[ids].set(nr), c2s_res, new_res_stack
            )
        wire_c2s = WireStats(
            index_bits=wire4[0],
            value_bits=wire4[1],
            dense_bits=wire4[2],
            saturated=wire4[3],
        )

        if participation is not None:
            live_count = jnp.maximum(jnp.sum(live), 1.0)
            mean_upd = jax.tree_util.tree_map(lambda s: s / live_count, upd_sum)
        else:
            mean_upd = jax.tree_util.tree_map(lambda s: s / C, upd_sum)
        new_params = jax.tree_util.tree_map(
            lambda w, u: w + self.fed.server_lr * u, state.params, mean_upd
        )
        wire = combine({"s2c": wire_s2c, "c2s": wire_c2s})
        new_state = FedAvgState(
            params=new_params,
            w_ref=w_ref,
            c2s_residuals=c2s_res,
            round=state.round + 1,
        )
        # dense bits counted once per direction-crossing: S2C once (broadcast)
        # + C2S per sampled client — matches the paper's Table-2 accounting
        # (relative data volume over everything transmitted)
        return new_state, {"wire": wire, "rel_volume": wire.rel_volume()}
