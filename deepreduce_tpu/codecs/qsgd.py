"""QSGD bucketed stochastic quantizer (order-preserving, lossy).

Reference (/root/reference/pytorch/deepreduce.py:852-907): values split into
512-element buckets; per bucket, levels = stochastic-round(q/||v||·|v|)·sign
as int8, with the bucket's float32 L2 norm byte-packed into 4 extra int8
slots appended to the bucket (:876-880). Defaults quantum_num=127,
bucket_size=512 (:857-858; paper Table 6: 7-bit, bucket 512).

TPU version: identical wire layout ``[bucket_size levels | 4 norm bytes] x B``
built with a single reshape — the norm bytes are the f32 bit-pattern via
`bitcast_convert_type` instead of a host `struct.pack` round-trip. The k
values are zero-padded to a whole number of buckets; padding quantizes to
level 0. Stochastic rounding draws from an explicit `jax.random` key.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu.sparse import SparseGrad


@dataclasses.dataclass(frozen=True)
class QSGDMeta:
    k: int
    quantum_num: int = 127
    bucket_size: int = 512
    use_pallas: bool = False  # hardware-PRNG stochastic rounding (TPU only)

    @property
    def num_buckets(self) -> int:
        return (self.k + self.bucket_size - 1) // self.bucket_size

    @property
    def level_bits(self) -> int:
        """Meaningful bits per transmitted level: sign + magnitude at the
        exact width of `quantum_num` (8 for the default q=127; 7 for the
        paper's Table-6 NCF config, whose caption reads "7-bits
        quantization" — q=63). The int8 container is an implementation
        detail; wire accounting reports meaningful bits, as everywhere else
        in this repo."""
        q = self.quantum_num
        return 1 + max(1, (q).bit_length())

    @property
    def payload_len(self) -> int:
        return self.num_buckets * (self.bucket_size + 4)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class QSGDPayload:
    data: jax.Array  # int8[B*(bucket+4)] — levels with in-band norm bytes
    indices: jax.Array  # i32[k] — passed through untouched (order-preserving)
    nnz: jax.Array


def bucket_scale(flat: jax.Array, quantum_num: int, bucket_size: int) -> Tuple[jax.Array, jax.Array]:
    """Per-bucket quantization geometry shared by this codec and the
    quantized-allreduce path (qar.py): (scale[n], norms[n/bucket]) with the
    zero-norm guard. `flat` length must be a multiple of bucket_size."""
    buckets = flat.reshape(-1, bucket_size)
    norms = jnp.linalg.norm(buckets, axis=1)
    safe = jnp.where(norms > 0, norms, 1.0)
    scale = jnp.broadcast_to((quantum_num / safe)[:, None], buckets.shape).reshape(-1)
    return scale, norms


def encode(sp: SparseGrad, meta: QSGDMeta, key: jax.Array) -> QSGDPayload:
    from deepreduce_tpu.ops import quantize_levels

    b, bs, q = meta.num_buckets, meta.bucket_size, meta.quantum_num
    padded = jnp.zeros((b * bs,), jnp.float32).at[: meta.k].set(sp.values)
    scale, norms = bucket_scale(padded, q, bs)
    levels_i8 = quantize_levels(padded, scale, key, use_pallas=meta.use_pallas).reshape(b, bs)
    norm_bytes = jax.lax.bitcast_convert_type(norms, jnp.uint8).astype(jnp.int8)  # [B, 4]
    data = jnp.concatenate([levels_i8, norm_bytes], axis=1).reshape(-1)
    return QSGDPayload(data=data, indices=sp.indices, nnz=sp.nnz)


def decode(payload: QSGDPayload, meta: QSGDMeta, shape: Tuple[int, ...]) -> SparseGrad:
    b, bs, q = meta.num_buckets, meta.bucket_size, meta.quantum_num
    rows = payload.data.reshape(b, bs + 4)
    levels = rows[:, :bs].astype(jnp.float32)
    norms = jax.lax.bitcast_convert_type(rows[:, bs:].astype(jnp.uint8), jnp.float32)  # [B]
    vals = (norms[:, None] / q * levels).reshape(-1)[: meta.k]
    return SparseGrad(values=vals, indices=payload.indices, nnz=payload.nnz, shape=shape)


def wire_bits(payload: QSGDPayload, meta: QSGDMeta) -> jax.Array:
    """`level_bits` per level + 32 bits of norm per live bucket (reference
    layout pytorch/deepreduce.py:876-880; 8 bits at the default q=127)."""
    nnz = payload.nnz.astype(jnp.float32)
    full_buckets = (nnz + meta.bucket_size - 1) // meta.bucket_size
    return nnz * meta.level_bits + full_buckets * 32
