"""Uniform codec interface + registry.

The reference keys codecs by name in a module dict
(/root/reference/pytorch/deepreduce.py:913-922). Same here, but each entry
is a small adapter class binding the static geometry (`meta`) at
construction — shapes are frozen per (k, d) pair, which is what makes every
codec jit-stable. Interface:

    codec = get_codec('bloom', kind='index')(k=..., d=..., params={...})
    payload = codec.encode(sp, dense=dense, step=step, key=key)
    sp2     = codec.decode(payload, shape, step=step)
    codec.index_wire_bits(payload), codec.value_wire_bits(payload)

`index_wire_bits` / `value_wire_bits` mirror the reference's split
idx/val relative-volume accounting (pytorch/deepreduce.py:93-95,148-150).
"""

from __future__ import annotations

import dataclasses as _dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu.codecs import (
    bloom,
    bloom_native,
    countsketch,
    doubleexp,
    gzip_codec,
    huffman,
    integer,
    polyfit,
    polyfit_host,
    polyseg,
    qsgd,
    rle,
)
from deepreduce_tpu.sparse import SparseGrad


class Codec:
    """Base adapter. Subclasses set kind/order_preserving/fixed_size and
    implement encode/decode/wire-bit accessors."""

    kind: str = ""
    order_preserving: bool = False
    fixed_size: bool = True  # all payloads are static-shape; False only marks
    # codecs whose *meaningful* size varies per worker
    # (the reference's tensors_size_are_same contract)

    def __init__(self, k: int, d: int, params: Optional[Dict[str, Any]] = None):
        self.k = k
        self.d = d
        self.params = dict(params or {})

    def encode(self, sp: SparseGrad, dense=None, *, step=0, key=None):
        raise NotImplementedError

    def decode(self, payload, shape: Tuple[int, ...], *, step=0) -> SparseGrad:
        raise NotImplementedError

    def index_wire_bits(self, payload) -> jax.Array:
        raise NotImplementedError

    def value_wire_bits(self, payload) -> jax.Array:
        raise NotImplementedError

    # -- 'both'-mode composition hooks (value codecs only) ---------------- #
    # In 'both' mode the value codec runs over the index codec's selection
    # with arange indices; its within-selection index field (the `mapping`,
    # pytorch/deepreduce.py:263) is stripped here so the wrapper can bit-pack
    # it at ceil(log2 k) bits, and restored before decode.

    def both_mapping_max(self) -> int:
        """Static max value of the stripped mapping; 0 = no mapping."""
        return self.k - 1

    def strip_for_both(self, payload):
        """-> (stripped_payload, mapping_uint32 | None, mapping_max)."""
        import dataclasses as _dc

        mapping = payload.indices.astype(jnp.uint32)
        stripped = _dc.replace(payload, indices=jnp.zeros((0,), jnp.int32))
        return stripped, mapping, self.both_mapping_max()

    def restore_for_both(self, stripped, mapping):
        import dataclasses as _dc

        n = self.k
        if mapping is None:
            idx = jnp.arange(n, dtype=jnp.int32)
        else:
            idx = mapping.astype(jnp.int32)
        return _dc.replace(stripped, indices=idx)


def _raw_value_bits(n) -> jax.Array:
    return jnp.asarray(n, jnp.float32) * 32


class BloomCodec(Codec):
    kind = "index"
    order_preserving = False
    fixed_size = True  # static budget; p0's live size rides the nsel word

    def __init__(self, k, d, params=None):
        super().__init__(k, d, params)
        self.threshold_insert = bool(self.params.get("bloom_threshold_insert", False))
        try:
            self.meta = bloom.BloomMeta.create(
                k,
                d,
                fpr=self.params.get("fpr"),
                policy=self.params.get("policy", "leftmost"),
                blocked=self.params.get("bloom_blocked", False),
                threshold_insert=self.threshold_insert,
            )
        except ValueError as e:
            # threshold_insert's layout requirement is the only ValueError
            # create() raises when the flag is set AND the policy is valid;
            # don't misattribute a policy/layout typo to the flag
            prefix = (
                "bloom_threshold_insert: "
                if self.threshold_insert and "policy" not in str(e)
                else ""
            )
            raise ValueError(f"{prefix}{e}") from e
        self.seed = int(self.params.get("seed", 0))

    def encode(self, sp, dense=None, *, step=0, key=None):
        return bloom.encode(
            sp,
            dense,
            self.meta,
            step=step,
            seed=self.seed,
            threshold_insert=self.threshold_insert,
        )

    def encode_direct(self, dense, *, sample_size, undershoot):
        """Sparsifier-free encode (bloom.encode_dense_direct): the wrapper
        routes here when the config statically selects the sampled-threshold
        sparsifier AND the threshold insert — the selection lives entirely
        in the filter, so no top-k is ever materialized."""
        return bloom.encode_dense_direct(
            dense, self.meta, sample_size=sample_size, undershoot=undershoot
        )

    def decode(self, payload, shape, *, step=0):
        return bloom.decode(payload, self.meta, shape, step=step, seed=self.seed)

    def decode_dense(self, payload, shape, *, step=0, values=None):
        """TPU fast path: rank-gather straight to dense (bloom.decode_dense),
        skipping the selection-list materialization entirely."""
        return bloom.decode_dense(
            payload, self.meta, shape, step=step, seed=self.seed, values=values
        )

    def index_wire_bits(self, payload):
        return jnp.asarray(64.0 + self.meta.m_bits, jnp.float32)

    def value_wire_bits(self, payload):
        return payload.nsel.astype(jnp.float32) * 32

    def fp_stats(self, payload):
        """Measured false-positive inputs for telemetry: (filter positives
        beyond the live selected count, not-selected universe size). The
        filter has no false negatives, so positives − nsel IS the FP count
        (threshold-insert overflow also lands here — either way it is
        reconstruction the receiver sees that the sender never ranked)."""
        positives = (
            bloom.query_universe(payload.words, self.meta)
            .sum()
            .astype(jnp.float32)
        )
        nsel = payload.nsel.astype(jnp.float32)
        return (
            jnp.maximum(positives - nsel, 0.0),
            jnp.maximum(jnp.asarray(float(self.d), jnp.float32) - nsel, 0.0),
        )


class RLECodec(Codec):
    kind = "index"
    order_preserving = False
    fixed_size = False

    def __init__(self, k, d, params=None):
        super().__init__(k, d, params)
        self.meta = rle.RLEMeta(k=k, d=d)

    def encode(self, sp, dense=None, *, step=0, key=None):
        return rle.encode(sp, self.meta)

    def decode(self, payload, shape, *, step=0):
        return rle.decode(payload, self.meta, shape)

    def index_wire_bits(self, payload):
        return rle.wire_bits(payload, self.meta)

    def value_wire_bits(self, payload):
        return _raw_value_bits(payload.nnz)


class IntegerCodec(Codec):
    kind = "index"
    order_preserving = False  # sorts ascending, like the reference RLE
    fixed_size = False

    def __init__(self, k, d, params=None):
        super().__init__(k, d, params)
        self.meta = integer.IntegerMeta(k=k, d=d)

    def encode(self, sp, dense=None, *, step=0, key=None):
        return integer.encode(sp, self.meta)

    def decode(self, payload, shape, *, step=0):
        return integer.decode(payload, self.meta, shape)

    def decode_dense(self, payload, shape, *, step=0, values=None):
        """TPU fast path: cumsum + one sorted unique scatter straight to
        dense, skipping the SparseGrad materialization."""
        return integer.decode_dense(payload, self.meta, shape, values=values)

    def index_wire_bits(self, payload):
        return integer.wire_bits(payload, self.meta)

    def value_wire_bits(self, payload):
        return _raw_value_bits(payload.nnz)


class HuffmanCodec(Codec):
    kind = "index"
    order_preserving = True
    fixed_size = False

    def __init__(self, k, d, params=None):
        super().__init__(k, d, params)
        self.meta = huffman.HuffmanMeta(k=k, d=d)

    def encode(self, sp, dense=None, *, step=0, key=None):
        return huffman.encode(sp, self.meta)

    def decode(self, payload, shape, *, step=0):
        return huffman.decode(payload, self.meta, shape)

    def index_wire_bits(self, payload):
        return huffman.wire_bits(payload, self.meta)

    def value_wire_bits(self, payload):
        return _raw_value_bits(payload.nnz)


class PolyFitCodec(Codec):
    kind = "value"
    order_preserving = False
    fixed_size = True  # the reference's one tensors_size_are_same=True value
    # codec on the PyTorch path (pytorch/deepreduce.py:57-59)

    def __init__(self, k, d, params=None):
        super().__init__(k, d, params)
        self.meta = polyfit.PolyFitMeta(
            k=k,
            degree=int(self.params.get("poly_degree", 5)),
            sort=bool(self.params.get("sort", False)),
        )

    def encode(self, sp, dense=None, *, step=0, key=None):
        return polyfit.encode(sp, self.meta)

    def decode(self, payload, shape, *, step=0):
        return polyfit.decode(payload, self.meta, shape)

    def index_wire_bits(self, payload):
        return _raw_value_bits(self.k)  # indices travel raw in value-only mode

    def value_wire_bits(self, payload):
        return polyfit.wire_bits(payload, self.meta)


class DoubleExpCodec(Codec):
    kind = "value"
    order_preserving = False
    fixed_size = True

    def __init__(self, k, d, params=None):
        super().__init__(k, d, params)
        self.meta = doubleexp.DoubleExpMeta(k=k)

    def encode(self, sp, dense=None, *, step=0, key=None):
        return doubleexp.encode(sp, self.meta)

    def decode(self, payload, shape, *, step=0):
        return doubleexp.decode(payload, self.meta, shape)

    def index_wire_bits(self, payload):
        return _raw_value_bits(self.k)

    def value_wire_bits(self, payload):
        return doubleexp.wire_bits(payload, self.meta)

    def both_mapping_max(self) -> int:
        return 2 * self.k

    def strip_for_both(self, payload):
        import dataclasses as _dc

        # signed indices carry sign info: shift to [0, 2k] so they pack as uints
        mapping = (payload.signed_indices + self.k).astype(jnp.uint32)
        stripped = _dc.replace(payload, signed_indices=jnp.zeros((0,), jnp.int32))
        return stripped, mapping, self.both_mapping_max()

    def restore_for_both(self, stripped, mapping):
        import dataclasses as _dc

        if mapping is None:
            signed = jnp.arange(1, self.k + 1, dtype=jnp.int32)
        else:
            signed = mapping.astype(jnp.int32) - self.k
        return _dc.replace(stripped, signed_indices=signed)


class QSGDCodec(Codec):
    kind = "value"
    order_preserving = True
    fixed_size = True

    def __init__(self, k, d, params=None):
        super().__init__(k, d, params)
        self.meta = qsgd.QSGDMeta(
            k=k,
            quantum_num=int(self.params.get("quantum_num", 127)),
            bucket_size=int(self.params.get("bucket_size", 512)),
            use_pallas=bool(self.params.get("use_pallas", False)),
        )

    def encode(self, sp, dense=None, *, step=0, key=None):
        if key is None:
            key = jax.random.fold_in(
                jax.random.PRNGKey(int(self.params.get("seed", 0))), jnp.asarray(step, jnp.uint32)
            )
        return qsgd.encode(sp, self.meta, key)

    def decode(self, payload, shape, *, step=0):
        return qsgd.decode(payload, self.meta, shape)

    def index_wire_bits(self, payload):
        return _raw_value_bits(self.k)

    def value_wire_bits(self, payload):
        return qsgd.wire_bits(payload, self.meta)

    def both_mapping_max(self) -> int:
        return 0

    def strip_for_both(self, payload):
        import dataclasses as _dc

        # order-preserving: the mapping is the identity — elide it
        return _dc.replace(payload, indices=jnp.zeros((0,), jnp.int32)), None, 0


class CountSketchCodec(Codec):
    """Summable value codec (codecs/countsketch.py): the payload's sketch
    planes are *linear*, so W workers' payloads can be summed element-wise
    (one psum) and decoded once — the only value codec here whose aggregate
    never needs per-worker decode. Lossy: decoded values carry collision
    noise bounded by ||g||_2 / sqrt(cols) per row (median-of-rows tail);
    the caller's residual error feedback re-injects the unsketch error."""

    kind = "value"
    order_preserving = True
    fixed_size = True

    def __init__(self, k, d, params=None):
        super().__init__(k, d, params)
        rows = int(self.params.get("rs_sketch_rows", 5))
        cols = int(self.params.get("rs_sketch_cols", 0))
        if cols <= 0:
            cols = max(256, -(-2 * k // max(1, rows)))
        self.meta = countsketch.CountSketchMeta(
            k=k, rows=rows, cols=cols, seed=int(self.params.get("seed", 0))
        )

    def encode(self, sp, dense=None, *, step=0, key=None):
        return countsketch.encode(sp, self.meta)

    def decode(self, payload, shape, *, step=0):
        return countsketch.decode(payload, self.meta, shape)

    def index_wire_bits(self, payload):
        return _raw_value_bits(self.k)

    def value_wire_bits(self, payload):
        return countsketch.wire_bits(payload, self.meta)

    def both_mapping_max(self) -> int:
        return 0

    def strip_for_both(self, payload):
        import dataclasses as _dc

        # order-preserving: the mapping is the identity — elide it
        return _dc.replace(payload, indices=jnp.zeros((0,), jnp.int32)), None, 0


class GzipCodec(Codec):
    kind = "value"
    order_preserving = True
    fixed_size = False

    def __init__(self, k, d, params=None):
        super().__init__(k, d, params)
        self.meta = gzip_codec.GzipMeta(k=k)

    def encode(self, sp, dense=None, *, step=0, key=None):
        return gzip_codec.encode(sp, self.meta)

    def decode(self, payload, shape, *, step=0):
        return gzip_codec.decode(payload, self.meta, shape)

    def index_wire_bits(self, payload):
        return _raw_value_bits(self.k)

    def value_wire_bits(self, payload):
        return gzip_codec.wire_bits(payload, self.meta)

    def both_mapping_max(self) -> int:
        return 0

    def strip_for_both(self, payload):
        import dataclasses as _dc

        return _dc.replace(payload, indices=jnp.zeros((0,), jnp.int32)), None, 0


class PolyFitHostCodec(Codec):
    """PolyFitCPU role: searched knots, transmitted breaks, host numpy fit."""

    kind = "value"
    order_preserving = False
    fixed_size = False  # break count varies (reference returns a tuple :673)

    def __init__(self, k, d, params=None):
        super().__init__(k, d, params)
        self.meta = polyfit_host.PolyFitHostMeta(
            k=k, degree=int(self.params.get("poly_degree", 5))
        )

    def encode(self, sp, dense=None, *, step=0, key=None):
        return polyfit_host.encode(sp, self.meta)

    def decode(self, payload, shape, *, step=0):
        return polyfit_host.decode(payload, self.meta, shape)

    def index_wire_bits(self, payload):
        return _raw_value_bits(self.k)

    def value_wire_bits(self, payload):
        return polyfit_host.wire_bits(payload, self.meta)


class PolySegCodec(Codec):
    """TF PolySegCompressor role: whole-layer sort, in-graph knot search,
    sign-embedded indices."""

    kind = "value"
    order_preserving = False
    fixed_size = True

    def __init__(self, k, d, params=None):
        super().__init__(k, d, params)
        self.meta = polyseg.PolySegMeta(
            k=k,
            degree=int(self.params.get("poly_degree", 5)),
            num_segments=int(self.params.get("num_segments", 0)),
        )

    def encode(self, sp, dense=None, *, step=0, key=None):
        return polyseg.encode(sp, self.meta)

    def decode(self, payload, shape, *, step=0):
        return polyseg.decode(payload, self.meta, shape)

    def index_wire_bits(self, payload):
        return _raw_value_bits(self.k)

    def value_wire_bits(self, payload):
        return polyseg.wire_bits(payload, self.meta)

    def both_mapping_max(self) -> int:
        return 2 * self.k

    def strip_for_both(self, payload):
        import dataclasses as _dc

        mapping = (payload.signed_indices + self.k).astype(jnp.uint32)
        stripped = _dc.replace(payload, signed_indices=jnp.zeros((0,), jnp.int32))
        return stripped, mapping, self.both_mapping_max()

    def restore_for_both(self, stripped, mapping):
        import dataclasses as _dc

        if mapping is None:
            signed = jnp.arange(1, self.k + 1, dtype=jnp.int32)
        else:
            signed = mapping.astype(jnp.int32) - self.k
        return _dc.replace(stripped, signed_indices=signed)


class BloomNativeCodec(Codec):
    """BloomCPU role (pytorch/deepreduce.py:696-736): the C++ host library
    (native/deepreduce_native.cc) as a registry codec via pure_callback.
    Index-mode only — its wire format carries the values in-band (the C++
    op's own layout), so composing a value codec on top would transmit the
    values twice. The only route to policy='conflict_sets' (P2), which is
    native-only in the reference too (policies.hpp)."""

    kind = "index"
    order_preserving = False
    fixed_size = False  # live wire length rides the in-band nbytes word

    def __init__(self, k, d, params=None):
        super().__init__(k, d, params)
        self.meta = bloom_native.BloomNativeMeta.create(
            k, d, fpr=self.params.get("fpr"),
            policy=self.params.get("policy", "leftmost"),
        )

    def encode(self, sp, dense=None, *, step=0, key=None):
        return bloom_native.encode(sp, dense, self.meta, step=step)

    def decode(self, payload, shape, *, step=0):
        return bloom_native.decode(payload, self.meta, shape, step=step)

    def index_wire_bits(self, payload):
        # wire minus the embedded values = header + bit-array
        return bloom_native.wire_bits(payload, self.meta) - self.value_wire_bits(payload)

    def value_wire_bits(self, payload):
        return payload.nsel.astype(jnp.float32) * 32

    def strip_for_both(self, payload):
        raise NotImplementedError(
            "bloom_native is index-mode only: its C++ wire format already "
            "carries the values in-band (bloom_filter_compression.cc layout)"
        )


@jax.tree_util.register_dataclass
@_dataclasses.dataclass(frozen=True)
class IntegerNativePayload:
    values: jax.Array  # f32[k] — values in ascending-index order
    wire: jax.Array  # uint32[budget_words] — named-codec wire, zero-padded
    nwords: jax.Array  # i32[] — live wire words
    nnz: jax.Array


class IntegerNativeCodec(Codec):
    """The C++ FastPFor-role family behind name-keyed selection — the
    reference's IntegerCompressorOp with string attr `code` routed through
    CODECFactory::getFromName (integer_compression.cc:20-42,62). Members:
    fbp (frame bit packing), varint (VByte), pfor (PFor128 with patched
    exceptions). Host path via pure_callback with a static wire budget."""

    kind = "index"
    order_preserving = False  # sorts ascending, like IntegerCodec
    fixed_size = False

    def __init__(self, k, d, params=None):
        super().__init__(k, d, params)
        self.code = str(self.params.get("code", "fbp"))
        from deepreduce_tpu import native

        if self.code not in native.INT_CODEC_NAMES:
            raise KeyError(
                f"unknown integer codec {self.code!r}; have {native.INT_CODEC_NAMES}"
            )
        # static budget: the family-wide worst case (b=32 pfor blocks /
        # 5-byte varints) — the shared sizing formula
        self.budget_words = native.int_cap_words(k)

    def encode(self, sp, dense=None, *, step=0, key=None):
        import numpy as np

        from deepreduce_tpu import native
        from deepreduce_tpu.native import xla_ops

        k, budget = self.k, self.budget_words
        code = self.code

        if xla_ops.available():
            # production route: sort in-graph (dead slots keyed past every
            # live index so they fall to the tail), then the name-keyed
            # C++ encoder as ONE custom call inside the jitted program
            live = jnp.arange(k, dtype=jnp.int32) < sp.nnz
            keyed = jnp.where(live, sp.indices, jnp.int32(self.d))
            order = jnp.argsort(keyed, stable=True)
            sorted_idx = jnp.take(keyed, order)
            sorted_vals = jnp.where(live, jnp.take(sp.values, order), 0.0)
            wire, nwords = xla_ops.int_encode(
                sorted_idx.astype(jnp.uint32), sp.nnz, code, budget
            )
            return IntegerNativePayload(
                values=sorted_vals, wire=wire, nwords=nwords, nnz=sp.nnz
            )

        def host(idx_np, val_np, nnz_np):
            enc, _ = native.int_codec_from_name(code)
            n = int(nnz_np)
            order = np.argsort(idx_np[:n], kind="stable")
            words = enc(idx_np[:n][order])
            out_w = np.zeros(budget, np.uint32)
            out_w[: len(words)] = words
            out_v = np.zeros(k, np.float32)
            out_v[:n] = val_np[:n][order]
            return out_w, np.int32(len(words)), out_v

        wire, nwords, values = jax.pure_callback(
            host,
            (
                jax.ShapeDtypeStruct((budget,), jnp.uint32),
                jax.ShapeDtypeStruct((), jnp.int32),
                jax.ShapeDtypeStruct((k,), jnp.float32),
            ),
            sp.indices, sp.values, sp.nnz,
        )
        return IntegerNativePayload(
            values=values, wire=wire, nwords=nwords, nnz=sp.nnz
        )

    def decode(self, payload, shape, *, step=0):
        import numpy as np  # noqa: F401 (host fn below)

        from deepreduce_tpu import native  # noqa: F401
        from deepreduce_tpu.native import xla_ops

        k = self.k
        code = self.code

        if xla_ops.available():
            idx = xla_ops.int_decode(payload.wire, payload.nwords, code, k)
            live = jnp.arange(k, dtype=jnp.int32) < payload.nnz
            from deepreduce_tpu.sparse import SparseGrad

            return SparseGrad(
                values=jnp.where(live, payload.values, 0.0),
                indices=jnp.where(live, idx.astype(jnp.int32), 0),
                nnz=payload.nnz,
                shape=shape,
            )

        def host(wire_np, nwords_np, nnz_np):
            _, dec = native.int_codec_from_name(code)
            idx = dec(wire_np[: int(nwords_np)], int(nnz_np))
            out = np.zeros(k, np.int32)
            out[: len(idx)] = idx.astype(np.int32)
            return out

        idx = jax.pure_callback(
            host,
            jax.ShapeDtypeStruct((k,), jnp.int32),
            payload.wire, payload.nwords, payload.nnz,
        )
        live = jnp.arange(k, dtype=jnp.int32) < payload.nnz
        from deepreduce_tpu.sparse import SparseGrad

        return SparseGrad(
            values=jnp.where(live, payload.values, 0.0),
            indices=jnp.where(live, idx, 0),
            nnz=payload.nnz,
            shape=shape,
        )

    def index_wire_bits(self, payload):
        return payload.nwords.astype(jnp.float32) * 32

    def value_wire_bits(self, payload):
        return _raw_value_bits(payload.nnz)


INDEX_CODECS: Dict[str, type] = {
    "bloom": BloomCodec,
    "bloom_native": BloomNativeCodec,
    "integer_native": IntegerNativeCodec,
    "rle": RLECodec,
    "integer": IntegerCodec,
    "huffman": HuffmanCodec,
}

VALUE_CODECS: Dict[str, type] = {
    "polyfit": PolyFitCodec,
    "polyfit_host": PolyFitHostCodec,
    "polyseg": PolySegCodec,
    "doubleexp": DoubleExpCodec,
    "qsgd": QSGDCodec,
    "gzip": GzipCodec,
    "countsketch": CountSketchCodec,
}


def get_codec(name: str, kind: str) -> type:
    table = INDEX_CODECS if kind == "index" else VALUE_CODECS
    if name not in table:
        raise KeyError(f"unknown {kind} codec {name!r}; have {sorted(table)}")
    return table[name]
