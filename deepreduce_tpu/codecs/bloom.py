"""Bloom-filter index codec, jit-compiled and table-free.

Reference parity (/root/reference/pytorch/deepreduce.py:431-555 and
tensorflow/bloom_filter_compression.cc): indices are inserted into a bloom
filter; only the packed bit-array crosses the wire; both sides re-derive the
index set by querying the whole universe and running a deterministic
selection *policy* over the positives. Because false positives shift which
indices are selected, the encoder is FP-aware: it re-reads values from the
dense tensor at the *selected* positions (pytorch/deepreduce.py:519-523), so
receivers scatter true gradient values to exactly the positions they will
derive.

TPU-first redesign:

- Hashing is computed, not gathered: a murmur3-finalizer integer mix per
  (index, seed_j) replaces the reference's precomputed ``[18M x H]`` hash
  table (pytorch/deepreduce.py:461-477) — no 18M-row tensor in HBM, no
  gather in the hot loop. The C++ native layer implements the identical mix
  so host and device payloads interoperate.
- Filter geometry follows the C++ op's optimal-m form
  (bloom_filter_compression.cc:85-99, SURVEY.md §2.6): ``m_bytes =
  ceil(k·|ln fpr| / ln²2 / 8)`` rounded up to 8-byte alignment,
  ``h = ceil((8·m_bytes/k)·ln 2)``; default FPR ``0.1·k/d``
  (pytorch/deepreduce.py:511).
- Policies ``leftmost`` / ``random`` / ``p0`` (pytorch/deepreduce.py:479-492)
  are mask+cumsum prefix selections — sort-free, static-shape. ``random`` is
  keyed by (seed, step) on *both* sides, fixing the reference's re-seeded
  ``manual_seed(42)`` quirk while keeping its cross-worker determinism
  contract (policies.hpp:160-180 seeds by step). ``conflict_sets`` (P2) is
  native-only, as in the reference (policies.hpp:43-146) — see
  `deepreduce_tpu.native`.
- P0's data-dependent output size (|P| >= k) becomes a static budget from
  the paper's Lemma-6 expectation ``|P| <= k + fpr·(d-k)`` with 5% + 64
  headroom; `nsel` is the in-band length word (the reference prepends the
  true count, pytorch/deepreduce.py:525-527).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu.codecs import packing
from deepreduce_tpu.sparse import SparseGrad

_LN2 = 0.6931471805599453
_GOLDEN = 0x9E3779B9
_QUERY_CHUNK = 1 << 16


def fmix32(x: jax.Array) -> jax.Array:
    """murmur3 32-bit finalizer (same constants as MurmurHash3_fmix32)."""
    x = jnp.asarray(x, jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def hash_seeds(num_hash: int) -> jax.Array:
    """Per-hash-function seeds, derived — not stored (uint32[h])."""
    j = jnp.arange(1, num_hash + 1, dtype=jnp.uint32)
    return fmix32(j * jnp.uint32(_GOLDEN))


def hash_positions(indices: jax.Array, seeds: jax.Array, m_bits: int) -> jax.Array:
    """Bit positions [..., h] for each index."""
    idx = jnp.asarray(indices, jnp.uint32)
    return (fmix32(idx[..., None] ^ seeds) % jnp.uint32(m_bits)).astype(jnp.int32)


_SEED_BLOCK = 0xA2C2A9F7
_SEED_LANE1 = 0x6A09E667
_SEED_LANE2 = 0xBB67AE85


def blocked_block_and_mask(indices: jax.Array, meta: "BloomMeta") -> Tuple[jax.Array, jax.Array]:
    """(word index [..], 32-bit in-word mask [..]) for the blocked filter.
    h bit lanes come from 5-bit fields of one or two mixed words."""
    idx = jnp.asarray(indices, jnp.uint32)
    n_words = meta.m_bits // 32
    block = (fmix32(idx ^ jnp.uint32(_SEED_BLOCK)) % jnp.uint32(n_words)).astype(jnp.int32)
    r1 = fmix32(idx ^ jnp.uint32(_SEED_LANE1))
    r2 = fmix32(idx ^ jnp.uint32(_SEED_LANE2))
    mask = jnp.zeros_like(idx)
    for j in range(meta.num_hash):
        r = r1 if j < 6 else r2
        lane = (r >> jnp.uint32(5 * (j % 6))) & jnp.uint32(31)
        mask = mask | (jnp.uint32(1) << lane)
    return block, mask


def bloom_config(k: int, d: int, fpr: Optional[float]) -> Tuple[int, int, float]:
    """(m_bits, num_hash, fpr) — static geometry from static (k, d)."""
    if fpr is None:
        fpr = 0.1 * k / d  # pytorch/deepreduce.py:511
    m_bytes = int(math.ceil(k * abs(math.log(fpr)) / (_LN2 * _LN2) / 8.0))
    m_bytes = max(8, (m_bytes + 7) // 8 * 8)  # 8-byte aligned, as the C++ op intends
    num_hash = max(1, int(math.ceil((m_bytes * 8.0 / k) * _LN2)))
    return m_bytes * 8, num_hash, fpr


# Register-blocked variant: all h bits of an index live in ONE 32-bit word,
# so the universe query needs a single gather per index instead of h — the
# difference between ~2.9s and ~0.25s for a 25.6M universe on v5e (gathers
# are latency-bound on TPU; arithmetic is nearly free). The space-for-speed
# tax is computed from the Poisson block-load mixture, not a fixed factor:
# a word holding j keys has ~32(1-(1-1/32)^{jh}) set bits and false-positive
# probability (set/32)^h; total FPR = E_j~Poisson(k/W)[fpr_j].


def _blocked_fpr(k: int, n_words: int, h: int) -> float:
    lam = k / n_words
    total = 0.0
    pj = math.exp(-lam)
    for j in range(0, 64):
        set_bits = 32.0 * (1.0 - (1.0 - 1.0 / 32.0) ** (j * h))
        total += pj * (set_bits / 32.0) ** h
        pj *= lam / (j + 1)
        if pj < 1e-12 and j > lam:
            break
    return total


def blocked_bloom_config(k: int, d: int, fpr: Optional[float]) -> Tuple[int, int, float]:
    if fpr is None:
        fpr = 0.1 * k / d
    classic_bits, _, _ = bloom_config(k, d, fpr)
    best = None
    n_words = max(1, classic_bits // 32)
    # grow the table until some h meets the target FPR
    for _ in range(16):
        for h in range(1, 13):
            if _blocked_fpr(k, n_words, h) <= fpr:
                best = (n_words, h)
                break
        if best:
            break
        n_words = int(n_words * 1.3) + 1
    if best is None:
        best = (n_words, 12)
    return best[0] * 32, best[1], fpr


def p0_budget(k: int, d: int, fpr: float) -> int:
    """Static slot budget for policy p0 (all positives): Lemma-6 expectation
    plus headroom (SURVEY.md §7 hard part 1)."""
    return min(d, int(math.ceil(k + 1.05 * fpr * (d - k))) + 64)


def policy_budget(policy: str, k: int, d: int, fpr: float) -> int:
    return p0_budget(k, d, fpr) if policy == "p0" else k


@dataclasses.dataclass(frozen=True)
class BloomMeta:
    """Static codec geometry, shared by encode and decode."""

    d: int
    k: int
    m_bits: int
    num_hash: int
    fpr: float
    policy: str
    budget: int
    blocked: bool = False

    @staticmethod
    def create(
        k: int,
        d: int,
        fpr: Optional[float] = None,
        policy: str = "leftmost",
        blocked: bool = False,
    ) -> "BloomMeta":
        if policy == "conflict_sets":
            raise NotImplementedError(
                "conflict_sets (P2) is native-only, as in the reference "
                "(policies.hpp:43-146); use deepreduce_tpu.native.bloom"
            )
        cfg_fn = blocked_bloom_config if blocked else bloom_config
        m_bits, num_hash, fpr_eff = cfg_fn(k, d, fpr)
        return BloomMeta(
            d=d,
            k=k,
            m_bits=m_bits,
            num_hash=num_hash,
            fpr=fpr_eff,
            policy=policy,
            budget=policy_budget(policy, k, d, fpr_eff),
            blocked=blocked,
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BloomPayload:
    values: jax.Array  # f32[budget] — values at the selected positions
    words: jax.Array  # uint32[m_bits/32] — packed filter bit-array
    nsel: jax.Array  # i32[] — live selected count (p0 count prefix role)


def insert(indices: jax.Array, nnz: jax.Array, meta: BloomMeta) -> jax.Array:
    """Build the packed filter from (possibly padded) indices.

    Dead slots are re-pointed at the first index — inserting a duplicate is a
    no-op under bloom set semantics, which keeps the scatter static-shape.
    """
    live = jnp.arange(indices.shape[0], dtype=jnp.int32) < nnz
    idx = jnp.where(live, indices, indices[0])
    if meta.blocked:
        block, mask = blocked_block_and_mask(idx, meta)
        lane = jnp.arange(32, dtype=jnp.uint32)
        bits_mat = ((mask[:, None] >> lane[None, :]) & jnp.uint32(1)).astype(jnp.uint8)
        pos = (block[:, None] * 32 + lane[None, :].astype(jnp.int32)).reshape(-1)
        bits = jnp.zeros((meta.m_bits,), jnp.uint8).at[pos].max(bits_mat.reshape(-1))
        return packing.pack_bitmap(bits)
    seeds = hash_seeds(meta.num_hash)
    pos = hash_positions(idx, seeds, meta.m_bits).reshape(-1)
    bits = jnp.zeros((meta.m_bits,), jnp.uint8).at[pos].max(jnp.uint8(1))
    return packing.pack_bitmap(bits)


def query_universe(words: jax.Array, meta: BloomMeta) -> jax.Array:
    """bool[d]: membership test for every index in the universe — the hot op
    (pytorch/deepreduce.py:466-477), chunked so the [chunk, h] position block
    stays small regardless of d."""
    d = meta.d
    if meta.blocked:
        # ONE gather per index: word + arithmetic in-word mask test
        idx = jnp.arange(d, dtype=jnp.int32)
        block, mask = blocked_block_and_mask(idx, meta)
        w = words[block]
        return (w & mask) == mask

    seeds = hash_seeds(meta.num_hash)
    chunk = min(_QUERY_CHUNK, max(1, d))
    n_chunks = (d + chunk - 1) // chunk

    def one_chunk(c: jax.Array) -> jax.Array:
        idx = c * chunk + jnp.arange(chunk, dtype=jnp.int32)
        pos = hash_positions(idx, seeds, meta.m_bits)
        w = words[pos // 32]
        bit = (w >> (pos % 32).astype(jnp.uint32)) & jnp.uint32(1)
        hit = jnp.min(bit, axis=-1) == 1
        return jnp.logical_and(hit, idx < d)

    if n_chunks == 1:
        return one_chunk(jnp.int32(0))[:d]
    mask = jax.lax.map(one_chunk, jnp.arange(n_chunks, dtype=jnp.int32))
    return mask.reshape(-1)[:d]


def _prefix_select(mask: jax.Array, budget: int) -> Tuple[jax.Array, jax.Array]:
    """First `budget` True positions of `mask`, ascending — exact stream
    compaction by rank-scatter: positive j's output slot IS its rank
    ``cumsum(mask)[j]-1``, so one masked unique-index scatter of the
    position values builds the list with no d-scale sort. Bit-consistent
    with `encode`'s rank-addressed value layout and with `decode_dense`'s
    rank-gather. Dead slots carry index 0 (the SparseGrad padding
    contract). Returns (indices[budget], count)."""
    d = mask.shape[0]
    cs = jnp.cumsum(mask.astype(jnp.int32))
    ranks = cs - 1
    count = jnp.minimum(cs[-1], budget)
    live = jnp.logical_and(mask, ranks < budget)
    tgt = jnp.where(live, ranks, budget + jnp.arange(d, dtype=jnp.int32))
    idx = (
        jnp.zeros((budget,), jnp.int32)
        .at[tgt]
        .set(jnp.arange(d, dtype=jnp.int32), mode="drop", unique_indices=True)
    )
    return idx, count


def select(
    mask: jax.Array, meta: BloomMeta, *, step: jax.Array, seed: int = 0
) -> Tuple[jax.Array, jax.Array]:
    """Run the selection policy over the positive mask. Deterministic given
    (mask, step, seed) — the encode/decode agreement contract
    (bloom_filter_compression.cc:217-218)."""
    if meta.policy in ("leftmost", "p0"):
        return _prefix_select(mask, meta.budget)
    if meta.policy == "random":
        key = jax.random.fold_in(jax.random.PRNGKey(seed), jnp.asarray(step, jnp.uint32))
        pri = jax.random.uniform(key, mask.shape)
        pri = jnp.where(mask, pri, -1.0)
        _, chosen = jax.lax.top_k(pri, meta.budget)
        count = jnp.minimum(jnp.sum(mask.astype(jnp.int32)), meta.budget)
        # fewer positives than budget: slots whose priority was -1 are dead —
        # push them past the live ones, emit canonical ascending order
        valid = mask[chosen]
        order = jnp.argsort(jnp.where(valid, chosen, meta.d))
        chosen = chosen[order]
        live = jnp.arange(meta.budget, dtype=jnp.int32) < count
        return jnp.where(live, chosen, 0).astype(jnp.int32), count
    raise ValueError(f"unknown policy {meta.policy!r}")


def encode(
    sp: SparseGrad,
    dense: Optional[jax.Array],
    meta: BloomMeta,
    *,
    step: jax.Array = 0,
    seed: int = 0,
) -> BloomPayload:
    """Insert + FP-aware value re-read (pytorch/deepreduce.py:505-533).

    For the prefix policies the re-read is rank-addressed: positive j's
    value lands in slot ``rank(j) = cumsum(mask)[j]-1`` (exactly the slot
    `decode_dense` will read it from) via one masked unique-index scatter —
    no d-scale sort. `select` remains for the `random` policy."""
    words = insert(sp.indices, sp.nnz, meta)
    if dense is not None and meta.policy in ("leftmost", "p0"):
        flat = dense.reshape(-1)
        d = flat.shape[0]
        mask = query_universe(words, meta)
        cs = jnp.cumsum(mask.astype(jnp.int32))
        ranks = cs - 1
        nsel = jnp.minimum(cs[-1], meta.budget)
        live = jnp.logical_and(mask, ranks < meta.budget)
        # dead slots get unique out-of-range targets so mode='drop' discards
        # them without breaking the unique-indices promise
        tgt = jnp.where(
            live, ranks, meta.budget + jnp.arange(d, dtype=jnp.int32)
        )
        values = (
            jnp.zeros((meta.budget,), flat.dtype)
            .at[tgt]
            .set(jnp.where(live, flat, 0.0), mode="drop", unique_indices=True)
        )
    elif dense is not None:
        mask = query_universe(words, meta)
        selected, nsel = select(mask, meta, step=step, seed=seed)
        flat = dense.reshape(-1)
        live = jnp.arange(meta.budget, dtype=jnp.int32) < nsel
        values = jnp.where(live, flat[selected], 0.0)
    else:
        # no dense tensor: transmit sparsifier values as-is (the reference's
        # non-fp-aware branch); only sensible when decode-side selection
        # happens to align (fpr ~ 0)
        values = jnp.zeros((meta.budget,), sp.values.dtype).at[: sp.k].set(sp.values)
        nsel = jnp.minimum(sp.nnz, meta.budget)
    return BloomPayload(values=values, words=words, nsel=nsel.astype(jnp.int32))


def decode(
    payload: BloomPayload,
    meta: BloomMeta,
    shape: Tuple[int, ...],
    *,
    step: jax.Array = 0,
    seed: int = 0,
) -> SparseGrad:
    """Query the universe, re-run the policy, pair with transmitted values
    (pytorch/deepreduce.py:535-555). The selection list is exact-rank, so
    it is bit-consistent with `encode`'s rank-addressed value layout; the
    wrapper's production path (`decode_dense`) skips the list entirely."""
    mask = query_universe(payload.words, meta)
    selected, nsel = select(mask, meta, step=step, seed=seed)
    nsel = jnp.minimum(nsel, payload.nsel)
    return SparseGrad(
        values=payload.values,
        indices=selected,
        nnz=nsel.astype(jnp.int32),
        shape=shape,
    )


def decode_dense(
    payload: BloomPayload,
    meta: BloomMeta,
    shape: Tuple[int, ...],
    *,
    step: jax.Array = 0,
    seed: int = 0,
    values: Optional[jax.Array] = None,
) -> jax.Array:
    """Rank-gather decode straight to the dense tensor — the TPU fast path.

    For the prefix policies (leftmost/p0) the selection is "the first
    `budget` positives ascending", so a universe index's slot in the value
    stream IS its rank among positives: ``rank(j) = cumsum(mask)[j] - 1``.
    Materializing the selection list (a d-scale sort or scatter — the round-1
    bottleneck) is unnecessary:

        dense[j] = live(j) ? values[rank(j)] : 0
        live(j)  = mask[j] and rank(j) < nsel

    Three fused memory-bound d-scale passes (hash+query, cumsum, gather from
    the budget-sized value table) — no sort, no scatter, nothing for XLA to
    serialize. `values` overrides the payload's value stream ('both' mode
    passes the value-codec output, already in rank order)."""
    if meta.policy not in ("leftmost", "p0"):
        # list-based fallback (random policy): selection order == value-slot
        # order, so an override table substitutes positionally
        sp = decode(payload, meta, shape, step=step, seed=seed)
        if values is not None:
            sp = dataclasses.replace(sp, values=values)
        return sp.to_dense()
    vals = payload.values if values is None else values
    mask = query_universe(payload.words, meta)
    ranks = jnp.cumsum(mask.astype(jnp.int32)) - 1
    nsel = jnp.minimum(payload.nsel, meta.budget)
    live = jnp.logical_and(mask, ranks < nsel)
    safe = jnp.clip(ranks, 0, vals.shape[0] - 1)
    dense = jnp.where(live, vals[safe], jnp.zeros((), vals.dtype))
    return dense.reshape(shape)


def wire_bits(payload: BloomPayload, meta: BloomMeta) -> jax.Array:
    """Filter bits + selected values + count word (the C++ wire format
    ``[m | h | values | bit-array]``, bloom_filter_compression.cc:112-141)."""
    return jnp.asarray(64.0 + meta.m_bits, jnp.float32) + payload.nsel.astype(jnp.float32) * 32


def measured_fpr(sp: SparseGrad, words: jax.Array, meta: BloomMeta) -> jax.Array:
    """Observed false-positive rate — the `Compute_False_Positives` diagnostic
    (compression_utils.hpp:137-148)."""
    mask = query_universe(words, meta)
    truth = jnp.zeros((meta.d,), jnp.bool_).at[sp.indices].set(True)
    fp = jnp.sum(jnp.logical_and(mask, ~truth).astype(jnp.int32))
    return fp / jnp.maximum(1, meta.d - sp.nnz)
