"""Bloom-filter index codec, jit-compiled and table-free.

Reference parity (/root/reference/pytorch/deepreduce.py:431-555 and
tensorflow/bloom_filter_compression.cc): indices are inserted into a bloom
filter; only the packed bit-array crosses the wire; both sides re-derive the
index set by querying the whole universe and running a deterministic
selection *policy* over the positives. Because false positives shift which
indices are selected, the encoder is FP-aware: it re-reads values from the
dense tensor at the *selected* positions (pytorch/deepreduce.py:519-523), so
receivers scatter true gradient values to exactly the positions they will
derive.

TPU-first redesign:

- Hashing is computed, not gathered: a murmur3-finalizer integer mix per
  (index, seed_j) replaces the reference's precomputed ``[18M x H]`` hash
  table (pytorch/deepreduce.py:461-477) — no 18M-row tensor in HBM, no
  gather in the hot loop. The C++ native layer implements the identical mix
  so host and device payloads interoperate.
- Filter geometry follows the C++ op's optimal-m form
  (bloom_filter_compression.cc:85-99, SURVEY.md §2.6): ``m_bytes =
  ceil(k·|ln fpr| / ln²2 / 8)`` rounded up to 8-byte alignment,
  ``h = ceil((8·m_bytes/k)·ln 2)``; default FPR ``0.1·k/d``
  (pytorch/deepreduce.py:511).
- Policies ``leftmost`` / ``random`` / ``p0`` (pytorch/deepreduce.py:479-492)
  are mask+cumsum prefix selections — sort-free, static-shape. ``random`` is
  keyed by (seed, step) on *both* sides, fixing the reference's re-seeded
  ``manual_seed(42)`` quirk while keeping its cross-worker determinism
  contract (policies.hpp:160-180 seeds by step). Exact ``conflict_sets``
  (P2) is native-only, as in the reference (policies.hpp:43-146) — see
  `deepreduce_tpu.native`; ``conflict_sets_approx`` is the in-graph
  parallel redesign of the same draw (one lexicographic sort by
  within-set random rank / set size / tiebreak over the positive pool —
  `_conflict_sets_select`), jit-native so it runs on TPU.
- P0's data-dependent output size (|P| >= k) becomes a static budget from
  the paper's Lemma-6 expectation ``|P| <= k + fpr·(d-k)`` with 5% + 64
  headroom; `nsel` is the in-band length word (the reference prepends the
  true count, pytorch/deepreduce.py:525-527).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu import sparse as _sparse
from deepreduce_tpu.sparse import (  # noqa: F401 — re-exported: profile_codec and tests address these as bloom._*
    SparseGrad,
    _prefix_positions,
    _select_bit,
)

_LN2 = 0.6931471805599453
_GOLDEN = 0x9E3779B9
_QUERY_CHUNK = 1 << 16


def fmix32(x: jax.Array) -> jax.Array:
    """murmur3 32-bit finalizer (same constants as MurmurHash3_fmix32)."""
    x = jnp.asarray(x, jnp.uint32)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def hash_seeds(num_hash: int) -> jax.Array:
    """Per-hash-function seeds, derived — not stored (uint32[h])."""
    j = jnp.arange(1, num_hash + 1, dtype=jnp.uint32)
    return fmix32(j * jnp.uint32(_GOLDEN))


def hash_positions(indices: jax.Array, seeds: jax.Array, m_bits: int) -> jax.Array:
    """Bit positions [..., h] for each index."""
    idx = jnp.asarray(indices, jnp.uint32)
    return (fmix32(idx[..., None] ^ seeds) % jnp.uint32(m_bits)).astype(jnp.int32)


_SEED_BLOCK = 0xA2C2A9F7
_SEED_LANE1 = 0x6A09E667
_SEED_LANE2 = 0xBB67AE85


def lane_mask(indices: jax.Array, num_hash: int) -> jax.Array:
    """32-bit in-word mask [..] for the blocked filters: h bit lanes from
    5-bit fields of one or two murmur-mixed words."""
    idx = jnp.asarray(indices, jnp.uint32)
    r1 = fmix32(idx ^ jnp.uint32(_SEED_LANE1))
    r2 = fmix32(idx ^ jnp.uint32(_SEED_LANE2))
    mask = jnp.zeros_like(idx)
    for j in range(num_hash):
        r = r1 if j < 6 else r2
        lane = (r >> jnp.uint32(5 * (j % 6))) & jnp.uint32(31)
        mask = mask | (jnp.uint32(1) << lane)
    return mask


def blocked_block_and_mask(indices: jax.Array, meta: "BloomMeta") -> Tuple[jax.Array, jax.Array]:
    """(word index [..], 32-bit in-word mask [..]) for the blocked filters.

    Block assignment by mode: ``hash`` mixes the index (classic blocked
    bloom); ``mod`` uses ``j mod W`` with W odd — arithmetic, so the
    universe query needs NO gather at all (see `query_universe`), and odd W
    is coprime to every power-of-2 stride, which spreads the structured
    index patterns gradients actually produce (consecutive runs, strided
    embedding rows) sub-Poisson across words."""
    idx = jnp.asarray(indices, jnp.uint32)
    n_words = meta.m_bits // 32
    if meta.blocked == "mod":
        block = (idx % jnp.uint32(n_words)).astype(jnp.int32)
    else:
        block = (fmix32(idx ^ jnp.uint32(_SEED_BLOCK)) % jnp.uint32(n_words)).astype(jnp.int32)
    return block, lane_mask(idx, meta.num_hash)


def bloom_config(k: int, d: int, fpr: Optional[float]) -> Tuple[int, int, float]:
    """(m_bits, num_hash, fpr) — static geometry from static (k, d)."""
    if fpr is None:
        fpr = 0.1 * k / d  # pytorch/deepreduce.py:511
    m_bytes = int(math.ceil(k * abs(math.log(fpr)) / (_LN2 * _LN2) / 8.0))
    m_bytes = max(8, (m_bytes + 7) // 8 * 8)  # 8-byte aligned, as the C++ op intends
    num_hash = max(1, int(math.ceil((m_bytes * 8.0 / k) * _LN2)))
    return m_bytes * 8, num_hash, fpr


# Register-blocked variant: all h bits of an index live in ONE 32-bit word,
# so the universe query needs a single gather per index instead of h — the
# difference between ~2.9s and ~0.25s for a 25.6M universe on v5e (gathers
# are latency-bound on TPU; arithmetic is nearly free). The space-for-speed
# tax is computed from the Poisson block-load mixture, not a fixed factor:
# a word holding j keys has ~32(1-(1-1/32)^{jh}) set bits and false-positive
# probability (set/32)^h; total FPR = E_j~Poisson(k/W)[fpr_j].


def _blocked_fpr(k: int, n_words: int, h: int) -> float:
    lam = k / n_words
    total = 0.0
    pj = math.exp(-lam)
    for j in range(0, 64):
        set_bits = 32.0 * (1.0 - (1.0 - 1.0 / 32.0) ** (j * h))
        total += pj * (set_bits / 32.0) ** h
        pj *= lam / (j + 1)
        if pj < 1e-12 and j > lam:
            break
    return total


def blocked_bloom_config(
    k: int, d: int, fpr: Optional[float], mode: str = "hash"
) -> Tuple[int, int, float]:
    if fpr is None:
        fpr = 0.1 * k / d
    classic_bits, _, _ = bloom_config(k, d, fpr)
    best = None
    n_words = max(1, classic_bits // 32)
    # grow the table until some h meets the target FPR
    for _ in range(16):
        for h in range(1, 13):
            if _blocked_fpr(k, n_words, h) <= fpr:
                best = (n_words, h)
                break
        if best:
            break
        n_words = int(n_words * 1.3) + 1
    if best is None:
        best = (n_words, 12)
    n_words, h = best
    if mode == "mod":
        n_words |= 1  # odd: coprime to power-of-2 index strides
    return n_words * 32, h, fpr


def p0_budget(k: int, d: int, fpr: float) -> int:
    """Static slot budget for policy p0 (all positives): Lemma-6 expectation
    plus headroom (SURVEY.md §7 hard part 1)."""
    return min(d, int(math.ceil(k + 1.05 * fpr * (d - k))) + 64)


def policy_budget(policy: str, k: int, d: int, fpr: float) -> int:
    return p0_budget(k, d, fpr) if policy == "p0" else k


@dataclasses.dataclass(frozen=True)
class BloomMeta:
    """Static codec geometry, shared by encode and decode.

    `blocked`: "" = classic bit-addressed filter (h positions/key);
    "hash" = register-blocked, block chosen by hash (1 gather/query);
    "mod" = register-blocked, block = j mod W with W odd (query is a pure
    broadcast — zero gathers; the measured-fastest TPU variant)."""

    d: int
    k: int
    m_bits: int
    num_hash: int
    fpr: float
    policy: str
    budget: int
    blocked: str = ""

    @staticmethod
    def normalize_blocked(blocked) -> str:
        """Config values False/True/"hash"/"mod" -> canonical mode string
        ("" / "mod" / "hash"). True means "the fast one" = mod."""
        if blocked is True:
            return "mod"
        if not blocked:
            return ""
        if blocked in ("hash", "mod"):
            return blocked
        raise ValueError(f"bloom_blocked must be bool, 'hash' or 'mod'; got {blocked!r}")

    @staticmethod
    def create(
        k: int,
        d: int,
        fpr: Optional[float] = None,
        policy: str = "leftmost",
        blocked=False,
        threshold_insert: bool = False,
    ) -> "BloomMeta":
        if policy == "conflict_sets":
            raise NotImplementedError(
                "exact conflict_sets (P2) is native-only, as in the reference "
                "(policies.hpp:43-146): use index='bloom_native' (host "
                "callback off-CPU), or policy='conflict_sets_approx' for the "
                "in-graph parallel redesign that runs on TPU"
            )
        if policy not in ("leftmost", "p0", "random", "conflict_sets_approx"):
            raise ValueError(f"unknown bloom policy {policy!r}")
        blocked = BloomMeta.normalize_blocked(blocked)
        if blocked:
            m_bits, num_hash, fpr_eff = blocked_bloom_config(k, d, fpr, mode=blocked)
        else:
            m_bits, num_hash, fpr_eff = bloom_config(k, d, fpr)
        budget = policy_budget(policy, k, d, fpr_eff)
        if threshold_insert:
            if blocked != "mod":
                raise ValueError(
                    "threshold_insert requires the 'mod' blocked layout "
                    f"(got {blocked or 'classic'!r})"
                )
            # the threshold superset can exceed k (ties; approx-top-k misses
            # above the kept minimum rejoin the filter) — widen the slot
            # budget so ascending-prefix truncation doesn't bias against
            # trailing parameters
            budget = min(d, budget + int(math.ceil(0.06 * k)) + 64)
        return BloomMeta(
            d=d,
            k=k,
            m_bits=m_bits,
            num_hash=num_hash,
            fpr=fpr_eff,
            policy=policy,
            budget=budget,
            blocked=blocked,
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BloomPayload:
    values: jax.Array  # f32[budget] — values at the selected positions
    words: jax.Array  # uint32[m_bits/32] — packed filter bit-array
    nsel: jax.Array  # i32[] — live selected count (p0 count prefix role)


def saturated(payload: BloomPayload, meta: BloomMeta) -> jax.Array:
    """True when the selection filled every slot (nsel == budget) — i.e.
    `_prefix_positions` may have TRUNCATED trailing positives. Under
    `threshold_insert` the widened budget (BloomMeta.create) is a heuristic;
    a saturated payload means the threshold superset overflowed it and an
    A/B against the scatter insert would compare different effective
    selections. Harnesses must check this (ADVICE r3)."""
    return jnp.asarray(payload.nsel, jnp.int32) >= jnp.int32(meta.budget)


def _scatter_or(n_words: int, word_idx: jax.Array, masks: jax.Array) -> jax.Array:
    """uint32[n_words]: OR-combine `masks` into their target words.

    XLA has no OR-scatter, and a per-bit ``.at[].max`` scatter serializes on
    collisions (the round-2 encode bottleneck: ~145ms at k=405k on TPU).
    Instead: k-scale sort by word, segmented OR via associative scan, then
    ONE unique-index scatter of each segment's end — ~5ms at the same size.
    """
    order = jnp.argsort(word_idx)
    ws = word_idx[order]
    ms = masks[order]

    def comb(a, b):
        aw, am = a
        bw, bm = b
        return bw, jnp.where(aw == bw, am | bm, bm)

    _, acc = jax.lax.associative_scan(comb, (ws, ms))
    is_end = jnp.concatenate([ws[1:] != ws[:-1], jnp.ones((1,), bool)])
    # dead slots park at unique out-of-range targets: mode='drop' discards
    # them without breaking the unique-indices promise
    tgt = jnp.where(
        is_end, ws, n_words + jnp.arange(ws.shape[0], dtype=ws.dtype)
    )
    return (
        jnp.zeros((n_words,), jnp.uint32)
        .at[tgt]
        .set(acc, mode="drop", unique_indices=True)
    )


def insert(indices: jax.Array, nnz: jax.Array, meta: BloomMeta) -> jax.Array:
    """Build the packed filter from (possibly padded) indices.

    On the classic and ``hash`` paths dead slots are re-pointed at the first
    index — inserting a duplicate is a no-op under bloom set semantics, which
    keeps everything static-shape.

    The ``mod`` blocked mode is sort-free: word(j) = j mod W, so scattering
    each index's lane mask at its own universe position into a [rows, W]
    buffer puts every contribution to word w in column w — one unique-index
    scatter plus a bitwise-OR reduction over rows. This is the insert-side
    dual of `query_universe`'s zero-gather broadcast, and replaces the
    k-scale argsort of `_scatter_or` (~44ms → sub-dispatch at k=405k on
    v5e). This path REQUIRES live indices to be distinct (every shipped
    sparsifier emits distinct indices; duplicates would repeat a scatter
    target, which XLA's unique_indices promise leaves undefined — though
    identical masks make it benign in practice).
    """
    live = jnp.arange(indices.shape[0], dtype=jnp.int32) < nnz
    n_words = meta.m_bits // 32
    if meta.blocked == "mod":
        mask = lane_mask(jnp.asarray(indices, jnp.uint32), meta.num_hash)
        rows = (meta.d + n_words - 1) // n_words
        # dead slots park at distinct out-of-range targets: mode='drop'
        # discards them without breaking the unique-indices promise
        tgt = jnp.where(
            live,
            indices,
            rows * n_words + jnp.arange(indices.shape[0], dtype=indices.dtype),
        )
        buf = (
            jnp.zeros((rows * n_words,), jnp.uint32)
            .at[tgt]
            .set(mask, mode="drop", unique_indices=True)
        )
        return jax.lax.reduce(
            buf.reshape(rows, n_words), jnp.uint32(0), jax.lax.bitwise_or, (0,)
        )
    idx = jnp.where(live, indices, indices[0])
    if meta.blocked:
        block, mask = blocked_block_and_mask(idx, meta)
        return _scatter_or(n_words, block, mask)
    seeds = hash_seeds(meta.num_hash)
    pos = hash_positions(idx, seeds, meta.m_bits).reshape(-1)
    word = pos // 32
    mask = jnp.uint32(1) << (pos % 32).astype(jnp.uint32)
    return _scatter_or(n_words, word, mask)


def _mod_grid(meta: BloomMeta) -> Tuple[int, jax.Array, jax.Array]:
    """(rows, universe index grid j[rows, W], lane masks[rows, W]) — the
    shared [ceil(d/W), W] layout both sides of the mod-blocked filter
    broadcast over (encode's insert_from_dense and query_universe must
    derive membership from the identical grid)."""
    n_words = meta.m_bits // 32
    rows = (meta.d + n_words - 1) // n_words
    j = (
        jnp.arange(rows, dtype=jnp.uint32)[:, None] * jnp.uint32(n_words)
        + jnp.arange(n_words, dtype=jnp.uint32)[None, :]
    )
    return rows, j, lane_mask(j, meta.num_hash)


def insert_from_dense(dense: jax.Array, thresh: jax.Array, meta: BloomMeta) -> jax.Array:
    """Filter words from a magnitude threshold — the scatter-free mod-mode
    insert: membership is ``|dense_j| >= thresh``, evaluated as a pure
    elementwise pass over the same [rows, W] layout `query_universe`
    broadcasts over, OR-reduced across rows. The inserted set is the
    threshold superset of any top-k whose smallest kept magnitude is
    `thresh` (ties join; bloom set semantics make that harmless, and the
    FP-aware re-read keeps every decoded value true)."""
    if meta.blocked != "mod":
        raise ValueError("insert_from_dense requires the 'mod' blocked layout")
    n_words = meta.m_bits // 32
    rows, _, mask = _mod_grid(meta)
    a = jnp.abs(dense.reshape(-1))
    pad = rows * n_words - meta.d
    if pad:
        a = jnp.concatenate([a, jnp.zeros((pad,), a.dtype)])
    live = a.reshape(rows, n_words) >= thresh
    contrib = jnp.where(live, mask, jnp.uint32(0))
    return jax.lax.reduce(contrib, jnp.uint32(0), jax.lax.bitwise_or, (0,))


def query_universe(words: jax.Array, meta: BloomMeta) -> jax.Array:
    """bool[d]: membership test for every index in the universe — the hot op
    (pytorch/deepreduce.py:466-477), chunked so the [chunk, h] position block
    stays small regardless of d."""
    d = meta.d
    if meta.blocked == "mod":
        # ZERO gathers: block(j) = j mod W, so scanning the universe in
        # natural order makes the word index cycle 0..W-1 — laying the
        # universe out as [ceil(d/W), W], each row tests against the whole
        # word array by broadcast. Pure elementwise + one reshape.
        _, j, mask = _mod_grid(meta)
        hit = (words[None, :] & mask) == mask
        hit = jnp.logical_and(hit, j < jnp.uint32(d))
        return hit.reshape(-1)[:d]
    if meta.blocked:
        # ONE gather per index: word + arithmetic in-word mask test
        idx = jnp.arange(d, dtype=jnp.int32)
        block, mask = blocked_block_and_mask(idx, meta)
        w = words[block]
        return (w & mask) == mask

    seeds = hash_seeds(meta.num_hash)
    chunk = min(_QUERY_CHUNK, max(1, d))
    n_chunks = (d + chunk - 1) // chunk

    def one_chunk(c: jax.Array) -> jax.Array:
        idx = c * chunk + jnp.arange(chunk, dtype=jnp.int32)
        pos = hash_positions(idx, seeds, meta.m_bits)
        w = words[pos // 32]
        bit = (w >> (pos % 32).astype(jnp.uint32)) & jnp.uint32(1)
        hit = jnp.min(bit, axis=-1) == 1
        return jnp.logical_and(hit, idx < d)

    if n_chunks == 1:
        return one_chunk(jnp.int32(0))[:d]
    mask = jax.lax.map(one_chunk, jnp.arange(n_chunks, dtype=jnp.int32))
    return mask.reshape(-1)[:d]


def _prefix_select(mask: jax.Array, budget: int) -> Tuple[jax.Array, jax.Array]:
    """First `budget` True positions of `mask`, ascending. Bit-consistent
    with `encode`'s rank-addressed value layout and with `decode_dense`.
    Dead slots carry index 0 (the SparseGrad padding contract). Returns
    (indices[budget], count)."""
    pos, count = _prefix_positions(mask, budget)
    live = jnp.arange(budget, dtype=jnp.int32) < count
    return jnp.where(live, pos, 0), count


def _conflict_sets_select(
    mask: jax.Array, meta: BloomMeta, *, step: jax.Array, seed: int = 0
) -> Tuple[jax.Array, jax.Array]:
    """In-graph approximation of the reference's P2 conflict-sets policy
    (policies.hpp:43-146): group positives by the filter bucket whose bits
    they share, then draw round-robin — one random member per set, smallest
    sets first — until the budget fills. The reference's sequential
    smallest-set-first loop becomes a single lexicographic sort over the
    positive pool by (within-set random rank, set size, random tiebreak):
    rank-0 rows are exactly "one draw per set", ordered small-sets-first,
    then rank-1 rows, and so on — the same visit order, computed in
    parallel (SURVEY.md §7 hard-part 2's 'segment-sort + segmented random
    pick' redesign). All randomness is keyed by (seed, step) only, so
    encode and decode derive the identical selection from the identical
    filter (the policies.hpp:117,172 determinism contract).

    Work is pool-scale (the Lemma-6 positive bound), never d-scale: one
    `_prefix_positions` over the mask, one histogram scatter-add over the
    filter words, two pool-length lexsorts."""
    pool = p0_budget(meta.k, meta.d, meta.fpr)
    n_groups = meta.m_bits // 32
    pos, cnt = _prefix_positions(mask, pool)
    slot = jnp.arange(pool, dtype=jnp.int32)
    live = slot < cnt
    g = jnp.where(live, conflict_group(pos, meta), n_groups)
    # set sizes: scatter-add histogram over words (+1 sentinel for dead)
    sizes = jnp.zeros((n_groups + 1,), jnp.int32).at[g].add(1, mode="drop")
    size_of = jnp.where(live, sizes[g], jnp.int32(2**30))
    key = jax.random.fold_in(jax.random.PRNGKey(seed), jnp.asarray(step, jnp.uint32))
    r = jax.random.uniform(key, (pool,))
    # within-set random rank: sort pool rows by (group, r); a row's rank is
    # its distance from the start of its group run
    order = jnp.lexsort((r, g))
    gs = g[order]
    run_start = jnp.concatenate([jnp.ones((1,), bool), gs[1:] != gs[:-1]])
    rank_sorted = slot - jax.lax.cummax(jnp.where(run_start, slot, 0))
    rank = jnp.zeros((pool,), jnp.int32).at[order].set(rank_sorted)
    rank = jnp.where(live, rank, jnp.int32(2**30))
    # round-robin visit order: all rank-0 draws (small sets first), then
    # rank-1, ... — take the first `budget`
    pick = jnp.lexsort((r, size_of, rank))[: meta.budget]
    chosen = pos[pick]
    count = jnp.minimum(cnt, meta.budget)
    # canonical ascending-index output, dead slots parked at 0
    out_live = jnp.arange(meta.budget, dtype=jnp.int32) < count
    chosen = jnp.sort(jnp.where(out_live, chosen, meta.d))
    return jnp.where(out_live, chosen, 0).astype(jnp.int32), count


def conflict_group(indices: jax.Array, meta: BloomMeta) -> jax.Array:
    """Primary conflict bucket of each index — the word of the filter its
    bits (or its first hash) land in. Two positives in the same word are
    exactly the keys whose membership evidence overlaps, the relation the
    reference's `build_conflict_sets` groups by hash bucket
    (policies.hpp:43-57); word granularity is that bucket rounded to the
    filter's physical layout."""
    if meta.blocked:
        block, _ = blocked_block_and_mask(indices, meta)
        return block
    seeds = hash_seeds(meta.num_hash)
    return hash_positions(indices, seeds[:1], meta.m_bits)[..., 0] // 32


def select(
    mask: jax.Array, meta: BloomMeta, *, step: jax.Array, seed: int = 0
) -> Tuple[jax.Array, jax.Array]:
    """Run the selection policy over the positive mask. Deterministic given
    (mask, step, seed) — the encode/decode agreement contract
    (bloom_filter_compression.cc:217-218)."""
    if meta.policy in ("leftmost", "p0"):
        return _prefix_select(mask, meta.budget)
    if meta.policy == "conflict_sets_approx":
        return _conflict_sets_select(mask, meta, step=step, seed=seed)
    if meta.policy == "random":
        key = jax.random.fold_in(jax.random.PRNGKey(seed), jnp.asarray(step, jnp.uint32))
        pri = jax.random.uniform(key, mask.shape)
        pri = jnp.where(mask, pri, -1.0)
        _, chosen = jax.lax.top_k(pri, meta.budget)
        count = jnp.minimum(jnp.sum(mask.astype(jnp.int32)), meta.budget)
        # fewer positives than budget: slots whose priority was -1 are dead —
        # push them past the live ones, emit canonical ascending order
        valid = mask[chosen]
        order = jnp.argsort(jnp.where(valid, chosen, meta.d))
        chosen = chosen[order]
        live = jnp.arange(meta.budget, dtype=jnp.int32) < count
        return jnp.where(live, chosen, 0).astype(jnp.int32), count
    raise ValueError(f"unknown policy {meta.policy!r}")


def encode(
    sp: SparseGrad,
    dense: Optional[jax.Array],
    meta: BloomMeta,
    *,
    step: jax.Array = 0,
    seed: int = 0,
    threshold_insert: bool = False,
) -> BloomPayload:
    """Insert + FP-aware value re-read (pytorch/deepreduce.py:505-533).

    For the prefix policies the re-read inverts the rank function instead
    of scattering by it: `_prefix_positions` yields slot s's universe
    position, so values are ONE budget-scale gather from the dense tensor
    — no d-scale sort or scatter anywhere in encode. `select` remains for
    the `random` policy. `threshold_insert` swaps the unique-scatter insert
    for the fully scatter-free `insert_from_dense` (mod mode with a dense
    tensor only — anything else raises; the flag must never silently
    compare a path against itself). A zero threshold would saturate the
    filter (every |g| >= 0), so that case falls back to the scatter insert
    under `lax.cond` — it happens when the sparsifier kept a zero value
    (fewer true nonzeros than k)."""
    if threshold_insert:
        if meta.blocked != "mod" or dense is None:
            raise ValueError(
                "threshold_insert requires blocked='mod' and a dense tensor "
                "(FP-aware encode); refusing to silently fall back"
            )
        live = jnp.arange(sp.k, dtype=jnp.int32) < sp.nnz
        thresh = jnp.min(
            jnp.where(live, jnp.abs(sp.values), jnp.inf).astype(jnp.float32)
        )
        words = jax.lax.cond(
            thresh > 0,
            lambda: insert_from_dense(dense, thresh.astype(dense.dtype), meta),
            lambda: insert(sp.indices, sp.nnz, meta),
        )
    else:
        words = insert(sp.indices, sp.nnz, meta)
    if dense is not None and meta.policy in ("leftmost", "p0"):
        return _fp_aware_payload(words, dense.reshape(-1), meta)
    elif dense is not None:
        mask = query_universe(words, meta)
        selected, nsel = select(mask, meta, step=step, seed=seed)
        flat = dense.reshape(-1)
        live = jnp.arange(meta.budget, dtype=jnp.int32) < nsel
        values = jnp.where(live, flat[selected], 0.0)
    else:
        # no dense tensor: transmit sparsifier values as-is (the reference's
        # non-fp-aware branch); only sensible when decode-side selection
        # happens to align (fpr ~ 0)
        values = jnp.zeros((meta.budget,), sp.values.dtype).at[: sp.k].set(sp.values)
        nsel = jnp.minimum(sp.nnz, meta.budget)
    return BloomPayload(values=values, words=words, nsel=nsel.astype(jnp.int32))


def _fp_aware_payload(words: jax.Array, flat: jax.Array, meta: BloomMeta) -> BloomPayload:
    """Shared FP-aware tail of every prefix-policy encode: query the
    universe, prefix-select the first `budget` positives, and re-read the
    TRUE dense values at those positions with one ascending (sorted) gather
    (pytorch/deepreduce.py:519-523). Both `encode` and `encode_dense_direct`
    must stay bit-identical here — the wire contract is this function."""
    mask = query_universe(words, meta)
    pos, nsel = _prefix_positions(mask, meta.budget)
    live = jnp.arange(meta.budget, dtype=jnp.int32) < nsel
    values = jnp.where(
        live,
        jnp.take(flat, pos, indices_are_sorted=True, mode="clip"),
        jnp.zeros((), flat.dtype),
    )
    return BloomPayload(values=values, words=words, nsel=nsel.astype(jnp.int32))


def encode_dense_direct(
    dense: jax.Array,
    meta: BloomMeta,
    *,
    sample_size: int = 1 << 15,
    undershoot: float = 0.9,
) -> BloomPayload:
    """Sparsifier-free flagship encode: the whole top-k materialization is
    skipped. The k-th magnitude is estimated from a strided sample
    (`sparse.sampled_kth_magnitude`), the filter is built straight from the
    dense tensor by the scatter-free threshold insert, and the FP-aware
    value stream comes from the usual query -> prefix -> sorted gather.

    Composition of two independently convergence-backed approximations
    (CONVERGENCE.json `drqsgd_bf_p0_sampled` for the sampled threshold,
    `bf_p0_index_ti` for the threshold-superset insert); the wire format
    and decode side are bit-identical to the standard path, so this is an
    encoder-only optimization — it removes the O(d)-compaction /
    O(d log k)-sort sparsify stage that dominates encode.

    Requires the 'mod' blocked layout and a prefix policy (leftmost/p0):
    the selection must be derivable from the filter alone. A zero estimated
    threshold (naturally sparse tensor the sample missed) falls back to
    exact top-k insertion under `lax.cond`, mirroring
    `sparse.topk_sampled`; small tensors take the exact path statically."""
    if meta.blocked != "mod":
        raise ValueError("encode_dense_direct requires the 'mod' blocked layout")
    if meta.policy not in ("leftmost", "p0"):
        raise ValueError(
            f"encode_dense_direct needs a prefix policy (leftmost/p0), got {meta.policy!r}"
        )
    flat = dense.reshape(-1)
    d = flat.shape[0]

    def exact_words():
        _, idxs = jax.lax.top_k(jnp.abs(flat), meta.k)
        return insert(
            jnp.sort(idxs).astype(jnp.int32), jnp.asarray(meta.k, jnp.int32), meta
        )

    if d <= max(4 * meta.k, 2 * sample_size):
        # small tensor: sampling error would dominate and exact top-k is
        # cheap — same static guard as sparse.topk_sampled
        words = exact_words()
    else:
        t = _sparse.sampled_kth_magnitude(
            flat, meta.k, sample_size=sample_size, undershoot=undershoot
        )
        words = jax.lax.cond(
            t > 0,
            lambda: insert_from_dense(dense, t.astype(dense.dtype), meta),
            exact_words,
        )
    return _fp_aware_payload(words, flat, meta)


def decode(
    payload: BloomPayload,
    meta: BloomMeta,
    shape: Tuple[int, ...],
    *,
    step: jax.Array = 0,
    seed: int = 0,
) -> SparseGrad:
    """Query the universe, re-run the policy, pair with transmitted values
    (pytorch/deepreduce.py:535-555). The selection list is exact-rank, so
    it is bit-consistent with `encode`'s rank-addressed value layout; the
    wrapper's production path (`decode_dense`) skips the list entirely."""
    mask = query_universe(payload.words, meta)
    selected, nsel = select(mask, meta, step=step, seed=seed)
    nsel = jnp.minimum(nsel, payload.nsel)
    return SparseGrad(
        values=payload.values,
        indices=selected,
        nnz=nsel.astype(jnp.int32),
        shape=shape,
    )


def decode_dense(
    payload: BloomPayload,
    meta: BloomMeta,
    shape: Tuple[int, ...],
    *,
    step: jax.Array = 0,
    seed: int = 0,
    values: Optional[jax.Array] = None,
) -> jax.Array:
    """Rank-inversion decode straight to the dense tensor — the TPU fast
    path.

    For the prefix policies (leftmost/p0) the selection is "the first
    `budget` positives ascending", so value slot s belongs at universe
    position `_prefix_positions(mask)[s]`:

        dense[pos(s)] = values[s]   for s < nsel

    One budget-scale unique-index scatter instead of the round-2 d-scale
    rank gather (`dense[j] = vals[cumsum(mask)[j]-1]`, ~20ms at d=4M on
    TPU; this is ~4ms). `values` overrides the payload's value stream
    ('both' mode passes the value-codec output, already in rank order)."""
    if meta.policy not in ("leftmost", "p0"):
        # list-based fallback (random policy): selection order == value-slot
        # order, so an override table substitutes positionally
        sp = decode(payload, meta, shape, step=step, seed=seed)
        if values is not None:
            sp = dataclasses.replace(sp, values=values)
        return sp.to_dense()
    vals = payload.values if values is None else values
    n_v = vals.shape[0]
    vals = _sparse.fit_length(vals, meta.budget)
    mask = query_universe(payload.words, meta)
    pos, derived = _prefix_positions(mask, meta.budget)
    nsel = jnp.minimum(jnp.minimum(payload.nsel, meta.budget), derived)
    nsel = jnp.minimum(nsel, n_v)
    return _sparse.scatter_ascending(vals, pos, nsel, meta.d).reshape(shape)


def wire_bits(payload: BloomPayload, meta: BloomMeta) -> jax.Array:
    """Filter bits + selected values + count word (the C++ wire format
    ``[m | h | values | bit-array]``, bloom_filter_compression.cc:112-141)."""
    return jnp.asarray(64.0 + meta.m_bits, jnp.float32) + payload.nsel.astype(jnp.float32) * 32


def measured_fpr(sp: SparseGrad, words: jax.Array, meta: BloomMeta) -> jax.Array:
    """Observed false-positive rate — the `Compute_False_Positives` diagnostic
    (compression_utils.hpp:137-148)."""
    mask = query_universe(words, meta)
    truth = jnp.zeros((meta.d,), jnp.bool_).at[sp.indices].set(True)
    fp = jnp.sum(jnp.logical_and(mask, ~truth).astype(jnp.int32))
    return fp / jnp.maximum(1, meta.d - sp.nnz)
