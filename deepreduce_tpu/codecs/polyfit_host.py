"""Host-path PolyFit variant with searched knots and transmitted breaks.

Reference parity: `PolyFitCPU` (/root/reference/pytorch/deepreduce.py:560-688)
— unlike the GPU PolyFit (geometric segments re-derived from (N, num_pos)),
this variant *searches* for knots by recursive max-distance-from-chord
(`find_breaks` :566-582), fits with numpy per segment, and transmits the
breaks explicitly alongside the coefficients (:669-675). Positive values are
knot-searched in reversed (ascending) order, negatives in sorted order, and
the pos/neg boundary is always a break (:653-665).

Placement: host codec under `pure_callback` (the reference's is CPU numpy
too); static payload budget = max_breaks segments. The on-device sort and
the mapping transmission stay in JAX; only the knot search + per-segment
polyfit round-trips to host."""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepreduce_tpu.sparse import SparseGrad

NUM_BREAKS = 5  # reference default (pytorch/deepreduce.py:632)
MIN_TAIL = 20  # `20 * num_of_breaks` guard (:574,578) scaled per break


def find_breaks(curve: np.ndarray, num_breaks: int = NUM_BREAKS) -> list:
    """Recursive ascending knot search: repeatedly split at the point of
    max |curve - chord| over the remaining suffix (reference :566-582)."""
    y = curve
    breaks = []
    base = 0
    for _ in range(num_breaks):
        if len(y) < MIN_TAIL * num_breaks:
            break
        line = np.linspace(y[0], y[-1], len(y))
        off = int(np.argmax(np.abs(line - y)))
        base += off
        if len(curve) - base < MIN_TAIL * num_breaks:
            break
        breaks.append(base)
        y = curve[base:]
    return breaks


def _fit_host(vals_sorted: np.ndarray, degree: int) -> Tuple[np.ndarray, np.ndarray, np.int32]:
    """Returns (coeffs [S, degree+1] f32, breaks [S+1] i32, n_seg)."""
    y = vals_sorted.astype(np.float64)
    num_pos = int(np.sum(y > 0))
    n = len(y)
    if num_pos == 0:
        breaks = find_breaks(y)
    elif num_pos == n:
        rev = find_breaks(y[::-1])
        breaks = sorted(n - b for b in rev)
    else:
        rev = find_breaks(y[:num_pos][::-1])
        breaks_pos = sorted(num_pos - b for b in rev)
        breaks_neg = [num_pos + b for b in find_breaks(y[num_pos:])]
        breaks = breaks_pos + [num_pos] + breaks_neg
    bounds = [0] + sorted(set(b for b in breaks if 0 < b < n)) + [n]

    max_seg = 2 * NUM_BREAKS + 2
    coeffs = np.zeros((max_seg, degree + 1), np.float32)
    out_bounds = np.zeros(max_seg + 1, np.int32)
    n_seg = len(bounds) - 1
    for s in range(n_seg):
        lo, hi = bounds[s], bounds[s + 1]
        x = np.arange(lo, hi, dtype=np.float64)
        c = np.polynomial.polynomial.polyfit(x, y[lo:hi], min(degree, max(1, hi - lo - 1)))
        coeffs[s, : len(c)] = c.astype(np.float32)
        out_bounds[s + 1] = hi
    out_bounds[n_seg + 1 :] = n
    return coeffs, out_bounds, np.int32(n_seg)


def _eval_host(coeffs: np.ndarray, bounds: np.ndarray, n_seg: int, n: int) -> np.ndarray:
    y = np.zeros(n, np.float32)
    for s in range(int(n_seg)):
        lo, hi = int(bounds[s]), int(bounds[s + 1])
        x = np.arange(lo, hi, dtype=np.float64)
        y[lo:hi] = np.polynomial.polynomial.polyval(x, coeffs[s].astype(np.float64)).astype(
            np.float32
        )
    return y


@dataclasses.dataclass(frozen=True)
class PolyFitHostMeta:
    k: int
    degree: int = 5

    @property
    def max_segments(self) -> int:
        return 2 * NUM_BREAKS + 2


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PolyFitHostPayload:
    coeffs: jax.Array  # f32[S, degree+1]
    bounds: jax.Array  # i32[S+1] — transmitted breaks (reference :670)
    n_seg: jax.Array  # i32[]
    indices: jax.Array  # i32[k] — value-sorted order (the mapping)


def encode(sp: SparseGrad, meta: PolyFitHostMeta) -> PolyFitHostPayload:
    order = jnp.argsort(-sp.values)
    vals = sp.values[order]
    idxs = sp.indices[order]
    s = meta.max_segments

    coeffs, bounds, n_seg = jax.pure_callback(
        lambda v: _fit_host(np.asarray(v), meta.degree),
        (
            jax.ShapeDtypeStruct((s, meta.degree + 1), jnp.float32),
            jax.ShapeDtypeStruct((s + 1,), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.int32),
        ),
        vals,
    )
    return PolyFitHostPayload(coeffs=coeffs, bounds=bounds, n_seg=n_seg, indices=idxs)


def decode(payload: PolyFitHostPayload, meta: PolyFitHostMeta, shape: Tuple[int, ...]) -> SparseGrad:
    vals = jax.pure_callback(
        lambda c, b, ns: _eval_host(np.asarray(c), np.asarray(b), int(ns), meta.k),
        jax.ShapeDtypeStruct((meta.k,), jnp.float32),
        payload.coeffs,
        payload.bounds,
        payload.n_seg,
    )
    return SparseGrad(
        values=vals,
        indices=payload.indices,
        nnz=jnp.asarray(meta.k, jnp.int32),
        shape=shape,
    )


def wire_bits(payload: PolyFitHostPayload, meta: PolyFitHostMeta) -> jax.Array:
    return payload.n_seg.astype(jnp.float32) * ((meta.degree + 1) * 32 + 32) + 32
