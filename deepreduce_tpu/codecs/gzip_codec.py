"""Deflate value codec (host path, lossless, order-preserving).

Reference (/root/reference/pytorch/deepreduce.py:742-764): zlib over the
float32 byte-packed values, CPU round trip. Same here — Deflate is
inherently host-side — but under `jax.pure_callback` with a static byte
budget and in-band length so it composes with jit/allgather.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepreduce_tpu.sparse import SparseGrad


@dataclasses.dataclass(frozen=True)
class GzipMeta:
    k: int

    @property
    def budget_bytes(self) -> int:
        # zlib worst case is input + 5 bytes/16KB block + 6
        n = 4 * self.k
        return n + (n // 16384 + 1) * 5 + 64


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GzipPayload:
    stream: jax.Array  # uint8[budget]
    nbytes: jax.Array  # i64[]
    indices: jax.Array  # i32[k] — untouched
    nnz: jax.Array


def encode(sp: SparseGrad, meta: GzipMeta) -> GzipPayload:
    def host(vals: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        packed = zlib.compress(np.ascontiguousarray(vals.astype("<f4")).tobytes())
        out = np.zeros(meta.budget_bytes, np.uint8)
        out[: len(packed)] = np.frombuffer(packed, np.uint8)
        return out, np.int32(len(packed))

    stream, nbytes = jax.pure_callback(
        host,
        (
            jax.ShapeDtypeStruct((meta.budget_bytes,), jnp.uint8),
            jax.ShapeDtypeStruct((), jnp.int32),
        ),
        sp.values,
    )
    return GzipPayload(stream=stream, nbytes=nbytes, indices=sp.indices, nnz=sp.nnz)


def decode(payload: GzipPayload, meta: GzipMeta, shape: Tuple[int, ...]) -> SparseGrad:
    def host(stream: np.ndarray, nbytes: np.ndarray) -> np.ndarray:
        raw = zlib.decompress(stream[: int(nbytes)].tobytes())
        return np.frombuffer(raw, "<f4").astype(np.float32)

    vals = jax.pure_callback(
        host, jax.ShapeDtypeStruct((meta.k,), jnp.float32), payload.stream, payload.nbytes
    )
    return SparseGrad(values=vals, indices=payload.indices, nnz=payload.nnz, shape=shape)


def wire_bits(payload: GzipPayload, meta: GzipMeta) -> jax.Array:
    return payload.nbytes.astype(jnp.float32) * 8 + 64
