"""Double-exponential curve-fit value codec (Fit-DExp).

Reference (/root/reference/tensorflow/deepreduce.py:376-442 and the
`double_exponential_fit` helper :67-144): absolute values sorted ascending
are fit with ``y = a·e^{p·x} + c·e^{q·x}`` by the integral-equation method —
cumulative trapezoid integrals S and SS of the curve give a 4x4 linear
system whose solution yields the exponents (p, q); a 2x2 system then gives
the amplitudes. Signs ride on the indices: ``(idx+1)·sign(value)``
(:398-399). Only 4 coefficients cross the wire for the values — fixed-size
output, hence the reference's ``tensors_size_are_same=True`` (:418).

TPU version: same math in f32 (the reference uses f64; the 4x4 solve is
regularized and x is kept at the reference's 1..K grid — the cumulative
integrals are benign because the sorted curve is monotone). Fully
jit-compiled: the reference's two `tf.linalg.solve`s become one fused
kernel; no host crossing.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu.sparse import SparseGrad


@dataclasses.dataclass(frozen=True)
class DoubleExpMeta:
    k: int


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DoubleExpPayload:
    coeffs: jax.Array  # f32[4] = (a, c, p, q)
    signed_indices: jax.Array  # i32[k] — (idx+1)*sign(val), ascending-|val| order
    nnz: jax.Array


def _fit(y: jax.Array) -> jax.Array:
    """Integral-method fit of a·e^{p·x}+c·e^{q·x} to y
    (tensorflow/deepreduce.py:67-144). The reference runs it in float64 over
    x=1..K; in f32 the normal-matrix entries (~K^3) cancel catastrophically,
    so we exploit the method's scale covariance and fit over x = i/K in
    (0, 1] — entries stay O(K) and f32 suffices. The stored exponents are in
    normalized units; `_eval` uses the same grid, so the wire format is
    self-consistent."""
    k = y.shape[0]
    x = jnp.arange(1, k + 1, dtype=jnp.float32) / jnp.float32(k)

    def cumtrapz(f):
        seg = 0.5 * (f[1:] + f[:-1]) * (x[1:] - x[:-1])
        return jnp.concatenate([jnp.zeros((1,), f.dtype), jnp.cumsum(seg)])

    s = cumtrapz(y)
    ss = cumtrapz(s)

    a11 = jnp.sum(ss * ss)
    a12 = jnp.sum(ss * s)
    a13 = jnp.sum(ss * x)
    a14 = jnp.sum(ss)
    a22 = jnp.sum(s * s)
    a23 = jnp.sum(s * x)
    a24 = jnp.sum(s)
    a33 = jnp.sum(x * x)
    a34 = jnp.sum(x)
    a44 = jnp.float32(k)
    a_mat = jnp.array(
        [
            [a11, a12, a13, a14],
            [a12, a22, a23, a24],
            [a13, a23, a33, a34],
            [a14, a24, a34, a44],
        ],
        jnp.float32,
    )
    b_vec = jnp.array([jnp.sum(ss * y), jnp.sum(s * y), jnp.sum(x * y), jnp.sum(y)], jnp.float32)
    tr = jnp.trace(a_mat)
    sol = jnp.linalg.solve(a_mat + 1e-7 * tr * jnp.eye(4, dtype=jnp.float32) / 4.0, b_vec)

    disc = jnp.maximum(sol[1] * sol[1] + 4.0 * sol[0], 0.0)
    root = jnp.sqrt(disc)
    p = 0.5 * (sol[1] + root)
    q = 0.5 * (sol[1] - root)
    # with peak-anchored evaluation (below) the basis lies in (0, 1]; the
    # clamp only bounds how fast the far end may underflow to 0
    p = jnp.clip(p, -80.0, 80.0)
    q = jnp.clip(q, -80.0, 80.0)

    # Amplitude solve. The exponents can be large (steep tails give p ~ 15+),
    # so the raw basis e^{p·x} spans many decades and its Gram matrix is
    # rank-deficient in f32 — the fit collapses (amplitudes ~1e-6, curve ~0
    # everywhere but the last points). Shift each exponential to peak at the
    # end of its OWN growth direction (x=1 for a positive exponent, x=x[0]
    # for a negative one) so every basis value lies in (0, 1] — no f32
    # overflow for either sign — then column-normalize before the solve.
    # The transmitted amplitudes A, C are the term values at the peak;
    # `_anchor` + `_eval` reconstruct from the same convention, so neither
    # side ever materializes e^{|p|}.
    beta = jnp.exp(p * (x - _anchor(p, x)))
    eta = jnp.exp(q * (x - _anchor(q, x)))
    nb = jnp.sqrt(jnp.sum(beta * beta))
    ne = jnp.sqrt(jnp.sum(eta * eta))
    basis = jnp.stack([beta / nb, eta / ne], axis=1)
    amp_n, _, _, _ = jnp.linalg.lstsq(basis, y)
    return jnp.array([amp_n[0] / nb, amp_n[1] / ne, p, q], jnp.float32)


def _anchor(exponent: jax.Array, x: jax.Array) -> jax.Array:
    """Peak location of e^{exponent·x} on the grid: x[-1] when growing,
    x[0] when decaying."""
    return jnp.where(exponent >= 0, x[-1], x[0])


def _eval(coeffs: jax.Array, k: int) -> jax.Array:
    x = jnp.arange(1, k + 1, dtype=jnp.float32) / jnp.float32(k)
    a, c, p, q = coeffs[0], coeffs[1], coeffs[2], coeffs[3]
    return a * jnp.exp(p * (x - _anchor(p, x))) + c * jnp.exp(q * (x - _anchor(q, x)))


def encode(sp: SparseGrad, meta: DoubleExpMeta) -> DoubleExpPayload:
    mags = jnp.abs(sp.values)
    order = jnp.argsort(mags)  # ascending |value|
    y = mags[order]
    signed = ((sp.indices[order] + 1) * jnp.sign(sp.values[order])).astype(jnp.int32)
    signed = jnp.where(signed == 0, sp.indices[order] + 1, signed)  # zero values keep +
    return DoubleExpPayload(coeffs=_fit(y), signed_indices=signed, nnz=sp.nnz)


def decode(payload: DoubleExpPayload, meta: DoubleExpMeta, shape: Tuple[int, ...]) -> SparseGrad:
    y = _eval(payload.coeffs, meta.k)
    sign = jnp.sign(payload.signed_indices).astype(jnp.float32)
    idxs = (jnp.abs(payload.signed_indices) - 1).astype(jnp.int32)
    return SparseGrad(
        values=y * sign,
        indices=jnp.maximum(idxs, 0),
        nnz=payload.nnz,
        shape=shape,
    )


def wire_bits(payload: DoubleExpPayload, meta: DoubleExpMeta) -> jax.Array:
    return jnp.asarray(4.0 * 32, jnp.float32)  # values side: 4 f32 coefficients
