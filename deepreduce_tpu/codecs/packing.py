"""Bit-packing with dynamic width into a static word budget.

The reference packs integers at runtime-chosen bit widths with CuPy
``packbits`` (/root/reference/pytorch/deepreduce.py:193-248: header
``[N×4 bytes | bits×1 byte | body | bit-planes]``) and its `both` mode packs
3×21-bit values per int64 (:165-191). Neither survives jit: output size
depends on data. TPU-native version: the caller supplies a static word
budget (worst case ``ceil(n * max_width / 32)``); the packed stream carries
``(words, n, width)`` and padding words are zero. `wire_bits` reports the
meaningful payload ``n * width`` so compression metrics see the true size
even though the allgather buffer is budget-shaped.

Bit order: value `i`'s bit `b` (LSB-first) lands at stream position
``i*width + b``; stream bit `p` lives in word ``p // 32`` at bit ``p % 32``.
The C++ native layer (`deepreduce_tpu/native`) implements the identical
layout so payloads are exchangeable across the JAX and host paths.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PackedInts:
    words: jax.Array  # uint32[budget_words]
    count: jax.Array  # i32[] — number of packed values
    width: jax.Array  # i32[] — bits per value (1..32)


def bits_needed(max_val: jax.Array) -> jax.Array:
    """Exact ceil(log2(max_val+1)), in integer arithmetic (float log2 is
    off-by-one near powers of two). Returns >= 1."""
    max_val = jnp.asarray(max_val, jnp.uint32)
    width = jnp.int32(1)
    for j in range(1, 32):
        width = width + (max_val >= jnp.uint32(1) << j).astype(jnp.int32)
    return width


def budget_words(n: int, max_width: int = 32) -> int:
    """Static word budget for packing `n` values at up to `max_width` bits."""
    return (n * max_width + 31) // 32


def _width_mask(width: jax.Array) -> jax.Array:
    w = jnp.asarray(width, jnp.int32)
    return jnp.where(
        w >= 32,
        jnp.uint32(0xFFFFFFFF),
        (jnp.uint32(1) << jnp.minimum(w, 31).astype(jnp.uint32)) - jnp.uint32(1),
    )


def pack(
    values: jax.Array, width: jax.Array, *, max_width: int = 32, n_budget_words: int | None = None
) -> PackedInts:
    """Pack uint values at `width` bits each (dynamic) into uint32 words
    (static budget). Values must fit in `width` bits; higher bits dropped.

    Value `i` spans stream bits [i*width, (i+1)*width), which straddle at
    most two uint32 words — so each value contributes exactly two
    scatter-adds (the high one zero when it ends in-word). Bit ranges are
    disjoint across values, so scatter-add == bitwise OR."""
    values = values.astype(jnp.uint32)
    n = values.shape[0]
    nw = budget_words(n, max_width) if n_budget_words is None else n_budget_words
    width = jnp.asarray(width, jnp.int32)
    v = values & _width_mask(width)

    p0 = jnp.arange(n, dtype=jnp.int32) * width
    w0 = p0 >> 5
    off = (p0 & 31).astype(jnp.uint32)
    lo = v << off  # the (32-off) low bits land in word w0; overflow drops
    sh = jnp.where(off == 0, jnp.uint32(1), jnp.uint32(32) - off)
    hi = jnp.where(off == 0, jnp.uint32(0), v >> sh)  # spillover into w0+1
    # two sorted scatter-adds (w0 is non-decreasing since p0 is ascending)
    # instead of one shuffled concat — XLA:TPU walks the word array twice
    # sequentially rather than random-access
    words = (
        jnp.zeros((nw,), jnp.uint32)
        .at[w0]
        .add(lo, mode="drop", indices_are_sorted=True)
        .at[w0 + 1]
        .add(hi, mode="drop", indices_are_sorted=True)
    )
    return PackedInts(words=words, count=jnp.asarray(n, jnp.int32), width=width)


def unpack(packed: PackedInts, n: int) -> jax.Array:
    """Inverse of `pack`; `n` is the static value count (== packing budget)."""
    width = packed.width
    last = packed.words.shape[0] - 1
    p0 = jnp.arange(n, dtype=jnp.int32) * width
    w0 = jnp.clip(p0 >> 5, 0, last)
    off = (p0 & 31).astype(jnp.uint32)
    lo = jnp.take(packed.words, w0, indices_are_sorted=True, mode="clip") >> off
    sh = jnp.where(off == 0, jnp.uint32(1), jnp.uint32(32) - off)
    hi = jnp.where(
        off == 0,
        jnp.uint32(0),
        jnp.take(
            packed.words,
            jnp.clip(w0 + 1, 0, last),
            indices_are_sorted=True,
            mode="clip",
        )
        << sh,
    )
    vals = (lo | hi) & _width_mask(width)
    live_vals = jnp.arange(n, dtype=jnp.int32) < packed.count
    return jnp.where(live_vals, vals, 0)


def wire_bits(packed: PackedInts) -> jax.Array:
    """Meaningful bits on the wire: header (count word + width byte, as in the
    reference's 5-byte header, pytorch/deepreduce.py:216-218) + n*width."""
    return 40 + packed.count * packed.width


def pack3x21(values: jax.Array) -> jax.Array:
    """3 x 21-bit values per int64 word — the reference's special-case
    `pack_` (pytorch/deepreduce.py:165-180, the 'both'-mode mapping packer
    for k < 2^21), bit-exact:

      * values are padded by ``3 - n % 3`` zeros (always >= 1, the
        reference's quirk), so ``nw = n//3 + 1`` data words;
      * grouping is STRIDED thirds (``padded.view(3, -1)``): word j holds
        values (j, j+nw, j+2nw);
      * the FIRST component sits at the high bits:
        ``word = v0 * 2^42 + v1 * 2^21 + v2``;
      * a trailing element carrying ``n`` is appended (word nw).

    Each int64 word is emitted as its little-endian uint32 halves (shape
    [nw+1, 2], column 0 = low half) so the layout survives
    jax_enable_x64=False, where 64-bit lanes silently degrade to 32.

    Wire-format parity shim, not a production path: the 'both' wrapper
    packs mappings with the generic `pack` at ceil(log2 k) bits (denser —
    0.657n vs 0.667n words at width 21, and valid for any k). This exists
    so the reference's exact 3x21 layout (SURVEY.md §2.6) remains
    producible and testable."""
    n = values.shape[0]
    nw = n // 3 + 1  # padding = 3 - n % 3, always at least one zero
    v = jnp.zeros((nw * 3,), jnp.uint32).at[:n].set(values & jnp.uint32((1 << 21) - 1))
    v0, v1, v2 = v.reshape(3, nw)  # strided thirds: word j <- (j, j+nw, j+2nw)
    # word = v0<<42 | v1<<21 | v2, as little-endian uint32 halves
    lo = v2 | (v1 << jnp.uint32(21))  # v2 bits 0..20 | low 11 bits of v1
    hi = (v1 >> jnp.uint32(11)) | (v0 << jnp.uint32(10))  # v1 bits 32..41, v0 42..62
    trailer = jnp.array([[n & 0xFFFFFFFF, n >> 32]], dtype=jnp.uint32)
    return jnp.concatenate([jnp.stack([lo, hi], axis=1), trailer], axis=0)


def unpack3x21(words: jax.Array, n: int) -> jax.Array:
    """Inverse of `pack3x21` (the reference's `unpack_`,
    pytorch/deepreduce.py:183-191). `n` is the static value count; the
    payload's own trailing count element (dynamic) must agree — callers
    outside jit can check ``packed_count3x21``."""
    m21 = jnp.uint32((1 << 21) - 1)
    lo, hi = words[:-1, 0], words[:-1, 1]  # drop the trailing count element
    v2 = lo & m21
    v1 = ((lo >> jnp.uint32(21)) | (hi << jnp.uint32(11))) & m21
    v0 = (hi >> jnp.uint32(10)) & m21
    # strided regrouping: cat([a1, a2, a3])[:n], reference unpack_ order
    return jnp.concatenate([v0, v1, v2])[:n]


def packed_count3x21(words: jax.Array) -> jax.Array:
    """The trailing count element of a `pack3x21` payload (reference
    ``encode[-1]``; low uint32 half — counts here are far below 2^32)."""
    return words[-1, 0].astype(jnp.int32)


def pack_bitmap(bits_u8: jax.Array) -> jax.Array:
    """uint8 0/1 array [m] -> uint32 words [ceil(m/32)], LSB-first (the CuPy
    ``packbits`` role, pytorch/deepreduce.py:446-450)."""
    m = bits_u8.shape[0]
    nw = (m + 31) // 32
    padded = jnp.zeros((nw * 32,), jnp.uint32).at[: m].set(bits_u8.astype(jnp.uint32))
    lanes = padded.reshape(nw, 32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(lanes << shifts[None, :], axis=1).astype(jnp.uint32)


def unpack_bitmap(words: jax.Array, m: int) -> jax.Array:
    """uint32 words -> uint8 0/1 array [m]."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (words[:, None] >> shifts[None, :]) & jnp.uint32(1)
    return bits.reshape(-1)[:m].astype(jnp.uint8)
