"""Codec layer: index and value compressors over `SparseGrad`.

Mirrors the reference's `SparseCompressor` registry
(/root/reference/pytorch/deepreduce.py:913-922) with jit-compatible,
static-shape codecs. Every codec is a pair of pure functions

    encode(sp, *, cfg, ...) -> payload   (pytree of fixed-shape arrays)
    decode(payload, *, cfg) -> SparseGrad-like

plus a `wire_bits(payload)` accounting of meaningful (non-padding) bits on
the wire, the role of GRACE's `tensor_bits` (pytorch/deepreduce.py:93-95).
"""

from deepreduce_tpu.codecs import (
    bloom,
    doubleexp,
    gzip_codec,
    huffman,
    integer,
    packing,
    polyfit,
    polyfit_host,
    polyseg,
    qsgd,
    rle,
)
from deepreduce_tpu.codecs.registry import INDEX_CODECS, VALUE_CODECS, get_codec

__all__ = [
    "bloom",
    "doubleexp",
    "gzip_codec",
    "huffman",
    "integer",
    "packing",
    "polyfit",
    "polyfit_host",
    "polyseg",
    "qsgd",
    "rle",
    "INDEX_CODECS",
    "VALUE_CODECS",
    "get_codec",
]
