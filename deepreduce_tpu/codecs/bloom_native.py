"""Native (C++) bloom index codec — the reference's BloomCPU registry slot.

The reference ships two bloom implementations: the GPU/CuPy one and a
host-library one reachable from the same codec registry
(/root/reference/pytorch/deepreduce.py:696-736 `BloomCPU`, :913-922). Here
the host implementation is `native/deepreduce_native.cc` (the role of the
reference's C++ TF ops bloom_filter_compression.cc) reached through
`jax.pure_callback` with a static wire budget, so it composes with jit and
the allgather like every other codec. This is also the only route to the
P2 `conflict_sets` policy, which is native-only in the reference too
(policies.hpp:43-146; SURVEY.md §2.6).

Wire format is the C++ layer's own: ``[int32 m | int32 h | int32 count |
count x int32 values | m/8 bytes bit-array]`` (bloom_filter_compression.cc:
112-141 shape), padded to the static budget with an in-band byte length.

Production route (round-4): the kernels execute as XLA custom calls
(`native/xla_ops.bloom_compress/bloom_decompress`) INSIDE the jitted
program — the counterpart of the reference loading its ops into the TF
graph (tensorflow/deepreduce.py:328-330) — whenever the CPU FFI registry
is available (`xla_ops.available()`); `jax.pure_callback` remains only as
the fallback for platforms with no host custom-call execution.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepreduce_tpu.codecs import bloom as bloom_jax
from deepreduce_tpu.sparse import SparseGrad


@dataclasses.dataclass(frozen=True)
class BloomNativeMeta:
    k: int
    d: int
    m_bits: int
    num_hash: int
    fpr: float
    policy: str
    budget: int  # selected-index cap (p0: Lemma-6 bound)

    @classmethod
    def create(cls, k: int, d: int, fpr: Optional[float], policy: str) -> "BloomNativeMeta":
        m_bits, num_hash, fpr_eff = bloom_jax.bloom_config(k, d, fpr)
        return cls(
            k=k, d=d, m_bits=m_bits, num_hash=num_hash, fpr=fpr_eff,
            policy=policy, budget=bloom_jax.policy_budget(policy, k, d, fpr_eff),
        )

    @property
    def wire_budget(self) -> int:
        return 12 + self.budget * 4 + self.m_bits // 8


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BloomNativePayload:
    wire: jax.Array  # int8[wire_budget] — C++ wire bytes, zero-padded
    nbytes: jax.Array  # i32[] — live wire length
    values: jax.Array  # f32[budget] — selected values (also inside wire)
    nsel: jax.Array  # i32[] — live selected count


def encode(
    sp: SparseGrad,
    dense: Optional[jax.Array],
    meta: BloomNativeMeta,
    *,
    step: jax.Array = 0,
) -> BloomNativePayload:
    from deepreduce_tpu import native
    from deepreduce_tpu.native import xla_ops

    if dense is None:
        dense = sp.to_dense()

    if xla_ops.available():
        wire, nbytes, values, nsel = xla_ops.bloom_compress(
            dense, sp.indices, sp.nnz, jnp.asarray(step, jnp.int32),
            m_bits=meta.m_bits, num_hash=meta.num_hash,
            policy_id=native.POLICY_IDS[meta.policy],
            select_cap=meta.budget, wire_budget=meta.wire_budget,
        )
        return BloomNativePayload(wire=wire, nbytes=nbytes, values=values, nsel=nsel)

    def host(dense_np, idx_np, nnz_np, step_np):
        idx = np.asarray(idx_np, np.int32)[: int(nnz_np)]
        wire = native.bloom_compress(
            np.asarray(dense_np, np.float32).reshape(-1), idx,
            meta.m_bits, meta.num_hash, meta.policy, int(step_np), meta.budget,
        )
        vals, sel = native.bloom_decompress(
            wire, meta.d, meta.k, meta.policy, int(step_np), meta.budget
        )
        out_wire = np.zeros(meta.wire_budget, np.int8)
        out_wire[: len(wire)] = wire
        out_vals = np.zeros(meta.budget, np.float32)
        out_vals[: len(vals)] = vals
        return out_wire, np.int32(len(wire)), out_vals, np.int32(len(sel))

    wire, nbytes, values, nsel = jax.pure_callback(
        host,
        (
            jax.ShapeDtypeStruct((meta.wire_budget,), jnp.int8),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((meta.budget,), jnp.float32),
            jax.ShapeDtypeStruct((), jnp.int32),
        ),
        dense.reshape(-1), sp.indices, sp.nnz, jnp.asarray(step, jnp.int32),
    )
    return BloomNativePayload(wire=wire, nbytes=nbytes, values=values, nsel=nsel)


def decode(
    payload: BloomNativePayload,
    meta: BloomNativeMeta,
    shape: Tuple[int, ...],
    *,
    step: jax.Array = 0,
) -> SparseGrad:
    from deepreduce_tpu import native
    from deepreduce_tpu.native import xla_ops

    if xla_ops.available():
        vals, idxs, nsel = xla_ops.bloom_decompress(
            payload.wire, payload.nbytes, jnp.asarray(step, jnp.int32),
            d=meta.d, k=meta.k, policy_id=native.POLICY_IDS[meta.policy],
            select_cap=meta.budget,
        )
        return SparseGrad(values=vals, indices=idxs, nnz=nsel, shape=shape)

    def host(wire_np, nbytes_np, step_np):
        wire = np.asarray(wire_np, np.int8)[: int(nbytes_np)]
        vals, idxs = native.bloom_decompress(
            wire, meta.d, meta.k, meta.policy, int(step_np), meta.budget
        )
        out_v = np.zeros(meta.budget, np.float32)
        out_i = np.zeros(meta.budget, np.int32)
        out_v[: len(vals)] = vals
        out_i[: len(idxs)] = idxs
        return out_v, out_i, np.int32(len(idxs))

    vals, idxs, nsel = jax.pure_callback(
        host,
        (
            jax.ShapeDtypeStruct((meta.budget,), jnp.float32),
            jax.ShapeDtypeStruct((meta.budget,), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.int32),
        ),
        payload.wire, payload.nbytes, jnp.asarray(step, jnp.int32),
    )
    return SparseGrad(values=vals, indices=idxs, nnz=nsel, shape=shape)


def wire_bits(payload: BloomNativePayload, meta: BloomNativeMeta) -> jax.Array:
    return payload.nbytes.astype(jnp.float32) * 8.0
