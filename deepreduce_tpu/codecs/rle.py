"""Run-length index codec over the implicit 0/1 bitmap (lossless).

Reference (/root/reference/pytorch/deepreduce.py:808-846): indices are
sorted ascending (values reordered to match — "not order-preserving"), the
d-length bitmap is run-length encoded by a Python loop into alternating
zero-run/one-run lengths starting with a zero-run, then bit-packed.

TPU version: the runs are derived *directly from the sorted indices* — a
one-run starts wherever ``idx[j] != idx[j-1]+1`` — so the d-length bitmap is
never materialized and there is no serial loop. Run count is data-dependent
(≤ 2k+1 incl. the trailing zero-run); the static budget is 2k+2 slots,
bit-packed at the dynamic width of the largest run with an in-band (count,
width) header, exactly the generic-pack discipline of `codecs.packing`.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu.codecs import packing
from deepreduce_tpu.sparse import SparseGrad


@dataclasses.dataclass(frozen=True)
class RLEMeta:
    k: int
    d: int

    @property
    def run_budget(self) -> int:
        return 2 * self.k + 2

    @property
    def max_width(self) -> int:
        return max(1, math.ceil(math.log2(self.d + 1)))


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class RLEPayload:
    values: jax.Array  # f32[k] — values in ascending-index order
    runs: packing.PackedInts
    nnz: jax.Array


def encode(sp: SparseGrad, meta: RLEMeta) -> RLEPayload:
    k, d = meta.k, meta.d
    live = jnp.arange(k, dtype=jnp.int32) < sp.nnz
    # ascending index order, dead slots pushed to the end
    order = jnp.argsort(jnp.where(live, sp.indices, d))
    idx = sp.indices[order]
    vals = jnp.where(live, sp.values[order], 0.0)

    prev = jnp.concatenate([jnp.full((1,), -2, jnp.int32), idx[:-1]])
    run_start = jnp.logical_and(live, idx != prev + 1)
    run_id = jnp.cumsum(run_start.astype(jnp.int32)) - 1  # one-run id per slot
    n_runs = jnp.maximum(jnp.sum(run_start.astype(jnp.int32)), 1)

    ones_len = jax.ops.segment_sum(live.astype(jnp.int32), run_id, num_segments=k)
    starts = (
        jnp.zeros((k,), jnp.int32)
        .at[jnp.where(run_start, run_id, k)]
        .max(jnp.where(run_start, idx, 0), mode="drop")
    )
    ends = starts + ones_len
    prev_end = jnp.concatenate([jnp.zeros((1,), jnp.int32), ends[:-1]])
    zeros_len = starts - prev_end  # zero-run before each one-run

    # interleave [z0, o0, z1, o1, ...] + trailing zero-run
    arr = jnp.zeros((meta.run_budget,), jnp.int32)
    r = jnp.arange(k, dtype=jnp.int32)
    in_use = r < n_runs
    arr = arr.at[jnp.where(in_use, 2 * r, meta.run_budget - 1)].set(
        jnp.where(in_use, zeros_len, 0), mode="drop"
    )
    arr = arr.at[jnp.where(in_use, 2 * r + 1, meta.run_budget - 1)].set(
        jnp.where(in_use, ones_len, 0), mode="drop"
    )
    last_end = ends[n_runs - 1]
    arr = arr.at[2 * n_runs].set(d - last_end)
    count = 2 * n_runs + 1

    width = packing.bits_needed(jnp.max(arr))
    packed = packing.pack(arr.astype(jnp.uint32), width, max_width=meta.max_width)
    packed = packing.PackedInts(words=packed.words, count=count, width=packed.width)
    return RLEPayload(values=vals, runs=packed, nnz=sp.nnz)


def decode(payload: RLEPayload, meta: RLEMeta, shape: Tuple[int, ...]) -> SparseGrad:
    k = meta.k
    arr = packing.unpack(payload.runs, meta.run_budget).astype(jnp.int32)
    n_runs = (payload.runs.count - 1) // 2
    zeros_len = arr[0 : 2 * k : 2][:k]
    ones_len = arr[1 : 2 * k + 1 : 2][:k]
    run_live = jnp.arange(k, dtype=jnp.int32) < n_runs
    ones_len = jnp.where(run_live, ones_len, 0)
    bounds = jnp.cumsum(zeros_len + ones_len)  # global end of each one-run
    starts = bounds - ones_len
    ones_prefix = jnp.cumsum(ones_len)  # slots consumed after each run
    j = jnp.arange(k, dtype=jnp.int32)
    run_of = jnp.searchsorted(ones_prefix, j, side="right").astype(jnp.int32)
    run_of = jnp.clip(run_of, 0, k - 1)
    before = jnp.where(run_of > 0, ones_prefix[jnp.maximum(run_of - 1, 0)], 0)
    idx = starts[run_of] + (j - before)
    live = j < payload.nnz
    return SparseGrad(
        values=jnp.where(live, payload.values, 0.0),
        indices=jnp.where(live, idx, 0).astype(jnp.int32),
        nnz=payload.nnz,
        shape=shape,
    )


def wire_bits(payload: RLEPayload, meta: RLEMeta) -> jax.Array:
    return packing.wire_bits(payload.runs).astype(jnp.float32)
