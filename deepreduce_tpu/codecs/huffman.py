"""Canonical Huffman index codec (host path, lossless, order-preserving).

Reference (/root/reference/pytorch/deepreduce.py:770-802): the int32 byte
stream of the indices is Huffman-coded with a codec *deterministically
rebuilt on both sides* from the byte stream of ``arange(d)`` — no tree is
transmitted. That codec-from-universe trick is the whole design; we keep it.

TPU placement: like the reference's (dahuffman, pure CPU), this is a host
codec — it runs under `jax.pure_callback` with a static output budget and an
in-band byte length, so it composes with jit and the allgather like every
other codec. The coder itself is numpy-vectorized (bit scatter via
repeat/cumsum) rather than dahuffman's per-symbol Python loop; decode walks
the canonical code table.
"""

from __future__ import annotations

import dataclasses
import heapq
from functools import lru_cache
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepreduce_tpu.sparse import SparseGrad


@dataclasses.dataclass(frozen=True)
class HuffmanMeta:
    k: int
    d: int

    @property
    def budget_bytes(self) -> int:
        # int32 stream is 4k bytes; the arange-universe code table is near
        # uniform (max code length ~9 bits), 2x headroom is ample
        return 8 * self.k + 16


def _code_lengths(freqs: np.ndarray) -> np.ndarray:
    """Huffman code length per byte symbol (0 for absent symbols)."""
    heap = [(int(f), s, s) for s, f in enumerate(freqs) if f > 0]
    if len(heap) == 1:
        lengths = np.zeros(256, np.int64)
        lengths[heap[0][1]] = 1
        return lengths
    heapq.heapify(heap)
    parent: dict = {}
    nxt = 256
    while len(heap) > 1:
        f1, t1, n1 = heapq.heappop(heap)
        f2, t2, n2 = heapq.heappop(heap)
        parent[n1] = nxt
        parent[n2] = nxt
        heapq.heappush(heap, (f1 + f2, min(t1, t2), nxt))
        nxt += 1
    lengths = np.zeros(256, np.int64)
    for s in range(256):
        if freqs[s] > 0:
            depth, node = 0, s
            while node in parent:
                node = parent[node]
                depth += 1
            lengths[s] = depth
    return lengths


@lru_cache(maxsize=64)
def _universe_codec(d: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(lengths[256], codes[256], decode_order) from the byte frequencies of
    the int32 stream of arange(d) — identical on every worker
    (pytorch/deepreduce.py:778-781)."""
    universe = np.arange(d, dtype="<i4").tobytes()
    freqs = np.bincount(np.frombuffer(universe, np.uint8), minlength=256)
    lengths = _code_lengths(freqs)
    # canonical assignment: sort by (length, symbol)
    order = np.lexsort((np.arange(256), np.where(lengths > 0, lengths, 999)))
    codes = np.zeros(256, np.uint64)
    code = 0
    prev_len = 0
    for s in order:
        length = lengths[s]
        if length == 0:
            continue
        code <<= int(length - prev_len)
        codes[s] = code
        code += 1
        prev_len = length
    return lengths, codes, order


def _encode_host(idx_bytes: np.ndarray, d: int, budget: int) -> Tuple[np.ndarray, np.ndarray]:
    lengths, codes, _ = _universe_codec(d)
    lens = lengths[idx_bytes]
    total = int(lens.sum())
    if (total + 7) // 8 > budget:
        raise ValueError("huffman payload exceeds static budget")
    max_len = int(lens.max()) if lens.size else 1
    # MSB-first bits of each code in a [n, max_len] lane grid; symbol i's
    # valid bits are the last `lens[i]` lanes (lane m holds bit
    # (code >> (max_len-1-m)) & 1, so the MSB sits at lane max_len-lens[i])
    shifts = np.arange(max_len - 1, -1, -1, dtype=np.uint64)
    bits_mat = (codes[idx_bytes][:, None] >> shifts[None, :]) & 1
    lane = np.arange(max_len)[None, :]
    valid = lane >= (max_len - lens[:, None])
    flat_bits = bits_mat[valid].astype(np.uint8)
    stream = np.packbits(flat_bits)
    out = np.zeros(budget, np.uint8)
    out[: stream.size] = stream
    return out, np.int32(total)


@lru_cache(maxsize=64)
def _decode_lut(d: int) -> Tuple[np.ndarray, np.ndarray, int]:
    """(symbol[2^L], length[2^L], L): every L-bit window resolves its first
    codeword in one lookup — L = max code length, ~9 bits for the
    arange-universe codec, so the table is tiny."""
    lengths, codes, _ = _universe_codec(d)
    max_len = int(lengths.max())
    lut_sym = np.zeros(1 << max_len, np.uint8)
    lut_len = np.ones(1 << max_len, np.int64)
    for s in range(256):
        length = int(lengths[s])
        if length == 0:
            continue
        lo = int(codes[s]) << (max_len - length)
        hi = (int(codes[s]) + 1) << (max_len - length)
        lut_sym[lo:hi] = s
        lut_len[lo:hi] = length
    return lut_sym, lut_len, max_len


def _decode_host(stream: np.ndarray, nbits: int, n_syms: int, d: int) -> np.ndarray:
    """LUT decode, fully vectorized via binary lifting (the round-2 version
    walked the canonical tables bit by bit in Python; round 3's first cut
    kept a per-symbol Python loop — still ~1.6M iterations at ResNet-50
    scale). Every bit position's successor is `pos + codeword_length(pos)`;
    decoding is the orbit of position 0 under that successor map. Doubling
    the known prefix of the orbit log2(n_syms) times extracts all symbol
    boundaries with O(n log n) numpy gathers and no Python-per-symbol work."""
    if n_syms == 0:
        return np.zeros(0, np.uint8)
    lut_sym, lut_len, max_len = _decode_lut(d)
    bits = np.unpackbits(stream)[:nbits]
    padded = np.concatenate([bits, np.zeros(max_len, np.uint8)])
    # window ints by max_len shifted adds — O(nbits) memory (a 2D
    # sliding-window matrix would transiently be ~max_len*8 bytes/bit)
    n = max(nbits, 1)
    windows = np.zeros(n, np.int32)
    for i in range(max_len):
        windows += padded[i : i + n].astype(np.int32) << (max_len - 1 - i)
    # successor map over bit positions; orbit positions past nbits park on a
    # self-loop sentinel slot n so doubling never reads out of range
    nxt = np.full(n + 1, n, np.int64)
    nxt[:n] = np.minimum(np.arange(n, dtype=np.int64) + lut_len[windows], n)
    # orbit-prefix doubling: `orbit` holds positions after 0..len-1 symbols;
    # jump[p] = position `len` symbols after p, squared each round
    orbit = np.zeros(1, np.int64)
    jump = nxt
    while orbit.size < n_syms:
        orbit = np.concatenate([orbit, jump[orbit]])
        if orbit.size < n_syms:
            jump = jump[jump]
    orbit = orbit[:n_syms]
    # a start position landing on the sentinel means the stream ran out
    # before all symbols decoded (truncated/corrupt payload, or the sides
    # disagree on d) — fail loudly like the per-symbol loop's IndexError did
    if int(orbit[-1]) >= n:
        raise ValueError("huffman stream exhausted before all symbols decoded")
    return lut_sym[windows[orbit]]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class HuffmanPayload:
    values: jax.Array  # f32[k] — untouched (order-preserving)
    stream: jax.Array  # uint8[budget]
    nbits: jax.Array  # i64[]
    nnz: jax.Array


def encode(sp: SparseGrad, meta: HuffmanMeta) -> HuffmanPayload:
    def host(idx: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        raw = np.frombuffer(np.ascontiguousarray(idx.astype("<i4")).tobytes(), np.uint8)
        return _encode_host(raw, meta.d, meta.budget_bytes)

    stream, nbits = jax.pure_callback(
        host,
        (
            jax.ShapeDtypeStruct((meta.budget_bytes,), jnp.uint8),
            jax.ShapeDtypeStruct((), jnp.int32),
        ),
        sp.indices,
    )
    return HuffmanPayload(values=sp.values, stream=stream, nbits=nbits, nnz=sp.nnz)


def decode(payload: HuffmanPayload, meta: HuffmanMeta, shape: Tuple[int, ...]) -> SparseGrad:
    def host(stream: np.ndarray, nbits: np.ndarray) -> np.ndarray:
        raw = _decode_host(stream, int(nbits), 4 * meta.k, meta.d)
        return np.frombuffer(raw.tobytes(), "<i4").astype(np.int32)

    idx = jax.pure_callback(
        host, jax.ShapeDtypeStruct((meta.k,), jnp.int32), payload.stream, payload.nbits
    )
    return SparseGrad(values=payload.values, indices=idx, nnz=payload.nnz, shape=shape)


def wire_bits(payload: HuffmanPayload, meta: HuffmanMeta) -> jax.Array:
    return payload.nbits.astype(jnp.float32) + 64
