"""Count-sketch value codec (summable, lossy) — S2 Reducer style.

A count sketch is a ``[rows, cols]`` f32 table; coordinate ``i`` with value
``v`` contributes ``s_r(i) * v`` to bucket ``h_r(i)`` of every row ``r``.
Reading a coordinate back takes the median across rows of
``sketch[r, h_r(i)] * s_r(i)`` — an unbiased estimate whose error is
bounded by the L2 mass of the colliding coordinates (O(||g||_2/sqrt(cols))
per row, median-of-rows sharpens the tail).

What makes it worth a codec slot: sketches are **linear**. The sum of W
workers' sketches is the sketch of the summed gradient, so the aggregate
can be formed by a single `psum` *inside the collective* and decoded once
per worker — no per-worker payload decode, unlike every other value codec
here. The sparse_rs ``rs_mode="sketch"`` route and the registry
`CountSketchCodec` both build on the primitives in this module.

Hashing is pairwise-independent-enough multiplicative hashing with static
odd constants derived arithmetically from (seed, row) — trace-time
constants, no host entropy, no data-dependent Python branching (this file
is in the AST-lint traced/codec scope).
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu.sparse import SparseGrad

# Knuth/Murmur-style odd mixing constants; odd * odd stays odd mod 2^32,
# so every derived multiplier is a bijection on u32 before the shift.
_PHI32 = 0x9E3779B1
_MURMUR32 = 0x85EBCA77


def row_constants(rows: int, seed: int = 0) -> List[Tuple[int, int]]:
    """Static (bucket, sign) multipliers for each sketch row."""
    out = []
    for r in range(rows):
        odd = 2 * (seed + r) + 1
        out.append(((_PHI32 * odd) & 0xFFFFFFFF, (_MURMUR32 * odd) & 0xFFFFFFFF))
    return out


def _bucket(idx_u32: jax.Array, mult: int, cols: int) -> jax.Array:
    return (((idx_u32 * jnp.uint32(mult)) >> jnp.uint32(16)) % jnp.uint32(cols)).astype(
        jnp.int32
    )


def _sign(idx_u32: jax.Array, mult: int) -> jax.Array:
    return 1.0 - 2.0 * ((idx_u32 * jnp.uint32(mult)) >> jnp.uint32(31)).astype(
        jnp.float32
    )


def sketch_from_sparse(
    values: jax.Array,
    indices: jax.Array,
    rows: int,
    cols: int,
    *,
    seed: int = 0,
) -> jax.Array:
    """Sketch a k-sparse vector: rows scatter-adds of k entries each —
    O(k * rows), never O(d). Dead slots (padded entries) must carry value
    0.0 so they contribute nothing."""
    u = indices.astype(jnp.uint32)
    planes = []
    for a_mult, b_mult in row_constants(rows, seed):
        plane = jnp.zeros((cols,), jnp.float32).at[_bucket(u, a_mult, cols)].add(
            values * _sign(u, b_mult)
        )
        planes.append(plane)
    return jnp.stack(planes)


def _median_rows(stacked: jax.Array) -> jax.Array:
    """Median over axis 0 with static row count (odd: middle element;
    even: mean of the middle two) — no data-dependent branching."""
    rows = stacked.shape[0]
    srt = jnp.sort(stacked, axis=0)
    return 0.5 * (srt[(rows - 1) // 2] + srt[rows // 2])


def unsketch_at(sketch: jax.Array, indices: jax.Array, *, seed: int = 0) -> jax.Array:
    """Median-of-rows point queries at `indices` — O(len(indices) * rows)
    gathers from the (cache-resident) sketch table."""
    rows, cols = sketch.shape
    u = indices.astype(jnp.uint32)
    ests = []
    for r, (a_mult, b_mult) in enumerate(row_constants(rows, seed)):
        ests.append(sketch[r, _bucket(u, a_mult, cols)] * _sign(u, b_mult))
    return _median_rows(jnp.stack(ests))


@dataclasses.dataclass(frozen=True)
class CountSketchMeta:
    k: int
    rows: int = 5
    cols: int = 2048
    seed: int = 0

    @property
    def table_size(self) -> int:
        return self.rows * self.cols


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CountSketchPayload:
    sketch: jax.Array  # f32[rows, cols] — linear: payloads sum coordinate-wise
    indices: jax.Array  # i32[k] — selection passed through (order-preserving)
    nnz: jax.Array


def encode(sp: SparseGrad, meta: CountSketchMeta) -> CountSketchPayload:
    live = jnp.arange(meta.k, dtype=jnp.int32) < sp.nnz
    vals = jnp.where(live, sp.values, 0.0)
    sk = sketch_from_sparse(vals, sp.indices, meta.rows, meta.cols, seed=meta.seed)
    return CountSketchPayload(sketch=sk, indices=sp.indices, nnz=sp.nnz)


def decode(
    payload: CountSketchPayload, meta: CountSketchMeta, shape: Tuple[int, ...]
) -> SparseGrad:
    est = unsketch_at(payload.sketch, payload.indices, seed=meta.seed)
    live = jnp.arange(meta.k, dtype=jnp.int32) < payload.nnz
    vals = jnp.where(live, est, 0.0)
    return SparseGrad(values=vals, indices=payload.indices, nnz=payload.nnz, shape=shape)


def wire_bits(payload: CountSketchPayload, meta: CountSketchMeta) -> jax.Array:
    """The whole f32 table goes on the wire regardless of nnz — that is the
    price of summability (and why cols should be sized ~2k/rows)."""
    return jnp.asarray(meta.table_size, jnp.float32) * 32
