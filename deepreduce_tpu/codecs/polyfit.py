"""Segmented polynomial curve-fit value codec (PolyFit).

Reference (/root/reference/pytorch/deepreduce.py:305-425): sort kept values
descending, split into geometric segments whose sizes derive from ``(N,
num_pos)`` — ratios {1/5 … 1/100000} gated at >30 elements, split at the
positive/negative boundary (get_segments :362-377) — then per-segment
degree-5 least squares in float64 with a CPU matrix inverse
(LeastSquares :326-338). Only the coefficients and the value-sorted indices
cross the wire; the receiver re-derives the segment structure from
``(N, num_pos)`` and evaluates (:411-425).

TPU-first redesign (same wire semantics, static shapes, no f64):

- The segment *count* is fixed at ``2·len(ratios) + 2``; inactive segments
  have zero length. Segment sizes stay a traced function of the traced
  ``num_pos``, so per-worker structure still differs (the reason the
  reference sets ``tensors_size_are_same=False`` :364-367) while every array
  shape is static.
- One masked pass builds all normal equations at once: per-element Legendre
  basis rows + `segment_sum` -> [S, 6, 6] systems, batched `linalg.solve`.
  No CPU round-trip (the reference's :330-334 workaround), no f64: fitting
  in a shifted-Legendre basis on the normalized segment domain keeps the
  normal matrix near-orthogonal (condition O(10) instead of the Vandermonde
  ~1e7), which is what made the reference need float64 in the first place.
- Coefficients travel as f32 (the reference sends f64 — half the bits for
  the same fitted curve within f32 noise).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu.sparse import SparseGrad

RATIOS = (1 / 5, 1 / 10, 1 / 30, 1 / 100, 1 / 300, 1 / 1000, 1 / 3000, 1 / 10000, 1 / 30000, 1 / 100000)
MIN_SEGMENT = 30  # reference's >30 gate (pytorch/deepreduce.py:371-374)


@dataclasses.dataclass(frozen=True)
class PolyFitMeta:
    k: int
    degree: int = 5  # params['poly_degree'] default (pytorch/deepreduce.py:385)
    sort: bool = False  # params['sort']: True = values arrive pre-ordered

    @property
    def num_segments(self) -> int:
        return 2 * len(RATIOS) + 2


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PolyFitPayload:
    coeffs: jax.Array  # f32[S, degree+1], Legendre basis per segment
    num_pos: jax.Array  # i32[] — the receiver's key to the segment structure
    indices: jax.Array  # i32[k] — indices in value-sorted order (the mapping)


def segment_sizes(k: int, num_pos: jax.Array) -> jax.Array:
    """i32[S] segment lengths along the descending-sorted value curve:
    fine→coarse positive segments, positive remainder, negative remainder,
    coarse→fine negative segments (get_segments, pytorch/deepreduce.py:362-377).
    Inactive ratio slots are zero-length."""
    num_pos = jnp.asarray(num_pos, jnp.int32)
    num_neg = jnp.int32(k) - num_pos
    r = jnp.asarray(RATIOS, jnp.float32)
    pos = jnp.floor(num_pos.astype(jnp.float32) * r).astype(jnp.int32)
    neg = jnp.floor(num_neg.astype(jnp.float32) * r).astype(jnp.int32)
    pos = jnp.where(pos > MIN_SEGMENT, pos, 0)
    neg = jnp.where(neg > MIN_SEGMENT, neg, 0)
    rem_pos = num_pos - jnp.sum(pos)
    rem_neg = num_neg - jnp.sum(neg)
    return jnp.concatenate([pos[::-1], rem_pos[None], rem_neg[None], neg])


def _boundaries(sizes: jax.Array) -> jax.Array:
    return jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(sizes)])


def _legendre_basis(t: jax.Array, degree: int) -> jax.Array:
    """Shifted-Legendre rows P_0..P_degree at t in [-1, 1]; shape [..., degree+1]."""
    cols = [jnp.ones_like(t), t]
    for m in range(1, degree):
        cols.append(((2 * m + 1) * t * cols[m] - m * cols[m - 1]) / (m + 1))
    return jnp.stack(cols[: degree + 1], axis=-1)


def _element_basis(k: int, sizes: jax.Array, degree: int) -> Tuple[jax.Array, jax.Array]:
    """Per sorted position i: its segment id and Legendre basis row, from the
    traced segment sizes. x_local = 1..n within the segment (the reference's
    1-based arange, GetInputMatrix_Polynomial :313), normalized to (-1, 1]."""
    bounds = _boundaries(sizes)
    i = jnp.arange(k, dtype=jnp.int32)
    seg_id = jnp.searchsorted(bounds[1:], i, side="right").astype(jnp.int32)
    seg_id = jnp.clip(seg_id, 0, sizes.shape[0] - 1)
    start = bounds[seg_id]
    n = jnp.maximum(sizes[seg_id], 1)
    x_local = (i - start + 1).astype(jnp.float32)
    t = 2.0 * x_local / n.astype(jnp.float32) - 1.0
    return seg_id, _legendre_basis(t, degree)


def encode(sp: SparseGrad, meta: PolyFitMeta) -> PolyFitPayload:
    """Sort descending (recording the mapping), fit every segment in one
    masked batched solve (pytorch/deepreduce.py:382-409 semantics)."""
    vals, idxs = sp.values, sp.indices
    if not meta.sort:
        order = jnp.argsort(-vals)
        vals = vals[order]
        idxs = idxs[order]
    num_pos = jnp.sum((vals > 0.0).astype(jnp.int32))

    sizes = segment_sizes(meta.k, num_pos)
    seg_id, phi = _element_basis(meta.k, sizes, meta.degree)

    s = meta.num_segments
    outer = phi[:, :, None] * phi[:, None, :]  # [k, p, p]
    a = jax.ops.segment_sum(outer, seg_id, num_segments=s)  # [S, p, p]
    b = jax.ops.segment_sum(phi * vals[:, None], seg_id, num_segments=s)  # [S, p]
    # Tikhonov jitter keeps zero-length segments solvable (coeffs ~ 0, never
    # evaluated) without perturbing active ones.
    p = meta.degree + 1
    eye = jnp.eye(p, dtype=jnp.float32)
    tr = jnp.trace(a, axis1=-2, axis2=-1)[:, None, None]
    coeffs = jnp.linalg.solve(a + (1e-6 * tr / p + 1e-12) * eye, b[..., None])[..., 0]
    return PolyFitPayload(coeffs=coeffs, num_pos=num_pos, indices=idxs.astype(jnp.int32))


def decode(payload: PolyFitPayload, meta: PolyFitMeta, shape: Tuple[int, ...]) -> SparseGrad:
    """Re-derive segments from (k, num_pos), evaluate the per-segment
    polynomials (pytorch/deepreduce.py:411-425)."""
    sizes = segment_sizes(meta.k, payload.num_pos)
    seg_id, phi = _element_basis(meta.k, sizes, meta.degree)
    vals = jnp.sum(phi * payload.coeffs[seg_id], axis=-1)
    return SparseGrad(
        values=vals.astype(jnp.float32),
        indices=payload.indices,
        nnz=jnp.asarray(meta.k, jnp.int32),
        shape=shape,
    )


def wire_bits(payload: PolyFitPayload, meta: PolyFitMeta) -> jax.Array:
    """Only active segments' coefficients count (+32 for num_pos, the
    reference's appended coefficient :405); the [S, p] buffer is padding."""
    sizes = segment_sizes(meta.k, payload.num_pos)
    active = jnp.sum((sizes > 0).astype(jnp.float32))
    return active * (meta.degree + 1) * 32 + 32
