"""Delta + bit-packed integer index codec — the FastPFor role.

Reference (/root/reference/tensorflow/integer_compression.cc): sorted uint32
index arrays run through a FastPFor codec chosen by string attr (delta/PFor/
VByte family), exposed as standalone TF CPU ops. Here the jit path uses
delta coding plus the dynamic-width static-budget bit packer
(`codecs.packing`) — the same wire idea as FastPFor's FBP (frame bit
packing) without patched exceptions, chosen because exception patching is
data-dependent control flow XLA can't tile. The C++ native layer
(`deepreduce_tpu.native`) provides a byte-exact host implementation of this
format plus a varint variant, standing in for the vendored FastPFor.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu import sparse
from deepreduce_tpu.codecs import packing
from deepreduce_tpu.sparse import SparseGrad


@dataclasses.dataclass(frozen=True)
class IntegerMeta:
    k: int
    d: int

    @property
    def max_width(self) -> int:
        return max(1, math.ceil(math.log2(self.d + 1)))


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class IntegerPayload:
    values: jax.Array  # f32[k] — values in ascending-index order
    deltas: packing.PackedInts
    nnz: jax.Array


def encode(sp: SparseGrad, meta: IntegerMeta) -> IntegerPayload:
    k, d = meta.k, meta.d
    live = jnp.arange(k, dtype=jnp.int32) < sp.nnz
    order = jnp.argsort(jnp.where(live, sp.indices, d))
    idx = jnp.where(live, sp.indices[order], 0)
    vals = jnp.where(live, sp.values[order], 0.0)
    prev = jnp.concatenate([jnp.zeros((1,), jnp.int32), idx[:-1]])
    deltas = jnp.where(live, idx - prev, 0)  # first delta = absolute index
    width = packing.bits_needed(jnp.max(deltas))
    packed = packing.pack(deltas.astype(jnp.uint32), width, max_width=meta.max_width)
    packed = packing.PackedInts(words=packed.words, count=sp.nnz, width=packed.width)
    return IntegerPayload(values=vals, deltas=packed, nnz=sp.nnz)


def decode(payload: IntegerPayload, meta: IntegerMeta, shape: Tuple[int, ...]) -> SparseGrad:
    deltas = packing.unpack(payload.deltas, meta.k).astype(jnp.int32)
    idx = jnp.cumsum(deltas)
    live = jnp.arange(meta.k, dtype=jnp.int32) < payload.nnz
    return SparseGrad(
        values=jnp.where(live, payload.values, 0.0),
        indices=jnp.where(live, idx, 0).astype(jnp.int32),
        nnz=payload.nnz,
        shape=shape,
    )


def decode_dense(
    payload: IntegerPayload,
    meta: IntegerMeta,
    shape: Tuple[int, ...],
    *,
    values: Optional[jax.Array] = None,
) -> jax.Array:
    """Straight-to-dense decode — the TPU fast path the wrapper prefers.

    Encode sorts ascending and deltas are zero past nnz, so the cumsum's
    live prefix is strictly increasing and the dead tail parks at distinct
    out-of-range targets: the scatter carries both the unique-indices and
    sorted promises (sequential HBM walk instead of random access).
    `values` overrides the payload's value stream ('both' mode passes the
    value-codec output, already in ascending-index order)."""
    k, d = meta.k, meta.d
    deltas = packing.unpack(payload.deltas, k).astype(jnp.int32)
    idx = jnp.clip(jnp.cumsum(deltas), 0, d - 1)
    vals = payload.values if values is None else values
    n_v = vals.shape[0]
    vals = sparse.fit_length(vals, k)
    nnz = jnp.minimum(payload.nnz, jnp.asarray(min(k, n_v), jnp.int32))
    return sparse.scatter_ascending(vals, idx, nnz, d).reshape(shape)


def wire_bits(payload: IntegerPayload, meta: IntegerMeta) -> jax.Array:
    return packing.wire_bits(payload.deltas).astype(jnp.float32)
