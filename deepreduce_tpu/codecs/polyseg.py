"""PolySeg value codec: whole-layer sort + searched segment fit.

Reference parity: TF `PolySegCompressor`
(/root/reference/tensorflow/deepreduce.py:445-557): sort the layer's values,
embed signs in the indices as ``(idx+1)*sign`` (:474-478), split the sorted
curve into a few segments and least-squares fit each; transmit segment
sizes + coefficients + indices. The reference defaults to *hard-coded*
per-model breakpoint tables keyed by layer size (get_breaks :182-219) and
ships an unused dynamic `find_breaks` (:167-180).

TPU redesign: the dynamic knot search is the default and runs in-graph —
``num_segments-1`` iterations of a masked argmax of |curve - chord| over the
remaining suffix (static shapes; the reference's TF loop does the same
eagerly). Segment fitting reuses the masked Legendre segment-LS machinery
from `codecs.polyfit` (one batched solve, f32, no f64 and no per-segment
Python loop). Breaks are transmitted (i32[S+1]) like the reference's sizes
vector; static per-layer segment count defaults to the reference's scale
(~log10 N, 2..5) and can be pinned via ``params['num_segments']``."""

from __future__ import annotations

import dataclasses
import math
from typing import Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu.codecs import polyfit as _pf
from deepreduce_tpu.sparse import SparseGrad


def default_num_segments(n: int) -> int:
    """2..5 segments growing with layer size — the shape of the reference's
    per-model tables (get_num_of_segments :244-253)."""
    return max(2, min(5, int(math.log10(max(n, 10)))))


@dataclasses.dataclass(frozen=True)
class PolySegMeta:
    k: int
    degree: int = 5
    num_segments: int = 0  # 0 = derive from k

    @property
    def segments(self) -> int:
        return self.num_segments or default_num_segments(self.k)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PolySegPayload:
    coeffs: jax.Array  # f32[S, degree+1]
    breaks: jax.Array  # i32[S+1] — transmitted sizes vector role (:511)
    signed_indices: jax.Array  # i32[k] — (idx+1)*sign, descending-|value| order


def find_breaks(y: jax.Array, num_segments: int) -> jax.Array:
    """In-graph max-distance-from-chord knot search (reference find_breaks
    :167-180): each iteration splits the remaining suffix at the point
    farthest from the chord. Returns ascending breaks i32[S+1] incl. 0, k."""
    k = y.shape[0]
    i = jnp.arange(k, dtype=jnp.float32)
    breaks = [jnp.int32(0)]
    b = jnp.int32(0)
    for _ in range(num_segments - 1):
        y_b = y[b]
        span = jnp.maximum(jnp.float32(k - 1) - b.astype(jnp.float32), 1.0)
        line = y_b + (y[-1] - y_b) * (i - b.astype(jnp.float32)) / span
        dist = jnp.where(i >= b.astype(jnp.float32), jnp.abs(line - y), -1.0)
        b = jnp.argmax(dist).astype(jnp.int32)
        breaks.append(b)
    breaks.append(jnp.int32(k))
    out = jnp.sort(jnp.stack(breaks))
    return out


def encode(sp: SparseGrad, meta: PolySegMeta) -> PolySegPayload:
    mags = jnp.abs(sp.values)
    order = jnp.argsort(-mags)  # descending |value|, whole-layer sort mode
    y = mags[order]
    signed = ((sp.indices[order] + 1) * jnp.sign(sp.values[order])).astype(jnp.int32)
    signed = jnp.where(signed == 0, sp.indices[order] + 1, signed)

    s = meta.segments
    breaks = find_breaks(y, s)
    sizes = jnp.diff(breaks)
    seg_id, phi = _pf._element_basis(meta.k, sizes, meta.degree)
    p = meta.degree + 1
    a = jax.ops.segment_sum(phi[:, :, None] * phi[:, None, :], seg_id, num_segments=s)
    b = jax.ops.segment_sum(phi * y[:, None], seg_id, num_segments=s)
    eye = jnp.eye(p, dtype=jnp.float32)
    tr = jnp.trace(a, axis1=-2, axis2=-1)[:, None, None]
    coeffs = jnp.linalg.solve(a + (1e-6 * tr / p + 1e-12) * eye, b[..., None])[..., 0]
    return PolySegPayload(coeffs=coeffs, breaks=breaks.astype(jnp.int32), signed_indices=signed)


def decode(payload: PolySegPayload, meta: PolySegMeta, shape: Tuple[int, ...]) -> SparseGrad:
    sizes = jnp.diff(payload.breaks)
    seg_id, phi = _pf._element_basis(meta.k, sizes, meta.degree)
    y = jnp.sum(phi * payload.coeffs[seg_id], axis=-1)
    sign = jnp.sign(payload.signed_indices).astype(jnp.float32)
    idxs = (jnp.abs(payload.signed_indices) - 1).astype(jnp.int32)
    return SparseGrad(
        values=y * sign,
        indices=jnp.maximum(idxs, 0),
        nnz=jnp.asarray(meta.k, jnp.int32),
        shape=shape,
    )


def wire_bits(payload: PolySegPayload, meta: PolySegMeta) -> jax.Array:
    s = meta.segments
    return jnp.asarray(s * (meta.degree + 1) * 32 + (s + 1) * 32, jnp.float32)
