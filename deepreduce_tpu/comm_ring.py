"""Overlapped ring-decode exchange for the fused communicator hot path.

The allgather-shaped fused exchange (`comm.py:_exchange_fused`) realizes
`allgather -> per-worker decompress -> aggregate` as one bulk collective
followed by a *sequential* decode loop: communication fully completes
before any decode starts, and the O(W·d) decode work sits undivided on the
step critical path. SparCML (arXiv:1802.08021) and Ok-Topk's sparse
allreduce (arXiv:2201.07598) both get their wins by hiding the gather
behind per-chunk decode/reduce; this module is that shape for the fused
uint8 payload buffer.

Structure: W-1 `lax.ppermute` hops around the mesh axis, double-buffered.
Each round issues the permute of the *next* chunk before decoding the one
in hand, so XLA can overlap the ICI transfer with the decode+accumulate
compute (the transfer has no data dependence on the decode, and the async
collective start/done pair brackets the decode program). Round 0 decodes
the worker's own payload — which is exactly the decode residual error
feedback needs — so the own-payload decode falls out for free instead of
costing a separate traced program or an in-loop select.

Wire accounting: every worker forwards the B-byte fused buffer W-1 times,
i.e. per-worker wire bytes are (W-1)·B — the (W-1)/W fraction of the total
gathered volume W·B (`metrics.ring_wire_bytes`). The bulk all_gather's
*logical* per-worker injection is B; its physical ring implementation moves
the same (W-1)·B, but XLA owns that schedule — here the hops are explicit,
so `GradientExchanger.payload_bytes` reports them explicitly.

Numerics: each worker accumulates chunks in its own ring order
(me, me-1, ..., me-W+1 mod W), so aggregates agree across strategies and
across workers only up to f32 sum associativity — an order-insensitive sum,
not a bitwise-replicated one. See ARCHITECTURE.md "Decode strategies".
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu.telemetry import spans


def _tree_add(a, b):
    return tuple(x + y for x, y in zip(a, b))


def ring_decode_exchange(
    buf: jax.Array,
    decode_row: Callable[[jax.Array], Tuple[jax.Array, ...]],
    *,
    axis_name: str,
    num_workers: int,
    need_own: bool,
    row_weights: Optional[jax.Array] = None,
) -> Tuple[Tuple[jax.Array, ...], Tuple[jax.Array, ...]]:
    """Ring-exchange the fused uint8 payload `buf` over `axis_name`,
    decoding and accumulating each arriving chunk.

    `decode_row` maps one worker's uint8[B] buffer to a tuple of dense f32
    leaves (the per-tensor decodes). Returns `(total, own)`: the elementwise
    sum of all W workers' decodes, and the own-payload decode (empty tuple
    when `need_own` is False — it is still computed, as round 0 of the sum).

    `row_weights` (f32[W], replicated, or None) is the participation mask:
    the chunk arriving at round r originated at worker (me - r) mod W, and
    its decode is scaled by that worker's weight before accumulation. The
    own chunk is round 0, so a masked-out worker's own decode is zeroed —
    exactly what residual error feedback needs to retain its un-sent mass.
    When None, the traced program is unchanged.

    `num_workers` must be the concrete mesh-axis size (ppermute needs a
    static permutation).
    """
    W = int(num_workers)
    if row_weights is not None:
        widx = jax.lax.axis_index(axis_name)

        def weight(decs, r):
            src = jnp.remainder(widx - r, W)  # who round r's chunk came from
            wgt = jax.lax.dynamic_index_in_dim(row_weights, src, keepdims=False)
            return tuple(d * wgt for d in decs)

    with spans.span("exchange/ring"):
        own = decode_row(buf)
        if row_weights is not None:
            own = weight(own, 0)
        if W == 1:
            return own, (own if need_own else ())

        perm = [(j, (j + 1) % W) for j in range(W)]
        send = lambda x: jax.lax.ppermute(x, axis_name, perm)

        # prologue: hop 1 departs while the own payload decodes
        nxt = send(buf)
        acc = own

        # rounds 1 .. W-2: issue hop i+1, then decode the chunk from round
        # i. The permute is issued first so its transfer has no dependence
        # on the decode program and can run concurrently with it. The mask
        # weighting stays behind the None-gate so the mask-free trace is
        # byte-identical to pre-resilience builds (no dead round-index
        # arithmetic in the loop body).
        def body(i, carry):
            acc, cur = carry
            nxt = send(cur)
            decs = decode_row(cur)
            if row_weights is not None:
                decs = weight(decs, i + 1)
            acc = _tree_add(acc, decs)
            return acc, nxt

        acc, last = jax.lax.fori_loop(0, W - 2, body, (acc, nxt))
        # epilogue: the final chunk has nothing left to forward
        last_decs = decode_row(last)
        if row_weights is not None:
            last_decs = weight(last_decs, W - 1)
        acc = _tree_add(acc, last_decs)
    return acc, (own if need_own else ())
