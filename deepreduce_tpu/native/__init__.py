"""ctypes bindings for the native host-path codec library.

The reference loads its C++ ops via TF `load_op_library`
(tensorflow/deepreduce.py:328-330); here the shared library is built with
the in-tree Makefile (g++, no external deps) on first import and bound via
ctypes. The C++ bloom filter uses the SAME hash mix as the JAX codec, so
`tests/test_native.py` cross-checks bitmaps bit-for-bit between the two
implementations — the cross-implementation golden tests SURVEY.md §4 calls
for.
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess
from typing import Optional, Tuple

import numpy as np

_DIR = pathlib.Path(__file__).parent
_LIB_PATH = _DIR / "libdeepreduce_native.so"

POLICY_IDS = {"leftmost": 0, "random": 1, "conflict_sets": 2, "p0": 3, "policy_zero": 3}

_lib: Optional[ctypes.CDLL] = None


def _build() -> None:
    subprocess.run(["make", "-s", "-C", str(_DIR)], check=True)


def load() -> ctypes.CDLL:
    """Build (if needed) and load the native library."""
    global _lib
    if _lib is not None:
        return _lib
    if not _LIB_PATH.exists() or _LIB_PATH.stat().st_mtime < (
        _DIR / "deepreduce_native.cc"
    ).stat().st_mtime:
        _build()
    lib = ctypes.CDLL(str(_LIB_PATH))
    i8p = ctypes.POINTER(ctypes.c_int8)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    f32p = ctypes.POINTER(ctypes.c_float)
    i32, i64, u32 = ctypes.c_int32, ctypes.c_int64, ctypes.c_uint32

    lib.drn_fmix32.restype = u32
    lib.drn_fmix32.argtypes = [u32]
    lib.drn_bloom_insert.restype = None
    lib.drn_bloom_insert.argtypes = [i32p, i32, i32, i32, u8p]
    lib.drn_bloom_query_universe.restype = i32
    lib.drn_bloom_query_universe.argtypes = [u8p, i32, i32, i32, u8p]
    lib.drn_select_leftmost.restype = i32
    lib.drn_select_leftmost.argtypes = [u8p, i32, i32, i32p]
    lib.drn_select_p0.restype = i32
    lib.drn_select_p0.argtypes = [u8p, i32, i32, i32p]
    lib.drn_select_random.restype = i32
    lib.drn_select_random.argtypes = [u8p, i32, i32, i64, i32p]
    lib.drn_select_conflict_sets.restype = i32
    lib.drn_select_conflict_sets.argtypes = [u8p, i32, i32, i32, i32, i64, i32p]
    lib.drn_bloom_compress.restype = i32
    lib.drn_bloom_compress.argtypes = [f32p, i32p, i32, i32, i32, i32, i32, i64, i32, i8p, i32]
    lib.drn_bloom_decompress.restype = i32
    lib.drn_bloom_decompress.argtypes = [i8p, i32, i32, i32, i32, i64, f32p, i32p, i32]
    lib.drn_fbp_encode.restype = i32
    lib.drn_fbp_encode.argtypes = [u32p, i32, u32p, i32]
    lib.drn_fbp_decode.restype = i32
    lib.drn_fbp_decode.argtypes = [u32p, i32, u32p, i32]
    lib.drn_varint_encode.restype = i32
    lib.drn_varint_encode.argtypes = [u32p, i32, u8p, i32]
    lib.drn_varint_decode.restype = i32
    lib.drn_varint_decode.argtypes = [u8p, i32, u32p, i32]
    lib.drn_pfor_encode.restype = i32
    lib.drn_pfor_encode.argtypes = [u32p, i32, u32p, i32]
    lib.drn_pfor_decode.restype = i32
    lib.drn_pfor_decode.argtypes = [u32p, i32, u32p, i32]
    lib.drn_int_encode_named.restype = i32
    lib.drn_int_encode_named.argtypes = [ctypes.c_char_p, u32p, i32, u32p, i32]
    lib.drn_int_decode_named.restype = i32
    lib.drn_int_decode_named.argtypes = [ctypes.c_char_p, u32p, i32, u32p, i32]
    _lib = lib
    return lib


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


# ------------------------- numpy-facing wrappers ------------------------- #


def fmix32(x: int) -> int:
    return int(load().drn_fmix32(ctypes.c_uint32(x)))


def bloom_insert(indices: np.ndarray, m_bits: int, num_hash: int) -> np.ndarray:
    lib = load()
    idx = np.ascontiguousarray(indices, np.int32)
    bitmap = np.zeros(m_bits // 8, np.uint8)
    lib.drn_bloom_insert(_ptr(idx, ctypes.c_int32), len(idx), m_bits, num_hash,
                         _ptr(bitmap, ctypes.c_uint8))
    return bitmap


def bloom_query_universe(bitmap: np.ndarray, num_hash: int, d: int) -> np.ndarray:
    lib = load()
    bm = np.ascontiguousarray(bitmap, np.uint8)
    mask = np.zeros(d, np.uint8)
    lib.drn_bloom_query_universe(_ptr(bm, ctypes.c_uint8), len(bm) * 8, num_hash, d,
                                 _ptr(mask, ctypes.c_uint8))
    return mask


def select(policy: str, mask: np.ndarray, k: int, *, m_bits: int = 0,
           num_hash: int = 0, step: int = 0, cap: Optional[int] = None) -> np.ndarray:
    lib = load()
    mask = np.ascontiguousarray(mask, np.uint8)
    d = len(mask)
    cap = cap or max(k, int(mask.sum()))
    out = np.zeros(cap, np.int32)
    pid = POLICY_IDS[policy]
    if pid == 0:
        n = lib.drn_select_leftmost(_ptr(mask, ctypes.c_uint8), d, min(k, cap),
                                    _ptr(out, ctypes.c_int32))
    elif pid == 1:
        n = lib.drn_select_random(_ptr(mask, ctypes.c_uint8), d, min(k, cap),
                                  step, _ptr(out, ctypes.c_int32))
    elif pid == 2:
        n = lib.drn_select_conflict_sets(_ptr(mask, ctypes.c_uint8), d, min(k, cap),
                                         m_bits, num_hash, step, _ptr(out, ctypes.c_int32))
    else:
        n = lib.drn_select_p0(_ptr(mask, ctypes.c_uint8), d, cap, _ptr(out, ctypes.c_int32))
    return out[:n]


def bloom_compress(dense: np.ndarray, indices: np.ndarray, m_bits: int,
                   num_hash: int, policy: str, step: int, select_cap: int) -> np.ndarray:
    lib = load()
    dense = np.ascontiguousarray(dense, np.float32).reshape(-1)
    idx = np.ascontiguousarray(indices, np.int32)
    cap = 12 + select_cap * 4 + m_bits // 8
    out = np.zeros(cap, np.int8)
    n = lib.drn_bloom_compress(_ptr(dense, ctypes.c_float), _ptr(idx, ctypes.c_int32),
                               len(idx), dense.size, m_bits, num_hash,
                               POLICY_IDS[policy], step, select_cap,
                               _ptr(out, ctypes.c_int8), cap)
    if n < 0:
        raise ValueError(f"bloom_compress needs {-n} bytes, capacity {cap}")
    return out[:n]


def bloom_decompress(payload: np.ndarray, d: int, k: int, policy: str,
                     step: int, cap: int) -> Tuple[np.ndarray, np.ndarray]:
    lib = load()
    payload = np.ascontiguousarray(payload, np.int8)
    vals = np.zeros(cap, np.float32)
    idxs = np.zeros(cap, np.int32)
    n = lib.drn_bloom_decompress(_ptr(payload, ctypes.c_int8), len(payload), d, k,
                                 POLICY_IDS[policy], step,
                                 _ptr(vals, ctypes.c_float), _ptr(idxs, ctypes.c_int32), cap)
    if n < 0:
        raise ValueError(f"bloom_decompress error {n}")
    return vals[:n], idxs[:n]


def fbp_encode(sorted_vals: np.ndarray) -> np.ndarray:
    lib = load()
    v = np.ascontiguousarray(sorted_vals, np.uint32)
    cap = 2 + len(v) + 1
    out = np.zeros(cap, np.uint32)
    n = lib.drn_fbp_encode(_ptr(v, ctypes.c_uint32), len(v), _ptr(out, ctypes.c_uint32), cap)
    if n < 0:
        raise ValueError("fbp_encode capacity")
    return out[:n]


def fbp_decode(words: np.ndarray, n_max: int) -> np.ndarray:
    lib = load()
    w = np.ascontiguousarray(words, np.uint32)
    out = np.zeros(n_max, np.uint32)
    n = lib.drn_fbp_decode(_ptr(w, ctypes.c_uint32), len(w), _ptr(out, ctypes.c_uint32), n_max)
    if n < 0:
        raise ValueError(f"fbp_decode error {n}")
    return out[:n]


def varint_encode(sorted_vals: np.ndarray) -> np.ndarray:
    lib = load()
    v = np.ascontiguousarray(sorted_vals, np.uint32)
    cap = 5 * len(v) + 8
    out = np.zeros(cap, np.uint8)
    n = lib.drn_varint_encode(_ptr(v, ctypes.c_uint32), len(v), _ptr(out, ctypes.c_uint8), cap)
    if n < 0:
        raise ValueError("varint_encode capacity")
    return out[:n]


def varint_decode(data: np.ndarray, n_max: int) -> np.ndarray:
    lib = load()
    b = np.ascontiguousarray(data, np.uint8)
    out = np.zeros(n_max, np.uint32)
    n = lib.drn_varint_decode(_ptr(b, ctypes.c_uint8), len(b), _ptr(out, ctypes.c_uint32), n_max)
    return out[:n]


def pfor_encode(sorted_vals: np.ndarray) -> np.ndarray:
    """PFor128 with patched exceptions over the sorted values' deltas."""
    lib = load()
    v = np.ascontiguousarray(sorted_vals, np.uint32)
    # worst case: every block falls back to b=32 (header + full words)
    cap = 1 + len(v) + 2 * ((len(v) + 127) // 128) + 8
    out = np.zeros(cap, np.uint32)
    n = lib.drn_pfor_encode(_ptr(v, ctypes.c_uint32), len(v), _ptr(out, ctypes.c_uint32), cap)
    if n < 0:
        raise ValueError(f"pfor_encode capacity {n}")
    return out[:n]


def pfor_decode(words: np.ndarray, n_max: int) -> np.ndarray:
    lib = load()
    w = np.ascontiguousarray(words, np.uint32)
    out = np.zeros(n_max, np.uint32)
    n = lib.drn_pfor_decode(_ptr(w, ctypes.c_uint32), len(w), _ptr(out, ctypes.c_uint32), n_max)
    if n < 0:
        raise ValueError(f"pfor_decode error {n}")
    return out[:n]


INT_CODEC_NAMES = ("fbp", "varint", "pfor")


def int_cap_words(k: int) -> int:
    """Family-wide worst-case wire size in words for k values: every pfor
    block at base width 32 (header + full words) / 5-byte varints, plus
    headers. The single sizing formula for every encode entry point."""
    return 2 * k + 2 * ((k + 127) // 128) + 16


def int_codec_from_name(name: str):
    """(encode, decode) for a named integer-codec family member — the
    CODECFactory::getFromName role (/root/reference/tensorflow/
    integer_compression.cc:62,161). Every member shares the words-in /
    words-out shape; unknown names raise like the factory does."""
    lib = load()
    cname = name.encode()
    if lib.drn_int_encode_named(cname, None, 0, None, 0) == -100:
        raise KeyError(f"unknown integer codec {name!r}; have {INT_CODEC_NAMES}")

    def enc(sorted_vals: np.ndarray) -> np.ndarray:
        v = np.ascontiguousarray(sorted_vals, np.uint32)
        cap = int_cap_words(len(v))
        out = np.zeros(cap, np.uint32)
        n = lib.drn_int_encode_named(
            cname, _ptr(v, ctypes.c_uint32), len(v), _ptr(out, ctypes.c_uint32), cap
        )
        if n < 0:
            raise ValueError(f"{name} encode error {n}")
        return out[:n]

    def dec(words: np.ndarray, n_max: int) -> np.ndarray:
        w = np.ascontiguousarray(words, np.uint32)
        out = np.zeros(max(1, n_max), np.uint32)
        n = lib.drn_int_decode_named(
            cname, _ptr(w, ctypes.c_uint32), len(w), _ptr(out, ctypes.c_uint32), n_max
        )
        if n < 0:
            raise ValueError(f"{name} decode error {n}")
        return out[:n]

    return enc, dec
