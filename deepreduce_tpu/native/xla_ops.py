"""XLA FFI custom-call bindings for the native codec kernels.

The reference registers its C++ kernels as TensorFlow custom ops
(bloom_filter_compression.cc:19-36, loaded at
tensorflow/deepreduce.py:328-330). The XLA-native equivalent: the same
kernels compiled against jaxlib's bundled XLA FFI headers
(`native/xla_ffi_ops.cc`), registered as CPU-platform custom-call targets —
they appear *inside* jitted programs instead of going through
`pure_callback`'s host round trip.

Available as `jax.ffi.ffi_call` wrappers after `register()` (idempotent;
CPU platform — the axon TPU PJRT executes no host custom-calls, like it
executes no callbacks)."""

from __future__ import annotations

import ctypes
import pathlib
import subprocess

import jax
import jax.numpy as jnp
import numpy as np

_DIR = pathlib.Path(__file__).parent
_LIB = _DIR / "libdeepreduce_xla_ffi.so"
_registered = False


def build() -> None:
    subprocess.run(["make", "-s", "-C", str(_DIR), "xla"], check=True)


def register() -> None:
    """Build (if needed) and register the FFI targets. Idempotent."""
    global _registered
    if _registered:
        return
    if not _LIB.exists():
        build()
    lib = ctypes.CDLL(str(_LIB))
    for name, sym in [
        ("drn_bloom_query", "DrnBloomQuery"),
        ("drn_bloom_insert", "DrnBloomInsert"),
        ("drn_fbp_decode", "DrnFbpDecode"),
        ("drn_varint_decode", "DrnVarintDecode"),
        ("drn_int_encode", "DrnIntEncode"),
        ("drn_int_decode", "DrnIntDecode"),
        ("drn_bloom_compress", "DrnBloomCompress"),
        ("drn_bloom_decompress", "DrnBloomDecompress"),
    ]:
        jax.ffi.register_ffi_target(name, jax.ffi.pycapsule(getattr(lib, sym)), platform="cpu")
    _registered = True


def available() -> bool:
    """True when the FFI route can serve as the production native path:
    CPU platform (the axon TPU PJRT executes no host custom-calls) and the
    library builds/registers. Codecs fall back to `pure_callback` when
    False.

    Trace-time assumption: this is evaluated once, when the enclosing codec
    traces, against `jax.default_backend()` — the FFI targets are registered
    for platform='cpu' only. A program traced on CPU but executed on another
    platform (explicit device placement, AOT export) would bake in a
    custom-call the executing platform cannot serve; don't move such traces
    across platforms. In this repo every entry point pins the platform
    before tracing (utils.force_platform / conftest), so trace and execute
    platforms always agree."""
    try:
        if jax.default_backend() != "cpu":
            return False
        register()
        return True
    except Exception:  # noqa: BLE001 — any build/registration failure
        return False


def bloom_query(bitmap_bytes: jax.Array, num_hash: int, d: int) -> jax.Array:
    """uint8[m_bytes] -> uint8[d] membership mask, as an XLA custom call."""
    register()
    return jax.ffi.ffi_call("drn_bloom_query", jax.ShapeDtypeStruct((d,), jnp.uint8))(
        bitmap_bytes, num_hash=np.int64(num_hash)
    )


def fbp_decode(words: jax.Array, n: int) -> jax.Array:
    """uint32 FBP stream -> uint32[n] delta-decoded values."""
    register()
    return jax.ffi.ffi_call("drn_fbp_decode", jax.ShapeDtypeStruct((n,), jnp.uint32))(words)


def varint_decode(data: jax.Array, n: int) -> jax.Array:
    register()
    return jax.ffi.ffi_call("drn_varint_decode", jax.ShapeDtypeStruct((n,), jnp.uint32))(data)


def bloom_insert(indices: jax.Array, m_bits: int, num_hash: int) -> jax.Array:
    """i32[k] indices -> uint8[m_bits/8] filter bitmap, as an XLA custom
    call — the encode-side counterpart of `bloom_query` (the reference's
    BloomCompressorOp insert loop, bloom_filter_compression.cc:102-105).
    Takes m_bits like its ctypes twin `native.bloom_insert`, so the two
    APIs are drop-in interchangeable. Dead slots should be pre-pointed at
    a live index (duplicate inserts are no-ops under bloom set
    semantics)."""
    register()
    return jax.ffi.ffi_call(
        "drn_bloom_insert", jax.ShapeDtypeStruct((m_bits // 8,), jnp.uint8)
    )(indices.astype(jnp.int32), num_hash=np.int64(num_hash))


def int_encode(vals: jax.Array, count: jax.Array, code: str, cap_words: int):
    """(u32[k] sorted values, i32[] live count) -> (u32[cap] wire words,
    i32[] live words) via the name-keyed integer-codec family
    (CODECFactory::getFromName role) as an XLA custom call."""
    register()
    words, nwords = jax.ffi.ffi_call(
        "drn_int_encode",
        (
            jax.ShapeDtypeStruct((cap_words,), jnp.uint32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ),
    )(vals.astype(jnp.uint32), count.reshape(1).astype(jnp.int32), code=code)
    return words, nwords[0]


def int_decode(words: jax.Array, nwords: jax.Array, code: str, n: int) -> jax.Array:
    """(u32 wire words, i32[] live word count) -> u32[n] decoded values —
    the name-keyed decode twin of `int_encode`."""
    register()
    return jax.ffi.ffi_call("drn_int_decode", jax.ShapeDtypeStruct((n,), jnp.uint32))(
        words, nwords.reshape(1).astype(jnp.int32), code=code
    )


def bloom_compress(
    dense: jax.Array,
    indices: jax.Array,
    nnz: jax.Array,
    step: jax.Array,
    *,
    m_bits: int,
    num_hash: int,
    policy_id: int,
    select_cap: int,
    wire_budget: int,
):
    """Full C++ bloom wire compress (insert + query + policy select +
    assemble) as ONE custom call — the BloomCompressorOp role. Returns
    (wire i8[wire_budget] zero-padded, nbytes i32[], values f32[select_cap],
    nsel i32[]) — the selected values/count are copied out of the assembled
    wire by the handler, so encode needs no decompress round trip."""
    register()
    wire, nbytes, values, nsel = jax.ffi.ffi_call(
        "drn_bloom_compress",
        (
            jax.ShapeDtypeStruct((wire_budget,), jnp.int8),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((select_cap,), jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ),
    )(
        dense.reshape(-1).astype(jnp.float32),
        indices.astype(jnp.int32),
        nnz.reshape(1).astype(jnp.int32),
        step.reshape(1).astype(jnp.int32),
        m_bits=np.int64(m_bits),
        num_hash=np.int64(num_hash),
        policy=np.int64(policy_id),
        select_cap=np.int64(select_cap),
    )
    return wire, nbytes[0], values, nsel[0]


def bloom_decompress(
    wire: jax.Array,
    nbytes: jax.Array,
    step: jax.Array,
    *,
    d: int,
    k: int,
    policy_id: int,
    select_cap: int,
):
    """C++ bloom wire decompress as ONE custom call — the
    BloomDecompressorOp role. Returns (values f32[select_cap],
    indices i32[select_cap], nsel i32[])."""
    register()
    values, indices, nsel = jax.ffi.ffi_call(
        "drn_bloom_decompress",
        (
            jax.ShapeDtypeStruct((select_cap,), jnp.float32),
            jax.ShapeDtypeStruct((select_cap,), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ),
    )(
        wire.astype(jnp.int8),
        nbytes.reshape(1).astype(jnp.int32),
        step.reshape(1).astype(jnp.int32),
        d=np.int64(d),
        k=np.int64(k),
        policy=np.int64(policy_id),
    )
    return values, indices, nsel[0]
