// XLA FFI custom-call wrappers around the native codec library — the true
// counterpart of the reference's TF custom ops (bloom_filter_compression.cc
// op registration :19-36): the same host kernels, but registered with XLA's
// FFI so they appear as custom-calls inside jitted programs on the CPU
// platform (TPU host offload goes through the same registry).
//
// Handlers:
//   drn_ffi_bloom_query   (bitmap u8[m_bytes], h) -> mask u8[d]
//   drn_ffi_fbp_decode    (words u32[n]) -> values u32[cap]  (delta-unpacked)
//   drn_ffi_varint_decode (bytes u8[n])  -> values u32[cap]
//
// Build: make -C deepreduce_tpu/native xla (adds -I jaxlib/include).

#include <cstdint>
#include <cstring>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

// from deepreduce_native.cc
extern "C" {
int32_t drn_bloom_query_universe(const uint8_t*, int32_t, int32_t, int32_t, uint8_t*);
int32_t drn_fbp_decode(const uint32_t*, int32_t, uint32_t*, int32_t);
int32_t drn_varint_decode(const uint8_t*, int32_t, uint32_t*, int32_t);
}

static ffi::Error BloomQueryImpl(ffi::Buffer<ffi::U8> bitmap, int64_t num_hash,
                                 ffi::ResultBuffer<ffi::U8> mask) {
  int32_t m_bits = (int32_t)bitmap.element_count() * 8;
  int32_t d = (int32_t)mask->element_count();
  drn_bloom_query_universe(bitmap.typed_data(), m_bits, (int32_t)num_hash, d,
                           mask->typed_data());
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    DrnBloomQuery, BloomQueryImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::U8>>()
        .Attr<int64_t>("num_hash")
        .Ret<ffi::Buffer<ffi::U8>>());

static ffi::Error FbpDecodeImpl(ffi::Buffer<ffi::U32> words,
                                ffi::ResultBuffer<ffi::U32> out) {
  int32_t cap = (int32_t)out->element_count();
  std::memset(out->typed_data(), 0, cap * 4);
  int32_t n = drn_fbp_decode(words.typed_data(), (int32_t)words.element_count(),
                             out->typed_data(), cap);
  if (n < 0) return ffi::Error(ffi::ErrorCode::kInvalidArgument, "fbp_decode failed");
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    DrnFbpDecode, FbpDecodeImpl,
    ffi::Ffi::Bind().Arg<ffi::Buffer<ffi::U32>>().Ret<ffi::Buffer<ffi::U32>>());

static ffi::Error VarintDecodeImpl(ffi::Buffer<ffi::U8> bytes,
                                   ffi::ResultBuffer<ffi::U32> out) {
  int32_t cap = (int32_t)out->element_count();
  std::memset(out->typed_data(), 0, cap * 4);
  drn_varint_decode(bytes.typed_data(), (int32_t)bytes.element_count(),
                    out->typed_data(), cap);
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    DrnVarintDecode, VarintDecodeImpl,
    ffi::Ffi::Bind().Arg<ffi::Buffer<ffi::U8>>().Ret<ffi::Buffer<ffi::U32>>());
