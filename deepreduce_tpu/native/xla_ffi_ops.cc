// XLA FFI custom-call wrappers around the native codec library — the true
// counterpart of the reference's TF custom ops (bloom_filter_compression.cc
// op registration :19-36): the same host kernels, but registered with XLA's
// FFI so they appear as custom-calls inside jitted programs on the CPU
// platform (TPU host offload goes through the same registry).
//
// Handlers (decode side AND encode side — both directions are
// custom-calls, like the reference's paired Compressor/Decompressor ops):
//   drn_ffi_bloom_query   (bitmap u8[m_bytes], h) -> mask u8[d]
//   drn_ffi_bloom_insert  (indices i32[k], h) -> bitmap u8[m_bytes]
//   drn_ffi_fbp_decode    (words u32[n]) -> values u32[cap]  (delta-unpacked)
//   drn_ffi_varint_decode (bytes u8[n])  -> values u32[cap]
//   drn_ffi_int_encode    (vals u32[k], count i32[1], code) ->
//                         (words u32[cap], nwords i32[1])   (name-keyed)
//
// Build: make -C deepreduce_tpu/native xla (adds -I jaxlib/include).

#include <cstdint>
#include <cstring>
#include <string>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

// from deepreduce_native.cc
extern "C" {
void drn_bloom_insert(const int32_t*, int32_t, int32_t, int32_t, uint8_t*);
int32_t drn_bloom_query_universe(const uint8_t*, int32_t, int32_t, int32_t, uint8_t*);
int32_t drn_fbp_decode(const uint32_t*, int32_t, uint32_t*, int32_t);
int32_t drn_varint_decode(const uint8_t*, int32_t, uint32_t*, int32_t);
int32_t drn_int_encode_named(const char*, const uint32_t*, int32_t, uint32_t*, int32_t);
int32_t drn_int_decode_named(const char*, const uint32_t*, int32_t, uint32_t*, int32_t);
int32_t drn_bloom_compress(const float*, const int32_t*, int32_t, int32_t,
                           int32_t, int32_t, int32_t, int64_t, int32_t,
                           int8_t*, int32_t);
int32_t drn_bloom_decompress(const int8_t*, int32_t, int32_t, int32_t,
                             int32_t, int64_t, float*, int32_t*, int32_t);
}

static ffi::Error BloomQueryImpl(ffi::Buffer<ffi::U8> bitmap, int64_t num_hash,
                                 ffi::ResultBuffer<ffi::U8> mask) {
  int32_t m_bits = (int32_t)bitmap.element_count() * 8;
  int32_t d = (int32_t)mask->element_count();
  drn_bloom_query_universe(bitmap.typed_data(), m_bits, (int32_t)num_hash, d,
                           mask->typed_data());
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    DrnBloomQuery, BloomQueryImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::U8>>()
        .Attr<int64_t>("num_hash")
        .Ret<ffi::Buffer<ffi::U8>>());

static ffi::Error FbpDecodeImpl(ffi::Buffer<ffi::U32> words,
                                ffi::ResultBuffer<ffi::U32> out) {
  int32_t cap = (int32_t)out->element_count();
  std::memset(out->typed_data(), 0, cap * 4);
  int32_t n = drn_fbp_decode(words.typed_data(), (int32_t)words.element_count(),
                             out->typed_data(), cap);
  if (n < 0) return ffi::Error(ffi::ErrorCode::kInvalidArgument, "fbp_decode failed");
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    DrnFbpDecode, FbpDecodeImpl,
    ffi::Ffi::Bind().Arg<ffi::Buffer<ffi::U32>>().Ret<ffi::Buffer<ffi::U32>>());

static ffi::Error VarintDecodeImpl(ffi::Buffer<ffi::U8> bytes,
                                   ffi::ResultBuffer<ffi::U32> out) {
  int32_t cap = (int32_t)out->element_count();
  std::memset(out->typed_data(), 0, cap * 4);
  drn_varint_decode(bytes.typed_data(), (int32_t)bytes.element_count(),
                    out->typed_data(), cap);
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    DrnVarintDecode, VarintDecodeImpl,
    ffi::Ffi::Bind().Arg<ffi::Buffer<ffi::U8>>().Ret<ffi::Buffer<ffi::U32>>());

static ffi::Error BloomInsertImpl(ffi::Buffer<ffi::S32> indices,
                                  int64_t num_hash,
                                  ffi::ResultBuffer<ffi::U8> bitmap) {
  int32_t m_bits = (int32_t)bitmap->element_count() * 8;
  std::memset(bitmap->typed_data(), 0, bitmap->element_count());
  drn_bloom_insert(indices.typed_data(), (int32_t)indices.element_count(),
                   m_bits, (int32_t)num_hash, bitmap->typed_data());
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    DrnBloomInsert, BloomInsertImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Attr<int64_t>("num_hash")
        .Ret<ffi::Buffer<ffi::U8>>());

static ffi::Error IntEncodeImpl(ffi::Buffer<ffi::U32> vals,
                                ffi::Buffer<ffi::S32> count,
                                std::string_view code,
                                ffi::ResultBuffer<ffi::U32> words,
                                ffi::ResultBuffer<ffi::S32> nwords) {
  int32_t cap = (int32_t)words->element_count();
  std::memset(words->typed_data(), 0, (size_t)cap * 4);
  int32_t n = count.typed_data()[0];
  if (n < 0 || n > (int32_t)vals.element_count())
    return ffi::Error(ffi::ErrorCode::kInvalidArgument, "bad live count");
  std::string code_s(code);
  int32_t w = drn_int_encode_named(code_s.c_str(), vals.typed_data(), n,
                                   words->typed_data(), cap);
  if (w < 0)
    return ffi::Error(ffi::ErrorCode::kInvalidArgument, "int encode failed");
  nwords->typed_data()[0] = w;
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    DrnIntEncode, IntEncodeImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::U32>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Attr<std::string_view>("code")
        .Ret<ffi::Buffer<ffi::U32>>()
        .Ret<ffi::Buffer<ffi::S32>>());

// (words u32[cap], nwords i32[1], code) -> values u32[cap_out] — the
// name-keyed decode twin of DrnIntEncode; unused output slots zeroed.
static ffi::Error IntDecodeImpl(ffi::Buffer<ffi::U32> words,
                                ffi::Buffer<ffi::S32> nwords,
                                std::string_view code,
                                ffi::ResultBuffer<ffi::U32> out) {
  int32_t cap = (int32_t)out->element_count();
  std::memset(out->typed_data(), 0, (size_t)cap * 4);
  int32_t nw = nwords.typed_data()[0];
  if (nw < 0 || nw > (int32_t)words.element_count())
    return ffi::Error(ffi::ErrorCode::kInvalidArgument, "bad live word count");
  std::string code_s(code);
  int32_t n = drn_int_decode_named(code_s.c_str(), words.typed_data(), nw,
                                   out->typed_data(), cap);
  if (n < 0)
    return ffi::Error(ffi::ErrorCode::kInvalidArgument, "int decode failed");
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    DrnIntDecode, IntDecodeImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::U32>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Attr<std::string_view>("code")
        .Ret<ffi::Buffer<ffi::U32>>());

// Full bloom wire codec (the reference's paired BloomCompressorOp /
// BloomDecompressorOp, bloom_filter_compression.cc:72-153) as custom
// calls: insert + query + policy select + wire assembly in one handler.
// `step` rides as a data buffer (it is a traced value under jit).
// Extra result buffers carry the selected values and live count straight
// out of the wire the compressor just assembled (nsel at byte offset 8,
// values from offset 12) — encode is ONE custom call, no decompress round
// trip to re-derive what compress already computed.
static ffi::Error BloomCompressImpl(ffi::Buffer<ffi::F32> dense,
                                    ffi::Buffer<ffi::S32> indices,
                                    ffi::Buffer<ffi::S32> nnz,
                                    ffi::Buffer<ffi::S32> step,
                                    int64_t m_bits, int64_t num_hash,
                                    int64_t policy, int64_t select_cap,
                                    ffi::ResultBuffer<ffi::S8> wire,
                                    ffi::ResultBuffer<ffi::S32> nbytes,
                                    ffi::ResultBuffer<ffi::F32> values,
                                    ffi::ResultBuffer<ffi::S32> nsel) {
  int32_t cap = (int32_t)wire->element_count();
  std::memset(wire->typed_data(), 0, cap);
  int32_t vcap = (int32_t)values->element_count();
  std::memset(values->typed_data(), 0, (size_t)vcap * 4);
  int32_t k = nnz.typed_data()[0];
  if (k < 0 || k > (int32_t)indices.element_count())
    return ffi::Error(ffi::ErrorCode::kInvalidArgument, "bad live count");
  int32_t n = drn_bloom_compress(
      dense.typed_data(), indices.typed_data(), k,
      (int32_t)dense.element_count(), (int32_t)m_bits, (int32_t)num_hash,
      (int32_t)policy, (int64_t)step.typed_data()[0], (int32_t)select_cap,
      wire->typed_data(), cap);
  if (n < 0)
    return ffi::Error(ffi::ErrorCode::kInvalidArgument, "bloom compress failed");
  nbytes->typed_data()[0] = n;
  int32_t ns = 0;
  if (n >= 12) {
    std::memcpy(&ns, wire->typed_data() + 8, 4);
    // clamp against both the output buffer and the bytes the C core
    // actually wrote, so format drift can never over-read the wire
    int32_t wire_max = (n - 12) / 4;
    if (ns < 0) ns = 0;
    if (ns > vcap) ns = vcap;
    if (ns > wire_max) ns = wire_max;
  }
  std::memcpy(values->typed_data(), wire->typed_data() + 12, (size_t)ns * 4);
  nsel->typed_data()[0] = ns;
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    DrnBloomCompress, BloomCompressImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::F32>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Attr<int64_t>("m_bits")
        .Attr<int64_t>("num_hash")
        .Attr<int64_t>("policy")
        .Attr<int64_t>("select_cap")
        .Ret<ffi::Buffer<ffi::S8>>()
        .Ret<ffi::Buffer<ffi::S32>>()
        .Ret<ffi::Buffer<ffi::F32>>()
        .Ret<ffi::Buffer<ffi::S32>>());

static ffi::Error BloomDecompressImpl(ffi::Buffer<ffi::S8> wire,
                                      ffi::Buffer<ffi::S32> nbytes,
                                      ffi::Buffer<ffi::S32> step,
                                      int64_t d, int64_t k, int64_t policy,
                                      ffi::ResultBuffer<ffi::F32> values,
                                      ffi::ResultBuffer<ffi::S32> indices,
                                      ffi::ResultBuffer<ffi::S32> nsel) {
  int32_t cap = (int32_t)values->element_count();
  std::memset(values->typed_data(), 0, (size_t)cap * 4);
  std::memset(indices->typed_data(), 0, (size_t)indices->element_count() * 4);
  int32_t len = nbytes.typed_data()[0];
  if (len < 0 || len > (int32_t)wire.element_count())
    return ffi::Error(ffi::ErrorCode::kInvalidArgument, "bad wire length");
  int32_t n = drn_bloom_decompress(
      wire.typed_data(), len, (int32_t)d, (int32_t)k, (int32_t)policy,
      (int64_t)step.typed_data()[0], values->typed_data(),
      indices->typed_data(), cap);
  if (n < 0)
    return ffi::Error(ffi::ErrorCode::kInvalidArgument, "bloom decompress failed");
  nsel->typed_data()[0] = n;
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    DrnBloomDecompress, BloomDecompressImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::S8>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Attr<int64_t>("d")
        .Attr<int64_t>("k")
        .Attr<int64_t>("policy")
        .Ret<ffi::Buffer<ffi::F32>>()
        .Ret<ffi::Buffer<ffi::S32>>()
        .Ret<ffi::Buffer<ffi::S32>>());
