// Native host-path codec library — the TPU-framework equivalent of the
// reference's TensorFlow CPU custom ops (bloom_filter_compression.cc,
// integer_compression.cc, policies.hpp) and their vendored third_party
// bloomfilter/FastPFor layers. Built from scratch:
//
// - the bloom filter uses the same murmur3-finalizer hash mix as the JAX
//   codec (deepreduce_tpu/codecs/bloom.py::fmix32), so bitmaps built on
//   either side are byte-identical and cross-checkable;
// - the wire format mirrors the reference op's
//   [int32 m_bytes | int32 hash_num | K x int32 value-bits | m bytes]
//   layout (bloom_filter_compression.cc:112-141);
// - selection policies: leftmostK, randomK, policy_zero, conflict_sets
//   (P2 — native-only in the reference too, policies.hpp:43-194). The RNG
//   is an explicit splitmix64/xorshift so determinism does not depend on a
//   particular libstdc++ (the reference's std::uniform_int_distribution
//   is not cross-implementation stable);
// - the integer codec implements delta + frame-bit-packing in the exact
//   bit layout of deepreduce_tpu/codecs/packing.py (value i bit b at
//   stream position i*width+b, LSB-first within little-endian uint32
//   words), a VByte/varint variant, and PFor128 with patched exceptions —
//   the FastPFor delta/PFor/VByte family role, selectable by name through
//   drn_int_{en,de}code_named (CODECFactory::getFromName,
//   integer_compression.cc:62).
//
// Exposed as a plain C ABI for ctypes; see native/__init__.py.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <vector>

extern "C" {

// ----------------------------------------------------------------------
// Hashing (matches codecs/bloom.py)

static inline uint32_t fmix32(uint32_t x) {
  x ^= x >> 16;
  x *= 0x85ebca6bu;
  x ^= x >> 13;
  x *= 0xc2b2ae35u;
  x ^= x >> 16;
  return x;
}

static const uint32_t kGolden = 0x9e3779b9u;

static inline uint32_t hash_pos(uint32_t idx, uint32_t j, uint32_t m_bits) {
  uint32_t seed = fmix32((j + 1u) * kGolden);
  return fmix32(idx ^ seed) % m_bits;
}

uint32_t drn_fmix32(uint32_t x) { return fmix32(x); }

// Deterministic RNG (splitmix64 -> xorshift-style stream)
struct Rng {
  uint64_t s;
  explicit Rng(uint64_t seed) : s(seed + 0x9e3779b97f4a7c15ull) {}
  uint64_t next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ull);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
    return z ^ (z >> 31);
  }
  // unbiased bounded draw (Lemire-style rejection)
  uint64_t below(uint64_t n) {
    if (n == 0) return 0;
    uint64_t x, r;
    do {
      x = next();
      r = x % n;
    } while (x - r > UINT64_MAX - n + 1);
    return r;
  }
};

// ----------------------------------------------------------------------
// Bloom filter core

void drn_bloom_insert(const int32_t* indices, int32_t k, int32_t m_bits,
                      int32_t num_hash, uint8_t* bitmap /* m_bits/8 bytes */) {
  for (int32_t i = 0; i < k; ++i) {
    uint32_t idx = (uint32_t)indices[i];
    for (int32_t j = 0; j < num_hash; ++j) {
      uint32_t p = hash_pos(idx, (uint32_t)j, (uint32_t)m_bits);
      bitmap[p >> 3] |= (uint8_t)(1u << (p & 7u));
    }
  }
}

static inline bool bloom_query(const uint8_t* bitmap, uint32_t idx,
                               int32_t num_hash, uint32_t m_bits) {
  for (int32_t j = 0; j < num_hash; ++j) {
    uint32_t p = hash_pos(idx, (uint32_t)j, m_bits);
    if (!(bitmap[p >> 3] & (1u << (p & 7u)))) return false;
  }
  return true;
}

// out_mask: d bytes of 0/1. Returns the positive count.
int32_t drn_bloom_query_universe(const uint8_t* bitmap, int32_t m_bits,
                                 int32_t num_hash, int32_t d, uint8_t* out_mask) {
  int32_t count = 0;
  for (int32_t i = 0; i < d; ++i) {
    bool hit = bloom_query(bitmap, (uint32_t)i, num_hash, (uint32_t)m_bits);
    out_mask[i] = hit ? 1 : 0;
    count += hit;
  }
  return count;
}

// ----------------------------------------------------------------------
// Selection policies (policies.hpp role). All return selected count;
// selected indices are ascending (canonical order) except randomK/
// conflict_sets which sort at the end, like the reference's
// choose_indices_from_conflict_sets (policies.hpp:130-134).

int32_t drn_select_leftmost(const uint8_t* mask, int32_t d, int32_t k,
                            int32_t* out) {
  int32_t n = 0;
  for (int32_t i = 0; i < d && n < k; ++i)
    if (mask[i]) out[n++] = i;
  return n;
}

int32_t drn_select_p0(const uint8_t* mask, int32_t d, int32_t cap, int32_t* out) {
  int32_t n = 0;
  for (int32_t i = 0; i < d && n < cap; ++i)
    if (mask[i]) out[n++] = i;
  return n;
}

int32_t drn_select_random(const uint8_t* mask, int32_t d, int32_t k,
                          int64_t step, int32_t* out) {
  std::vector<int32_t> positives;
  for (int32_t i = 0; i < d; ++i)
    if (mask[i]) positives.push_back(i);
  Rng rng((uint64_t)step);
  int32_t n = (int32_t)std::min<size_t>((size_t)k, positives.size());
  // partial Fisher-Yates: first n slots become the sample
  for (int32_t i = 0; i < n; ++i) {
    size_t j = i + (size_t)rng.below(positives.size() - i);
    std::swap(positives[i], positives[j]);
  }
  std::sort(positives.begin(), positives.begin() + n);
  std::copy(positives.begin(), positives.begin() + n, out);
  return n;
}

// P2: conflict sets — group positives by shared hash buckets, smallest set
// first, round-robin one random member per set, dedup against chosen
// (policies.hpp:43-146 semantics).
int32_t drn_select_conflict_sets(const uint8_t* mask, int32_t d, int32_t k,
                                 int32_t m_bits, int32_t num_hash, int64_t step,
                                 int32_t* out) {
  std::map<uint32_t, std::vector<int32_t>> sets;
  for (int32_t i = 0; i < d; ++i) {
    if (!mask[i]) continue;
    for (int32_t j = 0; j < num_hash; ++j)
      sets[hash_pos((uint32_t)i, (uint32_t)j, (uint32_t)m_bits)].push_back(i);
  }
  std::vector<std::vector<int32_t>> ordered;
  ordered.reserve(sets.size());
  for (auto& kv : sets) ordered.push_back(std::move(kv.second));
  std::stable_sort(ordered.begin(), ordered.end(),
                   [](const std::vector<int32_t>& a, const std::vector<int32_t>& b) {
                     return a.size() < b.size();
                   });
  Rng rng((uint64_t)step);
  std::vector<int32_t> chosen;
  std::vector<uint8_t> taken(d, 0);
  int32_t left = k;
  bool progress = true;
  while (left > 0 && progress) {
    progress = false;
    for (auto& cset : ordered) {
      if (left <= 0) break;
      // drop members already chosen; a compromised set skips this round
      size_t before = cset.size();
      cset.erase(std::remove_if(cset.begin(), cset.end(),
                                [&](int32_t v) { return taken[v]; }),
                 cset.end());
      bool compromised = cset.size() != before;
      if (compromised || cset.empty()) continue;
      size_t pick = (size_t)rng.below(cset.size());
      int32_t v = cset[pick];
      cset.erase(cset.begin() + pick);
      taken[v] = 1;
      chosen.push_back(v);
      --left;
      progress = true;
    }
  }
  // top up from any remaining positives if round-robin stalled
  for (int32_t i = 0; i < d && left > 0; ++i) {
    if (mask[i] && !taken[i]) {
      taken[i] = 1;
      chosen.push_back(i);
      --left;
    }
  }
  std::sort(chosen.begin(), chosen.end());
  std::copy(chosen.begin(), chosen.end(), out);
  return (int32_t)chosen.size();
}

static int32_t select_by_policy(int32_t policy, const uint8_t* mask, int32_t d,
                                int32_t k, int32_t m_bits, int32_t num_hash,
                                int64_t step, int32_t* out, int32_t cap) {
  switch (policy) {
    case 0:  // leftmostK
      return drn_select_leftmost(mask, d, k < cap ? k : cap, out);
    case 1:  // randomK
      return drn_select_random(mask, d, k < cap ? k : cap, step, out);
    case 2:  // conflict_sets
      return drn_select_conflict_sets(mask, d, k < cap ? k : cap, m_bits,
                                      num_hash, step, out);
    case 3:  // policy_zero: all positives
      return drn_select_p0(mask, d, cap, out);
  }
  return -1;
}

// ----------------------------------------------------------------------
// Bloom wire codec: [int32 m_bytes | int32 num_hash | int32 nsel |
//                    nsel x float values | m_bytes bitmap]
// (reference layout bloom_filter_compression.cc:112-141, with an explicit
// in-band nsel so policy_zero's variable size is self-describing).

int32_t drn_bloom_compress(const float* dense, const int32_t* indices,
                           int32_t k, int32_t d, int32_t m_bits,
                           int32_t num_hash, int32_t policy, int64_t step,
                           int32_t select_cap, int8_t* out, int32_t capacity) {
  int32_t m_bytes = m_bits / 8;
  std::vector<uint8_t> bitmap(m_bytes, 0);
  drn_bloom_insert(indices, k, m_bits, num_hash, bitmap.data());
  std::vector<uint8_t> mask(d);
  drn_bloom_query_universe(bitmap.data(), m_bits, num_hash, d, mask.data());
  std::vector<int32_t> selected(select_cap);
  int32_t nsel = select_by_policy(policy, mask.data(), d, k, m_bits, num_hash,
                                  step, selected.data(), select_cap);
  if (nsel < 0) return -1;
  int32_t need = 12 + nsel * 4 + m_bytes;
  if (need > capacity) return -need;
  int8_t* p = out;
  std::memcpy(p, &m_bytes, 4); p += 4;
  std::memcpy(p, &num_hash, 4); p += 4;
  std::memcpy(p, &nsel, 4); p += 4;
  for (int32_t i = 0; i < nsel; ++i) {
    float v = dense[selected[i]];
    std::memcpy(p, &v, 4); p += 4;
  }
  std::memcpy(p, bitmap.data(), m_bytes);
  return need;
}

int32_t drn_bloom_decompress(const int8_t* payload, int32_t payload_len,
                             int32_t d, int32_t k, int32_t policy, int64_t step,
                             float* out_values, int32_t* out_indices,
                             int32_t cap) {
  if (payload_len < 12) return -1;
  int32_t m_bytes, num_hash, nsel;
  std::memcpy(&m_bytes, payload, 4);
  std::memcpy(&num_hash, payload + 4, 4);
  std::memcpy(&nsel, payload + 8, 4);
  const int8_t* vals = payload + 12;
  const uint8_t* bitmap = (const uint8_t*)(payload + 12 + nsel * 4);
  if (12 + nsel * 4 + m_bytes > payload_len) return -2;
  std::vector<uint8_t> mask(d);
  drn_bloom_query_universe(bitmap, m_bytes * 8, num_hash, d, mask.data());
  std::vector<int32_t> selected(cap);
  int32_t n = select_by_policy(policy, mask.data(), d, k, m_bytes * 8, num_hash,
                               step, selected.data(), cap);
  if (n != nsel) n = n < nsel ? n : nsel;  // truncation guard
  for (int32_t i = 0; i < n; ++i) {
    std::memcpy(&out_values[i], vals + i * 4, 4);
    out_indices[i] = selected[i];
  }
  return n;
}

// ----------------------------------------------------------------------
// Integer codec (FastPFor role): delta + frame bit packing, same bit
// layout as codecs/packing.py. Header: [uint32 n | uint32 width].

static inline void set_stream_bit(uint32_t* words, uint64_t pos) {
  words[pos >> 5] |= (1u << (pos & 31u));
}
static inline uint32_t get_stream_bit(const uint32_t* words, uint64_t pos) {
  return (words[pos >> 5] >> (pos & 31u)) & 1u;
}

int32_t drn_fbp_encode(const uint32_t* sorted_vals, int32_t n,
                       uint32_t* out_words, int32_t capacity_words) {
  uint32_t max_delta = 0;
  uint32_t prev = 0;
  for (int32_t i = 0; i < n; ++i) {
    uint32_t delta = sorted_vals[i] - prev;
    prev = sorted_vals[i];
    if (delta > max_delta) max_delta = delta;
  }
  uint32_t width = 1;
  while (width < 32 && (max_delta >> width)) ++width;
  int64_t body_words = ((int64_t)n * width + 31) / 32;
  if (2 + body_words > capacity_words) return -(int32_t)(2 + body_words);
  out_words[0] = (uint32_t)n;
  out_words[1] = width;
  std::memset(out_words + 2, 0, (size_t)body_words * 4);
  prev = 0;
  for (int32_t i = 0; i < n; ++i) {
    uint32_t delta = sorted_vals[i] - prev;
    prev = sorted_vals[i];
    uint64_t base = (uint64_t)i * width;
    for (uint32_t b = 0; b < width; ++b)
      if ((delta >> b) & 1u) set_stream_bit(out_words + 2, base + b);
  }
  return (int32_t)(2 + body_words);
}

int32_t drn_fbp_decode(const uint32_t* words, int32_t nwords, uint32_t* out,
                       int32_t cap) {
  if (nwords < 2) return -1;
  int32_t n = (int32_t)words[0];
  uint32_t width = words[1];
  if (n > cap || width == 0 || width > 32) return -2;
  uint32_t prev = 0;
  for (int32_t i = 0; i < n; ++i) {
    uint32_t delta = 0;
    uint64_t base = (uint64_t)i * width;
    for (uint32_t b = 0; b < width; ++b)
      delta |= get_stream_bit(words + 2, base + b) << b;
    prev += delta;
    out[i] = prev;
  }
  return n;
}

// VByte / varint variant (the FastPFor "VByte" family member)
int32_t drn_varint_encode(const uint32_t* sorted_vals, int32_t n, uint8_t* out,
                          int32_t capacity) {
  int32_t pos = 0;
  uint32_t prev = 0;
  for (int32_t i = 0; i < n; ++i) {
    uint32_t delta = sorted_vals[i] - prev;
    prev = sorted_vals[i];
    do {
      if (pos >= capacity) return -1;
      uint8_t byte = delta & 0x7f;
      delta >>= 7;
      out[pos++] = byte | (delta ? 0x80 : 0);
    } while (delta);
  }
  return pos;
}

int32_t drn_varint_decode(const uint8_t* data, int32_t len, uint32_t* out,
                          int32_t cap) {
  int32_t n = 0, pos = 0;
  uint32_t prev = 0;
  while (pos < len && n < cap) {
    uint32_t delta = 0, shift = 0;
    while (true) {
      if (pos >= len) return n;
      uint8_t byte = data[pos++];
      delta |= (uint32_t)(byte & 0x7f) << shift;
      if (!(byte & 0x80)) break;
      shift += 7;
    }
    prev += delta;
    out[n++] = prev;
  }
  return n;
}

// ----------------------------------------------------------------------
// PFor with patched exceptions (the FastPFor "PFor/NewPFD" family member,
// /root/reference/tensorflow/integer_compression.cc CODECFactory role):
// deltas in blocks of 128; per block an exact-cost-minimized base width b
// stores the low b bits in a frame, and values that overflow b become
// *patched exceptions* — their in-block positions (1 byte each, 4/word)
// plus their full 32-bit deltas appended after the frame.
//
// Wire: [u32 n | blocks...]; block = [u32 (b<<16|n_exc) | frame words |
// position words | exception words].

static const int32_t kPforBlock = 128;

int32_t drn_pfor_encode(const uint32_t* sorted_vals, int32_t n,
                        uint32_t* out_words, int32_t capacity_words) {
  int64_t pos = 1;  // out_words[0] = n
  if (capacity_words < 1) return -1;
  out_words[0] = (uint32_t)n;
  uint32_t prev = 0;
  for (int32_t start = 0; start < n; start += kPforBlock) {
    int32_t len = (n - start) < kPforBlock ? (n - start) : kPforBlock;
    uint32_t deltas[kPforBlock];
    for (int32_t i = 0; i < len; ++i) {
      uint32_t v = sorted_vals[start + i];
      deltas[i] = v - prev;
      prev = v;
    }
    // exact cost scan: frame bits + 8 bits/exception position + 32/value
    uint32_t best_b = 32;
    int64_t best_cost = (int64_t)len * 32;
    for (uint32_t b = 0; b <= 31; ++b) {
      int32_t n_exc = 0;
      for (int32_t i = 0; i < len; ++i)
        if (b == 0 ? deltas[i] != 0 : (deltas[i] >> b) != 0) ++n_exc;
      int64_t cost = (int64_t)len * b + (int64_t)n_exc * (8 + 32);
      if (cost < best_cost) {
        best_cost = cost;
        best_b = b;
      }
    }
    uint32_t b = best_b;
    uint8_t exc_pos[kPforBlock];
    uint32_t exc_val[kPforBlock];
    int32_t n_exc = 0;
    for (int32_t i = 0; i < len; ++i)
      if (b == 32 ? false : (b == 0 ? deltas[i] != 0 : (deltas[i] >> b) != 0)) {
        exc_pos[n_exc] = (uint8_t)i;
        exc_val[n_exc] = deltas[i];
        ++n_exc;
      }
    int64_t frame_words = ((int64_t)len * b + 31) / 32;
    int64_t pos_words = (n_exc + 3) / 4;
    int64_t need = 1 + frame_words + pos_words + n_exc;
    if (pos + need > capacity_words) return -(int32_t)(pos + need);
    out_words[pos] = (b << 16) | (uint32_t)n_exc;
    uint32_t* frame = out_words + pos + 1;
    std::memset(frame, 0, (size_t)frame_words * 4);
    if (b > 0 && b < 32) {
      uint32_t mask = (b == 32) ? 0xffffffffu : ((1u << b) - 1u);
      for (int32_t i = 0; i < len; ++i) {
        uint32_t low = deltas[i] & mask;
        uint64_t base = (uint64_t)i * b;
        for (uint32_t bit = 0; bit < b; ++bit)
          if ((low >> bit) & 1u) set_stream_bit(frame, base + bit);
      }
    } else if (b == 32) {
      for (int32_t i = 0; i < len; ++i) frame[i] = deltas[i];
    }
    uint32_t* pwords = frame + frame_words;
    std::memset(pwords, 0, (size_t)pos_words * 4);
    for (int32_t e = 0; e < n_exc; ++e)
      pwords[e >> 2] |= (uint32_t)exc_pos[e] << (8 * (e & 3));
    uint32_t* evals = pwords + pos_words;
    for (int32_t e = 0; e < n_exc; ++e) evals[e] = exc_val[e];
    pos += need;
  }
  return (int32_t)pos;
}

int32_t drn_pfor_decode(const uint32_t* words, int32_t nwords, uint32_t* out,
                        int32_t cap) {
  if (nwords < 1) return -1;
  int32_t n = (int32_t)words[0];
  if (n > cap) return -2;
  int64_t pos = 1;
  uint32_t prev = 0;
  for (int32_t start = 0; start < n; start += kPforBlock) {
    int32_t len = (n - start) < kPforBlock ? (n - start) : kPforBlock;
    if (pos >= nwords) return -3;
    uint32_t hdr = words[pos];
    uint32_t b = hdr >> 16;
    int32_t n_exc = (int32_t)(hdr & 0xffffu);
    if (b > 32 || n_exc > len) return -4;
    int64_t frame_words = ((int64_t)len * b + 31) / 32;
    int64_t pos_words = (n_exc + 3) / 4;
    if (pos + 1 + frame_words + pos_words + n_exc > nwords) return -5;
    const uint32_t* frame = words + pos + 1;
    uint32_t deltas[kPforBlock];
    if (b == 32) {
      for (int32_t i = 0; i < len; ++i) deltas[i] = frame[i];
    } else if (b == 0) {
      for (int32_t i = 0; i < len; ++i) deltas[i] = 0;
    } else {
      for (int32_t i = 0; i < len; ++i) {
        uint32_t v = 0;
        uint64_t base = (uint64_t)i * b;
        for (uint32_t bit = 0; bit < b; ++bit)
          v |= get_stream_bit(frame, base + bit) << bit;
        deltas[i] = v;
      }
    }
    const uint32_t* pwords = frame + frame_words;
    const uint32_t* evals = pwords + pos_words;
    for (int32_t e = 0; e < n_exc; ++e) {
      uint32_t p = (pwords[e >> 2] >> (8 * (e & 3))) & 0xffu;
      if ((int32_t)p < len) deltas[p] = evals[e];
    }
    for (int32_t i = 0; i < len; ++i) {
      prev += deltas[i];
      out[start + i] = prev;
    }
    pos += 1 + frame_words + pos_words + n_exc;
  }
  return n;
}

// ----------------------------------------------------------------------
// Name-keyed codec selection — the CODECFactory::getFromName role
// (/root/reference/tensorflow/integer_compression.cc:62): one entry point,
// member chosen by string. varint's byte stream rides in words behind a
// [u32 nbytes] header so every member shares the words-in/words-out shape.

static int32_t pfor_name_id(const char* name) {
  std::string s(name ? name : "");
  if (s == "fbp" || s == "fastbinarypacking32") return 0;
  if (s == "varint" || s == "vbyte") return 1;
  if (s == "pfor" || s == "pfor128" || s == "newpfd") return 2;
  return -1;
}

int32_t drn_int_encode_named(const char* name, const uint32_t* sorted_vals,
                             int32_t n, uint32_t* out_words,
                             int32_t capacity_words) {
  switch (pfor_name_id(name)) {
    case 0:
      return drn_fbp_encode(sorted_vals, n, out_words, capacity_words);
    case 1: {
      if (capacity_words < 1) return -1;
      int32_t nbytes = drn_varint_encode(
          sorted_vals, n, reinterpret_cast<uint8_t*>(out_words + 1),
          (capacity_words - 1) * 4);
      if (nbytes < 0) return nbytes;
      out_words[0] = (uint32_t)nbytes;
      return 1 + (nbytes + 3) / 4;
    }
    case 2:
      return drn_pfor_encode(sorted_vals, n, out_words, capacity_words);
    default:
      return -100;  // unknown codec name
  }
}

int32_t drn_int_decode_named(const char* name, const uint32_t* words,
                             int32_t nwords, uint32_t* out, int32_t cap) {
  switch (pfor_name_id(name)) {
    case 0:
      return drn_fbp_decode(words, nwords, out, cap);
    case 1: {
      if (nwords < 1) return -1;
      int32_t nbytes = (int32_t)words[0];
      if (nbytes > (nwords - 1) * 4) return -2;
      return drn_varint_decode(reinterpret_cast<const uint8_t*>(words + 1),
                               nbytes, out, cap);
    }
    case 2:
      return drn_pfor_decode(words, nwords, out, cap);
    default:
      return -100;
  }
}

}  // extern "C"
