"""Checkpoint / resume for compressed-DP training state.

The reference delegates checkpointing entirely to its benchmark drivers
(``--train_dir=.../ckpts``, ``--load_checkpoint_path model_init.pth``,
run_deepreduce.sh:11,49) and does NOT checkpoint the residual error-feedback
memory (SURVEY.md §5) — resuming silently drops accumulated gradient mass.
Here the full `TrainState` (params, batch stats, optimizer state, residuals,
step) round-trips through orbax, fixing that gap.

Resilience hardening (two host-side gaps this module closes):

- **Config fingerprint**: a checkpoint restores into any same-shaped
  config — residuals written under one codec stack silently reinterpret
  under another, changing semantics mid-run. `save(..., config=cfg)`
  stamps a fingerprint of the codec-relevant config fields into a sibling
  ``<path>.config.json``; `restore(..., config=cfg)` fails fast on
  mismatch. Observability-only knobs (telemetry, micro_benchmark) are
  excluded, so toggling them never blocks a resume.
- **Transient I/O**: orbax save/restore and the stamp read/write route
  through `resilience.retry.retry_io` (deterministic exponential backoff
  on OSError).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import pathlib
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.resilience.retry import retry_io
from deepreduce_tpu.train import TrainState  # noqa: F401  (re-export: templates)

# config fields that change what is *observed*, never what is *computed* —
# a checkpoint written with telemetry off must restore under telemetry on,
# and turning the SLO health plane on/off (a host-side monitor over the
# already-logged report stream) must never invalidate a restore
_OBSERVABILITY_FIELDS = frozenset({
    "telemetry", "telemetry_every", "micro_benchmark",
    "slo_spec", "slo_window", "slo_hysteresis",
})


def config_fingerprint(cfg: DeepReduceConfig) -> str:
    """Stable hex fingerprint of the semantics-bearing config fields."""
    d = dataclasses.asdict(cfg)
    for f in _OBSERVABILITY_FIELDS:
        d.pop(f, None)
    blob = json.dumps(d, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _stamp_path(path) -> pathlib.Path:
    # a SIBLING of the orbax directory, not inside it — orbax owns (and on
    # save with force=True, deletes) the checkpoint directory's contents
    return pathlib.Path(str(pathlib.Path(path).absolute()) + ".config.json")


def _write_stamp(path, cfg: DeepReduceConfig) -> None:
    d = dataclasses.asdict(cfg)
    stamp = {
        "fingerprint": config_fingerprint(cfg),
        "config": {k: (v if isinstance(v, (int, float, bool, str, type(None))) else str(v)) for k, v in d.items()},
    }

    def _write():
        with open(_stamp_path(path), "w") as f:
            json.dump(stamp, f, sort_keys=True, indent=2)

    retry_io(_write)


def _check_stamp(path, cfg: DeepReduceConfig) -> None:
    sp = _stamp_path(path)
    if not sp.exists():
        return  # legacy checkpoint without a stamp: tolerated
    stamp = retry_io(lambda: json.loads(sp.read_text()))
    want = config_fingerprint(cfg)
    got = stamp.get("fingerprint")
    # tenant geometry first, with a dedicated message: a multi-tenant
    # state's every leaf carries a leading [T] dim, so restoring across a
    # T mismatch isn't a semantics drift — it's a shape error waiting to
    # happen deep inside orbax. Fail fast and name the geometry. (Legacy
    # stamps predating fed_tenants read as the single-tenant driver, 0.)
    stamped_t = int(stamp.get("config", {}).get("fed_tenants", 0) or 0)
    want_t = int(getattr(cfg, "fed_tenants", 0) or 0)
    if stamped_t != want_t:
        raise ValueError(
            f"checkpoint tenant-geometry mismatch: {sp} was written with "
            f"fed_tenants={stamped_t} but this run configures "
            f"fed_tenants={want_t} — a multi-tenant state's leaves are "
            "stacked [T, ...], so the checkpoint cannot restore into this "
            "geometry. Use the original fed_tenants, or delete the "
            "checkpoint to start fresh."
        )
    if got != want:
        raise ValueError(
            f"checkpoint config mismatch: {sp} was written under config "
            f"fingerprint {got!r} but this run's config fingerprints to "
            f"{want!r} — restoring would silently change codec semantics "
            "mid-run. Use the original config, or delete the checkpoint to "
            "start fresh."
        )


# orbax's ocdbt driver refuses zero-size arrays ("N params are missing in
# checkpoint") — e.g. a telemetry accumulator's bucket_saturated is shape
# (0,) for non-bucketed configs. Zero-size leaves carry no data, so they
# round-trip as a 1-element placeholder on disk and are rebuilt from the
# restore template's shape.
def _is_zero_size(x: Any) -> bool:
    return getattr(x, "size", 1) == 0


def _pad_zero_size(tree: Any) -> Any:
    return jax.tree_util.tree_map(
        lambda x: jnp.zeros((1,), x.dtype) if _is_zero_size(x) else x, tree
    )


def save(
    path: str, state: Any, *, force: bool = True, config: Optional[DeepReduceConfig] = None
) -> None:
    """Persist any pytree (a TrainState, or a composite like
    ``{"state": ..., "telemetry": acc}``). `config`, when given, stamps
    the sibling fingerprint file `restore` checks against."""
    def _save():
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(pathlib.Path(path).absolute(), _pad_zero_size(state), force=force)
        ckptr.wait_until_finished()

    retry_io(_save)
    if config is not None:
        _write_stamp(path, config)


def restore(path: str, template: Any, *, config: Optional[DeepReduceConfig] = None) -> Any:
    """Restore into the shape/dtype structure of `template` (build it with
    Trainer.init_state on the same config/mesh). With `config`, fail fast
    if the checkpoint's stamped config fingerprint doesn't match."""
    if config is not None:
        _check_stamp(path, config)

    def _restore():
        ckptr = ocp.StandardCheckpointer()
        abstract = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, _pad_zero_size(template)
        )
        return ckptr.restore(pathlib.Path(path).absolute(), abstract)

    out = retry_io(_restore)

    # orbax hands back arrays committed to device 0; a fresh init_state's
    # arrays are uncommitted, so jit is free to place them on the mesh.
    # Round-trip through host memory to drop the commitment — otherwise the
    # first post-resume step fails with "incompatible devices".
    def _uncommit(t, r):
        if _is_zero_size(t):
            return jnp.zeros(t.shape, t.dtype)
        return jnp.asarray(np.asarray(r))

    return jax.tree_util.tree_map(_uncommit, template, out)


def save_common_init(path: str, params) -> None:
    """The reference's `model_init.pth` common-initialization trick
    (run_deepreduce.sh:49): persist initial params so every worker/job starts
    identically."""
    def _save():
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(pathlib.Path(path).absolute(), params, force=True)
        ckptr.wait_until_finished()

    retry_io(_save)


def load_common_init(path: str, params_template):
    def _load():
        ckptr = ocp.StandardCheckpointer()
        abstract = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, params_template
        )
        return ckptr.restore(pathlib.Path(path).absolute(), abstract)

    return retry_io(_load)
