"""Checkpoint / resume for compressed-DP training state.

The reference delegates checkpointing entirely to its benchmark drivers
(``--train_dir=.../ckpts``, ``--load_checkpoint_path model_init.pth``,
run_deepreduce.sh:11,49) and does NOT checkpoint the residual error-feedback
memory (SURVEY.md §5) — resuming silently drops accumulated gradient mass.
Here the full `TrainState` (params, batch stats, optimizer state, residuals,
step) round-trips through orbax, fixing that gap."""

from __future__ import annotations

import pathlib
from typing import Optional

import jax
import orbax.checkpoint as ocp

from deepreduce_tpu.train import TrainState


def save(path: str, state: TrainState, *, force: bool = True) -> None:
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(pathlib.Path(path).absolute(), state, force=force)
    ckptr.wait_until_finished()


def restore(path: str, template: TrainState) -> TrainState:
    """Restore into the shape/dtype structure of `template` (build it with
    Trainer.init_state on the same config/mesh)."""
    ckptr = ocp.StandardCheckpointer()
    abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, template)
    return ckptr.restore(pathlib.Path(path).absolute(), abstract)


def save_common_init(path: str, params) -> None:
    """The reference's `model_init.pth` common-initialization trick
    (run_deepreduce.sh:49): persist initial params so every worker/job starts
    identically."""
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(pathlib.Path(path).absolute(), params, force=True)
    ckptr.wait_until_finished()


def load_common_init(path: str, params_template):
    ckptr = ocp.StandardCheckpointer()
    abstract = jax.tree_util.tree_map(ocp.utils.to_shape_dtype_struct, params_template)
    return ckptr.restore(pathlib.Path(path).absolute(), abstract)
