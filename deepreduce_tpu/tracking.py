"""Local experiment tracking — the reference's WANDB role.

The reference records every run in a public WANDB project as its regression
record (README.md:53; `--extra_wandb_tags`, run_deepreduce.sh:50,66). This
environment has no egress, so the same capability is file-based: each run
gets a directory under the tracking root holding

    config.json    — the run's full config dict + tags, written at start
    metrics.jsonl  — one JSON object per `log()` call (step-keyed)
    summary.json   — final metrics written by `finish()`

and `runs()` / `history()` give the offline query side (the role of the
WANDB dashboard when comparing configs across runs).
"""

from __future__ import annotations

import itertools
import json
import math
import os
import pathlib
import time
from typing import Any, Dict, Iterator, List, Optional

from deepreduce_tpu.resilience.retry import retry_io

_RUN_SEQ = itertools.count()  # disambiguates unnamed runs within one second


def _jsonable(obj: Any) -> Any:
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if hasattr(obj, "ndim"):  # numpy / jax arrays and scalars
        return _jsonable(obj.item()) if obj.ndim == 0 else _jsonable(obj.tolist())
    if hasattr(obj, "item"):  # other 0-d scalar wrappers
        return _jsonable(obj.item())
    if isinstance(obj, float):
        # json.dumps emits bare NaN/Infinity for non-finite floats, which
        # is not strict JSON and breaks history() consumers — map to null
        return obj if math.isfinite(obj) else None
    if isinstance(obj, (str, int, bool)) or obj is None:
        return obj
    return str(obj)


class Run:
    """One tracked experiment run (the wandb.init(...) object role)."""

    def __init__(
        self,
        root: str,
        name: Optional[str] = None,
        config: Optional[Dict[str, Any]] = None,
        tags: Optional[List[str]] = None,
    ):
        if name is None:
            stamp = time.strftime("%Y%m%d-%H%M%S")
            name = f"run-{stamp}-{os.getpid()}-{next(_RUN_SEQ)}"
        self.name = name
        self.dir = pathlib.Path(root) / self.name
        self.dir.mkdir(parents=True, exist_ok=True)
        self._metrics = retry_io(lambda: open(self.dir / "metrics.jsonl", "a"))
        self._step = 0

        def _write_config():
            with open(self.dir / "config.json", "w") as f:
                json.dump(
                    {"name": self.name, "tags": list(tags or []), "config": _jsonable(config or {})},
                    f,
                    indent=2,
                )

        retry_io(_write_config)

    def log(self, metrics: Dict[str, Any], step: Optional[int] = None) -> None:
        if step is None:
            step = self._step
        self._step = step + 1
        rec = {"step": int(step), "ts": time.time()}
        # user metrics must not clobber the record's own step/ts keys —
        # history() keys on them; rename collisions instead of dropping data
        user = {
            (f"metric.{k}" if k in ("step", "ts") else k): v
            for k, v in _jsonable(metrics).items()
        }
        rec.update(user)

        def _append():
            # retried as a unit: if the write lands but the flush raises, a
            # retry may duplicate the line — history() consumers key on
            # `step`, so a dup is harmless where a lost record is not
            self._metrics.write(json.dumps(rec) + "\n")
            self._metrics.flush()

        retry_io(_append)

    def finish(self, summary: Optional[Dict[str, Any]] = None) -> None:
        def _write_summary():
            with open(self.dir / "summary.json", "w") as f:
                json.dump(_jsonable(summary or {}), f, indent=2)

        retry_io(_write_summary)
        self._metrics.close()

    def __enter__(self) -> "Run":
        return self

    def __exit__(self, *exc) -> None:
        if not self._metrics.closed:
            self.finish()


def runs(root: str) -> List[str]:
    """Run names under a tracking root, oldest first (dashboard listing)."""
    p = pathlib.Path(root)
    if not p.is_dir():
        return []
    return sorted(d.name for d in p.iterdir() if (d / "config.json").exists())


def config(root: str, name: str) -> Dict[str, Any]:
    with open(pathlib.Path(root) / name / "config.json") as f:
        return json.load(f)


def history(root: str, name: str) -> Iterator[Dict[str, Any]]:
    """Step-keyed metric records of one run (wandb run.history role)."""
    path = pathlib.Path(root) / name / "metrics.jsonl"
    if not path.exists():
        return
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                yield json.loads(line)


def summary(root: str, name: str) -> Dict[str, Any]:
    path = pathlib.Path(root) / name / "summary.json"
    if not path.exists():
        return {}
    with open(path) as f:
        return json.load(f)
