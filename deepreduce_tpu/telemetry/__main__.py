"""`python -m deepreduce_tpu.telemetry {summary,compare,trace}` — the
offline consumer of tracking.py run directories.

- ``summary RUN``            one-screen digest of a run: loss trajectory,
                             rel-volume and step-time distributions, and
                             the device-accumulator fields from
                             summary.json when the run had telemetry on.
- ``compare RUN_A RUN_B``    diff two runs' step-time and rel-volume
                             distributions; exits 1 when B's mean step
                             time regresses past ``--tol`` vs A.
- ``compare RUN --against BENCH_DECODE_r06.json``
                             check a run against the committed decode-
                             strategy bench record (matched on the run's
                             `decode_strategy` config); exits 1 on a
                             step-time regression — the bench trajectory's
                             automated consumer.
- ``compare RUN_A RUN_B --ctrl``
                             adaptive-vs-fixed diff: cumulative wire
                             volume at matched (running-min) loss; exits 1
                             when the adaptive run (A) spent >= wire than
                             the fixed baseline (B).
- ``trace RUN [--out F]``    merged Chrome trace: the run's span events
                             (trace.json, written by benchmarks/train.py
                             --telemetry) plus per-step metrics as "C"
                             counter events; adaptive runs additionally get
                             ctrl_ladder_index/ctrl_ratio counter tracks and
                             instant markers at each operating-point switch.
                             Load the output in Perfetto.
- ``trace RUN --overlap``    wall-clock overlap fraction between the
                             train/forward_backward spans and the
                             exchange/bucket/* dispatch spans — ~1 for a
                             streaming run (cfg.stream_exchange), 0 for a
                             barrier/pipeline run; exits 1 below
                             ``--overlap-threshold`` (the CI gate that
                             backprop-overlapped dispatch actually
                             happened).
- ``calibrate RUN [--out F]``
                             fit a MachineProfile from the run's telemetry
                             (costmodel.calibrate): span self-times joined
                             with the per-axis wire counters, warmup
                             dropped. Emits a schema-validated profile
                             record — no wall clock in it, so a committed
                             run dir replays bitwise. Exits 1 when the
                             fitted model's predicted step time misses the
                             measured one by more than ``--tol``.
- ``compare --profile P --against BENCH.json``
                             re-price a committed bench claim under a
                             fitted machine profile: re-run the plan
                             selection with and without the profile and
                             report each sweep point where the static pick
                             and the calibrated pick disagree (and what
                             the static pick costs under the fitted
                             model). Informational — exits 0. Pass
                             ``--profile`` twice to compare the picks of
                             two fitted profiles (A vs B) instead of
                             static vs fitted.
- ``slo RUN --spec slo.json``
                             per-tenant SLO verdict table: replay the
                             run's tick stream through the health monitor
                             (slo/monitor.py) and print each target's
                             windowed value vs threshold plus any
                             OK/DEGRADED/BREACH transitions; exits 1 when
                             any tenant ends in BREACH — the gate a
                             "healthy at N clients/s" claim hangs on.
- ``bench-history [DIR]``    longitudinal view of the committed
                             BENCH_*.json ledger: one provenance-stamped
                             trend row per round (headline metric,
                             modeled/measured flag, profile_sha256 when
                             present); exits 2 on a schema-less record.
- ``profiles A.json B.json [...] --against BENCH.json``
                             cross-profile drift sentinel: per-parameter
                             drift between saved machine profiles,
                             per-route row disagreement from their v2
                             route tables, and — the load-bearing part —
                             which committed bench plan selections flip
                             between profiles. Exits 1 when any
                             ``--against`` sweep point's pick differs
                             between any two of the profiles.

Step-time statistics drop compile-dominated warmup intervals by default
(``--include-warmup`` keeps them). Runs with telemetry off get a clean
"telemetry was off" notice instead of partial output. RUN may be a run
directory or a tracking root (latest run is picked). Exit codes: 0 ok,
1 flagged regression, 2 usage/data error.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
from typing import Any, Dict, List, Optional

from deepreduce_tpu import costmodel


def _fail(msg: str) -> int:
    print(f"telemetry: error: {msg}", file=sys.stderr)
    return 2


def _resolve_run(path: str) -> Optional[pathlib.Path]:
    """A run dir (has config.json) or a tracking root (latest run wins)."""
    p = pathlib.Path(path)
    if (p / "config.json").exists():
        return p
    if p.is_dir():
        runs = sorted(
            (d for d in p.iterdir() if (d / "config.json").exists()),
            key=lambda d: d.stat().st_mtime,
        )
        if runs:
            return runs[-1]
    return None


def _history(run: pathlib.Path) -> List[Dict[str, Any]]:
    path = run / "metrics.jsonl"
    if not path.exists():
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _load_json(path: pathlib.Path) -> Dict[str, Any]:
    if not path.exists():
        return {}
    with open(path) as f:
        return json.load(f)


def _decisions(run: pathlib.Path) -> List[Dict[str, Any]]:
    """The adaptive controller's decisions.jsonl trail ([] when absent)."""
    path = run / "decisions.jsonl"
    if not path.exists():
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _telemetry_off(run: pathlib.Path, summ: Dict[str, Any]) -> bool:
    """True when the run recorded no telemetry artifacts at all: no device
    accumulators in summary.json and no span trace. Used to print a clean
    'telemetry was off' notice instead of silently partial output."""
    return not isinstance(summ.get("telemetry"), dict) and not (
        run / "trace.json"
    ).exists()


def _series(hist: List[Dict[str, Any]], key: str) -> List[float]:
    return [float(r[key]) for r in hist if isinstance(r.get(key), (int, float))]


def _step_times(
    hist: List[Dict[str, Any]], include_warmup: bool = False
) -> List[float]:
    """Per-step wall time from consecutive metrics.jsonl timestamps.
    Compile-dominated warmup intervals are dropped by default via
    costmodel.drop_warmup — robust to MULTIPLE compiled programs per run
    (a streaming run compiles two), where the old drop-first-only policy
    let the second warmup step skew p50/p99 and the calibration fit.
    `--include-warmup` keeps every interval."""
    ts = _series(hist, "ts")
    dt = [b - a for a, b in zip(ts, ts[1:]) if b >= a]
    if include_warmup or len(dt) <= 2:
        return dt
    return costmodel.drop_warmup(dt)


def _percentile(xs: List[float], q: float) -> float:
    """Sorted linear-interpolation quantile (numpy's default 'linear'
    method, without numpy): exact order statistics at the grid points,
    interpolated between them — so p95/p99 of short series move smoothly
    instead of snapping to the nearest rank."""
    if not xs:
        return float("nan")
    ys = sorted(xs)
    if len(ys) == 1:
        return ys[0]
    pos = q * (len(ys) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(ys) - 1)
    frac = pos - lo
    return ys[lo] * (1.0 - frac) + ys[hi] * frac


def _dist(xs: List[float]) -> Dict[str, float]:
    if not xs:
        return {"n": 0}
    return {
        "n": len(xs),
        "mean": sum(xs) / len(xs),
        "p50": _percentile(xs, 0.5),
        "p90": _percentile(xs, 0.9),
        "p95": _percentile(xs, 0.95),
        "p99": _percentile(xs, 0.99),
        "min": min(xs),
        "max": max(xs),
    }


def _fmt_dist(d: Dict[str, float], unit: str = "") -> str:
    if not d.get("n"):
        return "(no samples)"
    return (
        f"mean {d['mean']:.6g}{unit}  p50 {d['p50']:.6g}{unit}  "
        f"p90 {d['p90']:.6g}{unit}  p95 {d['p95']:.6g}{unit}  "
        f"p99 {d['p99']:.6g}{unit}  n={d['n']}"
    )


def _run_report(
    run: pathlib.Path, include_warmup: bool = False
) -> Dict[str, Any]:
    cfg = _load_json(run / "config.json")
    summ = _load_json(run / "summary.json")
    hist = _history(run)
    losses = _series(hist, "loss")
    report = {
        "run": run.name,
        "dir": str(run),
        "config": cfg.get("config", {}),
        "steps_logged": len(hist),
        "loss_first": losses[0] if losses else None,
        "loss_last": losses[-1] if losses else None,
        "rel_volume": _dist(_series(hist, "rel_volume")),
        "step_time_s": _dist(_step_times(hist, include_warmup)),
    }
    telem = summ.get("telemetry")
    if isinstance(telem, dict):
        report["telemetry"] = telem
    if _telemetry_off(run, summ):
        report["telemetry_off"] = True
    fedsim = _fedsim_report(hist)
    if fedsim is not None:
        report["fedsim"] = fedsim
    ctrl = _ctrl_report(run)
    if ctrl is not None:
        report["ctrl"] = ctrl
    return report


def _ctrl_report(run: pathlib.Path) -> Optional[Dict[str, Any]]:
    """Adaptive-controller digest from decisions.jsonl (None when the run
    had no controller). `effective_ratio` is the step-weighted mean of the
    rung each window actually ran at (old_ratio — the switch takes effect
    for the NEXT window); `ctrl_switches_per_step` normalizes switch churn
    by the decision span so runs of different lengths compare."""
    decs = _decisions(run)
    if not decs:
        return None
    switches = [d for d in decs if d.get("switched")]
    span = max((int(d.get("step", 0)) for d in decs), default=0)
    wsum = sum(float(d.get("window_steps", 0)) for d in decs)
    wratio = sum(
        float(d.get("window_steps", 0)) * float(d.get("old_ratio", 0.0))
        for d in decs
    )
    last = decs[-1]
    out: Dict[str, Any] = {
        "decisions": len(decs),
        "switches": len(switches),
        "ctrl_switches_per_step": len(switches) / span if span else 0.0,
        "effective_ratio": wratio / wsum if wsum else None,
        "final_index": last.get("new_index"),
        "final_ratio": last.get("new_ratio"),
        "trail": [
            f"{d.get('step')}: {d.get('old_index')}->{d.get('new_index')} "
            f"({d.get('trigger')}/{d.get('rationale')})"
            for d in switches
        ],
    }
    return out


def _fedsim_report(hist: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Federated-round rates when the run logged fedsim metrics (`clients` +
    `uplink_bytes` per round, as the fedsim CLI / bench drivers write).
    clients/sec pairs each round's live-client count with the wall interval
    to the previous record, first (compile-bearing) interval dropped like
    `_step_times`."""
    clients = _series(hist, "clients")
    uplink = _series(hist, "uplink_bytes")
    if not clients or not uplink:
        return None
    rates = []
    recs = [r for r in hist if isinstance(r.get("ts"), (int, float))]
    for prev, cur in zip(recs, recs[1:]):
        dt = cur["ts"] - prev["ts"]
        c = cur.get("clients")
        if dt > 0 and isinstance(c, (int, float)):
            rates.append(float(c) / dt)
    if len(rates) > 2:
        rates = rates[1:]
    out: Dict[str, Any] = {
        "uplink_bytes_per_round": _dist(uplink),
        "clients_per_round": _dist(clients),
    }
    if rates:
        out["clients_per_sec"] = _dist(rates)
    failures = _series(hist, "checksum_failures")
    if failures:
        out["checksum_failures_total"] = sum(failures)
    # asynchronous buffered mode: per-tick staleness + buffer digests (the
    # async driver logs staleness_mean/staleness_max/buffer_fill/applied)
    st_mean = _series(hist, "staleness_mean")
    st_max = _series(hist, "staleness_max")
    if st_mean:
        out["fed_staleness_mean"] = sum(st_mean) / len(st_mean)
    if st_max:
        out["fed_staleness_max"] = max(st_max)
    # exact staleness tail from the on-device per-tick histograms (the new
    # psum members): sum the f32[D] rows over the run, then read discrete
    # quantiles off the cumulative counts — no sampling, no interpolation
    hists = [
        r["staleness_hist"] for r in hist
        if isinstance(r.get("staleness_hist"), list) and r["staleness_hist"]
    ]
    if hists:
        from deepreduce_tpu.telemetry.device_metrics import hist_quantile

        depth = max(len(h) for h in hists)
        total = [
            sum(float(h[d]) for h in hists if d < len(h))
            for d in range(depth)
        ]
        out["fed_staleness_hist_total"] = total
        out["fed_staleness_p50"] = hist_quantile(total, 0.50)
        out["fed_staleness_p95"] = hist_quantile(total, 0.95)
        out["fed_staleness_p99"] = hist_quantile(total, 0.99)
    # heterogeneous populations: exact per-class participation from the
    # on-device pop_hist psum member (per-round f32[K] rows, logged as
    # lists) — cumulative counts, shares, and the worst class's share
    pop_hists = [
        r["pop_hist"] for r in hist
        if isinstance(r.get("pop_hist"), list) and r["pop_hist"]
    ]
    if pop_hists:
        K = max(len(h) for h in pop_hists)
        pop_total = [
            sum(float(h[k]) for h in pop_hists if k < len(h))
            for k in range(K)
        ]
        grand = max(sum(pop_total), 1.0)
        out["fed_pop_classes"] = K
        out["fed_pop_hist_total"] = pop_total
        out["fed_pop_shares"] = [v / grand for v in pop_total]
        out["fed_pop_residency_min"] = min(out["fed_pop_shares"])
    fills = [
        float(r["buffer_fill"])
        for r in hist
        if isinstance(r.get("buffer_fill"), (int, float))
        and float(r.get("applied", 0.0)) > 0
    ]
    if fills:
        # buffer occupancy at the moment of each buffered apply — how far
        # past the K threshold the ingest stream overshoots
        out["fed_buffer_fill_per_apply"] = sum(fills) / len(fills)
    # multi-tenant rows: the MT driver logs per-tenant LISTS under *_t keys
    # next to the scalar fleet aggregates digested above — render the
    # tenant-indexed variants of the r20 rows
    mt = _mt_fedsim_rows(hist)
    if mt:
        out.update(mt)
    return out


def _mt_series(hist: List[Dict[str, Any]], key: str) -> List[List[float]]:
    return [
        [float(x) for x in r[key]]
        for r in hist
        if isinstance(r.get(key), list) and r[key]
    ]


def _mt_fedsim_rows(hist: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Tenant-indexed fedsim digests from the per-tenant `*_t` list rows
    the multi-tenant driver logs ({} for single-tenant runs). Each output
    is a length-T list, index = tenant slot."""
    clients_t = _mt_series(hist, "clients_t")
    if not clients_t:
        return {}
    T = max(len(row) for row in clients_t)
    out: Dict[str, Any] = {"fed_tenants": T}
    # per-tenant clients/sec: pair each tick's per-tenant live count with
    # the wall interval to the previous record (first interval dropped,
    # like the aggregate rate)
    recs = [
        r for r in hist
        if isinstance(r.get("ts"), (int, float))
        and isinstance(r.get("clients_t"), list)
    ]
    rates: List[List[float]] = [[] for _ in range(T)]
    for prev, cur in zip(recs, recs[1:]):
        dt = cur["ts"] - prev["ts"]
        if dt <= 0:
            continue
        for t, c in enumerate(cur["clients_t"][:T]):
            rates[t].append(float(c) / dt)
    rates = [r[1:] if len(r) > 2 else r for r in rates]
    if any(rates):
        out["fed_mt_clients_per_sec"] = [
            (sum(r) / len(r)) if r else 0.0 for r in rates
        ]
    # rows can be RAGGED: a run dir mixing single-tenant and MT records,
    # or a tenant-geometry change mid-dir, logs rows shorter than T —
    # average/maximize each slot over the rows that actually carry it
    # instead of indexing row[t] into an IndexError
    st_mean_t = _mt_series(hist, "staleness_mean_t")
    if st_mean_t:
        out["fed_mt_staleness_mean"] = [
            (
                sum(row[t] for row in st_mean_t if t < len(row))
                / max(sum(1 for row in st_mean_t if t < len(row)), 1)
            )
            for t in range(T)
        ]
    st_max_t = _mt_series(hist, "staleness_max_t")
    if st_max_t:
        out["fed_mt_staleness_max"] = [
            max((row[t] for row in st_max_t if t < len(row)), default=0.0)
            for t in range(T)
        ]
    # per-tenant staleness tails from the [T, D] on-device histogram rows
    hist_t_rows = [
        r["staleness_hist_t"] for r in hist
        if isinstance(r.get("staleness_hist_t"), list) and r["staleness_hist_t"]
    ]
    if hist_t_rows:
        from deepreduce_tpu.telemetry.device_metrics import hist_quantile

        totals: List[List[float]] = [[] for _ in range(T)]
        for row in hist_t_rows:
            for t, h in enumerate(row):
                if t >= T or not isinstance(h, list):
                    continue
                if len(h) > len(totals[t]):
                    totals[t].extend([0.0] * (len(h) - len(totals[t])))
                for d, v in enumerate(h):
                    totals[t][d] += float(v)
        for q, name in ((0.50, "fed_mt_staleness_p50"),
                        (0.95, "fed_mt_staleness_p95"),
                        (0.99, "fed_mt_staleness_p99")):
            out[name] = [hist_quantile(tot, q) for tot in totals]
    # per-tenant buffer occupancy at that tenant's own applies (the
    # tenant-indexed fed_buffer_fill_per_apply)
    fill_rows = [
        r for r in hist
        if isinstance(r.get("buffer_fill_t"), list)
        and isinstance(r.get("applied_t"), list)
    ]
    if fill_rows:
        fills_t: List[List[float]] = [[] for _ in range(T)]
        for r in fill_rows:
            for t, (f, a) in enumerate(zip(r["buffer_fill_t"], r["applied_t"])):
                if t < T and float(a) > 0:
                    fills_t[t].append(float(f))
        if any(fills_t):
            out["fed_mt_buffer_fill_per_apply"] = [
                (sum(f) / len(f)) if f else 0.0 for f in fills_t
            ]
    return out


# ---------------------------------------------------------------------- #
# summary
# ---------------------------------------------------------------------- #


def cmd_summary(args) -> int:
    run = _resolve_run(args.run)
    if run is None:
        return _fail(f"no run directory under {args.run!r} (need config.json)")
    rep = _run_report(run, args.include_warmup)
    if args.json:
        print(json.dumps(rep, indent=2))
        return 0
    cfg = rep["config"]
    print(f"run {rep['run']}  ({rep['dir']})")
    if cfg:
        knobs = ", ".join(f"{k}={v}" for k, v in sorted(cfg.items()))
        print(f"  config: {knobs}")
    print(f"  steps logged: {rep['steps_logged']}")
    if rep["loss_first"] is not None:
        print(f"  loss: {rep['loss_first']:.4f} -> {rep['loss_last']:.4f}")
    print(f"  rel_volume: {_fmt_dist(rep['rel_volume'])}")
    print(f"  step_time:  {_fmt_dist(rep['step_time_s'], 's')}")
    if "fedsim" in rep:
        fed = rep["fedsim"]
        print("  fedsim:")
        if "clients_per_sec" in fed:
            print(f"    clients_per_sec: {_fmt_dist(fed['clients_per_sec'])}")
        print(
            "    uplink_bytes_per_round: "
            f"{_fmt_dist(fed['uplink_bytes_per_round'], 'B')}"
        )
        print(
            "    clients_per_round: "
            f"{_fmt_dist(fed['clients_per_round'])}"
        )
        if "checksum_failures_total" in fed:
            print(
                f"    checksum_failures_total: {fed['checksum_failures_total']:.6g}"
            )
        if "fed_staleness_mean" in fed:
            print(f"    fed_staleness_mean: {fed['fed_staleness_mean']:.6g}")
        if "fed_staleness_max" in fed:
            print(f"    fed_staleness_max: {fed['fed_staleness_max']:.6g}")
        if "fed_staleness_p95" in fed:
            print(
                "    fed_staleness_tail: "
                f"p50 {fed['fed_staleness_p50']:.6g}  "
                f"p95 {fed['fed_staleness_p95']:.6g}  "
                f"p99 {fed['fed_staleness_p99']:.6g}  "
                "(exact, on-device histogram)"
            )
        if "fed_buffer_fill_per_apply" in fed:
            print(
                "    fed_buffer_fill_per_apply: "
                f"{fed['fed_buffer_fill_per_apply']:.6g}"
            )
        if "fed_pop_classes" in fed:
            print(f"    fed_pop_classes: {fed['fed_pop_classes']}")
            shares = ", ".join(f"{v:.6g}" for v in fed["fed_pop_shares"])
            print(
                f"    fed_pop_shares: [{shares}]  "
                "(exact, on-device histogram)"
            )
            print(
                "    fed_pop_residency_min: "
                f"{fed['fed_pop_residency_min']:.6g}"
            )
        if "fed_tenants" in fed:
            print(f"    fed_tenants: {fed['fed_tenants']}")
            for row in (
                "fed_mt_clients_per_sec",
                "fed_mt_staleness_mean",
                "fed_mt_staleness_max",
                "fed_mt_staleness_p50",
                "fed_mt_staleness_p95",
                "fed_mt_staleness_p99",
                "fed_mt_buffer_fill_per_apply",
            ):
                if row in fed:
                    vals = ", ".join(f"{v:.6g}" for v in fed[row])
                    print(f"    {row}: [{vals}]")
    if "ctrl" in rep:
        ctrl = rep["ctrl"]
        print("  ctrl (adaptive compression controller):")
        print(
            f"    decisions: {ctrl['decisions']}  switches: {ctrl['switches']}"
            f"  final: rung {ctrl['final_index']} (ratio {ctrl['final_ratio']})"
        )
        print(f"    ctrl_switches_per_step: {ctrl['ctrl_switches_per_step']:.6g}")
        if ctrl["effective_ratio"] is not None:
            print(f"    effective_ratio: {ctrl['effective_ratio']:.6g}")
        for line in ctrl["trail"]:
            print(f"    switch {line}")
    if "telemetry" in rep:
        print("  device accumulators:")
        for k, v in sorted(rep["telemetry"].items()):
            print(f"    {k}: {v:.6g}" if isinstance(v, float) else f"    {k}: {v}")
    elif rep.get("telemetry_off"):
        print(
            "  telemetry: was off for this run — no device accumulators or "
            "span trace (re-run with --telemetry to record them)"
        )
    return 0


# ---------------------------------------------------------------------- #
# compare
# ---------------------------------------------------------------------- #


def _bench_step_time(bench: Dict[str, Any], strategy: str) -> Optional[float]:
    strategies = bench.get("detail", {}).get("strategies", {})
    rec = strategies.get(strategy)
    if isinstance(rec, dict) and isinstance(rec.get("t_step_s"), (int, float)):
        return float(rec["t_step_s"])
    return None


def _wire_to_loss(hist: List[Dict[str, Any]], target: float):
    """(cumulative rel_volume, step) at the first record whose running-min
    loss reaches `target`, or None if the run never gets there. rel_volume
    is proportional to wire bytes when both runs share the model, so the
    cumulative sum compares total gradient traffic at matched loss."""
    best = float("inf")
    wire = 0.0
    for rec in hist:
        rv = rec.get("rel_volume")
        loss = rec.get("loss")
        if isinstance(rv, (int, float)):
            wire += float(rv)
        if isinstance(loss, (int, float)):
            best = min(best, float(loss))
            if best <= target:
                return wire, int(rec.get("step", 0))
    return None


def _compare_ctrl(run_a, rep_a, run_b, rep_b) -> int:
    """`compare A B --ctrl`: A is the adaptive run, B the fixed baseline.
    Matched-loss wire comparison — target is the WORSE of the two best
    (running-min) losses, so both runs provably reached it; exits 1 when
    the adaptive run spent at least as much wire getting there."""
    hist_a = _history(run_a)
    hist_b = _history(run_b)
    loss_a = _series(hist_a, "loss")
    loss_b = _series(hist_b, "loss")
    if not loss_a or not loss_b:
        return _fail("--ctrl compare needs loss series in both runs")
    target = max(min(loss_a), min(loss_b))
    at_a = _wire_to_loss(hist_a, target)
    at_b = _wire_to_loss(hist_b, target)
    if at_a is None or at_b is None:
        return _fail("--ctrl compare: a run never reached the matched loss")
    wire_a, step_a = at_a
    wire_b, step_b = at_b
    ctrl_a = rep_a.get("ctrl")
    print(f"adaptive: {rep_a['run']}   fixed: {rep_b['run']}")
    print(f"  matched loss target: {target:.6g}")
    print(f"  adaptive: reached at step {step_a}, cum rel_volume {wire_a:.6g}")
    print(f"  fixed:    reached at step {step_b}, cum rel_volume {wire_b:.6g}")
    if ctrl_a:
        er = ctrl_a.get("effective_ratio")
        if er is not None:
            print(f"  adaptive effective_ratio: {er:.6g} "
                  f"({ctrl_a['switches']} switches)")
    if wire_b > 0:
        print(f"  wire adaptive/fixed: {wire_a / wire_b:.3f}x")
    if wire_a >= wire_b:
        print("  REGRESSION: adaptive run spent >= wire of fixed at matched loss")
        return 1
    print("  ok: adaptive reached matched loss on less wire")
    return 0


def _profile_points(detail: Dict[str, Any]) -> Optional[List[Dict[str, Any]]]:
    """Sweep points a machine profile can re-price. BENCH_CALIB records
    carry an explicit `detail.points` list; single-point hier records
    (BENCH_HIER_r12) carry the plan shape at the top of `detail`."""
    pts = detail.get("points")
    if isinstance(pts, list) and pts:
        return [p for p in pts if isinstance(p, dict)]
    if {"d", "ratio", "n_slices", "per_slice"} <= set(detail):
        return [detail]
    return None


def _point_pick(pt: Dict[str, Any], prof) -> Optional[tuple]:
    """Plan pick for one bench sweep point under a machine profile
    (prof=None selects with the static constants). Hier-shaped points
    (n_slices/per_slice) go through `select_hier_plan`; rs-shaped points
    (`workers`) through `select_rs_mode`. Returns
    (label, pick_key, modeled_step_s) or None when the point carries
    neither shape."""
    d = int(pt.get("d", 0))
    ratio = float(pt.get("ratio", 0.0))
    if not d:
        return None
    if "n_slices" in pt and "per_slice" in pt:
        n_slices, per_slice = int(pt["n_slices"]), int(pt["per_slice"])
        plan = costmodel.select_hier_plan(
            d, n_slices, per_slice, ratio, profile=prof
        )
        key = f"{plan['ici']}+{plan['dcn']}"
        label = f"d={d} ratio={ratio:g} {n_slices}x{per_slice}"
        return (label, key, float(plan["modeled_step_s"]))
    if "workers" in pt:
        W = int(pt["workers"])
        mode = costmodel.select_rs_mode(d, W, ratio, profile=prof)
        t = costmodel.rs_step_time(mode, d, W, ratio, profile=prof)
        return (f"d={d} ratio={ratio:g} W={W}", mode, float(t))
    return None


def _point_price(pt: Dict[str, Any], key: str, prof) -> Optional[float]:
    """Price a specific pick `key` for a sweep point under a profile —
    what the other side's choice would cost on this machine."""
    d = int(pt.get("d", 0))
    ratio = float(pt.get("ratio", 0.0))
    if "n_slices" in pt and "per_slice" in pt:
        plan = costmodel.select_hier_plan(
            d, int(pt["n_slices"]), int(pt["per_slice"]), ratio, profile=prof
        )
        t = plan["table"].get(key)
        return float(t) if t is not None else None
    if "workers" in pt:
        return float(
            costmodel.rs_step_time(key, d, int(pt["workers"]), ratio,
                                   profile=prof)
        )
    return None


def _compare_profile(args) -> int:
    """`compare --profile P --against BENCH.json`: re-price a committed
    bench claim under a fitted machine profile. For each hier-shaped sweep
    point the static `select_hier_plan` pick and the profile-driven pick
    are compared; when they disagree, the static pick is also priced under
    the fitted model to show what the constants would have cost on this
    machine. rs-shaped points (`workers` instead of slices) get the same
    treatment through `select_rs_mode`. Passing --profile TWICE compares
    profile-A picks against profile-B picks instead of static vs fitted.
    Informational: exits 0 — the exit-code-gated cross-profile sentinel
    is the `profiles` subcommand."""
    if len(args.profile) > 2:
        return _fail("compare takes at most two --profile flags")
    profs = []
    for path in args.profile:
        try:
            profs.append((path, costmodel.load_profile(path)))
        except (OSError, ValueError) as e:
            return _fail(f"cannot load profile {path!r}: {e}")
    if len(profs) == 2:
        (name_a, prof_a), (name_b, prof_b) = profs
    else:
        name_a, prof_a = "static", None
        name_b, prof_b = profs[0]
    bench = _load_json(pathlib.Path(args.against))
    if not bench:
        return _fail(f"cannot read bench record {args.against!r}")
    detail = bench.get("detail", {})
    points = _profile_points(detail if isinstance(detail, dict) else {})
    if points is None:
        return _fail(
            f"{args.against!r} has no profile-repriceable sweep points "
            "(need detail.points, or d/ratio/n_slices/per_slice in detail)"
        )
    print(f"re-pricing {args.against}: {name_a} vs {name_b}")
    for name, prof in profs:
        print(
            f"  {name}: bw_dcn {prof.bw_dcn:.4g} B/s  bw_ici "
            f"{prof.bw_ici:.4g} B/s  t_enc {prof.t_enc_s:.4g}s  t_dec "
            f"{prof.t_dec_s:.4g}s  {len(prof.routes)} route row(s)  "
            f"(fitted: {', '.join(prof.fitted) or 'none'})"
        )
    disagreements = 0
    for pt in points:
        got_a = _point_pick(pt, prof_a)
        got_b = _point_pick(pt, prof_b)
        if got_a is None or got_b is None:
            continue
        label, a_key, _ = got_a
        _, b_key, b_time = got_b
        if a_key == b_key:
            print(
                f"  {label}: {name_a} and {name_b} agree on {a_key} "
                f"({b_time:.6g}s under {name_b}'s model)"
            )
        else:
            disagreements += 1
            a_under_b = _point_price(pt, a_key, prof_b)
            priced = (
                f"({a_under_b:.6g}s under {name_b}'s model), "
                f"{name_b} picks {b_key} ({b_time:.6g}s, "
                f"{a_under_b / b_time:.2f}x better)"
                if a_under_b
                else f"{name_b} picks {b_key} ({b_time:.6g}s)"
            )
            print(f"  {label}: DISAGREE — {name_a} picks {a_key} {priced}")
    print(f"  {disagreements} pick disagreement(s) across {len(points)} point(s)")
    return 0


def cmd_compare(args) -> int:
    if args.profile:
        if not args.against:
            return _fail("--profile needs --against BENCH.json to re-price")
        return _compare_profile(args)
    if not args.run_a:
        return _fail("compare needs RUN_A (or --profile --against)")
    run_a = _resolve_run(args.run_a)
    if run_a is None:
        return _fail(f"no run directory under {args.run_a!r}")
    rep_a = _run_report(run_a, args.include_warmup)
    t_a = rep_a["step_time_s"].get("mean")

    if args.against:
        bench = _load_json(pathlib.Path(args.against))
        if not bench:
            return _fail(f"cannot read bench record {args.against!r}")
        strategy = str(rep_a["config"].get("decode_strategy", "loop"))
        t_bench = _bench_step_time(bench, strategy)
        if t_bench is None:
            return _fail(
                f"{args.against!r} has no detail.strategies[{strategy!r}]"
                ".t_step_s entry"
            )
        if t_a is None:
            return _fail(f"run {rep_a['run']} has no step-time samples")
        ratio = t_a / t_bench
        regressed = t_a > t_bench * (1.0 + args.tol)
        flag = "REGRESSION" if regressed else "ok"
        print(
            f"{rep_a['run']} [{strategy}]: step_time mean {t_a:.6g}s vs bench "
            f"{t_bench:.6g}s  ({ratio:.2f}x, tol {args.tol:.0%})  {flag}"
        )
        return 1 if regressed else 0

    if not args.run_b:
        return _fail("compare needs RUN_B or --against BENCH.json")
    run_b = _resolve_run(args.run_b)
    if run_b is None:
        return _fail(f"no run directory under {args.run_b!r}")
    rep_b = _run_report(run_b, args.include_warmup)
    t_b = rep_b["step_time_s"].get("mean")

    if args.ctrl:
        return _compare_ctrl(run_a, rep_a, run_b, rep_b)
    print(f"A: {rep_a['run']}   B: {rep_b['run']}")
    print(f"  step_time A: {_fmt_dist(rep_a['step_time_s'], 's')}")
    print(f"  step_time B: {_fmt_dist(rep_b['step_time_s'], 's')}")
    print(f"  rel_volume A: {_fmt_dist(rep_a['rel_volume'])}")
    print(f"  rel_volume B: {_fmt_dist(rep_b['rel_volume'])}")
    rv_a = rep_a["rel_volume"].get("mean")
    rv_b = rep_b["rel_volume"].get("mean")
    if rv_a and rv_b:
        print(f"  rel_volume B/A: {rv_b / rv_a:.3f}x")
    if t_a and t_b:
        print(f"  step_time  B/A: {t_b / t_a:.3f}x")
        if t_b > t_a * (1.0 + args.tol):
            print(f"  REGRESSION: B exceeds A by more than {args.tol:.0%}")
            return 1
    return 0


# ---------------------------------------------------------------------- #
# calibrate
# ---------------------------------------------------------------------- #


def cmd_calibrate(args) -> int:
    run = _resolve_run(args.run)
    if run is None:
        return _fail(f"no run directory under {args.run!r}")
    try:
        prof = costmodel.calibrate(run, include_warmup=args.include_warmup)
    except (ValueError, OSError) as e:
        return _fail(str(e))
    rec = prof.to_record()
    costmodel.validate_profile(rec)  # never emit an invalid profile
    src = prof.source
    T = float(src["measured_step_s"])
    P = float(src["predicted_step_s"])
    err = abs(P - T) / T if T > 0 else float("inf")
    if args.json:
        print(json.dumps(rec, indent=2))
    else:
        print(f"calibrate: run {run.name}  (W={src['workers']})")
        print(
            f"  steps: {src['steps_measured']} measured of "
            f"{src['steps_total']} ({src['warmup_dropped']} warmup dropped; "
            f"{src['step_time_source']})"
        )
        print(
            f"  measured step {T:.6g}s  predicted {P:.6g}s  "
            f"(error {err:.2%}, tol {args.tol:.0%})"
        )
        print(
            f"  components: encode {src['encode_s']:.6g}s  decode "
            f"{src['decode_s']:.6g}s  wire_dcn {src['wire_dcn_s']:.6g}s  "
            f"wire_ici {src['wire_ici_s']:.6g}s  compute "
            f"{src['compute_s']:.6g}s  other {src['other_s']:.6g}s"
        )
        print(
            f"  fitted: bw_dcn {prof.bw_dcn:.6g} B/s  bw_ici "
            f"{prof.bw_ici:.6g} B/s  t_enc {prof.t_enc_s:.6g}s  t_dec "
            f"{prof.t_dec_s:.6g}s  compute {prof.compute_time_s:.6g}s"
        )
        print(f"    measured: {', '.join(prof.fitted) or '(none)'}")
        print(f"    held at static constants: {', '.join(prof.fixed) or '(none)'}")
    if args.out:
        prof.save(args.out)
        print(f"wrote profile -> {args.out}")
    if err > args.tol:
        print(
            f"calibrate: REGRESSION: predicted step time misses measured by "
            f"{err:.2%} (> {args.tol:.0%}) — the fit does not explain this run",
            file=sys.stderr,
        )
        return 1
    missing = [p for p in (args.require_fitted or []) if p not in prof.fitted]
    if missing:
        print(
            f"calibrate: REGRESSION: required parameter(s) left at static "
            f"constants instead of fitted: {', '.join(missing)} "
            f"(fitted: {', '.join(prof.fitted) or 'none'}) — the run's "
            "telemetry carried no signal for them",
            file=sys.stderr,
        )
        return 1
    return 0


# ---------------------------------------------------------------------- #
# profiles (cross-profile drift sentinel)
# ---------------------------------------------------------------------- #

_PROFILE_PARAMS = (
    ("bw_dcn", "B/s"),
    ("bw_ici", "B/s"),
    ("t_enc_s", "s"),
    ("t_dec_s", "s"),
    ("compute_time_s", "s"),
)


def _rel_drift(values: List[float]) -> float:
    hi, lo = max(values), min(values)
    return (hi - lo) / hi if hi > 0 else 0.0


def cmd_profiles(args) -> int:
    """`profiles A.json B.json [...] --against BENCH.json`: the
    cross-profile drift sentinel. Reports (a) per-parameter drift between
    the saved profiles, (b) per-route row disagreement from their v2
    route tables, and (c) — the exit-code-gated part — which committed
    bench plan selections flip between the profiles: for every sweep
    point of every --against record, each profile's pick is computed and
    any point where two profiles disagree counts as a flip. Exits 1 when
    any pick flips; parameter/route drift alone is informational."""
    if len(args.profiles) < 2:
        return _fail("profiles needs at least two PROFILE.json paths")
    profs = []
    for path in args.profiles:
        try:
            profs.append((path, costmodel.load_profile(path)))
        except (OSError, ValueError) as e:
            return _fail(f"cannot load profile {path!r}: {e}")
    names = [name for name, _ in profs]
    print(f"profiles: comparing {len(profs)} profile(s)")
    for name, prof in profs:
        print(
            f"  {name}: sha256 {prof.content_hash()}  "
            f"{len(prof.routes)} route row(s)  "
            f"(fitted: {', '.join(prof.fitted) or 'none'})"
        )

    report: Dict[str, Any] = {"profiles": names, "params": {}, "routes": {},
                              "flips": []}
    print("  parameter drift:")
    for attr, unit in _PROFILE_PARAMS:
        vals = [float(getattr(prof, attr)) for _, prof in profs]
        drift = _rel_drift(vals)
        shown = "  ".join(f"{v:.6g}" for v in vals)
        print(f"    {attr:>15}: {shown} {unit}  (drift {drift:.2%})")
        report["params"][attr] = {"values": vals, "rel_drift": drift}

    labels = sorted({l for _, prof in profs for l in prof.routes})
    if labels:
        print("  route rows (t_enc_s/t_dec_s per route):")
    for label in labels:
        rows = [prof.routes.get(label) for _, prof in profs]
        cells, encs, decs = [], [], []
        for row in rows:
            if row is None:
                cells.append("(absent)")
            else:
                cells.append(f"{row['t_enc_s']:.4g}/{row['t_dec_s']:.4g}")
                encs.append(float(row["t_enc_s"]))
                decs.append(float(row["t_dec_s"]))
        missing = sum(1 for row in rows if row is None)
        drift = max(_rel_drift(encs), _rel_drift(decs)) if len(encs) > 1 else 0.0
        note = f"drift {drift:.2%}" if not missing else f"{missing} absent"
        print(f"    {label:>10}: {'  '.join(cells)}  ({note})")
        report["routes"][label] = {
            "rows": rows, "absent": missing, "rel_drift": drift,
        }

    flips = 0
    total_points = 0
    for bench_path in args.against or []:
        bench = _load_json(pathlib.Path(bench_path))
        if not bench:
            return _fail(f"cannot read bench record {bench_path!r}")
        detail = bench.get("detail", {})
        points = _profile_points(detail if isinstance(detail, dict) else {})
        if points is None:
            return _fail(
                f"{bench_path!r} has no profile-repriceable sweep points "
                "(need detail.points, or d/ratio/n_slices/per_slice in "
                "detail)"
            )
        for pt in points:
            picks = [_point_pick(pt, prof) for _, prof in profs]
            if any(p is None for p in picks):
                continue
            total_points += 1
            label = picks[0][0]
            keys = [p[1] for p in picks]
            if len(set(keys)) > 1:
                flips += 1
                shown = ", ".join(
                    f"{n} -> {k}" for n, k in zip(names, keys)
                )
                print(f"  FLIP {bench_path} {label}: {shown}")
                report["flips"].append(
                    {"bench": bench_path, "point": label,
                     "picks": dict(zip(names, keys))}
                )
            else:
                print(f"  ok   {bench_path} {label}: all pick {keys[0]}")
    if args.json:
        print(json.dumps(report, indent=2))
    print(
        f"profiles: {flips} plan flip(s) across {total_points} bench "
        f"point(s) from {len(args.against or [])} record(s)"
    )
    if flips:
        print(
            "profiles: REGRESSION: plan selections flip between profiles — "
            "the machines (or the fits) disagree enough to change decisions",
            file=sys.stderr,
        )
        return 1
    return 0


# ---------------------------------------------------------------------- #
# slo (health verdict, exit-gated)
# ---------------------------------------------------------------------- #


def cmd_slo(args) -> int:
    """`slo RUN --spec slo.json`: replay the run's metrics.jsonl tick
    stream through a fresh HealthMonitor and print the per-tenant verdict
    table. Exit 1 when any tenant ends in BREACH — the gate a CI job or a
    bench claim can hang a "healthy at N clients/s" statement on. The
    monitor consumes only recorded rows, so re-running the command on the
    same run dir is bitwise-repeatable."""
    run = _resolve_run(args.run)
    if run is None:
        return _fail(f"no run directory under {args.run!r}")
    from deepreduce_tpu.slo import HealthMonitor, SLOSpec

    try:
        spec = SLOSpec.load(args.spec)
    except ValueError as e:
        return _fail(str(e))
    if spec.is_noop:
        print(f"slo: run {run.name}: spec {args.spec} sets no targets — "
              "nothing to monitor (degenerate spec, monitor is a no-op)")
        return 0
    hist = _history(run)
    rows = [
        r for r in hist
        if isinstance(r.get("clients"), (int, float))
        or isinstance(r.get("clients_t"), list)
    ]
    if not rows:
        return _fail(
            f"run {run.name} has no federated tick rows (clients / "
            "clients_t) for the SLO monitor"
        )
    monitor = HealthMonitor(spec)
    tenants = 1
    rates: Dict[int, List[float]] = {}
    prev_ts: Optional[float] = None
    try:
        for i, r in enumerate(rows):
            tick = (
                int(r["round"])
                if isinstance(r.get("round"), (int, float))
                else i
            )
            ts = r.get("ts")
            dt = None
            if (isinstance(ts, (int, float))
                    and isinstance(prev_ts, (int, float)) and ts > prev_ts):
                dt = ts - prev_ts
            if isinstance(ts, (int, float)):
                prev_ts = ts
            if isinstance(r.get("clients_t"), list):
                T = len(r["clients_t"])
                tenants = max(tenants, T)
                for t in range(T):

                    def pick(key):
                        v = r.get(key)
                        if isinstance(v, list) and t < len(v):
                            return v[t]
                        return None

                    rep = {
                        "clients": pick("clients_t"),
                        "checksum_failures": pick("checksum_failures_t"),
                        "buffer_fill": pick("buffer_fill_t"),
                        "w_rel_err": pick("w_rel_err_t"),
                    }
                    hl = r.get("staleness_hist_t")
                    if (isinstance(hl, list) and t < len(hl)
                            and isinstance(hl[t], list)):
                        rep["staleness_hist"] = hl[t]
                    if dt and rep["clients"] is not None:
                        rep["clients_per_sec"] = float(rep["clients"]) / dt
                        rates.setdefault(t, []).append(
                            rep["clients_per_sec"]
                        )
                    monitor.observe(tick, rep, tenant=t)
            else:
                rep = {
                    "clients": r.get("clients"),
                    "checksum_failures": r.get("checksum_failures"),
                    "buffer_fill": r.get("buffer_fill"),
                    "w_rel_err": r.get("w_rel_err"),
                }
                if isinstance(r.get("staleness_hist"), list):
                    rep["staleness_hist"] = r["staleness_hist"]
                if isinstance(r.get("pop_hist"), list):
                    rep["pop_hist"] = r["pop_hist"]
                if dt and isinstance(r.get("clients"), (int, float)):
                    rep["clients_per_sec"] = float(r["clients"]) / dt
                    rates.setdefault(0, []).append(rep["clients_per_sec"])
                monitor.observe(tick, rep)
    except ValueError as e:
        return _fail(f"run {run.name}: {e}")

    verdicts = [monitor.verdict(t) for t in range(tenants)]
    states = [v["state"] for v in verdicts]
    if args.json:
        print(json.dumps(
            {
                "run": run.name,
                "spec": spec.to_dict(),
                "ticks": len(rows),
                "events": monitor.events,
                "verdicts": verdicts,
            },
            indent=2,
        ))
        return 1 if "BREACH" in states else 0
    print(f"slo: run {run.name}  spec {args.spec}  "
          f"({len(rows)} tick(s), {tenants} tenant(s))")
    if monitor.events:
        print(f"  {len(monitor.events)} health transition(s):")
        for ev in monitor.events:
            detail = ""
            if ev["value"] is not None:
                detail = f"  {ev['value']:.6g} vs {ev['threshold']:.6g}"
            print(
                f"    tick {ev['tick']} tenant {ev['tenant']}: "
                f"{ev['from_state']} -> {ev['to_state']} "
                f"({ev['trigger']}){detail}"
            )
    else:
        print("  0 health transitions")
    for v in verdicts:
        t = v["tenant"]
        print(f"  tenant {t}: {v['state']}")
        if t in rates:
            print(f"    clients_per_sec: {_fmt_dist(_dist(rates[t]))}")
        for key, row in v["targets"].items():
            if row["value"] is None:
                shown = "(no data)"
            else:
                shown = f"{row['value']:.6g} vs {row['threshold']:.6g}"
            burn = ""
            if row["burn_fast"] is not None:
                burn = (f"  burn fast {row['burn_fast']:.3g}x / "
                        f"slow {row['burn_slow']:.3g}x")
            flag = "ok" if row["ok"] else "VIOLATED"
            print(f"    {key}: {shown}{burn}  {flag}")
    if "BREACH" in states:
        print("slo: BREACH — at least one tenant ends outside its SLO",
              file=sys.stderr)
        return 1
    return 0


# ---------------------------------------------------------------------- #
# bench-history (longitudinal ledger view)
# ---------------------------------------------------------------------- #


def cmd_bench_history(args) -> int:
    """`bench-history [DIR]`: one provenance-stamped trend row per
    committed BENCH_*.json record, ordered by the round number parsed
    from the filename. Modern records carry metric/value/unit/platform
    (+ optional provenance lists and profile_sha256); the r01–r05 raw-log
    records and the TPU midround record render as `legacy` rows from
    their parsed/headline payloads. A record matching NONE of those
    shapes exits 2 — the ledger is an interface, not a junk drawer."""
    import re

    root = pathlib.Path(args.dir)
    paths = sorted(root.glob("BENCH_*.json"))
    if not paths:
        return _fail(f"no BENCH_*.json records under {root}")
    rows: List[Dict[str, Any]] = []
    for path in paths:
        try:
            rec = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError) as e:
            return _fail(f"{path.name}: unreadable bench record: {e}")
        m = re.search(r"_r(\d+)", path.stem)
        row: Dict[str, Any] = {
            "round": int(m.group(1)) if m else -1,
            "file": path.name,
        }
        detail = rec.get("detail")
        sha = rec.get("profile_sha256")
        if sha is None and isinstance(detail, dict):
            sha = detail.get("profile_sha256")
        if isinstance(rec.get("metric"), str):
            prov = rec.get("provenance")
            if isinstance(prov, dict):
                has_mod = bool(prov.get("modeled"))
                has_meas = bool(prov.get("measured"))
                stamp = (
                    "modeled+measured" if has_mod and has_meas
                    else "modeled" if has_mod
                    else "measured" if has_meas
                    else "unstamped"
                )
            else:
                stamp = "unstamped"
            row.update(
                metric=rec["metric"],
                value=rec.get("value"),
                unit=rec.get("unit", ""),
                platform=rec.get("platform", "?"),
                provenance=stamp,
            )
        elif {"cmd", "rc", "n"} <= set(rec):
            parsed = rec.get("parsed")
            parsed = parsed if isinstance(parsed, dict) else {}
            row.update(
                metric=parsed.get("metric", "(raw log)"),
                value=parsed.get("value"),
                unit=parsed.get("unit", ""),
                platform=rec.get("platform", "?"),
                provenance="legacy",
            )
        elif (isinstance(rec.get("headline"), dict)
              and isinstance(rec["headline"].get("metric"), str)):
            h = rec["headline"]
            row.update(
                metric=h["metric"],
                value=h.get("value"),
                unit=h.get("unit", ""),
                platform=rec.get("platform", "?"),
                provenance="legacy",
            )
        else:
            return _fail(
                f"{path.name}: schema-less bench record — carries neither "
                "a 'metric' headline, a raw-log (cmd/rc/n) shape, nor a "
                "'headline' block"
            )
        if sha:
            row["profile_sha256"] = sha
        rows.append(row)
    rows.sort(key=lambda r: (r["round"], r["file"]))
    if args.json:
        print(json.dumps(rows, indent=2))
        return 0
    print(f"bench-history: {len(rows)} record(s) under {root}")
    for row in rows:
        val = (
            f" = {row['value']:.6g}{row['unit']}"
            if isinstance(row["value"], (int, float))
            else ""
        )
        sha = (
            f"  profile:{str(row['profile_sha256'])[:12]}"
            if "profile_sha256" in row
            else ""
        )
        print(
            f"  r{row['round']:02d}  {row['file']:<28} "
            f"{row['metric']}{val}  [{row['platform']}]  "
            f"{row['provenance']}{sha}"
        )
    return 0


# ---------------------------------------------------------------------- #
# trace
# ---------------------------------------------------------------------- #


def _x_intervals(events, *, name: str = "", prefix: str = ""):
    """Sorted (start, end) µs intervals of the complete ("X") span events
    matching an exact name or a name prefix."""
    out = []
    for e in events:
        if e.get("ph") != "X":
            continue
        n = e.get("name", "")
        if name and n != name:
            continue
        if prefix and not n.startswith(prefix):
            continue
        ts, dur = e.get("ts"), e.get("dur")
        if isinstance(ts, (int, float)) and isinstance(dur, (int, float)):
            out.append((float(ts), float(ts) + float(dur)))
    out.sort()
    return out


def _trace_overlap(run: pathlib.Path, events, threshold: float) -> int:
    """`trace RUN --overlap`: wall-clock overlap fraction between the
    forward+backward spans and the per-bucket exchange dispatch spans.

    Each `train/forward_backward` interval is one step; the
    `exchange/bucket/*` spans starting in [step_i, step_{i+1}) belong to
    step i, and the step's overlap fraction is the share of their total
    duration that falls INSIDE the forward_backward interval. Streaming
    runs (cfg.stream_exchange) dispatch every bucket from the backward
    pass, so the fraction is ~1; barrier/pipeline runs dispatch from
    `train/exchange` after backward completes, so it is 0 — which makes
    the threshold a CI gate that the overlap structurally happened.
    Exits 1 below `--overlap-threshold`, 2 when the run has no usable
    span structure (no trace, no forward_backward span, no bucket spans).

    Composed runs on the (dcn, ici) mesh (cfg.stream_exchange AND
    cfg.hier) nest two more spans inside each bucket dispatch:
    `exchange/ici` (the bucket's dense slice-mean psum, run in the
    pre_encode slot) and `exchange/dcn` (the compressed gather half).
    When those spans are present the report attributes each leg to its
    forward_backward step window separately and the gate takes the
    MINIMUM fraction across bucket/dcn/ici — a composed run only passes
    when BOTH legs actually dispatched from inside backprop, not just
    the bucket wrapper. Flat streaming runs have no leg spans and keep
    the historical single-fraction behavior.
    """
    fb = _x_intervals(events, name="train/forward_backward")
    buckets = _x_intervals(events, prefix="exchange/bucket/")
    legs = {
        "dcn": _x_intervals(events, name="exchange/dcn"),
        "ici": _x_intervals(events, name="exchange/ici"),
    }
    if not fb:
        return _fail(
            f"run {run.name} has no train/forward_backward spans "
            "(telemetry off, or trace.json missing)"
        )
    if not buckets:
        return _fail(
            f"run {run.name} has no exchange/bucket/* spans — overlap "
            "needs the bucketed exchange (cfg.bucket_bytes)"
        )
    per_step = []
    tot_dur = tot_in = 0.0
    for i, (s, e) in enumerate(fb):
        nxt = fb[i + 1][0] if i + 1 < len(fb) else float("inf")
        mine = [(bs, be) for bs, be in buckets if s <= bs < nxt]
        if not mine:
            continue
        dur = sum(be - bs for bs, be in mine)
        inside = sum(
            max(0.0, min(be, e) - max(bs, s)) for bs, be in mine
        )
        tot_dur += dur
        tot_in += inside
        per_step.append((i, len(mine), inside / dur if dur else 0.0))
    if not per_step:
        return _fail(
            f"run {run.name}: no exchange/bucket/* span falls in any "
            "forward_backward step window"
        )
    frac = tot_in / tot_dur if tot_dur else 0.0
    # hierarchical leg attribution: same step-windowed accounting per leg
    leg_fracs = {}
    for leg, spans_ in legs.items():
        if not spans_:
            continue
        l_dur = l_in = 0.0
        for i, (s, e) in enumerate(fb):
            nxt = fb[i + 1][0] if i + 1 < len(fb) else float("inf")
            mine = [(ls, le) for ls, le in spans_ if s <= ls < nxt]
            l_dur += sum(le - ls for ls, le in mine)
            l_in += sum(
                max(0.0, min(le, e) - max(ls, s)) for ls, le in mine
            )
        leg_fracs[leg] = l_in / l_dur if l_dur else 0.0
    gate = min([frac, *leg_fracs.values()])
    print(f"overlap: run {run.name}")
    print(
        f"  forward_backward spans: {len(fb)}   "
        f"exchange/bucket spans: {len(buckets)}"
    )
    for i, n, f in per_step:
        print(f"  step {i}: {n} bucket dispatches, overlap fraction {f:.3f}")
    if leg_fracs:
        print(
            "  composed legs: "
            + "   ".join(
                f"exchange/{leg}: {len(legs[leg])} spans, "
                f"fraction {f:.3f}"
                for leg, f in sorted(leg_fracs.items())
            )
        )
    flag = "ok" if gate >= threshold else "BELOW THRESHOLD"
    print(
        f"  overall: {tot_in:.1f}us of {tot_dur:.1f}us bucket-dispatch time "
        f"inside forward_backward  (fraction {frac:.3f}"
        + (
            f", gate min over legs {gate:.3f}" if leg_fracs else ""
        )
        + f", threshold {threshold:g})  {flag}"
    )
    return 0 if gate >= threshold else 1


def cmd_trace(args) -> int:
    run = _resolve_run(args.run)
    if run is None:
        return _fail(f"no run directory under {args.run!r}")
    trace = _load_json(run / "trace.json")
    events = list(trace.get("traceEvents", []))
    if args.overlap:
        return _trace_overlap(run, events, args.overlap_threshold)
    hist = _history(run)
    # per-step metrics become counter tracks next to the span rows; their
    # wall clock is rebased so step 0 aligns with the trace origin
    ts0 = next((r["ts"] for r in hist if "ts" in r), None)
    for rec in hist:
        if "ts" not in rec:
            continue
        for key, val in rec.items():
            if key in ("step", "ts") or not isinstance(val, (int, float)):
                continue
            events.append(
                {
                    "name": key,
                    "ph": "C",
                    "ts": round((rec["ts"] - ts0) * 1e6, 3),
                    "pid": 1,
                    "tid": 0,
                    "args": {key: float(val)},
                }
            )
    # per-tick staleness percentiles become counter tracks of their own:
    # the logged staleness_hist rows are lists (skipped by the scalar
    # counter loop above), so read the exact discrete quantiles off each
    # tick's histogram — the Perfetto view of the SLO plane's tail signal
    st_rows = [
        r for r in hist
        if "ts" in r and isinstance(r.get("staleness_hist"), list)
        and r["staleness_hist"]
    ]
    if st_rows and ts0 is not None:
        from deepreduce_tpu.telemetry.device_metrics import hist_quantile

        for rec in st_rows:
            ts = round((rec["ts"] - ts0) * 1e6, 3)
            for q, name in ((0.50, "fed_staleness_p50"),
                            (0.95, "fed_staleness_p95"),
                            (0.99, "fed_staleness_p99")):
                events.append(
                    {"name": name, "ph": "C", "ts": ts, "pid": 1, "tid": 0,
                     "args": {name: float(
                         hist_quantile(rec["staleness_hist"], q)
                     )}}
                )
    # per-tick per-class participation shares become counter tracks too
    # (fed_pop_share_c{k}): the pop_hist rows are lists like the staleness
    # histograms, so derive each class's share per tick
    pop_rows = [
        r for r in hist
        if "ts" in r and isinstance(r.get("pop_hist"), list)
        and r["pop_hist"]
    ]
    if pop_rows and ts0 is not None:
        for rec in pop_rows:
            ts = round((rec["ts"] - ts0) * 1e6, 3)
            total = max(sum(float(v) for v in rec["pop_hist"]), 1.0)
            for k, v in enumerate(rec["pop_hist"]):
                name = f"fed_pop_share_c{k}"
                events.append(
                    {"name": name, "ph": "C", "ts": ts, "pid": 1, "tid": 0,
                     "args": {name: float(v) / total}}
                )
    # SLO health transitions (health.jsonl) become global instant markers,
    # anchored like ctrl decisions: the records carry no wall clock by
    # design, so map tick -> ts via the metrics.jsonl round column
    hpath = run / "health.jsonl"
    if hpath.exists() and ts0 is not None:
        round_ts = {
            int(r["round"]): r["ts"]
            for r in hist
            if isinstance(r.get("round"), (int, float)) and "ts" in r
        }
        max_known = max(round_ts) if round_ts else 0
        with open(hpath) as f:
            hrecs = [json.loads(ln) for ln in f if ln.strip()]
        for rec in hrecs:
            tick = int(rec.get("tick", 0))
            anchor = tick if tick in round_ts else min(tick, max_known)
            while anchor > 0 and anchor not in round_ts:
                anchor -= 1
            ts = round((round_ts.get(anchor, ts0) - ts0) * 1e6, 3)
            events.append(
                {
                    "name": (
                        f"slo {rec.get('from_state')}->"
                        f"{rec.get('to_state')} tenant "
                        f"{rec.get('tenant')} ({rec.get('trigger')})"
                    ),
                    "ph": "i", "s": "g", "ts": ts, "pid": 1, "tid": 0,
                    "args": {
                        "trigger": rec.get("trigger"),
                        "value": rec.get("value"),
                        "threshold": rec.get("threshold"),
                    },
                }
            )
    # adaptive-controller decisions ride along as their own counter tracks
    # (ladder index + active ratio) plus global instant markers at each
    # switch; decision steps are mapped to wall time via metrics.jsonl
    decs = _decisions(run)
    if decs and ts0 is not None:
        step_ts = {
            int(r["step"]): r["ts"]
            for r in hist
            if isinstance(r.get("step"), (int, float)) and "ts" in r
        }
        max_known = max(step_ts) if step_ts else 0
        for d in decs:
            step = int(d.get("step", 0))
            # decisions carry no wall clock by design (bitwise replay);
            # anchor at the nearest logged step at or before the decision
            anchor = step if step in step_ts else min(step, max_known)
            while anchor > 0 and anchor not in step_ts:
                anchor -= 1
            ts = round((step_ts.get(anchor, ts0) - ts0) * 1e6, 3)
            for name, val in (
                ("ctrl_ladder_index", d.get("new_index")),
                ("ctrl_ratio", d.get("new_ratio")),
            ):
                if isinstance(val, (int, float)):
                    events.append(
                        {"name": name, "ph": "C", "ts": ts, "pid": 1, "tid": 0,
                         "args": {name: float(val)}}
                    )
            if d.get("switched"):
                events.append(
                    {
                        "name": (
                            f"ctrl switch {d.get('old_index')}->"
                            f"{d.get('new_index')} ({d.get('rationale')})"
                        ),
                        "ph": "i", "s": "g", "ts": ts, "pid": 1, "tid": 0,
                        "args": {
                            "trigger": d.get("trigger"),
                            "old_ratio": d.get("old_ratio"),
                            "new_ratio": d.get("new_ratio"),
                        },
                    }
                )
    if not events:
        summ = _load_json(run / "summary.json")
        if _telemetry_off(run, summ):
            print(
                f"telemetry: run {run.name}: telemetry was off for this run — "
                "no span trace or metrics to export (re-run with --telemetry)"
            )
            return 0
        return _fail(f"run {run.name} has neither trace.json events nor metrics")
    if not trace.get("traceEvents"):
        print(
            f"telemetry: note: run {run.name} has no span trace "
            "(telemetry was off or trace.json missing); exporting metric "
            "counters only",
            file=sys.stderr,
        )
    events.sort(key=lambda e: e.get("ts", 0.0))
    merged = {"traceEvents": events, "displayTimeUnit": "ms"}
    if args.out and args.out != "-":
        with open(args.out, "w") as f:
            json.dump(merged, f)
        print(f"wrote {len(events)} events -> {args.out}")
    else:
        json.dump(merged, sys.stdout)
        print()
    return 0


# ---------------------------------------------------------------------- #


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deepreduce_tpu.telemetry",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("summary", help="digest one run")
    p.add_argument("run", help="run dir or tracking root (latest run)")
    p.add_argument("--json", action="store_true", help="machine-readable output")
    p.add_argument("--include-warmup", action="store_true",
                   help="keep compile-dominated warmup step times in the "
                        "statistics (dropped by default)")
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("compare", help="diff two runs, or a run vs a bench record")
    p.add_argument("run_a", nargs="?", default="")
    p.add_argument("run_b", nargs="?", default="")
    p.add_argument("--against", default="", metavar="BENCH.json",
                   help="committed bench record (e.g. BENCH_DECODE_r06.json); "
                        "matched on the run's decode_strategy")
    p.add_argument("--tol", type=float, default=0.10,
                   help="step-time regression tolerance (default 10%%)")
    p.add_argument("--ctrl", action="store_true",
                   help="adaptive-vs-fixed mode: RUN_A is the adaptive run, "
                        "RUN_B the fixed baseline; compares cumulative wire "
                        "volume at matched (running-min) loss and exits 1 "
                        "when adaptive spent >= wire")
    p.add_argument("--profile", action="append", default=[],
                   metavar="PROFILE.json",
                   help="fitted machine profile (telemetry calibrate --out); "
                        "with --against, re-prices the bench claim under the "
                        "profile and reports static-vs-calibrated pick "
                        "disagreements (no runs needed); pass twice to "
                        "compare two fitted profiles' picks instead")
    p.add_argument("--include-warmup", action="store_true",
                   help="keep compile-dominated warmup step times in the "
                        "statistics (dropped by default)")
    p.set_defaults(fn=cmd_compare)

    p = sub.add_parser(
        "calibrate",
        help="fit a machine profile (bw/t_enc/t_dec/compute) from a run's "
             "telemetry",
    )
    p.add_argument("run", help="tracking run dir with --telemetry artifacts")
    p.add_argument("--out", default="", metavar="PROFILE.json",
                   help="write the fitted profile record here")
    p.add_argument("--json", action="store_true",
                   help="print the full profile record instead of the digest")
    p.add_argument("--include-warmup", action="store_true",
                   help="keep compile-dominated warmup steps in the fit "
                        "(skews the step-time target; default drops them)")
    p.add_argument("--tol", type=float, default=0.05,
                   help="max |predicted - measured| / measured step time "
                        "before exiting 1 (default 5%%)")
    p.add_argument("--require-fitted", action="append", default=[],
                   metavar="PARAM",
                   help="exit 1 unless this parameter (e.g. bw_ici) came "
                        "out of the fit rather than the static constants; "
                        "repeatable — the CI gate that a hierarchical run "
                        "actually identified its ICI leg")
    p.set_defaults(fn=cmd_calibrate)

    p = sub.add_parser(
        "profiles",
        help="cross-profile drift sentinel: parameter/route drift between "
             "saved machine profiles and which committed bench plan "
             "selections flip between them (exit 1 on any flip)",
    )
    p.add_argument("profiles", nargs="+", metavar="PROFILE.json",
                   help="two or more saved machine profiles to compare")
    p.add_argument("--against", action="append", default=[],
                   metavar="BENCH.json",
                   help="committed bench record whose sweep points are "
                        "re-selected under every profile; repeatable — any "
                        "point whose pick differs between profiles exits 1")
    p.add_argument("--json", action="store_true",
                   help="also print the machine-readable drift report")
    p.set_defaults(fn=cmd_profiles)

    p = sub.add_parser(
        "slo",
        help="per-tenant SLO verdict table from a run's tick stream; "
             "exits 1 when any tenant ends in BREACH",
    )
    p.add_argument("run", help="run dir or tracking root (latest run)")
    p.add_argument("--spec", required=True, metavar="SLO.json",
                   help="schema-validated SLOSpec file (slo/spec.py)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable verdicts/events instead of the "
                        "table")
    p.set_defaults(fn=cmd_slo)

    p = sub.add_parser(
        "bench-history",
        help="longitudinal view of the committed BENCH_*.json ledger: one "
             "provenance-stamped trend row per round (exit 2 on a "
             "schema-less record)",
    )
    p.add_argument("dir", nargs="?", default=".",
                   help="directory holding BENCH_*.json (default: .)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable rows")
    p.set_defaults(fn=cmd_bench_history)

    p = sub.add_parser("trace", help="merged Chrome trace JSON (Perfetto)")
    p.add_argument("run")
    p.add_argument("--out", default="-", help="output path ('-' = stdout)")
    p.add_argument("--overlap", action="store_true",
                   help="report the wall-clock overlap fraction between "
                        "train/forward_backward and exchange/bucket/* spans "
                        "instead of exporting the trace; exits 1 below "
                        "--overlap-threshold (the streaming-exchange CI gate)")
    p.add_argument("--overlap-threshold", type=float, default=0.5,
                   metavar="FRAC",
                   help="minimum acceptable overlap fraction for --overlap "
                        "(default 0.5; streaming runs sit at ~1, barrier "
                        "runs at 0)")
    p.set_defaults(fn=cmd_trace)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
