"""Host-side hierarchical span tracing — the phase-timing half of telemetry.

The reference observes phase cost with cuda-synchronized wall-clock prints
(pytorch/deepreduce.py:70-76); papers like EQuARX and PacTrain make their
case from fine-grained phase traces instead. This module is that
capability: a `span("exchange/encode")` context manager that

- records a Chrome-trace-event "X" (complete) event — the accumulated
  trace is a ``{"traceEvents": [...]}`` JSON loadable in Perfetto or
  chrome://tracing;
- enters `jax.named_scope(name)`, so spans opened around traced code label
  the generated HLO and the same names appear inside XLA device profiles
  (`--profile_dir`);
- enters `jax.profiler.TraceAnnotation(name)`, so host-side spans show up
  on the profiler's host timeline next to the device rows.

Recording happens on ``__exit__`` regardless of whether the body raised,
so a span around a failing step still reports its elapsed time.

The off switch is structural, not conditional: when the module tracer is
disabled, ``span()`` returns one shared inert context manager — no clock
read, no named_scope, no allocation — so a telemetry-off program traces to
a byte-identical jaxpr (proven by tests/test_telemetry.py against the
analysis retrace hash). Spans are HOST-side objects: they may *wrap*
traced code (they fire once per trace), but must never appear inside codec
bodies — the `ast-span-outside-host` lint rule pins that.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import ExitStack
from typing import Any, Dict, List, Optional

import jax

try:  # host-timeline annotation; absent on some jax builds
    _TraceAnnotation = jax.profiler.TraceAnnotation
except AttributeError:  # pragma: no cover - version drift guard
    _TraceAnnotation = None


class _Span:
    """One live span: wall clock + named_scope + profiler annotation."""

    __slots__ = ("_tracer", "name", "route", "_t0", "_stack")

    def __init__(self, tracer: "Tracer", name: str, route: Optional[str] = None):
        self._tracer = tracer
        self.name = name
        self.route = route

    def __enter__(self) -> "_Span":
        self._stack = ExitStack()
        self._stack.enter_context(jax.named_scope(self.name))
        if _TraceAnnotation is not None:
            self._stack.enter_context(_TraceAnnotation(self.name))
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        # elapsed is taken first and recorded unconditionally: a raising
        # body still reports (the satellite contract metrics.timed shares)
        elapsed = time.perf_counter() - self._t0
        try:
            self._stack.close()
        finally:
            self._tracer._record(self.name, self._t0, elapsed, route=self.route)
        return False


class _NullSpan:
    """The disabled fast path: one shared, stateless, inert context
    manager. Returning this (instead of branching inside a live span)
    is what makes telemetry-off provably free."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL_SPAN = _NullSpan()


class Tracer:
    """Accumulates Chrome-trace-event records (µs, "X" complete events).

    Thread-safe append; per-thread events carry their thread id as `tid`
    so concurrent host work nests correctly in the viewer."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self.events: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._epoch = time.perf_counter()
        self._pid = os.getpid()

    # ------------------------------------------------------------------ #

    def span(self, name: str, route: Optional[str] = None):
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, route)

    def counter(self, name: str, values: Dict[str, float], ts: Optional[float] = None) -> None:
        """Record a Chrome "C" counter sample (e.g. per-step rel_volume)."""
        if not self.enabled:
            return
        now = ts if ts is not None else time.perf_counter()
        ev = {
            "name": name,
            "ph": "C",
            "ts": round((now - self._epoch) * 1e6, 3),
            "pid": self._pid,
            "tid": threading.get_ident(),
            "args": {k: float(v) for k, v in values.items()},
        }
        with self._lock:
            self.events.append(ev)

    def _record(
        self, name: str, t0: float, elapsed: float, route: Optional[str] = None
    ) -> None:
        ev = {
            "name": name,
            "cat": "telemetry",
            "ph": "X",
            "ts": round((t0 - self._epoch) * 1e6, 3),
            "dur": round(elapsed * 1e6, 3),
            "pid": self._pid,
            "tid": threading.get_ident(),
        }
        if route is not None:
            # route/codec attribution: lands in the Chrome event's args so
            # calibrate() can bucket encode/decode self-time per route. The
            # span NAME stays route-free — named_scope labels (and therefore
            # telemetry-on HLO) are identical with or without attribution.
            ev["args"] = {"route": str(route)}
        with self._lock:
            self.events.append(ev)

    # ------------------------------------------------------------------ #

    def reset(self) -> None:
        with self._lock:
            self.events = []
        self._epoch = time.perf_counter()

    def to_chrome_trace(self) -> Dict[str, Any]:
        """The Perfetto-loadable trace object."""
        with self._lock:
            events = list(self.events)
        # viewers sort more cheaply than they merge; emit time-ordered
        events.sort(key=lambda e: e["ts"])
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def save(self, path) -> None:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)


# ---------------------------------------------------------------------- #
# module-level tracer: the one instrumented modules talk to
# ---------------------------------------------------------------------- #

_tracer = Tracer(enabled=False)


def get_tracer() -> Tracer:
    return _tracer


def enabled() -> bool:
    return _tracer.enabled


def configure(*, enabled: Optional[bool] = None, reset: bool = False) -> Tracer:
    """Flip the global tracer on/off and/or clear its event buffer."""
    if reset:
        _tracer.reset()
    if enabled is not None:
        _tracer.enabled = bool(enabled)
    return _tracer


def span(name: str, route: Optional[str] = None):
    """`with span("exchange/encode", route="quantized"): ...` — records wall
    time + labels the XLA profile when telemetry is on; a shared inert no-op
    when off. ``route`` attributes the span to the active exchange route /
    codec (it lands in the trace event's args, never in the scope name), so
    calibrate() can fit per-route encode/decode rows."""
    if not _tracer.enabled:
        return _NULL_SPAN
    return _Span(_tracer, name, route)
