"""On-device running metric accumulators — the zero-sync half of telemetry.

A registered-dataclass pytree of f32 scalar counters that rides through
the jitted train step as an extra carry: every step adds its wire bits,
saturation count, residual L2, compression error (L2 and cosine vs. the
dense mean gradient) and measured bloom false positives *on device*; the
host fetches the whole pytree every `cfg.telemetry_every` steps (one
device-to-host transfer of ten scalars), so the hot loop itself gains zero
host syncs. When `cfg.telemetry=False` the accumulator is never
constructed and the step program is byte-identical to a build without
telemetry (tests/test_telemetry.py pins this with the analysis retrace
hash).

All counters are f32 sums, so cumulative ratios are exact aggregates of
the per-step quantities: ``rel_volume() == Σ(index+value bits)/Σ(dense
bits)`` equals the mean of per-step `WireStats.rel_volume()` whenever
dense_bits is step-constant (it is — shapes are static)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepreduce_tpu.metrics import WireStats

_EPS = 1e-12


def fetch_delta(cur: Dict[str, Any], prev: Dict[str, Any]) -> Dict[str, Any]:
    """Elementwise `cur - prev` of two cumulative `fetch()` snapshots —
    the per-window counters the adaptive controller consumes. Because
    every field is a running sum, the delta of two fetches IS the exact
    accumulation over the steps between them (pinned in
    tests/test_telemetry.py)."""
    out: Dict[str, Any] = {}
    for name in MetricAccumulators.scalar_fields():
        out[name] = cur[name] - prev[name]
    cur_b = cur.get("bucket_saturated", [])
    prev_b = prev.get("bucket_saturated", [])
    if len(cur_b) != len(prev_b):
        raise ValueError(
            f"fetch_delta bucket vector length mismatch: {len(cur_b)} vs {len(prev_b)}"
        )
    out["bucket_saturated"] = [c - p for c, p in zip(cur_b, prev_b)]
    cur_s = cur.get("staleness_hist", [])
    prev_s = prev.get("staleness_hist", [])
    if cur_s or prev_s:
        if len(cur_s) != len(prev_s):
            raise ValueError(
                "fetch_delta staleness_hist length mismatch: "
                f"{len(cur_s)} vs {len(prev_s)}"
            )
        out["staleness_hist"] = [c - p for c, p in zip(cur_s, prev_s)]
    cur_p = cur.get("pop_hist", [])
    prev_p = prev.get("pop_hist", [])
    if cur_p or prev_p:
        if len(cur_p) != len(prev_p):
            raise ValueError(
                "fetch_delta pop_hist length mismatch: "
                f"{len(cur_p)} vs {len(prev_p)}"
            )
        out["pop_hist"] = [c - p for c, p in zip(cur_p, prev_p)]
    return out


def hist_quantile(hist, q: float) -> float:
    """Exact q-quantile of the discrete staleness distribution a counts
    histogram encodes: the smallest level d whose CDF reaches q. Staleness
    levels are integers (indices into the latency distribution), so no
    interpolation is involved — the returned tail is exact, not estimated.
    0.0 on an empty/all-zero histogram (a degenerate run observed nothing)."""
    counts = [max(float(h), 0.0) for h in hist]
    total = sum(counts)
    if total <= 0.0:
        return 0.0
    target = q * total
    cum = 0.0
    for d, h in enumerate(counts):
        cum += h
        # 1e-9 absorbs f32-accumulated rounding at exact-boundary targets
        if cum + 1e-9 >= target:
            return float(d)
    return float(len(counts) - 1)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class MetricAccumulators:
    """Running f32 scalar counters, one pytree, threaded through jit."""

    steps: jax.Array
    index_bits: jax.Array
    value_bits: jax.Array
    dense_bits: jax.Array
    saturated: jax.Array      # total saturated tensor payloads (count)
    residual_l2: jax.Array    # Σ per-step mean-over-workers ‖residual‖₂
    err_l2: jax.Array         # Σ per-step ‖agg − dense_mean‖₂/‖dense_mean‖₂
    err_cos: jax.Array        # Σ per-step cos(agg, dense_mean)
    fp_count: jax.Array       # Σ bloom false positives (decoded-but-not-selected)
    fp_universe: jax.Array    # Σ not-selected universe size (FPR denominator)
    live_workers: jax.Array   # Σ per-step live-worker count (participation)
    dropped_steps: jax.Array  # steps where ≥1 worker was masked out
    checksum_failures: jax.Array  # Σ failed-checksum payload decodes
    # in-collective reduction (sparse_rs rs_mode='adaptive'): Σ per-step
    # traced post-reduce shard density, and Σ steps the density switch
    # chose the dense int8 phase-2 row over the sparse (value, index) one —
    # divide both by `steps` on the host for the running rates
    rs_density: jax.Array
    rs_dense_switches: jax.Array
    # oktopk route (sparse_rs rs_mode='oktopk'): Σ per-step psum'd global
    # survivor count the threshold admitted, Σ per-step threshold value
    # (the bit-pattern bucket floor, an f32 magnitude), and Σ per-step
    # survivors this worker's capacity dropped into the residual
    rs_oktopk_survivors: jax.Array
    rs_oktopk_threshold: jax.Array
    rs_oktopk_spills: jax.Array
    # hierarchical exchange: Σ per-step bits one device moved on the
    # intra-slice ICI fabric (slice-mean psum/qar leg + key repair). Stays
    # 0.0 in flat exchanges; the scarce-link (flat/DCN) volume remains in
    # index_bits/value_bits, so rel_volume keeps its pre-hier meaning
    ici_bits: jax.Array
    # Σ per-BUCKET saturation counts, f32[C] in bucket-spec order for the
    # bucketed exchange (f32[0] when unbucketed) — keeps one chronically
    # overfull bucket visible next to the summed `saturated` total
    bucket_saturated: jax.Array
    # Σ per-staleness-level ACCEPTED-contribution counts, f32[D] in latency
    # level order for the asynchronous federated tick (f32[0] everywhere
    # else) — the exact cumulative staleness distribution the SLO health
    # plane derives its p50/p95/p99 tails from
    staleness_hist: jax.Array
    # Σ per-class ACCEPTED-contribution counts, f32[K] in population class
    # order for the heterogeneous-population federated drivers. None (not
    # f32[0]) everywhere else: a None child contributes no pytree leaf, so
    # every population-free accumulator — and every committed trace hash
    # downstream of one — is structurally unchanged
    pop_hist: Optional[jax.Array] = None

    @classmethod
    def zeros(
        cls,
        num_buckets: int = 0,
        num_stale_levels: int = 0,
        num_pop_classes: int = 0,
    ) -> "MetricAccumulators":
        # one FRESH buffer per field: the accumulator is donated to the jitted
        # step (train.Trainer._build), and donating one shared zeros() buffer
        # for every field is a donate-twice XLA runtime error
        scalars = tuple(
            jnp.zeros((), jnp.float32)
            for _ in range(len(dataclasses.fields(cls)) - 3)
        )
        return cls(
            *scalars,
            jnp.zeros((int(num_buckets),), jnp.float32),
            jnp.zeros((int(num_stale_levels),), jnp.float32),
            (
                jnp.zeros((int(num_pop_classes),), jnp.float32)
                if num_pop_classes
                else None
            ),
        )

    def accumulate(
        self,
        wire: WireStats,
        *,
        residual_l2=0.0,
        err_l2=0.0,
        err_cos=0.0,
        fp_count=0.0,
        fp_universe=0.0,
        live_workers=0.0,
        dropped_steps=0.0,
        checksum_failures=0.0,
        rs_density=0.0,
        rs_dense_switches=0.0,
        rs_oktopk_survivors=0.0,
        rs_oktopk_threshold=0.0,
        rs_oktopk_spills=0.0,
        bucket_saturated=0.0,
        staleness_hist=0.0,
        pop_hist=0.0,
    ) -> "MetricAccumulators":
        f = lambda x: jnp.asarray(x, jnp.float32)
        return MetricAccumulators(
            steps=self.steps + 1.0,
            index_bits=self.index_bits + f(wire.index_bits),
            value_bits=self.value_bits + f(wire.value_bits),
            dense_bits=self.dense_bits + f(wire.dense_bits),
            saturated=self.saturated + f(wire.saturated),
            residual_l2=self.residual_l2 + f(residual_l2),
            err_l2=self.err_l2 + f(err_l2),
            err_cos=self.err_cos + f(err_cos),
            fp_count=self.fp_count + f(fp_count),
            fp_universe=self.fp_universe + f(fp_universe),
            live_workers=self.live_workers + f(live_workers),
            dropped_steps=self.dropped_steps + f(dropped_steps),
            checksum_failures=self.checksum_failures + f(checksum_failures),
            rs_density=self.rs_density + f(rs_density),
            rs_dense_switches=self.rs_dense_switches + f(rs_dense_switches),
            rs_oktopk_survivors=self.rs_oktopk_survivors + f(rs_oktopk_survivors),
            rs_oktopk_threshold=self.rs_oktopk_threshold + f(rs_oktopk_threshold),
            rs_oktopk_spills=self.rs_oktopk_spills + f(rs_oktopk_spills),
            ici_bits=self.ici_bits + f(wire.ici_bits),
            # broadcasts: [C] + [C] per-step vector, or [C] + 0.0 when the
            # caller has nothing to report this step (and [0] + 0.0 when
            # unbucketed — a no-op on the empty vector)
            bucket_saturated=self.bucket_saturated + f(bucket_saturated),
            staleness_hist=self.staleness_hist + f(staleness_hist),
            # the None/engaged branch is STATIC (population wiring is a
            # build-time property), so the disengaged accumulate stages the
            # exact ops it always did
            pop_hist=(
                None if self.pop_hist is None else self.pop_hist + f(pop_hist)
            ),
        )

    # ------------------------------------------------------------------ #
    # derived ratios (usable traced or on fetched values)
    # ------------------------------------------------------------------ #

    def rel_volume(self) -> jax.Array:
        return (self.index_bits + self.value_bits) / jnp.maximum(self.dense_bits, _EPS)

    def measured_fpr(self) -> jax.Array:
        """Observed bloom FPR: false positives / not-inserted universe,
        cumulatively — the empirical check of the configured `fpr`."""
        return self.fp_count / jnp.maximum(self.fp_universe, 1.0)

    @classmethod
    def scalar_fields(cls) -> Tuple[str, ...]:
        """Field names of the scalar counters, in declaration order
        (everything except the vector-valued `bucket_saturated`,
        `staleness_hist` and `pop_hist`)."""
        return tuple(
            f.name
            for f in dataclasses.fields(cls)
            if f.name not in ("bucket_saturated", "staleness_hist", "pop_hist")
        )

    def fetch(self) -> Dict[str, Any]:
        """Materialise the cumulative counters to host plain floats —
        the telemetry_every sync point. Scalars by field name, plus
        `bucket_saturated` as a list of floats (and `staleness_hist`
        when the accumulator carries one — async fedsim only, so every
        pre-existing consumer's key set is unchanged)."""
        vals: Dict[str, Any] = {
            name: float(np.asarray(getattr(self, name)))
            for name in self.scalar_fields()
        }
        vals["bucket_saturated"] = [
            float(v)
            for v in np.asarray(self.bucket_saturated, np.float32).reshape(-1)
        ]
        if self.staleness_hist.size:
            vals["staleness_hist"] = [
                float(v)
                for v in np.asarray(self.staleness_hist, np.float32).reshape(-1)
            ]
        if self.pop_hist is not None and self.pop_hist.size:
            vals["pop_hist"] = [
                float(v)
                for v in np.asarray(self.pop_hist, np.float32).reshape(-1)
            ]
        return vals

    @staticmethod
    def derive(vals: Dict[str, Any]) -> Dict[str, Any]:
        """Reduce a fetched (or delta'd) counter dict to the reported
        ratios/rates. Applied to a cumulative `fetch()` this is the
        classic summary; applied to a `fetch_delta()` it is the same
        rates over one telemetry window."""
        steps = max(vals["steps"], 1.0)
        dense = max(vals["dense_bits"], _EPS)
        bucket_sat = vals.get("bucket_saturated", [])
        out: Dict[str, Any] = {}
        if len(bucket_sat):
            out["bucket_saturated_per_step"] = [float(v) / steps for v in bucket_sat]
        stale_hist = vals.get("staleness_hist", [])
        if len(stale_hist):
            # exact staleness tails from the cumulative on-device histogram
            # (async fedsim): the distribution over every ACCEPTED
            # contribution this accumulator has seen
            out["staleness_hist"] = [float(v) for v in stale_hist]
            out["staleness_p50"] = hist_quantile(stale_hist, 0.50)
            out["staleness_p95"] = hist_quantile(stale_hist, 0.95)
            out["staleness_p99"] = hist_quantile(stale_hist, 0.99)
        pop_hist = vals.get("pop_hist", [])
        if len(pop_hist):
            # exact per-class participation from the cumulative on-device
            # histogram (heterogeneous populations): accepted-contribution
            # counts, their shares, and the worst class's share — the
            # residency floor the SLO health plane gates on
            out["pop_hist"] = [float(v) for v in pop_hist]
            total = max(sum(float(v) for v in pop_hist), 1.0)
            out["pop_shares"] = [float(v) / total for v in pop_hist]
            out["pop_residency_min"] = min(out["pop_shares"])
        return out | {
            "steps": vals["steps"],
            "cumulative_total_bits": vals["index_bits"] + vals["value_bits"],
            "rel_volume": (vals["index_bits"] + vals["value_bits"]) / dense,
            "idx_rel_volume": vals["index_bits"] / dense,
            "val_rel_volume": vals["value_bits"] / dense,
            "saturated_per_step": vals["saturated"] / steps,
            "residual_l2_per_step": vals["residual_l2"] / steps,
            "compress_err_l2": vals["err_l2"] / steps,
            "compress_err_cos": vals["err_cos"] / steps,
            "measured_fpr": vals["fp_count"] / max(vals["fp_universe"], 1.0),
            # resilience counters: mean live workers per step, total steps
            # with ≥1 masked worker, total failed-checksum payload decodes
            "live_workers_per_step": vals["live_workers"] / steps,
            "dropped_steps": vals["dropped_steps"],
            "checksum_failures": vals["checksum_failures"],
            # adaptive sparse_rs: mean traced shard density after the
            # phase-1 reduce, and the dense-row switch rate
            "rs_density_per_step": vals["rs_density"] / steps,
            "rs_dense_switch_rate": vals["rs_dense_switches"] / steps,
            # oktopk sparse_rs: mean global survivor count the psum'd
            # threshold admitted, mean threshold magnitude, and mean
            # capacity-spilled survivors per worker per step
            "rs_oktopk_survivors_per_step": vals["rs_oktopk_survivors"] / steps,
            "rs_oktopk_threshold": vals["rs_oktopk_threshold"] / steps,
            "rs_oktopk_spill_rate": vals["rs_oktopk_spills"] / steps,
            # hierarchical exchange: per-step per-device bytes on each
            # fabric (dcn = the scarce-link index+value volume above)
            "ici_bytes_per_step": vals["ici_bits"] / 8.0 / steps,
            "dcn_bytes_per_step": (vals["index_bits"] + vals["value_bits"])
            / 8.0 / steps,
        }

    def summary(self, prev: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Fetch to host and reduce to plain floats (also what the CLI
        prints). With `prev` — a previous `fetch()` snapshot — the result
        additionally carries every rate in per-window delta form under
        `window_*` keys, so the controller's inputs and the human-readable
        rows agree by construction."""
        vals = self.fetch()
        out = self.derive(vals)
        if prev is not None:
            window = self.derive(fetch_delta(vals, prev))
            out.update({f"window_{k}": v for k, v in window.items()})
        return out
