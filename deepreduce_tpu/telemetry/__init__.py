"""Telemetry subsystem: span tracing, on-device metric accumulators, and a
run-comparison CLI.

Three parts (ARCHITECTURE.md "Telemetry"):

- `spans` — host-side hierarchical tracer; `span("exchange/encode")`
  records Chrome-trace-event JSON (Perfetto-loadable) and forwards the
  label to `jax.named_scope` / `jax.profiler.TraceAnnotation` so the same
  names appear in XLA device profiles. Disabled (the default) it is a
  shared inert no-op.
- `device_metrics` — `MetricAccumulators`, a registered-dataclass pytree
  of running counters threaded through the jitted step when
  `cfg.telemetry=True`; fetched every `cfg.telemetry_every` steps.
- `python -m deepreduce_tpu.telemetry {summary,compare,trace}` — the
  offline consumer over `tracking.py` run directories (`__main__.py`).
"""

from deepreduce_tpu.telemetry import device_metrics, spans
from deepreduce_tpu.telemetry.device_metrics import MetricAccumulators
from deepreduce_tpu.telemetry.spans import (
    Tracer,
    configure,
    enabled,
    get_tracer,
    span,
)

__all__ = [
    "MetricAccumulators",
    "Tracer",
    "configure",
    "device_metrics",
    "enabled",
    "get_tracer",
    "span",
    "spans",
]
