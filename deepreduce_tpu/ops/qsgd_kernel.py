"""QSGD stochastic-rounding quantizer as a Pallas TPU kernel.

The QSGD inner loop (codecs/qsgd.py, reference pytorch/deepreduce.py:861-873)
is `level = floor(q/||v|| * |v|) + Bernoulli(frac)` with the Bernoulli drawn
per element. Under XLA the randomness is threefry — several full passes over
the data; the TPU core's hardware PRNG (`pltpu.prng_random_bits`) generates
the bits in-register, so the whole quantizer is one fused elementwise pass.

`quantize_levels(values, scale, seed)` dispatches to the kernel on TPU and
to the XLA reference implementation elsewhere; both produce identical-shape
int8 levels (stochastic bits differ by construction — the contract is the
distribution, not the stream).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_BLOCK_ROWS = 32  # int8 min tile is (32, 128); lanes = bucket layout chunks
_BLOCK_COLS = 512


def _kernel(seed_ref, vals_ref, scale_ref, out_ref):
    from jax.experimental.pallas import tpu as pltpu

    import jax.experimental.pallas as pl

    pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
    v = vals_ref[...]
    scale = scale_ref[...]
    level_float = jnp.abs(v) * scale
    lo = jnp.floor(level_float)
    bits = pltpu.prng_random_bits(v.shape)
    # bits is int32: mask after the shift so sign-extension can't push u
    # negative — u must be uniform on [0, 1) for unbiased rounding
    u = ((bits >> 8) & 0xFFFFFF).astype(jnp.float32) * (1.0 / (1 << 24))
    level = lo + (u < (level_float - lo)).astype(jnp.float32)
    out_ref[...] = (level * jnp.sign(v)).astype(jnp.int8)


def quantize_levels_pallas(values: jax.Array, scale: jax.Array, seed: jax.Array) -> jax.Array:
    """values f32[n], scale f32[n] (q/norm broadcast per bucket), seed i32[]
    -> int8[n] signed levels. Any n: inputs are padded to the (32, 512)
    int8 tile internally."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = values.shape[0]
    lane_pad = (-n) % _BLOCK_COLS
    if lane_pad:
        values = jnp.concatenate([values, jnp.zeros((lane_pad,), values.dtype)])
        scale = jnp.concatenate([scale, jnp.ones((lane_pad,), scale.dtype)])
    rows = (n + lane_pad) // _BLOCK_COLS
    pad_rows = (-rows) % _BLOCK_ROWS
    v2 = jnp.zeros((rows + pad_rows, _BLOCK_COLS), jnp.float32).at[:rows].set(
        values.reshape(rows, _BLOCK_COLS)
    )
    s2 = jnp.ones((rows + pad_rows, _BLOCK_COLS), jnp.float32).at[:rows].set(
        scale.reshape(rows, _BLOCK_COLS)
    )
    grid = ((rows + pad_rows) // _BLOCK_ROWS,)
    out = pl.pallas_call(
        _kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                # index maps get the prefetched scalar ref as a trailing arg
                pl.BlockSpec((_BLOCK_ROWS, _BLOCK_COLS), lambda i, *_: (i, 0)),
                pl.BlockSpec((_BLOCK_ROWS, _BLOCK_COLS), lambda i, *_: (i, 0)),
            ],
            out_specs=pl.BlockSpec((_BLOCK_ROWS, _BLOCK_COLS), lambda i, *_: (i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((rows + pad_rows, _BLOCK_COLS), jnp.int8),
    )(jnp.asarray(seed, jnp.int32).reshape(1), v2, s2)
    return out[:rows].reshape(-1)[:n]


def quantize_levels_xla(values: jax.Array, scale: jax.Array, key: jax.Array) -> jax.Array:
    level_float = jnp.abs(values) * scale
    lo = jnp.floor(level_float)
    prob = jax.random.uniform(key, values.shape)
    level = lo + (prob < (level_float - lo)).astype(jnp.float32)
    return (level * jnp.sign(values)).astype(jnp.int8)


def quantize_levels(
    values: jax.Array, scale: jax.Array, key: jax.Array, *, use_pallas: bool = False
) -> jax.Array:
    # Pallas TPU kernels don't lower on the CPU backend; degrade to the XLA
    # path silently so `DeepReduceConfig.tpu_defaults()` stays portable
    # (tests and the virtual-mesh dry runs all run on CPU).
    if use_pallas and jax.default_backend() != "cpu":
        seed = jax.random.randint(key, (), 0, 2**31 - 1, jnp.int32)
        return quantize_levels_pallas(values, scale, seed)
    return quantize_levels_xla(values, scale, key)
