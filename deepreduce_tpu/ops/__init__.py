"""Pallas TPU kernels for codec hot ops.

Engineering note on scope (SURVEY.md §7 hard part 4): the codec pipeline's
dominant ops — universe-sized filter queries, top-k selection, bit-scatter —
are *per-lane dynamic-indexing* ops. Mosaic/Pallas on TPU exposes only
contiguous dynamic slices (`pl.ds`), no per-lane VMEM gather, so those stay
on XLA's gather/top_k paths (which are latency-bound on the same hardware
either way); the blocked-bloom redesign (`codecs.bloom`) attacks them
algorithmically instead (h gathers -> 1). Pallas is used where it genuinely
beats XLA: stochastic quantization, whose XLA formulation must materialize
threefry random bits while `pltpu.prng_random_bits` is nearly free.
"""

from deepreduce_tpu.ops.qsgd_kernel import quantize_levels, quantize_levels_pallas

__all__ = ["quantize_levels", "quantize_levels_pallas"]
