"""Composition layer: the DeepReduce wrapper modes over a sparsifier.

Reference parity (/root/reference/pytorch/deepreduce.py:51-302):

- ``deepreduce=None``  — sparsify only (plain Top-r): raw (values, indices).
- ``'value'``          — sparsify, then value-compress (`ValueCompressor`).
- ``'index'``          — sparsify, then index-compress with FP-aware value
                         re-read from the dense tensor (`IndexCompressor`,
                         :117 passes the dense tensor in).
- ``'both'``           — index codec first; the value codec then runs on the
                         *codec-ordered* values with fresh arange indices,
                         producing a sort `mapping` transmitted alongside
                         (:262-263); decompress applies ``idxs[mapping]`` to
                         undo both reorderings (:290).

Differences by design: the `mapping` is always bit-packed at the static
width ceil(log2 k) (the reference left its `pack` call commented out
:264-265; the paper's volume numbers assume packing, pdf p.46) — and when
the value codec is order-preserving (QSGD — the DRQSGD-BF-P0 headline
config) the mapping is elided entirely, since it is the identity.

Small-tensor bypass: tensors with <= `min_compress_size` elements skip
compression (pytorch/deepreduce.py:68) — a *static* decision per tensor, so
jit sees a fixed payload structure.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from deepreduce_tpu import sparse
from deepreduce_tpu.codecs import packing
from deepreduce_tpu.codecs.registry import get_codec
from deepreduce_tpu.config import DeepReduceConfig
from deepreduce_tpu.metrics import WireStats
from deepreduce_tpu.sparse import SparseGrad


def _timed(fn) -> float:
    import time

    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class DensePayload:
    """Uncompressed-leaf wire format when dense transmission statically wins:
    the raw tensor, nothing else. Deliberate delta from the reference, whose
    >min-size gate ships the sparsifier's (values, indices) pair even when
    that pair exceeds the raw tensor (pytorch/deepreduce.py:68 returns the
    sparsifier output as-is). Transmitting dense is lossless AND never more
    than 1.0x, and the decision is static (the slot budget k is static), so
    jit sees a fixed payload structure. See PARITY.md 'dense fallback'."""

    tensor: jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class BothPayload:
    """'both' wire format: index payload (values stripped), value payload,
    packed mapping (pytorch/deepreduce.py:267)."""

    index_payload: Any
    value_payload: Any
    mapping: Optional[packing.PackedInts]
    nsel: jax.Array


class TensorCodec:
    """Per-tensor compressor bound to a static shape — the role of the
    reference's wrapper instance installed as `grc.compressor`
    (pytorch/deepreduce.py:45-46)."""

    def __init__(
        self,
        shape: Tuple[int, ...],
        cfg: DeepReduceConfig,
        name: str = "",
        slots: Optional[int] = None,
    ):
        """`slots` overrides the k = num_slots(d, ratio) budget — the
        bucketed exchange (comm_bucket.py) passes the SUM of its member
        leaves' per-tensor budgets so fusing never changes the total wire
        budget. Ignored for compressor='none' (k is the full tensor)."""
        self.shape = tuple(int(s) for s in shape)
        self.cfg = cfg
        self.name = name
        self.d = int(math.prod(self.shape)) if self.shape else 1
        # Layers excluded by the whitelist pass through FULLY uncompressed —
        # not even sparsified — the way TF PolySeg transmits non-conv layers
        # as-is (tensorflow/deepreduce.py:515-516). The small-size gate is
        # different: small tensors are still sparsified, just not
        # codec-compressed (pytorch/deepreduce.py:68 returns the sparsifier
        # output).
        # Per-codec gate parity when the knobs are left unset: TF DoubleExp
        # compresses only above 9000 elements (the generic PyTorch gate is
        # 1000; tensorflow/deepreduce.py:396,426), and TF PolySeg applies
        # only to convolutional layers — its hard-coded per-model size
        # whitelist (:458,515-516 is_convolutional) becomes a name-pattern
        # default here. Explicit settings always win
        # (min_compress_size=None means "reference default"; pass
        # layer_pattern='.*' to run polyseg on every layer).
        uses_value = cfg.deepreduce in ("value", "both")
        min_size = cfg.min_compress_size
        if min_size is None:
            min_size = 9000 if uses_value and cfg.value == "doubleexp" else 1000
        pattern = cfg.layer_pattern
        if uses_value and cfg.value == "polyseg" and pattern is None:
            pattern = r"(?i)conv"
        self.min_compress_size = min_size
        self.layer_pattern = pattern
        self.pattern_excluded = (
            pattern is not None and re.search(pattern, name) is None
        )
        self.compressed = (
            cfg.deepreduce is not None
            and self.d > min_size
            and not self.pattern_excluded
        )
        if cfg.compressor == "none":
            self.k = self.d
        elif slots is not None:
            self.k = int(slots)
        else:
            self.k = sparse.num_slots(self.d, cfg.compress_ratio)
        if self.k > self.d:
            raise ValueError(
                f"slot budget k={self.k} exceeds the tensor size d={self.d}"
            )

        if cfg.deepreduce == "both" and cfg.index == "bloom_native":
            raise ValueError(
                "bloom_native is index-mode only: its C++ wire format carries "
                "values in-band, so a value codec on top would transmit them "
                "twice — use index='bloom' for 'both' mode"
            )
        if (
            cfg.bloom_threshold_insert
            and cfg.index == "bloom"
            and cfg.deepreduce in ("index", "both")
            and cfg.compressor not in ("topk", "topk_sampled", "threshold")
        ):
            raise ValueError(
                "bloom_threshold_insert rebuilds the selection as a magnitude "
                f"threshold — incompatible with compressor={cfg.compressor!r} "
                "(randomk/none selections are not magnitude sets); use topk "
                "or threshold"
            )
        params = cfg.codec_params()
        self.idx_codec = None
        self.val_codec = None
        if self.compressed and cfg.deepreduce in ("index", "both"):
            self.idx_codec = get_codec(cfg.index, "index")(self.k, self.d, params)
        if self.compressed and cfg.deepreduce in ("value", "both"):
            if cfg.deepreduce == "both":
                # the value codec sees the index codec's selected values —
                # its slot count is the index codec's budget
                vk = self.idx_codec.meta.budget if hasattr(self.idx_codec, "meta") and hasattr(
                    self.idx_codec.meta, "budget"
                ) else self.k
                self.val_codec = get_codec(cfg.value, "value")(vk, self.d, params)
            else:
                self.val_codec = get_codec(cfg.value, "value")(self.k, self.d, params)
        # mapping pack width: ceil(log2 k) bits (paper pdf p.46)
        self._map_width = max(1, math.ceil(math.log2(max(2, self.k))))
        # Real dense-transmission fallback for uncompressed leaves: when the
        # leaf is never sparsified (compressor 'none', pattern-excluded) or
        # the static sparse budget pair already costs >= the raw tensor
        # (k*64 >= d*32 bits), transmit the dense tensor itself. Static
        # decision -> fixed jit payload structure; the wire accounting below
        # then reflects what is actually sent.
        never_sparse = cfg.compressor == "none" or self.pattern_excluded
        self.dense_fallback = not self.compressed and (
            never_sparse or self.k * 64 >= self.d * 32
        )
        # Sparsifier-free bloom encode (bloom.encode_dense_direct): when the
        # config statically combines the sampled-threshold sparsifier with
        # the threshold insert under a prefix policy, the selection lives
        # entirely in the filter and the top-k materialization is skipped.
        # Static predicate -> fixed jit graph; decode is unchanged.
        # bloom_blocked == 'mod' is spelled out even though BloomMeta.create
        # already rejects threshold_insert on non-mod layouts: the routing
        # condition must be self-contained, not rely on a downstream
        # constructor raising (ADVICE.md round-5 item 1)
        self.direct_bloom = (
            self.compressed
            and cfg.deepreduce in ("index", "both")
            and cfg.index == "bloom"
            and cfg.compressor == "topk_sampled"
            and cfg.bloom_threshold_insert
            and cfg.bloom_blocked == "mod"
            and cfg.policy in ("leftmost", "p0")
        )

    # ------------------------------------------------------------------ #

    def sparsify(self, tensor: jax.Array, *, key: Optional[jax.Array] = None) -> SparseGrad:
        cfg = self.cfg
        if self.pattern_excluded:
            return sparse.none_sparsifier(tensor)
        # k=self.k keeps the sparsifier's selection budget and the codec's
        # payload budget the same value when a `slots` override is in play
        # (identical to the ratio-derived k otherwise)
        if cfg.compressor == "topk":
            return sparse.topk(
                tensor, cfg.compress_ratio, approx=cfg.approx_topk, k=self.k
            )
        if cfg.compressor == "topk_sampled":
            return sparse.topk_sampled(
                tensor,
                cfg.compress_ratio,
                sample_size=cfg.topk_sample_size,
                undershoot=cfg.topk_undershoot,
                k=self.k,
            )
        if cfg.compressor == "randomk":
            if key is None:
                raise ValueError("randomk sparsifier needs a PRNG key")
            return sparse.randomk(tensor, cfg.compress_ratio, key, k=self.k)
        if cfg.compressor == "threshold":
            return sparse.threshold(
                tensor, cfg.threshold_val, budget_ratio=cfg.compress_ratio, k=self.k
            )
        if cfg.compressor == "none":
            return sparse.none_sparsifier(tensor)
        raise ValueError(f"unknown sparsifier {cfg.compressor!r}")

    def encode(
        self, tensor: jax.Array, *, step: jax.Array = 0, key: Optional[jax.Array] = None
    ) -> Any:
        """tensor -> payload (the reference's wrapper.compress,
        pytorch/deepreduce.py:250-272)."""
        if self.dense_fallback:
            return DensePayload(tensor=tensor)
        mode = self.cfg.deepreduce
        if self.direct_bloom:
            # sparsifier-free: the filter IS the selection; no top-k runs
            ipay = self.idx_codec.encode_direct(
                tensor,
                sample_size=self.cfg.topk_sample_size,
                undershoot=self.cfg.topk_undershoot,
            )
            if mode == "index":
                return ipay
            nsel = ipay.nsel
        else:
            sp = self.sparsify(tensor, key=key)
            if not self.compressed:
                return sp
            if mode == "value":
                return self.val_codec.encode(sp, step=step, key=key)
            if mode == "index":
                return self.idx_codec.encode(sp, dense=tensor, step=step, key=key)

            # both: index codec first (FP-aware), then value codec over the
            # selected values with fresh arange indices (pytorch/deepreduce.py:261-263)
            ipay = self.idx_codec.encode(sp, dense=tensor, step=step, key=key)
            nsel = getattr(ipay, "nsel", None)
            nsel = sp.nnz if nsel is None else nsel
        sel_vals = ipay.values
        vk = sel_vals.shape[0]
        inner = SparseGrad(
            values=sel_vals,
            indices=jnp.arange(vk, dtype=jnp.int32),
            nnz=nsel,
            shape=(vk,),
        )
        vpay = self.val_codec.encode(inner, step=step, key=key)
        vpay, mapping_arr, mapping_max = self.val_codec.strip_for_both(vpay)
        if mapping_arr is None:
            mapping = None
        else:
            width = max(1, math.ceil(math.log2(max(2, mapping_max + 1))))
            mapping = packing.pack(mapping_arr, jnp.asarray(width, jnp.int32), max_width=width)
        ipay_stripped = dataclasses.replace(ipay, values=jnp.zeros((0,), jnp.float32))
        return BothPayload(
            index_payload=ipay_stripped, value_payload=vpay, mapping=mapping, nsel=nsel
        )

    def decode(self, payload: Any, *, step: jax.Array = 0) -> jax.Array:
        """payload -> dense tensor (wrapper.decompress,
        pytorch/deepreduce.py:274-302)."""
        if self.dense_fallback:
            return payload.tensor.reshape(self.shape)
        if not self.compressed:
            return payload.to_dense()

        mode = self.cfg.deepreduce
        if mode == "value":
            return self.val_codec.decode(payload, self.shape, step=step).to_dense()
        if mode == "index":
            if hasattr(self.idx_codec, "decode_dense"):
                return self.idx_codec.decode_dense(payload, self.shape, step=step)
            return self.idx_codec.decode(payload, self.shape, step=step).to_dense()

        vk = self.val_codec.k
        if payload.mapping is None:
            mapping_arr = None
        else:
            mapping_arr = packing.unpack(payload.mapping, vk)
        vpay = self.val_codec.restore_for_both(payload.value_payload, mapping_arr)
        vsp = self.val_codec.decode(vpay, self.shape, step=step)  # codec-order values
        ipay = dataclasses.replace(
            payload.index_payload, values=jnp.zeros((vk,), jnp.float32)
        )
        if hasattr(self.idx_codec, "decode_dense"):
            # rank-gather fast path: build the slot-ordered value table (the
            # inverse of the codec reordering — identity when the mapping was
            # elided) and let the index codec place it densely, skipping the
            # selection-list materialization
            if mapping_arr is None:
                table = vsp.values
            else:
                # vsp.indices is a permutation of arange(vk) by construction,
                # but defend against codec dead-slot padding: out-of-range
                # targets drop instead of clipping onto a live slot
                table = (
                    jnp.zeros((vk,), vsp.values.dtype)
                    .at[vsp.indices]
                    .set(vsp.values, mode="drop")
                )
            return self.idx_codec.decode_dense(
                ipay, self.shape, step=step, values=table
            )
        isp = self.idx_codec.decode(ipay, self.shape, step=step)  # ascending indices
        # undo both reorderings (:290): vsp.indices maps codec order -> selection slot
        sel = jnp.clip(vsp.indices, 0, vk - 1)
        idxs = isp.indices[sel]
        out = SparseGrad(values=vsp.values, indices=idxs, nnz=payload.nsel, shape=self.shape)
        return out.to_dense()

    # ------------------------------------------------------------------ #

    def micro_benchmark(self, tensor: jax.Array, *, iters: int = 5) -> dict:
        """The reference's ``'micro-benchmark': True`` mode
        (pytorch/deepreduce.py:70-76,255-257): per-stage wall times and
        relative volumes, measured host-side around jitted encode/decode.
        Synchronization reads a scalar back (axon's block_until_ready is a
        no-op)."""
        import numpy as np

        key = jax.random.PRNGKey(self.cfg.seed)
        enc = jax.jit(lambda t, s: self.encode(t, step=s, key=key))
        dec = jax.jit(lambda p, s: self.decode(p, step=s))

        def sync(x):
            for leaf in jax.tree_util.tree_leaves(x):
                if getattr(leaf, "size", 0):
                    np.asarray(leaf.reshape(-1)[0])
                    return x
            return x

        payload = sync(enc(tensor, 0))
        sync(dec(payload, 0))
        t_enc = min(
            _timed(lambda: sync(enc(tensor, 1))) for _ in range(iters)
        )
        t_dec = min(_timed(lambda: sync(dec(payload, 1))) for _ in range(iters))
        stats = self.wire_stats(payload)
        out = {
            "compression_time": t_enc,
            "decompression_time": t_dec,
            "idx_relative_volume": float(stats.idx_rel_volume()),
            "val_relative_volume": float(stats.val_rel_volume()),
            "relative_volume": float(stats.rel_volume()),
        }
        if self.cfg.micro_benchmark:
            for k, v in out.items():
                print(f"{k}:{v}")
        return out

    def fp_stats(self, payload: Any) -> Optional[Tuple[jax.Array, jax.Array]]:
        """Measured index-codec false positives for telemetry:
        (fp_count, not_selected_universe) traced scalars, or None when the
        index codec is exact (no FP notion) or bypassed for this tensor."""
        if self.dense_fallback or not self.compressed:
            return None
        if self.idx_codec is None or not hasattr(self.idx_codec, "fp_stats"):
            return None
        ipay = payload.index_payload if isinstance(payload, BothPayload) else payload
        return self.idx_codec.fp_stats(ipay)

    def _saturation(self, index_payload: Any) -> jax.Array:
        """1.0 when the index payload's selection filled its whole static
        budget (nsel == budget) — the silent-truncation signal for the
        threshold-superset encodes (bloom.encode_dense_direct inserts
        {|g| >= t}; an underestimated t overflows the budget and the
        FP-aware prefix read then drops high-index large-magnitude entries
        with no error). Surfaced through WireStats so training runs can
        watch for chronic overflow (ADVICE.md round-5 item 2)."""
        budget = getattr(getattr(self.idx_codec, "meta", None), "budget", None)
        nsel = getattr(index_payload, "nsel", None)
        if budget is None or nsel is None:
            return jnp.zeros((), jnp.float32)
        return (jnp.asarray(nsel, jnp.int32) >= jnp.int32(budget)).astype(jnp.float32)

    def wire_stats(self, payload: Any) -> WireStats:
        dense_bits = jnp.asarray(self.d * 32, jnp.float32)
        saturated = jnp.zeros((), jnp.float32)
        if self.dense_fallback:
            # the wire carries exactly the raw tensor: no index stream, 1.0x
            idx_bits = jnp.zeros(())
            val_bits = dense_bits
        elif not self.compressed:
            # sparse (idx, val) pair actually transmitted; k*64 < d*32 here
            # (else dense_fallback), so nnz <= k keeps every leaf <= 1.0
            nnz = payload.nnz.astype(jnp.float32)
            idx_bits = nnz * 32
            val_bits = nnz * 32
        elif self.cfg.deepreduce == "value":
            # positional dense transmission (no sparsifier): values arrive in
            # slot order covering the whole tensor — the plain-QSGD wire has
            # no index stream
            if self.cfg.compressor == "none":
                idx_bits = jnp.zeros(())
            else:
                idx_bits = self.val_codec.index_wire_bits(payload)
            val_bits = self.val_codec.value_wire_bits(payload)
        elif self.cfg.deepreduce == "index":
            idx_bits = self.idx_codec.index_wire_bits(payload)
            val_bits = self.idx_codec.value_wire_bits(payload)
            saturated = self._saturation(payload)
        else:
            idx_bits = self.idx_codec.index_wire_bits(payload.index_payload)
            if payload.mapping is not None:
                idx_bits = idx_bits + packing.wire_bits(payload.mapping).astype(jnp.float32)
            val_bits = self.val_codec.value_wire_bits(payload.value_payload)
            saturated = self._saturation(payload.index_payload)
        return WireStats(
            index_bits=jnp.asarray(idx_bits, jnp.float32),
            value_bits=jnp.asarray(val_bits, jnp.float32),
            dense_bits=dense_bits,
            saturated=saturated,
        )
