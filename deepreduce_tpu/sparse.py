"""Sparse-gradient core: the `SparseGrad` pytree and the sparsifiers.

Reference parity: GRACE supplies `topk`/`randomk`/`threshold` sparsifiers on
the PyTorch path, and the TF path fuses them into the codec
(/root/reference/tensorflow/deepreduce.py:273-298). Here they are pure JAX
functions with *static* output shapes: every sparsifier returns exactly
`k` slots; `nnz` says how many are live, and dead slots carry
``index = 0, value = 0`` so that scatter-adds of padding are no-ops.

The reference's `randomk` seeds by ``hash(tensor_name) + global_step``
(tensorflow/deepreduce.py:293) and its GPU bloom `random` policy re-seeds
``torch.manual_seed(42)`` every call — an acknowledged bug
(pytorch/deepreduce.py:484-488). We take explicit `jax.random` keys instead;
helpers derive per-tensor per-step keys so no two steps repeat.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _static_field(**kw):
    return dataclasses.field(metadata=dict(static=True), **kw)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SparseGrad:
    """A sparsified gradient with a static slot budget.

    values:  f32[k]  — kept magnitudes (0 in dead slots)
    indices: i32[k]  — flat positions into the dense tensor (0 in dead slots)
    nnz:     i32[]   — number of live slots (<= k)
    shape:   static  — dense tensor shape (the reference threads `ctx=shape`,
                       pytorch/deepreduce.py:64)
    """

    values: jax.Array
    indices: jax.Array
    nnz: jax.Array
    shape: Tuple[int, ...] = _static_field(default=())

    @property
    def k(self) -> int:
        return self.values.shape[0]

    @property
    def dense_size(self) -> int:
        size = 1
        for s in self.shape:
            size *= int(s)
        return size

    def to_dense(self) -> jax.Array:
        """Scatter values back to a dense tensor (GRACE sparsifier.decompress
        role, pytorch/deepreduce.py:301)."""
        d = self.dense_size
        mask = live_mask(self)
        vals = jnp.where(mask, self.values, 0.0)
        idxs = jnp.where(mask, self.indices, 0)
        dense = jnp.zeros((d,), self.values.dtype).at[idxs].add(vals)
        return dense.reshape(self.shape)


def live_mask(sp: SparseGrad) -> jax.Array:
    """Boolean [k] mask of live slots."""
    return jnp.arange(sp.k, dtype=jnp.int32) < sp.nnz


def fit_length(vals: jax.Array, n: int) -> jax.Array:
    """Zero-pad or truncate a value table to exactly `n` slots ('both' mode
    can hand a codec a table shorter or longer than its budget)."""
    if vals.shape[0] < n:
        return jnp.zeros((n,), vals.dtype).at[: vals.shape[0]].set(vals)
    return vals[:n]


def scatter_ascending(
    vals: jax.Array, pos: jax.Array, nsel: jax.Array, d: int
) -> jax.Array:
    """f32[d]: place `vals[s]` at `pos[s]` for live slots s < nsel.

    The contract that makes this the TPU fast path: live `pos` is strictly
    ascending and in [0, d). Dead slots park at distinct out-of-range targets
    (d + s > every live position, still ascending), so the ONE scatter
    carries both the unique-indices and sorted promises — XLA:TPU walks HBM
    sequentially — and mode='drop' discards the parked tail."""
    budget = vals.shape[0]
    live = jnp.arange(budget, dtype=jnp.int32) < nsel
    tgt = jnp.where(live, pos, d + jnp.arange(budget, dtype=jnp.int32))
    return (
        jnp.zeros((d,), vals.dtype)
        .at[tgt]
        .set(vals, mode="drop", unique_indices=True, indices_are_sorted=True)
    )


def num_slots(dense_size: int, compress_ratio: float) -> int:
    """k = max(1, N * ratio) (tensorflow/deepreduce.py:307-308)."""
    return max(1, int(dense_size * compress_ratio))


def bucket_num_slots(sizes, compress_ratio: float) -> int:
    """Slot budget of a fused bucket: the SUM of its member leaves'
    per-tensor budgets, not `num_slots(sum(sizes))`. Per-leaf rounding and
    the max(1, .) floor are preserved, so bucketing a pytree never changes
    the total wire budget the per-leaf codecs would have transmitted
    (comm_bucket.py's budget contract)."""
    return sum(num_slots(int(s), compress_ratio) for s in sizes)


def topk(
    tensor: jax.Array,
    compress_ratio: float,
    *,
    sort_indices: bool = True,
    approx: bool = False,
    k: Optional[int] = None,
) -> SparseGrad:
    """Top-k by magnitude. Indices ascending when `sort_indices` (the TF
    reference sorts, tensorflow/deepreduce.py:276).

    `approx=True` uses `jax.lax.approx_max_k` — the TPU-native top-k
    (~4x faster at 25M elements, recall ~0.95). Missed elements are exactly
    what residual error-feedback re-injects next step, so recall<1 trades
    a little convergence speed for a lot of wall-clock; deterministic, so
    the encode/decode contract is unaffected. An explicit `k` overrides the
    ratio-derived budget (the bucketed exchange's summed per-leaf budget,
    `bucket_num_slots`)."""
    flat = tensor.reshape(-1)
    k = num_slots(flat.shape[0], compress_ratio) if k is None else int(k)
    if approx and flat.shape[0] > 4 * k:
        _, idxs = jax.lax.approx_max_k(jnp.abs(flat), k, recall_target=0.95)
    else:
        _, idxs = jax.lax.top_k(jnp.abs(flat), k)
    if sort_indices:
        idxs = jnp.sort(idxs)
    # the ascending sort above is a promise XLA can only exploit if the
    # gather carries it (jx-unsorted-budget-gather pins this)
    vals = jnp.take(flat, idxs, indices_are_sorted=sort_indices)
    return SparseGrad(
        values=vals,
        indices=idxs.astype(jnp.int32),
        nnz=jnp.asarray(k, jnp.int32),
        shape=tuple(tensor.shape),
    )


def _select_bit(word: jax.Array, t: jax.Array) -> jax.Array:
    """Position of the (t+1)-th set bit of each uint32 `word` — 5-step
    binary select over popcounts of low halves, fully vectorized."""
    pos = jnp.zeros_like(t)
    rem = t
    for width in (16, 8, 4, 2, 1):
        low = (word >> pos.astype(jnp.uint32)) & (
            (jnp.uint32(1) << jnp.uint32(width)) - 1
        )
        c = jax.lax.population_count(low).astype(jnp.int32)
        hi = rem >= c
        rem = rem - jnp.where(hi, c, 0)
        pos = pos + jnp.where(hi, width, 0)
    return pos


def _prefix_positions(mask: jax.Array, budget: int) -> Tuple[jax.Array, jax.Array]:
    """(positions[budget], count): universe positions of the first `budget`
    True entries of `mask`, ascending — WITHOUT a d-scale sort or scatter.

    Rank inversion in three cheap moves (the round-3 encode unlock; the
    round-2 rank-scatter cost ~17ms at d=4M on TPU, this costs ~3ms):
      1. pack the mask into 32-bit group words; per-group popcounts and
         their (exclusive) prefix P give every group's first output slot;
      2. ONE small scatter-add of a marker per group at slot P[g] (parked
         past `budget` when the group starts beyond it); cumsum of the
         markers tells each output slot s which group it reads from —
         g(s) = cumsum[s] - 1, exact even across empty-group runs;
      3. the in-group bit offset is `_select_bit(word[g], s - P[g])`.
    Only budget-scale gathers + one G-scale unique-ish scatter-add remain.
    Dead slots (s >= count) return position clipped into range — callers
    mask them."""
    d = mask.shape[0]
    g_count = (d + 31) // 32
    padded = (
        jnp.zeros((g_count * 32,), jnp.uint32).at[:d].set(mask.astype(jnp.uint32))
    )
    hw = jnp.sum(
        padded.reshape(g_count, 32) << jnp.arange(32, dtype=jnp.uint32)[None, :],
        axis=1,
    ).astype(jnp.uint32)
    cnt = jax.lax.population_count(hw).astype(jnp.int32)
    cs = jnp.cumsum(cnt)
    p_ex = cs - cnt
    count = jnp.minimum(cs[-1], budget)
    markers = (
        jnp.zeros((budget + 1,), jnp.int32)
        .at[jnp.minimum(p_ex, budget)]
        .add(1, indices_are_sorted=True)
    )
    g_of_s = jnp.clip(jnp.cumsum(markers)[:budget] - 1, 0, g_count - 1)
    # g_of_s is non-decreasing by construction (cumsum of non-negative
    # markers) — sorted gathers let XLA:TPU walk HBM sequentially
    t = jnp.arange(budget, dtype=jnp.int32) - jnp.take(
        p_ex, g_of_s, indices_are_sorted=True, mode="clip"
    )
    b = _select_bit(jnp.take(hw, g_of_s, indices_are_sorted=True, mode="clip"), t)
    pos = jnp.clip(g_of_s * 32 + b, 0, d - 1)
    return pos, count


def sampled_kth_magnitude(
    flat: jax.Array, k: int, *, sample_size: int = 1 << 15, undershoot: float = 0.9
) -> jax.Array:
    """Estimate the k-th largest |flat| from a strided systematic sample.

    Sorts only ``sample_size`` elements (O(s log s), s << d) instead of the
    full tensor. The returned threshold targets an expected capture of
    ``undershoot * k`` elements: with sample rank r ≈ s·k·undershoot/d the
    relative capture error is ~1/sqrt(r), so undershoot < 1 keeps the
    captured count below the k-slot budget with high probability — an
    ascending-index truncation of an overfull capture could drop a
    *large*-magnitude element, while an underfull capture only misses
    boundary elements, which residual error-feedback re-injects next step.

    Systematic (strided) sampling is deterministic and unbiased for the
    order statistics of gradients, whose magnitude has no index-periodic
    structure at the sampling stride; pass a pre-shuffled view if yours does.
    """
    d = flat.shape[0]
    mags = jnp.abs(flat)
    if d <= 2 * sample_size:
        return jnp.sort(mags)[d - k]
    stride = d // sample_size
    samp = mags[::stride]
    s = samp.shape[0]
    r = max(1, int(round(s * k * undershoot / d)))
    return jnp.sort(samp)[s - r]


def topk_sampled(
    tensor: jax.Array,
    compress_ratio: float,
    *,
    sample_size: int = 1 << 15,
    undershoot: float = 0.9,
    k: Optional[int] = None,
) -> SparseGrad:
    """Sortless O(d) approximate top-k: sampled-quantile threshold + rank-
    inversion compaction (the Deep-Gradient-Compression selection shape;
    no reference counterpart — the reference's TF threshold path
    tensorflow/deepreduce.py:283-298 takes a *fixed* threshold).

    Two elementwise passes over d (abs+compare, mask bit-pack) plus the
    budget-scale rank-inversion compaction (`_prefix_positions`) and one
    tiny sample sort — no ``top_k``/``sort`` over the full tensor, so
    nothing scales O(d log k). Selection is the exact ascending-index set
    ``{j : |g_j| >= t}`` for the estimated threshold t; ``nnz <= k`` is
    dynamic and approx-misses are exactly what residual error-feedback
    re-injects (same contract as ``approx_max_k``'s recall<1). A zero
    estimated threshold (naturally sparse gradient the sample missed)
    falls back to exact selection via ``lax.cond``."""
    flat = tensor.reshape(-1)
    d = flat.shape[0]
    k = num_slots(d, compress_ratio) if k is None else int(k)
    if d <= max(4 * k, 2 * sample_size):
        # small tensors: the exact path is already cheap, and sampling error
        # would dominate
        return topk(tensor, compress_ratio, k=k)
    t = sampled_kth_magnitude(flat, k, sample_size=sample_size, undershoot=undershoot)

    def sampled(flat):
        # t > 0: threshold mask -> ascending positions via the same
        # rank-inversion compaction the bloom encode uses (_prefix_positions
        # — no d-scale sort, scatter, or cumsum-searchsorted)
        pos, count = _prefix_positions(jnp.abs(flat) >= t, k)
        nnz = count.astype(jnp.int32)
        live = jnp.arange(k, dtype=jnp.int32) < nnz
        idxs = jnp.where(live, pos, 0).astype(jnp.int32)
        vals = jnp.where(live, flat[idxs], 0.0)
        return vals, idxs, nnz

    def exact(flat):
        # t == 0 means the sample was all zeros (naturally sparse gradient
        # with fewer nonzeros than the sample could see): a >= 0 mask would
        # select the first k positions REGARDLESS of magnitude — and being
        # deterministic, starve the same high-index coordinates every step,
        # which residual feedback can never recover. Fall back to exact
        # magnitude selection for this step.
        _, idxs = jax.lax.top_k(jnp.abs(flat), k)
        idxs = jnp.sort(idxs).astype(jnp.int32)
        return flat[idxs], idxs, jnp.asarray(k, jnp.int32)

    vals, idxs, nnz = jax.lax.cond(t > 0, sampled, exact, flat)
    return SparseGrad(
        values=vals,
        indices=idxs,
        nnz=nnz,
        shape=tuple(tensor.shape),
    )


def randomk(
    tensor: jax.Array,
    compress_ratio: float,
    key: jax.Array,
    *,
    sort_indices: bool = True,
    k: Optional[int] = None,
) -> SparseGrad:
    """Uniform random k of d without replacement, keyed per tensor per step
    (fixing the reference's fixed-seed quirk, pytorch/deepreduce.py:484-488).

    Implemented as top-k over i.i.d. uniform priorities — O(d log k), static
    shapes, no d-length permutation materialised.
    """
    flat = tensor.reshape(-1)
    d = flat.shape[0]
    k = num_slots(d, compress_ratio) if k is None else int(k)
    priorities = jax.random.uniform(key, (d,))
    _, idxs = jax.lax.top_k(priorities, k)
    if sort_indices:
        idxs = jnp.sort(idxs)
    vals = jnp.take(flat, idxs, indices_are_sorted=sort_indices)
    return SparseGrad(
        values=vals,
        indices=idxs.astype(jnp.int32),
        nnz=jnp.asarray(k, jnp.int32),
        shape=tuple(tensor.shape),
    )


def natural_sparsity(tensor: jax.Array, threshold_val: float = 0.0) -> jax.Array:
    """Fraction of elements strictly above `threshold_val` in magnitude —
    the model's true sparsity at this step. With 0.0 this counts nonzeros
    (the NCF embedding-gradient case, run_deepreduce.sh:89)."""
    flat = tensor.reshape(-1)
    if threshold_val <= 0.0:
        passing = flat != 0
    else:
        passing = jnp.abs(flat) >= threshold_val
    return jnp.mean(passing.astype(jnp.float32))


def calibrate_threshold_budget(
    sample_grads, threshold_val: float = 0.0, *, safety: float = 1.25
) -> float:
    """budget_ratio for `threshold` measured from sample gradients: the max
    observed natural sparsity across leaves times a safety headroom,
    clipped to [1/d-ish, 1.0]. Host-side, called once before building the
    codec — the static-shape answer to the reference's dynamic-size
    above-threshold list (tensorflow/deepreduce.py:283-298)."""
    import numpy as np

    worst = 0.0
    for leaf in jax.tree_util.tree_leaves(sample_grads):
        worst = max(worst, float(natural_sparsity(jnp.asarray(leaf), threshold_val)))
    return float(np.clip(worst * safety, 1e-6, 1.0))


def threshold_overflow(
    tensor: jax.Array, threshold_val: float, *, budget_ratio: float = 1.0
) -> jax.Array:
    """How many above-threshold elements did NOT fit the static budget this
    step (0 = the budget captured true natural sparsity). The reference
    transmits every above-threshold element (tensorflow/deepreduce.py:
    283-298); under static shapes overflow is the fidelity loss to watch —
    dump it per step (`logging_utils.DumpLogger`) or assert it stays 0."""
    flat = tensor.reshape(-1)
    d = flat.shape[0]
    k = num_slots(d, budget_ratio)
    mags = jnp.abs(flat)
    if threshold_val <= 0.0:
        passing = flat != 0
    else:
        thr = jnp.minimum(jnp.asarray(threshold_val, flat.dtype), jnp.max(mags))
        passing = mags >= thr
    n_above = jnp.sum(passing.astype(jnp.int32))
    return jnp.maximum(n_above - k, 0)


def threshold(
    tensor: jax.Array,
    threshold_val: float,
    *,
    budget_ratio: float = 1.0,
    k: Optional[int] = None,
) -> SparseGrad:
    """Keep |g| >= max(threshold, needed-to-fit-budget).

    The reference clamps the threshold down to the max |g| so at least one
    element survives (tensorflow/deepreduce.py:283) and emits a dynamic-size
    index list. Static-shape version: the slot budget is
    ``d * budget_ratio``; if more elements pass the threshold than fit, the
    largest-magnitude ones win. ``threshold_val=0.0`` captures natural
    sparsity (the NCF config, run_deepreduce.sh:89) — size the budget with
    `calibrate_threshold_budget` and watch `threshold_overflow` to verify
    the static budget really captures it.
    """
    flat = tensor.reshape(-1)
    d = flat.shape[0]
    k = num_slots(d, budget_ratio) if k is None else int(k)
    mags = jnp.abs(flat)
    thr = jnp.minimum(jnp.asarray(threshold_val, flat.dtype), jnp.max(mags))
    vals_top, idxs = jax.lax.top_k(mags, k)
    keep = vals_top >= thr
    if threshold_val <= 0.0:
        # >= 0.0 would admit exact zeros (everything); natural sparsity
        # means nonzeros only (the reference's dynamic list contains only
        # gradient-touched elements)
        keep = jnp.logical_and(keep, vals_top > 0)
    nnz = jnp.sum(keep).astype(jnp.int32)
    # Compact live slots to the front, preserving ascending index order.
    idxs = jnp.where(keep, idxs, d)  # push dead slots to the end of the sort
    idxs = jnp.sort(idxs)
    mask = jnp.arange(k, dtype=jnp.int32) < nnz
    idxs = jnp.where(mask, idxs, 0)
    vals = jnp.where(mask, flat[idxs], 0.0)
    return SparseGrad(
        values=vals,
        indices=idxs.astype(jnp.int32),
        nnz=nnz,
        shape=tuple(tensor.shape),
    )


def none_sparsifier(tensor: jax.Array) -> SparseGrad:
    """Identity sparsifier (the dense baseline's 'none', run_deepreduce.sh:51)."""
    flat = tensor.reshape(-1)
    d = flat.shape[0]
    return SparseGrad(
        values=flat,
        indices=jnp.arange(d, dtype=jnp.int32),
        nnz=jnp.asarray(d, jnp.int32),
        shape=tuple(tensor.shape),
    )


def stable_name_hash(name: str) -> int:
    """PYTHONHASHSEED-independent 32-bit hash of a tensor name.

    Murmur3 ``fmix32`` finalizer chained over the UTF-8 bytes — the same
    mixer the bloom codec uses (codecs/bloom.py:56), so every process on
    every host derives the identical value for the same name. Python's
    built-in ``hash(str)`` is salted per process and would desynchronize
    the deterministic-selection contract multi-worker codecs rely on
    (reference: bloom_filter_compression.cc:217-218 — all workers must
    make the same pseudo-random choices)."""
    h = 0x9747B28C
    for b in name.encode("utf-8"):
        h = (h ^ b) & 0xFFFFFFFF
        h ^= h >> 16
        h = (h * 0x85EBCA6B) & 0xFFFFFFFF
        h ^= h >> 13
        h = (h * 0xC2B2AE35) & 0xFFFFFFFF
        h ^= h >> 16
    return h


def per_tensor_key(base_key: jax.Array, name: str, step: jax.Array) -> jax.Array:
    """Per-tensor per-step PRNG key — the role of the reference's
    ``hash(tensor_name) + global_step`` seed (tensorflow/deepreduce.py:293),
    made stable across processes via :func:`stable_name_hash`."""
    name_hash = jnp.uint32(stable_name_hash(name))
    return jax.random.fold_in(jax.random.fold_in(base_key, name_hash), step)
