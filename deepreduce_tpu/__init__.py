"""deepreduce_tpu — a TPU-native sparse-gradient communication framework.

Capabilities mirror DeepReduce (NeurIPS'21, hangxu0304/DeepReduce): sparse
gradients are decomposed into values and indices, each compressed independently
or jointly (reference README.md:5), then exchanged between data-parallel
workers. Where the reference stacks GRACE + Horovod + NCCL allgather and
CUDA/CuPy/C++-CPU codecs, this framework is built from scratch on JAX:

- static-shape, jit-compiled codecs (`deepreduce_tpu.codecs`)
- `jax.lax.all_gather` over ICI inside `shard_map` (`deepreduce_tpu.comm`)
- functional residual error-feedback state (`deepreduce_tpu.memory`)
- flax model zoo for the reference's benchmark families
  (`deepreduce_tpu.models`)
- a C++ native layer for the host-side codec path (`deepreduce_tpu.native`),
  standing in for the reference's TensorFlow CPU custom ops.

Nothing here is a translation: dynamic-size payloads (the reference's
`tensors_size_are_same=False` contract, pytorch/deepreduce.py:54-59) become
fixed-budget payloads with in-band length words so XLA collectives get static
shapes.
"""

from deepreduce_tpu import (
    codecs,
    comm,
    config,
    memory,
    metrics,
    parallel,
    qar,
    sparse,
    telemetry,
    tracking,
)
from deepreduce_tpu.config import DeepReduceConfig, from_params
from deepreduce_tpu.fedavg import FedAvg, FedAvgState, FedConfig
from deepreduce_tpu.sparse import SparseGrad

__version__ = "0.1.0"

__all__ = [
    "SparseGrad",
    "DeepReduceConfig",
    "from_params",
    "FedAvg",
    "FedAvgState",
    "FedConfig",
    "codecs",
    "comm",
    "config",
    "memory",
    "metrics",
    "parallel",
    "qar",
    "sparse",
    "telemetry",
    "tracking",
]
