"""Quantized allreduce — int8 reduce-scatter + allgather over a mesh axis.

The reference has exactly two collectives: dense fp32 allreduce (baseline)
and allgather-of-compressed-payloads, where every worker receives every
other worker's payload and decodes all W of them (SURVEY.md §2.5). A TPU
mesh admits a third shape the reference's Horovod world can't express — the
one EQuARX-style quantized XLA collectives use (PAPERS.md): quantize
*inside* the collective.

    phase 1 (reduce-scatter): g reshaped [W, s]; every shard QSGD-bucket
        quantized to int8 + f32 bucket norms; `all_to_all` routes shard i
        of every worker to worker i; dequantize the W received rows and
        sum -> worker i owns the aggregated shard i.
    phase 2 (allgather): the aggregated shard is re-quantized and
        `all_gather`ed; every worker dequantizes W shards back into the
        full mean gradient.

Wire cost per worker ~ 2·(W-1)/W·d int8 bytes (+ 1 f32 norm per 512-bucket),
vs 8·(W-1)/W·d bytes for fp32 ring allreduce — ~4x less traffic — and vs the
reference scheme's W-fold receive volume. Quantization is unbiased
(stochastic rounding, E[q(x)] = x) at both phases, so this works on *dense*
gradients with no sparsifier and no residual memory. One fused buffer
carries the whole gradient pytree.

`GradientExchanger` exposes this as ``communicator='qar'``.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def bucket_quantize(
    flat: jax.Array,
    quantum_num: int,
    bucket_size: int,
    key: jax.Array,
    use_pallas: bool = False,
    norms: jax.Array = None,
) -> Tuple[jax.Array, jax.Array]:
    """QSGD-style per-bucket stochastic quantization of a [n] vector (n a
    static multiple of bucket_size) -> (int8[n] levels, f32[n/bucket] norms).
    Shares the bucket geometry (codecs.qsgd.bucket_scale) and the
    floor+Bernoulli int8 step (ops.quantize_levels, incl. the Pallas
    hardware-PRNG fast path) with the QSGD codec — one quantizer.

    `norms` optionally supplies externally-agreed per-bucket norms (e.g. a
    `pmax` across workers) in place of the locally-measured L2 — required
    when workers must share one scale so their int8 levels are summable
    in-collective (sparse_rs rs_mode='quantized'). Supplied norms must
    upper-bound the local per-element magnitudes or levels clip meaning."""
    from deepreduce_tpu.codecs.qsgd import bucket_scale
    from deepreduce_tpu.ops import quantize_levels

    if quantum_num > 127:
        raise ValueError(
            f"quantum_num={quantum_num} does not fit the int8 wire (max 127); "
            "levels would wrap and flip gradient signs"
        )
    if norms is None:
        scale, norms = bucket_scale(flat, quantum_num, bucket_size)
    else:
        safe = jnp.where(norms > 0, norms, 1.0)
        scale = jnp.broadcast_to(
            (quantum_num / safe)[:, None], (norms.shape[0], bucket_size)
        ).reshape(-1)
    levels = quantize_levels(flat, scale, key, use_pallas=use_pallas)
    return levels, norms


def bucket_dequantize(
    levels: jax.Array, norms: jax.Array, quantum_num: int, bucket_size: int
) -> jax.Array:
    b = levels.reshape(-1, bucket_size).astype(jnp.float32)
    return (b * (norms / quantum_num)[:, None]).reshape(-1)


# internal aliases kept for call-site stability inside this module's history
_bucket_quantize = bucket_quantize
_bucket_dequantize = bucket_dequantize


def pad_len(d: int, num_workers: int, bucket_size: int) -> int:
    """Padded length: a whole number of buckets per worker shard."""
    shard = -(-d // num_workers)  # ceil
    shard = -(-shard // bucket_size) * bucket_size
    return shard * num_workers


def wire_bits_per_worker(d: int, num_workers: int, bucket_size: int) -> float:
    """Bytes-on-ICI accounting: int8 levels + f32 norms actually sent by one
    worker across both phases (ring collectives transmit the (W-1)/W
    fraction)."""
    n = pad_len(d, num_workers, bucket_size)
    payload_bits = n * 8 + (n // bucket_size) * 32
    return 2.0 * payload_bits * (num_workers - 1) / max(1, num_workers)


def quantized_allreduce(
    flat: jax.Array,
    axis_name: str,
    num_workers: int,
    *,
    key: jax.Array,
    quantum_num: int = 127,
    bucket_size: int = 512,
    use_pallas: bool = False,
) -> jax.Array:
    """Mean of `flat` over `axis_name` via the int8 two-phase exchange.

    `flat` must be zero-padded to `pad_len(d, num_workers, bucket_size)`;
    `num_workers` is the static mesh-axis size (shapes must be static under
    jit — the traced `psum(1, axis)` cannot drive a reshape). Call inside
    shard_map over `axis_name`. Returns the elementwise mean.
    """
    if quantum_num > 127:
        raise ValueError(
            f"quantum_num={quantum_num} does not fit the int8 wire (max 127); "
            "levels would wrap and flip gradient signs"
        )
    n = flat.shape[0]
    if n % (num_workers * bucket_size):
        raise ValueError(
            f"flat length {n} not a multiple of W*bucket = {num_workers * bucket_size}; "
            "pad with pad_len()"
        )
    shard = n // num_workers
    widx = jax.lax.axis_index(axis_name)

    # --- phase 1: quantize, all_to_all shards to their owners, reduce ----
    levels, norms = _bucket_quantize(
        flat, quantum_num, bucket_size, jax.random.fold_in(key, widx), use_pallas
    )
    lv = levels.reshape(num_workers, shard)
    nm = norms.reshape(num_workers, shard // bucket_size)
    # tiled all_to_all: row j of every worker lands on worker j; the
    # received rows stack along the same axis -> [W, shard] where row w is
    # worker w's contribution to MY shard
    lv_rx = jax.lax.all_to_all(lv, axis_name, split_axis=0, concat_axis=0, tiled=True)
    nm_rx = jax.lax.all_to_all(nm, axis_name, split_axis=0, concat_axis=0, tiled=True)
    contrib = jax.vmap(
        lambda l, s: _bucket_dequantize(l, s, quantum_num, bucket_size)
    )(lv_rx, nm_rx)
    own_sum = jnp.sum(contrib, axis=0)  # aggregated shard owned by this worker

    # --- phase 2: re-quantize the aggregate, allgather, dequantize -------
    k2 = jax.random.fold_in(jax.random.fold_in(key, widx), jnp.uint32(0x5EED))
    lv2, nm2 = _bucket_quantize(own_sum, quantum_num, bucket_size, k2, use_pallas)
    lv_all = jax.lax.all_gather(lv2, axis_name)  # [W, shard]
    nm_all = jax.lax.all_gather(nm2, axis_name)
    full = jax.vmap(
        lambda l, s: _bucket_dequantize(l, s, quantum_num, bucket_size)
    )(lv_all, nm_all).reshape(n)
    return full / num_workers
